"""Admission-controlled run scheduler: many runs, one process.

The north star is a SERVICE — many small heterogeneous analyses from
many tenants sharing one hot device — and ``run_recipe()`` alone is
the wrong shape for it: every call is an island with unbounded
concurrency, a fresh circuit breaker per run (ten concurrent runs
each independently burn K failures rediscovering the same dead
backend), no queueing, no quotas, and no way to shed load before the
host falls over.  :class:`RunScheduler` is the admission-control and
scheduling layer in front of ``runner.ResilientRunner``:

* **Bounded concurrency** — a fixed worker pool (``max_concurrency``
  threads); everything else waits in a priority/FIFO queue (higher
  ``priority=`` first, FIFO within a priority).
* **Per-tenant quotas** — each submission carries ``tenant=``; a
  tenant has an in-flight cap (enforced at dispatch: an over-quota
  tenant's work stays queued and CANNOT starve other tenants — lower
  priority work from under-quota tenants dispatches past it) and a
  queue-depth cap (enforced at admission:
  :class:`RunRejected` ``reason="tenant_queue_quota"``).
* **Queue deadlines** — a submission with ``deadline_s=`` whose
  deadline would expire before it could plausibly START (estimated
  from queue position and an EWMA of observed run walls) is rejected
  AT ADMISSION (``reason="deadline_unmeetable"``) instead of timing
  out mid-queue; a deadline that expires while queued anyway (the
  estimate was optimistic) is shed at dispatch time
  (``reason="deadline_expired"``).
* **Load shedding** — when the queue would exceed
  ``queue_high_water``, the LOWEST-priority queued item (tie-broken
  toward the most queue-hogging tenant, then the youngest arrival)
  is shed with a journaled ``shed`` event to make room for
  higher-priority work; an arrival that is itself the lowest
  priority is rejected (``reason="queue_full"``) — overload degrades
  the cheapest work, not everyone.
* **Shared failure state** — every worker resolves its circuit
  breaker from one :class:`~sctools_tpu.utils.failsafe.BreakerRegistry`
  (per BACKEND, not per run): the first run to trip the tpu breaker
  short-circuits every queued run straight to the degrade ruling,
  and one half-open probe success un-degrades the whole pool.
* **Budgeted device memory** — with ``mem_budget=`` (a
  :class:`~sctools_tpu.memory.MemoryBudget`), every submission's peak
  memory is estimated at admission (learned compiled estimates + the
  registry ``mem_cost`` heuristic, ``memory.estimate_run_peak``): an
  estimate that cannot fit beside the standing residents at ZERO
  concurrency is refused ``RunRejected(reason="over_memory")`` at the
  door; an admitted run RESERVES its estimate at dispatch — work that
  does not fit right now QUEUES instead of co-scheduling into an OOM
  — and releases at terminal (or at a preemption yield).  Each
  reservation is journaled ``mem_reserved``/``mem_released``; the
  worker installs the budget thread-locally
  (``memory.budget_scope``), so residents created inside ops — the
  streaming trainer's feed window — hold NAMED reservations against
  the same ledger (run-scoped holds stay dynamic; only
  service-lifetime residents like the serving model are STANDING,
  because standing bytes shrink what admission may ever promise).
  Chaos ``mem_pressure`` (consulted per submission through
  ``ChaosMonkey.on_memory``) shrinks the apparent budget for the
  fault's window.
* **Observability** — a JSONL journal (``submitted`` → ``admitted`` |
  ``rejected``, then ``shed`` | ``run_completed`` | ``run_failed``
  per ticket; every terminal state carries a reason) plus ``sched.*``
  metrics in the shared ``MetricsRegistry``: queue-depth gauge,
  admitted/rejected/shed counters labelled ``tenant=``/``reason=``,
  and a queue-wait histogram.
* **Cooperative preemption** — ``submit(..., preemptible=True)``
  declares a long-running checkpoint-then-yield job (the out-of-core
  trainer, ``models/train_stream.py``).  A strictly-higher-priority
  arrival with every worker busy asks the lowest-priority running
  preemptible job to yield (``failsafe.PreemptToken``, polled by the
  job at its shard boundaries): the job saves its cursor, raises
  ``JobPreempted``, is journaled ``preempted`` (NOT terminal) and
  re-enters the queue — the next dispatch RESUMES from the cursor.
  ``RunHandle.cancel()`` rides the same path (queued = shed
  ``reason="cancelled"``; running = yield then terminal shed),
  closing the "no way to stop a long job" gap.
* **Chaos** — ``chaos=`` arms the same seeded ``ChaosMonkey`` for
  every worker (activated once for the pool's lifetime, so faults
  fire on every thread) AND gives admission its own fault channel:
  ``reject_storm`` faults fire through ``ChaosMonkey.on_admission``,
  so the shed/reject paths are tier-1 testable like device faults;
  ``preempt`` faults fire through ``ChaosMonkey.on_worker`` at a
  preemptible job's Nth shard-boundary poll.

All scheduling runs on the injectable clock (``utils/vclock.py``) —
queue waits, deadline estimates and EWMA run walls move on a
``VirtualClock`` in tests with zero real sleeps.  Thread-safety of
the underlying layers is part of the contract: deadline tokens are
thread-local, each runner's telemetry/deadline wrappers install
thread-locally, and the shared breaker's transitions are atomic
(``failsafe.CircuitBreaker.lock``).

>>> from sctools_tpu.scheduler import RunScheduler
>>> with RunScheduler(max_concurrency=2) as sched:
...     h = sched.submit(seurat_pipeline(), data, tenant="lab-a",
...                      priority=1, deadline_s=300, backend="tpu")
...     out = h.result()
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import uuid

from . import memory as _memory
from .registry import Pipeline
from .runner import (DEFAULT_FALLBACK_BACKEND, ResilientRunner,
                     _Journal, run_backend_signature)
from .utils import telemetry
from .utils.failsafe import (BreakerRegistry, JobPreempted,
                             PreemptToken, default_breaker_registry,
                             preempt_scope)
from .utils.vclock import SYSTEM_CLOCK

#: every submission ends in exactly ONE of these (the journal
#: coherence contract the chaos soak asserts).  ``preempted`` is
#: deliberately NOT terminal: a preempted ticket re-enters the queue
#: with its cursor and still terminates exactly once later.
TERMINAL_STATES = ("completed", "failed", "rejected", "shed")

#: EWMA smoothing for observed run walls (the deadline estimator)
_EWMA_ALPHA = 0.3


def new_trace_id() -> str:
    """A fresh admission-stamped causal id.  Opaque and globally
    unique — it joins journal records and span metadata across every
    process a ticket touches (supervisor, worker, runner), so it must
    never collide across the fleet; nothing ever parses it."""
    return f"tr-{uuid.uuid4().hex[:16]}"


class RunRejected(RuntimeError):
    """A submission refused AT ADMISSION.  ``reason`` is machine-
    readable (``tenant_queue_quota`` / ``deadline_unmeetable`` /
    ``queue_full`` / ``reject_storm`` / ``scheduler_closed`` /
    ``over_memory``) and matches the journal record and the
    ``sched.rejected`` metric label."""

    def __init__(self, msg: str, *, reason: str,
                 tenant: str | None = None):
        super().__init__(msg)
        self.reason = reason
        self.tenant = tenant


class RunShed(RunRejected):
    """An ADMITTED submission dropped before it ran (load shedding,
    expired queue deadline, scheduler shutdown).  Raised by
    ``RunHandle.result()``; ``reason`` matches the journaled ``shed``
    event."""


class RunHandle:
    """The caller's view of one admitted submission.

    ``status`` moves ``queued`` → ``running`` → ``completed`` |
    ``failed``, or ``queued`` → ``shed``.  ``result()`` blocks until
    terminal and returns the run's output, re-raises the run's real
    exception (``failed``), or raises :class:`RunShed`.  ``report``
    carries the worker's ``RunReport`` once the run started —
    per-step attempts, degrade rulings and the shared-breaker
    snapshot, exactly as a direct ``ResilientRunner`` caller would
    see them."""

    def __init__(self, ticket: int, tenant: str, priority: int,
                 deadline_s: float | None, clock=None,
                 trace_id: str | None = None):
        self.ticket = ticket
        self.tenant = tenant
        self.priority = priority
        self.deadline_s = deadline_s
        #: the admission-stamped causal id (fleet trace join key)
        self.trace_id = trace_id
        self.report = None
        self.reason: str | None = None
        #: the scheduler clock's reading at the terminal transition
        #: (None until terminal) — composing layers (the annotation
        #: service's latency accounting) read the REAL terminal time
        #: here instead of their own collection time
        self.finished_at: float | None = None
        self._clock = clock
        self._status = "queued"
        self._result = None
        self._error: BaseException | None = None
        self._terminal = threading.Event()
        self._cancel_cb = None  # wired by the owning scheduler

    def cancel(self) -> bool:
        """Cooperatively cancel this submission.  QUEUED: shed
        immediately (journaled ``shed`` ``reason="cancelled"``,
        ``result()`` raises :class:`RunShed`).  RUNNING: the same
        checkpoint-then-yield path as preemption — the run's preempt
        token is armed with ``reason="cancelled"`` and a job that
        polls it (the out-of-core trainer does, at every shard
        boundary) checkpoints, yields, and terminals as shed exactly
        once.  Returns True when the cancellation was DELIVERED
        (shed, or the running job's token armed); False when the run
        is already terminal.  Cooperative by design: a running job
        that never polls its token simply completes — cancellation
        can close the "no way to stop a long job" gap only for jobs
        built to stop at safe boundaries."""
        cb = self._cancel_cb
        return bool(cb is not None and cb(self))

    @property
    def status(self) -> str:
        return self._status

    def done(self) -> bool:
        return self._terminal.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the run is terminal; False on timeout."""
        return self._terminal.wait(timeout)

    def result(self, timeout: float | None = None):
        if not self._terminal.wait(timeout):
            raise TimeoutError(
                f"run {self.ticket} (tenant {self.tenant!r}) not "
                f"terminal after {timeout}s (status {self._status!r})")
        if self._status == "completed":
            return self._result
        raise self._error

    def _mark_running(self) -> None:
        self._status = "running"

    def _finish(self, status: str, result=None,
                error: BaseException | None = None,
                reason: str | None = None) -> None:
        self._result = result
        self._error = error
        self.reason = reason
        if self._clock is not None:
            self.finished_at = self._clock.monotonic()
        self._status = status
        self._terminal.set()

    def __repr__(self):
        return (f"RunHandle(ticket={self.ticket}, "
                f"tenant={self.tenant!r}, priority={self.priority}, "
                f"status={self._status!r})")


@dataclasses.dataclass
class TenantQuota:
    """Per-tenant admission limits.  ``max_in_flight`` bounds how many
    of the tenant's runs execute concurrently (enforced at dispatch —
    must be >= 1, or admitted work could never dispatch and shutdown
    would wait on it forever); ``max_queued`` bounds its queue depth
    (enforced at admission — 0 is legal and means "reject everything
    from this tenant at the door")."""

    max_in_flight: int = 2
    max_queued: int = 8

    def __post_init__(self):
        if self.max_in_flight < 1:
            raise ValueError(
                "TenantQuota.max_in_flight must be >= 1 — a 0 quota "
                "would admit work that can never dispatch (use "
                "max_queued=0 to refuse a tenant at admission)")
        if self.max_queued < 0:
            raise ValueError("TenantQuota.max_queued must be >= 0")


@dataclasses.dataclass
class _QueueItem:
    seq: int
    tenant: str
    priority: int
    deadline_s: float | None
    submitted_at: float
    pipeline: Pipeline
    data: object
    backend: str | None
    runner_kw: dict
    handle: RunHandle
    #: the admission-stamped causal id: rides every journal record of
    #: this ticket and into the runner's span metadata
    trace_id: str = ""
    #: declared long-running + checkpoint-then-yield capable: a
    #: preemption victim when higher-priority work arrives with no
    #: free worker (the job polls its token at its safe boundaries)
    preemptible: bool = False
    #: the run's cooperative preemption signal (fresh per dispatch —
    #: a consumed yield must not instantly re-fire on the requeue)
    token: PreemptToken | None = None
    #: times this ticket checkpoint-then-yielded so far
    preemptions: int = 0
    #: estimated peak device-memory bytes (0 = no budget configured);
    #: reserved at dispatch, released at terminal/yield
    mem_bytes: int = 0

    def sort_key(self):
        # higher priority first, FIFO within a priority
        return (-self.priority, self.seq)


class RunScheduler:
    """Bounded worker pool + admission-controlled priority queue in
    front of ``ResilientRunner`` (module docstring has the full
    contract).

    Parameters
    ----------
    max_concurrency : int
        Worker threads — the GLOBAL in-flight bound.
    queue_high_water : int
        Queue depth above which load shedding kicks in (shed the
        lowest-priority queued item, or reject the arrival when it
        is itself the lowest).
    tenant_max_in_flight, tenant_max_queued : int
        Default per-tenant quotas; ``quotas={tenant: TenantQuota}``
        overrides individual tenants.
    expected_run_s : float
        Seed for the EWMA of observed run walls that the
        ``deadline_s`` admission estimate uses; 0 disables
        estimate-based rejection until the first run completes.
    clock : vclock.Clock
        Time source for queue waits, deadlines and the EWMA
        (default: the system clock; tests share one VirtualClock
        with runners, breakers and chaos).
    metrics : telemetry.MetricsRegistry | None
        Where ``sched.*`` series land; defaults to the process-wide
        registry (shared with every runner the pool creates).
    journal_path : str | None
        JSONL admission/terminal journal; at ``shutdown()`` the
        metrics snapshot is written next to it as ``metrics.json``
        (the pair ``tools/sctreport.py`` renders a scheduler section
        from).
    breakers : failsafe.BreakerRegistry | None
        Shared per-backend breaker state for every worker; defaults
        to the process-wide ``default_breaker_registry()``.
    chaos : ChaosMonkey | None
        Armed ONCE for the pool's lifetime (faults fire on every
        worker thread; the runner's own activation is a no-op while
        the pool holds the hook) and consulted at admission for
        ``reject_storm`` faults (plus ``mem_pressure`` against the
        memory budget, when one is configured).
    mem_budget : memory.MemoryBudget | None
        Per-backend device-memory budget (module docstring).  ``None``
        (the default) disables memory-aware admission entirely —
        estimates are not even computed.
    runner_defaults : dict | None
        Keyword defaults for every ``ResilientRunner`` the pool
        constructs (``policy=``, ``probe=``, ``step_deadline_s=`` …);
        per-submission ``runner_kw`` overrides them.
    """

    def __init__(self, *, max_concurrency: int = 2,
                 queue_high_water: int = 64,
                 tenant_max_in_flight: int = 2,
                 tenant_max_queued: int = 8,
                 quotas: dict | None = None,
                 expected_run_s: float = 0.0,
                 clock=None, metrics=None,
                 journal_path: str | None = None,
                 breakers: BreakerRegistry | None = None,
                 chaos=None, runner_defaults: dict | None = None,
                 mem_budget: "_memory.MemoryBudget | None" = None,
                 slo_objectives=None):
        if max_concurrency < 1:
            raise ValueError("max_concurrency must be >= 1")
        if queue_high_water < 1:
            raise ValueError("queue_high_water must be >= 1")
        self.max_concurrency = int(max_concurrency)
        self.queue_high_water = int(queue_high_water)
        # TenantQuota.__post_init__ validates everything constructed
        # here — the defaults and any tuple-shaped overrides (an
        # unvalidated max_in_flight=0 would admit work that can never
        # dispatch and deadlock shutdown on it)
        self._default_quota = TenantQuota(tenant_max_in_flight,
                                          tenant_max_queued)
        self._quotas = {t: (q if isinstance(q, TenantQuota)
                            else TenantQuota(*q))
                        for t, q in (quotas or {}).items()}
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.metrics = (metrics if metrics is not None
                        else telemetry.default_registry())
        self.journal = _Journal(journal_path)
        self.breakers = (breakers if breakers is not None
                         else default_breaker_registry())
        self.chaos = chaos
        self.runner_defaults = dict(runner_defaults or {})
        self.mem_budget = mem_budget

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: list[_QueueItem] = []   # kept sorted by sort_key
        self._queued_by_tenant: dict[str, int] = {}
        self._running_items: list[_QueueItem] = []
        self._running_total = 0
        self._running_by_tenant: dict[str, int] = {}
        self._seq = 0
        self._closed = False
        self._threads: list[threading.Thread] = []
        self._ewma_run_s = float(expected_run_s)
        self._stats = {
            "submitted": 0, "admitted": 0, "rejected": 0, "shed": 0,
            "completed": 0, "failed": 0, "preempted": 0,
            "max_queue_depth": 0, "max_in_flight_total": 0,
            "max_in_flight_by_tenant": {},
        }
        # audit trail for the shed-ordering contract: one
        # (victim_priority, min_priority_left_in_queue) pair per shed
        self._shed_audit: list[tuple[int, int | None]] = []
        # the pool holds the chaos hook for its whole lifetime so a
        # finishing run can never pop the wrapper out from under a
        # concurrent one (the monkey's own activation is reentrant)
        self._hooks = contextlib.ExitStack()
        if chaos is not None:
            self._hooks.enter_context(chaos.activate())
        # SLO rulings over the admission funnel, on by default: the
        # monitor journals slo_breach/slo_recovered into THIS journal
        # and is poked (rate-limited, outside the dispatch lock) from
        # the worker loop.  slo_objectives=() disables it.
        from .slo import SLOMonitor, scheduler_objectives

        objectives = (scheduler_objectives()
                      if slo_objectives is None else slo_objectives)
        self.slo = (SLOMonitor(self.metrics, journal=self.journal,
                               clock=self.clock,
                               objectives=objectives)
                    if objectives else None)

    # -- context manager ------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # always wait: popping the chaos hook / snapshotting metrics
        # under still-running workers would change their behavior
        # mid-run.  On the exception path the queue is shed so the
        # wait is bounded by the in-flight runs only.
        self.shutdown(wait=True, shed_queued=exc[0] is not None)
        return False

    @property
    def journal_path(self) -> str | None:
        """Path of the pool's journal file (``None`` when journaling
        is disabled) — the file the factory's training step and the
        report tooling read/extend, mirroring
        ``FederationSupervisor.journal_path``."""
        return self.journal.path

    # -- admission ------------------------------------------------------
    def submit(self, pipeline: Pipeline, data, *, tenant: str = "default",
               priority: int = 0, deadline_s: float | None = None,
               backend: str | None = None,
               runner_kw: dict | None = None,
               preemptible: bool = False,
               trace_id: str | None = None) -> RunHandle:
        """Admit one run (or refuse it, raising :class:`RunRejected`).

        Admission rulings, in order: scheduler open → chaos
        ``reject_storm`` → tenant queue quota → queue-deadline
        feasibility → global high-water (shed a lower-priority victim
        or reject the arrival).  An admitted run returns a
        :class:`RunHandle`; its journal trail is
        ``submitted`` → ``admitted`` → (``preempted`` …)* →
        (``shed`` | ``run_completed`` | ``run_failed``).

        ``preemptible=True`` declares a LONG-RUNNING job that honours
        the cooperative checkpoint-then-yield contract (it polls
        ``failsafe.check_preempt()`` at its safe boundaries — the
        out-of-core trainer does, at every shard boundary): when a
        strictly-higher-priority submission arrives and every worker
        is busy, the lowest-priority running preemptible job is asked
        to yield; it saves its cursor, raises ``JobPreempted``, is
        journaled ``preempted`` (NOT a terminal state) and RE-ENTERS
        the queue — the next dispatch resumes from the cursor instead
        of the job being shed or restarted.  Queue-wait accounting
        and the ``deadline_s`` ruling restart PER SEGMENT on requeue:
        wall the job spent running is progress (it holds a cursor),
        not queue wait, and must not terminal-shed the resumed
        segment as ``deadline_expired``.  A chaos ``preempt``
        fault (consulted per shard-boundary poll through
        ``ChaosMonkey.on_worker``, pattern = the tenant name) rules
        the same yield deterministically."""
        # the memory work runs BEFORE the dispatch lock: the chaos
        # consult, the (possibly fused-form) estimate and the
        # admissibility read depend only on (pipeline, data,
        # runner_kw) — planning a pipeline / walking a large pytree
        # under self._cv would stall every worker's dispatch behind
        # each submission (the same discipline as the out-of-lock
        # journal writes).  The admissibility read is re-checked at
        # dispatch anyway (the over_memory shed sweep), so the tiny
        # TOCTOU window is covered.
        mem_bytes = 0
        mem_refusal = None
        if self.mem_budget is not None:
            if self.chaos is not None:
                # chaos mem_pressure: apparent budget shrinks to
                # pressure_frac while the fault fires, restores when
                # its window passes (consulted once per submission —
                # deterministic on one VirtualClock)
                ruling = self.chaos.on_memory(self.mem_budget.name,
                                              backend=backend)
                if ruling is not None and \
                        ruling.get("mode") == "mem_pressure":
                    self.mem_budget.set_pressure(
                        ruling.get("pressure_frac", 0.5))
                else:
                    self.mem_budget.clear_pressure()
            # estimate the pipeline AS THE RUNNER WILL RUN IT: a
            # fuse=True submission executes fused stages, and the
            # estimate store keys on the stage form — admission must
            # read (and OOM corrections must feed) the same keys the
            # runtime writes
            est_pipeline = pipeline
            rkw = {**self.runner_defaults, **(runner_kw or {})}
            if rkw.get("fuse"):
                from .plan import fused_pipeline as _fuse

                # mesh included: a sharded submission's stages key
                # their estimates under the sharded form
                est_pipeline = _fuse(
                    pipeline, no_fuse=rkw.get("isolate", ()),
                    mesh=rkw.get("mesh"))
            mem_bytes = _memory.estimate_run_peak(
                est_pipeline, data)["bytes"]
            admissible = self.mem_budget.admissible_bytes()
            if mem_bytes > admissible:
                # infeasible at ANY concurrency: the estimate cannot
                # fit beside the standing residents even alone —
                # refuse at the door instead of queueing work that
                # can never dispatch
                mem_refusal = (f"estimated peak {mem_bytes} bytes > "
                               f"admissible {admissible} bytes "
                               f"(capacity minus standing "
                               f"reservations)")
        # the causal id is stamped AT ADMISSION — every journal record
        # of this ticket (here, in a federation worker, in the runner)
        # and every span the run records carries it, so the whole
        # fleet journey joins on one key.  Callers that already hold
        # one (the federation worker re-dispatching a ticket, the
        # serving tier) pass it through instead.
        if not trace_id:
            trace_id = new_trace_id()
        with self._cv:
            ticket = self._seq
            self._seq += 1
            self._stats["submitted"] += 1
            self.journal.write(
                "submitted", ticket=ticket, tenant=tenant,
                priority=priority, deadline_s=deadline_s,
                trace_id=trace_id,
                queue_depth=len(self._queue))
            if self._closed:
                self._reject(ticket, tenant, "scheduler_closed",
                             trace_id=trace_id)
            if self.chaos is not None and \
                    self.chaos.on_admission(tenant, backend=backend):
                self._reject(ticket, tenant, "reject_storm",
                             trace_id=trace_id)
            quota = self._quota(tenant)
            if self._queued_by_tenant.get(tenant, 0) >= quota.max_queued:
                self._reject(ticket, tenant, "tenant_queue_quota",
                             trace_id=trace_id)
            if deadline_s is not None:
                est = self._estimate_start_wait_locked(priority, ticket)
                if deadline_s <= 0 or est > deadline_s:
                    self._reject(
                        ticket, tenant, "deadline_unmeetable",
                        trace_id=trace_id,
                        detail=f"estimated start wait {est:g}s > "
                               f"deadline {deadline_s:g}s")
            if mem_refusal is not None:
                self._reject(ticket, tenant, "over_memory",
                             trace_id=trace_id, detail=mem_refusal)
            if len(self._queue) >= self.queue_high_water:
                victim = self._pick_victim_locked(priority)
                if victim is None:
                    self._reject(ticket, tenant, "queue_full",
                                 trace_id=trace_id)
                self._shed_locked(victim, "queue_high_water")
            handle = RunHandle(ticket, tenant, priority, deadline_s,
                               clock=self.clock, trace_id=trace_id)
            handle._cancel_cb = self._cancel
            item = _QueueItem(ticket, tenant, int(priority), deadline_s,
                              self.clock.monotonic(), pipeline, data,
                              backend, dict(runner_kw or {}), handle,
                              trace_id=trace_id,
                              preemptible=bool(preemptible),
                              mem_bytes=int(mem_bytes))
            self._insert_locked(item)
            self._stats["admitted"] += 1
            self.journal.write("admitted", ticket=ticket, tenant=tenant,
                               priority=priority,
                               mem_bytes=int(mem_bytes),
                               trace_id=trace_id,
                               queue_depth=len(self._queue))
            self.metrics.counter("sched.admitted", tenant=tenant).inc()
            self._ensure_workers_locked()
            # high-priority arrival with every worker busy: ask the
            # lowest-priority RUNNING preemptible job to checkpoint-
            # then-yield — serving traffic borrows the device, the
            # training job re-enters the queue with its cursor
            # instead of being shed
            if self._running_total >= self.max_concurrency:
                victim = self._pick_preempt_victim_locked(priority)
                if victim is not None:
                    victim.token.request("priority")
            self._cv.notify()
            return handle

    def _pick_preempt_victim_locked(self, new_priority: int):
        """The running job to preempt for an arriving
        ``new_priority`` submission: preemptible, strictly lower
        priority (yielding an equal never helps the arrival), not
        already asked to yield; lowest priority first, tie-broken
        toward the youngest (oldest work keeps its claim, mirroring
        the shed rule).  None → nobody to preempt; the arrival waits
        its turn in the queue."""
        cands = [it for it in self._running_items
                 if it.preemptible and it.priority < new_priority
                 and it.token is not None
                 and it.token.requested() is None]
        if not cands:
            return None
        return min(cands, key=lambda it: (it.priority, -it.seq))

    def _cancel(self, handle: RunHandle) -> bool:
        """``RunHandle.cancel()``'s implementation (see its docstring
        for the contract).  Under the dispatch lock the handle's item
        is in exactly one of {queue, running set, terminal}."""
        with self._cv:
            if handle.done():
                return False
            for it in self._queue:
                if it.handle is handle:
                    self._shed_locked(it, "cancelled")
                    return True
            for it in self._running_items:
                if it.handle is handle and it.token is not None:
                    it.token.request("cancelled")
                    return True
        return False

    def _quota(self, tenant: str) -> TenantQuota:
        return self._quotas.get(tenant, self._default_quota)

    def _reject(self, ticket: int, tenant: str, reason: str,
                detail: str = "", trace_id: str = ""):
        self._stats["rejected"] += 1
        self.journal.write("rejected", ticket=ticket, tenant=tenant,
                           reason=reason, trace_id=trace_id)
        self.metrics.counter("sched.rejected", tenant=tenant,
                             reason=reason).inc()
        raise RunRejected(
            f"run {ticket} (tenant {tenant!r}) rejected at admission: "
            f"{reason}" + (f" ({detail})" if detail else ""),
            reason=reason, tenant=tenant)

    def _insert_locked(self, item: _QueueItem) -> None:
        # sorted insert; the queue is short (bounded by the high-water
        # mark), so a linear scan beats heap bookkeeping under sheds
        key = item.sort_key()
        idx = len(self._queue)
        for j, other in enumerate(self._queue):
            if key < other.sort_key():
                idx = j
                break
        self._queue.insert(idx, item)
        self._queued_by_tenant[item.tenant] = \
            self._queued_by_tenant.get(item.tenant, 0) + 1
        self._note_queue_depth_locked()

    def _remove_locked(self, item: _QueueItem) -> None:
        self._queue.remove(item)
        self._queued_by_tenant[item.tenant] -= 1
        self._note_queue_depth_locked()

    def _note_queue_depth_locked(self) -> None:
        depth = len(self._queue)
        self._stats["max_queue_depth"] = max(
            self._stats["max_queue_depth"], depth)
        self.metrics.gauge("sched.queue_depth").set(depth)

    def _estimate_start_wait_locked(self, priority: int,
                                    seq: int) -> float:
        """How long a new (priority, seq) arrival would plausibly wait
        before STARTING: queue position ahead of it over the worker
        count, scaled by the EWMA of observed run walls.  Returns 0
        while no wall has been observed (nothing to estimate from)."""
        avg = self._ewma_run_s
        if avg <= 0.0:
            return 0.0
        key = (-int(priority), seq)
        ahead = sum(1 for it in self._queue if it.sort_key() < key)
        free = self.max_concurrency - self._running_total
        if ahead < max(0, free):
            return 0.0
        waves = (ahead - max(0, free)) // self.max_concurrency + 1
        return waves * avg

    def _pick_victim_locked(self, new_priority: int):
        """The shed victim for an arriving ``new_priority`` run:
        strictly-lower priority only (shedding an equal never helps
        the arrival), lowest priority first, tie-broken toward the
        tenant hogging the most queue, then the youngest arrival
        (oldest work keeps its FIFO claim).  None → nothing to shed;
        the arrival is rejected instead."""
        cands = [it for it in self._queue if it.priority < new_priority]
        if not cands:
            return None
        return min(cands, key=lambda it: (
            it.priority,
            -self._queued_by_tenant.get(it.tenant, 0),
            -it.seq))

    def _shed_locked(self, item: _QueueItem, reason: str) -> None:
        self._remove_locked(item)
        left = [it.priority for it in self._queue]
        self._shed_audit.append((item.priority,
                                 min(left) if left else None))
        self._stats["shed"] += 1
        self.journal.write("shed", ticket=item.seq, tenant=item.tenant,
                           priority=item.priority, reason=reason,
                           trace_id=item.trace_id,
                           queue_depth=len(self._queue))
        self.metrics.counter("sched.shed", tenant=item.tenant,
                             reason=reason).inc()
        item.handle._finish(
            "shed", error=RunShed(
                f"run {item.seq} (tenant {item.tenant!r}) shed while "
                f"queued: {reason}", reason=reason, tenant=item.tenant),
            reason=reason)

    # -- dispatch -------------------------------------------------------
    def _ensure_workers_locked(self) -> None:
        while len(self._threads) < self.max_concurrency:
            th = threading.Thread(
                target=self._worker, daemon=True,
                name=f"sct-sched-worker-{len(self._threads)}")
            self._threads.append(th)
            th.start()

    def _pop_eligible_locked(self):
        """The next runnable item: highest priority (FIFO within)
        whose tenant is under its in-flight quota — an over-quota
        tenant's head-of-queue work never blocks other tenants — and,
        under a memory budget, whose estimated peak FITS what is left
        (over-budget work queues instead of co-scheduling into an
        OOM; smaller work may dispatch past it).  Items whose queue
        deadline expired — or whose estimate can no longer EVER fit
        beside the standing residents (they grew since admission) —
        are shed on the way.  Marks the winner running (counters +
        stats + memory reservation) before returning it.

        The ``_locked`` suffix contract (every caller holds
        ``self._cv`` = ``self._lock``) is PROVEN by the call graph —
        no locked-by-caller annotation needed."""
        now = self.clock.monotonic()
        for it in [q for q in self._queue
                   if q.deadline_s is not None
                   and now - q.submitted_at >= q.deadline_s]:
            self._shed_locked(it, "deadline_expired")
        if self.mem_budget is not None:
            # admission promised feasibility-at-zero-concurrency;
            # standing residents that grew since then can break the
            # promise — shed, or the item waits forever (and wedges
            # a draining shutdown behind it).  ONE ledger read per
            # poll, not per item: this runs under the dispatch lock
            adm = self.mem_budget.admissible_bytes()
            for it in [q for q in self._queue if q.mem_bytes > adm]:
                self._shed_locked(it, "over_memory")
        if self._running_total >= self.max_concurrency:
            return None
        for it in self._queue:
            quota = self._quota(it.tenant)
            if self._running_by_tenant.get(it.tenant, 0) \
                    >= quota.max_in_flight:
                continue
            if self.mem_budget is not None and \
                    not self.mem_budget.fits(it.mem_bytes):
                continue
            if self.mem_budget is not None:
                self.mem_budget.reserve(f"run:{it.seq}", it.mem_bytes,
                                        tenant=it.tenant)
            self._remove_locked(it)
            self._running_total += 1
            # a FRESH token per dispatch: the previous dispatch's
            # consumed yield must not instantly re-preempt the
            # resumed run (the chaos probe carries over — its
            # per-tenant boundary-poll windows keep counting)
            it.token = PreemptToken(
                probe=self._preempt_probe(it.tenant))
            self._running_items.append(it)
            n = self._running_by_tenant.get(it.tenant, 0) + 1
            self._running_by_tenant[it.tenant] = n
            self._stats["max_in_flight_total"] = max(
                self._stats["max_in_flight_total"], self._running_total)
            per = self._stats["max_in_flight_by_tenant"]
            per[it.tenant] = max(per.get(it.tenant, 0), n)
            return it
        return None

    def _preempt_probe(self, tenant: str):
        """The chaos seam of a run's preempt token: each poll (= one
        shard boundary of a preemptible job) consults the WORKER
        fault channel under the tenant's name, so a ``preempt`` fault
        with ``on_call=N`` yields the job at exactly its Nth
        boundary — deterministic on one VirtualClock."""
        if self.chaos is None:
            return None

        def probe():
            f = self.chaos.on_worker(tenant)
            if f is not None and f.get("mode") == "preempt":
                return "preempt"
            return None

        return probe

    def _worker(self) -> None:
        while True:
            with self._cv:
                item = self._pop_eligible_locked()
                while item is None:
                    if self._closed and not self._queue:
                        return
                    self._cv.wait()
                    item = self._pop_eligible_locked()
                waited = self.clock.monotonic() - item.submitted_at
                self.metrics.histogram("sched.queue_wait_s") \
                    .observe(waited)
                item.handle._mark_running()
            if self.mem_budget is not None:
                # journaled OUTSIDE the dispatch lock (disk latency
                # must not stall admission); per-ticket order holds —
                # this thread owns the ticket until its terminal
                self.journal.write(
                    "mem_reserved", ticket=item.seq,
                    tenant=item.tenant, bytes=item.mem_bytes,
                    reserved_total=self.mem_budget.reserved_bytes(),
                    budget_bytes=self.mem_budget.capacity_bytes)
            t0 = self.clock.monotonic()
            status, result, error = "completed", None, None
            preempted: JobPreempted | None = None
            runner = None
            try:
                # the pool's budget rides thread-locally into the run
                # (memory.current_budget), so residents created deep
                # inside an op — the streaming trainer's feed window —
                # hold standing reservations against the same ledger
                mem_scope = (_memory.budget_scope(self.mem_budget)
                             if self.mem_budget is not None
                             else contextlib.nullcontext())
                with preempt_scope(item.token), mem_scope:
                    runner = self._make_runner(item)
                    result = runner.run(item.data,
                                        backend=item.backend)
            except JobPreempted as e:
                # cooperative checkpoint-then-yield: the job saved its
                # cursor and stopped at a safe boundary.  NOT terminal
                # (unless cancelled) — ruled below under the lock.
                preempted = e
            except BaseException as e:  # noqa: BLE001 — the worker
                # must survive anything a run raises (including
                # chaos-injected process-death stand-ins); the error
                # is kept for the handle, classified by the runner's
                # own journal/report, and re-raised to the caller
                # from RunHandle.result()
                status, error = "failed", e
            wall = self.clock.monotonic() - t0
            if runner is not None:
                item.handle.report = runner.report
            released_total = None
            with self._cv:
                self._running_total -= 1
                self._running_by_tenant[item.tenant] -= 1
                self._running_items.remove(item)
                if self.mem_budget is not None:
                    # release INSIDE the dispatch lock: a waiting
                    # worker woken by the notify below must see the
                    # freed bytes when it re-runs the fit check
                    released_total = self.mem_budget.release(
                        f"run:{item.seq}")
                if preempted is None:
                    # a preempted segment's wall is partial work — it
                    # must not drag the deadline estimator down
                    self._ewma_run_s = (
                        wall if self._ewma_run_s <= 0.0
                        else (1 - _EWMA_ALPHA) * self._ewma_run_s
                        + _EWMA_ALPHA * wall)
                    self._stats[status] += 1
                else:
                    # a cancel() that landed BETWEEN the yield and
                    # this requeue armed a token nobody will poll
                    # again — honour it here or the handle never
                    # terminals (the job's cursor is saved either
                    # way)
                    if (preempted.reason != "cancelled"
                            and item.token.requested() == "cancelled"):
                        preempted = JobPreempted(
                            str(preempted), reason="cancelled",
                            cursor=preempted.cursor)
                    if preempted.reason != "cancelled":
                        # journal the yield BEFORE the ticket re-
                        # enters the queue (the same rule submit()
                        # follows for 'admitted'): with >1 worker the
                        # resumed segment can be dispatched the
                        # instant _insert_locked returns, and its
                        # events — even its terminal — must never
                        # precede this line
                        self.journal.write(
                            "preempted", ticket=item.seq,
                            tenant=item.tenant,
                            priority=item.priority,
                            trace_id=item.trace_id,
                            reason=preempted.reason,
                            cursor=preempted.cursor,
                            wall_s=round(wall, 4),
                            queue_depth=len(self._queue))
                        # requeue WITH the cursor: the job re-enters
                        # at its own priority/seq (FIFO claim kept)
                        # and the next dispatch resumes where it
                        # yielded.  submitted_at restarts — queue
                        # wait and the deadline_s ruling are PER
                        # SEGMENT (a job preempted past its original
                        # deadline already holds a cursor; shedding
                        # it for wall it spent RUNNING would punish
                        # exactly the cooperative yield the contract
                        # asks for)
                        item.preemptions += 1
                        self._stats["preempted"] += 1
                        item.handle._status = "queued"
                        item.submitted_at = self.clock.monotonic()
                        self._insert_locked(item)
                self._cv.notify_all()
            # terminal journal writes OUTSIDE the dispatch lock: disk
            # latency must not stall other tenants' admission or other
            # workers' dispatch.  Ordering is safe — this ticket's
            # "admitted" line was flushed before the item ever entered
            # the queue, and _Journal serializes concurrent appends.
            if self.mem_budget is not None:
                self.journal.write(
                    "mem_released", ticket=item.seq,
                    tenant=item.tenant, bytes=item.mem_bytes,
                    reserved_total=released_total)
            if preempted is not None:
                if preempted.reason == "cancelled":
                    # the cancel ruling: journaled terminal exactly
                    # once, as a shed — the job checkpointed, so a
                    # later identical submission resumes its cursor
                    self._stats["shed"] += 1
                    self.journal.write(
                        "shed", ticket=item.seq, tenant=item.tenant,
                        priority=item.priority, reason="cancelled",
                        trace_id=item.trace_id,
                        queue_depth=self.queue_depth())
                    self.metrics.counter(
                        "sched.shed", tenant=item.tenant,
                        reason="cancelled").inc()
                    item.handle._finish(
                        "shed", error=RunShed(
                            f"run {item.seq} (tenant "
                            f"{item.tenant!r}) cancelled while "
                            f"running: checkpoint-then-yield "
                            f"honoured", reason="cancelled",
                            tenant=item.tenant),
                        reason="cancelled")
                # (the non-cancelled yield was journaled under the
                # lock, before the requeue became dispatchable)
                continue
            if status == "completed":
                self.journal.write(
                    "run_completed", ticket=item.seq,
                    tenant=item.tenant, wall_s=round(wall, 4),
                    trace_id=item.trace_id,
                    degraded=bool(runner.report.degraded))
            else:
                self.journal.write(
                    "run_failed", ticket=item.seq,
                    tenant=item.tenant, wall_s=round(wall, 4),
                    trace_id=item.trace_id,
                    error=f"{type(error).__name__}: {error}")
            item.handle._finish(status, result=result, error=error,
                                reason=None if error is None
                                else type(error).__name__)
            # SLO rulings ride the worker loop's cadence — evaluated
            # OUTSIDE the dispatch lock (journal appends inside), and
            # rate-limited on the injectable clock
            if self.slo is not None:
                self.slo.maybe_evaluate()

    def _make_runner(self, item: _QueueItem) -> ResilientRunner:
        kw = dict(self.runner_defaults)
        kw.update(item.runner_kw)
        kw.setdefault("clock", self.clock)
        kw.setdefault("metrics", self.metrics)
        kw.setdefault("trace_id", item.trace_id or None)
        if self.chaos is not None:
            kw.setdefault("chaos", self.chaos)
        if kw.get("breaker") is None:
            # shared per-backend failure state — THE scheduler
            # contract: resolve from this pool's registry, not a
            # fresh run-local breaker (signature keyed by the run's
            # accelerator backend, matching what feeds the breaker).
            # An explicit breaker=None in runner kwargs means "use
            # the default" — which, inside a pool, is THIS registry,
            # never the runner's process-global fallback
            kw["breaker"] = self.breakers.get(
                run_backend_signature(
                    item.pipeline, item.backend,
                    kw.get("fallback_backend",
                           DEFAULT_FALLBACK_BACKEND)),
                clock=self.clock)
        return ResilientRunner(item.pipeline, **kw)

    # -- introspection / shutdown --------------------------------------
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def stats(self) -> dict:
        """Counters and high-water marks for quota audits: submission
        funnel totals, max observed global/per-tenant in-flight, max
        queue depth, and the shed audit trail
        ``(victim_priority, min_priority_left)`` — the soak's
        shed-ordering oracle."""
        with self._lock:
            out = dict(self._stats)
            out["max_in_flight_by_tenant"] = dict(
                self._stats["max_in_flight_by_tenant"])
            out["shed_audit"] = list(self._shed_audit)
            out["queue_depth"] = len(self._queue)
            out["ewma_run_s"] = self._ewma_run_s
        # breaker/budget snapshots OUTSIDE the dispatch lock: they
        # take other locks (and, federated, read files) — holding the
        # dispatch lock across that would stall every worker's
        # dispatch on a stats() caller (SCT011)
        out["breakers"] = self.breakers.snapshot()
        if self.mem_budget is not None:
            out["mem_budget"] = self.mem_budget.snapshot()
        return out

    def shutdown(self, wait: bool = True, shed_queued: bool = False,
                 timeout: float | None = None) -> bool:
        """Stop admitting; drain (default) or shed the queue
        (``shed_queued=True``, journaled ``reason="shutdown"``), join
        the workers, release the chaos hook, and write the metrics
        snapshot next to the journal (``metrics.json``) for
        ``tools/sctreport.py``.  Idempotent; returns True when
        teardown completed.  ``timeout`` bounds the TOTAL wait across
        all workers.  With ``wait=False`` — or a timeout that expires
        with workers still mid-run (returns False, with a warning) —
        the hook release and the metrics snapshot are DEFERRED:
        popping the chaos wrapper or snapshotting under live workers
        would change in-flight behavior; call again with ``wait=True``
        to finish teardown."""
        with self._cv:
            self._closed = True
            if self.mem_budget is not None:
                # admissions are over, so no later submission's chaos
                # consult can end a mem_pressure episode — leaving it
                # set would wedge the drain on queued work that fits
                # the REAL budget
                self.mem_budget.clear_pressure()
            if shed_queued:
                for it in list(self._queue):
                    self._shed_locked(it, "shutdown")
            self._cv.notify_all()
        if not wait:
            return False
        # SYSTEM clock on purpose (cf. failsafe.watch_process): these
        # are REAL thread joins — a virtual clock would rule a healthy
        # drain timed out instantly
        deadline = (None if timeout is None
                    else SYSTEM_CLOCK.monotonic() + timeout)
        for th in self._threads:
            th.join(None if deadline is None else
                    max(0.0, deadline - SYSTEM_CLOCK.monotonic()))
        if any(th.is_alive() for th in self._threads):
            import warnings

            warnings.warn(
                f"RunScheduler.shutdown: workers still running after "
                f"{timeout:g}s — teardown (chaos hook release, "
                f"metrics snapshot) DEFERRED; call shutdown() again "
                f"to finish.", RuntimeWarning, stacklevel=2)
            return False
        self._hooks.close()
        if self.journal.path:
            mpath = os.path.join(
                os.path.dirname(os.path.abspath(self.journal.path)),
                "metrics.json")
            try:
                self.metrics.write(mpath)
            except OSError as e:
                import warnings

                warnings.warn(
                    f"RunScheduler: could not write {mpath} "
                    f"({type(e).__name__}: {e})", RuntimeWarning,
                    stacklevel=2)
        return True
