"""Global configuration for sctools-tpu.

Capability parity target: the reference (dpeerlab/sctools) exposes a
``Transform`` operator registry with a ``backend=`` kwarg (see
BASELINE.json ``north_star``; the reference source itself was not
available — /root/reference was empty, see SURVEY.md §0).  This module
holds the knobs that govern how the TPU backend lays data out on the
device: block sizes aligned to the MXU/VPU tiling (128 lanes), compute
dtypes, and interpret-mode fallbacks for running Pallas kernels on CPU
in tests.
"""

from __future__ import annotations

import dataclasses
import os
from contextlib import contextmanager

import jax


def _on_tunnel() -> bool:
    """True when the default backend is the tunneled single-chip
    "axon" platform.  Detection must not key on any single string:
    round 4 measured ``jax.default_backend() == "tpu"`` on a live axon
    session (device_kind "TPU v5 lite") even though the platform was
    registered as ``axon`` — which silently disabled the stream_sync
    drain and let the deep async pipeline crash the remote worker.  So
    check the backend name, the device platforms, AND the configured
    platform list."""
    try:
        backend = jax.default_backend()
        if backend == "axon":
            return True
        if backend != "tpu":
            # cpu/gpu fallback after a tunnel death is NOT the tunnel —
            # don't pay per-shard drains there
            return False
        plats = str(getattr(jax.config, "jax_platforms", "") or "")
        if "axon" in plats.split(","):
            return True
        return any(getattr(d, "platform", "") == "axon"
                   for d in jax.devices())
    except Exception:
        return False


@dataclasses.dataclass
class Config:
    # Row/lane alignment.  TPU vector lanes are 128 wide; float32 tiles
    # are (8, 128).  All padded dimensions round up to these.
    lane: int = 128
    sublane: int = 8

    # Default row-block size for tiled kernels (queries per tile).
    row_block: int = 1024
    # Candidate-block size for blocked kNN (columns of the score tile).
    col_block: int = 2048
    # Bin count for the binned Pallas top-k merge (collision odds
    # ~k²/(2·knn_bins), see pallas_knn.py).  The kernel microbench
    # measures this exact value, so a routed atlas runs the same
    # kernel configuration the recall gate approved.
    knn_bins: int = 1024

    # Compute dtypes — THE NUMERICS CONTRACT (per-op):
    #
    # * per-cell / per-gene element ops and reductions (normalize.*,
    #   qc.*, gene stats/moments, segment sums) run float32 on every
    #   backend, ALWAYS — matmul_dtype does not touch them.  Their
    #   error sources on TPU are reduction order (~√N·ε relative) and
    #   the transcendental units (log1p measured ~1.1e-4 absolute in
    #   the log domain); bench.py run_config0 derives its gates from
    #   exactly this model.
    # * MXU matmuls where a float32 refinement recovers the result
    #   follow matmul_dtype: kNN coarse scoring (exact f32 re-rank
    #   after), PCA matvecs via spmm (CholeskyQR2 re-orthonormalises
    #   with HIGHEST-precision f32 Gram products), multi-chip ring
    #   scoring.  bfloat16 inputs + float32 accumulation under the
    #   bf16 policy; Precision.HIGHEST under the f32 policy (f32
    #   inputs at DEFAULT silently run bf16 MXU passes).
    # * decompositions and gates stay float32 HIGHEST regardless:
    #   cholesky_qr's Gram, the kNN refine re-rank, recall oracles.
    # * cross-shard statistics combine in float64 ON HOST (Chan's
    #   update, stream_stats) — per-shard device moments are centered
    #   sums of non-negative f32 terms so no cancellation survives.
    dtype: str = "float32"
    matmul_dtype: str = "float32"  # set to "bfloat16" for speed

    # Run Pallas kernels in interpreter mode (required off-TPU).
    # "auto" => interpret unless the default backend is a real TPU.
    pallas_interpret: str = "auto"

    # kNN search implementation: "xla" (blocked lax.top_k merge),
    # "pallas" (fused distance+top-k kernel, ops/pallas_knn.py),
    # "pallas_binned", or "auto".  Auto resolves to the EXACT Pallas
    # kernel on a real TPU backend, XLA elsewhere: the round-5 live
    # window finally measured the sweep hard-sync'd and roofline-
    # gated (artifacts/bench_stages_0731T0103.jsonl kernel_knn,
    # 131072x50 k=15: pallas 15.3x over blocked-XLA at idx agreement
    # 1.0; pallas_binned 63.9x but recall 0.9933 — that loss stacks
    # with the TPU-vs-CPU-oracle loss, so the binned variant stays
    # opt-in where a ~0.993 kernel-level recall is acceptable).
    knn_impl: str = "auto"

    # Graph-tail kernel family (ops/pallas_graph.py): implementation
    # behind graph.knn_matvec / knn_rmatvec / graph.jaccard and the
    # t-SNE repulsion sweep.  "gather" = the legacy whole-graph
    # gather/segment-sum path (the correctness fallback the escape
    # hatch restores), "xla" = the blocked row-tiled twins (bitwise
    # identical to gather, measured 5.5x on the CPU CI box at 32k
    # cells), "pallas" = the banded one-hot Mosaic kernels
    # (interpreter mode off-TPU — parity tests only), "auto" =
    # pallas on a real TPU backend, xla elsewhere.
    # Env: SCTOOLS_PALLAS_GRAPH (0 -> gather, 1 -> pallas, or an
    # explicit impl name).
    graph_impl: str = "auto"

    def resolved_graph_impl(self) -> str:
        from .ops.pallas_graph import resolved_impl

        return resolved_impl()

    # Coarse top-k operator for the blocked XLA path: "topk" (exact
    # lax.top_k over each merged tile) or "approx"
    # (lax.approx_max_k on the fresh tile — the TPU-native binned
    # PartialReduce — followed by a tiny EXACT merge with the running
    # carry, so per-block recall never compounds across blocks).  Use
    # "approx" with a refine>=k re-rank; the recall gate stays with
    # the caller/bench.
    knn_coarse: str = "topk"

    def resolved_knn_impl(self) -> str:
        if self.knn_impl == "auto":
            # measured paths only (see knn_impl comment): exact pallas
            # won the r5 hard-sync'd sweep on hardware; interpret-mode
            # pallas off-TPU would be pure overhead
            if not self.interpret_mode():
                return "pallas"
            return "xla"
        return self.knn_impl

    # Capacity rounding for the padded-ELL sparse format.
    capacity_multiple: int = 128

    # Device synthetic generation: rows per jitted generator program.
    # The full-shard (131072-row) generator program deterministically
    # crashed the tunneled TPU worker ("kernel fault") in the round-5
    # live window — three times, probe + both bench ramp attempts —
    # while every smaller program ran; generating a shard as a few
    # fixed-quantum chunks keeps each program small and the output
    # deterministic in (key, quantum) alone.
    gen_chunk_rows: int = 16384

    # Streaming PCA matvec/rmatvec: rows per jitted program.  -1 =
    # auto (32768 on the tunneled backend, whole-shard elsewhere);
    # 0 = whole shard; >0 explicit.  Execution-only — results are
    # identical, the chunk just bounds program size: the full-shard
    # stream_pca programs at 131072 rows WEDGED the tunneled worker
    # (round-5 probe step4, >19 min no progress) after the same-sized
    # datagen program crashed it outright.  32768 was chosen by an
    # on-chip sweep (round-5 session 3): 16384 -> 31.6 s, 32768 ->
    # 15.9 s, 65536 -> 14.0 s for the full 131k stream_pca, all
    # wedge-free; 32768 takes nearly all the win while keeping 4x
    # size margin from the wedge-prone whole-shard program.
    stream_row_chunk: int = -1

    def stream_row_chunk_rows(self) -> int:
        v = int(self.stream_row_chunk)
        if v < -1:
            # a negative typo must not silently select whole-shard
            # programs — the exact mode that wedges the tunneled worker
            raise ValueError(
                f"stream_row_chunk={v}: use -1 (auto), 0 (whole "
                f"shard) or a positive row count")
        if v == -1:
            return 32768 if _on_tunnel() else 0
        return v

    # f32-refine GATHER strategy: "blocked" (per-query-block row
    # gathers — fine while the candidate table fits on-chip) or
    # "sorted" (argsort the flattened candidate ids, gather in
    # ascending order, inverse-permute only the scores — built for
    # tables beyond on-chip residency, where the r5 session-3
    # measurement showed blocked refine at 1.3M costs ~10x its 131k
    # wall).  "auto" thresholds on the candidate-table size: blocked
    # below refine_sorted_min_cand, sorted at or above it, so library
    # callers at the >=786k regime the sorted gather was built for get
    # it without going through bench.py's A/B.  The sorted path
    # selects the same neighbours (scores differ only by f32
    # reduction-order ulps; tests pin set-equality + tolerance).
    # Env: SCTOOLS_TPU_REFINE_MODE.
    knn_refine_mode: str = "auto"
    # The 'auto' cutoff: 6 x 131072 — the r5 session-3 measurement
    # showed blocked refine at 1.3M candidates costing ~10x its 131k
    # wall, and 786432 is the same breakpoint bench.py's atlas A/B
    # brackets.  Callers below it keep the on-chip blocked gather.
    refine_sorted_min_cand: int = 786432

    def resolved_refine_mode(self, n_cand: int) -> str:
        if self.knn_refine_mode == "auto":
            return ("sorted" if n_cand >= self.refine_sorted_min_cand
                    else "blocked")
        return self.knn_refine_mode

    # f32-refine candidate count for the benchmarked kNN pipeline
    # (bench.py atlas path and tools/tpu_probe.py step4 — the probe
    # must compile the exact program the bench runs, so BOTH read this
    # one value).  32 was chosen by an on-chip measurement (round-5
    # session 3): top-15 set agreement 1.00000 vs refine=64 at
    # 131k x 50 PCA-like scores, with the refine pass 5.9 s -> 2.0 s
    # and its compile 31 s -> 14 s.  Env: SCTOOLS_BENCH_KNN_REFINE.
    bench_knn_refine: int = 32

    # Streaming loops: block on each shard's outputs before dispatching
    # the next shard.  "auto" => sync only on the tunneled single-chip
    # backend ("axon"), where deep async pipelines of large mixed
    # programs have been observed to crash or wedge the remote worker
    # (see bench.py's round-4 notes); on real local TPUs the async
    # overlap is the whole point and stays on.
    stream_sync: str = "auto"

    def stream_sync_enabled(self) -> bool:
        if self.stream_sync == "auto":
            return _on_tunnel()
        return self.stream_sync in ("1", "true", "True", True)

    def interpret_mode(self) -> bool:
        if self.pallas_interpret == "auto":
            return jax.default_backend() not in ("tpu", "axon")
        return self.pallas_interpret in ("1", "true", "True", True)


config = Config()

if os.environ.get("SCTOOLS_TPU_MATMUL_DTYPE"):
    config.matmul_dtype = os.environ["SCTOOLS_TPU_MATMUL_DTYPE"]
if os.environ.get("SCTOOLS_GEN_CHUNK_ROWS"):
    config.gen_chunk_rows = int(os.environ["SCTOOLS_GEN_CHUNK_ROWS"])
if os.environ.get("SCTOOLS_STREAM_ROW_CHUNK"):
    config.stream_row_chunk = int(os.environ["SCTOOLS_STREAM_ROW_CHUNK"])
if os.environ.get("SCTOOLS_BENCH_KNN_REFINE"):
    config.bench_knn_refine = int(os.environ["SCTOOLS_BENCH_KNN_REFINE"])
if os.environ.get("SCTOOLS_TPU_REFINE_MODE"):
    _rm = os.environ["SCTOOLS_TPU_REFINE_MODE"]
    if _rm not in ("auto", "blocked", "sorted"):
        raise ValueError(
            f"SCTOOLS_TPU_REFINE_MODE={_rm!r}: use auto, blocked or "
            f"sorted (an unknown value would silently run blocked "
            f"while the artifact records the bogus name)")
    config.knn_refine_mode = _rm
if os.environ.get("SCTOOLS_TPU_KNN_IMPL"):
    # lets the bench orchestrator route atlas children onto the kernel
    # sweep's measured winner within the same run
    _impl = os.environ["SCTOOLS_TPU_KNN_IMPL"]
    if _impl not in ("auto", "xla", "pallas", "pallas_binned"):
        raise ValueError(
            f"SCTOOLS_TPU_KNN_IMPL={_impl!r}: use auto, xla, pallas "
            f"or pallas_binned (an unknown value would silently run "
            f"xla while the artifact records the bogus name)")
    config.knn_impl = _impl
if os.environ.get("SCTOOLS_TPU_COL_BLOCK"):
    try:
        _cb = int(os.environ["SCTOOLS_TPU_COL_BLOCK"])
    except ValueError as e:
        raise ValueError(
            f"SCTOOLS_TPU_COL_BLOCK="
            f"{os.environ['SCTOOLS_TPU_COL_BLOCK']!r} is not an "
            f"integer") from e
    if _cb <= 0:
        raise ValueError(f"SCTOOLS_TPU_COL_BLOCK={_cb} must be > 0")
    config.col_block = _cb
if os.environ.get("SCTOOLS_TPU_PALLAS_INTERPRET"):
    config.pallas_interpret = os.environ["SCTOOLS_TPU_PALLAS_INTERPRET"]


def _parse_graph_impl(val: str) -> str:
    """SCTOOLS_PALLAS_GRAPH -> config.graph_impl.  ``0``/``false``
    restore the legacy gather path byte-for-byte (the escape hatch
    docs/ARCHITECTURE.md "Graph kernels & layout" documents);
    ``1``/``true`` force the Pallas kernels; explicit impl names pass
    through.  Unknown values raise — silently running gather while
    the bench artifact records the bogus name is the same trap the
    other env knobs guard against."""
    alias = {"0": "gather", "false": "gather", "1": "pallas",
             "true": "pallas"}
    impl = alias.get(val.strip().lower(), val.strip().lower())
    if impl not in ("auto", "gather", "xla", "pallas"):
        raise ValueError(
            f"SCTOOLS_PALLAS_GRAPH={val!r}: use 0/1, auto, gather, "
            f"xla or pallas")
    return impl


if os.environ.get("SCTOOLS_PALLAS_GRAPH"):
    config.graph_impl = _parse_graph_impl(
        os.environ["SCTOOLS_PALLAS_GRAPH"])


@contextmanager
def configure(**kw):
    """Temporarily override config fields.

    >>> with configure(matmul_dtype="bfloat16"):
    ...     ...
    """
    old = {k: getattr(config, k) for k in kw}
    try:
        for k, v in kw.items():
            setattr(config, k, v)
        yield config
    finally:
        for k, v in old.items():
            setattr(config, k, v)


def round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m
