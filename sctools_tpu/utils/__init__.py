"""Auxiliary subsystems: tracing (+ Perfetto export), the telemetry
metrics registry, checkpoint/resume (+ integrity), and the injectable
clock the resilience stack schedules through."""

from .trace import (  # noqa: F401
    all_spans, export_trace, profile, report, reset, span, spans,
)
from .telemetry import (  # noqa: F401
    MetricsRegistry, default_registry, instrument_calls,
)
from .checkpoint import (  # noqa: F401
    PipelineCheckpointer, data_digest, load_celldata,
    quarantine_checkpoint, save_celldata, verify_checkpoint,
)
from .vclock import SystemClock, VirtualClock  # noqa: F401
