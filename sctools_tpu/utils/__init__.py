"""Auxiliary subsystems: tracing and checkpoint/resume."""

from .trace import profile, report, reset, span, spans  # noqa: F401
from .checkpoint import (  # noqa: F401
    PipelineCheckpointer, load_celldata, save_celldata,
)
