"""Auxiliary subsystems: tracing, checkpoint/resume (+ integrity),
and the injectable clock the resilience stack schedules through."""

from .trace import profile, report, reset, span, spans  # noqa: F401
from .checkpoint import (  # noqa: F401
    PipelineCheckpointer, data_digest, load_celldata,
    quarantine_checkpoint, save_celldata, verify_checkpoint,
)
from .vclock import SystemClock, VirtualClock  # noqa: F401
