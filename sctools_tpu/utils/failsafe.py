"""Failure detection + containment for flaky accelerator backends.

Reference parity: the reference framework ships failure-detection
machinery in its runtime (source unavailable — SURVEY.md §0).  What
this module owns is the TPU-shaped version of that problem, learned
the hard way in rounds 1-4 of the bench (bench.py's module docstring
has the full history): a tunneled device can CRASH (worker dies, every
later call in the process raises UNAVAILABLE) or WEDGE (calls block
forever — even ``import``-time plugin registration can hang).  Neither
is recoverable in-process; containment means subprocesses + watchdogs.

* :func:`probe_device` — is the accelerator usable RIGHT NOW?  Runs a
  tiny matmul in a subprocess under a timeout, so a wedged tunnel
  costs ``timeout_s``, not forever, and a crashed worker cannot
  poison the caller's jax runtime.
* :func:`run_isolated` — run ``fn(*args)`` in a watched subprocess:
  killed on deadline or when it stops emitting heartbeats.  The child
  reports its result via a JSON file; the parent never imports jax.
* :class:`Heartbeat` — the child-side pulse emitter (any stderr line
  resets the parent's stall timer; ``beat()`` is a cheap explicit
  pulse for long device waits).
* :func:`classify_error` — the retryable-error taxonomy: is an
  exception a TRANSIENT device condition (retry with backoff), a
  DETERMINISTIC program error (retrying re-raises the same thing),
  or a RESOURCE exhaustion (device memory — neither: answered by the
  runner's OOM containment ladder, ``docs/ARCHITECTURE.md`` "Memory
  fault domain")?  The runner (``sctools_tpu/runner.py``) routes
  every step failure through this one function so the retry policy
  exists exactly once.
* :func:`classify_child_result` — the same taxonomy for a contained
  child's death: a deterministic traceback in the stderr tail FAILS
  FAST; only genuine device/timeout signatures (watchdog kills,
  tracebackless process death, UNAVAILABLE noise) retry.
* :class:`DeadlineToken` / :func:`deadline_scope` — a cooperative
  per-step wall-clock budget, threaded through the registry's
  call-wrapper hooks by the runner; overrun raises
  :class:`StepDeadlineExceeded` (a transient — retried/degraded like
  any other device error).
* :class:`CircuitBreaker` — after K classified-transient failures in
  a sliding window the breaker OPENS and the runner short-circuits
  further accelerator attempts straight to the degrade ruling (no
  more probe storms); after a cooldown it HALF-OPENS and a single
  successful probe closes it again.  Thread-safe, with an EXCLUSIVE
  half-open probe slot (``try_acquire_probe``) so contending runs
  never probe-storm a recovering device.
* :class:`BreakerRegistry` — process-wide breakers keyed by backend
  signature (one per BACKEND, not per run): the first run to trip
  the tpu breaker short-circuits every concurrent/queued run, and
  one half-open probe success un-degrades the whole pool.
  ``ResilientRunner`` resolves its default breaker here; the run
  scheduler (``sctools_tpu/scheduler.py``) hands every worker the
  same registry.

All scheduling here goes through the injectable clock
(``utils/vclock.py``), so every recovery path is tier-1 testable with
zero real sleeps.
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import re
import subprocess
import sys
import tempfile
import threading
import time

from .vclock import SYSTEM_CLOCK, Clock

# ---------------------------------------------------------------------------
# Retryable-error taxonomy
# ---------------------------------------------------------------------------

TRANSIENT = "transient"
DETERMINISTIC = "deterministic"
FATAL = "fatal"  # BaseException (process-death class): never retried
#: device memory exhausted (XlaRuntimeError RESOURCE_EXHAUSTED — the
#: canonical TPU production failure).  Deliberately NEITHER transient
#: nor deterministic: a retry at the same shapes recurs (the live set
#: is the live set — nothing 'recovers'), so blind retry only burns
#: budget, but the error says nothing about program correctness
#: either — the runner answers it with the OOM containment ladder
#: (unfuse → re-plan smaller → cpu) instead of retry-or-fail-fast,
#: and only a recurrence at the bottom rung is ruled deterministic.
RESOURCE = "resource"


class TransientDeviceError(RuntimeError):
    """A device condition worth retrying: the tunneled worker died or
    went unreachable (UNAVAILABLE), a watched child was killed for
    wedging, a heartbeat deadline passed.  Raise this to *assert*
    transience when the wrapped error type alone cannot prove it
    (e.g. a contained subprocess death reported by run_isolated)."""


class StepDeadlineExceeded(TransientDeviceError):
    """A step overran its cooperative wall-clock budget
    (:class:`DeadlineToken`).  Subclass of TransientDeviceError on
    purpose: an overrun is device-shaped (a wedged tunnel, an op that
    silently recompiled) — the runner retries/degrades it like any
    other transient, it never fails the run outright."""


class JobPreempted(Exception):
    """A long-running job cooperatively YIELDED at a safe boundary
    after checkpointing (preemption or cancellation — ``reason``
    says which; ``cursor`` is the job's machine-readable resume
    position).  Deliberately neither transient nor deterministic:
    the runner journals it as ``preempted`` and re-raises WITHOUT
    retrying (the job already saved its state and wants to stop),
    and the scheduler's worker either requeues the ticket (the job
    re-enters the queue with its cursor) or — ``reason ==
    "cancelled"`` — terminals it as shed."""

    def __init__(self, msg: str, *, reason: str = "preempt",
                 cursor: dict | None = None):
        super().__init__(msg)
        self.reason = reason
        self.cursor = cursor or {}


class DeviceOOMError(RuntimeError):
    """Device memory exhausted — the in-repo way to *assert* the
    RESOURCE classification when the wrapped error type alone cannot
    (jaxlib raises one XlaRuntimeError class for every status; chaos
    ``oom`` faults raise this directly).  Classified
    :data:`RESOURCE`, same as a real ``RESOURCE_EXHAUSTED``
    message."""


class DeterministicChildError(RuntimeError):
    """An isolated child died raising a deterministic program error
    (a ``ValueError``-class traceback in its stderr tail).  Registered
    in the DETERMINISTIC type set so the runner FAILS FAST instead of
    burning the retry budget and a ~90 s probe on an error that
    replays identically — the isolated-child misclassification the
    PR-1 review flagged."""


# Substrings (lowercased) that mark an accelerator-runtime error as
# transient.  jaxlib's XlaRuntimeError is one class for every gRPC
# status, so the status name in the MESSAGE is the only signal; the
# exact list is the round-1..5 crash corpus (bench.py history):
# UNAVAILABLE / DEADLINE_EXCEEDED from a dead or unreachable tunnel
# worker, ABORTED on worker restart, socket-level noise in between.
# RESOURCE_EXHAUSTED is deliberately absent — an HBM OOM recurs at
# the same shapes, so it is its own class (RESOURCE, matched by
# _RESOURCE_MARKERS below) answered by the runner's containment
# ladder, never by blind retry.
_TRANSIENT_MARKERS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "aborted",
    "connection reset",
    "connection refused",
    "connection closed",
    "socket closed",
    "broken pipe",
    "failed to connect",
    "heartbeat",
    # host-IO transients (the ingest tier's disk-shaped failures): a
    # flaky disk/NFS read raises OSError(EIO, "Input/output error") —
    # worth retrying, unlike ENOENT/ENOSPC which recur identically
    "input/output error",
    # a compute that raced a buffer eviction ("Array has been
    # deleted"): the serving tier's resident reference-model state can
    # be evicted out from under an in-flight query (device restart,
    # chaos evict_state) — the retried attempt re-enters the residency
    # ladder, re-places the state and succeeds, so failing fast here
    # would turn a survivable eviction into a lost query
    "been deleted",
)

# Substrings (lowercased) that mark an accelerator-runtime error as a
# device-memory exhaustion.  The message corpus: jaxlib's
# XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to
# allocate N bytes."), the TPU allocator's "Ran out of memory in
# memory space hbm. Used X of Y hbm.", and the BFC allocator's
# "Resource exhausted: Out of memory" shape.  Checked BEFORE the
# transient scan: an OOM message must never be mistaken for a
# retryable outage (several carry "failed to allocate device buffer"
# noise that says nothing transient).
_RESOURCE_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "ran out of memory",
    "memory space hbm",
)

_TRANSIENT_TYPES = (TransientDeviceError, TimeoutError, ConnectionError,
                    InterruptedError)
# Program errors: identical inputs give an identical raise — a retry
# can only burn the attempt budget.  Checked BEFORE the message scan
# so a ValueError whose text happens to contain "aborted" stays
# deterministic.
_DETERMINISTIC_TYPES = (ValueError, TypeError, KeyError, IndexError,
                        AttributeError, ArithmeticError, AssertionError,
                        NotImplementedError, DeterministicChildError)


def classify_error(exc: BaseException) -> str:
    """Classify ``exc`` as :data:`TRANSIENT`, :data:`DETERMINISTIC`,
    :data:`RESOURCE` or :data:`FATAL`.

    Type beats message: known-transient types (timeouts, connection
    drops, :class:`TransientDeviceError`), the explicit
    :class:`DeviceOOMError`, and known-deterministic types
    (ValueError/TypeError/shape errors …) are decided outright; only
    the remaining grey zone — jaxlib's single XlaRuntimeError class
    carrying any gRPC status — falls through to the status-marker
    message scan, RESOURCE markers first (an OOM message must never
    read as a retryable outage).  Unknown errors default to
    DETERMINISTIC: failing fast on a novel error is cheap to
    diagnose, retrying a permanent one is not."""
    if not isinstance(exc, Exception):
        return FATAL
    if isinstance(exc, DeviceOOMError):
        return RESOURCE
    if isinstance(exc, _TRANSIENT_TYPES):
        return TRANSIENT
    if isinstance(exc, _DETERMINISTIC_TYPES):
        return DETERMINISTIC
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(m in msg for m in _RESOURCE_MARKERS):
        return RESOURCE
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return TRANSIENT
    return DETERMINISTIC


def is_transient(exc: BaseException) -> bool:
    return classify_error(exc) == TRANSIENT


# ---------------------------------------------------------------------------
# Child-death taxonomy (run_isolated results)
# ---------------------------------------------------------------------------

# Terminal traceback lines in a child's stderr tail: "ValueError: msg",
# "numpy.linalg.LinAlgError: ...".  The LAST match is the exception the
# child actually died on (earlier ones are chained causes).
_CHILD_EXC_RE = re.compile(
    r"^([A-Za-z_][A-Za-z0-9_.]*(?:Error|Exception|Exceeded))\b(?::.*)?$",
    re.MULTILINE)

# Exception TYPE NAMES that prove the child's death deterministic:
# identical inputs replay the identical raise, so a retry only burns
# budget.  The classify_error type set plus the common concrete
# subclasses whose base-class identity a name match cannot see.
_DETERMINISTIC_CHILD_NAMES = frozenset(
    t.__name__ for t in _DETERMINISTIC_TYPES) | {
    "ZeroDivisionError", "FloatingPointError", "OverflowError",
    "RecursionError", "NameError", "UnboundLocalError", "LookupError",
    "ImportError", "ModuleNotFoundError", "UnicodeError",
    "PicklingError", "UnpicklingError",
}

# ...and the transient mirror (_TRANSIENT_TYPES + concrete subclasses):
# the same TimeoutError that retries in-process must retry when it
# killed a child instead
_TRANSIENT_CHILD_NAMES = frozenset(
    t.__name__ for t in _TRANSIENT_TYPES) | {
    "StepDeadlineExceeded", "ConnectionResetError",
    "ConnectionRefusedError", "ConnectionAbortedError",
    "BrokenPipeError",
}


def classify_child_result(res: dict, step: str) -> BaseException:
    """Map a non-``completed`` :func:`run_isolated` result to the
    exception the caller should raise.

    Rules, in order:

    * ``timeout`` / ``stalled`` — the watchdog killed a wedged child:
      :class:`TransientDeviceError` (retry/degrade).
    * a deterministic exception type name terminates the stderr
      traceback — :class:`DeterministicChildError` (FAIL FAST; the
      child will raise the same thing on every retry).
    * a RESOURCE_EXHAUSTED / out-of-memory signature in the tail —
      :class:`DeviceOOMError` (the parent's runner answers with the
      OOM containment ladder, exactly as it would for an in-process
      OOM; mirrors the in-process marker scan).
    * a transient exception type name (the ``_TRANSIENT_TYPES``
      mirror: timeouts, connection drops), or any named exception
      with a transient device marker (``UNAVAILABLE`` …) in the
      tail — transient, exactly as in-process classification would
      rule the same raise.
    * an *unknown* named exception with no device signature —
      deterministic (same default as :func:`classify_error`: failing
      fast on a novel error is cheap to diagnose).
    * no Python traceback at all — hard process death (SIGKILL,
      preemption, ``os._exit``): device-shaped, transient.
    """
    status = res.get("status")
    tail = res.get("stderr_tail", "") or ""
    detail = (f"(status={status}, rc={res.get('rc')}, "
              f"wall={res.get('wall_s')}s); stderr tail: {tail[-300:]}")
    if status in ("timeout", "stalled"):
        return TransientDeviceError(
            f"isolated step {step!r} {status} — watchdog killed a "
            f"wedged child {detail}")
    low = tail.lower()
    names = _CHILD_EXC_RE.findall(tail)
    if names:
        last = names[-1].rsplit(".", 1)[-1]
        if last in _DETERMINISTIC_CHILD_NAMES:
            return DeterministicChildError(
                f"isolated step {step!r} died on a deterministic "
                f"{names[-1]} — failing fast, a retry replays the "
                f"same raise {detail}")
        if any(m in low for m in _RESOURCE_MARKERS):
            return DeviceOOMError(
                f"isolated step {step!r} died on device memory "
                f"exhaustion ({names[-1]}) {detail}")
        if last in _TRANSIENT_CHILD_NAMES or \
                any(m in low for m in _TRANSIENT_MARKERS):
            return TransientDeviceError(
                f"isolated step {step!r} died on a device-shaped "
                f"{names[-1]} {detail}")
        return DeterministicChildError(
            f"isolated step {step!r} died on {names[-1]} — novel "
            f"error, failing fast {detail}")
    if any(m in low for m in _RESOURCE_MARKERS):
        return DeviceOOMError(
            f"isolated step {step!r} died with an out-of-memory "
            f"signature {detail}")
    if any(m in low for m in _TRANSIENT_MARKERS):
        return TransientDeviceError(
            f"isolated step {step!r} died with a device signature "
            f"{detail}")
    return TransientDeviceError(
        f"isolated step {step!r} died with no Python traceback — "
        f"hard process death (signal/preemption/_exit) {detail}")


# ---------------------------------------------------------------------------
# Cooperative per-step deadlines
# ---------------------------------------------------------------------------

#: innermost-last stack of active DeadlineTokens, PER THREAD (the
#: runner scopes one token per step attempt; with the scheduler's
#: worker pool several runs execute concurrently, and thread A's
#: deadline must never rule thread B's op overrun)
_DEADLINES = threading.local()


def _deadline_stack() -> list["DeadlineToken"]:
    stack = getattr(_DEADLINES, "stack", None)
    if stack is None:
        stack = _DEADLINES.stack = []
    return stack


class DeadlineToken:
    """A wall-clock budget for one unit of work, measured on an
    injectable clock.  COOPERATIVE: nothing interrupts a running op —
    the token is checked at every registry call-wrapper boundary (the
    runner installs the check for the whole run) and by any op that
    calls :func:`check_deadline` inside a long loop.  Overrun raises
    :class:`StepDeadlineExceeded`, which classifies transient."""

    def __init__(self, budget_s: float, clock: Clock | None = None,
                 label: str = ""):
        self.budget_s = float(budget_s)
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.label = label
        self._t0 = self.clock.monotonic()

    def elapsed(self) -> float:
        return self.clock.monotonic() - self._t0

    def remaining(self) -> float:
        return self.budget_s - self.elapsed()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self) -> None:
        if self.expired():
            raise StepDeadlineExceeded(
                f"deadline: {self.label or 'step'} exceeded its "
                f"{self.budget_s:g}s budget "
                f"(elapsed {self.elapsed():g}s)")


@contextlib.contextmanager
def deadline_scope(token: DeadlineToken):
    """Make ``token`` the current deadline for the enclosed block
    (on THIS thread — scopes never leak across scheduler workers)."""
    stack = _deadline_stack()
    stack.append(token)
    try:
        yield token
    finally:
        stack.remove(token)


def current_deadline() -> DeadlineToken | None:
    stack = _deadline_stack()
    return stack[-1] if stack else None


def check_deadline() -> None:
    """Raise :class:`StepDeadlineExceeded` if the innermost active
    deadline is overrun; no-op outside any :func:`deadline_scope`."""
    tok = current_deadline()
    if tok is not None:
        tok.check()


# ---------------------------------------------------------------------------
# Cooperative preemption (checkpoint-then-yield)
# ---------------------------------------------------------------------------

#: innermost-last stack of active PreemptTokens, PER THREAD (the
#: scheduler scopes one token per dispatched run on its own worker
#: thread; thread A's preemption must never yield thread B's job)
_PREEMPTS = threading.local()


def _preempt_stack() -> list["PreemptToken"]:
    stack = getattr(_PREEMPTS, "stack", None)
    if stack is None:
        stack = _PREEMPTS.stack = []
    return stack


class PreemptToken:
    """A cooperative checkpoint-then-yield signal for long-running
    jobs.  COOPERATIVE like :class:`DeadlineToken`: nothing interrupts
    a running step — the job polls :func:`check_preempt` at its safe
    boundaries (the out-of-core trainer checks at every SHARD
    boundary), and on a pending request it saves its cursor state and
    raises :class:`JobPreempted`.

    ``request(reason)`` arms the token (first reason wins —
    ``"cancelled"`` is terminal for the scheduler, anything else
    requeues).  ``probe`` is the chaos seam: an optional zero-arg
    callable consulted on every poll that may return a reason string
    (the scheduler wires it to ``ChaosMonkey.on_worker`` so a
    ``preempt`` fault fires at the Nth shard boundary on one
    VirtualClock with zero real sleeps)."""

    def __init__(self, probe=None):
        self.probe = probe
        self._reason: str | None = None
        self._lock = threading.Lock()

    def request(self, reason: str = "preempt") -> None:
        with self._lock:
            if self._reason is None:
                self._reason = reason

    def requested(self) -> str | None:
        """The armed reason WITHOUT consulting the chaos probe — the
        scheduler's victim pick peeks here (a peek must not burn a
        shard-boundary fault window)."""
        with self._lock:
            return self._reason

    def pending(self) -> str | None:
        """The pending yield reason, or ``None``.  Consults the chaos
        probe (if any) before answering, so injected preemptions are
        counted per poll — i.e. per shard boundary."""
        if self._reason is None and self.probe is not None:
            r = self.probe()
            if r:
                self.request(str(r))
        with self._lock:
            return self._reason


@contextlib.contextmanager
def preempt_scope(token: PreemptToken):
    """Make ``token`` the current preemption signal for the enclosed
    block (on THIS thread — scopes never leak across scheduler
    workers)."""
    stack = _preempt_stack()
    stack.append(token)
    try:
        yield token
    finally:
        stack.remove(token)


def current_preempt() -> PreemptToken | None:
    stack = _preempt_stack()
    return stack[-1] if stack else None


def check_preempt() -> str | None:
    """The pending yield reason of the innermost active token (or
    ``None`` — including outside any :func:`preempt_scope`).  The
    POLLING half only: the job decides when to act, because it must
    checkpoint BEFORE raising :class:`JobPreempted` — that ordering
    is the whole crash-safety contract."""
    tok = current_preempt()
    return tok.pending() if tok is not None else None


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Sliding-window circuit breaker over classified-transient
    accelerator failures.

    States (``.state``): ``closed`` (normal), ``open`` (accelerator
    attempts short-circuit straight to the degrade ruling — no retry
    storm, no probe storm), ``half_open`` (the cooldown elapsed: ONE
    probe is allowed; success closes the breaker, failure re-opens
    it).  The open→half-open transition is lazy — evaluated on read
    from the injectable clock, so tests drive it with a
    :class:`~sctools_tpu.utils.vclock.VirtualClock` and zero real
    sleeps.

    THREAD-SAFE: one breaker instance is shared by every concurrent
    run against the same backend (:class:`BreakerRegistry`), so all
    state transitions and snapshots happen under ``self.lock`` (a
    public, reentrant lock — callers that must observe a transition
    atomically, e.g. the runner's did-THIS-failure-open-it check,
    take it around their read-modify sequence).  The half-open probe
    is EXCLUSIVE: :meth:`try_acquire_probe` hands the single probe
    slot to exactly one caller per half-open episode; everyone else
    keeps treating the breaker as open until the probe resolves
    (``record_success`` closes / ``record_failure`` re-opens — both
    release the slot, as does :meth:`release_probe` for a probe that
    ended without a transient verdict).

    ``signature`` names the backend this breaker guards when it came
    from a :class:`BreakerRegistry` (``None`` for run-local
    breakers); it rides in every snapshot so journals say WHICH
    shared breaker ruled.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold: int = 3,
                 window_s: float = 300.0, cooldown_s: float = 60.0,
                 clock: Clock | None = None,
                 signature: str | None = None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.signature = signature
        self.lock = threading.RLock()
        self._failures: list[float] = []
        self._state = self.CLOSED
        self._opened_at: float | None = None
        self._probe_claimed = False
        self.opened_count = 0

    @property
    def state(self) -> str:
        with self.lock:
            if self._state == self.OPEN and self._opened_at is not None \
                    and self.clock.monotonic() - self._opened_at \
                    >= self.cooldown_s:
                self._state = self.HALF_OPEN
                self._probe_claimed = False
            return self._state

    def allow(self) -> bool:
        """May the caller attempt the accelerator right now?  False
        only while OPEN (cooldown not yet elapsed)."""
        return self.state != self.OPEN

    def try_acquire_probe(self) -> bool:
        """Claim the single half-open probe slot.  True for exactly
        ONE caller per half-open episode; False while not half-open
        or while another caller's probe is in flight.  The claim is
        released by ``record_success`` / ``record_failure`` /
        ``release_probe``."""
        with self.lock:
            if self.state != self.HALF_OPEN or self._probe_claimed:
                return False
            self._probe_claimed = True
            return True

    def release_probe(self) -> None:
        """Release a claimed probe slot WITHOUT a verdict (the probe
        attempt died on a deterministic/fatal error that says nothing
        about the device) — another caller may claim it."""
        with self.lock:
            self._probe_claimed = False

    def record_failure(self, probe: bool = True) -> str:
        """Record one classified-transient failure; returns the new
        state.  K failures inside the window trip CLOSED→OPEN; a
        PROBE failure while HALF_OPEN re-opens (the probe lied) and
        releases the probe slot.

        ``probe=False`` marks a failure from a caller that does NOT
        hold the half-open probe slot (e.g. a shared-breaker run
        whose attempt started before the cooldown elapsed): it counts
        into the window but neither re-opens the breaker nor touches
        another run's in-flight probe claim — in HALF_OPEN, only the
        probe holder's verdict moves the state.  The default stays
        ``True`` because the single-run breaker's only half-open
        failure IS the probe verdict."""
        with self.lock:
            now = self.clock.monotonic()
            self._failures.append(now)
            self._failures = [t for t in self._failures
                              if now - t <= self.window_s]
            st = self.state
            if (st == self.HALF_OPEN and probe) or (
                    st == self.CLOSED
                    and len(self._failures) >= self.failure_threshold):
                self._state = self.OPEN
                self._opened_at = now
                self.opened_count += 1
                self._probe_claimed = False
            elif probe:
                # a probe holder failing outside HALF_OPEN (state
                # moved on under it) still releases its claim
                self._probe_claimed = False
            return self.state

    def record_success(self) -> str:
        """A successful probe (or accelerator attempt): close the
        breaker and clear the failure window."""
        with self.lock:
            self._failures.clear()
            self._state = self.CLOSED
            self._opened_at = None
            self._probe_claimed = False
            return self._state

    def snapshot(self) -> dict:
        """Journal/report-ready view of the breaker.  Atomic: taken
        under the lock, so a concurrent ``record_failure`` can never
        tear ``state`` apart from ``failures_in_window``."""
        with self.lock:
            return {"state": self.state,
                    "failures_in_window": len(self._failures),
                    "opened_count": self.opened_count,
                    "failure_threshold": self.failure_threshold,
                    "window_s": self.window_s,
                    "cooldown_s": self.cooldown_s,
                    "signature": self.signature}


class BreakerRegistry:
    """Process-wide circuit breakers, ONE PER BACKEND — not per run.

    A fresh ``CircuitBreaker`` per ``ResilientRunner`` means ten
    concurrent runs each independently burn K failures rediscovering
    the same dead backend.  The registry keys breakers by a backend
    signature (``"tpu"``, ``"cpu"``, …): the first run to trip the
    tpu breaker short-circuits every queued run straight to the
    degrade ruling, and one half-open probe success un-degrades the
    whole pool.  ``get`` is get-or-create (creation kwargs — clock,
    thresholds — apply on FIRST sight of a signature only);
    ``snapshot`` is the report-ready view of every breaker.  The
    clock is injectable per registry AND per ``get``, so tests drive
    cooldowns on a ``VirtualClock`` with zero real sleeps.
    """

    def __init__(self, clock: Clock | None = None, **breaker_defaults):
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self._defaults = dict(breaker_defaults)
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    def get(self, signature: str, **kw) -> CircuitBreaker:
        """The shared breaker for ``signature`` (get-or-create).
        ``kw`` (``failure_threshold=``, ``clock=`` …) applies only
        when this call creates the breaker — later callers share the
        first creator's instance unchanged."""
        signature = str(signature)
        with self._lock:
            b = self._breakers.get(signature)
            if b is None:
                merged = {**self._defaults, **kw}
                merged.setdefault("clock", self.clock)
                b = self._breakers[signature] = CircuitBreaker(
                    signature=signature, **merged)
            return b

    def signatures(self) -> list[str]:
        with self._lock:
            return sorted(self._breakers)

    def snapshot(self) -> dict:
        """``{signature: breaker.snapshot()}`` for every breaker the
        process has seen — the scheduler/report view of shared
        failure state."""
        with self._lock:
            breakers = dict(self._breakers)
        return {sig: b.snapshot() for sig, b in sorted(breakers.items())}

    def reset(self) -> None:
        """Drop every breaker (tests; a long-lived service that wants
        to forget history).  Runs holding a breaker reference keep
        it — they just stop sharing with future runs."""
        with self._lock:
            self._breakers.clear()


#: the process-wide default registry — ``ResilientRunner`` resolves
#: its breaker here (keyed by the run's backend) unless handed an
#: explicit ``breaker=``; "process-wide" is the contract that makes
#: breaker state shared PER BACKEND, not per run
_DEFAULT_BREAKERS = BreakerRegistry()


def default_breaker_registry() -> BreakerRegistry:
    return _DEFAULT_BREAKERS


def probe_device(timeout_s: float = 90.0, platform: str | None = None) -> dict:
    """Check accelerator health from a throwaway subprocess.

    Returns ``{"ok": True, "device_kind", "wall_s"}`` on success or
    ``{"ok": False, "reason": "timeout"|"error", ...}``.  Safe to call
    even while the tunnel is wedged — the caller's process never
    touches jax.
    """
    code = (
        "import json,sys,time\n"
        "t0=time.time()\n"
        "import jax, jax.numpy as jnp\n"
        + (f"jax.config.update('jax_platforms', {platform!r})\n"
           if platform else "")
        + "x = jnp.ones((1024, 1024), jnp.bfloat16)\n"
        # fetch, not block_until_ready: the tunneled backend returns
        # from block_until_ready before execution (utils/sync.py) and
        # the probe's whole job is proving the device EXECUTES
        "assert float((x @ x)[0, 0]) == 1024.0\n"
        "print(json.dumps({'kind': jax.devices()[0].device_kind,"
        " 'wall_s': round(time.time()-t0, 2)}))\n"
    )
    t0 = time.time()
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"ok": False, "reason": "timeout",
                "wall_s": round(time.time() - t0, 1)}
    for line in reversed(p.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
            return {"ok": True, "device_kind": rec["kind"],
                    "wall_s": rec["wall_s"]}
        except (json.JSONDecodeError, KeyError, TypeError):
            # noise lines may parse as non-dict JSON ("123", "null");
            # this function's contract is to never raise for child
            # weirdness, only report ok=False
            continue
    return {"ok": False, "reason": "error", "rc": p.returncode,
            "stderr": (p.stderr or "")[-300:]}


class Heartbeat:
    """Child-side pulse for :func:`run_isolated`: any line on stderr
    resets the parent's stall timer."""

    def __init__(self, every_s: float = 15.0):
        self.every_s = every_s
        self._last = 0.0

    def beat(self, note: str = "") -> None:
        now = time.time()
        if now - self._last >= self.every_s:
            print(f"[heartbeat]{(' ' + note) if note else ''}",
                  file=sys.stderr, flush=True)
            self._last = now


def watch_process(cmd, *, timeout_s: float, stall_timeout_s: float,
                  env: dict | None = None, cwd: str | None = None,
                  on_line=None, extra_stop=None,
                  poll_s: float = 1.0) -> dict:
    """Run ``cmd`` under the crash/wedge watchdog — THE containment
    primitive (bench.py's phase runner and :func:`run_isolated` both
    build on it, so the kill/stall logic exists exactly once).

    The child's stderr is pumped line-by-line; every line resets the
    stall timer and is passed to ``on_line`` (when given).  The child
    is killed on deadline, on stall, or when ``extra_stop()`` returns
    a truthy status string (e.g. an outer budget check).  Timing is
    deliberately pinned to the SYSTEM clock (not injectable): this
    watches a REAL subprocess, and a virtual clock here would hot-spin
    the poll loop and rule a healthy child timed-out in milliseconds —
    tests shrink ``poll_s``/``stall_timeout_s`` instead.  Returns
    ``{"status": completed|crashed|stalled|timeout|<extra>, "rc",
    "wall_s", "lines", "stderr_tail"}``.
    """
    clk = SYSTEM_CLOCK
    t0 = clk.monotonic()
    proc = subprocess.Popen(cmd, stderr=subprocess.PIPE,
                            stdout=subprocess.DEVNULL, text=True,
                            env=env, cwd=cwd)
    last = [clk.monotonic()]
    lines = [0]
    tail: list = []

    def pump():
        for line in proc.stderr:
            last[0] = clk.monotonic()
            lines[0] += 1
            tail.append(line)
            if len(tail) > 50:
                del tail[:-50]
            if on_line is not None:
                on_line(line)

    th = threading.Thread(target=pump, daemon=True)
    th.start()
    status = "completed"
    while proc.poll() is None:
        clk.sleep(poll_s)
        now = clk.monotonic()
        extra = extra_stop() if extra_stop is not None else None
        if now - t0 > timeout_s:
            status = "timeout"
        elif now - last[0] > stall_timeout_s:
            status = "stalled"
        elif extra:
            status = extra
        else:
            continue
        proc.kill()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        break
    th.join(timeout=5)
    rc = proc.returncode
    if status == "completed" and rc not in (0, None):
        status = "crashed"
    return {"status": status, "rc": rc, "lines": lines[0],
            "wall_s": round(clk.monotonic() - t0, 1),
            "stderr_tail": "".join(tail)[-2000:]}


def _child_main(payload_path: str, result_path: str) -> int:
    with open(payload_path, "rb") as f:
        fn, args, kwargs = pickle.load(f)
    out = fn(*args, **kwargs)
    tmp = result_path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(out, f)
    os.replace(tmp, result_path)
    return 0


def run_isolated(fn, *args, timeout_s: float = 600.0,
                 stall_timeout_s: float = 240.0, env: dict | None = None,
                 **kwargs) -> dict:
    """Run ``fn(*args, **kwargs)`` in a watched subprocess.

    ``fn`` must be an importable module-level callable; arguments and
    the return value are pickled.  The child is killed when it exceeds
    ``timeout_s`` OR goes ``stall_timeout_s`` without writing a line
    to stderr (jax's own logging plus any :class:`Heartbeat` both
    count).  Returns::

        {"status": "completed"|"crashed"|"stalled"|"timeout",
         "result": <fn's return value, when completed>,
         "rc": int | None, "wall_s": float, "stderr_tail": str}

    A crashed or wedged TPU worker takes the CHILD down; the caller's
    process — and its jax runtime, if any — is untouched.
    """
    workdir = tempfile.mkdtemp(prefix="sctools_failsafe_")
    payload_path = os.path.join(workdir, "payload.pkl")
    result_path = os.path.join(workdir, "result.pkl")
    with open(payload_path, "wb") as f:
        pickle.dump((fn, args, kwargs), f)
    code = ("import sys\n"
            "from sctools_tpu.utils.failsafe import _child_main\n"
            "sys.exit(_child_main(sys.argv[1], sys.argv[2]))\n")
    child_env = dict(os.environ)
    # the payload pickles fn BY REFERENCE — the child must be able to
    # import the caller's module, so the caller's import path rides
    # along (covers pytest's rootdir insertions etc.)
    paths = [p for p in sys.path if p] + \
        [p for p in child_env.get("PYTHONPATH", "").split(os.pathsep) if p]
    child_env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(paths))
    child_env.update(env or {})
    out = watch_process(
        [sys.executable, "-c", code, payload_path, result_path],
        timeout_s=timeout_s, stall_timeout_s=stall_timeout_s,
        env=child_env)
    if out["status"] == "completed":
        try:
            with open(result_path, "rb") as f:
                out["result"] = pickle.load(f)
        except (OSError, pickle.UnpicklingError) as e:
            out["status"] = "crashed"
            out["stderr_tail"] += f"\n[result unreadable: {e!r}]"
    for p in (payload_path, result_path):
        try:
            os.remove(p)
        except OSError:
            pass
    try:
        os.rmdir(workdir)
    except OSError:
        pass
    return out
