"""Lightweight tracing for pipeline stages.

Reference parity: the reference framework ships a tracing subsystem
for its pipeline runtime (source unavailable — SURVEY.md §0).  Two
layers here:

* ``span(name)`` — nested wall-clock spans with an in-process tree,
  cheap enough to leave on.  ``sync=True`` inserts a device barrier
  before closing so the span charges queued TPU work to the stage
  that launched it (jax dispatch is async — without the barrier a
  span only measures Python time).
* ``profile(logdir)`` — wraps ``jax.profiler.trace`` for full XLA
  traces viewable in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from dataclasses import dataclass, field

# Process-wide monotonic span ids: external records (e.g. the
# ResilientRunner's JSONL run journal) reference a span by id instead
# of copying its timings, so one id joins the journal to the in-tree
# span and to the profiler trace that wraps it.
_span_ids = itertools.count(1)


@dataclass
class Span:
    name: str
    start: float
    duration: float = 0.0
    children: list = field(default_factory=list)
    id: int = 0
    meta: dict = field(default_factory=dict)

    def flat(self, depth=0):
        yield depth, self
        for c in self.children:
            yield from c.flat(depth + 1)


class _State(threading.local):
    def __init__(self):
        self.roots: list[Span] = []
        self.stack: list[Span] = []


_state = _State()


def _sync_device():
    """Barrier: enqueue a trivial computation and FETCH its value —
    device streams execute in order, so the fetch drains everything
    queued.  Fetch, not block_until_ready: the tunneled backend returns
    from block_until_ready before execution (see utils/sync.py)."""
    import jax.numpy as jnp

    from .sync import hard_sync

    hard_sync(jnp.zeros(()) + 0.0)


@contextlib.contextmanager
def span(name: str, sync: bool = False, meta: dict | None = None):
    """Context manager recording a (nested) timing span.

    ``meta`` attaches arbitrary journal-linkage payload (step index,
    attempt number, …); the span's process-unique ``id`` is the join
    key external records use."""
    s = Span(name, time.perf_counter(), id=next(_span_ids),
             meta=dict(meta) if meta else {})
    if _state.stack:
        _state.stack[-1].children.append(s)
    else:
        _state.roots.append(s)
    _state.stack.append(s)
    try:
        yield s
    finally:
        try:
            if sync:
                _sync_device()
        finally:
            # always record + pop, even if the device barrier raises —
            # otherwise the dead span corrupts the stack for the whole
            # thread
            s.duration = time.perf_counter() - s.start
            _state.stack.pop()


def spans() -> list[Span]:
    """Completed root spans of this thread."""
    return list(_state.roots)


def reset() -> None:
    _state.roots.clear()
    _state.stack.clear()


def report() -> str:
    """Indented text table of recorded spans."""
    lines = []
    for root in _state.roots:
        for depth, s in root.flat():
            lines.append(f"{'  ' * depth}{s.name:<40s} {s.duration * 1e3:10.2f} ms")
    return "\n".join(lines)


@contextlib.contextmanager
def profile(logdir: str):
    """Full XLA profiler trace (TensorBoard/Perfetto), when the
    backend supports it; degrades to a plain span otherwise."""
    import jax

    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as e:
        # degrade to a plain span, but say so — a silently missing
        # trace looks exactly like a trace that was never requested
        import warnings

        warnings.warn(
            f"profiler start_trace failed ({type(e).__name__}: {e}); "
            f"recording a wall-clock span only — no XLA trace in "
            f"{logdir}", stacklevel=2)
        started = False
    with span(f"profile:{logdir}"):
        try:
            yield
        finally:
            if started:
                try:
                    jax.profiler.stop_trace()
                except Exception as e:
                    import warnings

                    warnings.warn(
                        f"profiler stop_trace failed: {e!r}; trace in "
                        f"{logdir} may be incomplete", stacklevel=2)
