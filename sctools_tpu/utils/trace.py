"""Lightweight tracing for pipeline stages.

Reference parity: the reference framework ships a tracing subsystem
for its pipeline runtime (source unavailable — SURVEY.md §0).  Three
layers here:

* ``span(name)`` — nested wall-clock spans with an in-process tree,
  cheap enough to leave on.  ``sync=True`` inserts a device barrier
  before closing so the span charges queued TPU work to the stage
  that launched it (jax dispatch is async — without the barrier a
  span only measures Python time).
* the **process-wide collector** — span stacks are thread-local (a
  worker thread's nesting can't corrupt the main thread's), but
  completed trees from EVERY thread are visible to ``all_spans()`` /
  ``report()`` and cleared by ``reset()``; opt out with
  ``set_cross_thread(False)`` when a long-lived service must not
  accumulate span trees process-wide.
* **export** — ``export_trace(path)`` writes Chrome/Perfetto
  ``trace_event`` JSON; ``serialize_spans()``/``graft()`` move a span
  tree across a process boundary (how an isolated child's spans
  survive into the parent's trace instead of vanishing — the
  run-journal's ``span_id`` stays the join key throughout).
* ``profile(logdir)`` — wraps ``jax.profiler.trace`` for full XLA
  traces viewable in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
import weakref
from dataclasses import dataclass, field

# Process-wide monotonic span ids: external records (e.g. the
# ResilientRunner's JSONL run journal) reference a span by id instead
# of copying its timings, so one id joins the journal to the in-tree
# span and to the exported trace_event record that carries it.
_span_ids = itertools.count(1)


@dataclass
class Span:
    name: str
    start: float
    duration: float = 0.0
    children: list = field(default_factory=list)
    id: int = 0
    meta: dict = field(default_factory=dict)

    def flat(self, depth=0):
        yield depth, self
        for c in self.children:
            yield from c.flat(depth + 1)

    def to_dict(self) -> dict:
        """JSON-safe tree form (the isolation-handoff wire format)."""
        return {"name": self.name, "start": self.start,
                "duration": self.duration, "id": self.id,
                "meta": dict(self.meta),
                "children": [c.to_dict() for c in self.children]}


def span_from_dict(d: dict) -> Span:
    return Span(d["name"], float(d["start"]),
                duration=float(d.get("duration", 0.0)),
                id=int(d.get("id", 0)), meta=dict(d.get("meta") or {}),
                children=[span_from_dict(c)
                          for c in d.get("children", ())])


# ---------------------------------------------------------------------------
# Per-thread state + the process-wide collector
# ---------------------------------------------------------------------------

#: (thread weakref, thread name, THE SAME list object as that
#: thread's local roots) per recording thread.  Sharing the list is
#: the whole trick: clearing it from any thread resets the owning
#: thread's state too — the bug ``reset()`` used to have (it only
#: ever saw the calling thread).  Keyed by the thread OBJECT (weakly),
#: not its ident: CPython reuses idents after a join, and an
#: ident-keyed map would let a later thread silently evict a dead
#: thread's recorded spans.  Dead threads' entries are pruned on
#: ``reset()``.
_COLLECTOR_LOCK = threading.Lock()
_ALL_ROOTS: list[tuple] = []
_CROSS_THREAD = True


def set_cross_thread(enabled: bool) -> None:
    """Opt out of (or back into) process-wide collection.  While
    disabled, threads that record their FIRST span are not registered
    with the collector — their spans stay visible only to themselves
    (pre-collector behaviour); already-registered threads keep
    reporting.  Disabling is for long-lived services where
    accumulating every worker's span trees process-wide is a leak."""
    global _CROSS_THREAD
    _CROSS_THREAD = bool(enabled)


class _State(threading.local):
    def __init__(self):
        self.roots: list[Span] = []
        self.stack: list[Span] = []
        if _CROSS_THREAD:
            t = threading.current_thread()
            with _COLLECTOR_LOCK:
                _ALL_ROOTS.append((weakref.ref(t), t.name, self.roots))


_state = _State()


def _sync_device():
    """Barrier: enqueue a trivial computation and FETCH its value —
    device streams execute in order, so the fetch drains everything
    queued.  Fetch, not block_until_ready: the tunneled backend returns
    from block_until_ready before execution (see utils/sync.py)."""
    import jax.numpy as jnp

    from .sync import hard_sync

    hard_sync(jnp.zeros(()) + 0.0)


@contextlib.contextmanager
def span(name: str, sync: bool = False, meta: dict | None = None):
    """Context manager recording a (nested) timing span.

    ``meta`` attaches arbitrary journal-linkage payload (step index,
    attempt number, …); the span's process-unique ``id`` is the join
    key external records use."""
    s = Span(name, time.perf_counter(), id=next(_span_ids),
             meta=dict(meta) if meta else {})
    if _state.stack:
        _state.stack[-1].children.append(s)
    else:
        _state.roots.append(s)
    _state.stack.append(s)
    try:
        yield s
    finally:
        try:
            if sync:
                _sync_device()
        finally:
            # always record + pop, even if the device barrier raises —
            # otherwise the dead span corrupts the stack for the whole
            # thread
            s.duration = time.perf_counter() - s.start
            _state.stack.pop()


def spans() -> list[Span]:
    """Completed root spans of THIS thread (see ``all_spans`` for the
    process-wide view)."""
    return list(_state.roots)


def all_spans() -> list[Span]:
    """Root spans recorded by EVERY collected thread (living or
    dead), in start order."""
    with _COLLECTOR_LOCK:
        out = [s for _, _, roots in _ALL_ROOTS for s in roots]
    return sorted(out, key=lambda s: s.start)


def _threads() -> list[tuple[str, list[Span]]]:
    """(thread name, roots) per collected thread, calling thread
    first — the export's tid assignment."""
    me = threading.current_thread()
    with _COLLECTOR_LOCK:
        items = [(ref() is me, name, list(roots))
                 for ref, name, roots in _ALL_ROOTS]
    items.sort(key=lambda it: (not it[0], it[1]))
    return [(name, roots) for _, name, roots in items if roots]


def reset() -> None:
    """Clear recorded spans — including trees recorded by OTHER
    threads (their registered root lists are shared objects, so the
    owning thread's view empties too).  The calling thread's open-span
    stack is also cleared; other threads' in-flight stacks are left
    alone (popping a span out from under a running thread would
    corrupt its nesting)."""
    _state.roots.clear()
    _state.stack.clear()
    with _COLLECTOR_LOCK:
        for _, _, roots in _ALL_ROOTS:
            roots.clear()
        # a live thread's registration survives the reset (its next
        # span appends to the SAME list); dead threads' now-empty
        # entries are pruned so sequential short-lived workers don't
        # accumulate slots forever
        _ALL_ROOTS[:] = [e for e in _ALL_ROOTS if e[0]() is not None]


def report(all_threads: bool = True) -> str:
    """Indented text table of recorded spans.  Covers every collected
    thread by default (thread-name headers appear only when more than
    one thread recorded); ``all_threads=False`` restores the
    calling-thread-only view."""
    groups = (_threads() if all_threads
              else [(threading.current_thread().name, spans())])
    lines = []
    named = len(groups) > 1
    for tname, roots in groups:
        if named and roots:
            lines.append(f"[thread {tname}]")
        for root in roots:
            for depth, s in root.flat():
                lines.append(
                    f"{'  ' * depth}{s.name:<40s} "
                    f"{s.duration * 1e3:10.2f} ms")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Chrome/Perfetto trace_event export
# ---------------------------------------------------------------------------

def trace_events(span_list: list[Span] | None = None,
                 pid: int = 1,
                 process_name: str | None = None) -> list[dict]:
    """Flatten span trees into Chrome ``trace_event`` complete events
    (``ph: "X"``; ts/dur in microseconds, rebased so the earliest
    span starts at 0).  ``None`` exports every collected thread, one
    ``tid`` per thread with a thread-name metadata record.  Children
    are clamped inside their parent's [ts, ts+dur] window so float
    rounding can never make a trace viewer rule a child "outside" the
    stage that ran it.

    ``pid``/``process_name`` label the emitted events as one PROCESS
    row — the federated-merge seam: each fleet member gets its own
    pid (plus a ``process_name`` metadata record) so the whole fleet
    renders as separate process tracks in one timeline."""
    groups = ([(threading.current_thread().name, list(span_list))]
              if span_list is not None else _threads())
    starts = [s.start for _, roots in groups for s in roots]
    if not starts:
        return []
    t0 = min(starts)
    events: list[dict] = []
    if process_name is not None:
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": process_name}})

    def emit(s: Span, tid: int, lo: float, hi: float):
        ts = max((s.start - t0) * 1e6, lo)
        end = min(ts + s.duration * 1e6, hi) if hi is not None \
            else ts + s.duration * 1e6
        end = max(end, ts)  # a zero-length child never goes negative
        events.append({
            "name": s.name, "cat": "span", "ph": "X",
            "ts": round(ts, 3), "dur": round(end - ts, 3),
            "pid": pid, "tid": tid,
            "args": {"span_id": s.id, **s.meta},
        })
        for c in s.children:
            emit(c, tid, ts, end)

    for tid, (tname, roots) in enumerate(groups):
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": tname}})
        for root in roots:
            emit(root, tid, 0.0, None)
    return events


def export_trace(path: str, span_list: list[Span] | None = None,
                 append: bool = False) -> str:
    """Write a Perfetto/chrome://tracing-loadable ``trace.json``
    (atomic tmp + rename).  Returns ``path``.

    ``append=True`` merges into an existing file instead of
    clobbering it — the new events are shifted to start after the old
    ones end, so a crash → resume sequence (which APPENDS to the run
    journal) accumulates one trace covering every run, and the
    journal's span ids keep resolving.  An unreadable existing file
    is overwritten (it would fail every viewer anyway)."""
    events = trace_events(span_list)
    if append and os.path.exists(path):
        try:
            with open(path) as f:
                old = json.load(f)["traceEvents"]
        except (OSError, json.JSONDecodeError, KeyError, TypeError):
            old = None
        if old:
            end = max((e.get("ts", 0.0) + e.get("dur", 0.0)
                       for e in old if e.get("ph") == "X"),
                      default=0.0)
            shift = end + 10_000.0  # 10 ms of daylight between runs
            for e in events:
                if e.get("ph") == "X":
                    e["ts"] = round(e["ts"] + shift, 3)
            # drop duplicate thread-name metadata records
            seen = {(e.get("tid"), e["args"].get("name"))
                    for e in old if e.get("ph") == "M"}
            events = [e for e in events
                      if e.get("ph") != "M"
                      or (e.get("tid"), e["args"].get("name"))
                      not in seen]
            events = old + events
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def fleet_trace_events(processes) -> list[dict]:
    """The federated merge: flatten MANY processes' span trees into
    one trace_event stream, one ``pid`` (and ``process_name``
    metadata record) per fleet member.

    ``processes`` is ``[(process_name, parts), ...]`` where ``parts``
    is a list of root :class:`Span` objects or serialized span dicts
    (the :func:`serialize_spans` wire form the federation result-file
    handoff carries).  Each process is rebased to its OWN earliest
    span: ``perf_counter`` epochs are not comparable across
    processes, so per-process zero is the honest alignment — the
    trace shows each member's internal causality, and the journal's
    wall-clock ``ts`` fields remain the cross-process ordering
    record.  Members with no spans are skipped (no empty rows)."""
    events: list[dict] = []
    for pid, (pname, parts) in enumerate(processes, start=1):
        roots = [p if isinstance(p, Span) else span_from_dict(p)
                 for p in (parts or ())]
        if not roots:
            continue
        events.extend(trace_events(roots, pid=pid,
                                   process_name=str(pname)))
    return events


def export_fleet_trace(path: str, processes) -> str:
    """Write the federated merge of ``processes`` (see
    :func:`fleet_trace_events`) as one Perfetto-loadable
    ``trace.json`` — the whole fleet on one timeline.  Atomic tmp +
    rename; returns ``path``."""
    doc = {"traceEvents": fleet_trace_events(processes),
           "displayTimeUnit": "ms"}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# Cross-process span handoff (isolated children)
# ---------------------------------------------------------------------------

def serialize_spans(span_list: list[Span] | None = None) -> list[dict]:
    """JSON-safe dump of root spans (default: this thread's) for a
    handoff file — the form an isolated child returns its tree in."""
    return [s.to_dict() for s in (span_list if span_list is not None
                                  else spans())]


def graft(span_dicts: list[dict], rebase: bool = True) -> list[Span]:
    """Attach a serialized span tree (from :func:`serialize_spans`,
    typically recorded in an isolated child process) under the
    CURRENT span — or as roots of this thread if none is open.

    Every grafted span gets a FRESH id from this process's counter
    (the child's counter starts at 1 too, so its ids would collide
    with the parent's; the original id is kept as
    ``meta["child_span_id"]`` for cross-referencing the child's own
    artifacts).  With ``rebase=True`` (default) the tree is shifted
    onto this process's clock so the children END at the graft point
    — a child's ``perf_counter`` epoch is meaningless here, and the
    graft happens right after the child finished."""
    roots = [span_from_dict(d) for d in span_dicts]
    if not roots:
        return []

    def reid(s: Span):
        if s.id:
            s.meta.setdefault("child_span_id", s.id)
        s.id = next(_span_ids)
        for c in s.children:
            reid(c)

    for r in roots:
        reid(r)
    if rebase:
        end = max(r.start + r.duration for r in roots)
        offset = time.perf_counter() - end
        if _state.stack:
            # never rebase a child to before its new parent's start:
            # a child tree whose recorded duration exceeds the
            # parent's elapsed-so-far would otherwise "begin" before
            # the span it is grafted under (ending-at-now yields; the
            # parent is still open, so containment holds either way)
            first = min(r.start for r in roots)
            offset = max(offset, _state.stack[-1].start - first)
        def shift(s: Span):
            s.start += offset
            for c in s.children:
                shift(c)
        for r in roots:
            shift(r)
    if _state.stack:
        _state.stack[-1].children.extend(roots)
    else:
        _state.roots.extend(roots)
    return roots


@contextlib.contextmanager
def profile(logdir: str):
    """Full XLA profiler trace (TensorBoard/Perfetto), when the
    backend supports it; degrades to a plain span otherwise."""
    import jax

    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as e:
        # degrade to a plain span, but say so — a silently missing
        # trace looks exactly like a trace that was never requested
        import warnings

        warnings.warn(
            f"profiler start_trace failed ({type(e).__name__}: {e}); "
            f"recording a wall-clock span only — no XLA trace in "
            f"{logdir}", stacklevel=2)
        started = False
    with span(f"profile:{logdir}"):
        try:
            yield
        finally:
            if started:
                try:
                    jax.profiler.stop_trace()
                except Exception as e:
                    import warnings

                    warnings.warn(
                        f"profiler stop_trace failed: {e!r}; trace in "
                        f"{logdir} may be incomplete", stacklevel=2)
