"""Deterministic fault injection for pipeline transforms.

The failure modes this harness injects are the ones the live TPU
tunnel actually produced in bench rounds 1–5 (bench.py's history):
``UNAVAILABLE`` raises from a dead worker, wedges that hang a call
past every deadline, corrupted results, and hard process death.  The
resilient runner (``sctools_tpu/runner.py``) exists to survive those;
this module exists so its recovery paths are exercised in tier-1 CPU
tests instead of only on a live flaky tunnel.

Everything is deterministic and seedable: a :class:`ChaosMonkey` with
the same faults and seed injects the same failures at the same calls,
so a recovery test is exactly reproducible.

>>> from sctools_tpu.utils.chaos import ChaosMonkey, Fault
>>> monkey = ChaosMonkey([Fault("hvg.select", "unavailable", times=2)])
>>> with monkey.activate():                 # registry-level wrap
...     out = runner.run(data)              # first 2 hvg calls raise
>>> monkey.injected                         # what actually fired
"""

from __future__ import annotations

import contextlib
import dataclasses
import fnmatch
import os
import random
import threading

import numpy as np

from .. import registry
from .failsafe import (DeviceOOMError, TransientDeviceError,
                       check_deadline)
from .vclock import SYSTEM_CLOCK

MODES = ("unavailable", "hang", "wedge", "corrupt",
         "corrupt_checkpoint", "crash", "kill", "reject_storm",
         "slow_read", "truncate_shard", "io_error",
         "kill_worker", "lease_wedge", "preempt",
         "evict_state", "corrupt_model",
         "oom", "mem_pressure", "stage_crash",
         "net_drop", "net_delay", "net_dup", "net_partition")

# which hook channel each mode fires on: most modes wrap the op CALL;
# corrupt_checkpoint fires through the runner's on_checkpoint hook,
# reject_storm through the scheduler's on_admission hook (where the
# fault's ``op`` pattern matches TENANT names, not transform names),
# the three IO modes through the shard-read scheduler's on_io hook
# (pattern matches CHUNK file basenames, e.g. "chunk-00002"), and the
# WORKER-channel modes through on_worker — consulted by the
# federation supervisor per heartbeat (kill_worker / lease_wedge,
# pattern matches WORKER names like "w0") AND by the run scheduler's
# preemption probe per SHARD BOUNDARY of a preemptible job (preempt,
# pattern matches the submission's TENANT name; ``on_call=N`` = the
# Nth boundary poll), and the SERVING-channel modes through
# on_serving — consulted by the annotation service once per QUERY
# EXECUTION (evict_state / corrupt_model, pattern matches the SERVICE
# name; ``on_call=N`` = the Nth query executed against the resident
# model).  ``oom`` stays on the op CALL channel (a RESOURCE_EXHAUSTED
# raise from a matching op — the canonical TPU production failure,
# driving the runner's whole containment ladder); ``mem_pressure``
# fires through on_memory — consulted by the run scheduler once per
# SUBMISSION against its MemoryBudget's name, shrinking the apparent
# budget for the fault's window.  ``stage_crash`` fires through
# on_factory — consulted by the annotation factory once per stage
# ENTRY (pattern matches "<factory>/<stage>" composites like
# "fac/build"; ``on_call=N`` = the Nth entry into that stage), the
# deterministic in-process stand-in for a worker SIGKILLed BETWEEN
# pipeline stages — the cross-domain resume seam the factory's
# cursor/fingerprint ladder exists for.  The four ``net_*`` modes
# fire through on_network — consulted by a Transport
# (sctools_tpu/transport.py) once per SEND ATTEMPT toward a peer
# (pattern matches the PEER name, windows specced ``"<peer>@net"``;
# ``on_call=N`` = the Nth attempt toward that peer): net_drop loses
# the attempt, net_delay defers it by ``slow_s`` on the transport's
# injectable clock, net_dup delivers the frame twice (the per-peer
# sequence dedup must make it at-most-once), net_partition fails
# EVERY attempt inside the window (the split-brain case: breakers go
# LOCAL-ONLY, leases ride to lease_timeout_s, heal reconciles by
# epoch).
_MODE_CHANNEL = {"corrupt_checkpoint": "checkpoint",
                 "reject_storm": "admission",
                 "slow_read": "io", "truncate_shard": "io",
                 "io_error": "io",
                 "kill_worker": "worker", "lease_wedge": "worker",
                 "preempt": "worker",
                 "evict_state": "serving", "corrupt_model": "serving",
                 "mem_pressure": "memory",
                 "stage_crash": "factory",
                 "net_drop": "net", "net_delay": "net",
                 "net_dup": "net", "net_partition": "net"}


class ChaosCrash(BaseException):
    """Simulated hard process death (preemption, SIGKILL, worker
    segfault).  Deliberately a ``BaseException``: no ``except
    Exception`` handler — including the resilient runner's retry loop
    — survives it in-process, exactly like the real thing.  Recovery
    from it is a NEW run resuming from checkpoints."""


@dataclasses.dataclass
class Fault:
    """One injected failure rule.

    ``op`` is an fnmatch pattern over dotted transform names
    (``"hvg.select"``, ``"normalize.*"``); ``backend`` optionally
    restricts the fault to one backend (so a TPU-only outage leaves
    the CPU fallback healthy).  The fault fires on calls
    ``on_call .. on_call+times-1`` of a matching op (1-based count
    per op name; ``times=-1`` means forever), each firing gated by
    probability ``p`` drawn from the monkey's seeded stream.
    """

    op: str
    mode: str  # one of MODES
    on_call: int = 1
    times: int = 1
    backend: str | None = None
    p: float = 1.0

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(
                f"Fault mode {self.mode!r}: use one of {MODES}")


def _corrupt_value(out, rng: random.Random):
    """Deterministically damage a transform result: one element of X
    (CellData) or of the array itself becomes NaN — the silent-wrong-
    answer failure a health probe cannot see."""
    import scipy.sparse as sp

    def damage_dense(a):
        a = np.array(a, np.float32, copy=True)
        if a.size:
            a.flat[rng.randrange(a.size)] = np.nan
        return a

    if hasattr(out, "X") and hasattr(out, "to_host"):  # CellData
        host = out.to_host()
        X = host.X
        if sp.issparse(X):
            # raw 10x counts are commonly integer — cast like the
            # dense branch, or the NaN assignment itself raises
            X = (X.astype(np.float32) if X.data.dtype.kind != "f"
                 else X.copy())
            if X.data.size:
                X.data[rng.randrange(X.data.size)] = np.nan
        else:
            X = damage_dense(X)
        return host.with_X(X)
    if isinstance(out, np.ndarray):
        return damage_dense(out)
    return out  # non-array result: nothing meaningful to corrupt


class ChaosMonkey:
    """Wraps registered transforms (via the registry's call-wrapper
    hook) to inject :class:`Fault` rules.

    * ``unavailable`` — raise :class:`TransientDeviceError` with an
      ``UNAVAILABLE`` message (classified transient → retried).
    * ``hang`` — sleep ``hang_s`` before proceeding (a wedge; under
      subprocess containment the watchdog kills the child).  The
      sleeper is injectable so tier-1 tests hang no real clock.
    * ``wedge`` — advance the monkey's ``clock`` by ``wedge_s`` and
      then check the cooperative deadline: with the SAME (virtual)
      clock shared with a ResilientRunner's ``step_deadline_s``
      token, the op overruns its budget and raises
      ``StepDeadlineExceeded`` — the in-process wedge the per-step
      deadline layer exists to bound, with zero real sleeps.
    * ``corrupt`` — run the op, then deterministically NaN one element
      of the result.
    * ``corrupt_checkpoint`` — never fires on the op call itself;
      fires through :meth:`on_checkpoint` (the runner calls it after
      every step-checkpoint save) and flips bytes of the file on
      disk — the bit-rot/truncation damage the digest verify +
      quarantine path exists to catch on the next resume.
    * ``oom`` — raise :class:`~.failsafe.DeviceOOMError` with the
      real XlaRuntimeError ``RESOURCE_EXHAUSTED: Out of memory``
      message shape (classified :data:`~.failsafe.RESOURCE`): the
      canonical TPU production failure, driving the runner's OOM
      containment ladder (unfuse → re-plan smaller → cpu).  Restrict
      with ``backend="tpu"`` so the cpu rung completes.
    * ``mem_pressure`` — the MEMORY channel (:meth:`on_memory`,
      consulted by the run scheduler once per submission under its
      ``MemoryBudget``'s name; ``on_call``/``times`` windows count
      submissions).  Only RULES — the scheduler shrinks the budget's
      apparent capacity to the monkey's ``pressure_frac`` while the
      fault fires and restores it when the window passes, so
      dispatch-time fit rulings tighten mid-soak with zero real
      sleeps.
    * ``crash`` — raise :class:`ChaosCrash` (in-process stand-in for
      process death; aborts the whole run, testing resume).
    * ``reject_storm`` — never fires on an op call; fires through
      :meth:`on_admission` (the run scheduler consults it for every
      ``submit()``) and makes admission REJECT the submission
      (``RunRejected(reason="reject_storm")``).  The fault's ``op``
      pattern matches TENANT names on this channel
      (``Fault("tenant-a", "reject_storm", times=3)``), so the
      shed/reject paths are testable under the same seeded spec as
      device faults.
    * ``kill`` — ``os._exit(9)``: REAL process death.  Only meaningful
      inside a contained child (``failsafe.run_isolated``); in the
      parent process it takes the test runner down with it.
    * ``kill_worker`` / ``lease_wedge`` — the WORKER channel
      (:meth:`on_worker`, consulted by the federation supervisor at
      every heartbeat it receives; the fault's ``op`` pattern matches
      WORKER names like ``"w0"``, counted per worker under
      ``"<worker>@worker"``).  Both only RULE — the supervisor owns
      the subprocess and the lease clock, so it implements the
      semantics: ``kill_worker`` → SIGKILL the worker's pid (hard
      host/process death mid-run; the reap → fence → requeue →
      respawn ladder must recover every in-flight ticket);
      ``lease_wedge`` → stop crediting that worker's heartbeats (the
      worker is ALIVE but its lease goes stale — the split-brain
      partition case: the supervisor must FENCE the old worker before
      requeueing, or both could commit).
    * ``preempt`` — the run scheduler's cooperative checkpoint-then-
      yield ruling, also on the WORKER channel: the scheduler's
      preemption probe consults :meth:`on_worker` at every SHARD
      BOUNDARY of a running preemptible job (the fault's ``op``
      pattern matches the submission's TENANT name, so
      ``Fault("train-lab", "preempt", on_call=3)`` preempts at the
      3rd boundary).  The mode only RULES — the trainer saves its
      cursor checkpoint and raises ``JobPreempted``, the scheduler
      requeues the ticket — so the whole preempt → requeue → resume
      ladder runs on one VirtualClock with zero real sleeps.
    * ``evict_state`` / ``corrupt_model`` — the SERVING channel
      (:meth:`on_serving`, consulted by the annotation service
      (``sctools_tpu/serving.py``) once per query execution; the
      fault's ``op`` pattern matches the SERVICE name, counted per
      service under ``"<service>@serving"``).  ``evict_state`` only
      RULES — the service owns the resident buffers, so it implements
      the semantics (delete the device-resident reference-model
      arrays, the HBM-eviction / device-restart failure the residency
      ladder's re-place rung exists for).  ``corrupt_model`` damages
      the model ARTIFACT on disk here (XOR byte flips, like
      ``corrupt_checkpoint`` — the monkey owns file damage) and the
      service additionally drops its in-memory state, so the ladder's
      reload-from-artifact rung meets the corrupt file and the digest
      verify quarantines it + falls back to the ``.prev``
      generation.
    * ``slow_read`` / ``truncate_shard`` / ``io_error`` — the IO
      channel (:meth:`on_io`, consulted by the shard-read scheduler
      for every chunk read; the fault's ``op`` pattern matches CHUNK
      file basenames like ``"chunk-00002"``).  ``truncate_shard``
      damages the chunk file on disk (truncates it to half its bytes
      — the partial-write/bit-rot failure the digest verify +
      quarantine path exists to catch); ``slow_read`` and
      ``io_error`` only RULE (the hook returns the firing mode plus
      ``slow_s``) — the scheduler implements the semantics, because
      it owns the injectable clock and the read concurrency: an
      injected EIO raises transient and retries, a slow read defers
      the result's virtual ready-time so the hedge/SLO ladder runs
      with zero real sleeps.
    * ``net_drop`` / ``net_delay`` / ``net_dup`` / ``net_partition``
      — the NETWORK channel (:meth:`on_network`, consulted by a
      ``Transport`` once per send attempt toward a peer; the fault's
      ``op`` pattern matches the PEER name, counted per peer under
      ``"<peer>@net"``).  All four only RULE — the transport owns
      the socket and the injectable clock, so it implements the
      semantics (drop the attempt / defer it ``slow_s`` on the clock
      / frame it twice / fail every attempt in the window), which is
      what keeps partition soaks at zero real sleeps.

    ``calls`` counts invocations per op name (checkpoint saves count
    separately under ``"<op>@checkpoint"``, admission consults under
    ``"<tenant>@admission"``, serving consults under
    ``"<service>@serving"``, budget consults under
    ``"<budget>@memory"``, send attempts under ``"<peer>@net"``);
    ``injected`` logs every
    firing as ``{"op", "call", "mode", "backend"}`` — two monkeys with
    equal faults/seed driving the same workload produce identical
    logs (the determinism contract tier-1 pins).
    """

    def __init__(self, faults, seed: int = 0, hang_s: float = 3600.0,
                 sleep=None, clock=None, wedge_s: float | None = None,
                 slow_s: float = 30.0, pressure_frac: float = 0.5):
        self.faults = list(faults)
        self.seed = seed
        self.hang_s = hang_s
        self.clock = clock
        self.wedge_s = hang_s if wedge_s is None else wedge_s
        self.slow_s = float(slow_s)
        self.pressure_frac = float(pressure_frac)
        self.sleep = (sleep if sleep is not None
                      else (clock or SYSTEM_CLOCK).sleep)
        self.calls: dict[str, int] = {}
        self.injected: list[dict] = []
        self._rng = random.Random(seed)
        # one monkey serves every scheduler worker thread (the chaos
        # wrapper is deliberately GLOBAL): the count-increment →
        # fault-match → injected-log sequence must be atomic or
        # concurrent calls lose counts and shift every Nth-call
        # window.  Op execution itself never runs under this lock.
        self._lock = threading.RLock()
        # activation refcount: concurrent activate() calls (e.g. two
        # pool workers whose runners both carry chaos=) must install
        # the wrapper exactly once and pop it only when the LAST
        # activation exits — an unguarded membership check could
        # double-install, and a finishing run could strip the wrapper
        # out from under a concurrent one
        self._active = 0

    # -- picklable spec: forwards the monkey (with its call counts)
    # into failsafe.run_isolated children so Nth-call semantics span
    # the containment boundary
    def spec(self) -> dict:
        with self._lock:
            calls = dict(self.calls)
        return {"faults": [dataclasses.asdict(f) for f in self.faults],
                "seed": self.seed, "hang_s": self.hang_s,
                "wedge_s": self.wedge_s, "slow_s": self.slow_s,
                "pressure_frac": self.pressure_frac,
                "calls": calls}

    @classmethod
    def from_spec(cls, spec: dict) -> "ChaosMonkey":
        m = cls([Fault(**f) for f in spec["faults"]], seed=spec["seed"],
                hang_s=spec["hang_s"], wedge_s=spec.get("wedge_s"),
                slow_s=spec.get("slow_s", 30.0),
                pressure_frac=spec.get("pressure_frac", 0.5))
        m.calls = dict(spec.get("calls", {}))
        return m

    def note_external_call(self, name: str) -> None:
        """Record that a contained child invoked ``name`` once (the
        parent's counter must advance even though the wrap ran in the
        child's process)."""
        with self._lock:
            self.calls[name] = self.calls.get(name, 0) + 1

    def on_admission(self, tenant: str,
                     backend: str | None = None) -> bool:
        """Scheduler hook, consulted at every ``submit()``: True when
        a matching ``reject_storm`` fault fires — the scheduler then
        rejects the submission at admission.  On this channel the
        fault's ``op`` pattern matches the TENANT name; call counting
        is per tenant under ``"<tenant>@admission"``, so
        ``on_call``/``times`` windows work exactly like device
        faults."""
        key = f"{tenant}@admission"
        with self._lock:
            call_no = self.calls.get(key, 0) + 1
            self.calls[key] = call_no
            f = self._firing(tenant, backend, call_no,
                             channel="admission")
            if f is None:
                return False
            self.injected.append({"op": tenant, "call": call_no,
                                  "mode": f.mode, "backend": backend})
        return True

    def on_worker(self, name: str,
                  backend: str | None = None) -> dict | None:
        """Federation-supervisor hook, consulted at every heartbeat
        received from a worker: returns ``None`` (healthy) or
        ``{"mode": "kill_worker" | "lease_wedge"}`` for a firing
        worker fault.  On this channel the fault's ``op`` pattern
        matches the WORKER name (``"w0"``, ``"w*"``); call counting
        is per worker under ``"<worker>@worker"``, so
        ``on_call``/``times`` windows count HEARTBEATS — a
        ``Fault("w0", "kill_worker", on_call=3)`` kills w0 at its 3rd
        heartbeat.  The hook only rules; the supervisor implements
        the semantics (it owns the subprocess pid and the lease
        clock), exactly like the on_io slow_read/io_error split."""
        key = f"{name}@worker"
        with self._lock:
            call_no = self.calls.get(key, 0) + 1
            self.calls[key] = call_no
            f = self._firing(name, backend, call_no, channel="worker")
            if f is None:
                return None
            self.injected.append({"op": name, "call": call_no,
                                  "mode": f.mode, "backend": backend})
        return {"mode": f.mode}

    def on_memory(self, name: str,
                  backend: str | None = None) -> dict | None:
        """Memory-budget hook, consulted by the run scheduler once
        per SUBMISSION against its budget: returns ``None`` (no
        pressure) or ``{"mode": "mem_pressure", "pressure_frac":
        ...}`` for a firing fault.  On this channel the fault's
        ``op`` pattern matches the BUDGET name (``MemoryBudget.name``,
        default ``"device"``); call counting is per budget under
        ``"<budget>@memory"``, so ``on_call``/``times`` windows count
        submissions — deterministic on one VirtualClock.  The hook
        only rules; the scheduler implements the semantics (it owns
        the budget): apparent capacity shrinks to ``pressure_frac``
        while the fault fires and restores when the window passes."""
        key = f"{name}@memory"
        with self._lock:
            call_no = self.calls.get(key, 0) + 1
            self.calls[key] = call_no
            f = self._firing(name, backend, call_no, channel="memory")
            if f is None:
                return None
            self.injected.append({"op": name, "call": call_no,
                                  "mode": f.mode, "backend": backend})
        return {"mode": f.mode, "pressure_frac": self.pressure_frac}

    def on_serving(self, name: str, path: str | None = None,
                   backend: str | None = None) -> dict | None:
        """Annotation-service hook, consulted once per query executed
        against the resident reference model: returns ``None``
        (healthy) or ``{"mode": "evict_state" | "corrupt_model"}``
        for a firing serving fault.  On this channel the fault's
        ``op`` pattern matches the SERVICE name; call counting is per
        service under ``"<service>@serving"``, so ``on_call``/
        ``times`` windows count query executions.  ``corrupt_model``
        damages the artifact file at ``path`` HERE (XOR byte flips,
        deterministic from the seed — the monkey owns file damage,
        like ``corrupt_checkpoint``); ``evict_state`` only rules —
        the service owns the resident buffers and implements the
        eviction."""
        key = f"{name}@serving"
        with self._lock:
            call_no = self.calls.get(key, 0) + 1
            self.calls[key] = call_no
            f = self._firing(name, backend, call_no,
                             channel="serving")
            if f is None:
                return None
            self.injected.append({"op": name, "call": call_no,
                                  "mode": f.mode, "backend": backend})
        if f.mode == "corrupt_model" and path is not None \
                and os.path.exists(path):
            rng = random.Random((self.seed, name, call_no,
                                 "model").__repr__())
            try:
                with open(path, "r+b") as fh:
                    blob = bytearray(fh.read())
                    if blob:
                        for _ in range(min(16, len(blob))):
                            blob[rng.randrange(len(blob))] ^= 0xFF
                        fh.seek(0)
                        fh.write(blob)
            except OSError:
                pass  # file already quarantined/moved: the ruling stands
        return {"mode": f.mode}

    def on_factory(self, name: str, stage: str,
                   backend: str | None = None) -> dict | None:
        """Annotation-factory hook, consulted once per stage ENTRY of
        a factory cycle: returns ``None`` (healthy) or ``{"mode":
        "stage_crash"}`` for a firing fault.  On this channel the
        fault's ``op`` pattern matches the ``"<factory>/<stage>"``
        composite (``"fac/build"``, ``"*/swap"``); call counting is
        per composite under ``"<factory>/<stage>@factory"``, so
        ``on_call``/``times`` windows count entries into ONE stage —
        a crash-on-first-entry fault dies exactly between the
        previous stage's durable commit and this stage's first byte
        of work.  The hook only rules; the factory implements the
        semantics (it raises :class:`ChaosCrash`, and a fresh factory
        on the same directory proves the between-stage resume)."""
        key = f"{name}/{stage}@factory"
        with self._lock:
            call_no = self.calls.get(key, 0) + 1
            self.calls[key] = call_no
            f = self._firing(f"{name}/{stage}", backend, call_no,
                             channel="factory")
            if f is None:
                return None
            self.injected.append({"op": f"{name}/{stage}",
                                  "call": call_no, "mode": f.mode,
                                  "backend": backend})
        return {"mode": f.mode}

    def on_network(self, peer: str,
                   backend: str | None = None) -> dict | None:
        """Transport hook, consulted once per SEND ATTEMPT toward a
        peer: returns ``None`` (the attempt goes out clean) or
        ``{"mode": ..., "delay_s": ...}`` for a firing network fault.
        On this channel the fault's ``op`` pattern matches the PEER
        name (``"supervisor"``, ``"w*"``); call counting is per peer
        under ``"<peer>@net"``, so ``on_call``/``times`` windows
        count send attempts — a retried send's SECOND attempt
        consults again, which is how a ``net_drop times=1`` burst
        loses exactly one frame and the retry heals it.  The hook
        only RULES — the transport owns the socket and the injectable
        clock, so it implements the semantics: ``net_drop`` loses
        this attempt (no frame on the wire), ``net_delay`` defers it
        by ``delay_s`` on the transport's clock before sending,
        ``net_dup`` puts the frame on the wire twice (the receiver's
        per-peer sequence dedup must deliver it once),
        ``net_partition`` fails every attempt in the window as if the
        peer were unreachable."""
        key = f"{peer}@net"
        with self._lock:
            call_no = self.calls.get(key, 0) + 1
            self.calls[key] = call_no
            f = self._firing(peer, backend, call_no, channel="net")
            if f is None:
                return None
            self.injected.append({"op": peer, "call": call_no,
                                  "mode": f.mode, "backend": backend})
        return {"mode": f.mode, "delay_s": self.slow_s}

    def on_io(self, name: str, path: str | None = None,
              backend: str | None = None) -> dict | None:
        """Shard-read hook, consulted by the ingest scheduler for
        every chunk read attempt: returns ``None`` (healthy) or
        ``{"mode": ..., "slow_s": ...}`` for a firing IO fault.  On
        this channel the fault's ``op`` pattern matches the CHUNK
        file basename (``"chunk-00002"``); call counting is per chunk
        under ``"<chunk>@io"``, so ``on_call``/``times`` windows work
        exactly like device faults (the retried read's SECOND attempt
        consults again and falls outside a ``times=1`` window).

        ``truncate_shard`` damages the file HERE (truncate to half
        its bytes — like ``corrupt_checkpoint``, the monkey owns file
        damage) and then lets the read proceed so the digest verify
        rules it corrupt; ``slow_read``/``io_error`` only return the
        ruling — the scheduler owns the clock and the concurrency, so
        it implements the wait/raise semantics."""
        key = f"{name}@io"
        with self._lock:
            call_no = self.calls.get(key, 0) + 1
            self.calls[key] = call_no
            f = self._firing(name, backend, call_no, channel="io")
            if f is None:
                return None
            self.injected.append({"op": name, "call": call_no,
                                  "mode": f.mode, "backend": backend})
        if f.mode == "truncate_shard" and path is not None:
            try:
                size = os.path.getsize(path)
                with open(path, "r+b") as fh:
                    fh.truncate(max(size // 2, 1))
            except OSError:
                pass  # file already gone/quarantined: the ruling stands
        return {"mode": f.mode, "slow_s": self.slow_s}

    def on_checkpoint(self, name: str, path: str,
                      backend: str | None = None) -> bool:
        """Runner hook, called after every step-checkpoint save: a
        matching ``corrupt_checkpoint`` fault XOR-flips bytes of the
        file in place (deterministically from the seed) and returns
        True.  The run that wrote the file continues unharmed — the
        damage is exactly the silent on-disk corruption that only the
        NEXT resume's digest verification can catch."""
        key = f"{name}@checkpoint"
        with self._lock:
            call_no = self.calls.get(key, 0) + 1
            self.calls[key] = call_no
            f = self._firing(name, backend, call_no,
                             channel="checkpoint")
            if f is None:
                return False
            self.injected.append({"op": name, "call": call_no,
                                  "mode": f.mode, "backend": backend})
        rng = random.Random((self.seed, name, call_no, "ckpt").__repr__())
        with open(path, "r+b") as fh:
            blob = bytearray(fh.read())
            if blob:
                for _ in range(min(16, len(blob))):
                    blob[rng.randrange(len(blob))] ^= 0xFF
                fh.seek(0)
                fh.write(blob)
        return True

    def _firing(self, name: str, backend: str, call_no: int,
                channel: str = "call"):
        for f in self.faults:
            # every mode fires on exactly one hook channel (op call /
            # checkpoint save / admission) — a fault never fires on
            # the wrong one
            if _MODE_CHANNEL.get(f.mode, "call") != channel:
                continue
            if not fnmatch.fnmatchcase(name, f.op):
                continue
            if f.backend is not None and backend != f.backend:
                continue
            if call_no < f.on_call:
                continue
            if f.times >= 0 and call_no >= f.on_call + f.times:
                continue
            if f.p < 1.0 and self._rng.random() >= f.p:
                continue
            return f
        return None

    def _wrap(self, name: str, backend: str, fn):
        def chaotic(data, *args, **kw):
            with self._lock:
                call_no = self.calls.get(name, 0) + 1
                self.calls[name] = call_no
                f = self._firing(name, backend, call_no)
                if f is not None:
                    self.injected.append(
                        {"op": name, "call": call_no,
                         "mode": f.mode, "backend": backend})
            if f is None:
                return fn(data, *args, **kw)
            if f.mode == "unavailable":
                raise TransientDeviceError(
                    f"chaos: UNAVAILABLE injected in {name!r} "
                    f"(call {call_no})")
            if f.mode == "oom":
                # the real jaxlib message shape, so the classifier's
                # marker scan — not just the explicit type — is what
                # tier-1 exercises
                raise DeviceOOMError(
                    f"chaos: RESOURCE_EXHAUSTED: Out of memory while "
                    f"trying to allocate bytes in {name!r} "
                    f"(call {call_no})")
            if f.mode == "crash":
                raise ChaosCrash(
                    f"chaos: process death injected in {name!r} "
                    f"(call {call_no})")
            if f.mode == "kill":
                import os
                import sys

                print(f"[chaos] killing process in {name!r}",
                      file=sys.stderr, flush=True)
                os._exit(9)
            if f.mode == "hang":
                self.sleep(self.hang_s)
                return fn(data, *args, **kw)
            if f.mode == "wedge":
                # burn the step's wall-clock budget on the SHARED
                # (virtual) clock, then let the cooperative token rule
                # the op overrun — the op itself "never returns".
                # Without an injected clock there is nothing to
                # advance (and a real hang_s-scale sleep — e.g. a
                # spec-rebuilt monkey inside an isolated child, which
                # cannot inherit the parent's clock — would break the
                # zero-real-sleeps contract): warn and skip the burn.
                if self.clock is not None:
                    self.clock.sleep(self.wedge_s)
                else:
                    import warnings

                    warnings.warn(
                        f"chaos: 'wedge' fault on {name!r} has no "
                        "shared clock= to advance — skipping the "
                        "time burn (use mode='hang' for real-clock "
                        "wedges)", RuntimeWarning, stacklevel=2)
                check_deadline()
                return fn(data, *args, **kw)
            # corrupt: per-firing rng derived from (seed, op, call) so
            # the damage is reproducible regardless of what else drew
            # from the monkey's main stream
            out = fn(data, *args, **kw)
            sub = random.Random((self.seed, name, call_no).__repr__())
            return _corrupt_value(out, sub)

        return chaotic

    @contextlib.contextmanager
    def activate(self):
        """Install into the transform registry for the enclosed block;
        every ``apply``/``Transform``/``Pipeline`` call is wrapped.

        Reentrant AND thread-safe via an activation refcount: nested
        or concurrent activation of the SAME monkey (a test's ``with
        monkey.activate():`` around a runner that was also given
        ``chaos=monkey``, or two scheduler workers whose runners both
        carry it) installs the wrapper once, and only the LAST exit
        pops it — a double wrap would double-count every call and
        shift Nth-call faults, and an early pop would strip fault
        injection from a still-running concurrent run."""
        with self._lock:
            self._active += 1
            if self._active == 1:
                registry.push_call_wrapper(self._wrap)
        try:
            yield self
        finally:
            with self._lock:
                self._active -= 1
                if self._active == 0:
                    registry.pop_call_wrapper(self._wrap)
