"""Determinism checking — the functional-framework analogue of race
detection.

Reference parity: the reference framework ships race detection for its
threaded runtime (source unavailable — SURVEY.md §0).  In this
framework the device compute path is functional JAX (no shared mutable
state to race on), so the corresponding hazard class is
NON-DETERMINISM: accidental dependence on host thread timing (the
shard prefetcher, the native packer's worker threads), unseeded or
reused PRNG keys, unstable reductions across shard orderings, or
nondeterministic collectives.  ``check_deterministic`` catches all of
those the same way a race detector catches races: run twice, demand
bit-identical results.

Structure comparison rides on ``jax.tree_util`` — dict/list/tuple
layouts, registered pytrees (``SparseCells`` flattens to
indices/data with n_cells/n_genes in the treedef), and key ORDER all
live in the treedef, so a run-to-run structural change is a mismatch
even when the leaf values happen to agree.  scipy sparse matrices are
tree leaves and get an exact sparse comparison.

>>> from sctools_tpu.utils.determinism import check_deterministic
>>> rep = check_deterministic(lambda: stream_stats(src))
>>> assert rep.ok, rep.mismatches
"""

from __future__ import annotations

import dataclasses
import logging

import numpy as np

_logger = logging.getLogger(__name__)


@dataclasses.dataclass
class DeterminismReport:
    ok: bool
    mismatches: list  # [(path, max_abs_diff | reason), ...]
    n_leaves: int

    def __bool__(self):
        return self.ok


def _leaf_mismatch(a, b, exact: bool, atol: float):
    """None when the leaves agree; otherwise a reason/diff value."""
    import scipy.sparse as sp

    if sp.issparse(a) or sp.issparse(b):
        if not (sp.issparse(a) and sp.issparse(b)):
            return "sparse vs non-sparse"
        if a.shape != b.shape:
            return f"shape {a.shape} vs {b.shape}"
        d = (a - b)
        if d.nnz == 0:
            return None
        diff = float(np.max(np.abs(d.data)))
        return diff if (exact or diff > atol) else None
    try:
        a_np = np.asarray(a)
        b_np = np.asarray(b)
    except Exception as e:
        # non-arrayable leaf (custom object in uns, etc.) — fall back
        # to identity/equality, and log what was swallowed so a
        # conversion failure is diagnosable rather than silent
        _logger.debug("leaf not array-convertible (%s: %s); comparing "
                      "by equality", type(e).__name__, e)
        try:
            same = a is b or bool(a == b)
        except Exception as e2:  # incomparable objects are a mismatch
            return (f"non-array leaf, equality check failed "
                    f"({type(e2).__name__}: {e2})")
        return None if same else (
            f"non-array mismatch (asarray failed: {type(e).__name__})")
    if a_np.shape != b_np.shape or a_np.dtype != b_np.dtype:
        return (f"shape/dtype {a_np.shape}/{a_np.dtype} vs "
                f"{b_np.shape}/{b_np.dtype}")
    if a_np.dtype.kind in "OUS":
        # object arrays compare via each element's __eq__, which can
        # itself raise — a determinism CHECK must report that, not
        # crash the run it is checking
        try:
            same = bool(np.array_equal(a_np, b_np))
        except Exception as e:
            return (f"object equality raised "
                    f"({type(e).__name__}: {e})")
        return None if same else "string/object mismatch"
    if exact:
        if np.array_equal(a_np, b_np, equal_nan=True):
            return None
        return float(np.max(np.abs(a_np.astype(np.float64)
                                   - b_np.astype(np.float64))))
    diff = float(np.max(np.abs(a_np.astype(np.float64)
                               - b_np.astype(np.float64))))
    return diff if diff > atol else None


def check_deterministic(fn, *args, runs: int = 2, exact: bool = True,
                        atol: float = 0.0, **kwargs) -> DeterminismReport:
    """Run ``fn(*args, **kwargs)`` ``runs`` times and compare outputs.

    ``exact=True`` (default) demands bit-identical arrays — the right
    bar for a single device, where XLA programs are deterministic and
    any drift means hidden host-side state or key reuse.  Set
    ``exact=False`` with ``atol`` when comparing across runs that
    legitimately reorder float reductions (e.g. different shard
    orderings by design).
    """
    if runs < 2:
        raise ValueError(f"runs={runs} asserts nothing; need >= 2")
    import jax

    outs = [fn(*args, **kwargs) for _ in range(runs)]
    leaves0, tree0 = jax.tree_util.tree_flatten_with_path(outs[0])
    mismatches = []
    for other in outs[1:]:
        leaves, tree = jax.tree_util.tree_flatten_with_path(other)
        if tree != tree0:
            # covers renamed dict keys, changed container types, and
            # registered-pytree aux data (SparseCells n_cells/n_genes)
            mismatches.append(("$", f"tree structure differs: "
                                    f"{tree0} vs {tree}"))
            continue
        for (p0, a), (_, b) in zip(leaves0, leaves):
            bad = _leaf_mismatch(a, b, exact, atol)
            if bad is not None:
                mismatches.append((jax.tree_util.keystr(p0), bad))
    return DeterminismReport(ok=not mismatches, mismatches=mismatches,
                             n_leaves=len(leaves0))
