"""Checkpoint/resume for CellData and pipelines.

Reference parity: the reference framework checkpoints pipeline state
so long multi-stage runs survive preemption (source unavailable —
SURVEY.md §0).

Format: one ``.npz`` per checkpoint — X stored as CSR triples (sparse)
or dense, every obs/var/obsm/varm/obsp/uns array under a prefixed key.
Device arrays are fetched to host first (``CellData.to_host`` trims
row padding), so checkpoints are portable across chip counts and
backends.  ``PipelineCheckpointer`` wraps a ``Pipeline`` and skips
completed steps on resume.

Integrity (the run-integrity layer): every file carries a content
digest, a schema version and (when the writer knows it) the step
fingerprint under ``_integrity/*`` keys.  :func:`verify_checkpoint`
re-hashes a file before anyone trusts it; a file that fails — bit
rot, a truncated write that survived the atomic rename race, chaos-
injected corruption — is never deleted but moved aside by
:func:`quarantine_checkpoint` so resume falls back past it
deterministically while the bytes stay available as evidence.
:func:`data_digest` hashes a run's INPUT, and
:func:`step_fingerprint` mixes that digest into every step identity —
so ``resume=True`` with *different* data and the same checkpoint
directory recomputes instead of silently returning the previous run's
result (the PR-1 latent bug).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import warnings

import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells

_SECTIONS = ("obs", "var", "obsm", "varm", "obsp", "uns")

#: bump when the npz layout changes incompatibly; files stamped with a
#: NEWER schema than the reader understands fail verification (an old
#: reader must not half-parse a future layout)
CHECKPOINT_SCHEMA = 1

#: npz key prefix for integrity metadata — never part of the payload
_INTEGRITY = "_integrity/"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed digest/schema/fingerprint verification.
    Deterministic by classification: re-reading the same bytes fails
    the same way — callers quarantine and fall back, never retry.
    ``.reason`` carries the machine-readable why (the same string
    :func:`verify_checkpoint` returns), ``.path`` the file."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"{path}: {reason}")
        self.path = path
        self.reason = reason


def _content_digest(arrays: dict) -> str:
    """Order-independent sha256 over every payload array (key, dtype,
    shape, raw bytes); ``_integrity/*`` keys are excluded so the
    digest can be stored inside the file it covers."""
    h = hashlib.sha256()
    for k in sorted(arrays):
        if k.startswith(_INTEGRITY):
            continue
        a = np.asarray(arrays[k])
        h.update(k.encode())
        h.update(f"|{a.dtype}|{a.shape}|".encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()[:16]


def save_celldata(data: CellData, path: str, *,
                  fingerprint: str | None = None,
                  digest: bool = True) -> None:
    """Write a CellData to ``path`` (.npz, atomic via rename).

    The file self-describes its integrity: a content digest over every
    payload array, the writer's :data:`CHECKPOINT_SCHEMA`, and — when
    the caller passes ``fingerprint=`` (the runner does, with the
    step's :func:`step_fingerprint`) — the step identity, so
    :func:`verify_checkpoint` can detect renamed/mismatched files as
    well as damaged ones.  ``digest=False`` skips the integrity keys
    entirely (a full hash pass over the payload) — for throwaway
    same-process transfer files that are never resumed from, e.g. the
    runner's isolation handoffs."""
    import jax
    import scipy.sparse as sp

    if isinstance(data.X, (SparseCells, jax.Array)) or any(
        isinstance(v, (jax.Array, SparseCells))
        for d in (data.obs, data.var, data.obsm, data.varm, data.obsp,
                  data.uns, data.layers)
        for v in d.values()
    ):
        data = data.to_host()
    arrays: dict[str, np.ndarray] = {}

    def put_matrix(prefix, M):
        if sp.issparse(M):
            M = M.tocsr()
            arrays[f"{prefix}/format"] = np.array("csr")
            arrays[f"{prefix}/data"] = M.data
            arrays[f"{prefix}/indices"] = M.indices
            arrays[f"{prefix}/indptr"] = M.indptr
            arrays[f"{prefix}/shape"] = np.asarray(M.shape, np.int64)
        else:
            arrays[f"{prefix}/format"] = np.array("dense")
            arrays[f"{prefix}/data"] = np.asarray(M)

    skipped = []
    put_matrix("X", data.X)
    # layers are X-shaped (possibly sparse): same triple encoding,
    # namespaced so load can rebuild them as matrices
    for k, v in data.layers.items():
        arr_like = v if sp.issparse(v) else np.asarray(v)
        if getattr(arr_like, "dtype", None) is not None and \
                arr_like.dtype == object:
            skipped.append(f"layers/{k}")  # pickled npz breaks resume
            continue
        put_matrix(f"LAYER::{k}", v)

    def put(key, v):
        if isinstance(v, dict):
            # nested dicts (e.g. de.rank_genes_groups results) flatten
            # into "//"-joined keys — np.savez would otherwise pickle
            # them as object arrays that allow_pickle=False can't load
            for sk, sv in v.items():
                put(f"{key}//{sk}", sv)
            return
        arr = np.asarray(v)
        if arr.dtype == object:
            skipped.append(key)
            return
        arrays[key] = arr

    for section in _SECTIONS:
        for k, v in getattr(data, section).items():
            put(f"{section}/{k}", v)
    if skipped:
        warnings.warn(
            f"save_celldata: skipped non-array entries {skipped}",
            stacklevel=2)
    if digest:
        arrays[f"{_INTEGRITY}digest"] = np.array(_content_digest(arrays))
        arrays[f"{_INTEGRITY}schema"] = np.array(CHECKPOINT_SCHEMA,
                                                 np.int64)
        arrays[f"{_INTEGRITY}fingerprint"] = np.array(fingerprint or "")
    tmp = path + ".tmp.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)


def _read_arrays(path: str) -> dict:
    """One-pass read of every npz entry into memory (reading each
    member also runs the zip CRC checks).  The SAME dict feeds both
    verification and CellData reconstruction, so a verified load
    touches the file exactly once."""
    with np.load(path, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def _verify_arrays(arrays: dict,
                   expect_fingerprint: str | None = None) -> dict:
    """Integrity ruling over already-read arrays (see
    :func:`verify_checkpoint` for the reason vocabulary)."""
    if f"{_INTEGRITY}digest" not in arrays:
        return {"ok": True, "reason": "legacy", "schema": 0,
                "fingerprint": None}
    try:
        stored = str(arrays[f"{_INTEGRITY}digest"])
        schema = int(arrays[f"{_INTEGRITY}schema"])
        fp = str(arrays[f"{_INTEGRITY}fingerprint"]) or None
    except (KeyError, TypeError, ValueError) as e:
        # a digest with its sibling keys stripped or mangled is a
        # tampered/truncated file, not a legacy one — same ruling as
        # unreadable, and NEVER a raw raise out of a verify call
        return {"ok": False, "schema": None, "fingerprint": None,
                "reason": "unreadable (integrity keys incomplete: "
                          f"{type(e).__name__}: {e})"}
    if schema > CHECKPOINT_SCHEMA:
        return {"ok": False, "schema": schema, "fingerprint": fp,
                "reason": f"schema {schema} newer than supported "
                          f"{CHECKPOINT_SCHEMA}"}
    computed = _content_digest(arrays)
    if computed != stored:
        return {"ok": False, "schema": schema, "fingerprint": fp,
                "reason": f"digest mismatch (stored {stored}, "
                          f"computed {computed})"}
    if expect_fingerprint and fp and fp != expect_fingerprint:
        return {"ok": False, "schema": schema, "fingerprint": fp,
                "reason": f"fingerprint mismatch (file {fp}, "
                          f"expected {expect_fingerprint})"}
    return {"ok": True, "reason": None, "schema": schema,
            "fingerprint": fp}


def save_npz_verified(path: str, *, fingerprint: str | None = None,
                      **arrays) -> str:
    """Write a plain dict of arrays as a checksummed ``.npz`` (atomic
    rename) carrying the SAME ``_integrity/*`` keys as a CellData
    checkpoint — content digest, :data:`CHECKPOINT_SCHEMA`, optional
    identity ``fingerprint``.  This is the generic writer behind every
    non-CellData durable file in the ingest tier: shard-store chunks
    (``data/io.py`` ``write_csr_chunk``) and the streaming passes'
    resume files (``data/stream.py``) all route here, so ONE integrity
    convention covers the whole IO path.  Returns the content digest
    (computed exactly once — a terabyte-scale store write must not
    pay a second full hashing pass just to record digests in its
    manifest)."""
    out = {k: np.asarray(v) for k, v in arrays.items()}
    digest = _content_digest(out)
    out[f"{_INTEGRITY}digest"] = np.array(digest)
    out[f"{_INTEGRITY}schema"] = np.array(CHECKPOINT_SCHEMA, np.int64)
    out[f"{_INTEGRITY}fingerprint"] = np.array(fingerprint or "")
    tmp = path + ".tmp.npz"
    np.savez(tmp, **out)
    os.replace(tmp, path)
    return digest


def load_npz_verified(path: str, *,
                      expect_fingerprint: str | None = None,
                      require_digest: bool = False,
                      expect_digest: str | None = None) -> dict:
    """Read-and-verify the twin of :func:`save_npz_verified`: one pass
    over the file feeds both the digest check and the returned array
    dict (``_integrity/*`` keys stripped).  Any failure — unreadable
    bytes, digest/schema/fingerprint mismatch — raises
    :class:`CheckpointCorruptError` with a machine-readable
    ``.reason``.  ``require_digest=True`` additionally rejects files
    with NO integrity keys (shard-store chunks are always written
    with them, so a digestless chunk is a truncated or foreign file,
    not a legacy one; legacy resume files stay loadable by default).
    ``expect_digest=`` (an externally recorded digest, e.g. a store
    manifest's) catches the cross-wired-file case — intact bytes that
    self-verify but belong in a different slot — from the same single
    read."""
    try:
        arrays = _read_arrays(path)
    except Exception as e:  # noqa: BLE001 — unreadable is an
        # integrity ruling here, exactly as in load_celldata
        raise CheckpointCorruptError(
            path, f"unreadable ({type(e).__name__}: {e})") from e
    chk = _verify_arrays(arrays, expect_fingerprint)
    if not chk["ok"]:
        raise CheckpointCorruptError(path, chk["reason"])
    if require_digest and chk["reason"] == "legacy":
        raise CheckpointCorruptError(
            path, "missing integrity keys (digestless file where a "
                  "verified one is required)")
    if expect_digest:
        stored = str(arrays.get(f"{_INTEGRITY}digest", ""))
        if stored != expect_digest:
            raise CheckpointCorruptError(
                path, f"manifest digest mismatch (file {stored}, "
                      f"manifest {expect_digest})")
    return {k: v for k, v in arrays.items()
            if not k.startswith(_INTEGRITY)}


def save_npz_generations(path: str, fingerprint: str | None = None,
                         **arrays) -> str:
    """:func:`save_npz_verified` with GENERATION ROTATION: the
    previous file at ``path`` rotates to ``<path>.prev`` first, so a
    reader whose newest generation is later ruled corrupt falls back
    exactly ONE save (one shard / one cursor step of lost work)
    instead of restarting the whole pass.  This is the write half of
    the resumable-pass convention shared by the streaming passes
    (``data/stream.py``) and the out-of-core trainer
    (``models/train_stream.py``).  Returns the content digest."""
    if os.path.exists(path):
        os.replace(path, path + ".prev")
    return save_npz_verified(path, fingerprint=fingerprint, **arrays)


def load_npz_generations(path: str,
                         fingerprint: str | None = None) -> dict | None:
    """Verify-then-load the newest surviving generation written by
    :func:`save_npz_generations`, falling back deterministically:
    newest → ``.prev`` → ``None`` (fresh start).  A candidate that
    fails verification — bit rot, a write truncated by the very crash
    being recovered from, chaos damage — is QUARANTINED
    (:func:`quarantine_checkpoint`: moved beside the data with a
    ``.reason.json`` sidecar, never deleted) and the next generation
    is tried.  Files from before the integrity layer carry no digest
    and load as legacy."""
    for cand in (path, path + ".prev"):
        if not os.path.exists(cand):
            continue
        try:
            return load_npz_verified(cand,
                                     expect_fingerprint=fingerprint)
        except CheckpointCorruptError as e:
            dest = quarantine_checkpoint(cand, e.reason)
            warnings.warn(
                f"checkpoint {cand!r} failed verification "
                f"({e.reason}) — quarantined to {dest!r}, falling "
                f"back a generation", RuntimeWarning, stacklevel=3)
    return None


def clear_npz_generations(path: str) -> None:
    """Remove every generation at ``path`` (the pass/run completed;
    its resume state is stale, keeping it would resume a finished
    run)."""
    for cand in (path, path + ".prev"):
        if os.path.exists(cand):
            os.remove(cand)


def verify_checkpoint(path: str,
                      expect_fingerprint: str | None = None) -> dict:
    """Re-hash a checkpoint before trusting it.

    Returns ``{"ok": bool, "reason": str | None, "schema": int,
    "fingerprint": str | None}``.  Failure reasons: ``unreadable``
    (not an npz / zip CRC failure / missing keys), ``digest
    mismatch`` (bit rot or tampering), ``schema ... newer`` (written
    by a future layout), ``fingerprint mismatch`` (the file's stored
    step identity disagrees with ``expect_fingerprint`` — a renamed
    or cross-wired file).  Files from before the integrity layer
    carry no digest and verify ``ok`` with ``reason="legacy"`` — an
    unverifiable file is not the same as a corrupt one.  To verify
    AND load in one read, use ``load_celldata(path, verify=True)``.
    """
    try:
        arrays = _read_arrays(path)
        return _verify_arrays(arrays, expect_fingerprint)
    except Exception as e:  # noqa: BLE001 — any unreadable byte
        # pattern (BadZipFile, zlib, KeyError on truncated archives)
        # means the same thing to the caller: do not trust this file
        return {"ok": False,
                "reason": f"unreadable ({type(e).__name__}: {e})",
                "schema": None, "fingerprint": None}


def quarantine_checkpoint(path: str, reason: str) -> str:
    """Move a corrupt/mismatched checkpoint into a ``quarantine/``
    subdir beside it — NEVER deleted; the bytes are the evidence a
    post-mortem needs — and drop a ``.reason.json`` sidecar.  Returns
    the quarantined path.  Resume then falls back past the file
    deterministically (``latest_step(upto=...)``)."""
    d = os.path.dirname(os.path.abspath(path))
    qdir = os.path.join(d, "quarantine")
    os.makedirs(qdir, exist_ok=True)
    base = os.path.basename(path)
    dest = os.path.join(qdir, base)
    n = 1
    while os.path.exists(dest):
        dest = os.path.join(qdir, f"{base}.{n}")
        n += 1
    os.replace(path, dest)
    try:
        with open(dest + ".reason.json", "w") as f:
            json.dump({"reason": reason, "ts": round(time.time(), 3),
                       "original": os.path.abspath(path)}, f)
    except OSError as e:
        # the MOVE is the contract; a failed sidecar only loses the
        # human-readable why
        warnings.warn(f"quarantine_checkpoint: could not write reason "
                      f"sidecar ({e})", stacklevel=2)
    return dest


def data_digest(data) -> str | None:
    """Cheap content digest (12 hex chars) of a run's INPUT: the X
    matrix plus every obs/var/obsm/varm/obsp/uns/layers entry.
    Annotations are part of the identity on purpose — transforms
    consume them too (``abundance.*`` reads obs condition labels, DE
    reads groupings), so two inputs with the same counts but
    different labels must invalidate each other's checkpoints.
    Mixed into every step fingerprint so checkpoints from a run over
    different data can never be resumed by mistake.  Returns ``None``
    (with a warning) when the input cannot be hashed; callers must
    then treat resume as unverified rather than fail the run."""
    import scipy.sparse as sp

    def hash_matrix(h, M):
        if hasattr(M, "to_scipy_csr"):  # device-packed SparseCells
            M = M.to_scipy_csr()
        if sp.issparse(M):
            M = M.tocsr()
            h.update(f"csr|{M.shape}|{M.data.dtype}|".encode())
            for a in (M.data, M.indices, M.indptr):
                h.update(np.ascontiguousarray(a).tobytes())
            return
        a = np.asarray(M)  # fetches device arrays to host
        if a.dtype == object:
            # labels/dicts: repr of the nested value is content-
            # deterministic; order-sensitive for dicts, which only
            # errs toward recomputing (fails safe)
            h.update(f"obj|{a.shape}|".encode())
            h.update(repr(a.tolist()).encode())
            return
        h.update(f"dense|{a.shape}|{a.dtype}|".encode())
        h.update(np.ascontiguousarray(a).tobytes())

    try:
        h = hashlib.sha256()
        if not hasattr(data, "X"):
            hash_matrix(h, data)
            return h.hexdigest()[:12]
        hash_matrix(h, data.X)
        for section in _SECTIONS + ("layers",):
            d = getattr(data, section, None) or {}
            for k in sorted(d):
                h.update(f"|{section}/{k}|".encode())
                hash_matrix(h, d[k])
        return h.hexdigest()[:12]
    except Exception as e:  # noqa: BLE001 — an unhashable input must
        # not kill a run; resume just loses input-identity checking
        warnings.warn(
            f"data_digest: could not hash the input "
            f"({type(e).__name__}: {e}) — resume will NOT detect a "
            "changed input dataset", stacklevel=2)
        return None


def load_celldata(path: str, *, verify: bool = False,
                  expect_fingerprint: str | None = None) -> CellData:
    """Load a CellData checkpoint.  ``verify=True`` rules on the
    file's integrity (digest/schema/``expect_fingerprint``) from the
    SAME single read that feeds reconstruction — no second pass over
    a multi-GB file — raising :class:`CheckpointCorruptError` (with
    ``.reason``) on any failure, unreadable bytes included."""
    import scipy.sparse as sp

    if verify:
        try:
            arrays = _read_arrays(path)
        except Exception as e:  # noqa: BLE001 — unreadable is an
            # integrity ruling here, not a programming error
            raise CheckpointCorruptError(
                path, f"unreadable ({type(e).__name__}: {e})") from e
        chk = _verify_arrays(arrays, expect_fingerprint)
        if not chk["ok"]:
            raise CheckpointCorruptError(path, chk["reason"])
    else:
        arrays = _read_arrays(path)

    def get_matrix(prefix):
        fmt = str(arrays[f"{prefix}/format"])
        if fmt == "csr":
            shape = tuple(arrays[f"{prefix}/shape"])
            return sp.csr_matrix(
                (arrays[f"{prefix}/data"], arrays[f"{prefix}/indices"],
                 arrays[f"{prefix}/indptr"]), shape=shape)
        return arrays[f"{prefix}/data"]

    X = get_matrix("X")
    layers = {}
    for key in arrays:
        if key.startswith("LAYER::") and key.endswith("/format"):
            name = key[len("LAYER::"):-len("/format")]
            layers[name] = get_matrix(f"LAYER::{name}")
    sections: dict[str, dict] = {s: {} for s in _SECTIONS}
    for key in arrays:
        section, _, name = key.partition("/")
        if (section not in sections or key.startswith("X/")
                or key.startswith("LAYER::")):
            continue
        target = sections[section]
        parts = name.split("//")
        for p in parts[:-1]:  # rebuild nested dicts
            target = target.setdefault(p, {})
        target[parts[-1]] = arrays[key]
    return CellData(X, layers=layers, **sections)


def step_fingerprint(steps, i: int,
                     input_digest: str | None = None) -> str:
    """Content hash (10 hex chars) of the step-``i`` prefix of
    ``steps`` — name plus parameters of every step up to and including
    ``i``, so a change to ANY earlier step invalidates everything
    downstream of it.  ``input_digest`` (from :func:`data_digest`)
    seeds the hash when given, making the INPUT DATA part of the step
    identity — a resume against the same directory with different
    data then matches nothing instead of silently returning the
    previous run's result.  This is the step identity the checkpoint
    filenames embed; the ResilientRunner journals it so a run record
    can be matched to the exact pipeline configuration that produced
    it."""

    def sig(v, h):
        # repr() alone is unsafe: numpy elides large arrays
        # ("[0, 1, ..., 9]"), so two configs differing mid-array
        # would collide — hash raw bytes for array-likes instead
        if isinstance(v, (list, tuple)):
            h.update(f"<{type(v).__name__}{len(v)}".encode())
            for x in v:
                sig(x, h)
            h.update(b">")
        elif isinstance(v, dict):
            h.update(f"<dict{len(v)}".encode())
            for kk in sorted(v, key=repr):
                h.update(repr(kk).encode())
                sig(v[kk], h)
            h.update(b">")
        elif isinstance(v, np.ndarray) or type(v).__module__.startswith(
                ("jax", "jaxlib")):
            a = np.asarray(v)
            h.update(f"nd{a.dtype}{a.shape}".encode())
            h.update(np.ascontiguousarray(a).tobytes())
        else:
            r = repr(v)
            # A default object repr embeds the memory address
            # ("<Foo object at 0x7f..>"), which changes every
            # process — hashing it would silently invalidate every
            # checkpoint on resume.  Strip addresses (stable across
            # runs) and warn that the param carries no real state.
            if " at 0x" in r:
                import re
                import warnings

                r = re.sub(r" at 0x[0-9a-fA-F]+", "", r)
                warnings.warn(
                    f"step_fingerprint: parameter {r!r} has no "
                    "stable repr; its internal state is NOT part of "
                    "the checkpoint hash — changing it will not "
                    "invalidate old checkpoints", stacklevel=2)
            h.update(r.encode())

    # hash of the (input digest, (name, sorted params) prefix chain) —
    # stale checkpoints from a different configuration, an edited
    # earlier step, OR a different input dataset are never resumed
    h = hashlib.sha256()
    if input_digest:
        h.update(f"input:{input_digest}|".encode())
    for t in steps[: i + 1]:
        h.update(t.name.encode())
        sig(dict(t.params), h)
    return h.hexdigest()[:10]


def step_filename(steps, i: int,
                  input_digest: str | None = None) -> str:
    """Checkpoint basename for step ``i``:
    ``step{i:03d}_{transform}_{fingerprint}.npz``.  Pure function of
    the step list (and the optional input digest) —
    PipelineCheckpointer and the ResilientRunner both name through
    here, so their checkpoints interoperate (a run started under one
    resumes under the other)."""
    safe = steps[i].name.replace(".", "_").replace("/", "_")
    fp = step_fingerprint(steps, i, input_digest=input_digest)
    return f"step{i:03d}_{safe}_{fp}.npz"


def latest_step(directory: str, steps, upto: int | None = None,
                input_digest: str | None = None,
                verify: bool = False) -> int | None:
    """Index of the newest step whose checkpoint exists in
    ``directory`` under the CURRENT fingerprints, or ``None``.  Stale
    files from an edited configuration (or, with ``input_digest``, a
    different input dataset) never match — their fingerprint differs —
    so they are simply ignored.  ``verify=True`` additionally re-hashes
    each candidate (:func:`verify_checkpoint`) and skips files that
    fail, falling back to the next-newest intact one.  ``upto`` bounds
    the search to indices ``<= upto`` — how a resumer skips past a
    checkpoint it has already quarantined."""
    hi = len(steps) - 1 if upto is None else min(upto, len(steps) - 1)
    for i in range(hi, -1, -1):
        p = os.path.join(
            directory, step_filename(steps, i, input_digest=input_digest))
        if not os.path.exists(p):
            continue
        if verify and not verify_checkpoint(p)["ok"]:
            continue
        return i
    return None


class PipelineCheckpointer:
    """Run a ``Pipeline`` with a checkpoint after every step; resume
    skips steps whose checkpoint already exists.

    >>> ckpt = PipelineCheckpointer(pipe, "/path/to/ckpts")
    >>> out = ckpt.run(data, backend="tpu")       # writes step files
    >>> out = ckpt.run(data, backend="tpu")       # resumes: loads last

    Step files are named ``step{i:03d}_{transform}_{paramhash}.npz``
    (see :func:`step_filename`); a change to the step list OR to any
    step's parameters invalidates mismatched names automatically (the
    hash covers every step up to and including step ``i``, so editing
    an earlier step also invalidates everything downstream of it).
    The input data's :func:`data_digest` is part of the hash too, so
    a resume against different data recomputes.  Resume only trusts
    files that pass :func:`verify_checkpoint` (corrupt ones are
    skipped; the ResilientRunner additionally quarantines them).
    """

    def __init__(self, pipeline, directory: str, save_every: int = 1):
        self.pipeline = pipeline
        self.directory = directory
        self.save_every = max(1, save_every)
        os.makedirs(directory, exist_ok=True)

    def _step_path(self, i: int, steps,
                   input_digest: str | None = None) -> str:
        return os.path.join(
            self.directory,
            step_filename(steps, i, input_digest=input_digest))

    def run(self, data: CellData, backend: str | None = None,
            resume: bool = True) -> CellData:
        steps = list(self.pipeline.steps)
        dig = data_digest(data)
        start = 0
        if resume:
            # single-pass verified load per candidate: a corrupt file
            # is skipped (the ResilientRunner additionally quarantines
            # in this situation; here we only refuse to trust it)
            i = latest_step(self.directory, steps, input_digest=dig)
            while i is not None:
                try:
                    loaded = load_celldata(
                        self._step_path(i, steps, dig), verify=True)
                except Exception as e:  # noqa: BLE001 — untrusted
                    # file: fall back, never crash a resumable run
                    warnings.warn(
                        f"PipelineCheckpointer: checkpoint for step "
                        f"{i} rejected ({e}) — falling back",
                        RuntimeWarning, stacklevel=2)
                    i = latest_step(self.directory, steps,
                                    upto=i - 1, input_digest=dig)
                    continue
                data = loaded
                if backend in (None, "tpu"):
                    data = data.device_put()
                start = i + 1
                break
        for i in range(start, len(steps)):
            t = steps[i]
            if backend is not None and backend != t.backend:
                t = t.with_backend(backend)
            data = t(data)
            if (i + 1) % self.save_every == 0 or i == len(steps) - 1:
                save_celldata(
                    data, self._step_path(i, steps, dig),
                    fingerprint=step_fingerprint(steps, i,
                                                 input_digest=dig))
        return data

    def clear(self) -> None:
        for f in os.listdir(self.directory):
            if f.startswith("step") and f.endswith(".npz"):
                os.remove(os.path.join(self.directory, f))
