"""Checkpoint/resume for CellData and pipelines.

Reference parity: the reference framework checkpoints pipeline state
so long multi-stage runs survive preemption (source unavailable —
SURVEY.md §0).

Format: one ``.npz`` per checkpoint — X stored as CSR triples (sparse)
or dense, every obs/var/obsm/varm/obsp/uns array under a prefixed key.
Device arrays are fetched to host first (``CellData.to_host`` trims
row padding), so checkpoints are portable across chip counts and
backends.  ``PipelineCheckpointer`` wraps a ``Pipeline`` and skips
completed steps on resume.
"""

from __future__ import annotations

import os

import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells

_SECTIONS = ("obs", "var", "obsm", "varm", "obsp", "uns")


def save_celldata(data: CellData, path: str) -> None:
    """Write a CellData to ``path`` (.npz, atomic via rename)."""
    import jax
    import scipy.sparse as sp

    if isinstance(data.X, (SparseCells, jax.Array)) or any(
        isinstance(v, (jax.Array, SparseCells))
        for d in (data.obs, data.var, data.obsm, data.varm, data.obsp,
                  data.uns, data.layers)
        for v in d.values()
    ):
        data = data.to_host()
    arrays: dict[str, np.ndarray] = {}

    def put_matrix(prefix, M):
        if sp.issparse(M):
            M = M.tocsr()
            arrays[f"{prefix}/format"] = np.array("csr")
            arrays[f"{prefix}/data"] = M.data
            arrays[f"{prefix}/indices"] = M.indices
            arrays[f"{prefix}/indptr"] = M.indptr
            arrays[f"{prefix}/shape"] = np.asarray(M.shape, np.int64)
        else:
            arrays[f"{prefix}/format"] = np.array("dense")
            arrays[f"{prefix}/data"] = np.asarray(M)

    skipped = []
    put_matrix("X", data.X)
    # layers are X-shaped (possibly sparse): same triple encoding,
    # namespaced so load can rebuild them as matrices
    for k, v in data.layers.items():
        arr_like = v if sp.issparse(v) else np.asarray(v)
        if getattr(arr_like, "dtype", None) is not None and \
                arr_like.dtype == object:
            skipped.append(f"layers/{k}")  # pickled npz breaks resume
            continue
        put_matrix(f"LAYER::{k}", v)

    def put(key, v):
        if isinstance(v, dict):
            # nested dicts (e.g. de.rank_genes_groups results) flatten
            # into "//"-joined keys — np.savez would otherwise pickle
            # them as object arrays that allow_pickle=False can't load
            for sk, sv in v.items():
                put(f"{key}//{sk}", sv)
            return
        arr = np.asarray(v)
        if arr.dtype == object:
            skipped.append(key)
            return
        arrays[key] = arr

    for section in _SECTIONS:
        for k, v in getattr(data, section).items():
            put(f"{section}/{k}", v)
    if skipped:
        import warnings

        warnings.warn(
            f"save_celldata: skipped non-array entries {skipped}",
            stacklevel=2)
    tmp = path + ".tmp.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, path)


def load_celldata(path: str) -> CellData:
    import scipy.sparse as sp

    with np.load(path, allow_pickle=False) as z:
        def get_matrix(prefix):
            fmt = str(z[f"{prefix}/format"])
            if fmt == "csr":
                shape = tuple(z[f"{prefix}/shape"])
                return sp.csr_matrix(
                    (z[f"{prefix}/data"], z[f"{prefix}/indices"],
                     z[f"{prefix}/indptr"]), shape=shape)
            return z[f"{prefix}/data"]

        X = get_matrix("X")
        layers = {}
        for key in z.files:
            if key.startswith("LAYER::") and key.endswith("/format"):
                name = key[len("LAYER::"):-len("/format")]
                layers[name] = get_matrix(f"LAYER::{name}")
        sections: dict[str, dict] = {s: {} for s in _SECTIONS}
        for key in z.files:
            section, _, name = key.partition("/")
            if (section not in sections or key.startswith("X/")
                    or key.startswith("LAYER::")):
                continue
            target = sections[section]
            parts = name.split("//")
            for p in parts[:-1]:  # rebuild nested dicts
                target = target.setdefault(p, {})
            target[parts[-1]] = z[key]
    return CellData(X, layers=layers, **sections)


def step_fingerprint(steps, i: int) -> str:
    """Content hash (10 hex chars) of the step-``i`` prefix of
    ``steps`` — name plus parameters of every step up to and including
    ``i``, so a change to ANY earlier step invalidates everything
    downstream of it.  This is the step identity the checkpoint
    filenames embed; the ResilientRunner journals it so a run record
    can be matched to the exact pipeline configuration that produced
    it."""
    import hashlib

    def sig(v, h):
        # repr() alone is unsafe: numpy elides large arrays
        # ("[0, 1, ..., 9]"), so two configs differing mid-array
        # would collide — hash raw bytes for array-likes instead
        if isinstance(v, (list, tuple)):
            h.update(f"<{type(v).__name__}{len(v)}".encode())
            for x in v:
                sig(x, h)
            h.update(b">")
        elif isinstance(v, dict):
            h.update(f"<dict{len(v)}".encode())
            for kk in sorted(v, key=repr):
                h.update(repr(kk).encode())
                sig(v[kk], h)
            h.update(b">")
        elif isinstance(v, np.ndarray) or type(v).__module__.startswith(
                ("jax", "jaxlib")):
            a = np.asarray(v)
            h.update(f"nd{a.dtype}{a.shape}".encode())
            h.update(np.ascontiguousarray(a).tobytes())
        else:
            r = repr(v)
            # A default object repr embeds the memory address
            # ("<Foo object at 0x7f..>"), which changes every
            # process — hashing it would silently invalidate every
            # checkpoint on resume.  Strip addresses (stable across
            # runs) and warn that the param carries no real state.
            if " at 0x" in r:
                import re
                import warnings

                r = re.sub(r" at 0x[0-9a-fA-F]+", "", r)
                warnings.warn(
                    f"step_fingerprint: parameter {r!r} has no "
                    "stable repr; its internal state is NOT part of "
                    "the checkpoint hash — changing it will not "
                    "invalidate old checkpoints", stacklevel=2)
            h.update(r.encode())

    # hash of the (name, sorted params) prefix chain — stale
    # checkpoints from a different configuration (or an edited
    # earlier step) are never resumed
    h = hashlib.sha256()
    for t in steps[: i + 1]:
        h.update(t.name.encode())
        sig(dict(t.params), h)
    return h.hexdigest()[:10]


def step_filename(steps, i: int) -> str:
    """Checkpoint basename for step ``i``:
    ``step{i:03d}_{transform}_{fingerprint}.npz``.  Pure function of
    the step list — PipelineCheckpointer and the ResilientRunner both
    name through here, so their checkpoints interoperate (a run
    started under one resumes under the other)."""
    safe = steps[i].name.replace(".", "_").replace("/", "_")
    return f"step{i:03d}_{safe}_{step_fingerprint(steps, i)}.npz"


def latest_step(directory: str, steps, upto: int | None = None) -> int | None:
    """Index of the newest step whose checkpoint exists in
    ``directory`` under the CURRENT fingerprints, or ``None``.  Stale
    files from an edited configuration never match (their fingerprint
    differs), so they are simply ignored.  ``upto`` bounds the search
    to indices ``<= upto`` — how a resumer skips past a checkpoint it
    found unreadable and falls back to the next-newest one."""
    hi = len(steps) - 1 if upto is None else min(upto, len(steps) - 1)
    for i in range(hi, -1, -1):
        if os.path.exists(os.path.join(directory, step_filename(steps, i))):
            return i
    return None


class PipelineCheckpointer:
    """Run a ``Pipeline`` with a checkpoint after every step; resume
    skips steps whose checkpoint already exists.

    >>> ckpt = PipelineCheckpointer(pipe, "/path/to/ckpts")
    >>> out = ckpt.run(data, backend="tpu")       # writes step files
    >>> out = ckpt.run(data, backend="tpu")       # resumes: loads last

    Step files are named ``step{i:03d}_{transform}_{paramhash}.npz``
    (see :func:`step_filename`); a change to the step list OR to any
    step's parameters invalidates mismatched names automatically (the
    hash covers every step up to and including step ``i``, so editing
    an earlier step also invalidates everything downstream of it).
    """

    def __init__(self, pipeline, directory: str, save_every: int = 1):
        self.pipeline = pipeline
        self.directory = directory
        self.save_every = max(1, save_every)
        os.makedirs(directory, exist_ok=True)

    def _step_path(self, i: int, steps) -> str:
        return os.path.join(self.directory, step_filename(steps, i))

    def run(self, data: CellData, backend: str | None = None,
            resume: bool = True) -> CellData:
        steps = list(self.pipeline.steps)
        start = 0
        if resume:
            i = latest_step(self.directory, steps)
            if i is not None:
                data = load_celldata(self._step_path(i, steps))
                if backend in (None, "tpu"):
                    data = data.device_put()
                start = i + 1
        for i in range(start, len(steps)):
            t = steps[i]
            if backend is not None and backend != t.backend:
                t = t.with_backend(backend)
            data = t(data)
            if (i + 1) % self.save_every == 0 or i == len(steps) - 1:
                save_celldata(data, self._step_path(i, steps))
        return data

    def clear(self) -> None:
        for f in os.listdir(self.directory):
            if f.startswith("step") and f.endswith(".npz"):
                os.remove(os.path.join(self.directory, f))
