"""Execution barriers that cannot be lied to.

``block_until_ready`` is the canonical JAX barrier, but on the tunneled
single-chip backend this project benches on it can return *before* the
producing program has executed (round-4 measurement: a 68k-cell QC pass
"completed" in 1.2 ms — 58M cells/s — and the exact-kNN microbench
timed at 20x the chip's peak FLOP rate; both were dispatch-only
timings).  Fetching a result-dependent element to the host is the one
barrier no async runtime can skip: the bytes cannot arrive before the
program that produces them has run.

``hard_sync`` is therefore the project-wide drain primitive for
streaming loops (``config.stream_sync``) and for every steady-state
benchmark timing.  The fetch is one element per array — microseconds of
transfer — so using it on a real local TPU costs one RTT, nothing more.
"""

from __future__ import annotations

import numpy as np

__all__ = ["hard_sync"]


def hard_sync(*arrays):
    """Block until every ``array`` has actually been computed, by
    fetching a single element of each to the host.  Accepts jax arrays,
    numpy arrays (no-op), scalars (no-op), and objects exposing a
    ``.data`` array (``SparseCells``).  Returns the last fetched
    element (handy for smoke asserts)."""
    out = None
    for a in arrays:
        if a is None:
            continue
        if hasattr(a, "data") and not hasattr(a, "ndim"):
            a = a.data  # SparseCells and friends
        ndim = getattr(a, "ndim", None)
        if ndim is None:
            continue  # python scalar
        idx = (0,) * ndim
        # np.asarray of a 1-element slice forces execution of the
        # producing program; block_until_ready alone does not on the
        # tunneled backend (see module docstring).
        out = np.asarray(a[idx])
    return out
