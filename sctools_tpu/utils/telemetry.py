"""Process-wide telemetry: the metrics registry and the call
instrumentation hook.

The resilience stack (PRs 1–3) answers "did the run survive"; this
module answers "what did the run DO" — how many retries, degrades,
cache misses and checkpoint bytes, and where the wall-clock went per
op — without reading three disjoint artifacts by hand.  Three pieces:

* :class:`MetricsRegistry` — counters, gauges and histograms with
  FIXED bucket boundaries, keyed by ``(name, labels)``.  Metric names
  are drawn from the central :data:`METRICS` vocabulary (sctlint
  SCT009 checks every literal call site against it, so a typo'd
  counter name fails lint instead of silently forking a series).
  Anything timed goes through the injectable clock
  (``utils/vclock.py``), so timing-shaped tests run with zero real
  sleeps.
* :func:`instrument_calls` — a ``registry.push_call_wrapper`` hook
  that auto-instruments EVERY transform invocation (``apply``,
  ``Transform.__call__``, every ``Pipeline``/recipe step) with
  per-op call counts, error counts and duration histograms, labelled
  by op name and backend (``cpu`` / ``tpu`` / ``degraded``).
* :data:`EVENTS` — the run-journal event vocabulary.  The runner's
  ``journal.write(event, ...)`` literals must be members (SCT009
  again): the journal, the metrics snapshot and the exported span
  trace are one joined observability surface (docs/ARCHITECTURE.md
  "Observability"), and a typo'd event name would silently fall out
  of every ``tools/sctreport.py`` report.

NO DEVICE SYNCS ON THE HOT PATH: recording a metric touches Python
scalars and the injectable clock only — never a device array.  On an
async backend the instrumented duration is therefore the HOST
DISPATCH wall, not the device execution wall; for execution walls put
a ``trace.span(sync=True)`` barrier at the stage boundary instead
(that is a measurement you opt into, never a side effect of
telemetry being on).

>>> from sctools_tpu.utils import telemetry
>>> with telemetry.instrument_calls() as m:
...     sct.apply("normalize.log1p", data, backend="tpu")
>>> m.snapshot()["counters"]["op.calls{backend=tpu,op=normalize.log1p}"]
1
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time

from .vclock import SYSTEM_CLOCK, Clock

# ---------------------------------------------------------------------------
# Central vocabularies (the SCT009 contract)
# ---------------------------------------------------------------------------

#: Every legal run-journal event name.  ``journal.write(...)`` call
#: sites must use literal members (sctlint SCT009); sctreport and the
#: docs/ARCHITECTURE.md event table are generated against this set.
EVENTS = frozenset({
    # run lifecycle
    "run_start", "run_completed", "run_failed", "run_aborted",
    # per-step execution
    "attempt", "backoff", "deadline", "checkpoint",
    # containment ladder rulings
    "breaker_open", "breaker_close", "breaker_reopen",
    "health_check", "fallback", "degrade", "quarantine",
    # resume
    "resume", "resume_unverified_input", "resume_place_failed",
    # end-of-run telemetry artifacts
    "metrics_written", "trace_exported",
    # scheduler admission funnel (sctools_tpu/scheduler.py; terminal
    # run events reuse run_completed/run_failed with ticket= fields)
    "submitted", "admitted", "rejected", "shed",
    # ingest IO-failure domain (sctools_tpu/data/shardstore.py): a
    # corrupt/truncated shard chunk was moved — never deleted — to
    # quarantine/ with a .reason.json sidecar
    "shard_quarantined",
    # federation tier (sctools_tpu/federation.py): worker-process
    # supervision.  assigned = ticket handed to a worker's inbox;
    # worker_lost carries the dead worker's journal tail grafted in;
    # requeued = an in-flight ticket moved back to the queue with a
    # bumped epoch (the fencing guard: only the CURRENT epoch's
    # result is ever accepted); commit_refused = a result from a
    # fenced/stale epoch was refused — by the worker itself (it saw
    # the fence before committing) or by the supervisor (epoch
    # mismatch at acceptance)
    "worker_spawned", "worker_lost", "worker_respawned",
    "assigned", "requeued", "commit_refused",
    # preemption-tolerant training (models/train_stream.py +
    # scheduler.py cooperative preemption): preempted = a job
    # checkpoint-then-yielded at a shard boundary (runner: step-level
    # record; scheduler: the ticket re-enters the queue with its
    # cursor — NOT a terminal state — or terminals as shed when the
    # reason is "cancelled"); train_shard/train_epoch mark completed
    # training units (the no-replayed-shards proof joins on their
    # (epoch, pos) pairs), train_checkpoint a cursor save,
    # train_resume a restart from a verified cursor checkpoint
    "preempted", "train_shard", "train_epoch", "train_checkpoint",
    "train_resume",
    # resident-state serving (sctools_tpu/serving.py): the reference-
    # model lifecycle.  model_loaded = a verified artifact generation
    # became the resident model (initial load, reload after state
    # loss, or the .prev fallback after a quarantine);
    # model_quarantined = an artifact generation failed its digest/
    # fingerprint verification and was moved — never deleted — to
    # quarantine/ with a .reason.json sidecar; model_swapped = a
    # canary-validated hot-swap flipped the serving epoch (in-flight
    # queries complete on the epoch they were admitted under);
    # swap_rolled_back = a candidate model was refused (corrupt
    # artifact or canary disagreement) and the old epoch kept serving
    "model_loaded", "model_quarantined", "model_swapped",
    "swap_rolled_back",
    # memory fault domain (sctools_tpu/memory.py + scheduler/serving/
    # train_stream): mem_reserved = bytes held against the per-backend
    # MemoryBudget (a dispatched run's estimated peak, or a named
    # resident — the serving model's STANDING hold, the trainer's
    # run-scoped feed window); mem_released = the hold dropped (run
    # terminal, preemption yield, resident retired).
    # OOM containment rulings reuse the existing `degrade` event with
    # reason="oom" + rung= + from/to estimates.
    "mem_reserved", "mem_released",
    # annotation factory (sctools_tpu/factory.py): the closed-loop
    # ingest -> retrain -> freeze -> swap cycle.  Every record carries
    # cycle= (NEVER ticket= — the factory's lifecycle is a stage
    # ladder, not an admission funnel, and must not merge with the
    # scheduler's terminal-exactly-once proof).  ingest_committed =
    # a verified batch durably appended to the live shard store
    # (manifest replace = the at-most-once commit point);
    # retrain_triggered = streamed retraining submitted through the
    # shared scheduler funnel; artifact_built = the retrained model
    # frozen into a digest-verified reference artifact;
    # swap_promoted = the canary-validated artifact became the live
    # serving epoch (the factory-side record of serving's
    # model_swapped; rollback reuses swap_rolled_back with cycle=)
    "ingest_committed", "retrain_triggered", "artifact_built",
    "swap_promoted",
    # network fault domain (sctools_tpu/transport.py): the message-
    # transport plane federation/breaker protocols ride on.  Every
    # record carries peer= (NEVER ticket= — transport messages are a
    # notification plane, not the admission funnel, and must not
    # merge with the scheduler's terminal-exactly-once proof).
    # net_sent = a frame was delivered and acknowledged (terminal for
    # the message); net_retry = a send attempt timed out / was
    # dropped and a seeded-jitter backoff rescheduled it; net_gave_up
    # = retries exhausted, the message was abandoned (terminal — the
    # caller degrades: leases ride to lease_timeout_s, commits fall
    # back to the result-file probe, breakers go LOCAL-ONLY);
    # net_partition_entered = the first gave-up against a reachable-
    # until-now peer opened a partition window; net_rejoin = the next
    # successful delivery healed it (breaker registries reconcile by
    # epoch under this record — the no-split-brain proof joins
    # entered/rejoin pairs)
    "net_sent", "net_retry", "net_gave_up",
    "net_partition_entered", "net_rejoin",
    # file-transport breaker claim audit (federation.py): a stale
    # .probe claim file (its owner died mid-probe, claim older than
    # the lease timeout) was swept so the HALF_OPEN probe slot frees
    "probe_reclaimed",
    # SLO rulings (sctools_tpu/slo.py): a declared objective's error
    # budget started burning faster than its fast+slow windows allow
    # (slo_breach, with the measured burn rates and the window), and
    # the later record that closed the breach window once the fast
    # window cooled (slo_recovered — every breach must eventually pair
    # with exactly one recovery, the windows-close contract sctreport
    # joins on)
    "slo_breach", "slo_recovered",
})

#: Every legal metric name → one-line meaning (the docs table).  Like
#: EVENTS, literal ``counter()/gauge()/histogram()/timer()`` call
#: sites must use members (SCT009) — a typo would fork a series that
#: no report ever reads.
METRICS = {
    "op.calls": "counter: transform invocations (labels op=, backend=)",
    "op.errors": "counter: transform invocations that raised "
                 "(labels op=, backend=)",
    "op.duration_s": "histogram: per-transform host dispatch wall "
                     "seconds (labels op=, backend=)",
    "runner.attempts": "counter: step attempts (labels status=, "
                       "backend=)",
    "runner.retries": "counter: backoff retries scheduled",
    "runner.deadline_overruns": "counter: StepDeadlineExceeded raises",
    "runner.degrades": "counter: degrade-to-fallback rulings "
                       "(labels reason=)",
    "runner.breaker_transitions": "counter: circuit-breaker "
                                  "transitions (labels to=)",
    "runner.quarantines": "counter: checkpoints quarantined on resume",
    "runner.resumes": "counter: runs resumed from a verified "
                      "checkpoint",
    "runner.checkpoint_writes": "counter: step checkpoints written",
    "runner.checkpoint_bytes": "counter: bytes written to step "
                               "checkpoints",
    "runner.step_wall_s": "histogram: per-step-attempt wall seconds "
                          "(labels status=)",
    "plan.cache_hits": "counter: fused-stage executions served from "
                       "the process-wide plan cache (zero retrace)",
    "plan.cache_misses": "counter: fused-stage compilations (trace + "
                         "compile on first sight of a signature)",
    "plan.fused_ops": "counter: member transforms executed inside "
                      "fused stages (the dispatch loop they skipped)",
    "plan.fallbacks": "counter: fused stages that failed to trace and "
                      "fell back to eager step-by-step execution",
    "bucket.pad_rows": "counter: padding rows added by "
                       "buckets.pad_to_bucket across all admissions — "
                       "the rows the device computes and throws away",
    "bucket.pad_frac": "gauge: padding fraction of the most recent "
                       "pad_to_bucket (labels axis= cells|genes) — "
                       "sustained high values mean the bucket ladder "
                       "is too coarse for the traffic",
    "bucket.hits": "counter: datasets padded into each bucket shape "
                   "(labels bucket= <rows>x<genes>) — the occupancy "
                   "histogram sctreport's buckets section renders",
    "plan.sharded_stages": "counter: mesh-sharded stage executions "
                           "(GSPMD-fused or collective-bodied)",
    "plan.reshards_avoided": "counter: sharded-stage input leaves that "
                             "arrived already partitioned to the "
                             "stage's in_shardings (no boundary "
                             "reshard)",
    "plan.mesh_cache_misses": "counter: plan-cache misses attributable "
                              "to a mesh change on an already-seen "
                              "stage signature (a rebuilt identical "
                              "mesh never counts)",
    "stream.overlap_s": "counter: prefetch worker seconds (decode + "
                        "pack + device_put) hidden behind consumer "
                        "compute",
    "stream.stall_s": "counter: consumer seconds stalled waiting on "
                      "the prefetch queue (producer-bound stream)",
    "graph.reorder_s": "counter: seconds spent computing + applying "
                       "locality reorders (graph.reorder / "
                       "graph.restore_order host passes)",
    "graph.tile_density": "gauge: fraction of kNN edges within one "
                          "row block of the diagonal (labels "
                          "layout=natural|reordered) — the locality "
                          "the tiled graph kernels exploit",
    "graph.kernel_calls": "counter: tiled graph-kernel dispatches "
                          "(labels kernel=, impl=) — one per "
                          "execution from eager call sites, one per "
                          "TRACE when the caller is inside an "
                          "enclosing jit (the compiled program "
                          "re-runs without re-dispatching)",
    "sched.queue_depth": "gauge: runs waiting in the scheduler's "
                         "admission queue (set on every queue "
                         "mutation)",
    "sched.admitted": "counter: submissions admitted to the queue "
                      "(labels tenant=)",
    "sched.rejected": "counter: submissions refused at admission "
                      "(labels tenant=, reason= tenant_queue_quota|"
                      "deadline_unmeetable|queue_full|reject_storm|"
                      "scheduler_closed|over_memory)",
    "sched.shed": "counter: admitted runs dropped before running or "
                  "cooperatively cancelled while running (labels "
                  "tenant=, reason= queue_high_water|"
                  "deadline_expired|shutdown|cancelled|over_memory)",
    "sched.queue_wait_s": "histogram: admission-to-dispatch queue "
                          "wait seconds (on the injectable clock)",
    "ingest.reads": "counter: shard reads served to a consumer "
                    "(labels outcome= served|retried|hedged) — every "
                    "terminated read lands in exactly one outcome "
                    "(quarantined shards count under "
                    "ingest.quarantines instead)",
    "ingest.retries": "counter: shard-read attempts re-issued after a "
                      "classified-transient IO failure (plus "
                      "prefetch-worker prepare retries)",
    "ingest.hedges": "counter: duplicate reads issued for stragglers "
                     "past the hedge latency SLO (first result wins)",
    "ingest.quarantines": "counter: corrupt/truncated shard chunks "
                          "moved to quarantine/ (never deleted)",
    "ingest.bytes": "counter: decoded padded-ELL bytes handed to "
                    "consumers by the shard-read scheduler",
    "ingest.read_wait_s": "histogram: consumer wait for a shard read "
                          "(submission to first served result, on "
                          "the injectable clock)",
    "fed.heartbeats": "counter: worker heartbeats credited by the "
                      "federation supervisor (labels worker=) — a "
                      "wedged worker's withheld beats are NOT counted",
    "fed.lease_age_s": "histogram: worker lease age at each "
                       "supervision check (on the injectable clock); "
                       "ages past the lease timeout classify the "
                       "worker process_lost",
    "fed.workers_lost": "counter: workers ruled lost (labels reason= "
                        "exited|lease_expired) — each is fenced, "
                        "reaped and its in-flight tickets requeued",
    "fed.requeues": "counter: in-flight tickets requeued off a lost "
                    "worker with a bumped epoch (the new owner "
                    "RESUMES from the checkpoint fingerprint — never "
                    "replays completed stages)",
    "fed.fenced_commits": "counter: results refused because they came "
                          "from a fenced worker or a stale epoch "
                          "(the at-most-once acceptance guard)",
    "fed.recovered_commits": "counter: commits accepted from the "
                             "result file on the supervision tick — "
                             "the worker's `done` line was lost in "
                             "transit (the rename is the record, the "
                             "stderr line only the doorbell)",
    "fed.breaker_syncs": "counter: remote breaker transitions applied "
                         "from the cross-process transport (labels "
                         "signature=, to= open|closed) — how one "
                         "worker's trip short-circuits the pool",
    "train.steps": "counter: optimizer steps taken by the streaming "
                   "trainer (one per minibatch inside the per-shard "
                   "scan)",
    "train.epochs": "counter: training epochs completed over the "
                    "shard store",
    "train.shards": "counter: shards trained through (one per "
                    "completed per-shard scan — the unit the resume "
                    "cursor moves in)",
    "train.preemptions": "counter: checkpoint-then-yield rulings "
                         "honoured at a shard boundary (labels "
                         "reason= preempt|cancelled|priority|...)",
    "train.resumes": "counter: training runs resumed from a verified "
                     "cursor checkpoint (never a silent epoch "
                     "restart)",
    "train.overlap_s": "counter: shard decode + device_put seconds "
                       "hidden behind the train step on the previous "
                       "shard (the double-buffered device feed)",
    "train.stall_s": "counter: trainer seconds stalled waiting on "
                     "the shard feed (IO-bound training)",
    "train.loss": "gauge: mean negative ELBO of the last completed "
                  "epoch (labels epoch=) — the loss trajectory "
                  "sctreport renders",
    "serve.queries": "counter: annotation-service queries by terminal "
                     "state (labels outcome= completed|failed|"
                     "rejected|shed) — every query lands in exactly "
                     "one outcome",
    "serve.latency_s": "histogram: completed-query wall seconds from "
                       "admission to terminal (on the injectable "
                       "clock)",
    "serve.swaps": "counter: canary-validated hot-swaps that flipped "
                   "the serving epoch",
    "serve.rollbacks": "counter: refused model swaps (corrupt "
                       "candidate artifact or canary disagreement) — "
                       "the old epoch kept serving",
    "serve.state_reloads": "counter: residency-ladder rungs taken for "
                           "resident reference-model state (labels "
                           "reason= replace|artifact|breaker_open|"
                           "cpu|oom) — replace = re-place evicted "
                           "device buffers from the host mirror, "
                           "artifact = verified reload from disk, "
                           "breaker_open/cpu = queries served from "
                           "host arrays, oom = device memory refused "
                           "the placement or kernel",
    "mem.budget_bytes": "gauge: the per-backend MemoryBudget's "
                        "nameplate capacity (device "
                        "memory_stats()['bytes_limit'] or the "
                        "SCTOOLS_MEM_BUDGET_BYTES env cap)",
    "mem.reserved_bytes": "gauge: bytes currently reserved against "
                          "the budget (dispatched runs' estimated "
                          "peaks + standing resident reservations), "
                          "set on every ledger mutation",
    "mem.oom_events": "counter: RESOURCE-classified step failures by "
                      "the containment-ladder rung that answered "
                      "them (labels rung= unfuse|replan|cpu|fail)",
    "mem.estimate_corrections": "counter: stored peak-memory "
                                "estimates inflated by an observed "
                                "OOM (the self-correcting model's "
                                "learning events)",
    "net.rtt_ms": "histogram: socket-transport send-to-ack round "
                  "trip milliseconds (labels peer=) — real wall "
                  "time on localhost, virtual time under injected "
                  "net_delay",
    "net.retries": "counter: socket-transport send attempts "
                   "re-issued after a timeout/drop (labels peer=) — "
                   "seeded-jitter backoff on the injectable clock",
    "obs.ticks": "counter: time-series ticks recorded into this "
                 "registry's bounded ring buffer (one per tick(), on "
                 "the injectable clock)",
    "obs.frames": "counter: obs delta frames merged into the fleet "
                  "registry by the supervisor-side aggregator "
                  "(labels worker=)",
    "obs.dropped": "counter: obs delta frames discarded instead of "
                   "merged (labels reason= stale_gen|decode|merge) — "
                   "obs is a lossy plane, a dropped frame is a "
                   "counted non-event, never an error",
    "obs.flushes": "counter: tick-stamped fleet snapshots durably "
                   "written under obs/ by the supervisor",
    "slo.burn_rate": "gauge: latest measured error-budget burn rate "
                     "per objective window (labels objective=, "
                     "window= fast|slow) — 1.0 burns the whole "
                     "budget in exactly the objective's period",
    "slo.breaches": "counter: slo_breach rulings journaled (labels "
                    "objective=)",
}

#: Per-module journal PROTOCOLS — which EVENTS members a module may
#: emit, and which of them are TERMINAL for that module's lifecycle
#: (every ticket/run must reach exactly one; chaos soaks assert the
#: runtime half, sctlint SCT012 the static half: every emission site
#: names a legal event for its module, and every declared terminal
#: state has at least one emission site, so a refactor cannot
#: silently drop the path that closes a ticket).  Keys are module
#: basenames (matched on the repo-relative path tail, like SCT005/
#: SCT008); the tables are AST-extracted by the linter, never
#: imported.  Adding an event: put it in EVENTS, add it to its
#: module's table here, then emit it (docs/GUIDE.md "Adding a journal
#: event without breaking SCT012").
JOURNAL_PROTOCOLS = {
    # admission funnel: submitted -> admitted | rejected, then
    # (preempted ...)* and exactly one terminal per ticket; with a
    # MemoryBudget the dispatch/terminal pair also journals the
    # ticket's reservation (mem_reserved/mem_released)
    "scheduler": {
        "events": ["submitted", "admitted", "rejected", "shed",
                   "preempted", "run_completed", "run_failed",
                   "mem_reserved", "mem_released"],
        "terminal": ["rejected", "shed", "run_completed",
                     "run_failed"],
    },
    # the federated funnel adds worker supervision + fencing records;
    # terminal-exactly-once must hold even when a worker dies mid-run
    "federation": {
        "events": ["submitted", "admitted", "rejected", "shed",
                   "run_completed", "run_failed", "worker_spawned",
                   "worker_lost", "worker_respawned", "assigned",
                   "requeued", "commit_refused", "probe_reclaimed"],
        "terminal": ["rejected", "shed", "run_completed",
                     "run_failed"],
    },
    # per-run lifecycle: run_start -> attempts/rulings -> exactly one
    # of the three verdicts (preempted is deliberately non-terminal)
    "runner": {
        "events": ["run_start", "attempt", "backoff", "deadline",
                   "checkpoint", "breaker_open", "breaker_close",
                   "breaker_reopen", "health_check", "fallback",
                   "degrade", "quarantine", "resume",
                   "resume_unverified_input", "resume_place_failed",
                   "metrics_written", "trace_exported", "preempted",
                   "run_completed", "run_failed", "run_aborted"],
        "terminal": ["run_completed", "run_failed", "run_aborted"],
    },
    # train cursor events: shard/epoch progress + cursor saves; the
    # epoch record is the unit the no-replayed-shards proof joins on
    "train_stream": {
        "events": ["train_shard", "train_epoch", "train_checkpoint",
                   "train_resume", "preempted",
                   "mem_reserved", "mem_released"],
        "terminal": ["train_epoch"],
    },
    # the IO-failure domain journals only the quarantine verdict
    "shardstore": {
        "events": ["shard_quarantined"],
        "terminal": ["shard_quarantined"],
    },
    # resident-state serving journals the MODEL lifecycle only; the
    # per-query funnel (submitted -> admitted|rejected -> shed|
    # run_completed|run_failed) is emitted by the scheduler the
    # service admits through, into the same journal file.  No
    # terminal: the model lifecycle is a ladder, not a ticket funnel
    # (the queries' terminal-exactly-once contract lives in the
    # scheduler's table).
    "serving": {
        "events": ["model_loaded", "model_quarantined",
                   "model_swapped", "swap_rolled_back",
                   "mem_reserved", "mem_released"],
        "terminal": [],
    },
    # the annotation factory's closed loop: each cycle climbs ingest
    # -> retrain -> build -> swap, every record keyed cycle= (never
    # ticket=), and terminals exactly once per cycle: swap_promoted
    # on a canary-validated promotion, swap_rolled_back (with the
    # journaled reason) when the candidate was refused and the old
    # epoch kept serving
    "factory": {
        "events": ["ingest_committed", "retrain_triggered",
                   "artifact_built", "swap_promoted",
                   "swap_rolled_back"],
        "terminal": ["swap_promoted", "swap_rolled_back"],
    },
    # the network message plane: every message keyed peer= terminals
    # exactly once — net_sent (delivered + acked) or net_gave_up
    # (retries exhausted; the caller's degradation ladder takes
    # over).  net_retry records each re-issued attempt in between;
    # partition windows are the entered/rejoin pair sctreport's
    # convergence check joins on.
    "transport": {
        "events": ["net_sent", "net_retry", "net_gave_up",
                   "net_partition_entered", "net_rejoin"],
        "terminal": ["net_sent", "net_gave_up"],
    },
    # SLO burn-rate rulings (sctools_tpu/slo.py): every record keyed
    # objective= (never ticket= — an objective window aggregates many
    # tickets and must not merge with the admission funnel's
    # terminal-exactly-once proof).  A breach window opens with
    # slo_breach (fast AND slow burn rates over threshold) and closes
    # with exactly one slo_recovered once the fast window cools —
    # the terminal here is the window's, not a ticket's.
    "slo": {
        "events": ["slo_breach", "slo_recovered"],
        "terminal": ["slo_recovered"],
    },
}

#: Fixed histogram bucket upper bounds (seconds), chosen to straddle
#: everything from a cached jit dispatch (~1 ms) to a wedged-step
#: deadline (minutes).  FIXED on purpose: snapshots from different
#: runs/processes merge bucket-by-bucket only if the boundaries never
#: move.  A terminal +inf bucket is implicit.
DURATION_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                    1.0, 5.0, 10.0, 30.0, 60.0, 300.0)

#: Millisecond-scale latency ladder for paths whose p99 lives well
#: below DURATION_BUCKETS' first rung (a resident-model serving query
#: completes in ~2.5 ms; on the coarse ladder its whole distribution
#: collapses into two buckets and a p99 estimate is meaningless).
#: Spans 0.1 ms – 2.5 s.  Same fixed-boundary contract as
#: DURATION_BUCKETS: snapshots merge bucket-by-bucket only because
#: the boundaries never move.
LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                   0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)

#: Per-metric bucket presets.  ``histogram(name)`` call sites that do
#: not pass ``buckets=`` get the preset ladder for ``name`` (falling
#: back to DURATION_BUCKETS), so EVERY call site of a preset metric
#: agrees on boundaries without repeating them — get-or-create keeps
#: the first creation's buckets, and the preset makes the first
#: creation the same everywhere.
BUCKET_PRESETS = {
    "serve.latency_s": LATENCY_BUCKETS,
    "sched.queue_wait_s": LATENCY_BUCKETS,
}

#: metrics.json layout version (bump on incompatible change)
SNAPSHOT_SCHEMA = 1

#: default bounded ring-buffer capacity for time-series ticks — at
#: the federation supervisor's per-supervision-tick cadence this
#: holds minutes of trail; the ring discards the oldest tick, never
#: blocks a recorder
SERIES_CAPACITY = 240


# ---------------------------------------------------------------------------
# Metric primitives
# ---------------------------------------------------------------------------

class Counter:
    """Monotonic sum.  ``inc`` only — a counter that can go down is a
    gauge wearing the wrong name.  Mutation holds a lock (the owning
    registry's RLock, so a snapshot mid-``inc`` never tears): ``+=``
    on an attribute is read-modify-write, and the GIL does not make
    that atomic."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock=None):
        self.value = 0.0
        self._lock = lock if lock is not None else threading.RLock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("Counter.inc(n) requires n >= 0 — use a "
                             "Gauge for values that go down")
        with self._lock:
            self.value += n


class Gauge:
    """Last-written value (queue depth, residency bytes, breaker
    failures-in-window)."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock=None):
        self.value = 0.0
        self._lock = lock if lock is not None else threading.RLock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Fixed-boundary histogram: per-bucket counts plus count/sum/max.

    ``observe(v)`` increments the first bucket whose upper bound
    holds ``v`` (terminal +inf bucket implicit).  The snapshot emits
    CUMULATIVE counts per bound (prometheus ``le`` style), which is
    what makes cross-run merges a per-bucket add.  ``observe`` and
    ``to_dict`` hold the lock, so a snapshot never sees ``count``
    disagree with the bucket totals."""

    __slots__ = ("buckets", "counts", "count", "sum", "max", "_lock")

    def __init__(self, buckets=DURATION_BUCKETS, lock=None):
        self.buckets = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError("histogram buckets must be strictly "
                             "increasing")
        self.counts = [0] * (len(self.buckets) + 1)  # +1: the +inf bucket
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self._lock = lock if lock is not None else threading.RLock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if v > self.max:
                self.max = v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def to_dict(self) -> dict:
        with self._lock:
            cum, acc = {}, 0
            for b, c in zip(self.buckets, self.counts):
                acc += c
                cum[f"{b:g}"] = acc
            cum["+inf"] = acc + self.counts[-1]
            return {"count": self.count, "sum": round(self.sum, 6),
                    "max": round(self.max, 6), "buckets": cum}

    def merge(self, d: dict) -> None:
        """Fold a delta doc (``count``/``sum``/``max`` plus RAW
        per-bucket ``counts`` on the SAME boundaries) into this
        histogram — the fleet aggregator's cross-process add.
        Boundary mismatch raises: fixed buckets are the merge
        precondition, a silent re-bin would fabricate latencies."""
        bounds = tuple(float(b) for b in (d.get("buckets")
                                          or self.buckets))
        if bounds != self.buckets:
            raise ValueError(
                "histogram merge across differing bucket boundaries: "
                f"{bounds} vs {self.buckets}")
        counts = d.get("counts") or [0] * len(self.counts)
        if len(counts) != len(self.counts):
            raise ValueError("histogram merge: bucket count mismatch")
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += int(c)
            self.count += int(d.get("count", 0))
            self.sum += float(d.get("sum", 0.0))
            if float(d.get("max", 0.0)) > self.max:
                self.max = float(d.get("max", 0.0))


def _series_key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def split_series_key(key: str) -> tuple:
    """Inverse of the series-key encoding:
    ``"name{a=b,c=d}"`` → ``("name", {"a": "b", "c": "d"})``.  The
    fleet aggregator uses it to re-label another process's series
    with ``worker=`` before merging."""
    if "{" not in key or not key.endswith("}"):
        return key, {}
    name, _, inner = key.partition("{")
    labels = {}
    for part in inner[:-1].split(","):
        if part:
            k, _, v = part.partition("=")
            labels[k] = v
    return name, labels


class MetricsRegistry:
    """Process-wide, thread-safe registry of labelled metric series.

    ``counter/gauge/histogram`` are get-or-create on the
    ``(name, labels)`` key; :meth:`timer` observes an elapsed-seconds
    histogram measured on the INJECTABLE clock (``clock=``, default
    the system clock) — hand every participant one ``VirtualClock``
    and timing tests never really sleep.  One RLock (reentrant: a
    snapshot reads histogram cells under it) guards the series maps
    AND every cell's mutation, so concurrent increments never lose
    updates and snapshots never tear.
    """

    def __init__(self, clock: Clock | None = None,
                 series_capacity: int = SERIES_CAPACITY):
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self._lock = threading.RLock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        # time-series trail: bounded ring of tick records plus the
        # snapshot_delta() cursors (last exported value per series)
        self._ticks: collections.deque = collections.deque(
            maxlen=max(1, int(series_capacity)))
        self._tick_seq = 0
        self._last_tick_t: float | None = None
        self._delta_seq = 0
        self._delta_counters: dict[str, float] = {}
        self._delta_gauges: dict[str, float] = {}
        self._delta_hists: dict[str, tuple] = {}

    # -- series accessors ------------------------------------------------
    def counter(self, name: str, **labels) -> Counter:
        key = _series_key(name, labels)
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter(lock=self._lock)
        return c

    def gauge(self, name: str, **labels) -> Gauge:
        key = _series_key(name, labels)
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = Gauge(lock=self._lock)
        return g

    def histogram(self, name: str, buckets=None,
                  **labels) -> Histogram:
        if buckets is None:
            buckets = BUCKET_PRESETS.get(name, DURATION_BUCKETS)
        key = _series_key(name, labels)
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = Histogram(buckets,
                                                      lock=self._lock)
        return h

    @contextlib.contextmanager
    def timer(self, name: str, **labels):
        """Observe the enclosed block's elapsed seconds (on the
        injectable clock) into the ``name`` histogram."""
        h = self.histogram(name, **labels)
        t0 = self.clock.monotonic()
        try:
            yield h
        finally:
            h.observe(self.clock.monotonic() - t0)

    # -- time series -----------------------------------------------------
    def tick(self) -> dict:
        """Record one time-series tick — the full state of every
        series, stamped with the injectable clock AND wall time — into
        the bounded ring buffer.  Telemetry as a TRAIL: a process
        SIGKILLed mid-run has its series up to the last tick, not just
        a final number it never got to write.  Histograms keep RAW
        per-bucket counts here (cheap windowed deltas for the SLO
        burn-rate math); ``time.time()`` is the journal-FACT wall
        stamp, scheduling stays on ``self.clock``."""
        with self._lock:
            self.counter("obs.ticks").inc()
            self._tick_seq += 1
            rec = {
                "tick": self._tick_seq,
                "t": round(self.clock.monotonic(), 6),
                "wall": round(time.time(), 3),
                "counters": {k: c.value
                             for k, c in self._counters.items()},
                "gauges": {k: g.value
                           for k, g in self._gauges.items()},
                "histograms": {
                    k: {"count": h.count, "sum": round(h.sum, 6),
                        "max": round(h.max, 6),
                        "buckets": list(h.buckets),
                        "counts": list(h.counts)}
                    for k, h in self._histograms.items()},
            }
            self._ticks.append(rec)
            self._last_tick_t = rec["t"]
            return rec

    def maybe_tick(self, interval_s: float):
        """``tick()`` if at least ``interval_s`` has elapsed on the
        injectable clock since the last one (else ``None``) — the
        rate-limited form hot paths call without owning a schedule."""
        with self._lock:
            if self._last_tick_t is not None and \
                    self.clock.monotonic() - self._last_tick_t \
                    < interval_s:
                return None
            return self.tick()

    def series(self) -> list:
        """The ring-buffer trail, oldest tick first."""
        with self._lock:
            return list(self._ticks)

    def snapshot_delta(self) -> dict:
        """Cheap incremental export: only series that CHANGED since
        the previous ``snapshot_delta()`` call, with counter/histogram
        values as deltas (gauges as last value).  This is the payload
        workers ship to the supervisor on the lossy obs plane — small
        because idle series drop out, and mergeable because histogram
        deltas ride raw fixed-boundary bucket counts.

        The cursor advances on export, so a LOST frame loses that
        window's increments — by design: obs is lossy-tolerant, the
        next full snapshot/tick still has the true totals locally."""
        with self._lock:
            self._delta_seq += 1
            out = {"seq": self._delta_seq,
                   "t": round(self.clock.monotonic(), 6),
                   "wall": round(time.time(), 3),
                   "counters": {}, "gauges": {}, "histograms": {}}
            for k, c in self._counters.items():
                prev = self._delta_counters.get(k, 0.0)
                if c.value != prev:
                    out["counters"][k] = round(c.value - prev, 6)
                    self._delta_counters[k] = c.value
            for k, g in self._gauges.items():
                if self._delta_gauges.get(k) != g.value:
                    out["gauges"][k] = g.value
                    self._delta_gauges[k] = g.value
            for k, h in self._histograms.items():
                prev = self._delta_hists.get(k)
                if prev is None or h.count != prev[0]:
                    pc, ps, pcounts = prev if prev is not None else (
                        0, 0.0, [0] * len(h.counts))
                    out["histograms"][k] = {
                        "count": h.count - pc,
                        "sum": round(h.sum - ps, 6),
                        "max": round(h.max, 6),
                        "buckets": list(h.buckets),
                        "counts": [a - b for a, b
                                   in zip(h.counts, pcounts)],
                    }
                    self._delta_hists[k] = (h.count, h.sum,
                                            list(h.counts))
            return out

    def merge_delta(self, delta: dict, **extra_labels) -> None:
        """Apply a ``snapshot_delta()`` doc from ANOTHER process into
        this registry, re-labelling every series with
        ``extra_labels`` (the fleet aggregator passes ``worker=``).
        Counters add, gauges overwrite, histograms fold bucket-by-
        bucket (same fixed boundaries or :meth:`Histogram.merge`
        raises)."""
        for key, v in (delta.get("counters") or {}).items():
            name, labels = split_series_key(key)
            labels.update(extra_labels)
            if v > 0:
                self.counter(name, **labels).inc(v)
        for key, v in (delta.get("gauges") or {}).items():
            name, labels = split_series_key(key)
            labels.update(extra_labels)
            self.gauge(name, **labels).set(v)
        for key, d in (delta.get("histograms") or {}).items():
            name, labels = split_series_key(key)
            labels.update(extra_labels)
            bounds = tuple(float(b) for b in (d.get("buckets")
                                              or DURATION_BUCKETS))
            self.histogram(name, buckets=bounds, **labels).merge(d)

    # -- snapshots -------------------------------------------------------
    def snapshot(self) -> dict:
        """Full JSON-ready view: ``{"counters", "gauges",
        "histograms"}``, each keyed ``name{label=value,...}``."""
        with self._lock:
            return {
                "counters": {k: c.value
                             for k, c in sorted(self._counters.items())},
                "gauges": {k: g.value
                           for k, g in sorted(self._gauges.items())},
                "histograms": {k: h.to_dict()
                               for k, h in sorted(self._histograms.items())},
            }

    def snapshot_compact(self) -> dict:
        """Counters only — the cheap glimpse bench stage lines embed."""
        with self._lock:
            return {k: c.value for k, c in sorted(self._counters.items())}

    def write(self, path: str, series: bool = False) -> str:
        """Atomically write the snapshot as ``metrics.json`` (tmp +
        rename — a crash mid-write must not leave a half file where
        sctreport looks).  ``series=True`` embeds the ring-buffer
        trail too — the tick-stamped form the federation supervisor
        flushes under ``obs/``."""
        doc = {"schema": SNAPSHOT_SCHEMA,
               "written_at": round(time.time(), 3),
               "metrics": self.snapshot()}
        if series:
            doc["series"] = self.series()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._ticks.clear()
            self._tick_seq = 0
            self._last_tick_t = None
            self._delta_seq = 0
            self._delta_counters.clear()
            self._delta_gauges.clear()
            self._delta_hists.clear()


#: the process-wide default registry ("process-wide" is the contract:
#: every layer that doesn't get an explicit ``metrics=`` records here,
#: so one snapshot sees the whole process)
_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT


# ---------------------------------------------------------------------------
# Auto-instrumentation of transform calls
# ---------------------------------------------------------------------------

class CallInstrumentor:
    """The ``registry.push_call_wrapper``-shaped hook: wraps every
    transform invocation with call/error counters and a duration
    histogram.  Safe to install for a whole run (the ResilientRunner
    does) or a single ``with`` block.

    ``backend_override`` is the degraded-run label seam: while set
    (the owning ResilientRunner sets it to ``"degraded"`` for the
    lifetime of a degrade ruling), ops are labelled with it instead
    of the dispatch backend — so a post-mortem can split "tpu when
    healthy" from "cpu because we were ruled off the device".  It
    lives on the instrumentor, NOT the (possibly process-shared)
    registry: each run's degrade ruling scopes to that run's own
    hook, so concurrent runs cannot cross-contaminate labels."""

    def __init__(self, metrics: MetricsRegistry | None = None):
        self.metrics = metrics if metrics is not None else _DEFAULT
        self.backend_override: str | None = None

    def wrap(self, name: str, backend: str, fn):
        m = self.metrics

        def instrumented(data, *args, **kw):
            label = self.backend_override or backend
            t0 = m.clock.monotonic()
            try:
                out = fn(data, *args, **kw)
            except BaseException:
                m.counter("op.errors", op=name, backend=label).inc()
                raise
            finally:
                # counts + duration recorded for error attempts too —
                # a wedge that burned 60 s then raised is exactly the
                # duration a post-mortem needs.  Python scalars only:
                # `out` is never touched, so no device sync.
                m.counter("op.calls", op=name, backend=label).inc()
                m.histogram("op.duration_s", op=name, backend=label) \
                    .observe(m.clock.monotonic() - t0)
            return out

        return instrumented


@contextlib.contextmanager
def instrument_calls(metrics: MetricsRegistry | None = None):
    """Scoped auto-instrumentation of every transform call:

    >>> with telemetry.instrument_calls() as m:
    ...     pipeline.run(data, backend="tpu")
    >>> m.snapshot()["counters"]

    Yields the target :class:`MetricsRegistry` (the process default
    unless ``metrics=`` is given).  Composes with other call wrappers
    (chaos, deadlines) — most recently pushed runs outermost."""
    from .. import registry as _registry

    inst = CallInstrumentor(metrics)
    with _registry.call_wrapper(inst.wrap):
        yield inst.metrics
