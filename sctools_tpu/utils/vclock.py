"""Injectable wall-clock for the resilience stack.

Every module in the retry/deadline/breaker stack (``runner.py``,
``utils/failsafe.py``, ``utils/checkpoint.py``, ``utils/chaos.py``)
measures elapsed time and sleeps through a :class:`Clock` object
instead of calling ``time.sleep``/``time.monotonic`` directly — the
sctlint rule SCT008 (and the shell guard in ``tools/run_checks.sh``)
enforce that.  The single seam is what lets tier-1 tests drive
deadline overruns, circuit-breaker cooldowns, wedged-step chaos and
backoff schedules with ZERO real sleeps: hand every participant the
same :class:`VirtualClock` and time moves only when someone sleeps or
calls ``advance``.

``time.time()`` stays legal everywhere — journal/sidecar timestamps
are wall-clock *facts about when something happened*; only *schedules*
(how long to wait, whether a budget is spent) must be injectable.
"""

from __future__ import annotations

import time


class Clock:
    """The clock interface the resilience stack depends on:
    ``monotonic()`` for elapsed-time arithmetic (never wall time — it
    must survive NTP steps) and ``sleep(seconds)`` for waiting."""

    def monotonic(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class SystemClock(Clock):
    """The real clock — the only sanctioned call sites of
    ``time.monotonic``/``time.sleep`` in the resilience stack (SCT008
    exempts this module)."""

    def monotonic(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(max(0.0, seconds))


class VirtualClock(Clock):
    """Deterministic test clock: starts at ``start``, ``sleep``
    advances virtual time instantly (and records the request in
    ``.sleeps``), ``advance`` moves time without a sleeper.  Sharing
    one instance between a ResilientRunner, its ChaosMonkey and its
    CircuitBreaker is how a test wedges a step past its deadline or
    expires a breaker cooldown without waiting a real second."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self.sleeps: list[float] = []

    def monotonic(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        self.sleeps.append(float(seconds))
        self._now += max(0.0, float(seconds))

    def advance(self, seconds: float) -> None:
        self._now += max(0.0, float(seconds))


#: module-level default so every resilience module shares one instance
SYSTEM_CLOCK = SystemClock()
