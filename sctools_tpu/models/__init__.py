"""Deep probabilistic models.  Importing registers their transforms."""

from . import scvi  # noqa: F401
