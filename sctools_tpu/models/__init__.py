"""Deep probabilistic models.  Importing registers their transforms."""

from . import scvi  # noqa: F401
from . import train_stream  # noqa: F401
