"""Preemption-tolerant out-of-core scVI training on a durable shard
store — the workload rung the fault ladder never carried.

Every rung built so far (retry → breaker → degrade → quarantine →
requeue) protects runs that finish in seconds; *training* on a store
that never fits host RAM (the annbatch story, PAPERS.md) means
hours-long jobs where a crash, preemption or lost device mid-epoch is
a certainty.  This module marries ``data/shardstore.py`` to the scvi
trainer (``models/scvi.py``) into a crash-safe loop, in three layers:

**Device feed** — each epoch walks the store in a PERMUTED-BLOCK
shard order (:func:`epoch_shard_order`: blocks of consecutive shards
shuffled per epoch, ascending within a block) so training sees fresh
data order every epoch while the read scheduler's elevator heap still
serves the in-flight window in ascending shard order — epoch-level
randomness, coalesced disk reads.  Shards stream through the
double-buffered prefetch worker (``data/stream.py``
``_prefetch_iter``): native chunk decode + ``device_put`` + densify
of shard N+1 overlap the compiled train scan on shard N, accounted in
``train.overlap_s``/``train.stall_s``.  The per-shard program IS
``models/scvi.py`` ``_train_epoch`` — the identical minibatch update
math as the in-RAM path, which is what the loss-parity gate
(``bench.py --phase train``) rests on.

**Crash-safe cursor** — with ``checkpoint=``, optimizer state +
params + the training cursor (epoch, position in the epoch's permuted
order, global step, partial-epoch loss accumulators) are written
through the checkpoint integrity layer after every
``checkpoint_every`` shards (``utils/checkpoint.py``
``save_npz_generations``: content digest + schema + identity
fingerprint, atomic rename, previous generation rotated to
``.prev``).  Every RNG input is a PURE FUNCTION of (seed, epoch,
position/shard) — no sequential host RNG state survives only in
memory — so a SIGKILL at ANY minibatch resumes from the last shard
boundary and, in the deterministic regime, reaches params BITWISE
IDENTICAL to an uninterrupted run (tier-1 pins this).  A corrupt
training checkpoint is QUARANTINED (never deleted, reason sidecar)
and resume falls back one generation — never a silent epoch restart.
Argument mismatches stay ``ValueError``: a cursor for different
hyperparameters is WRONG, not corrupt.

**Cooperative preemption** — at every shard boundary the trainer
polls ``failsafe.check_preempt()`` (plus an optional explicit
``preempt=`` token).  A pending request — a high-priority serving run
borrowing the device through ``RunScheduler``, a
``RunHandle.cancel()``, or a chaos ``preempt`` fault — makes the
trainer SAVE ITS CURSOR FIRST and then raise
``failsafe.JobPreempted``: checkpoint-then-yield.  The scheduler
requeues the ticket with its cursor (reason ``"cancelled"`` terminals
it as shed instead); the next dispatch resumes from the cursor,
journaled ``train_resume`` — no replayed shards, provable from the
``train_shard`` (epoch, pos) pairs.  Device-failure rulings mid-epoch
(breaker-open, mesh-shrink, host_lost) compose for free: the runner
retries/degrades the training STEP, and the retried attempt re-enters
here and resumes from the same cursor file.

Journal events: ``train_resume`` → (``train_shard`` … ``train_epoch``
| ``train_checkpoint``)* → (``preempted`` | completion).  Metrics:
the ``train.*`` family (SCT009 vocabulary).  Every wait rides the
injectable clock; chaos preemption counts shard-boundary polls, so
the whole ladder is tier-1 testable with zero real sleeps.
"""

from __future__ import annotations

import hashlib
import os
import warnings

import jax
import numpy as np

from .. import memory as _memory
from ..data.shardstore import ShardStore
from ..data.stream import _prefetch_iter
from ..registry import register
from ..utils import telemetry
from ..utils.checkpoint import (clear_npz_generations,
                                load_npz_generations,
                                save_npz_generations)
from ..utils.failsafe import JobPreempted, check_preempt
from ..utils.vclock import SYSTEM_CLOCK
from .scvi import _make_tx, _train_epoch, init_params

#: identity fingerprint the cursor checkpoints carry (a foreign file
#: renamed onto the cursor path fails verification instead of
#: half-parsing); bump on incompatible cursor layout changes
_CURSOR_FP = "scvi-stream-v1"


def epoch_shard_order(n_shards: int, epoch: int, seed: int,
                      block: int = 4) -> np.ndarray:
    """The epoch's shard visit order: permuted at BLOCK granularity —
    blocks of ``block`` consecutive shards are shuffled, order within
    a block stays ascending.  Pure function of (seed, epoch), so a
    resumed epoch recomputes the identical order from its cursor
    alone.  Block permutation is the randomness/locality compromise:
    the trainer sees a fresh data order every epoch, while the read
    scheduler's lookahead window still holds near-consecutive shard
    indices that its elevator heap serves in ascending disk order."""
    if n_shards <= 0:
        return np.zeros(0, np.int64)
    block = max(1, int(block))
    rng = np.random.default_rng([int(seed) & 0x7FFFFFFF, int(epoch),
                                 0x5EED])
    n_blocks = -(-n_shards // block)
    out = []
    for b in rng.permutation(n_blocks):
        out.extend(range(b * block, min((b + 1) * block, n_shards)))
    return np.asarray(out, np.int64)


def _shard_perm(rows: int, take: int, seed: int, epoch: int,
                shard: int) -> np.ndarray:
    """Minibatch row sampling for one shard: a permutation of the
    shard's REAL rows, derived from (seed, epoch, shard) — pure
    function, so resume replays nothing and skips nothing."""
    rng = np.random.default_rng([int(seed) & 0x7FFFFFFF, int(epoch),
                                 int(shard), 0xBA7C4])
    return rng.permutation(rows)[:take].astype(np.int32)


def _as_journal(j):
    if j is None or hasattr(j, "write"):
        return j
    from ..runner import _Journal

    return _Journal(str(j))


def _state_template(n_genes: int, n_latent: int, n_hidden: int):
    """Params/opt-state pytrees with the run's exact structure (values
    irrelevant) — the treedefs cursor checkpoints unflatten into."""
    params = init_params(jax.random.PRNGKey(0), n_genes, 0,
                         n_latent, n_hidden)
    return params, _make_tx().init(params)


def _pack_state(params, opt_state) -> dict:
    out = {}
    for i, leaf in enumerate(jax.tree_util.tree_leaves(params)):
        out[f"p{i:03d}"] = np.asarray(leaf)
    for i, leaf in enumerate(jax.tree_util.tree_leaves(opt_state)):
        out[f"o{i:03d}"] = np.asarray(leaf)
    return out


def _unpack_state(z: dict, n_genes: int, n_latent: int,
                  n_hidden: int):
    pt, ot = _state_template(n_genes, n_latent, n_hidden)
    p_leaves = [z[f"p{i:03d}"] for i in range(
        len(jax.tree_util.tree_leaves(pt)))]
    o_leaves = [z[f"o{i:03d}"] for i in range(
        len(jax.tree_util.tree_leaves(ot)))]
    params = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(pt), p_leaves)
    opt_state = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(ot), o_leaves)
    return params, opt_state


class _Cursor:
    """The mutable training position one checkpoint freezes: epoch,
    position within the epoch's permuted shard order, global step,
    and the partial-epoch loss accumulators (so a mid-epoch resume
    reports the same epoch mean an uninterrupted run would)."""

    __slots__ = ("epoch", "pos", "step", "loss_sum", "loss_steps",
                 "history")

    def __init__(self):
        self.epoch = 0
        self.pos = 0
        self.step = 0
        self.loss_sum = 0.0
        self.loss_steps = 0
        self.history: list[float] = []

    def as_dict(self) -> dict:
        return {"epoch": self.epoch, "pos": self.pos,
                "step": self.step}


def fit_scvi_stream(store, *, n_latent: int = 10, n_hidden: int = 128,
                    epochs: int = 10, batch_size: int = 512,
                    seed: int = 0, kl_warmup: int = 10,
                    scheduler=None, checkpoint: str | None = None,
                    checkpoint_every: int = 1, order_block: int = 4,
                    prefetch: bool = True, prefetch_depth: int = 2,
                    encode: bool = False, preempt=None,
                    clock=None, metrics=None, journal=None,
                    mem_budget=None,
                    params_out: str | None = None) -> dict:
    """Train the NB-VAE (``models/scvi.py`` generative model, no
    batch covariate) out-of-core over a :class:`ShardStore` — the
    module docstring has the crash/preemption contract.

    Parameters
    ----------
    store : ShardStore | str
        The durable shard store (or its directory).  The full counts
        never materialise: at most ``prefetch_depth + 1`` decoded
        dense shards are in flight.
    scheduler : ShardReadScheduler | None
        Route every shard read through the IO-failure ladder
        (retry/hedge/quarantine, RAM budget); ``None`` reads the
        store directly (still verified).
    checkpoint : str | None
        Cursor checkpoint path → the run is RESUMABLE (and
        preemption keeps its progress).  ``None`` disables
        checkpointing — a preemption then restarts from scratch.
    checkpoint_every : int
        Cursor save cadence in shards (1 = every shard boundary, the
        SIGKILL-anywhere-bitwise-resume regime).
    order_block : int
        Shard-order permutation block (:func:`epoch_shard_order`).
    encode : bool
        After training, stream ONE more ascending pass encoding every
        cell → ``latent`` (n_cells, n_latent) in the result.
    preempt : failsafe.PreemptToken | None
        Explicit preemption signal; the thread-local scope installed
        by ``RunScheduler`` (``failsafe.check_preempt``) is always
        polled as well.
    journal
        ``runner._Journal``-shaped object or a path; receives the
        ``train_*``/``preempted`` events.
    mem_budget : memory.MemoryBudget | None
        Device-memory budget the feed window holds a NAMED
        reservation against for the training's lifetime
        (``prefetch_depth + 1`` decoded dense shards — the
        double-buffered device feed's live set), journaled
        ``mem_reserved``/``mem_released``.  Deliberately DYNAMIC, not
        standing: the hold is run-scoped (released when this call
        returns or yields), so it tightens dispatch-time fit rulings
        — beside the run's own admission reservation, conservatively
        — without shrinking ``admissible_bytes()`` and permanently
        shedding queued work that would fit the moment training ends
        (only service-lifetime residents like the serving model are
        standing).  ``None`` falls back to the thread's current
        budget (``memory.current_budget()`` — installed by a
        ``RunScheduler`` worker whose pool carries one), so a
        scheduler-admitted training job contends honestly with
        serving traffic without any parameter plumbing.

    params_out : str | None
        Persist the trained parameters as a digest-verified,
        generation-rotated ``scvi.save_model`` artifact at this path
        once training completes — BEFORE the cursor checkpoint is
        cleared, so a kill between the two resumes from a
        training-complete cursor and rewrites the identical artifact
        (the factory's build stage trusts this file, never an
        in-memory pytree that dies with the worker).  The content
        digest lands in the result as ``params_digest``.

    Returns ``{"params", "history", "epochs_run", "resumed_from",
    "latent"}`` (``latent`` only with ``encode=True``;
    ``params_digest`` only with ``params_out=``).
    """
    if scheduler is not None:
        want = os.path.realpath(store if isinstance(store, str)
                                else store.directory)
        if os.path.realpath(scheduler.store.directory) != want:
            raise ValueError("scheduler serves a different store")
        store = scheduler.store
        if scheduler.on_corrupt == "skip":
            # the same refusal as ShardStore.source(): a silently
            # skipped shard would shift every later position under
            # the cursor — wrong per-shard RNG/permutation, a journal
            # naming the wrong shards, and a checkpoint no resume
            # could trust.  Corruption must FAIL the step (the
            # runner's retry re-enters from the cursor).
            raise ValueError(
                "fit_scvi_stream: on_corrupt='skip' would silently "
                "shift shard positions under the training cursor; "
                "use on_corrupt='fail'")
    elif isinstance(store, str):
        store = ShardStore.open(store)
    clock = clock if clock is not None else SYSTEM_CLOCK
    m = metrics if metrics is not None else telemetry.default_registry()
    journal = _as_journal(journal)
    n_shards = store.n_shards
    n_genes = store.n_genes
    if n_shards == 0:
        raise ValueError("fit_scvi_stream: empty store")
    checkpoint_every = max(1, int(checkpoint_every))

    # ---- deterministic init (mirrors scvi._fit's key schedule, so
    # the streaming and in-RAM paths start from identical params)
    base = jax.random.PRNGKey(seed)
    key, ki = jax.random.split(base)
    tx = _make_tx()
    cur = _Cursor()
    params = opt_state = None
    resumed_from = None

    # ---- resume: verified cursor load, quarantine-fallback one
    # generation, argument mismatch = ValueError (wrong, not corrupt)
    z = (load_npz_generations(checkpoint, fingerprint=_CURSOR_FP)
         if checkpoint is not None else None)
    if z is not None:
        want = dict(n_cells=store.n_cells, n_genes=n_genes,
                    n_latent=n_latent, n_hidden=n_hidden,
                    batch_size=batch_size, seed=seed,
                    kl_warmup=kl_warmup, order_block=order_block)
        got = {k: int(z[k]) for k in want}
        if got != want:
            raise ValueError(
                f"fit_scvi_stream: checkpoint {checkpoint!r} was "
                f"written for different arguments ({got} != {want}); "
                f"delete it or pass a fresh path")
        if str(z["store_digest"]) != str(
                store.manifest.get("store_digest", "")):
            raise ValueError(
                f"fit_scvi_stream: checkpoint {checkpoint!r} belongs "
                f"to a different store (digest mismatch); delete it "
                f"or pass a fresh path")
        params, opt_state = _unpack_state(z, n_genes, n_latent,
                                          n_hidden)
        cur.epoch = int(z["epoch"])
        cur.pos = int(z["pos"])
        cur.step = int(z["step"])
        cur.loss_sum = float(z["loss_sum"])
        cur.loss_steps = int(z["loss_steps"])
        cur.history = [float(x) for x in z["history"]]
        resumed_from = cur.as_dict()
        m.counter("train.resumes").inc()
        if journal is not None:
            journal.write("train_resume", **cur.as_dict(),
                          checkpoint=checkpoint)

    last_saved = [None]

    def save_cursor() -> None:
        if checkpoint is None:
            return
        if last_saved[0] == (cur.epoch, cur.pos):
            # already persisted at this exact cursor (a preemption
            # right after a due save): a second write would rotate
            # the REAL previous generation out of .prev, silently
            # shortening the corrupt-checkpoint fallback to zero
            return
        last_saved[0] = (cur.epoch, cur.pos)
        save_npz_generations(
            checkpoint, fingerprint=_CURSOR_FP,
            n_cells=store.n_cells, n_genes=n_genes,
            n_latent=n_latent, n_hidden=n_hidden,
            batch_size=batch_size, seed=seed, kl_warmup=kl_warmup,
            order_block=order_block,
            store_digest=str(store.manifest.get("store_digest", "")),
            epoch=cur.epoch, pos=cur.pos, step=cur.step,
            loss_sum=np.float64(cur.loss_sum),
            loss_steps=cur.loss_steps,
            # float64: the history round-trips through every
            # preemption's checkpoint, and the loss-trajectory parity
            # proof compares it against an uninterrupted run
            history=np.asarray(cur.history, np.float64),
            **_pack_state(params, opt_state))
        m.counter("runner.checkpoint_writes").inc()
        if journal is not None:
            journal.write("train_checkpoint", **cur.as_dict())

    if params is None:
        params = init_params(ki, n_genes, 0, n_latent, n_hidden)
        opt_state = tx.init(params)
        # generation 0 is written BEFORE the first shard read: the
        # prefetch worker runs reads AHEAD of the (JIT-compiling)
        # first train step, so a SIGKILL early in the epoch can land
        # with several reads done but no shard boundary reached —
        # this save makes that window resume through the verified-
        # cursor path too, never a silent start-over
        save_cursor()
    else:
        # a fresh save at the resume cursor would rotate the REAL
        # previous generation out of .prev (identical content,
        # corrupt-checkpoint fallback shortened to zero) — the
        # loaded cursor counts as already persisted
        last_saved[0] = (cur.epoch, cur.pos)

    def poll_preempt() -> str | None:
        r = preempt.pending() if preempt is not None else None
        return r or check_preempt()

    def yield_now(reason: str) -> None:
        if checkpoint is None:
            warnings.warn(
                "fit_scvi_stream: preempted without a checkpoint= — "
                "progress is lost; the requeued run restarts from "
                "scratch", RuntimeWarning, stacklevel=3)
        else:
            save_cursor()
        m.counter("train.preemptions", reason=reason).inc()
        if journal is not None:
            journal.write("preempted", reason=reason,
                          **cur.as_dict())
        raise JobPreempted(
            f"training yielded at epoch {cur.epoch} pos {cur.pos} "
            f"({reason})", reason=reason, cursor=cur.as_dict())

    import jax.numpy as jnp

    def to_device_dense(sh):
        # runs IN the prefetch worker: H2D + densify of shard N+1
        # overlap the compiled train scan on shard N
        d = sh.device_put()
        return d.to_dense(), sh.n_cells

    stall_c = m.counter("train.stall_s")
    overlap_c = m.counter("train.overlap_s")

    # the device feed's live set — up to prefetch_depth+1 decoded
    # DENSE shards at once — holds a named DYNAMIC reservation
    # against the memory budget (explicit mem_budget=, or the
    # scheduler worker's thread-local budget_scope) for the
    # training's lifetime, so serving queries sharing the device
    # contend for what is actually left.  Dynamic on purpose: a
    # run-scoped hold must tighten dispatch fitting, not the
    # admission-feasibility floor (a STANDING hold would permanently
    # shed queued work that fits the moment training ends).  Released
    # on EVERY exit — completion, preemption yield, crash — by the
    # finally below.
    budget = (mem_budget if mem_budget is not None
              else _memory.current_budget())
    feed_name = f"train:feed:{id(cur)}"
    feed_bytes = 0
    feed_reserved = False
    try:
        if budget is not None:
            # INSIDE the try: a raising journal append right after
            # the reserve must still reach the release below, or the
            # phantom hold starves a shared pool's dispatch forever
            depth = prefetch_depth if prefetch else 0
            feed_bytes = (depth + 1) * store.shard_rows * n_genes * 4
            reserved = budget.reserve(feed_name, feed_bytes)
            feed_reserved = True
            if journal is not None:
                journal.write("mem_reserved", name=feed_name,
                              bytes=feed_bytes,
                              reserved_total=reserved)
        while cur.epoch < epochs:
            ep = cur.epoch
            order = epoch_shard_order(n_shards, ep, seed,
                                      block=order_block)
            klw = jnp.float32(min(1.0, (ep + 1) / max(kl_warmup, 1)))
            ke = jax.random.fold_in(key, ep)
            tail = [int(s) for s in order[cur.pos:]]

            def feed(tail=tail):
                if scheduler is not None:
                    yield from scheduler.iter_order(tail)
                else:
                    for si in tail:
                        yield store.read_shard(si)

            it = (_prefetch_iter(feed, depth=prefetch_depth,
                                 prepare=to_device_dense, clock=clock,
                                 metrics=m, stall_counter=stall_c,
                                 overlap_counter=overlap_c)
                  if prefetch else
                  (to_device_dense(sh) for sh in feed()))
            try:
                for Xd, rows in it:
                    shard = int(order[cur.pos])
                    bs = min(batch_size, rows)
                    n_steps = max(rows // bs, 1)
                    perm = jnp.asarray(_shard_perm(
                        rows, n_steps * bs, seed, ep, shard))
                    oh = jnp.zeros((Xd.shape[0], 0), jnp.float32)
                    ks = jax.random.fold_in(ke, cur.pos)
                    params, opt_state, loss = _train_epoch(
                        params, opt_state, Xd, oh, perm, ks, klw,
                        n_steps=n_steps, batch_size=bs)
                    # the fetch is the per-shard sync point: the
                    # journal and the cursor need host values anyway,
                    # and it makes the consumer wall real for the
                    # overlap accounting
                    loss_f = float(loss)
                    cur.loss_sum += loss_f * n_steps
                    cur.loss_steps += n_steps
                    cur.step += n_steps
                    cur.pos += 1
                    m.counter("train.steps").inc(n_steps)
                    m.counter("train.shards").inc()
                    # save BEFORE journaling the shard: a kill between
                    # the two leaves a journal gap, never a replayed
                    # shard — the (epoch, pos) uniqueness proof rests
                    # on this order AND on checkpoint_every=1; a
                    # coarser cadence trades it away (a kill between
                    # saves replays up to checkpoint_every-1 shards,
                    # honestly re-journaled as repeated pairs)
                    if (cur.pos % checkpoint_every == 0
                            or cur.pos >= len(order)):
                        save_cursor()
                    if journal is not None:
                        journal.write("train_shard", epoch=ep,
                                      pos=cur.pos - 1, shard=shard,
                                      loss=round(loss_f, 6),
                                      steps=n_steps)
                    r = poll_preempt()
                    if r is not None:
                        yield_now(r)
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()  # stop the prefetch worker + flush counters
            loss_ep = cur.loss_sum / max(cur.loss_steps, 1)
            cur.history.append(loss_ep)
            cur.epoch += 1
            cur.pos = 0
            cur.loss_sum = 0.0
            cur.loss_steps = 0
            m.counter("train.epochs").inc()
            m.gauge("train.loss", epoch=ep).set(loss_ep)
            save_cursor()
            if journal is not None:
                journal.write("train_epoch", epoch=ep,
                              loss=round(loss_ep, 6), step=cur.step)

        out = {"params": params, "history": np.asarray(cur.history,
                                                       np.float64),
               "epochs_run": cur.epoch, "resumed_from": resumed_from,
               "latent": None}
        if encode:
            from .scvi import _encode

            parts = []
            it = (scheduler.iter_shards() if scheduler is not None
                  else store.iter_shards())
            for sh in it:
                d = sh.device_put()
                oh = jnp.zeros((d.rows_padded, 0), jnp.float32)
                parts.append(np.asarray(
                    _encode(params, d.to_dense(), oh))[: sh.n_cells])
            out["latent"] = np.concatenate(parts, axis=0)
    finally:
        if budget is not None and feed_reserved:
            total = budget.release(feed_name)
            if journal is not None:
                journal.write("mem_released", name=feed_name,
                              bytes=feed_bytes, reserved_total=total)
    if params_out is not None:
        # persist BEFORE clearing the cursor: a kill between the two
        # resumes from a training-complete cursor and deterministically
        # rewrites the identical artifact
        from .scvi import save_model

        out["params_digest"] = save_model(
            params, params_out,
            meta={"epochs": cur.epoch, "seed": seed,
                  "n_latent": n_latent, "n_hidden": n_hidden})
    if checkpoint is not None:
        clear_npz_generations(checkpoint)  # done; cursor is stale
    return out


@register("model.scvi_stream", backend="tpu")
@register("model.scvi_stream", backend="cpu")
def scvi_stream(data, store_dir: str = "", n_latent: int = 10,
                n_hidden: int = 128, epochs: int = 10,
                batch_size: int = 512, seed: int = 0,
                kl_warmup: int = 10, checkpoint: str | None = None,
                checkpoint_every: int = 1, order_block: int = 4,
                encode: bool = False, journal: str | None = None,
                params_out: str | None = None):
    """Train scVI OUT-OF-CORE on the durable shard store at
    ``store_dir`` (see :func:`fit_scvi_stream` — permuted-block shard
    order, prefetched device feed, mid-epoch checkpointed resume,
    cooperative preemption).  The counts stream from disk, so
    ``data`` is a carrier, not the training set: results land in its
    uns — ``scvi_stream_elbo_history`` (negative ELBO per epoch),
    ``scvi_stream_epochs`` and, with ``encode=True``,
    ``scvi_stream_latent`` ((store n_cells, n_latent) posterior
    means).  ``checkpoint=``/``journal=``/``params_out=`` accept
    paths containing the ``{ticket_dir}`` placeholder under
    federation (the worker substitutes the per-ticket directory, so a
    REQUEUED training ticket resumes from the previous owner's
    cursor).  ``params_out=`` persists the trained parameters as a
    digest-verified ``scvi.save_model`` artifact — the durable
    hand-off the annotation factory's build stage loads (the pytree
    itself never crosses the worker boundary); its digest lands in
    uns as ``scvi_stream_params_digest``.  One
    registration serves both backends: the program is identical, only
    the device differs.  Submitted through ``RunScheduler`` with
    ``preemptible=True`` this is the long-running job the cooperative
    preemption contract exists for."""
    res = fit_scvi_stream(
        ShardStore.open(store_dir), n_latent=n_latent,
        n_hidden=n_hidden, epochs=epochs, batch_size=batch_size,
        seed=seed, kl_warmup=kl_warmup, checkpoint=checkpoint,
        checkpoint_every=checkpoint_every, order_block=order_block,
        encode=encode, journal=journal, params_out=params_out)
    uns = {"scvi_stream_elbo_history": res["history"],
           "scvi_stream_epochs": np.int64(res["epochs_run"])}
    if res["latent"] is not None:
        uns["scvi_stream_latent"] = res["latent"]
    if "params_digest" in res:
        uns["scvi_stream_params_digest"] = res["params_digest"]
    return data.with_uns(**uns)
