"""``model.scvi`` — a negative-binomial VAE for count matrices (the
scVI model family).

Capability parity: scVI (Lopez et al. 2018) is the de-facto deep
model for scRNA-seq — a VAE whose decoder parameterises a negative
binomial over raw counts with a per-gene dispersion and the cell's
library size as an offset, optionally conditioned on a batch
covariate.  The reference source was unavailable (/root/reference
empty — SURVEY.md §0); the published generative model is the
contract:

    z ~ N(0, I)                       (n_latent)
    rho = softmax(decoder(z, batch))  (gene expression fractions)
    x_g ~ NB(mean = l * rho_g, inverse-dispersion theta_g)

with l the cell's observed library size (scVI's fixed-l variant —
no latent library; it trains stably and keeps the ELBO exact).

TPU design: training IS the workload TPUs are built for — everything
is dense bf16-friendly matmuls.  One jitted update step consumes a
(B, G) count slab; an epoch is a ``lax.scan`` over the permuted
minibatch index array, so the whole epoch executes as ONE device
program (no per-step dispatch over the tunnel — the round-4 lesson).
Parameters are a plain pytree (no framework dependency); optax Adam;
reparameterised KL in closed form; NB log-likelihood via lgamma.

The same code is the CPU oracle (same program, cpu backend) — tests
assert the ELBO improves, the latent separates generative clusters,
and the decoded expression correlates with the truth.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..data.dataset import CellData
from ..data.sparse import SparseCells
from ..registry import register

#: identity fingerprint of the on-disk scvi/scanvi parameter artifact
#: (:func:`save_model`/:func:`load_model`) — a foreign npz renamed
#: onto a model path fails verification instead of half-parsing; bump
#: on incompatible layout changes
MODEL_FINGERPRINT = "scvi-model-v1"


def flatten_params(params, prefix: str = "param") -> dict:
    """Flatten an scvi/scanvi parameter pytree (nested dicts/lists of
    arrays) into ``{"<prefix>/enc/000/w": ndarray, ...}`` — the
    SELF-DESCRIBING key layout :func:`save_model` writes, shared with
    the serving artifact (``sctools_tpu/serving.py`` embeds trained
    params under ``scvi/...`` keys with the same encoding), so one
    on-disk convention covers every durable model file instead of
    ad-hoc param pickling."""
    out: dict = {}

    def rec(v, key):
        if isinstance(v, dict):
            for k in sorted(v):
                rec(v[k], f"{key}/{k}")
        elif isinstance(v, (list, tuple)):
            for i, x in enumerate(v):
                rec(x, f"{key}/{i:03d}")
        else:
            out[key] = np.asarray(v)

    rec(params, prefix)
    return out


def unflatten_params(arrays: dict, prefix: str = "param"):
    """Rebuild the parameter pytree :func:`flatten_params` encoded:
    all-numeric key segments become list indices, everything else
    dict keys; leaves come back as jax arrays ready for
    ``_train_epoch``/``_encode``."""
    root: dict = {}
    for key in arrays:
        if not key.startswith(prefix + "/"):
            continue
        parts = key[len(prefix) + 1:].split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arrays[key]

    def build(node):
        if not isinstance(node, dict):
            return jnp.asarray(node)
        keys = list(node)
        if keys and all(k.isdigit() for k in keys):
            return [build(node[k]) for k in sorted(keys, key=int)]
        return {k: build(node[k]) for k in sorted(keys)}

    if not root:
        raise ValueError(
            f"unflatten_params: no {prefix!r}-prefixed keys — not a "
            f"flatten_params() encoding")
    return build(root)


def save_model(params, path: str, *, meta: dict | None = None) -> str:
    """Write a trained scvi/scanvi parameter pytree as a verified,
    generation-rotated artifact: :func:`flatten_params` keys plus
    ``meta/<k>`` scalars, through
    ``checkpoint.save_npz_generations`` (content digest +
    :data:`MODEL_FINGERPRINT` identity, atomic rename, previous
    generation rotated to ``.prev``) — the SAME integrity/rollback
    conventions the streaming trainer's cursors and the serving
    artifacts ride.  Returns the content digest."""
    from ..utils.checkpoint import save_npz_generations

    arrays = flatten_params(params)
    for k, v in (meta or {}).items():
        arrays[f"meta/{k}"] = np.asarray(v)
    return save_npz_generations(path, fingerprint=MODEL_FINGERPRINT,
                                **arrays)


def load_model(path: str):
    """Verify-then-load a :func:`save_model` artifact: returns
    ``(params, meta)``.  Any damage — bit rot, truncation, a foreign
    file renamed onto the path — raises
    ``checkpoint.CheckpointCorruptError`` from the digest/fingerprint
    verify; callers that want the ``.prev``-generation fallback load
    through ``checkpoint.load_npz_generations`` semantics (the
    serving layer does, with quarantine + journal)."""
    from ..utils.checkpoint import load_npz_verified

    arrays = load_npz_verified(path,
                               expect_fingerprint=MODEL_FINGERPRINT,
                               require_digest=True)
    meta = {k[len("meta/"):]: arrays[k]
            for k in arrays if k.startswith("meta/")}
    return unflatten_params(arrays), meta


def _init_mlp(key, sizes):
    params = []
    for kin, kout in zip(sizes[:-1], sizes[1:]):
        key, k1 = jax.random.split(key)
        w = jax.random.normal(k1, (kin, kout)) * jnp.sqrt(2.0 / kin)
        params.append({"w": w, "b": jnp.zeros((kout,))})
    return params


def _mlp(params, x, final_linear=True):
    for i, lyr in enumerate(params):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(params) - 1 or not final_linear:
            x = jax.nn.relu(x)
    return x


def init_params(key, n_genes, n_batches, n_latent=10, n_hidden=128):
    ke, kd = jax.random.split(key)
    return {
        "enc": _init_mlp(ke, (n_genes + n_batches, n_hidden,
                              2 * n_latent)),
        "dec": _init_mlp(kd, (n_latent + n_batches, n_hidden, n_genes)),
        # per-gene inverse dispersion, initialised CONCENTRATED
        # (theta ~ 7): starting at theta=1 (very overdispersed) is a
        # training trap — with fuzzy reconstruction the ELBO prefers
        # lowering theta further over sharpening the means, and the
        # latent never learns structure (measured: theta collapsed to
        # ~0.4 and cluster ARI halved)
        "log_theta": jnp.full((n_genes,), 2.0),
    }


def _nb_logpmf(x, mean, theta):
    """Negative binomial log-pmf, mean/inverse-dispersion form."""
    eps = 1e-8
    log_theta_mu = jnp.log(theta + mean + eps)
    return (jax.lax.lgamma(x + theta)
            - jax.lax.lgamma(theta)
            - jax.lax.lgamma(x + 1.0)
            + theta * (jnp.log(theta + eps) - log_theta_mu)
            + x * (jnp.log(mean + eps) - log_theta_mu))


def _enc_input(x, batch_oh):
    """Encoder sees LIBRARY-NORMALISED log counts: with the fixed-l NB
    decoder the library is an observed offset, so feeding raw counts
    would make the encoder burn capacity re-deriving depth before it
    can represent cell state."""
    lib = jnp.sum(x, axis=1, keepdims=True)
    xn = jnp.log1p(x * (1e4 / jnp.maximum(lib, 1.0)))
    return jnp.concatenate([xn, batch_oh], axis=1)


def _enc_z(params, x, batch_oh, key):
    """Encoder half: sampled z + the posterior moments (the caller
    picks the prior — N(0,I) for scVI, class-conditional for scANVI)."""
    xin = _enc_input(x, batch_oh)
    h = _mlp(params["enc"], xin)
    mu, logvar = jnp.split(h, 2, axis=1)
    logvar = jnp.clip(logvar, -10.0, 10.0)
    z = mu + jnp.exp(0.5 * logvar) * jax.random.normal(key, mu.shape)
    return z, mu, logvar


def _kl_gauss(mu, logvar, prior_mu=0.0):
    """KL( N(mu, e^logvar) || N(prior_mu, I) ), per cell."""
    return 0.5 * jnp.sum(jnp.exp(logvar) + (mu - prior_mu) ** 2
                         - 1.0 - logvar, axis=1)


def _nb_ll(params, x, lib, dec_in):
    """NB log-likelihood of counts x given a decoder input row."""
    rho = jax.nn.softmax(_mlp(params["dec"], dec_in), axis=1)
    theta = jnp.exp(jnp.clip(params["log_theta"], -10.0, 10.0))
    return jnp.sum(_nb_logpmf(x, lib * rho, theta[None, :]), axis=1)


def _vae_terms(params, x, batch_oh, key):
    """Shared VAE body: per-cell (log-likelihood, KL, sampled z)."""
    lib = jnp.sum(x, axis=1, keepdims=True)
    z, mu, logvar = _enc_z(params, x, batch_oh, key)
    kl = _kl_gauss(mu, logvar)
    ll = _nb_ll(params, x, lib,
                jnp.concatenate([z, batch_oh], axis=1))
    return ll, kl, z


def elbo_fn(params, x, batch_oh, key, kl_weight=1.0):
    """Mean per-cell negative ELBO for a (B, G) count slab."""
    ll, kl, _ = _vae_terms(params, x, batch_oh, key)
    return -jnp.mean(ll - kl_weight * kl)


@partial(jax.jit, static_argnames=("n_steps", "batch_size"))
def _train_epoch(params, opt_state, Xd, batch_oh, perm, key, kl_weight,
                 *, n_steps: int, batch_size: int):
    """One epoch as a single compiled scan over minibatches.

    Also the out-of-core trainer's PER-SHARD program
    (``models/train_stream.py``): there ``Xd`` is one decoded store
    shard and ``perm`` samples its real rows, so the identical update
    math serves both the in-RAM and the streaming path — the loss-
    parity contract between them rests on this function being the
    single implementation.  Uniform shard shapes mean one compiled
    program serves every full shard."""
    tx = _make_tx()

    def step(carry, i):
        params, opt_state, key = carry
        key, ks = jax.random.split(key)
        rows = jax.lax.dynamic_slice_in_dim(perm, i * batch_size,
                                            batch_size)
        xb = jnp.take(Xd, rows, axis=0)
        bb = jnp.take(batch_oh, rows, axis=0)
        loss, grads = jax.value_and_grad(elbo_fn)(params, xb, bb, ks,
                                                  kl_weight)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state, key), loss

    (params, opt_state, key), losses = jax.lax.scan(
        step, (params, opt_state, key), jnp.arange(n_steps))
    return params, opt_state, jnp.mean(losses)


_LR = 1e-3


def _make_tx():
    return optax.adam(_LR)


def _make_epoch_sharded(mesh, Xd, batch_oh, extras=(), loss_call=None):
    """Build the COMPILED data-parallel epoch once (re-jitting per
    epoch cost minutes on the virtual mesh).

    **X is cells-axis SHARDED across the mesh** — the atlas-scale
    shape where no chip holds the full matrix.  Each device samples
    minibatch rows from ITS OWN shard (``perm`` carries local
    indices, batch-axis sharded), computes local gradients, and a
    ``pmean`` keeps the replicated params in lockstep — the standard
    DP recipe, expressed as ``shard_map`` so the same step compiles
    for any device count.

    ``extras`` are additional per-cell ``(n,)`` arrays sharded along
    cells (scANVI's labels and label mask); their minibatch gathers
    are passed to ``loss_call(params, xb, bb, *ebs, key, kl_weight)``,
    which defaults to the plain scVI ELBO."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[0]
    tx = _make_tx()
    if loss_call is None:
        loss_call = elbo_fn
    Xd = jax.device_put(Xd, NamedSharding(mesh, P(axis, None)))
    batch_oh = jax.device_put(batch_oh, NamedSharding(mesh, P(axis, None)))
    extras_d = tuple(
        jax.device_put(e, NamedSharding(mesh, P(axis))) for e in extras)
    n_extra = len(extras_d)

    def epoch(params, opt_state, X_local, oh_local, *rest):
        extra_locals = rest[:n_extra]
        perm_local, key, kl_weight = rest[n_extra:]

        def step(carry, inp):
            params, opt_state = carry
            step_i, rows = inp
            # key = f(epoch key, step index, device index): unique per
            # step AND device by construction — deriving it from
            # rows[0] collided whenever two steps sampled the same
            # first row, and across devices at n_local > 100003
            ks = jax.random.fold_in(
                jax.random.fold_in(key, step_i),
                jax.lax.axis_index(axis))
            xb = jnp.take(X_local, rows, axis=0)
            bb = jnp.take(oh_local, rows, axis=0)
            ebs = tuple(jnp.take(el, rows) for el in extra_locals)
            loss, grads = jax.value_and_grad(loss_call)(
                params, xb, bb, *ebs, ks, kl_weight)
            grads = jax.lax.pmean(grads, axis)
            loss = jax.lax.pmean(loss, axis)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state),
            (jnp.arange(perm_local.shape[0]), perm_local))
        return params, opt_state, jnp.mean(losses)

    fn = jax.jit(shard_map(
        epoch, mesh=mesh,
        in_specs=(P(), P(), P(axis, None), P(axis, None),
                  *([P(axis)] * n_extra), P(None, axis), P(), P()),
        out_specs=(P(), P(), P()),
        check_rep=False))

    def run(params, opt_state, perm, key, klw):
        return fn(params, opt_state, Xd, batch_oh, *extras_d,
                  perm, key, klw)

    run.x_sharded = Xd  # introspection hook for tests
    return run


@partial(jax.jit, static_argnames=())
def _encode(params, x, batch_oh):
    mu, _ = jnp.split(_mlp(params["enc"], _enc_input(x, batch_oh)),
                      2, axis=1)
    return mu


@partial(jax.jit, static_argnames=())
def _decode_rho(params, z, batch_oh):
    """Posterior-mean denoised expression fractions (scVI's
    get_normalized_expression)."""
    return jax.nn.softmax(
        _mlp(params["dec"], jnp.concatenate([z, batch_oh], axis=1)),
        axis=1)


def _batch_onehot(data: CellData, batch_key, n, opname):
    """(n, n_batches) one-hot of obs[batch_key]; (n, 0) when None."""
    if batch_key is None:
        return jnp.zeros((n, 0), jnp.float32)
    if batch_key not in data.obs:
        raise KeyError(f"{opname}: obs has no {batch_key!r}")
    levels, codes = np.unique(
        np.asarray(data.obs[batch_key])[:n], return_inverse=True)
    return jax.nn.one_hot(jnp.asarray(codes), len(levels))


def _counts_dense(data: CellData):
    """Raw counts as dense (n, G) — layers['counts'] if the pipeline
    snapshotted them, else X."""
    M = data.layers.get("counts", data.X)
    n = data.n_cells
    if isinstance(M, SparseCells):
        return M.to_dense()[:n]
    if hasattr(M, "toarray"):
        return jnp.asarray(M.toarray(), jnp.float32)
    return jnp.asarray(M, jnp.float32)[:n]


def _fit(data: CellData, n_latent, n_hidden, epochs, batch_size,
         batch_key, seed, kl_warmup, mesh=None):
    n = data.n_cells
    X = _counts_dense(data)
    batch_oh = _batch_onehot(data, batch_key, n, "model.scvi")
    key = jax.random.PRNGKey(seed)
    key, ki = jax.random.split(key)
    params = init_params(ki, data.n_genes, batch_oh.shape[1],
                         n_latent, n_hidden)
    tx = _make_tx()
    opt_state = tx.init(params)
    batch_size = min(batch_size, n)
    if mesh is not None:
        nd = mesh.devices.size
        batch_size = max(batch_size // nd, 1) * nd  # divisible shards
    n_steps = max(n // batch_size, 1)
    rng = np.random.default_rng(seed)
    history = []
    if mesh is not None:
        nd = mesh.devices.size
        n_local = -(-n // nd)
        # wrap-pad so every device's shard holds REAL cells (zero-pad
        # rows would be sampled as fake empty cells)
        pad_rows = np.arange(n_local * nd - n) % n
        Xp = jnp.concatenate([X, X[pad_rows]]) if len(pad_rows) else X
        ohp = (jnp.concatenate([batch_oh, batch_oh[pad_rows]])
               if len(pad_rows) else batch_oh)
        epoch_sharded = _make_epoch_sharded(mesh, Xp, ohp)
        b_local = batch_size // nd
    for ep in range(epochs):
        key, ke = jax.random.split(key)
        klw = jnp.float32(min(1.0, (ep + 1) / max(kl_warmup, 1)))
        if mesh is not None:
            # per-device LOCAL row indices, device blocks side by side
            perm2 = jnp.asarray(rng.integers(
                0, n_local, size=(n_steps, nd * b_local),
                dtype=np.int32))
            params, opt_state, loss = epoch_sharded(
                params, opt_state, perm2, ke, klw)
        else:
            perm = jnp.asarray(
                rng.permutation(n)[: n_steps * batch_size]
                .astype(np.int32))
            params, opt_state, loss = _train_epoch(
                params, opt_state, X, batch_oh, perm, ke, klw,
                n_steps=n_steps, batch_size=batch_size)
        history.append(float(loss))
    latent_d = _encode(params, X, batch_oh)
    latent = np.asarray(latent_d)
    theta = np.exp(np.clip(np.asarray(params["log_theta"]), -10, 10))
    return latent, theta, history, params, (latent_d, batch_oh)


@register("model.scvi", backend="tpu")
@register("model.scvi", backend="cpu")
def scvi(data: CellData, n_latent: int = 10, n_hidden: int = 128,
         epochs: int = 40, batch_size: int = 512,
         batch_key: str | None = None, seed: int = 0,
         kl_warmup: int = 10, n_devices: int | None = None,
         store_normalized: bool = False,
         save_model_path: str | None = None) -> CellData:
    """Train the NB-VAE and embed every cell.  Adds obsm["X_scvi"]
    (the posterior mean latent), var["scvi_dispersion"], and
    uns["scvi_elbo_history"] (negative ELBO per epoch — should
    decrease).  One registration serves both backends: the program is
    identical, only the device differs.  ``n_devices`` > 1 trains
    data-parallel over a 1-D mesh: X lives cells-axis SHARDED
    (``NamedSharding``), each device samples minibatches from its own
    shard, gradients pmean — no chip ever holds the full matrix
    during training (the final encode pass is currently unsharded).
    Run AFTER hvg subsetting (training densifies gene space) and
    BEFORE normalisation, or snapshot counts first
    (``util.snapshot_layer``).  ``save_model_path`` additionally
    writes the trained parameters as a verified on-disk artifact
    (:func:`save_model`: digest + fingerprint + ``.prev`` rotation) —
    the stable form the annotation service
    (``sctools_tpu/serving.py``) and downstream tooling reload with
    :func:`load_model`."""
    mesh = None
    if n_devices is not None and n_devices > 1:
        from ..parallel.mesh import make_mesh

        mesh = make_mesh(n_devices)
    latent, theta, history, params, (latent_d, batch_oh) = _fit(
        data, n_latent, n_hidden, epochs, batch_size, batch_key, seed,
        kl_warmup, mesh=mesh)
    if save_model_path:
        save_model(params, save_model_path,
                   meta=dict(n_genes=data.n_genes,
                             n_batches=batch_oh.shape[1],
                             n_latent=n_latent, n_hidden=n_hidden,
                             seed=seed))
    out = (data.with_obsm(X_scvi=latent)
           .with_var(scvi_dispersion=theta.astype(np.float32))
           .with_uns(scvi_elbo_history=np.asarray(history)))
    if store_normalized:
        # (n, G) dense — opt-in; scVI get_normalized_expression parity
        out = out.with_layers(scvi_normalized=np.asarray(
            _decode_rho(params, latent_d, batch_oh), np.float32))
    return out


# ----------------------------------------------------------------------
# model.scanvi — semi-supervised variant (classifier head on z)
# ----------------------------------------------------------------------


def _clf_logits(params, z):
    return _mlp(params["clf"], z)


def semi_elbo_fn(params, x, batch_oh, y, has_label, key,
                 kl_weight=1.0, alpha=50.0):
    """Classifier-head-only objective (``classifier_only=True``):
    negative ELBO + alpha-weighted cross-entropy on labelled cells.
    The decoder does NOT see y — kept as the cheap variant; the
    published y-conditioned generative model is
    :func:`semi_elbo_y_fn` (the default)."""
    ll, kl, z = _vae_terms(params, x, batch_oh, key)
    logits = _clf_logits(params, z)
    logp = jax.nn.log_softmax(logits, axis=1)
    ce = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
    ce = jnp.where(has_label, ce, 0.0)
    n_lab = jnp.maximum(jnp.sum(has_label), 1.0)
    return (-jnp.mean(ll - kl_weight * kl)
            + alpha * jnp.sum(ce) / n_lab)


def semi_elbo_y_fn(params, x, batch_oh, y, has_label, key,
                   kl_weight=1.0, alpha=50.0):
    """Published scANVI objective (Xu et al. 2021 / Kingma M2): the
    GENERATIVE model is conditioned on y — the decoder input carries
    the class one-hot AND the latent prior is class-conditional,
    p(z|y) = N(prior_mu[y], I) with learned anchors (the collapsed
    one-level form of scANVI's z1/z2 hierarchy).

    Labelled cells use their observed y; unlabelled cells MARGINALISE
    both the reconstruction and the z-KL over y under q(y|z) and add
    the entropy bonus H(q) (the M2 ``U(x)`` term), so the classifier
    is trained by the generative likelihood itself, not only by the
    alpha-weighted cross-entropy.  Cost: one decoder pass per class
    (vmapped over a C-row one-hot eye — C is small and static, so XLA
    sees one batched matmul, MXU-friendly)."""
    lib = jnp.sum(x, axis=1, keepdims=True)
    z, mu, logvar = _enc_z(params, x, batch_oh, key)
    logits = _clf_logits(params, z)
    logq = jax.nn.log_softmax(logits, axis=1)
    n_classes = logits.shape[1]

    def terms_for_class(c, c_oh):
        dec_in = jnp.concatenate(
            [z, jnp.broadcast_to(c_oh, (z.shape[0], n_classes)),
             batch_oh], axis=1)
        ll_c = _nb_ll(params, x, lib, dec_in)
        kl_c = _kl_gauss(mu, logvar, params["prior_mu"][c][None, :])
        return ll_c, kl_c

    ll_all, kl_all = jax.vmap(terms_for_class)(
        jnp.arange(n_classes), jnp.eye(n_classes))  # (C, B) each
    elbo_all = ll_all - kl_weight * kl_all
    elbo_obs = jnp.take_along_axis(elbo_all, y[None, :], axis=0)[0]
    q = jnp.exp(logq)
    elbo_marg = jnp.sum(q * elbo_all.T, axis=1)
    ent = -jnp.sum(q * logq, axis=1)
    per_cell = jnp.where(has_label > 0, -elbo_obs,
                         -(elbo_marg + ent))
    ce = -jnp.take_along_axis(logq, y[:, None], axis=1)[:, 0]
    ce = jnp.where(has_label > 0, ce, 0.0)
    n_lab = jnp.maximum(jnp.sum(has_label), 1.0)
    return jnp.mean(per_cell) + alpha * jnp.sum(ce) / n_lab


@register("model.scanvi", backend="tpu")
@register("model.scanvi", backend="cpu")
def scanvi(data: CellData, labels_key: str = "cell_type",
           unlabeled_category: str = "Unknown", n_latent: int = 10,
           n_hidden: int = 128, epochs: int = 40,
           batch_size: int = 512, batch_key: str | None = None,
           seed: int = 0, kl_warmup: int = 10,
           alpha: float = 50.0, classifier_only: bool = False,
           n_devices: int | None = None,
           store_normalized: bool = False) -> CellData:
    """Semi-supervised scVI: cells whose ``obs[labels_key]`` equals
    ``unlabeled_category`` (or "" / "nan") are unlabelled; everyone
    else supervises the classifier head.  Adds obsm["X_scanvi"],
    obs["scanvi_prediction"] (+ "_confidence"),
    uns["scanvi_elbo_history"], and (default model)
    uns["scanvi_class_profiles"] — the per-class decoded mean
    expression profile, the counterfactual readout the y-conditioned
    decoder exists for.

    By default this is the published scANVI generative model
    (:func:`semi_elbo_y_fn`: decoder conditioned on y, unlabelled
    cells marginalised over q(y|z)).  ``classifier_only=True`` keeps
    the round-4 cheap variant (classifier head only, decoder blind
    to y).  ``n_devices`` > 1 trains data-parallel over a 1-D mesh
    exactly like :func:`scvi` — X, y, and the label mask live
    cells-axis sharded, gradients pmean."""
    n = data.n_cells
    if labels_key not in data.obs:
        raise KeyError(f"model.scanvi: obs has no {labels_key!r}")
    raw = np.asarray(data.obs[labels_key]).astype(str)[:n]
    unl = (raw == str(unlabeled_category)) | (raw == "") | (raw == "nan")
    levels = np.unique(raw[~unl])
    if len(levels) < 2:
        raise ValueError("model.scanvi: need >=2 labelled categories")
    lut = {l: i for i, l in enumerate(levels)}
    y = np.array([lut.get(v, 0) for v in raw], np.int32)
    has_label = (~unl).astype(np.float32)

    X = _counts_dense(data)
    batch_oh = _batch_onehot(data, batch_key, n, "model.scanvi")
    key = jax.random.PRNGKey(seed)
    key, ki, kc, kd = jax.random.split(key, 4)
    params = init_params(ki, data.n_genes, batch_oh.shape[1],
                         n_latent, n_hidden)
    params["clf"] = _init_mlp(kc, (n_latent, n_hidden // 2,
                                   len(levels)))
    if not classifier_only:
        # published model: the decoder sees y — widen its input by the
        # class one-hot (fresh init; the y-less weights have no slot)
        # — and the latent prior is class-conditional with learned
        # anchors
        params["dec"] = _init_mlp(
            kd, (n_latent + len(levels) + batch_oh.shape[1],
                 n_hidden, data.n_genes))
        params["prior_mu"] = jnp.zeros((len(levels), n_latent))
    loss_fn = semi_elbo_fn if classifier_only else semi_elbo_y_fn
    tx = _make_tx()
    opt_state = tx.init(params)
    batch_size = min(batch_size, n)
    y_d = jnp.asarray(y)
    hl_d = jnp.asarray(has_label)

    mesh = None
    if n_devices is not None and n_devices > 1:
        from ..parallel.mesh import make_mesh

        mesh = make_mesh(n_devices)
        nd = mesh.devices.size
        batch_size = max(batch_size // nd, 1) * nd
    n_steps = max(n // batch_size, 1)
    if mesh is not None:
        # mirror _fit's DP layout: wrap-pad so every device's shard
        # holds REAL cells, then shard X/y/mask along the cell axis
        n_local = -(-n // nd)
        pad_rows = np.arange(n_local * nd - n) % n
        Xp = jnp.concatenate([X, X[pad_rows]]) if len(pad_rows) else X
        ohp = (jnp.concatenate([batch_oh, batch_oh[pad_rows]])
               if len(pad_rows) else batch_oh)
        yp = (jnp.concatenate([y_d, y_d[pad_rows]])
              if len(pad_rows) else y_d)
        hlp = (jnp.concatenate([hl_d, hl_d[pad_rows]])
               if len(pad_rows) else hl_d)
        epoch_sharded = _make_epoch_sharded(
            mesh, Xp, ohp, extras=(yp, hlp),
            loss_call=lambda p, xb, bb, yb, hlb, ks, klw:
                loss_fn(p, xb, bb, yb, hlb, ks, klw, alpha))
        b_local = batch_size // nd

    # arrays enter as jit ARGUMENTS (closing over the dense X would
    # bake it into the jaxpr as a constant — the large-constant
    # pathology _train_epoch avoids the same way)
    @partial(jax.jit, static_argnames=("n_steps", "batch_size"))
    def train_epoch(params, opt_state, Xd, oh, yv, hlv, perm, key, klw,
                    *, n_steps: int, batch_size: int):
        def step(carry, i):
            params, opt_state, key = carry
            key, ks = jax.random.split(key)
            rows = jax.lax.dynamic_slice_in_dim(perm, i * batch_size,
                                                batch_size)
            loss, grads = jax.value_and_grad(loss_fn)(
                params, jnp.take(Xd, rows, axis=0),
                jnp.take(oh, rows, axis=0),
                jnp.take(yv, rows), jnp.take(hlv, rows), ks, klw,
                alpha)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, key), loss

        (params, opt_state, key), losses = jax.lax.scan(
            step, (params, opt_state, key), jnp.arange(n_steps))
        return params, opt_state, jnp.mean(losses)

    rng = np.random.default_rng(seed)
    history = []
    for ep in range(epochs):
        key, ke = jax.random.split(key)
        klw = jnp.float32(min(1.0, (ep + 1) / max(kl_warmup, 1)))
        if mesh is not None:
            # per-device LOCAL row indices, device blocks side by side
            perm2 = jnp.asarray(rng.integers(
                0, n_local, size=(n_steps, nd * b_local),
                dtype=np.int32))
            params, opt_state, loss = epoch_sharded(
                params, opt_state, perm2, ke, klw)
        else:
            perm = jnp.asarray(
                rng.permutation(n)[: n_steps * batch_size]
                .astype(np.int32))
            params, opt_state, loss = train_epoch(
                params, opt_state, X, batch_oh, y_d, hl_d, perm, ke,
                klw, n_steps=n_steps, batch_size=batch_size)
        history.append(float(loss))
    Z = _encode(params, X, batch_oh)
    probs = np.asarray(jax.nn.softmax(_clf_logits(params, Z), axis=1))
    pred_idx = probs.argmax(axis=1)
    uns = {"scanvi_elbo_history": np.asarray(history)}
    if not classifier_only:
        # class-archetype readout: decode each class's learned latent
        # anchor under its own label (conditioning enters through BOTH
        # prior_mu[y] and the decoder's y one-hot), at the dataset's
        # mean batch composition — the counterfactual profile the
        # y-conditioned generative model exists for (pinned by a test)
        C = len(levels)
        bmean = jnp.asarray(batch_oh).mean(axis=0, keepdims=True)
        dec_in = jnp.concatenate(
            [params["prior_mu"], jnp.eye(C),
             jnp.broadcast_to(bmean, (C, bmean.shape[1]))], axis=1)
        rho = jax.nn.softmax(_mlp(params["dec"], dec_in), axis=1)
        uns["scanvi_class_profiles"] = np.asarray(rho)
    layers = {}
    if store_normalized:
        # scvi-tools get_normalized_expression parity: decode each
        # cell's z under its OBSERVED label (predicted where
        # unlabelled); the classifier-only decoder has no y input
        y_use = jnp.asarray(np.where(has_label > 0, y, pred_idx))
        if classifier_only:
            dec_in = jnp.concatenate([Z, jnp.asarray(batch_oh)], axis=1)
        else:
            dec_in = jnp.concatenate(
                [Z, jax.nn.one_hot(y_use, len(levels)),
                 jnp.asarray(batch_oh)], axis=1)
        layers["scanvi_normalized"] = np.asarray(
            jax.nn.softmax(_mlp(params["dec"], dec_in), axis=1))
    out = (data.with_obsm(X_scanvi=np.asarray(Z))
           .with_obs(scanvi_prediction=levels[pred_idx],
                     scanvi_confidence=probs[
                         np.arange(n), pred_idx].astype(np.float32))
           .with_uns(**uns))
    if layers:
        out = out.with_layers(**layers)
    return out
