"""``sct.pl`` — the scanpy-style plotting namespace.

Plotting is host-side by nature: every function fetches the (small)
arrays it needs from the ``CellData`` container (device or host
residency both work — ``obs_vector``/``np.asarray`` handle the fetch)
and draws with matplotlib.  Nothing here dispatches device programs;
the TPU work happened upstream in the ops that produced the
embeddings/scores being drawn.

API shape follows scanpy's ``sc.pl`` (a reference user should find the
canonical names): ``pl.umap(adata, color="leiden")``,
``pl.violin(adata, ["n_genes"], groupby="leiden")``,
``pl.dotplot(adata, markers, groupby="leiden")``,
``pl.rank_genes_groups(adata)``, ``pl.paga(adata)``,
``pl.velocity(adata, genes)`` (phase portraits), …  Every function
returns the matplotlib ``Axes`` and accepts ``ax=``, ``save=`` (write
the figure to a path — bare names land in ``settings.figdir`` at
``settings.dpi_save``; ``save=True`` derives the scanpy-style name —
closing self-created figures so batch loops don't accumulate) and
``show=`` (kept for scanpy call-site compatibility).  The exceptions
are ``rank_genes_groups`` and ``velocity``, which draw multi-panel
figures and return the 2-D axes array (no ``ax=``).
"""

from __future__ import annotations

from functools import partial

import numpy as np


def _plt():
    import os

    import matplotlib

    # Backend init is lazy in modern matplotlib, so "import pyplot"
    # succeeds even where figure creation would later TclError: switch
    # to Agg up front when an interactive backend is configured but no
    # display exists (Linux: DISPLAY/WAYLAND_DISPLAY).
    headless = not (os.environ.get("DISPLAY")
                    or os.environ.get("WAYLAND_DISPLAY"))
    if headless and matplotlib.get_backend().lower() not in (
            "agg", "pdf", "svg", "ps", "cairo", "template"):
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


# Integer columns are treated as categorical only when they look like
# cluster labels (a handful of levels — tab20-sized); count-like
# metrics (n_genes, ...) must render as a colormap, not a legend.
_CAT_MAX_INT = 20


def _is_categorical(v: np.ndarray) -> bool:
    if v.dtype.kind in ("U", "S", "O", "b"):
        return True
    if v.dtype.kind in ("i", "u"):
        return len(np.unique(v)) <= _CAT_MAX_INT
    return False


def _resolve_color(data, key):
    """obs column or gene name -> (values, is_categorical)."""
    v = np.asarray(data.obs_vector(key))
    return v, _is_categorical(v)


def _basis_key(data, basis: str) -> str:
    key = basis if basis.startswith("X_") else f"X_{basis}"
    if key not in data.obsm:
        raise KeyError(
            f"pl: obsm has no {key!r} — run the matching embedding op "
            f"first (available: {sorted(data.obsm)})")
    return key


def _cat_palette(plt, n):
    base = plt.get_cmap("tab20").colors
    if n <= 20:
        return [base[i] for i in range(n)]
    return [plt.get_cmap("hsv")(i / n) for i in range(n)]


def _finish(fig, ax, save, show, created=False, kind="plot"):
    if save:
        import os

        from .settings import settings

        if save is True:
            # scanpy's bool form derives the filename from the plot
            # kind; callers pass their own name explicitly (a frame
            # inspection here breaks under any wrapper/decorator)
            save = f"{kind}.{settings.file_format_figs}"
        path = str(save)
        if not os.path.dirname(path):  # bare name -> settings.figdir
            os.makedirs(settings.figdir, exist_ok=True)
            path = os.path.join(settings.figdir, path)
        fig.savefig(path, bbox_inches="tight", dpi=settings.dpi_save)
        if created:  # saved batch plots must not accumulate in pyplot's
            import matplotlib.pyplot as plt  # global figure registry

            plt.close(fig)
    return ax


def _std_scale(means: np.ndarray, standard_scale):
    """scanpy's standard_scale: None, 'var' (per column) or 'group'
    (per row), each min-max scaled over the other axis."""
    if standard_scale is None:
        return means
    if standard_scale == "var":
        rng = means.max(axis=0) - means.min(axis=0)
        return (means - means.min(axis=0)) / np.where(rng > 0, rng, 1)
    if standard_scale == "group":
        rng = (means.max(axis=1) - means.min(axis=1))[:, None]
        return ((means - means.min(axis=1)[:, None])
                / np.where(rng > 0, rng, 1))
    raise ValueError(
        f"standard_scale={standard_scale!r}: use None, 'var' or "
        f"'group'")


def embedding(data, basis: str = "X_umap", *, color=None, ax=None,
              size=None, cmap: str = "viridis", title=None,
              legend_loc: str = "right margin", alpha: float = 0.9,
              components=(0, 1), save=None, show=None):
    """Scatter an obsm embedding, optionally colored by an obs column
    or a gene (scanpy ``pl.embedding``).  Categorical colors get a
    legend; continuous a colorbar."""
    plt = _plt()
    E = np.asarray(data.obsm[_basis_key(data, basis)])[: data.n_cells]
    x, y = E[:, components[0]], E[:, components[1]]
    created = ax is None
    if created:
        fig, ax = plt.subplots(figsize=(4.2, 4.0))
    else:
        fig = ax.figure
    if size is None:
        size = max(120000 / max(len(x), 1), 0.5)
    if color is None:
        ax.scatter(x, y, s=size, c="tab:blue", alpha=alpha,
                   linewidths=0)
    else:
        v, cat = _resolve_color(data, color)
        if cat:
            levels = np.unique(v)
            pal = _cat_palette(plt, len(levels))
            for li, lev in enumerate(levels):
                m = v == lev
                ax.scatter(x[m], y[m], s=size, color=pal[li],
                           alpha=alpha, linewidths=0, label=str(lev))
            if legend_loc == "on data":
                for li, lev in enumerate(levels):
                    m = v == lev
                    ax.text(x[m].mean(), y[m].mean(), str(lev),
                            ha="center", va="center", fontsize=8,
                            weight="bold")
            elif legend_loc:
                ax.legend(loc="center left", bbox_to_anchor=(1.0, 0.5),
                          frameon=False, markerscale=3, fontsize=8)
        else:
            sc = ax.scatter(x, y, s=size, c=v, cmap=cmap, alpha=alpha,
                            linewidths=0)
            fig.colorbar(sc, ax=ax, shrink=0.7)
    name = basis.removeprefix("X_")
    ax.set_xlabel(f"{name}{components[0] + 1}")
    ax.set_ylabel(f"{name}{components[1] + 1}")
    ax.set_title(title if title is not None else (color or name))
    ax.set_xticks([])
    ax.set_yticks([])
    if save is True:
        # scanpy's bool form names the file after the basis (pl.umap
        # -> umap.pdf); the generic frame-name fallback in _finish
        # would say "embedding" for every aliased basis
        from .settings import settings

        save = f"{name}.{settings.file_format_figs}"
    return _finish(fig, ax, save, show, created, kind="embedding")


umap = partial(embedding, basis="X_umap")
tsne = partial(embedding, basis="X_tsne")
pca = partial(embedding, basis="X_pca")
diffmap = partial(embedding, basis="X_diffmap")
draw_graph = partial(embedding, basis="X_draw_graph")
phate = partial(embedding, basis="X_phate")


def scatter(data, x: str, y: str, *, color=None, ax=None, save=None,
            show=None):
    """Scatter two obs columns / genes against each other
    (scanpy ``pl.scatter``)."""
    plt = _plt()
    xv = np.asarray(data.obs_vector(x), float)
    yv = np.asarray(data.obs_vector(y), float)
    created = ax is None
    if created:
        fig, ax = plt.subplots(figsize=(4.0, 3.6))
    else:
        fig = ax.figure
    if color is None:
        ax.scatter(xv, yv, s=8, alpha=0.7, linewidths=0)
    else:
        v, cat = _resolve_color(data, color)
        if cat:
            levels = np.unique(v)
            pal = _cat_palette(plt, len(levels))
            for li, lev in enumerate(levels):
                m = v == lev
                ax.scatter(xv[m], yv[m], s=8, color=pal[li], alpha=0.7,
                           linewidths=0, label=str(lev))
            ax.legend(frameon=False, fontsize=8)
        else:
            sc = ax.scatter(xv, yv, s=8, c=v, alpha=0.7, linewidths=0)
            fig.colorbar(sc, ax=ax, shrink=0.7)
    ax.set_xlabel(x)
    ax.set_ylabel(y)
    return _finish(fig, ax, save, show, created, kind="scatter")


def violin(data, keys, *, groupby: str | None = None, log: bool = False,
           ax=None, save=None, show=None, rotation: float = 0.0):
    """Violin plot of obs columns / genes, optionally split by a
    categorical obs column (scanpy ``pl.violin``)."""
    plt = _plt()
    created = ax is None
    if isinstance(keys, str):
        keys = [keys]
    if groupby is None:
        if created:
            fig, ax = plt.subplots(figsize=(0.9 * len(keys) + 1.6, 3.2))
        else:
            fig = ax.figure
        vals = [np.asarray(data.obs_vector(k), float) for k in keys]
        ax.violinplot(vals, showmedians=True, widths=0.8)
        ax.set_xticks(np.arange(1, len(keys) + 1), keys,
                      rotation=rotation)
    else:
        if len(keys) != 1:
            raise ValueError(
                "pl.violin: pass exactly one key with groupby= "
                "(scanpy semantics)")
        g = np.asarray(data.obs_vector(groupby))
        levels = np.unique(g)
        v = np.asarray(data.obs_vector(keys[0]), float)
        if created:
            fig, ax = plt.subplots(
                figsize=(0.6 * len(levels) + 1.6, 3.2))
        else:
            fig = ax.figure
        ax.violinplot([v[g == lev] for lev in levels], showmedians=True,
                      widths=0.8)
        ax.set_xticks(np.arange(1, len(levels) + 1),
                      [str(lev) for lev in levels], rotation=rotation)
        ax.set_xlabel(groupby)
        ax.set_ylabel(keys[0])
    if log:
        ax.set_yscale("log")
    return _finish(fig, ax, save, show, created, kind="violin")


def highest_expr_genes(data, n_top: int = 30, *, ax=None, save=None,
                       show=None):
    """Boxplot of the genes with the highest mean fraction of total
    counts per cell (scanpy ``pl.highest_expr_genes``)."""
    plt = _plt()
    host = data.to_host()
    X = host.X
    import scipy.sparse as sp

    M = X.tocsr() if sp.issparse(X) else sp.csr_matrix(np.asarray(X))
    M = M[: host.n_cells]
    totals = np.maximum(np.asarray(M.sum(axis=1)).ravel(), 1e-12)
    frac = sp.diags(1.0 / totals) @ M
    mean_frac = np.asarray(frac.mean(axis=0)).ravel()
    top = np.argsort(-mean_frac)[:n_top]
    names = (np.asarray(host.var["gene_name"]).astype(str)
             if "gene_name" in host.var
             else np.array([str(i) for i in range(host.n_genes)]))
    created = ax is None
    if created:
        fig, ax = plt.subplots(figsize=(4.0, 0.22 * n_top + 1.2))
    else:
        fig = ax.figure
    cols = [np.asarray(frac[:, j].todense()).ravel() * 100 for j in top]
    ax.boxplot(cols[::-1], orientation="horizontal", showfliers=False,
               tick_labels=list(names[top])[::-1])
    ax.set_xlabel("% of total counts")
    return _finish(fig, ax, save, show, created, kind="highest_expr_genes")


def _grouped_stats(data, var_names, groupby):
    """(group levels, mean expression (G, V), fraction expressing)."""
    g = np.asarray(data.obs_vector(groupby))
    levels = np.unique(g)
    vals = np.stack([np.asarray(data.obs_vector(v), float)
                     for v in var_names], axis=1)  # (n, V)
    means = np.stack([vals[g == lev].mean(axis=0) for lev in levels])
    fracs = np.stack([(vals[g == lev] > 0).mean(axis=0)
                      for lev in levels])
    return levels, means, fracs


def dotplot(data, var_names, groupby: str, *, standard_scale=None,
            cmap: str = "Reds", ax=None, save=None, show=None):
    """Mean expression (color) x fraction-expressing (dot size) per
    group (scanpy ``pl.dotplot``)."""
    plt = _plt()
    if isinstance(var_names, str):
        var_names = [var_names]
    levels, means, fracs = _grouped_stats(data, var_names, groupby)
    means = _std_scale(means, standard_scale)
    G, V = means.shape
    created = ax is None
    if created:
        fig, ax = plt.subplots(
            figsize=(0.45 * V + 2.0, 0.45 * G + 1.2))
    else:
        fig = ax.figure
    xx, yy = np.meshgrid(np.arange(V), np.arange(G))
    sc = ax.scatter(xx.ravel(), yy.ravel(), s=12 + 260 * fracs.ravel(),
                    c=means.ravel(), cmap=cmap, edgecolors="0.6",
                    linewidths=0.4)
    ax.set_xticks(np.arange(V), list(var_names), rotation=90)
    ax.set_yticks(np.arange(G), [str(lev) for lev in levels])
    ax.set_xlim(-0.7, V - 0.3)
    ax.set_ylim(G - 0.3, -0.7)
    ax.set_ylabel(groupby)
    fig.colorbar(sc, ax=ax, shrink=0.6, label="mean expression")
    return _finish(fig, ax, save, show, created, kind="dotplot")


def matrixplot(data, var_names, groupby: str, *, cmap: str = "viridis",
               standard_scale=None, ax=None, save=None, show=None):
    """Heatmap of per-group mean expression (scanpy ``pl.matrixplot``)."""
    plt = _plt()
    if isinstance(var_names, str):
        var_names = [var_names]
    levels, means, _ = _grouped_stats(data, var_names, groupby)
    means = _std_scale(means, standard_scale)
    G, V = means.shape
    created = ax is None
    if created:
        fig, ax = plt.subplots(
            figsize=(0.45 * V + 2.0, 0.45 * G + 1.2))
    else:
        fig = ax.figure
    im = ax.imshow(means, cmap=cmap, aspect="auto")
    ax.set_xticks(np.arange(V), list(var_names), rotation=90)
    ax.set_yticks(np.arange(G), [str(lev) for lev in levels])
    ax.set_ylabel(groupby)
    ax.figure.colorbar(im, ax=ax, shrink=0.6, label="mean expression")
    return _finish(fig, ax, save, show, created, kind="matrixplot")


def heatmap(data, var_names, groupby: str, *, cmap: str = "viridis",
            ax=None, save=None, show=None):
    """Per-cell expression heatmap with cells ordered by group
    (scanpy ``pl.heatmap``)."""
    plt = _plt()
    if isinstance(var_names, str):
        var_names = [var_names]
    g = np.asarray(data.obs_vector(groupby))
    order = np.argsort(g, kind="stable")
    vals = np.stack([np.asarray(data.obs_vector(v), float)
                     for v in var_names], axis=1)[order]
    created = ax is None
    if created:
        fig, ax = plt.subplots(
            figsize=(0.45 * len(var_names) + 2.0, 4.0))
    else:
        fig = ax.figure
    im = ax.imshow(vals, cmap=cmap, aspect="auto",
                   interpolation="nearest")
    ax.set_xticks(np.arange(len(var_names)), list(var_names),
                  rotation=90)
    for b in np.flatnonzero(g[order][1:] != g[order][:-1]):
        ax.axhline(b + 0.5, color="w", lw=0.8)
    ax.set_ylabel(f"cells (grouped by {groupby})")
    ax.set_yticks([])
    fig.colorbar(im, ax=ax, shrink=0.6)
    return _finish(fig, ax, save, show, created, kind="heatmap")


def rank_genes_groups(data, *, n_genes: int = 20,
                      key: str = "rank_genes_groups", ncols: int = 4,
                      save=None, show=None):
    """Per-group top-gene score panels (scanpy
    ``pl.rank_genes_groups``)."""
    plt = _plt()
    if key not in data.uns:
        raise KeyError(f"pl.rank_genes_groups: uns has no {key!r} — "
                       "run de.rank_genes_groups first")
    res = data.uns[key]
    groups = list(res["groups"])
    names = np.asarray(res["names"])
    scores = np.asarray(res["scores"], float)
    ncols = min(ncols, len(groups))
    nrows = -(-len(groups) // ncols)
    fig, axes = plt.subplots(nrows, ncols, squeeze=False,
                             figsize=(2.6 * ncols, 2.4 * nrows),
                             sharey=False)
    ymin = scores[:, :n_genes].min()
    ymax = scores[:, :n_genes].max()
    for gi, grp in enumerate(groups):
        ax = axes[gi // ncols][gi % ncols]
        s = scores[gi, :n_genes]
        ax.set_title(str(grp), fontsize=9)
        for r in range(len(s)):
            ax.text(r, s[r], str(names[gi, r]), rotation=90,
                    va="bottom", ha="center", fontsize=7)
        ax.set_xlim(-1, n_genes)
        ax.set_ylim(ymin, ymax + 0.25 * (ymax - ymin + 1e-12))
        if gi % ncols == 0:
            ax.set_ylabel("score")
    for gi in range(len(groups), nrows * ncols):
        axes[gi // ncols][gi % ncols].axis("off")
    fig.tight_layout()
    if save:
        fig.savefig(save, bbox_inches="tight", dpi=150)
    return axes


def paga(data, *, threshold: float = 0.01, basis: str | None = None,
         groups: str | None = None, node_scale: float = 900.0,
         ax=None, save=None, show=None):
    """Cluster-abstraction graph: nodes at group centroids (of
    ``basis``, default the first available embedding), edge width
    proportional to PAGA connectivity (scanpy ``pl.paga``)."""
    plt = _plt()
    if "paga_connectivities" not in data.uns:
        raise KeyError("pl.paga: run graph.paga first")
    theta = np.asarray(data.uns["paga_connectivities"], float)
    levels = np.asarray(data.uns["paga_groups"])
    if groups is None:
        # graph.paga stores the column it ran over; the level-matching
        # scan is only a fallback for pre-r5 results and can pick the
        # wrong column when two clusterings share level names
        groups = data.uns.get("paga_groups_key")
    if groups is None:
        groups = next((k for k in data.obs
                       if np.array_equal(
                           np.unique(np.asarray(data.obs[k])[
                               : data.n_cells]), levels)), None)
    if basis is None:
        for cand in ("X_umap", "X_draw_graph", "X_tsne", "X_phate",
                     "X_pca"):
            if cand in data.obsm:
                basis = cand
                break
    if groups is not None and basis is not None:
        E = np.asarray(data.obsm[_basis_key(data, basis)])[
            : data.n_cells, :2]
        g = np.asarray(data.obs[groups])[: data.n_cells]
        pos = np.stack([E[g == lev].mean(axis=0) for lev in levels])
    else:  # circular layout fallback
        ang = 2 * np.pi * np.arange(len(levels)) / len(levels)
        pos = np.stack([np.cos(ang), np.sin(ang)], axis=1)
    created = ax is None
    if created:
        fig, ax = plt.subplots(figsize=(4.0, 4.0))
    else:
        fig = ax.figure
    wmax = theta.max() or 1.0
    for i in range(len(levels)):
        for j in range(i + 1, len(levels)):
            if theta[i, j] >= threshold:
                ax.plot(*zip(pos[i], pos[j]), color="0.5",
                        lw=0.5 + 4.0 * theta[i, j] / wmax, zorder=1)
    sizes = np.array([(np.asarray(data.obs[groups])[: data.n_cells]
                       == lev).mean() if groups else 1 / len(levels)
                      for lev in levels])
    ax.scatter(pos[:, 0], pos[:, 1], s=100 + node_scale * sizes,
               c=_cat_palette(plt, len(levels)), zorder=2,
               edgecolors="k", linewidths=0.5)
    for i, lev in enumerate(levels):
        ax.text(pos[i, 0], pos[i, 1], str(lev), ha="center",
                va="center", fontsize=8, zorder=3)
    ax.set_xticks([])
    ax.set_yticks([])
    ax.set_title("PAGA")
    return _finish(fig, ax, save, show, created, kind="paga")


def embedding_density(data, basis: str = "X_umap", *, key: str | None =
                      None, ax=None, save=None, show=None):
    """Embedding colored by the ``embed.density`` KDE (scanpy
    ``pl.embedding_density``)."""
    name = basis.removeprefix("X_")
    key = key or f"{name}_density"
    if key not in data.obs:
        raise KeyError(f"pl.embedding_density: obs has no {key!r} — "
                       "run embed.density first")
    return embedding(data, basis, color=key, cmap="YlOrRd", ax=ax,
                     save=save, show=show, title=key)


def dendrogram(data, groupby: str, *, ax=None, save=None, show=None):
    """The stored ``cluster.dendrogram`` linkage as a tree (scanpy
    ``pl.dendrogram``)."""
    plt = _plt()
    key = f"dendrogram_{groupby}"
    if key not in data.uns:
        raise KeyError(f"pl.dendrogram: uns has no {key!r} — run "
                       "cluster.dendrogram first")
    from scipy.cluster import hierarchy

    d = data.uns[key]
    created = ax is None
    if created:
        fig, ax = plt.subplots(figsize=(4.0, 3.0))
    else:
        fig = ax.figure
    cats = d.get("categories")
    if cats is None:
        # levels in original order: invert categories_ordered by idx
        order = np.asarray(d["categories_idx_ordered"])
        cats = np.empty(len(order), object)
        cats[order] = d["categories_ordered"]
    hierarchy.dendrogram(np.asarray(d["linkage"], float),
                         labels=list(map(str, cats)), ax=ax,
                         color_threshold=0)
    ax.set_ylabel("distance")
    return _finish(fig, ax, save, show, created, kind="dendrogram")


def velocity_embedding(data, basis: str = "umap", *, scale: float = 1.0,
                       color=None, ax=None, save=None, show=None):
    """Per-cell velocity arrows over an embedding (scVelo
    ``pl.velocity_embedding``); requires ``velocity.embedding``."""
    plt = _plt()
    name = basis.removeprefix("X_")
    vcol = f"velocity_{name}"
    if vcol not in data.obsm:
        raise KeyError(f"pl.velocity_embedding: obsm has no {vcol!r} — "
                       "run velocity.embedding first")
    ax = embedding(data, f"X_{name}", color=color, ax=ax, alpha=0.35)
    E = np.asarray(data.obsm[f"X_{name}"])[: data.n_cells, :2]
    V = np.asarray(data.obsm[vcol])[: data.n_cells, :2]
    ax.quiver(E[:, 0], E[:, 1], V[:, 0], V[:, 1], angles="xy",
              scale_units="xy", scale=1.0 / max(scale, 1e-12),
              width=0.002, color="k", alpha=0.7)
    return _finish(ax.figure, ax, save, show, kind="velocity_embedding")


def velocity(data, var_names, *, ncols: int = 4, color: str | None = None,
             save=None, show=None):
    """Per-gene (spliced, unspliced) phase portraits (scVelo
    ``pl.velocity``): Ms-vs-Mu scatter, the steady-state line from
    ``var['velocity_gamma']``, and — when ``velocity.recover_dynamics``
    has run — the fitted dynamical trajectory (drawn from the stored
    fit_* parameters through the same closed form the fit used,
    un-normalised back to raw layer units)."""
    plt = _plt()
    if "Ms" not in data.layers or "Mu" not in data.layers:
        raise KeyError("pl.velocity: layers need Ms/Mu — run "
                       "velocity.moments first")
    if isinstance(var_names, (str, int)):
        var_names = [var_names]
    gene_names = (np.asarray(data.var["gene_name"])
                  if "gene_name" in data.var else None)

    def gene_index(v):
        if isinstance(v, (int, np.integer)):
            return int(v)
        if gene_names is None:
            raise KeyError(f"pl.velocity: no var['gene_name'] to "
                           f"resolve {v!r}; pass integer indices")
        hit = np.flatnonzero(gene_names == v)
        if not len(hit):
            raise KeyError(f"pl.velocity: unknown gene {v!r}")
        return int(hit[0])

    idx = [gene_index(v) for v in var_names]
    n = data.n_cells
    Ms = np.asarray(data.layers["Ms"], np.float32)[:n]
    Mu = np.asarray(data.layers["Mu"], np.float32)[:n]
    plt_colors = None
    legend_handles = None
    cvals = None
    if color is not None:
        cvals, cat = _resolve_color(data, color)
        if cat:  # per-level palette + legend, same as pl.embedding
            levels, codes = np.unique(cvals, return_inverse=True)
            pal = _cat_palette(plt, len(levels))
            plt_colors = np.asarray(pal)[codes]
            legend_handles = [
                plt.Line2D([], [], marker="o", ls="", color=pal[i],
                           label=str(lev))
                for i, lev in enumerate(levels)]
            cvals = None
    # the ODE-scale switch time is required to redraw the curve; fits
    # saved before it existed fall back to the steady-state line only
    has_fit = ("fit_alpha" in data.var
               and "fit_t_switch_geo" in data.var)
    ncols = min(ncols, len(idx))
    nrows = -(-len(idx) // ncols)
    fig, axes = plt.subplots(nrows, ncols, squeeze=False,
                             figsize=(2.8 * ncols, 2.6 * nrows))
    for pi, j in enumerate(idx):
        ax = axes[pi // ncols][pi % ncols]
        s, u = Ms[:, j], Mu[:, j]
        if plt_colors is not None:
            ax.scatter(s, u, s=4, c=plt_colors, alpha=0.6,
                       linewidths=0)
            if pi == 0 and legend_handles:
                ax.legend(handles=legend_handles, fontsize=6,
                          frameon=False, loc="best")
        elif cvals is not None:
            ax.scatter(s, u, s=4, c=cvals, cmap="viridis", alpha=0.6,
                       linewidths=0)
        else:
            # scalar color: passing cmap= alongside it makes matplotlib
            # emit a UserWarning per panel — only map when values resolve
            ax.scatter(s, u, s=4, c="tab:blue", alpha=0.6,
                       linewidths=0)
        if "velocity_gamma" in data.var:
            g = float(np.asarray(data.var["velocity_gamma"])[j])
            xs = np.linspace(0.0, max(s.max(), 1e-9), 32)
            ax.plot(xs, g * xs, "k--", lw=1, alpha=0.8)
        if has_fit:
            import jax.numpy as jnp

            from .ops.velocity import _dyn_traj

            var = data.var
            la = np.log(max(float(np.asarray(var["fit_alpha"])[j]),
                            1e-12))
            lb = np.log(max(float(np.asarray(var["fit_beta"])[j]),
                            1e-12))
            lg = np.log(max(float(np.asarray(var["fit_gamma"])[j]),
                            1e-12))
            # the GEOMETRIC switch time — fit_t_switch is ECDF-warped
            # onto the uniform cell-time scale and does not
            # parameterise the ODE
            ts = float(np.asarray(var["fit_t_switch_geo"])[j])
            c = float(np.asarray(var["fit_scaling"])[j])
            tg = jnp.linspace(0.0, 1.0, 200)
            ut, st = _dyn_traj(la, lb, lg, ts, tg)
            # back to raw units: the fit saw u/su99 = c·u_ode,
            # s/ss99 = s_ode
            su = max(float(np.percentile(u, 99)), 1e-6)
            ss = max(float(np.percentile(s, 99)), 1e-6)
            ax.plot(np.asarray(st) * ss, np.asarray(ut) * c * su,
                    color="purple", lw=1.5, alpha=0.9)
        title = (str(gene_names[j]) if gene_names is not None
                 else f"gene {j}")
        ax.set_title(title, fontsize=9)
        ax.set_xlabel("Ms (spliced)", fontsize=8)
        if pi % ncols == 0:
            ax.set_ylabel("Mu (unspliced)", fontsize=8)
    for pi in range(len(idx), nrows * ncols):
        axes[pi // ncols][pi % ncols].axis("off")
    fig.tight_layout()
    return _finish(fig, axes, save, show, created=True, kind="velocity")
