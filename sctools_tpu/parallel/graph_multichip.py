"""Multi-chip edge-list graph primitives: ``knn_matvec_sharded`` /
``diffuse_sharded``.

Every downstream graph op in this framework (velocity moments, MAGIC
imputation, diffusion operators, DPT flows) reduces to ``P @ X`` with
P in the padded (n, k) edge-list form (``ops/graph.py knn_matvec``).
This module gives that primitive a cells-sharded multi-chip execution
so the graph FAMILY scales the same way the kNN build does
(``parallel/knn_multichip.py``), not just the search.

TPU design — two strategies over the 1-D cell mesh, shared by the
one-shot matvec and the t-step diffusion through the same per-step
helpers (a fix to the ring arithmetic lands in exactly one place):

* ``"all_gather"``: one ``jax.lax.all_gather`` of the source matrix,
  then a purely local edge gather.  Right when the gathered operand is
  narrow (PCA scores, velocity layers after HVG subset: n × ≤2k
  floats) — one ICI collective, maximal MXU/VPU locality.
* ``"ring"``: the source shard circulates with ``jax.lax.ppermute``;
  at inner step ``s`` device ``i`` holds the chunk that STARTED on
  device ``(i − s) mod P``, so membership of each edge's global target
  id in the circulating chunk is computed, not communicated — the same
  provenance arithmetic as the ring kNN.  Peak per-device memory is
  one chunk, for wide operands that must never materialise gathered.

Edge ids are GLOBAL row indices; ``idx``/``weights``/``x`` are sharded
along cells.  Rows must divide evenly over the mesh —
``pad_rows_for_mesh`` implements the contract (-1 edges, zero
weights, zero rows; padded rows contribute nothing and callers slice
them back off).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import CELL_AXIS, shard_map

_STRATEGIES = ("all_gather", "ring")


def require_cell_axis(mesh, who: str, axis: str = CELL_AXIS) -> int:
    """The mesh-axis guard every sharded graph op needs: returns the
    device count, raising the explicit error (not a bare KeyError)
    when the mesh was built with a different axis name."""
    if axis not in mesh.shape:
        raise ValueError(
            f"{who}: mesh has axes {tuple(mesh.shape)}; expected a "
            f"{axis!r} axis (parallel.make_mesh)")
    return mesh.shape[axis]


def pad_rows_for_mesh(mesh, *, idx, weights, x, axis: str = CELL_AXIS,
                      who: str = "graph_multichip"):
    """Pad (idx, weights, x) rows to a device multiple under the
    module's contract (-1 edges, zero weights, zero rows).  Returns
    the padded triple plus the original row count to slice with."""
    n_dev = require_cell_axis(mesh, who, axis)
    n = x.shape[0]
    rows = -(-n // n_dev) * n_dev
    if rows == n:
        return idx, weights, x, n

    def pad(a, fill):
        width = ((0, rows - n),) + tuple((0, 0) for _ in a.shape[1:])
        return jnp.pad(a, width, constant_values=fill)

    return pad(idx, -1), pad(weights, 0.0), pad(x, 0.0), n


def _check(who, knn_idx, weights, x, n_dev, strategy):
    if strategy not in _STRATEGIES:
        raise ValueError(f"{who}: unknown strategy {strategy!r} "
                         f"(use 'all_gather' or 'ring')")
    if not (knn_idx.shape[0] == weights.shape[0] == x.shape[0]):
        raise ValueError(
            f"{who}: idx/weights/x row counts differ "
            f"({knn_idx.shape[0]}/{weights.shape[0]}/{x.shape[0]}) — "
            f"independently-divisible mismatches would shard-misalign "
            f"SILENTLY, pairing wrong rows per device")
    if x.shape[0] % n_dev:
        raise ValueError(
            f"{who}: {x.shape[0]} rows do not divide over {n_dev} "
            f"devices; pad rows first (pad_rows_for_mesh)")


def _step_all_gather(idx_b, w_b, x_b, axis):
    """One ``P @ x`` application, all-gather strategy (shard-local
    view).  -1 edges masked exactly like ops.graph.knn_matvec."""
    x_full = jax.lax.all_gather(x_b, axis, axis=0, tiled=True)
    safe = jnp.where(idx_b < 0, 0, idx_b)
    w = jnp.where(idx_b < 0, 0.0, w_b)
    g = jnp.take(x_full, safe, axis=0)
    return jnp.einsum("nk,nkd->nd", w, g,
                      precision=jax.lax.Precision.HIGHEST)


def _step_ring(idx_b, w_b, x_b, axis, n_dev):
    """One ``P @ x`` application, ring strategy: the source shard
    circulates; chunk provenance at inner step ``s`` is device
    ``(me − s) mod P`` (computed, not communicated)."""
    rows = x_b.shape[0]
    me = jax.lax.axis_index(axis)
    perm = [(d, (d + 1) % n_dev) for d in range(n_dev)]

    def inner(s, carry):
        acc, chunk = carry
        src = (me - s) % n_dev
        off = src * rows
        in_chunk = (idx_b >= off) & (idx_b < off + rows)
        loc = jnp.clip(idx_b - off, 0, rows - 1)
        w = jnp.where(in_chunk & (idx_b >= 0), w_b, 0.0)
        g = jnp.take(chunk, loc, axis=0)
        acc = acc + jnp.einsum("nk,nkd->nd", w, g,
                               precision=jax.lax.Precision.HIGHEST)
        chunk = jax.lax.ppermute(chunk, axis, perm)
        return acc, chunk

    # x_b * 0, not jnp.zeros: the carry must enter the loop with the
    # same varying-over-the-mesh-axis type it exits with (shard_map
    # tracks per-value manual axes; a plain constant is unvarying and
    # the fori_loop carry types then mismatch)
    acc, _ = jax.lax.fori_loop(0, n_dev, inner, (x_b * 0.0, x_b))
    return acc


def knn_matvec_sharded(knn_idx, weights, x, mesh,
                       axis: str = CELL_AXIS,
                       strategy: str = "all_gather"):
    """``P @ x`` with everything cells-sharded over ``mesh``.

    Matches ``ops.graph.knn_matvec`` exactly (same masking of -1
    edges, same einsum precision); only the execution is distributed.
    """
    n_dev = require_cell_axis(mesh, "knn_matvec_sharded", axis)
    _check("knn_matvec_sharded", knn_idx, weights, x, n_dev, strategy)

    def body(idx_b, w_b, x_b):
        if strategy == "all_gather":
            return _step_all_gather(idx_b, w_b, x_b, axis)
        return _step_ring(idx_b, w_b, x_b, axis, n_dev)

    spec = P(axis)
    return shard_map(body, mesh=mesh,
                     in_specs=(spec, spec, spec),
                     out_specs=spec)(knn_idx, weights, x)


def smooth_layers_sharded(knn_idx, weights, layers, mesh,
                          axis: str = CELL_AXIS,
                          strategy: str = "all_gather"):
    """The velocity-moments smoothing kernel, sharded:
    ``(X + P @ X) / (1 + rowsum(P))`` for each layer (what
    ``velocity.moments`` computes per layer after weight
    symmetrisation) — one mesh program per list entry, so callers
    that can concatenate layers along genes should pass one matrix
    (velocity.moments does)."""
    w = jnp.where(knn_idx < 0, 0.0, weights)
    denom = 1.0 + jnp.sum(w, axis=1, keepdims=True)
    return [
        (X + knn_matvec_sharded(knn_idx, weights, X, mesh, axis=axis,
                                strategy=strategy)) / denom
        for X in layers
    ]


def diffuse_sharded(knn_idx, weights, x, mesh, t: int,
                    axis: str = CELL_AXIS,
                    strategy: str = "all_gather"):
    """``P^t @ x`` cells-sharded — MAGIC's diffusion — as ONE mesh
    program: the t-step ``lax.scan`` lives INSIDE the shard_map body
    (t steps cost t collectives, not t program dispatches; each step
    must re-communicate since the operand changes, so the per-step
    collective is inherent — the dispatch overhead is not).  Uses the
    same per-step helpers as ``knn_matvec_sharded``."""
    n_dev = require_cell_axis(mesh, "diffuse_sharded", axis)
    _check("diffuse_sharded", knn_idx, weights, x, n_dev, strategy)

    def body(idx_b, w_b, x_b):
        def step(xc, _):
            if strategy == "all_gather":
                return _step_all_gather(idx_b, w_b, xc, axis), None
            return _step_ring(idx_b, w_b, xc, axis, n_dev), None

        out, _ = jax.lax.scan(step, x_b, None, length=t)
        return out

    spec = P(axis)
    return shard_map(body, mesh=mesh,
                     in_specs=(spec, spec, spec),
                     out_specs=spec)(knn_idx, weights, x)
