"""Multi-chip edge-list graph primitives: ``knn_matvec_sharded``.

Every downstream graph op in this framework (velocity moments, MAGIC
imputation, diffusion operators, DPT flows) reduces to ``P @ X`` with
P in the padded (n, k) edge-list form (``ops/graph.py knn_matvec``).
This module gives that primitive a cells-sharded multi-chip execution
so the graph FAMILY scales the same way the kNN build does
(``parallel/knn_multichip.py``), not just the search.

TPU design — two strategies over the 1-D cell mesh:

* ``"all_gather"``: one ``jax.lax.all_gather`` of the source matrix,
  then a purely local edge gather.  Right when the gathered operand is
  narrow (PCA scores, velocity layers after HVG subset: n × ≤2k
  floats) — one ICI collective, maximal MXU/VPU locality.
* ``"ring"``: the source shard circulates with ``jax.lax.ppermute``;
  at step ``t`` device ``i`` holds the chunk that STARTED on device
  ``(i − t) mod P``, so membership of each edge's global target id in
  the circulating chunk is computed, not communicated — the same
  provenance arithmetic as the ring kNN.  Peak per-device memory is
  one chunk, for wide operands that must never materialise gathered.

Edge ids are GLOBAL row indices; ``idx``/``weights``/``x`` are sharded
along cells.  Rows must divide evenly over the mesh (pad with -1
edges / zero rows — the same contract every sharded op here uses).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .mesh import CELL_AXIS


def knn_matvec_sharded(knn_idx, weights, x, mesh,
                       axis: str = CELL_AXIS,
                       strategy: str = "all_gather"):
    """``P @ x`` with everything cells-sharded over ``mesh``.

    Matches ``ops.graph.knn_matvec`` exactly (same masking of -1
    edges, same einsum precision); only the execution is distributed.
    """
    n_dev = mesh.shape[axis]
    if not (knn_idx.shape[0] == weights.shape[0] == x.shape[0]):
        raise ValueError(
            f"knn_matvec_sharded: idx/weights/x row counts differ "
            f"({knn_idx.shape[0]}/{weights.shape[0]}/{x.shape[0]}) — "
            f"independently-divisible mismatches would shard-misalign "
            f"SILENTLY, pairing wrong rows per device")
    if x.shape[0] % n_dev:
        raise ValueError(
            f"knn_matvec_sharded: {x.shape[0]} rows do not divide "
            f"over {n_dev} devices; pad rows (zero x, -1 edges) to a "
            f"device multiple first")

    def body_all_gather(idx_b, w_b, x_b):
        x_full = jax.lax.all_gather(x_b, axis, axis=0, tiled=True)
        safe = jnp.where(idx_b < 0, 0, idx_b)
        w = jnp.where(idx_b < 0, 0.0, w_b)
        g = jnp.take(x_full, safe, axis=0)
        return jnp.einsum("nk,nkd->nd", w, g,
                          precision=jax.lax.Precision.HIGHEST)

    def body_ring(idx_b, w_b, x_b):
        rows = x_b.shape[0]
        me = jax.lax.axis_index(axis)
        perm = [(d, (d + 1) % n_dev) for d in range(n_dev)]

        def step(t, carry):
            acc, chunk = carry
            src = (me - t) % n_dev
            off = src * rows
            in_chunk = (idx_b >= off) & (idx_b < off + rows)
            loc = jnp.clip(idx_b - off, 0, rows - 1)
            w = jnp.where(in_chunk & (idx_b >= 0), w_b, 0.0)
            g = jnp.take(chunk, loc, axis=0)
            acc = acc + jnp.einsum(
                "nk,nkd->nd", w, g,
                precision=jax.lax.Precision.HIGHEST)
            chunk = jax.lax.ppermute(chunk, axis, perm)
            return acc, chunk

        # x_b * 0, not jnp.zeros: the carry must enter the loop with
        # the same varying-over-the-mesh-axis type it exits with
        # (shard_map tracks per-value manual axes; a plain constant
        # is unvarying and the fori_loop carry types then mismatch)
        acc = x_b * 0.0
        acc, _ = jax.lax.fori_loop(0, n_dev, step, (acc, x_b))
        return acc

    if strategy == "all_gather":
        body = body_all_gather
    elif strategy == "ring":
        body = body_ring
    else:
        raise ValueError(
            f"knn_matvec_sharded: unknown strategy {strategy!r} "
            f"(use 'all_gather' or 'ring')")
    spec = P(axis)
    return jax.shard_map(body, mesh=mesh,
                     in_specs=(spec, spec, spec),
                     out_specs=spec)(knn_idx, weights, x)


def smooth_layers_sharded(knn_idx, weights, layers, mesh,
                          axis: str = CELL_AXIS,
                          strategy: str = "all_gather"):
    """The velocity-moments smoothing kernel, sharded:
    ``(X + P @ X) / (1 + rowsum(P))`` for each layer (what
    ``velocity.moments`` computes per layer after weight
    symmetrisation) — one mesh program per layer."""
    w = jnp.where(knn_idx < 0, 0.0, weights)
    denom = 1.0 + jnp.sum(w, axis=1, keepdims=True)
    return [
        (X + knn_matvec_sharded(knn_idx, weights, X, mesh, axis=axis,
                                strategy=strategy)) / denom
        for X in layers
    ]
