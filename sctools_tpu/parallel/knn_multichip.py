"""Multi-chip kNN graph construction: ``neighbors.knn_multichip``.

Reference parity: BASELINE.json configs[4] — "multi-chip kNN on a
10M-cell slice (v5e-8, ICI all-gather)".

TPU design: a **ring** over the 1-D cell mesh.  Every device keeps its
query shard resident and a candidate chunk circulates with
``jax.lax.ppermute`` — after P steps every query has been scored
against every candidate, but peak per-device memory is one chunk, not
the full matrix (a literal ``all_gather`` of the PCA block works too
and is exposed via ``strategy="all_gather"``; the ring is the default
because it overlaps compute with ICI transfers and never materialises
the gathered (N, d) array).  The per-step merge is the same
MXU-tiled score + ``lax.top_k`` used by the single-chip path, carried
as a running (k) state per query row.

Chunk provenance is computed, not communicated: at step ``t`` device
``i`` holds the chunk that started on device ``(i - t) mod P``, so the
global column offset is ``((i - t) mod P) * chunk_rows``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import config, round_up
from ..data.dataset import CellData
from ..registry import register
from .mesh import CELL_AXIS, make_mesh, pvary, shard_map


def _merge_chunk(q, chunk, chunk_offset, running, *, k, metric, block,
                 n_valid, q_ids, exclude_self,
                 precision=jax.lax.Precision.DEFAULT):
    """Merge top-k of ``q`` vs one candidate ``chunk`` into ``running``.

    q: (nq, d) prepped; chunk: (m, d) prepped; running: ((nq, k) vals
    descending-score, (nq, k) global idx).  Processes the chunk in
    ``block``-column tiles and q in ``block``-row tiles.
    """
    nq, d = q.shape
    m = chunk.shape[0]
    c_blocks = chunk.reshape(m // block, block, d)
    if metric == "euclidean":
        cn2_blocks = jnp.sum(c_blocks.astype(jnp.float32) ** 2, axis=2)
    else:
        cn2_blocks = jnp.zeros((m // block, block), jnp.float32)
    offsets = chunk_offset + jnp.arange(m // block, dtype=jnp.int32) * block
    col_iota = jnp.arange(block, dtype=jnp.int32)

    def per_qblock(args):
        qblk, ids_blk, rv, ri = args
        if metric == "euclidean":
            qn2 = jnp.sum(qblk.astype(jnp.float32) ** 2, axis=1)

        def body(carry, inp):
            bvals, bidx = carry
            cblk, cn2, off = inp
            s = jnp.dot(qblk, cblk.T, preferred_element_type=jnp.float32,
                        precision=precision)
            if metric == "euclidean":
                s = -(qn2[:, None] - 2.0 * s + cn2[None, :])
            gcol = off + col_iota
            s = jnp.where((gcol >= n_valid)[None, :], -jnp.inf, s)
            if exclude_self:
                s = jnp.where(gcol[None, :] == ids_blk[:, None], -jnp.inf, s)
            allv = jnp.concatenate([bvals, s], axis=1)
            alli = jnp.concatenate(
                [bidx, jnp.broadcast_to(gcol[None, :], s.shape)], axis=1
            )
            v, sel = jax.lax.top_k(allv, k)
            return (v, jnp.take_along_axis(alli, sel, axis=1)), None

        (v, i), _ = jax.lax.scan(body, (rv, ri), (c_blocks, cn2_blocks, offsets))
        return v, i

    rv, ri = running
    nqb = nq // block
    v, i = jax.lax.map(
        per_qblock,
        (q.reshape(nqb, block, d), q_ids.reshape(nqb, block),
         rv.reshape(nqb, block, k), ri.reshape(nqb, block, k)),
    )
    return v.reshape(nq, k), i.reshape(nq, k)


def _prep(points, metric, dtype):
    points = jnp.asarray(points)
    if metric == "cosine":
        norms = jnp.linalg.norm(points, axis=1, keepdims=True)
        points = points / jnp.maximum(norms, 1e-12)
    return points.astype(dtype)


def knn_multichip_arrays(
    points,
    *,
    k: int = 15,
    metric: str = "cosine",
    mesh=None,
    n_valid: int | None = None,
    block: int | None = None,
    exclude_self: bool = False,
    strategy: str = "ring",
):
    """Exact multi-device kNN of ``points`` against themselves.

    Returns (indices, distances) with the same row padding as the
    sharded input (trim to n_valid on host).  ``strategy``: "ring"
    (ppermute pipeline, default) or "all_gather" (one collective,
    simplest; memory O(N·d) per device).
    """
    if metric not in ("cosine", "euclidean"):
        raise ValueError(f"unknown metric {metric!r}")
    mesh = mesh or make_mesh()
    n_dev = int(mesh.devices.size)
    points = jnp.asarray(points)
    n = points.shape[0]
    n_valid = n_valid if n_valid is not None else n
    d = points.shape[1]

    if block is None:
        block = min(config.row_block, max(8, round_up((n + n_dev - 1) // n_dev, 8)))
    rows = round_up(n, n_dev * block)
    if rows != n:
        points = jnp.concatenate(
            [points, jnp.zeros((rows - n, d), points.dtype)]
        )
    sharding = NamedSharding(mesh, P(CELL_AXIS, None))
    pts = jax.device_put(points, sharding)
    return _knn_multichip_jit(
        pts, k=k, metric=metric, n_valid=n_valid, block=block,
        exclude_self=exclude_self, strategy=strategy, mesh=mesh,
        mm_dtype=str(jnp.dtype(config.matmul_dtype)),
    )


@partial(
    jax.jit,
    static_argnames=("k", "metric", "n_valid", "block", "exclude_self",
                     "strategy", "mesh", "mm_dtype"),
)
def _knn_multichip_jit(pts, *, k, metric, n_valid, block, exclude_self,
                       strategy, mesh, mm_dtype):
    n_dev = int(mesh.devices.size)
    rows = pts.shape[0]
    m = rows // n_dev
    mm_dtype = jnp.dtype(mm_dtype)
    # f32 inputs need HIGHEST on TPU or the MXU silently drops to bf16
    # (same mapping as the single-chip _knn_jit).
    precision = (jax.lax.Precision.HIGHEST if mm_dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    pts = _prep(pts, metric, mm_dtype)

    def vary(x):
        # shard_map's vma type system: constants are "invariant" until
        # cast; scan carries must enter with their final (varying) type
        # (identity on jax versions that track replication implicitly
        # — mesh.pvary is the compat shim).
        return pvary(x, (CELL_AXIS,))

    def ring(q_local):
        shard = jax.lax.axis_index(CELL_AXIS)
        q_ids = shard * m + jnp.arange(m, dtype=jnp.int32)
        running = (
            vary(jnp.full((m, k), -jnp.inf, jnp.float32)),
            vary(jnp.full((m, k), -1, jnp.int32)),
        )

        def step(t, state):
            chunk, running = state
            src = (shard - t) % n_dev
            running = _merge_chunk(
                q_local, chunk, (src * m).astype(jnp.int32), running,
                k=k, metric=metric, block=block, n_valid=n_valid,
                q_ids=q_ids, exclude_self=exclude_self, precision=precision,
            )
            chunk = jax.lax.ppermute(
                chunk, CELL_AXIS,
                perm=[(i, (i + 1) % n_dev) for i in range(n_dev)],
            )
            return chunk, running

        # n_dev is static: unrolled python loop lets XLA overlap the
        # ppermute of step t with the matmuls of step t (async send).
        state = (q_local, running)
        for t in range(n_dev):
            state = step(t, state)
        _, running = state
        return running

    def gather(q_local):
        shard = jax.lax.axis_index(CELL_AXIS)
        q_ids = shard * m + jnp.arange(m, dtype=jnp.int32)
        cand = jax.lax.all_gather(q_local, CELL_AXIS, tiled=True)  # (rows, d)
        running = (
            vary(jnp.full((m, k), -jnp.inf, jnp.float32)),
            vary(jnp.full((m, k), -1, jnp.int32)),
        )
        return _merge_chunk(
            q_local, cand, jnp.int32(0), running, k=k, metric=metric,
            block=block, n_valid=n_valid, q_ids=q_ids,
            exclude_self=exclude_self, precision=precision,
        )

    fn = ring if strategy == "ring" else gather
    vals, idx = shard_map(
        fn, mesh=mesh, in_specs=P(CELL_AXIS, None),
        out_specs=(P(CELL_AXIS, None), P(CELL_AXIS, None)),
    )(pts)
    if metric == "cosine":
        dists = 1.0 - vals
    else:
        dists = jnp.sqrt(jnp.maximum(-vals, 0.0))
    qvalid = jnp.arange(rows) < n_valid
    idx = jnp.where(qvalid[:, None], idx, -1)
    return idx, dists


@register("neighbors.knn_multichip", backend="tpu",
          sharding="cells", collective=True)
def knn_multichip_tpu(data: CellData, k: int = 15, metric: str = "cosine",
                      use_rep: str = "X_pca", n_devices: int | None = None,
                      block: int | None = None, exclude_self: bool = False,
                      strategy: str = "ring", mesh=None) -> CellData:
    """Multi-device kNN over all available devices (or ``n_devices``,
    or an explicit ``mesh=`` — how ``plan.fused_pipeline(mesh=...)``
    threads its mesh into this collective stage).  Adds the same
    obsp/uns fields as ``neighbors.knn``."""
    from ..ops.knn import _get_rep

    rep = _get_rep(data, use_rep)
    if mesh is None:
        mesh = make_mesh(n_devices)
    idx, dist = knn_multichip_arrays(
        rep, k=k, metric=metric, mesh=mesh, n_valid=data.n_cells,
        block=block, exclude_self=exclude_self, strategy=strategy,
    )
    from ..ops.graph import invalidate_graph_layout_stats

    data = invalidate_graph_layout_stats(data)
    return data.with_obsp(knn_indices=idx, knn_distances=dist).with_uns(
        knn_k=k, knn_metric=metric
    )


@register("neighbors.knn_multichip", backend="cpu")
def knn_multichip_cpu(data: CellData, k: int = 15, metric: str = "cosine",
                      use_rep: str = "X_pca", exclude_self: bool = False,
                      **_ignored) -> CellData:
    """CPU oracle: identical to neighbors.knn (brute force)."""
    from ..ops.knn import knn_cpu

    return knn_cpu(data, k=k, metric=metric, use_rep=use_rep,
                   exclude_self=exclude_self)
