"""Device-mesh utilities, single-host and multi-host.

Multi-chip execution follows the standard JAX recipe (pick a mesh,
annotate shardings, let XLA insert collectives): cells are the batch
axis and shard across devices; genes stay replicated-contiguous so
per-gene reductions become single ``psum``-backed ``segment_sum``s.
The reference's NCCL/MPI communication backend maps onto XLA
collectives — over ICI within a slice, DCN across hosts — and
``init_distributed`` below is the SPMD bring-up that replaces its
``MPI_Init``: after it, ``jax.devices()`` spans every host's chips
and ``make_mesh()`` (no argument) lays the cell axis across the whole
pod, so the SAME pipeline code runs 1-chip, 8-chip, or multi-host.
Nothing in this package opens sockets; the collectives are entirely
XLA's.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CELL_AXIS = "cells"


# ---------------------------------------------------------------------------
# jax API compatibility: shard_map moved from jax.experimental to the
# top level, and the manual-axes "varying" cast was renamed/introduced
# across releases.  Every call site in this package goes through these
# two shims so one jax upgrade lands in exactly one place.
# ---------------------------------------------------------------------------


def shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` where it exists, else the
    ``jax.experimental.shard_map`` form (jax <= 0.4.x)."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def pvary(x, axis_names=(CELL_AXIS,)):
    """Cast a shard_map-invariant constant to the mesh-varying type
    (``jax.lax.pcast(..., to="varying")`` on new jax, ``jax.lax
    .pvary`` on intermediate releases).  Older jax tracks replication
    per-value without an explicit cast, so the shim degrades to the
    identity there — semantics are unchanged, only the vma type
    system needs the hint."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, tuple(axis_names), to="varying")
    pv = getattr(jax.lax, "pvary", None)
    if pv is not None:
        return pv(x, tuple(axis_names))
    return x


def active_mesh() -> Mesh | None:
    """The mesh currently entered via ``with mesh:`` (jax's thread-
    local mesh context), or ``None``.  The plan layer consults this
    when ``fused_pipeline`` is called without an explicit ``mesh=``."""
    try:
        from jax._src import mesh as _mesh_lib

        m = _mesh_lib.thread_resources.env.physical_mesh
    except Exception:  # pragma: no cover - jax internals moved
        return None
    if m is None or getattr(m, "empty", False) or m.devices.size == 0:
        return None
    return m


def mesh_signature(mesh: Mesh) -> tuple:
    """Hashable, repr-stable identity of a mesh: axis names, shape and
    the flat device ids.  A REBUILT mesh over the same devices yields
    the same signature (plan-cache hit, identical checkpoint
    fingerprints); a different device count/order does not."""
    return (tuple(str(a) for a in mesh.axis_names),
            tuple(int(mesh.shape[a]) for a in mesh.axis_names),
            tuple(int(d.id) for d in mesh.devices.flat))


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> dict:
    """Multi-host SPMD bring-up (the reference's MPI_Init analogue).

    Wraps ``jax.distributed.initialize``: on managed TPU pods every
    argument is auto-detected from the environment; pass them
    explicitly elsewhere.  Exactly two failure modes are benign and
    degrade to a no-op — a repeat call (jax 0.9 raises
    "should only be called once"), and a bare single-process call
    with NO arguments where cluster detection finds nothing (jax
    raises "coordinator_address should be defined").  Everything else
    re-raises: a failed bring-up on a real pod must never silently
    fall back to num_processes=1 per host (each host would run the
    whole job independently and produce duplicated results).
    Returns {"process_id", "num_processes", "local_devices",
    "global_devices"}.
    """
    import os

    bare_call = (coordinator_address is None and num_processes is None
                 and process_id is None)
    # pod-environment hints: when any of these exist, a failed bring-up
    # is NEVER benign (swallowing it would run every host standalone).
    # TPU_WORKER_HOSTNAMES counts only with MULTIPLE entries — single-
    # chip tunnels set it with one hostname on plain one-host sessions.
    pod_env = any(os.environ.get(v) for v in (
        "MEGASCALE_COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
        "CLOUD_TPU_TASK_ID")) or (
        len(os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")) > 1)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    except RuntimeError as e:
        benign = ("only be called once" in str(e)  # repeat call
                  # bare late call on a plain single-process host
                  # (backend already up, no pod to join)
                  or (bare_call and not pod_env
                      and "before any JAX" in str(e)))
        if not benign:
            raise
    except ValueError as e:
        # bare call, cluster auto-detection found nothing to join
        if not (bare_call and not pod_env
                and "coordinator_address" in str(e)):
            raise
    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def make_mesh(n_devices: int | None = None, axis_name: str = CELL_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` GLOBAL devices (all by
    default — after :func:`init_distributed` that spans every host)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def cell_sharding(mesh: Mesh, ndim: int = 2,
                  axis_name: str = CELL_AXIS) -> NamedSharding:
    """Shard the leading (cell) axis; replicate the rest."""
    return NamedSharding(mesh, P(axis_name, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_celldata(data, mesh: Mesh):
    """Move a host CellData onto a mesh, cells sharded across devices.

    Pads rows to a multiple of the mesh size (times the sublane
    multiple) first so every device gets an equal block.
    """
    from ..config import config, round_up
    from ..data.dataset import CellData
    from ..data.sparse import SparseCells
    import scipy.sparse as sp

    n_dev = mesh.devices.size
    X = data.X
    if sp.issparse(X):
        X = SparseCells.from_scipy_csr(X)
    if isinstance(X, SparseCells):
        mult = n_dev * config.sublane
        X = X.pad_rows_to(round_up(X.rows_padded, mult))
        X = SparseCells(
            jax.device_put(jnp_asarray(X.indices), cell_sharding(mesh)),
            jax.device_put(jnp_asarray(X.data), cell_sharding(mesh)),
            X.n_cells, X.n_genes,
        )
    else:
        X = np.asarray(X)
        rows = round_up(X.shape[0], n_dev * config.sublane)
        if rows != X.shape[0]:
            X = np.pad(X, ((0, rows - X.shape[0]), (0, 0)))
        X = jax.device_put(X, cell_sharding(mesh))
    out = CellData(
        X, dict(data.obs), dict(data.var), dict(data.obsm),
        dict(data.varm), dict(data.obsp), dict(data.uns),
        dict(data.layers),  # carried host-side; shard on use
    )
    return out


def jnp_asarray(x):
    """``jnp.asarray`` that PRESERVES an existing committed sharding:
    a jax array already placed (sharded over a mesh, or pinned to a
    device) passes through untouched — re-wrapping it with
    ``jnp.asarray`` would re-place it on the default device, silently
    gathering a sharded operand before the very ``device_put`` that
    was about to shard it again (one extra full-array transfer per
    call)."""
    import jax.numpy as jnp

    if isinstance(x, jax.Array):
        return x
    return jnp.asarray(x)
