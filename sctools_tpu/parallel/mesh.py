"""Device-mesh utilities, single-host and multi-host.

Multi-chip execution follows the standard JAX recipe (pick a mesh,
annotate shardings, let XLA insert collectives): cells are the batch
axis and shard across devices; genes stay replicated-contiguous so
per-gene reductions become single ``psum``-backed ``segment_sum``s.
The reference's NCCL/MPI communication backend maps onto XLA
collectives — over ICI within a slice, DCN across hosts — and
``init_distributed`` below is the SPMD bring-up that replaces its
``MPI_Init``: after it, ``jax.devices()`` spans every host's chips
and ``make_mesh()`` (no argument) lays the cell axis across the whole
pod, so the SAME pipeline code runs 1-chip, 8-chip, or multi-host.
Nothing in this package opens sockets; the collectives are entirely
XLA's.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CELL_AXIS = "cells"


# ---------------------------------------------------------------------------
# jax API compatibility: shard_map moved from jax.experimental to the
# top level, and the manual-axes "varying" cast was renamed/introduced
# across releases.  Every call site in this package goes through these
# two shims so one jax upgrade lands in exactly one place.
# ---------------------------------------------------------------------------


def shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` where it exists, else the
    ``jax.experimental.shard_map`` form (jax <= 0.4.x)."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def pvary(x, axis_names=(CELL_AXIS,)):
    """Cast a shard_map-invariant constant to the mesh-varying type
    (``jax.lax.pcast(..., to="varying")`` on new jax, ``jax.lax
    .pvary`` on intermediate releases).  Older jax tracks replication
    per-value without an explicit cast, so the shim degrades to the
    identity there — semantics are unchanged, only the vma type
    system needs the hint."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, tuple(axis_names), to="varying")
    pv = getattr(jax.lax, "pvary", None)
    if pv is not None:
        return pv(x, tuple(axis_names))
    return x


def active_mesh() -> Mesh | None:
    """The mesh currently entered via ``with mesh:`` (jax's thread-
    local mesh context), or ``None``.  The plan layer consults this
    when ``fused_pipeline`` is called without an explicit ``mesh=``."""
    try:
        from jax._src import mesh as _mesh_lib

        m = _mesh_lib.thread_resources.env.physical_mesh
    except Exception:  # pragma: no cover - jax internals moved
        return None
    if m is None or getattr(m, "empty", False) or m.devices.size == 0:
        return None
    return m


def mesh_signature(mesh: Mesh) -> tuple:
    """Hashable, repr-stable identity of a mesh: axis names, shape and
    the flat device ids.  A REBUILT mesh over the same devices yields
    the same signature (plan-cache hit, identical checkpoint
    fingerprints); a different device count/order does not."""
    return (tuple(str(a) for a in mesh.axis_names),
            tuple(int(mesh.shape[a]) for a in mesh.axis_names),
            tuple(int(d.id) for d in mesh.devices.flat))


#: bring-up failure signatures that are TRANSIENT at the transport
#: level: the coordinator's port still in TIME_WAIT from a previous
#: incarnation, workers racing the coordinator's startup (connect
#: refused / barrier deadline), and the usual socket noise in
#: between.  A bounded retry gives the port time to free and the
#: coordinator time to come up; anything else recurs identically and
#: must surface immediately.
_BRINGUP_TRANSIENT_MARKERS = (
    "address already in use",
    "address in use",
    "failed to bind",
    "bind failed",
    "deadline exceeded",
    "deadline_exceeded",
    "timed out",
    "timeout",
    "unavailable",
    "failed to connect",
    "connection refused",
    "connection reset",
    "connection closed",
    "socket closed",
    "broken pipe",
)


def classify_bringup_error(exc: BaseException) -> str:
    """``"transient"`` when a distributed bring-up failure is worth a
    bounded retry (port in TIME_WAIT, coordinator not up yet, barrier
    timeout), ``"deterministic"`` otherwise (misconfig recurs
    identically — retrying only hides the actionable message)."""
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(m in msg for m in _BRINGUP_TRANSIENT_MARKERS):
        return "transient"
    return "deterministic"


#: hosts a coordinator bind-probe can meaningfully test from this
#: process (process 0 binds the coordinator locally; probing a
#: remote host's NIC from here would always fail)
_LOCAL_BIND_HOSTS = ("127.0.0.1", "localhost", "0.0.0.0", "::1", "")


def _await_coordinator_port(host: str, port: int, attempts: int,
                            retry_delay_s: float, clock) -> None:
    """Bounded-retry bind probe of the coordinator port BEFORE jax
    touches it.  This is not an optimization: jaxlib's coordinator
    service SEGFAULTS the whole process when its gRPC listener cannot
    bind (observed on jaxlib 0.4.36: rc=-11, "Address already in
    use" on stderr) — there is no Python exception to classify after
    the fact, so the port-in-use case must be ruled out up front.  A
    port still in TIME_WAIT from a previous coordinator incarnation
    frees within seconds, hence the retry; a port held by a LIVE
    listener never frees, hence the bounded attempts + actionable
    error."""
    import socket

    family = (socket.AF_INET6 if ":" in (host or "")
              else socket.AF_INET)
    last = None
    for attempt in range(1, attempts + 1):
        try:
            with socket.socket(family) as s:
                # match gRPC's own bind semantics: SO_REUSEADDR lets
                # a TIME_WAIT port pass (gRPC would bind it too) but
                # an actively-listening holder still refuses
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind((host or "127.0.0.1", port))
            return
        except OSError as e:
            last = e
        if attempt < attempts:
            clock.sleep(retry_delay_s * attempt)
    raise RuntimeError(
        f"init_distributed: coordinator port {host or '127.0.0.1'}:"
        f"{port} is still in use after {attempts} bind attempt(s) "
        f"(last: {type(last).__name__}: {last}).  jax's coordinator "
        "service CRASHES the process on a bind failure, so the "
        "bring-up is refused here instead — pick a free port, or "
        "raise attempts=/retry_delay_s= to wait out a TIME_WAIT "
        "holder.") from last


def _validate_bringup_args(coordinator_address, num_processes,
                           process_id) -> None:
    """Actionable misconfig errors BEFORE touching jax.distributed —
    a bad argument must fail with advice, not a gRPC hang or an
    opaque coordinator-side crash on a real pod."""
    if (num_processes is None) != (process_id is None):
        raise ValueError(
            "init_distributed: pass num_processes and process_id "
            "TOGETHER (got num_processes="
            f"{num_processes!r}, process_id={process_id!r}) — every "
            "process must agree on the cluster size, and a partial "
            "spec makes jax fall back to cluster auto-detection for "
            "the missing half")
    if num_processes is not None:
        if num_processes < 1:
            raise ValueError(
                f"init_distributed: num_processes={num_processes} "
                "must be >= 1")
        if not (0 <= process_id < num_processes):
            raise ValueError(
                f"init_distributed: process_id={process_id} out of "
                f"range for num_processes={num_processes} — ids are "
                "0-based and every process needs a distinct one "
                f"(valid: 0..{num_processes - 1})")
    if coordinator_address is not None:
        host, sep, port = str(coordinator_address).rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(
                "init_distributed: coordinator_address="
                f"{coordinator_address!r} is not 'host:port' — every "
                "process passes the SAME address, the one process "
                "whose process_id is 0 binds it (e.g. "
                "'10.0.0.1:8476')")


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None, *,
                     attempts: int = 3,
                     retry_delay_s: float = 2.0,
                     timeout_s: float | None = None,
                     clock=None) -> dict:
    """Multi-host SPMD bring-up (the reference's MPI_Init analogue).

    Wraps ``jax.distributed.initialize``: on managed TPU pods every
    argument is auto-detected from the environment; pass them
    explicitly elsewhere.  Exactly two failure modes are benign and
    degrade to a no-op — a repeat call (jax 0.9 raises
    "should only be called once"), and a bare single-process call
    with NO arguments where cluster detection finds nothing (jax
    raises "coordinator_address should be defined").  Everything else
    re-raises: a failed bring-up on a real pod must never silently
    fall back to num_processes=1 per host (each host would run the
    whole job independently and produce duplicated results).

    The bring-up is HARDENED three ways (the federation tier respawns
    worker processes, so re-joins against a half-torn-down coordinator
    are the common case, not the exception):

    * misconfig (mismatched ``process_id``/``num_processes``, a
      malformed address) raises an ACTIONABLE ``ValueError`` before
      jax is touched — never a gRPC hang;
    * the coordinator-binding process (``process_id == 0`` with a
      loopback/wildcard address) bind-probes its port first with the
      same bounded retry — jaxlib's coordinator service segfaults the
      process outright on a bind failure, so port-in-use must be
      ruled out BEFORE jax touches the socket;
    * transient bring-up failures (:func:`classify_bringup_error`) —
      the coordinator's port still in TIME_WAIT, workers racing the
      coordinator's startup — are retried up to ``attempts`` times
      with a linear backoff on the injectable ``clock``
      (``utils/vclock.py``; partial jax state is shut down between
      attempts), then surface as a ``RuntimeError`` naming the
      attempt count;
    * ``timeout_s`` bounds how long each attempt's coordinator
      handshake may block (jax's ``initialization_timeout``, default
      300 s) so a dead coordinator is a classified failure, not a
      five-minute hang.

    Returns {"process_id", "num_processes", "local_devices",
    "global_devices"}.
    """
    import os

    from ..utils.vclock import SYSTEM_CLOCK

    clock = clock if clock is not None else SYSTEM_CLOCK
    if attempts < 1:
        raise ValueError(f"init_distributed: attempts={attempts} "
                         "must be >= 1")
    _validate_bringup_args(coordinator_address, num_processes,
                           process_id)
    bare_call = (coordinator_address is None and num_processes is None
                 and process_id is None)
    # pod-environment hints: when any of these exist, a failed bring-up
    # is NEVER benign (swallowing it would run every host standalone).
    # TPU_WORKER_HOSTNAMES counts only with MULTIPLE entries — single-
    # chip tunnels set it with one hostname on plain one-host sessions.
    pod_env = any(os.environ.get(v) for v in (
        "MEGASCALE_COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
        "CLOUD_TPU_TASK_ID")) or (
        len(os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")) > 1)
    kw = {}
    if timeout_s is not None:
        kw["initialization_timeout"] = int(max(1, timeout_s))
    if (coordinator_address is not None and process_id == 0):
        # we are the process that BINDS the coordinator: rule out the
        # port-in-use segfault before jax can hit it (probe only
        # loopback/wildcard hosts — a pod's NIC address is bound by
        # the runtime itself and cannot be probed generically)
        host, _, port = str(coordinator_address).rpartition(":")
        if host in _LOCAL_BIND_HOSTS:
            _await_coordinator_port(host, int(port), attempts,
                                    retry_delay_s, clock)
    last_err: BaseException | None = None
    for attempt in range(1, attempts + 1):
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                **kw)
            last_err = None
            break
        except RuntimeError as e:
            benign = ("only be called once" in str(e)  # repeat call
                      # bare late call on a plain single-process host
                      # (backend already up, no pod to join)
                      or (bare_call and not pod_env
                          and "before any JAX" in str(e)))
            if benign:
                last_err = None
                break
            last_err = e
        except ValueError as e:
            # bare call, cluster auto-detection found nothing to join
            if bare_call and not pod_env \
                    and "coordinator_address" in str(e):
                last_err = None
                break
            last_err = e
        if classify_bringup_error(last_err) != "transient" \
                or attempt >= attempts:
            break
        # clear any partially-initialized distributed state so the
        # retry starts clean (a half-connected client would make the
        # next initialize raise "only be called once")
        try:
            jax.distributed.shutdown()
        except Exception as cleanup_err:  # noqa: BLE001 — nothing was
            # up to tear down; the retry's own failure is the signal
            del cleanup_err
        clock.sleep(retry_delay_s * attempt)
    if last_err is not None:
        if classify_bringup_error(last_err) == "transient":
            raise RuntimeError(
                f"init_distributed: bring-up failed {attempts} "
                f"time(s) on a transient transport error (last: "
                f"{type(last_err).__name__}: {last_err}).  The "
                "coordinator port may be held by another process — "
                "pick a free port, or raise attempts=/retry_delay_s= "
                "if the coordinator is slow to start."
            ) from last_err
        raise last_err
    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def coordination_sum(value: float, tag: str,
                     timeout_s: float = 60.0) -> float:
    """Sum one host-local float across every process of the cluster
    through the coordination service's key-value store — the same DCN
    control plane :func:`init_distributed` established, with NO
    device collective involved.

    This is the portable cross-process reduction for control-plane
    scalars (row counts, checksums, bench gates): XLA backends that
    cannot run cross-process computations (jax <= 0.4.x CPU raises
    "Multiprocess computations aren't implemented") still carry it,
    because only gRPC key-value traffic moves.  ``tag`` must be
    unique per reduction (the KV namespace is cluster-global and
    write-once per key).  Single-process (no distributed client):
    returns ``value`` unchanged."""
    from jax._src import distributed as _dist

    client = getattr(_dist.global_state, "client", None)
    n = jax.process_count()
    if client is None or n <= 1:
        return float(value)
    pid = jax.process_index()
    client.key_value_set(f"sctools/{tag}/{pid}", repr(float(value)))
    total = 0.0
    for i in range(n):
        total += float(client.blocking_key_value_get(
            f"sctools/{tag}/{i}", int(timeout_s * 1000)))
    return total


def make_mesh(n_devices: int | None = None, axis_name: str = CELL_AXIS,
              devices=None) -> Mesh:
    """1-D mesh over the first ``n_devices`` GLOBAL devices (all by
    default — after :func:`init_distributed` that spans every host).
    ``devices=`` instead takes an EXPLICIT device list — the lost-host
    degrade rung re-plans onto the surviving processes' devices, which
    are not a prefix of ``jax.devices()``."""
    if devices is not None:
        if n_devices is not None:
            raise ValueError(
                "make_mesh: pass n_devices or devices=, not both")
        devs = list(devices)
        if not devs:
            raise ValueError("make_mesh: devices= is empty")
    else:
        devs = jax.devices()
        if n_devices is not None:
            if n_devices > len(devs):
                raise ValueError(
                    f"requested {n_devices} devices, have {len(devs)}"
                )
            devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def mesh_host_groups(mesh: Mesh) -> list[list]:
    """The mesh's devices grouped by owning HOST (process), in mesh
    order — the granularity the lost-host degrade rung drops at.

    Grouping is by ``device.process_index`` (on a real multi-process
    mesh each host contributes one group).  The single-process
    host-platform harness (``--xla_force_host_platform_device_count``)
    reports every virtual device as process 0, so the env override
    ``SCTOOLS_MESH_HOSTS=N`` partitions the device list into N equal
    contiguous groups instead — that is what lets CI drive the
    host_lost rung on one box; it is ignored when the mesh already
    spans multiple real processes."""
    import os

    devs = list(mesh.devices.flat)
    by_proc: dict[int, list] = {}
    for d in devs:
        by_proc.setdefault(int(getattr(d, "process_index", 0)),
                           []).append(d)
    if len(by_proc) > 1:
        return [by_proc[p] for p in sorted(by_proc)]
    fake = os.environ.get("SCTOOLS_MESH_HOSTS", "")
    if (fake.isdigit() and int(fake) > 1
            and len(devs) % int(fake) == 0
            and len(devs) == len(jax.devices())):
        # only the FULL device set fake-splits: a mesh already shrunk
        # by a host_lost rung is "one surviving host" (further
        # degrades halve, exactly as a real single-host remainder
        # would)
        n = int(fake)
        per = len(devs) // n
        return [devs[i * per:(i + 1) * per] for i in range(n)]
    return [devs]


def cell_sharding(mesh: Mesh, ndim: int = 2,
                  axis_name: str = CELL_AXIS) -> NamedSharding:
    """Shard the leading (cell) axis; replicate the rest."""
    return NamedSharding(mesh, P(axis_name, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_celldata(data, mesh: Mesh):
    """Move a host CellData onto a mesh, cells sharded across devices.

    Pads rows to a multiple of the mesh size (times the sublane
    multiple) first so every device gets an equal block.
    """
    from ..config import config, round_up
    from ..data.dataset import CellData
    from ..data.sparse import SparseCells
    import scipy.sparse as sp

    n_dev = mesh.devices.size
    X = data.X
    if sp.issparse(X):
        X = SparseCells.from_scipy_csr(X)
    if isinstance(X, SparseCells):
        mult = n_dev * config.sublane
        X = X.pad_rows_to(round_up(X.rows_padded, mult))
        X = SparseCells(
            jax.device_put(jnp_asarray(X.indices), cell_sharding(mesh)),
            jax.device_put(jnp_asarray(X.data), cell_sharding(mesh)),
            X.n_cells, X.n_genes,
        )
    else:
        X = np.asarray(X)
        rows = round_up(X.shape[0], n_dev * config.sublane)
        if rows != X.shape[0]:
            X = np.pad(X, ((0, rows - X.shape[0]), (0, 0)))
        X = jax.device_put(X, cell_sharding(mesh))
    out = CellData(
        X, dict(data.obs), dict(data.var), dict(data.obsm),
        dict(data.varm), dict(data.obsp), dict(data.uns),
        dict(data.layers),  # carried host-side; shard on use
    )
    return out


def jnp_asarray(x):
    """``jnp.asarray`` that PRESERVES an existing committed sharding:
    a jax array already placed (sharded over a mesh, or pinned to a
    device) passes through untouched — re-wrapping it with
    ``jnp.asarray`` would re-place it on the default device, silently
    gathering a sharded operand before the very ``device_put`` that
    was about to shard it again (one extra full-array transfer per
    call)."""
    import jax.numpy as jnp

    if isinstance(x, jax.Array):
        return x
    return jnp.asarray(x)
