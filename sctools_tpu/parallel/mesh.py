"""Device-mesh utilities.

Multi-chip execution follows the standard JAX recipe (pick a mesh,
annotate shardings, let XLA insert collectives): cells are the batch
axis and shard across devices; genes stay replicated-contiguous so
per-gene reductions become single ``psum``-backed ``segment_sum``s.
The reference's NCCL/MPI communication backend maps onto XLA
collectives over ICI/DCN — nothing here opens sockets.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CELL_AXIS = "cells"


def make_mesh(n_devices: int | None = None, axis_name: str = CELL_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (all by default)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def cell_sharding(mesh: Mesh, ndim: int = 2,
                  axis_name: str = CELL_AXIS) -> NamedSharding:
    """Shard the leading (cell) axis; replicate the rest."""
    return NamedSharding(mesh, P(axis_name, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_celldata(data, mesh: Mesh):
    """Move a host CellData onto a mesh, cells sharded across devices.

    Pads rows to a multiple of the mesh size (times the sublane
    multiple) first so every device gets an equal block.
    """
    from ..config import config, round_up
    from ..data.dataset import CellData
    from ..data.sparse import SparseCells
    import scipy.sparse as sp

    n_dev = mesh.devices.size
    X = data.X
    if sp.issparse(X):
        X = SparseCells.from_scipy_csr(X)
    if isinstance(X, SparseCells):
        mult = n_dev * config.sublane
        X = X.pad_rows_to(round_up(X.rows_padded, mult))
        X = SparseCells(
            jax.device_put(jnp_asarray(X.indices), cell_sharding(mesh)),
            jax.device_put(jnp_asarray(X.data), cell_sharding(mesh)),
            X.n_cells, X.n_genes,
        )
    else:
        X = np.asarray(X)
        rows = round_up(X.shape[0], n_dev * config.sublane)
        if rows != X.shape[0]:
            X = np.pad(X, ((0, rows - X.shape[0]), (0, 0)))
        X = jax.device_put(X, cell_sharding(mesh))
    out = CellData(
        X, dict(data.obs), dict(data.var), dict(data.obsm),
        dict(data.varm), dict(data.obsp), dict(data.uns),
    )
    return out


def jnp_asarray(x):
    import jax.numpy as jnp

    return jnp.asarray(x)
