"""Multi-chip execution: device meshes, sharded pipelines, ring kNN.

Importing registers the multi-chip transforms."""

from . import knn_multichip  # noqa: F401  (registers transforms)
from .graph_multichip import (diffuse_sharded, knn_matvec_sharded,
                              smooth_layers_sharded)
from .knn_multichip import knn_multichip_arrays
from .mesh import CELL_AXIS, cell_sharding, make_mesh, replicated, shard_celldata

__all__ = [
    "CELL_AXIS", "make_mesh", "cell_sharding", "replicated",
    "shard_celldata", "knn_multichip_arrays",
    "knn_matvec_sharded", "smooth_layers_sharded", "diffuse_sharded",
]
