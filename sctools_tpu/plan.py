"""Execution plans: fuse runs of device transforms into single cached
jitted programs.

Why this layer exists: a registry ``Pipeline`` is a Python dispatch
loop — every step pays per-op dispatch, every eager jnp call inside a
step pays its own XLA launch, and every invocation of a recipe
re-traces nothing but still re-dispatches everything.  On the GPU
single-cell stacks this framework tracks (rapids-singlecell,
PAPERS.md) that per-op tax is the dominant cost of the preprocessing
hot path.  The plan layer removes it structurally:

* :func:`fused_pipeline` compiles a ``Pipeline`` into STAGES — maximal
  runs of consecutive transforms whose implementations declared
  themselves jit-traceable (``registry.register(..., fusable=...)``)
  become one :class:`FusedTransform`; everything else (host-only ops,
  data-dependent-shape materialisation points like
  ``hvg.select(subset=True)``, backend breaks) stays an eager step and
  forms a FUSION BREAK.  ``CellData`` stays device-resident across
  stage boundaries; transfers happen only at breaks.
* Each fused stage executes as ONE ``jax.jit`` program: intermediates
  between member ops never materialise (XLA reuses their buffers —
  the in-program form of buffer donation).  Donation of the stage's
  INPUT buffers is opt-in (``donate=True``) and never applied to the
  pipeline's first stage: CellData stages routinely alias buffers
  (``util.snapshot_layer`` shares X with ``layers['counts']``), so
  donating a caller-visible input could invalidate arrays the caller
  still holds.  The ResilientRunner path never donates — a retried
  attempt must be able to replay its input.
* Compiled programs live in a PROCESS-WIDE cache keyed by (op chain +
  params, input tree structure, traced leaf shapes/dtypes, opaque
  -leaf content, jax backend, donate flag): a second invocation of the
  same recipe on same-shaped data performs ZERO retraces
  (``plan.cache_hits`` / ``plan.cache_misses`` counters prove it).
* The layer composes with every cross-cutting hook.  A fused stage is
  called through the registry call-wrapper chain ONCE PER MEMBER OP:
  chaos faults targeting an op inside a fused stage still fire (and
  classify) on that op's name with unchanged Nth-call counting, the
  runner's cooperative deadline token is checked at stage boundaries,
  and telemetry's per-op call counters keep ticking (durations are
  attributed at stage granularity — the stage IS the dispatch unit).
  If tracing a stage fails (an op lied about fusability, or host
  values leak into control flow), the stage falls back to eager
  step-by-step execution with a warning and a ``plan.fallbacks``
  count — never a changed result.
* MESH-SHARDED STAGES: with a device mesh (``fused_pipeline(mesh=)``,
  or the mesh entered via ``with mesh:``) a fused stage compiles as
  ONE program ACROSS THE MESH — per-leaf ``in_shardings`` built from
  ``parallel.mesh.cell_sharding``/``replicated`` (an arriving
  committed sharding on the same mesh is honoured, so a stage whose
  producer already emitted matching shardings pays no reshard —
  ``plan.reshards_avoided`` counts the boundary crossings that stayed
  free), output leaves pinned by ``with_sharding_constraint`` under
  the same rule so CONSECUTIVE stages hand over pre-partitioned
  arrays (the pjit contract: outputs of one compiled stage match the
  next's in_shardings).  Member ops that registered a COLLECTIVE body
  (``register(..., collective=True)`` — the ppermute-ring kNN, the
  sharded graph matvec family) cannot be traced under GSPMD; they
  become a :class:`ShardedCollective` stage that threads the plan's
  mesh into the op call.  The cache key gains the mesh signature
  (axis names + shape + device ids) and the per-leaf PartitionSpecs:
  a REBUILT identical mesh is a hit (zero retraces on the second run
  of a sharded recipe), a different mesh is a miss
  (``plan.mesh_cache_misses`` splits those from shape misses).

>>> from sctools_tpu.plan import fused_pipeline
>>> fast = fused_pipeline(seurat_pipeline())
>>> out = fast.run(data.device_put())      # compiles fused stages
>>> out = fast.run(data.device_put())      # 100% plan-cache hit
"""

from __future__ import annotations

import threading
import warnings

import jax
import numpy as np

from . import memory as _mem_model
from . import registry as _registry
from .registry import Pipeline, Transform
from .utils import telemetry, trace

# ---------------------------------------------------------------------------
# The process-wide plan cache
# ---------------------------------------------------------------------------

_CACHE: dict = {}
_CACHE_LOCK = threading.RLock()
_FALLBACK = object()  # cache sentinel: this stage signature won't trace
#: per-entry debug metadata (ops, backend, mesh+shape signature),
#: written at insert under the same lock — cache_info()'s substrate
_CACHE_META: dict = {}
#: mesh-part index: base key (everything BUT the mesh) -> mesh parts
#: seen, so a miss can be attributed to a mesh change vs a new chain
_BY_BASE: dict = {}
#: process-lifetime hit/miss tallies (metric counters are per
#: MetricsRegistry; the debugging helper needs one process-wide view)
_STATS = {"hits": 0, "misses": 0, "mesh_misses": 0}


def plan_cache_stats() -> dict:
    """Cheap introspection: entry count and per-kind split of the
    process-wide plan cache."""
    with _CACHE_LOCK:
        vals = list(_CACHE.values())
    return {"entries": len(vals),
            "compiled": sum(1 for v in vals if v is not _FALLBACK),
            "fallback": sum(1 for v in vals if v is _FALLBACK)}


def cache_info() -> dict:
    """Debugging view of the process-wide plan cache: process-lifetime
    hit/miss tallies (``mesh_misses`` = misses attributable to a mesh
    change on an already-seen chain) and one record per entry — the op
    chain, backend, kind (compiled/fallback/sharded), traced leaf
    shapes and the mesh signature (axis names, shape, device ids) it
    was compiled against.  ``python -m tools.sctreport`` prints the
    counter-level view from ``metrics.json``; this helper is the
    in-process form with per-entry detail."""
    with _CACHE_LOCK:
        entries = []
        for key, val in _CACHE.items():
            meta = dict(_CACHE_META.get(key, {}))
            meta["kind"] = ("fallback" if val is _FALLBACK
                            else ("sharded" if meta.get("mesh")
                                  else "compiled"))
            entries.append(meta)
        stats = dict(_STATS)
    return {"n_entries": len(entries), "entries": entries, **stats}


def clear_plan_cache() -> None:
    """Drop every compiled plan (tests; or after a ``config`` change
    that alters traced behaviour — the cache key covers op chain,
    params, shapes and backend, not global config knobs)."""
    with _CACHE_LOCK:
        _CACHE.clear()
        _CACHE_META.clear()
        _BY_BASE.clear()
        _STATS.update(hits=0, misses=0, mesh_misses=0)


# ---------------------------------------------------------------------------
# CellData <-> (traced leaves, opaque leaves) splitting
# ---------------------------------------------------------------------------


def _is_traced_leaf(v) -> bool:
    if isinstance(v, jax.Array):
        return True
    return isinstance(v, np.ndarray) and v.dtype.kind in "biufc"


def _split(data):
    """Flatten a pytree into (traced numeric leaves, opaque host
    leaves, treedef, mask).  Opaque leaves — string/object arrays,
    python scalars, anything jit cannot trace — ride around the
    compiled program by value."""
    leaves, treedef = jax.tree_util.tree_flatten(data)
    mask = tuple(_is_traced_leaf(v) for v in leaves)
    traced = [v for v, m in zip(leaves, mask) if m]
    opaque = [v for v, m in zip(leaves, mask) if not m]
    return traced, opaque, treedef, mask


def _merge(traced, opaque, treedef, mask):
    it_t, it_o = iter(traced), iter(opaque)
    leaves = [next(it_t) if m else next(it_o) for m in mask]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _digest(payload: bytes) -> bytes:
    """16-byte content digest for array payloads in cache keys.  Keys
    must cover CONTENT (ops bake host values into traced constants)
    but must not RETAIN it: raw bytes in a process-wide cache key
    would pin megabyte gene-name arrays forever and re-hash them on
    every dict lookup — the digest costs one pass per call and the
    key stays 16 bytes."""
    import hashlib

    return hashlib.blake2b(payload, digest_size=16).digest()


def _opaque_token(v):
    """Hashable content token for an opaque leaf.  Opaque content must
    be part of the cache key: ops may READ it at trace time and bake
    the result into the program as a constant (``qc.per_cell_metrics``
    derives the mito mask from ``var['gene_name']`` strings)."""
    if v is None or isinstance(v, (bool, int, float, complex, str, bytes)):
        return ("v", type(v).__name__, v)
    if isinstance(v, np.ndarray):
        if v.dtype.kind == "O":
            return ("nd", "O", v.shape, _digest(repr(v.tolist()).encode()))
        return ("nd", str(v.dtype), v.shape, _digest(v.tobytes()))
    return ("r", type(v).__name__, repr(v))


def _freeze(v):
    """Hashable token for a bound parameter value (the op-chain part
    of the cache key)."""
    if isinstance(v, dict):
        return ("d",) + tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple, frozenset, set)):
        items = sorted(v, key=repr) if isinstance(v, (set, frozenset)) else v
        return (type(v).__name__,) + tuple(_freeze(x) for x in items)
    if isinstance(v, (np.ndarray, jax.Array)):
        a = np.asarray(v)
        return ("nd", str(a.dtype), a.shape,
                _digest(a.tobytes() if a.dtype.kind != "O"
                        else repr(a.tolist()).encode()))
    return v


# ---------------------------------------------------------------------------
# Mesh sharding decisions
# ---------------------------------------------------------------------------


def _pm():
    # parallel.mesh imported lazily: plan.py must stay importable
    # before the parallel package (and its transform registrations)
    from .parallel import mesh as pm

    return pm


def _rule_sharding(shape, mesh, n_dev: int, rule: str):
    """The sharding one leaf gets under a partitioning rule:
    ``"cells"`` shards the leading axis over the cell mesh axis when
    it divides the device count (row-padded CellData leaves — X, obs
    columns, obsm blocks), everything else replicates; ``"replicated"``
    replicates outright (per-gene reductions, uns scalars)."""
    pm = _pm()
    if (rule != "replicated" and len(shape) >= 1 and shape[0]
            and shape[0] % n_dev == 0):
        return pm.cell_sharding(mesh, ndim=max(len(shape), 1))
    return pm.replicated(mesh)


def _pick_in_sharding(v, mesh, sig, n_dev: int):
    """In-sharding for one traced input leaf: an arriving COMMITTED
    NamedSharding on the same mesh (by signature) is honoured — that
    leaf crosses the stage boundary with zero data movement, which is
    the whole no-reshard contract — anything else gets the "cells"
    rule."""
    s = getattr(v, "sharding", None)
    if (getattr(v, "committed", False) and s is not None
            and hasattr(s, "mesh") and hasattr(s, "spec")):
        try:
            if _pm().mesh_signature(s.mesh) == sig:
                return s
        except Exception:  # pragma: no cover - exotic sharding type
            pass
    return _rule_sharding(getattr(v, "shape", ()), mesh, n_dev, "cells")


def _aot_placement_refusal(e: BaseException) -> bool:
    """True for the ONE error the AOT ``Compiled.__call__`` raises
    that the dispatch path would have handled silently: an input
    committed to a device/sharding the executable was not compiled
    for (``jax.jit`` reshards it; the AOT call refuses).  Matched by
    message because jax raises a plain ValueError — which must NOT be
    confused with the trace-failure ValueErrors that rule a permanent
    eager fallback."""
    return (isinstance(e, ValueError)
            and "Compiled object called with input sharding" in str(e))


def _compiled_peak_bytes(compiled) -> int | None:
    """Peak device bytes an XLA executable declares for one
    invocation: arguments resident + outputs + the temp arena, minus
    input/output aliasing (donated buffers are not double-counted).
    ``None`` when the platform's executable exposes no analysis — the
    caller falls back to the ``mem_cost`` heuristic."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        return int(ma.argument_size_in_bytes + ma.output_size_in_bytes
                   + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
    except Exception:  # pragma: no cover - platform without analysis
        return None


class _StageProgram:
    """One compiled fused stage: the jitted callable plus the output
    reassembly spec captured at trace time.  ``out_map`` rebuilds the
    output's opaque leaves per call: ``("in", j)`` means the j-th
    input opaque leaf passed through by identity (the common case —
    gene names, uns scalars), ``("const", v)`` a value created during
    the trace."""

    __slots__ = ("jitted", "dispatch", "out_treedef", "out_mask",
                 "out_map")

    def __init__(self, jitted, out_treedef, out_mask, out_map,
                 dispatch=None):
        self.jitted = jitted
        #: the jax.jit dispatch form, kept ONLY while ``jitted`` is an
        #: AOT executable: a later call whose inputs arrive committed
        #: to another device/sharding is refused by the AOT call but
        #: re-placed by dispatch (see ``call``)
        self.dispatch = dispatch
        self.out_treedef = out_treedef
        self.out_mask = out_mask
        self.out_map = out_map

    def call(self, traced):
        try:
            return self.jitted(traced)
        except Exception as e:
            if not _aot_placement_refusal(e):
                raise
            # strictly-placed inputs the AOT executable refuses: the
            # dispatch path re-places them — and keeps serving this
            # entry from now on (one executable re-compile, once).
            # Swap order matters under concurrency: ``jitted`` is
            # written FIRST, so a racing caller that finds
            # ``dispatch`` already consumed retries ``self.jitted``
            # and gets the dispatch form the winner installed (a
            # never-AOT program cannot raise the refusal at all).
            dispatch = self.dispatch
            if dispatch is not None:
                self.jitted = dispatch
                self.dispatch = None
            return self.jitted(traced)

    def rebuild(self, out_traced, in_opaque):
        out_opaque = [in_opaque[j] if kind == "in" else v
                      for kind, j, v in self.out_map]
        return _merge(out_traced, out_opaque, self.out_treedef,
                      self.out_mask)


# ---------------------------------------------------------------------------
# FusedTransform — the Transform-alike a Pipeline can hold as one step
# ---------------------------------------------------------------------------


class FusedTransform:
    """A run of consecutive fusable transforms executed as ONE jitted
    program behind the process-wide plan cache.

    Quacks like :class:`registry.Transform` — ``name`` / ``backend`` /
    ``params`` / callable / ``with_backend`` — so everything built on
    Transforms (Pipeline iteration, ResilientRunner retry/checkpoint
    fingerprints, journal records) treats a fused stage as one
    retryable step.  ``params`` carries the member ``(name, params)``
    chain, so checkpoint fingerprints change when any member does.
    ``with_backend`` returns an UNFUSED sequential chain on the new
    backend — the degrade-to-cpu ruling falls back to the oracle path
    step by step, exactly as an unfused pipeline would.

    With ``mesh=`` the stage is MESH-SHARDED: it compiles with
    per-leaf ``in_shardings`` and sharding-constrained outputs and
    runs as one program across the mesh (module docstring).  The mesh
    signature joins ``params`` — checkpoint fingerprints for a
    sharded stage differ from the single-device form AND between
    meshes, so a resume across a mesh change recomputes.  ``replan``
    rebuilds the same member chain on fewer devices (``None`` →
    single-device) — the runner's mesh-shrink degrade rung.
    """

    def __init__(self, members, backend: str | None = None,
                 metrics=None, donate: bool = False, mesh=None):
        if not members:
            raise ValueError("FusedTransform needs at least one member")
        self.members = list(members)
        self.backend = backend or self.members[0].backend
        self.mesh = mesh
        prefix = "fused:" if mesh is None else "sharded:"
        self.name = prefix + "+".join(t.name for t in self.members)
        self.params = {"ops": [(t.name, dict(t.params))
                               for t in self.members]}
        if mesh is not None:
            self.params["mesh"] = _pm().mesh_signature(mesh)
        self.metrics = metrics
        self.donate = donate

    # -- Transform protocol -------------------------------------------
    def with_backend(self, backend: str):
        if backend == self.backend:
            return self
        return _UnfusedChain(
            [t.with_backend(backend) for t in self.members],
            backend, self.name, self.params)

    def unfuse(self):
        """The same member chain executed step by step on the SAME
        backend — the OOM containment ladder's FIRST rung.  One fused
        program holds every member's intermediates in one live set
        (plus XLA's temp arena for the whole chain); the unfused
        chain frees each member's intermediates before the next
        dispatches, trading the fusion win back for peak-memory
        headroom.  Results are identical; ``name``/``params`` are
        kept, so journal records and checkpoint fingerprints stay
        joined across the ruling."""
        return _UnfusedChain(list(self.members), self.backend,
                             self.name, self.params)

    def replan(self, n_devices: int | None, devices=None):
        """The same member chain planned for ``n_devices`` (``None``
        or ``<= 1`` → the plain single-device fused stage), or — the
        lost-host rung — for an EXPLICIT surviving-device list
        (``devices=``; not a prefix of ``jax.devices()``, so a count
        cannot express it).  Never donates: the caller is the
        runner's degrade ladder, and a re-planned attempt must be
        able to replay its input."""
        if devices is not None:
            mesh = (_pm().make_mesh(devices=list(devices))
                    if len(devices) > 1 else None)
        else:
            mesh = (_pm().make_mesh(n_devices)
                    if n_devices is not None and n_devices > 1
                    else None)
        return FusedTransform(self.members, self.backend,
                              metrics=self.metrics, donate=False,
                              mesh=mesh)

    def __repr__(self):
        return (f"FusedTransform([{', '.join(t.name for t in self.members)}]"
                f", backend={self.backend!r})")

    def __call__(self, data, **overrides):
        if overrides:
            raise TypeError(
                "FusedTransform takes no per-call overrides — member "
                "params are baked into the compiled program")
        fn = self._execute
        if _registry._active_wrappers():
            # one wrapper application PER MEMBER op (first member
            # outermost): chaos faults fnmatch member names and keep
            # their Nth-call counting, the deadline token is checked
            # at the stage boundary, telemetry counts each member call
            for t in reversed(self.members):
                fn = _registry._wrap_call(t.name, self.backend, fn)
        return fn(data)

    # -- execution -----------------------------------------------------
    def _metrics(self):
        return (self.metrics if self.metrics is not None
                else telemetry.default_registry())

    def _ensure_device(self, data):
        """Fused stages consume device-resident data; pack a host
        scipy X at the boundary (same adaptation the runner's
        ``_match_residency`` performs)."""
        X = getattr(data, "X", None)
        if X is None or not hasattr(data, "device_put"):
            return data
        import scipy.sparse as sp

        if sp.issparse(X):
            return data.device_put()
        return data

    def _ops_key(self):
        return tuple((t.name, t.backend, _freeze(dict(t.params)))
                     for t in self.members)

    def _run_eager(self, data):
        for t in self.members:
            data = t._fn(data, **t.params)
        return data

    def _out_rule(self) -> str:
        """Output partitioning rule for the stage: the LAST member's
        registered ``sharding=`` declaration (its outputs are what
        cross the boundary), default ``"cells"``."""
        t = self.members[-1]
        return (_registry.sharding_of(t.name, t.backend, t.params)
                or "cells")

    def _execute(self, data):
        m = self._metrics()
        data = self._ensure_device(data)
        traced, opaque, treedef, mask = _split(data)
        mesh = self.mesh
        # sharded stages never donate (a mesh re-plan after a failure
        # must replay the stage input), and donation is a cpu no-op
        donate = (bool(self.donate) and mesh is None
                  and jax.default_backend() != "cpu")
        in_shards = None
        mesh_part = None
        if mesh is not None:
            pm = _pm()
            sig = pm.mesh_signature(mesh)
            n_dev = int(mesh.devices.size)
            in_shards = [_pick_in_sharding(v, mesh, sig, n_dev)
                         for v in traced]
            # mesh shape + axis names + device ids + per-leaf
            # PartitionSpec: a rebuilt identical mesh hashes the same
            # (hit), any mesh/spec change is a miss
            mesh_part = ("mesh", sig,
                         tuple(str(s.spec) for s in in_shards))
        try:
            key = (self._ops_key(), treedef, mask,
                   tuple((tuple(v.shape), str(v.dtype)) for v in traced),
                   tuple(_opaque_token(v) for v in opaque),
                   jax.default_backend(), donate, mesh_part)
        except TypeError as e:
            # unhashable param/opaque content: this chain cannot be
            # cached — run it eagerly rather than retrace forever
            warnings.warn(
                f"plan: {self.name} has an unhashable cache key "
                f"({e}) — executing unfused", RuntimeWarning,
                stacklevel=2)
            m.counter("plan.fallbacks").inc()
            return self._run_eager(data)
        with _CACHE_LOCK:
            prog = _CACHE.get(key)
            if prog is not None and prog is not _FALLBACK:
                _STATS["hits"] += 1
        if prog is _FALLBACK:
            return self._run_eager(data)
        n_ops = len(self.members)
        if mesh is not None:
            m.counter("plan.sharded_stages").inc()
            # boundary crossings that stayed free: device leaves that
            # arrived already partitioned to the program's in_shardings
            matched = sum(
                1 for v, s in zip(traced, in_shards)
                if getattr(v, "committed", False)
                and getattr(v, "sharding", None) == s)
            if matched:
                m.counter("plan.reshards_avoided").inc(matched)
        with trace.span(f"plan:{self.name}",
                        meta={"backend": self.backend, "n_ops": n_ops,
                              "cached": prog is not None}):
            if prog is not None:
                m.counter("plan.cache_hits").inc()
                out_traced = prog.call(traced)
                m.counter("plan.fused_ops").inc(n_ops)
                return prog.rebuild(out_traced, opaque)
            # miss: trace + compile + execute in one first call
            m.counter("plan.cache_misses").inc()
            with _CACHE_LOCK:
                _STATS["misses"] += 1
                base = key[:-1]
                seen = _BY_BASE.setdefault(base, set())
                if mesh_part is not None and seen \
                        and mesh_part not in seen:
                    _STATS["mesh_misses"] += 1
                    m.counter("plan.mesh_cache_misses").inc()
                seen.add(mesh_part)
            box: dict = {}
            members = self.members
            out_rule = self._out_rule() if mesh is not None else None

            def fused(traced_in):
                d = _merge(traced_in, opaque, treedef, mask)
                for t in members:
                    d = t._fn(d, **t.params)
                out_traced, out_opaque, out_treedef, out_mask = _split(d)
                if mesh is not None:
                    # pin output partitioning so the NEXT sharded
                    # stage's in_shardings match what leaves here —
                    # the reshard-free boundary contract
                    n_dev = int(mesh.devices.size)
                    out_traced = [
                        jax.lax.with_sharding_constraint(
                            v, _rule_sharding(v.shape, mesh, n_dev,
                                              out_rule))
                        for v in out_traced]
                box["spec"] = (out_opaque, out_treedef, out_mask)
                return out_traced

            jit_kw: dict = {"donate_argnums": (0,) if donate else ()}
            if mesh is not None:
                jit_kw["in_shardings"] = (in_shards,)
            jitted = jax.jit(fused, **jit_kw)
            exec_fn = jitted
            peak_bytes = None
            try:
                if mesh is None:
                    # AOT lower → compile: ONE XLA compile serves both
                    # execution and the PEAK-MEMORY ESTIMATE the
                    # memory fault domain records per plan-cache entry
                    # (compiled.memory_analysis(); the dispatch path
                    # exposes no executable to ask).  Mesh-sharded
                    # stages keep the dispatch path — an AOT call
                    # refuses committed inputs arriving from another
                    # mesh where jit reshards them, so their entries
                    # carry the mem_cost heuristic instead.
                    compiled = jitted.lower(traced).compile()
                    peak_bytes = _compiled_peak_bytes(compiled)
                    try:
                        out_traced = compiled(traced)
                        exec_fn = compiled
                    except Exception as e:
                        # the AOT call validates input placement
                        # strictly (a ValueError that must NOT be
                        # mistaken for a trace failure); the dispatch
                        # path re-places — identical program, second
                        # compile accepted.  Everything else re-raises
                        # into the trace-failure ruling below.
                        if not _aot_placement_refusal(e):
                            raise
                        out_traced = jitted(traced)
                else:
                    out_traced = jitted(traced)
            except (jax.errors.JAXTypeError, TypeError, ValueError,
                    NotImplementedError) as e:
                # the chain does not trace (host sync / concretisation
                # inside a member, or a sharding the chain cannot
                # carry): permanent eager fallback for this signature,
                # identical results
                warnings.warn(
                    f"plan: tracing {self.name} failed "
                    f"({type(e).__name__}: {e}) — falling back to "
                    f"step-by-step execution for this input signature",
                    RuntimeWarning, stacklevel=2)
                m.counter("plan.fallbacks").inc()
                with _CACHE_LOCK:
                    _CACHE[key] = _FALLBACK
                    _CACHE_META[key] = self._cache_meta(traced)
                return self._run_eager(data)
            if peak_bytes is not None:
                # the learned estimate the admission layer consults:
                # keyed by (stage chain, input-size bucket), so a
                # rebuilt pipeline over same-bucket data reads the
                # compiled number instead of the mem_cost heuristic
                input_bytes = sum(int(v.nbytes) for v in traced)
                _mem_model.default_estimates().record(
                    _mem_model.step_sig(self, input_bytes),
                    peak_bytes, source="compiled")
            out_opaque, out_treedef, out_mask = box["spec"]
            opaque_pos = {id(v): j for j, v in enumerate(opaque)}
            out_map = tuple(
                ("in", opaque_pos[id(v)], None) if id(v) in opaque_pos
                else ("const", -1, v)
                for v in out_opaque)
            prog = _StageProgram(
                exec_fn, out_treedef, out_mask, out_map,
                dispatch=jitted if exec_fn is not jitted else None)
            with _CACHE_LOCK:
                _CACHE[key] = prog
                _CACHE_META[key] = self._cache_meta(traced, peak_bytes)
            m.counter("plan.fused_ops").inc(n_ops)
            return prog.rebuild(out_traced, opaque)

    def _cache_meta(self, traced, peak_bytes: int | None = None) -> dict:
        return {
            "ops": [t.name for t in self.members],
            "backend": self.backend,
            "shapes": [f"{tuple(v.shape)}:{v.dtype}" for v in traced],
            "mesh": (None if self.mesh is None
                     else self.params["mesh"]),
            "peak_bytes": peak_bytes,
        }


class _UnfusedChain:
    """``FusedTransform.with_backend`` result: the same member chain
    executed step by step on another backend (the degrade ruling's
    fallback form).  Keeps the fused step's ``name``/``params`` so
    journal records and checkpoint fingerprints stay joined."""

    def __init__(self, members, backend, name, params):
        self.members = list(members)
        self.backend = backend
        self.name = name
        self.params = params

    def with_backend(self, backend: str):
        if backend == self.backend:
            return self
        return _UnfusedChain(
            [t.with_backend(backend) for t in self.members],
            backend, self.name, self.params)

    def __call__(self, data, **overrides):
        if overrides:
            raise TypeError("fused steps take no per-call overrides")
        for t in self.members:
            data = t(data)  # Transform.__call__: wrappers per member
        return data

    def __repr__(self):
        return (f"_UnfusedChain([{', '.join(t.name for t in self.members)}]"
                f", backend={self.backend!r})")


class ShardedCollective:
    """A single member op with a registered COLLECTIVE body
    (``register(..., collective=True)`` — the ppermute-ring kNN, the
    sharded graph matvec family), executed as one sharded plan stage.

    These implementations carry their own ``shard_map`` body and
    manage their own compile cache (a jit keyed on the static mesh),
    so the plan layer's job is placement, not tracing: thread the
    plan's mesh into the call (``mesh=`` kwarg), present the stage as
    one Transform-alike retryable step whose ``params`` carry the
    mesh signature (checkpoint fingerprints differ between meshes),
    and count it as a sharded stage.  ``with_backend`` falls back to
    the plain registered op on the new backend (the cpu oracle path);
    ``replan`` rebuilds on a smaller mesh — the degrade rung."""

    def __init__(self, member: Transform, mesh, metrics=None):
        self.member = member
        self.mesh = mesh
        self.backend = member.backend
        self.name = "sharded:" + member.name
        self.params = {"ops": [(member.name, dict(member.params))],
                       "mesh": _pm().mesh_signature(mesh)}
        self.metrics = metrics

    @property
    def members(self):  # symmetry with FusedTransform (runner, tests)
        return [self.member]

    def with_backend(self, backend: str):
        if backend == self.backend:
            return self
        return Transform(self.member.name, backend=backend,
                         **self.member.params)

    def replan(self, n_devices: int | None, devices=None):
        """The same collective op planned for ``n_devices`` devices
        (``None``/``<=1`` → a 1-device mesh: the op's collective body
        still runs, with every collective a self-edge), or for an
        explicit surviving-device list (``devices=`` — the lost-host
        rung)."""
        if devices is not None:
            return ShardedCollective(
                self.member, _pm().make_mesh(devices=list(devices)),
                self.metrics)
        n = n_devices if n_devices is not None and n_devices >= 1 else 1
        return ShardedCollective(self.member, _pm().make_mesh(n),
                                 self.metrics)

    def __call__(self, data, **overrides):
        if overrides:
            raise TypeError(
                "ShardedCollective takes no per-call overrides — "
                "member params are part of the plan")
        fn = self._execute
        if _registry._active_wrappers():
            fn = _registry._wrap_call(self.member.name, self.backend, fn)
        return fn(data)

    def _execute(self, data):
        m = (self.metrics if self.metrics is not None
             else telemetry.default_registry())
        m.counter("plan.sharded_stages").inc()
        with trace.span(f"plan:{self.name}",
                        meta={"backend": self.backend, "n_ops": 1,
                              "mesh_devices":
                                  int(self.mesh.devices.size)}):
            return self.member._fn(data, mesh=self.mesh,
                                   **self.member.params)

    def __repr__(self):
        return (f"ShardedCollective({self.member.name!r}, "
                f"devices={int(self.mesh.devices.size)})")


# ---------------------------------------------------------------------------
# Pipeline compilation
# ---------------------------------------------------------------------------


def fused_pipeline(pipeline: Pipeline, backend: str | None = None,
                   *, no_fuse=(), min_run: int = 2,
                   donate: bool = False, metrics=None,
                   mesh=None) -> Pipeline:
    """Compile a :class:`Pipeline` into fused execution stages.

    Walks the step list and groups maximal runs of consecutive
    transforms that (a) share a backend, (b) registered as fusable for
    it (``registry.is_fusable``), and (c) are not named in
    ``no_fuse`` (the runner passes its ``isolate`` set — an isolated
    step must stay an individually-containable dispatch).  Runs of at
    least ``min_run`` become one :class:`FusedTransform` step; shorter
    runs and everything else stay eager steps (single eager ops
    already amortise their compiles through jax's own jit cache).

    ``mesh=`` (or a mesh entered via ``with mesh:`` —
    ``parallel.mesh.active_mesh``) makes every fused stage
    MESH-SHARDED: one program across the mesh with per-leaf
    in_shardings and sharding-constrained outputs (module docstring).
    Member ops that registered a collective body
    (``registry.is_collective``) become their own
    :class:`ShardedCollective` stage with the mesh threaded into the
    call — how the multichip kNN and the sharded graph tail land
    INSIDE plans instead of being hand-dispatched around them.

    ``donate=True`` lets stages past the pipeline's FIRST step donate
    their input buffers to the compiled program (device backends only;
    a no-op on CPU, never on sharded stages).  Leave it off — the
    default — whenever the caller, a checkpointing runner, or an
    aliasing op (``util.snapshot_layer``) may still hold references
    into a stage's input.  Returns a new Pipeline; the original is
    untouched.
    """
    if mesh is None:
        mesh = _pm().active_mesh()
    steps = []
    for t in pipeline.steps:
        if backend is not None and t.backend != backend:
            t = t.with_backend(backend)
        steps.append(t)
    no_fuse = frozenset(no_fuse)
    out: list = []
    run: list = []
    first_member_index = 0

    def flush():
        nonlocal first_member_index
        if len(run) >= min_run:
            out.append(FusedTransform(
                run, run[0].backend, metrics=metrics,
                donate=donate and first_member_index > 0,
                mesh=mesh))
        else:
            out.extend(run)
        run.clear()

    for i, t in enumerate(steps):
        if (mesh is not None and isinstance(t, Transform)
                and t.name not in no_fuse
                and _registry.is_collective(t.name, t.backend,
                                            t.params)):
            # collective body: its own sharded stage, mesh threaded in
            flush()
            out.append(ShardedCollective(t, mesh, metrics=metrics))
            continue
        fusable = (isinstance(t, Transform)
                   and t.name not in no_fuse
                   and _registry.is_fusable(t.name, t.backend, t.params))
        if fusable and run and run[-1].backend != t.backend:
            flush()
        if fusable:
            if not run:
                first_member_index = i
            run.append(t)
        else:
            flush()
            out.append(t)
    flush()
    return Pipeline(out)


def describe_plan(pipeline: Pipeline, backend: str | None = None,
                  **kw) -> str:
    """Human-readable stage map of what :func:`fused_pipeline` would
    compile — which ops fuse, where the breaks fall and why a break is
    a break (the first thing to look at when a recipe is slower than
    expected; docs/GUIDE.md "Making a recipe fast")."""
    compiled = fused_pipeline(pipeline, backend=backend, **kw)
    lines = []
    for i, t in enumerate(compiled.steps):
        if isinstance(t, ShardedCollective):
            lines.append(f"[{i:02d}] SHARDED collective "
                         f"({int(t.mesh.devices.size)} devices): "
                         f"{t.member.name}")
        elif isinstance(t, FusedTransform):
            over = ("" if t.mesh is None else
                    f", over {int(t.mesh.devices.size)} devices")
            lines.append(f"[{i:02d}] FUSED ({len(t.members)} ops, one "
                         f"program{over}): " +
                         " -> ".join(m.name for m in t.members))
        else:
            why = ("not registered fusable"
                   if not _registry.is_fusable(t.name, t.backend,
                                               t.params)
                   else "run too short / isolated")
            lines.append(f"[{i:02d}] eager: {t.name}  ({why})")
    return "\n".join(lines)
