"""Execution plans: fuse runs of device transforms into single cached
jitted programs.

Why this layer exists: a registry ``Pipeline`` is a Python dispatch
loop — every step pays per-op dispatch, every eager jnp call inside a
step pays its own XLA launch, and every invocation of a recipe
re-traces nothing but still re-dispatches everything.  On the GPU
single-cell stacks this framework tracks (rapids-singlecell,
PAPERS.md) that per-op tax is the dominant cost of the preprocessing
hot path.  The plan layer removes it structurally:

* :func:`fused_pipeline` compiles a ``Pipeline`` into STAGES — maximal
  runs of consecutive transforms whose implementations declared
  themselves jit-traceable (``registry.register(..., fusable=...)``)
  become one :class:`FusedTransform`; everything else (host-only ops,
  data-dependent-shape materialisation points like
  ``hvg.select(subset=True)``, backend breaks) stays an eager step and
  forms a FUSION BREAK.  ``CellData`` stays device-resident across
  stage boundaries; transfers happen only at breaks.
* Each fused stage executes as ONE ``jax.jit`` program: intermediates
  between member ops never materialise (XLA reuses their buffers —
  the in-program form of buffer donation).  Donation of the stage's
  INPUT buffers is opt-in (``donate=True``) and never applied to the
  pipeline's first stage: CellData stages routinely alias buffers
  (``util.snapshot_layer`` shares X with ``layers['counts']``), so
  donating a caller-visible input could invalidate arrays the caller
  still holds.  The ResilientRunner path never donates — a retried
  attempt must be able to replay its input.
* Compiled programs live in a PROCESS-WIDE cache keyed by (op chain +
  params, input tree structure, traced leaf shapes/dtypes, opaque
  -leaf content, jax backend, donate flag): a second invocation of the
  same recipe on same-shaped data performs ZERO retraces
  (``plan.cache_hits`` / ``plan.cache_misses`` counters prove it).
* The layer composes with every cross-cutting hook.  A fused stage is
  called through the registry call-wrapper chain ONCE PER MEMBER OP:
  chaos faults targeting an op inside a fused stage still fire (and
  classify) on that op's name with unchanged Nth-call counting, the
  runner's cooperative deadline token is checked at stage boundaries,
  and telemetry's per-op call counters keep ticking (durations are
  attributed at stage granularity — the stage IS the dispatch unit).
  If tracing a stage fails (an op lied about fusability, or host
  values leak into control flow), the stage falls back to eager
  step-by-step execution with a warning and a ``plan.fallbacks``
  count — never a changed result.

>>> from sctools_tpu.plan import fused_pipeline
>>> fast = fused_pipeline(seurat_pipeline())
>>> out = fast.run(data.device_put())      # compiles fused stages
>>> out = fast.run(data.device_put())      # 100% plan-cache hit
"""

from __future__ import annotations

import threading
import warnings

import jax
import numpy as np

from . import registry as _registry
from .registry import Pipeline, Transform
from .utils import telemetry, trace

# ---------------------------------------------------------------------------
# The process-wide plan cache
# ---------------------------------------------------------------------------

_CACHE: dict = {}
_CACHE_LOCK = threading.RLock()
_FALLBACK = object()  # cache sentinel: this stage signature won't trace


def plan_cache_stats() -> dict:
    """Cheap introspection: entry count and per-kind split of the
    process-wide plan cache."""
    with _CACHE_LOCK:
        vals = list(_CACHE.values())
    return {"entries": len(vals),
            "compiled": sum(1 for v in vals if v is not _FALLBACK),
            "fallback": sum(1 for v in vals if v is _FALLBACK)}


def clear_plan_cache() -> None:
    """Drop every compiled plan (tests; or after a ``config`` change
    that alters traced behaviour — the cache key covers op chain,
    params, shapes and backend, not global config knobs)."""
    with _CACHE_LOCK:
        _CACHE.clear()


# ---------------------------------------------------------------------------
# CellData <-> (traced leaves, opaque leaves) splitting
# ---------------------------------------------------------------------------


def _is_traced_leaf(v) -> bool:
    if isinstance(v, jax.Array):
        return True
    return isinstance(v, np.ndarray) and v.dtype.kind in "biufc"


def _split(data):
    """Flatten a pytree into (traced numeric leaves, opaque host
    leaves, treedef, mask).  Opaque leaves — string/object arrays,
    python scalars, anything jit cannot trace — ride around the
    compiled program by value."""
    leaves, treedef = jax.tree_util.tree_flatten(data)
    mask = tuple(_is_traced_leaf(v) for v in leaves)
    traced = [v for v, m in zip(leaves, mask) if m]
    opaque = [v for v, m in zip(leaves, mask) if not m]
    return traced, opaque, treedef, mask


def _merge(traced, opaque, treedef, mask):
    it_t, it_o = iter(traced), iter(opaque)
    leaves = [next(it_t) if m else next(it_o) for m in mask]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _digest(payload: bytes) -> bytes:
    """16-byte content digest for array payloads in cache keys.  Keys
    must cover CONTENT (ops bake host values into traced constants)
    but must not RETAIN it: raw bytes in a process-wide cache key
    would pin megabyte gene-name arrays forever and re-hash them on
    every dict lookup — the digest costs one pass per call and the
    key stays 16 bytes."""
    import hashlib

    return hashlib.blake2b(payload, digest_size=16).digest()


def _opaque_token(v):
    """Hashable content token for an opaque leaf.  Opaque content must
    be part of the cache key: ops may READ it at trace time and bake
    the result into the program as a constant (``qc.per_cell_metrics``
    derives the mito mask from ``var['gene_name']`` strings)."""
    if v is None or isinstance(v, (bool, int, float, complex, str, bytes)):
        return ("v", type(v).__name__, v)
    if isinstance(v, np.ndarray):
        if v.dtype.kind == "O":
            return ("nd", "O", v.shape, _digest(repr(v.tolist()).encode()))
        return ("nd", str(v.dtype), v.shape, _digest(v.tobytes()))
    return ("r", type(v).__name__, repr(v))


def _freeze(v):
    """Hashable token for a bound parameter value (the op-chain part
    of the cache key)."""
    if isinstance(v, dict):
        return ("d",) + tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple, frozenset, set)):
        items = sorted(v, key=repr) if isinstance(v, (set, frozenset)) else v
        return (type(v).__name__,) + tuple(_freeze(x) for x in items)
    if isinstance(v, (np.ndarray, jax.Array)):
        a = np.asarray(v)
        return ("nd", str(a.dtype), a.shape,
                _digest(a.tobytes() if a.dtype.kind != "O"
                        else repr(a.tolist()).encode()))
    return v


class _StageProgram:
    """One compiled fused stage: the jitted callable plus the output
    reassembly spec captured at trace time.  ``out_map`` rebuilds the
    output's opaque leaves per call: ``("in", j)`` means the j-th
    input opaque leaf passed through by identity (the common case —
    gene names, uns scalars), ``("const", v)`` a value created during
    the trace."""

    __slots__ = ("jitted", "out_treedef", "out_mask", "out_map")

    def __init__(self, jitted, out_treedef, out_mask, out_map):
        self.jitted = jitted
        self.out_treedef = out_treedef
        self.out_mask = out_mask
        self.out_map = out_map

    def rebuild(self, out_traced, in_opaque):
        out_opaque = [in_opaque[j] if kind == "in" else v
                      for kind, j, v in self.out_map]
        return _merge(out_traced, out_opaque, self.out_treedef,
                      self.out_mask)


# ---------------------------------------------------------------------------
# FusedTransform — the Transform-alike a Pipeline can hold as one step
# ---------------------------------------------------------------------------


class FusedTransform:
    """A run of consecutive fusable transforms executed as ONE jitted
    program behind the process-wide plan cache.

    Quacks like :class:`registry.Transform` — ``name`` / ``backend`` /
    ``params`` / callable / ``with_backend`` — so everything built on
    Transforms (Pipeline iteration, ResilientRunner retry/checkpoint
    fingerprints, journal records) treats a fused stage as one
    retryable step.  ``params`` carries the member ``(name, params)``
    chain, so checkpoint fingerprints change when any member does.
    ``with_backend`` returns an UNFUSED sequential chain on the new
    backend — the degrade-to-cpu ruling falls back to the oracle path
    step by step, exactly as an unfused pipeline would.
    """

    def __init__(self, members, backend: str | None = None,
                 metrics=None, donate: bool = False):
        if not members:
            raise ValueError("FusedTransform needs at least one member")
        self.members = list(members)
        self.backend = backend or self.members[0].backend
        self.name = "fused:" + "+".join(t.name for t in self.members)
        self.params = {"ops": [(t.name, dict(t.params))
                               for t in self.members]}
        self.metrics = metrics
        self.donate = donate

    # -- Transform protocol -------------------------------------------
    def with_backend(self, backend: str):
        if backend == self.backend:
            return self
        return _UnfusedChain(
            [t.with_backend(backend) for t in self.members],
            backend, self.name, self.params)

    def __repr__(self):
        return (f"FusedTransform([{', '.join(t.name for t in self.members)}]"
                f", backend={self.backend!r})")

    def __call__(self, data, **overrides):
        if overrides:
            raise TypeError(
                "FusedTransform takes no per-call overrides — member "
                "params are baked into the compiled program")
        fn = self._execute
        if _registry._CALL_WRAPPERS:
            # one wrapper application PER MEMBER op (first member
            # outermost): chaos faults fnmatch member names and keep
            # their Nth-call counting, the deadline token is checked
            # at the stage boundary, telemetry counts each member call
            for t in reversed(self.members):
                fn = _registry._wrap_call(t.name, self.backend, fn)
        return fn(data)

    # -- execution -----------------------------------------------------
    def _metrics(self):
        return (self.metrics if self.metrics is not None
                else telemetry.default_registry())

    def _ensure_device(self, data):
        """Fused stages consume device-resident data; pack a host
        scipy X at the boundary (same adaptation the runner's
        ``_match_residency`` performs)."""
        X = getattr(data, "X", None)
        if X is None or not hasattr(data, "device_put"):
            return data
        import scipy.sparse as sp

        if sp.issparse(X):
            return data.device_put()
        return data

    def _ops_key(self):
        return tuple((t.name, t.backend, _freeze(dict(t.params)))
                     for t in self.members)

    def _run_eager(self, data):
        for t in self.members:
            data = t._fn(data, **t.params)
        return data

    def _execute(self, data):
        m = self._metrics()
        data = self._ensure_device(data)
        traced, opaque, treedef, mask = _split(data)
        donate = bool(self.donate) and jax.default_backend() != "cpu"
        try:
            key = (self._ops_key(), treedef, mask,
                   tuple((tuple(v.shape), str(v.dtype)) for v in traced),
                   tuple(_opaque_token(v) for v in opaque),
                   jax.default_backend(), donate)
        except TypeError as e:
            # unhashable param/opaque content: this chain cannot be
            # cached — run it eagerly rather than retrace forever
            warnings.warn(
                f"plan: {self.name} has an unhashable cache key "
                f"({e}) — executing unfused", RuntimeWarning,
                stacklevel=2)
            m.counter("plan.fallbacks").inc()
            return self._run_eager(data)
        with _CACHE_LOCK:
            prog = _CACHE.get(key)
        if prog is _FALLBACK:
            return self._run_eager(data)
        n_ops = len(self.members)
        with trace.span(f"plan:{self.name}",
                        meta={"backend": self.backend, "n_ops": n_ops,
                              "cached": prog is not None}):
            if prog is not None:
                m.counter("plan.cache_hits").inc()
                out_traced = prog.jitted(traced)
                m.counter("plan.fused_ops").inc(n_ops)
                return prog.rebuild(out_traced, opaque)
            # miss: trace + compile + execute in one first call
            m.counter("plan.cache_misses").inc()
            box: dict = {}
            members = self.members

            def fused(traced_in):
                d = _merge(traced_in, opaque, treedef, mask)
                for t in members:
                    d = t._fn(d, **t.params)
                out_traced, out_opaque, out_treedef, out_mask = _split(d)
                box["spec"] = (out_opaque, out_treedef, out_mask)
                return out_traced

            jitted = jax.jit(fused,
                             donate_argnums=(0,) if donate else ())
            try:
                out_traced = jitted(traced)
            except (jax.errors.JAXTypeError, TypeError,
                    NotImplementedError) as e:
                # the chain does not trace (host sync / concretisation
                # inside a member): permanent eager fallback for this
                # signature, identical results
                warnings.warn(
                    f"plan: tracing {self.name} failed "
                    f"({type(e).__name__}: {e}) — falling back to "
                    f"step-by-step execution for this input signature",
                    RuntimeWarning, stacklevel=2)
                m.counter("plan.fallbacks").inc()
                with _CACHE_LOCK:
                    _CACHE[key] = _FALLBACK
                return self._run_eager(data)
            out_opaque, out_treedef, out_mask = box["spec"]
            opaque_pos = {id(v): j for j, v in enumerate(opaque)}
            out_map = tuple(
                ("in", opaque_pos[id(v)], None) if id(v) in opaque_pos
                else ("const", -1, v)
                for v in out_opaque)
            prog = _StageProgram(jitted, out_treedef, out_mask, out_map)
            with _CACHE_LOCK:
                _CACHE[key] = prog
            m.counter("plan.fused_ops").inc(n_ops)
            return prog.rebuild(out_traced, opaque)


class _UnfusedChain:
    """``FusedTransform.with_backend`` result: the same member chain
    executed step by step on another backend (the degrade ruling's
    fallback form).  Keeps the fused step's ``name``/``params`` so
    journal records and checkpoint fingerprints stay joined."""

    def __init__(self, members, backend, name, params):
        self.members = list(members)
        self.backend = backend
        self.name = name
        self.params = params

    def with_backend(self, backend: str):
        if backend == self.backend:
            return self
        return _UnfusedChain(
            [t.with_backend(backend) for t in self.members],
            backend, self.name, self.params)

    def __call__(self, data, **overrides):
        if overrides:
            raise TypeError("fused steps take no per-call overrides")
        for t in self.members:
            data = t(data)  # Transform.__call__: wrappers per member
        return data

    def __repr__(self):
        return (f"_UnfusedChain([{', '.join(t.name for t in self.members)}]"
                f", backend={self.backend!r})")


# ---------------------------------------------------------------------------
# Pipeline compilation
# ---------------------------------------------------------------------------


def fused_pipeline(pipeline: Pipeline, backend: str | None = None,
                   *, no_fuse=(), min_run: int = 2,
                   donate: bool = False, metrics=None) -> Pipeline:
    """Compile a :class:`Pipeline` into fused execution stages.

    Walks the step list and groups maximal runs of consecutive
    transforms that (a) share a backend, (b) registered as fusable for
    it (``registry.is_fusable``), and (c) are not named in
    ``no_fuse`` (the runner passes its ``isolate`` set — an isolated
    step must stay an individually-containable dispatch).  Runs of at
    least ``min_run`` become one :class:`FusedTransform` step; shorter
    runs and everything else stay eager steps (single eager ops
    already amortise their compiles through jax's own jit cache).

    ``donate=True`` lets stages past the pipeline's FIRST step donate
    their input buffers to the compiled program (device backends only;
    a no-op on CPU).  Leave it off — the default — whenever the
    caller, a checkpointing runner, or an aliasing op
    (``util.snapshot_layer``) may still hold references into a stage's
    input.  Returns a new Pipeline; the original is untouched.
    """
    steps = []
    for t in pipeline.steps:
        if backend is not None and t.backend != backend:
            t = t.with_backend(backend)
        steps.append(t)
    no_fuse = frozenset(no_fuse)
    out: list = []
    run: list = []
    first_member_index = 0

    def flush():
        nonlocal first_member_index
        if len(run) >= min_run:
            out.append(FusedTransform(
                run, run[0].backend, metrics=metrics,
                donate=donate and first_member_index > 0))
        else:
            out.extend(run)
        run.clear()

    for i, t in enumerate(steps):
        fusable = (isinstance(t, Transform)
                   and t.name not in no_fuse
                   and _registry.is_fusable(t.name, t.backend, t.params))
        if fusable and run and run[-1].backend != t.backend:
            flush()
        if fusable:
            if not run:
                first_member_index = i
            run.append(t)
        else:
            flush()
            out.append(t)
    flush()
    return Pipeline(out)


def describe_plan(pipeline: Pipeline, backend: str | None = None,
                  **kw) -> str:
    """Human-readable stage map of what :func:`fused_pipeline` would
    compile — which ops fuse, where the breaks fall and why a break is
    a break (the first thing to look at when a recipe is slower than
    expected; docs/GUIDE.md "Making a recipe fast")."""
    compiled = fused_pipeline(pipeline, backend=backend, **kw)
    lines = []
    for i, t in enumerate(compiled.steps):
        if isinstance(t, FusedTransform):
            lines.append(f"[{i:02d}] FUSED ({len(t.members)} ops, one "
                         f"program): " +
                         " -> ".join(m.name for m in t.members))
        else:
            why = ("not registered fusable"
                   if not _registry.is_fusable(t.name, t.backend,
                                               t.params)
                   else "run too short / isolated")
            lines.append(f"[{i:02d}] eager: {t.name}  ({why})")
    return "\n".join(lines)
