"""Tabular accessors (scanpy's ``sc.get`` namespace) — exposed as
``sct.get.obs_df`` etc. via the callable namespace in ``__init__``
(``sct.get("op", backend=...)`` remains the registry lookup).

No pandas dependency is assumed by the core package, so "DataFrame"
here means a plain ``dict[str, np.ndarray]`` of aligned columns —
``pandas.DataFrame(result)`` turns any of these into the real thing
when pandas is around.
"""

from __future__ import annotations

import numpy as np

from .data.dataset import CellData


def rank_genes_groups_df(data: CellData, group: str,
                         key: str = "rank_genes_groups") -> dict:
    """scanpy ``get.rank_genes_groups_df``: one group's ranking as
    aligned columns (names, scores, pvals, pvals_adj, logfoldchanges,
    and pct_nz_group/pct_nz_reference when ``pts=True`` was used)."""
    if key not in data.uns:
        raise KeyError(f"get.rank_genes_groups_df: uns has no {key!r} "
                       f"— run de.rank_genes_groups first")
    res = data.uns[key]
    groups = [str(g) for g in res["groups"]]
    if str(group) not in groups:
        raise ValueError(f"group {group!r} not in {groups}")
    gi = groups.index(str(group))
    out = {
        "names": np.asarray(res["names"][gi]),
        "scores": np.asarray(res["scores"][gi]),
        "pvals": np.asarray(res["pvals"][gi]),
        "pvals_adj": np.asarray(res["pvals_adj"][gi]),
        "logfoldchanges": np.asarray(res["logfoldchanges"][gi]),
    }
    if "pts" in res:
        # pts is stored unsorted by gene id; align to the ranked order
        idx = np.asarray(res["indices"][gi])
        out["pct_nz_group"] = np.asarray(res["pts"][gi])[idx]
        out["pct_nz_reference"] = np.asarray(res["pts_rest"][gi])[idx]
    return out


def obs_df(data: CellData, keys) -> dict:
    """scanpy ``get.obs_df``: per-cell columns by name — obs columns,
    gene names (expression pulled from X), or ``obsm`` columns given
    as ``(obsm_key, column_index)`` tuples."""
    out = {}
    for k in keys:
        if isinstance(k, tuple):
            m, j = k
            out[f"{m}-{j}"] = np.asarray(data.obsm[m])[: data.n_cells, j]
        else:
            out[str(k)] = data.obs_vector(k)
    return out


def var_df(data: CellData, keys) -> dict:
    """scanpy ``get.var_df``: per-gene columns by name — var columns
    or cell ids (int index: that cell's expression across genes)."""
    out = {}
    for k in keys:
        if isinstance(k, (int, np.integer)):
            out[f"cell{int(k)}"] = data.var_vector(int(k))
        else:
            out[str(k)] = data.var_vector(k)
    return out
