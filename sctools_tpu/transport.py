"""The network as a fault domain: message transports for federation.

Every cross-process notification in the stack — worker heartbeats,
ticket-commit doorbells, federated breaker transitions — is a
MESSAGE, and until now every message rode one of two implicit
transports: a worker's stderr pipe (the ``[fed]`` line protocol) or
the shared filesystem (breaker state files).  This module names the
seam: a :class:`Transport` delivers ``(kind, fields)`` messages to a
named peer, and the callers' contracts are written against the seam,
not the medium.

Two implementations:

* :class:`FileTransport` — the existing behaviour, refactored behind
  the seam: one protocol line per message on a byte stream (the
  worker's stderr), parsed by the supervisor's pump thread.  Loss
  semantics unchanged: a mangled line is worker noise, and the
  durable artifact (result file, breaker state file) remains the
  commit of record.
* :class:`SocketTransport` — length-prefixed JSON frames over TCP on
  localhost: per-peer sequence numbers for at-most-once delivery
  (duplicates are acked but never re-delivered), bounded send/ack
  timeouts, seeded-jitter retry/backoff (the runner's
  :class:`~sctools_tpu.runner.RetryPolicy` schedule on the
  injectable clock), and per-peer partition tracking.

The headline invariant is GRACEFUL DEGRADATION, not delivery: a
``send`` that exhausts its retries returns ``False`` and journals
``net_gave_up`` — it never raises, never blocks unboundedly, and the
caller's existing ladder takes over (a lost beat is healed by the
next beat; a lost ``done`` doorbell by the supervisor's result-file
probe; an unreachable breaker sharer by LOCAL-ONLY decisions until
the partition heals and epochs reconcile).  The first gave-up
against a previously-reachable peer journals ``net_partition_entered``;
the next successful delivery journals ``net_rejoin`` and fires the
``on_rejoin`` hook (the breaker registry re-syncs its state there,
epoch-max wins — the no-split-brain proof sctreport's ``-- network --``
section joins on).

Chaos: every send attempt consults :meth:`ChaosMonkey.on_network`
(``net_drop`` / ``net_delay`` / ``net_dup`` / ``net_partition``,
windows specced ``"<peer>@net"``).  The faults are ruled BEFORE the
real socket is touched, so a partition soak burns no real timeouts:
drop/partition fail the attempt instantly, delay advances the
injectable clock, dup puts the frame on the wire twice and the
receiver's sequence dedup proves at-most-once.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import re
import socket
import struct
import sys
import threading

from .runner import RetryPolicy
from .utils.failsafe import classify_error
from .utils.vclock import SYSTEM_CLOCK

# ---------------------------------------------------------------------------
# The line codec (the FileTransport wire format)
# ---------------------------------------------------------------------------

#: one protocol line per message on the byte stream.  Anything not
#: matching is peer noise (jax logging etc.) and deliberately does
#: NOT count as a message — only explicit protocol lines carry state.
LINE_RE = re.compile(r"^\[fed\] ([a-z_]+)((?: [a-z_]+=\S+)*)\s*$")


def parse_fields(raw: str) -> dict:
    """Decode the ``k=v`` tail of a protocol line."""
    out = {}
    for part in raw.split():
        k, _, v = part.partition("=")
        out[k] = v
    return out


def encode_line(kind: str, **fields) -> str:
    """One protocol line (newline-terminated) for ``kind``/fields."""
    kv = " ".join(f"{k}={v}" for k, v in fields.items())
    return f"[fed] {kind}{(' ' + kv) if kv else ''}\n"


def decode_line(line: str) -> tuple[str, dict] | None:
    """Parse one stream line; ``None`` for non-protocol noise."""
    m = LINE_RE.match(line.strip())
    if m is None:
        return None
    return m.group(1), parse_fields(m.group(2))


# ---------------------------------------------------------------------------
# The transport seam
# ---------------------------------------------------------------------------

#: LOSSY frame kinds: periodic signals whose next emission supersedes
#: a lost one — a heartbeat, worker noise, and the ``obs`` telemetry
#: delta frames the fleet observability plane ships.  ``send`` gives
#: these ZERO retries by default: re-delivering a stale beat or a
#: stale metrics delta is worse than dropping it (the next one
#: carries fresher state), and the obs plane in particular must never
#: block a worker's heartbeat cadence behind a retry schedule.  A
#: dropped lossy frame still degrades per the net ladder (it counts a
#: gave-up and can open/heal a partition window) — it just is not
#: fought for.
LOSSY_KINDS = frozenset({"beat", "noise", "obs"})


class Transport:
    """Delivers ``(kind, fields)`` messages to named peers.

    ``send`` is best-effort with bounded latency: ``True`` means the
    message reached the peer (or, for stream transports, the stream),
    ``False`` means delivery was abandoned and the caller's
    degradation ladder owns recovery.  A transport never raises out
    of ``send`` and never blocks past its configured timeouts."""

    name = ""

    def send(self, peer: str, kind: str, retries: int | None = None,
             **fields) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def stats(self) -> dict:
        return {}


class FileTransport(Transport):
    """The shared-filesystem-era message plane, behind the seam: one
    protocol line per message on a byte stream (default: this
    process's stderr, read by the federation supervisor's per-worker
    pump thread).  The stream IS the peer — ``peer`` is accepted for
    interface parity and ignored.

    Loss semantics are the stream's: a line mangled in transit is
    dropped by the reader as noise, which is exactly why the durable
    artifacts (result files, breaker state files) stay the commit of
    record and this plane stays a doorbell."""

    def __init__(self, name: str = "", stream=None):
        self.name = name
        self._stream = stream
        # serializes emission across caller threads (heartbeat thread
        # + main loop): ``print`` issues SEPARATE write calls for the
        # text and the newline, so two threads could interleave
        # mid-line — and the supervisor pump drops unparseable lines
        # as noise, which for a ``done`` line meant a ticket stuck
        # in_flight on a healthy worker forever (caught by the chaos
        # soak; the result-file recovery probe is the belt to this
        # brace)
        self._lock = threading.Lock()
        self._sent = 0

    def send(self, peer: str, kind: str, retries: int | None = None,
             **fields) -> bool:
        line = encode_line(kind, **fields)
        stream = self._stream if self._stream is not None else sys.stderr
        with self._lock:
            # sanctioned write-under-lock: this lock exists solely to
            # make the line+flush atomic against the caller's other
            # threads; it guards nothing else
            try:
                stream.write(line)  # sctlint: disable=SCT011
                stream.flush()  # sctlint: disable=SCT011
            except (OSError, ValueError):
                return False  # stream gone (teardown): the ladder owns it
            self._sent += 1
        return True

    def stats(self) -> dict:
        with self._lock:
            return {"sent": self._sent}


def _frame(obj: dict) -> bytes:
    blob = json.dumps(obj, separators=(",", ":")).encode()
    return struct.pack(">I", len(blob)) + blob


def _recv_exact(conn: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None  # peer closed mid-frame
        buf += chunk
    return buf


def _read_frame(conn: socket.socket) -> dict | None:
    head = _recv_exact(conn, 4)
    if head is None:
        return None
    (size,) = struct.unpack(">I", head)
    if size > 1 << 22:  # 4 MiB: a notification plane, not a data plane
        return None
    body = _recv_exact(conn, size)
    if body is None:
        return None
    try:
        obj = json.loads(body)
    except ValueError:
        return None
    return obj if isinstance(obj, dict) else None


class SocketTransport(Transport):
    """Length-prefixed JSON frames over TCP on localhost.

    Frames carry ``{v, from, inst, seq, kind, fields}``; the receiver
    acks every frame with ``{ack: seq}`` on the same connection and
    delivers each ``(from, inst, seq)`` at most once — ``inst`` is a
    per-process incarnation tag, so a respawned worker restarting its
    sequence numbers is a NEW sender, never a replay.  ``send`` is
    synchronous per peer (a per-peer lock serializes frames in
    sequence order): write the frame, wait for the matching ack under
    ``ack_timeout_s``, and on failure retry up to ``retries`` times
    with the :class:`~sctools_tpu.runner.RetryPolicy` seeded-jitter
    schedule on the injectable ``clock``.  Real socket errors are
    classified through the ``failsafe`` taxonomy and recorded on the
    retry/gave-up journal records.

    Telemetry (the ``JOURNAL_PROTOCOLS['transport']`` contract):
    every message terminals exactly once — ``net_sent`` (delivered +
    acked) or ``net_gave_up`` (abandoned; the caller degrades) — with
    ``net_retry`` records in between; the first gave-up against a
    reachable-until-now peer journals ``net_partition_entered``, the
    next delivery ``net_rejoin`` (and fires ``on_rejoin(peer)``, the
    breaker registry's epoch-reconcile hook).  ``net.rtt_ms``
    observes send-to-ack latency, ``net.retries`` counts re-issued
    attempts.
    """

    def __init__(self, name: str, *, clock=None, journal=None,
                 metrics=None, chaos=None, host: str = "127.0.0.1",
                 ack_timeout_s: float = 5.0, retries: int = 3,
                 backoff: RetryPolicy | None = None, seed: int = 0,
                 on_message=None, on_rejoin=None):
        self.name = name
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.journal = journal
        self.metrics = metrics
        self.chaos = chaos
        self.ack_timeout_s = float(ack_timeout_s)
        self.retries = int(retries)
        self.backoff = backoff if backoff is not None else RetryPolicy(
            base_delay_s=0.05, max_delay_s=1.0, jitter=0.5, seed=seed)
        self.seed = int(seed)
        #: ``on_message(from_name, kind, fields)`` — called on a
        #: receiver thread for every first-time delivery
        self.on_message = on_message
        #: ``on_rejoin(peer)`` — called (off the sender's thread of
        #: control, but synchronously within ``send``) when a
        #: partitioned peer becomes reachable again
        self.on_rejoin = on_rejoin
        #: per-process incarnation tag: a restarted sender must never
        #: look like a replay of its predecessor's sequence numbers
        self._inst = f"{os.getpid()}.{id(self):x}"
        self._lock = threading.Lock()
        self._peers: dict[str, tuple[str, int]] = {}
        self._conns: dict[str, socket.socket] = {}
        # one lock per peer: frames toward a peer must hit the wire
        # in sequence order (the receiver's at-most-once dedup drops
        # seq <= last-seen, so an out-of-order retry would be acked
        # and silently lost) — but two different peers' exchanges
        # never serialize against each other
        self._peer_locks: dict[str, threading.Lock] = {}
        self._send_seq: dict[str, int] = {}
        self._recv_seq: dict[tuple[str, str], int] = {}
        self._partitioned: set[str] = set()
        self._counts: dict[str, dict] = {}
        self._closed = False
        self._threads: list[threading.Thread] = []
        self._listener = socket.create_server((host, 0))
        self.host, self.port = self._listener.getsockname()[:2]
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"sct-net-accept-{name}")
        t.start()
        self._threads.append(t)

    # -- receive side ---------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed: shutdown
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True,
                                 name=f"sct-net-serve-{self.name}")
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        with contextlib.suppress(OSError), conn:
            while True:
                frame = _read_frame(conn)
                if frame is None:
                    return  # EOF / unframeable garbage: drop the conn
                frm = str(frame.get("from", ""))
                inst = str(frame.get("inst", ""))
                seq = int(frame.get("seq", 0))
                # ack FIRST, duplicates included: the sender's retry
                # loop only stops on the ack, and a dup means a
                # previous ack was lost in transit
                conn.sendall(_frame({"ack": seq}))
                with self._lock:
                    last = self._recv_seq.get((frm, inst), 0)
                    if seq <= last:
                        continue  # at-most-once: seen it, ack was enough
                    self._recv_seq[(frm, inst)] = seq
                    cb = self.on_message
                if cb is not None:
                    cb(frm, str(frame.get("kind", "")),
                       dict(frame.get("fields") or {}))

    # -- send side ------------------------------------------------------
    def connect(self, peer: str, host: str, port: int) -> None:
        """Register ``peer``'s listening address; the connection
        itself is opened lazily on the first send (and re-opened
        after any wire failure)."""
        with self._lock:
            self._peers[peer] = (host, int(port))
            self._peer_locks.setdefault(peer, threading.Lock())

    def peers(self) -> list[str]:
        with self._lock:
            return sorted(self._peers)

    def _wire_send(self, peer: str, payload: bytes, seq: int,
                   dup: bool = False) -> bool:
        """One real attempt: frame on the wire, wait for the matching
        ack.  Any wire failure drops the cached connection (the next
        attempt reconnects) and reports False."""
        conn = self._conns.get(peer)
        try:
            if conn is None:
                addr = self._peers[peer]
                conn = socket.create_connection(
                    addr, timeout=self.ack_timeout_s)
                self._conns[peer] = conn
            conn.settimeout(self.ack_timeout_s)
            conn.sendall(payload)
            if dup:
                conn.sendall(payload)  # chaos net_dup: same seq twice
            while True:
                ack = _read_frame(conn)
                if ack is None:
                    raise OSError("connection closed awaiting ack")
                got = int(ack.get("ack", -1))
                if got >= seq:
                    return True
                # stale ack (a prior attempt's dup): keep reading
        except OSError:
            self._conns.pop(peer, None)
            with contextlib.suppress(OSError):
                if conn is not None:
                    conn.close()
            return False
        except KeyError:
            return False  # never connected to this peer

    def send(self, peer: str, kind: str, retries: int | None = None,
             **fields) -> bool:
        if self._closed:
            return False
        if retries is None and kind in LOSSY_KINDS:
            retries = 0  # lossy class: the next frame supersedes this one
        with self._lock:
            plock = self._peer_locks.setdefault(peer, threading.Lock())
        # the exchange runs under the per-peer lock (wire order is a
        # correctness invariant — see _peer_locks); everything with
        # its own latency or lock (journal appends, metrics, the
        # on_rejoin hook) is RECORDED during the exchange and emitted
        # after release, so one peer's slow disk never serializes
        # another peer's sends
        with plock:
            out = self._exchange(peer, kind, retries, fields)
        seq = out["seq"]
        if self.metrics is not None:
            for _ in out["retried"]:
                self.metrics.counter("net.retries", peer=peer).inc()
            if out["sent"]:
                self.metrics.histogram("net.rtt_ms", peer=peer).observe(
                    out["rtt_ms"])
        if self.journal is not None:
            for attempt, err in out["retried"]:
                self.journal.write("net_retry", peer=peer, kind=kind,
                                   seq=seq, attempt=attempt, error=err)
            if out["rejoined"]:
                self.journal.write("net_rejoin", peer=peer, kind=kind,
                                   seq=seq)
            if out["sent"]:
                self.journal.write("net_sent", peer=peer, kind=kind,
                                   seq=seq, attempt=out["attempt"],
                                   rtt_ms=round(out["rtt_ms"], 3))
            else:
                self.journal.write("net_gave_up", peer=peer, kind=kind,
                                   seq=seq, attempts=out["attempt"],
                                   error=out["error"])
                if out["entered"]:
                    self.journal.write("net_partition_entered",
                                       peer=peer, kind=kind, seq=seq)
        if out["rejoined"] and self.on_rejoin is not None:
            self.on_rejoin(peer)
        return out["sent"]

    def _exchange(self, peer: str, kind: str, retries: int | None,
                  fields: dict) -> dict:
        """The attempt loop (caller holds the per-peer lock): returns
        the outcome record ``send`` journals after release."""
        # sctlint: io-under-lock — the clock.sleep sites below (chaos
        # net_delay, retry backoff) are ordering-mandated under the
        # per-peer lock: releasing it mid-message would let a later
        # seq overtake this one on the wire and be deduped as its
        # replay.  Free under a VirtualClock (zero real sleeps in
        # soaks); bounded by ack_timeout_s and the backoff cap live.
        with self._lock:
            seq = self._send_seq.get(peer, 0) + 1
            self._send_seq[peer] = seq
            counts = self._counts.setdefault(
                peer, {"sent": 0, "retries": 0, "gave_up": 0})
        payload = _frame({"v": 1, "from": self.name,
                          "inst": self._inst, "seq": seq,
                          "kind": kind, "fields": fields})
        attempts = (self.retries if retries is None
                    else int(retries)) + 1
        rng = random.Random((self.seed, self.name, peer, seq).__repr__())
        out = {"seq": seq, "sent": False, "attempt": attempts,
               "rtt_ms": 0.0, "error": None, "retried": [],
               "entered": False, "rejoined": False}
        for attempt in range(1, attempts + 1):
            ruling = (self.chaos.on_network(peer)
                      if self.chaos is not None else None)
            mode = ruling["mode"] if ruling is not None else None
            t0 = self.clock.monotonic()
            if mode in ("net_drop", "net_partition"):
                # ruled unreachable BEFORE the real socket: the frame
                # never exists, no real timeout is burned
                ok, err = False, f"chaos:{mode}"
            else:
                if mode == "net_delay":
                    # injected latency on the INJECTABLE clock
                    self.clock.sleep(float(ruling["delay_s"]))
                try:
                    ok = self._wire_send(peer, payload, seq,
                                         dup=(mode == "net_dup"))
                    err = None if ok else "wire"
                except Exception as e:  # pragma: no cover — belt: the
                    # wire layer already catches OSError; classify
                    # anything exotic and treat the attempt as lost
                    ok = False
                    err = f"{classify_error(e)}:{type(e).__name__}"
            if ok:
                out["sent"] = True
                out["attempt"] = attempt
                out["rtt_ms"] = (self.clock.monotonic() - t0) * 1000.0
                with self._lock:
                    counts["sent"] += 1
                    if peer in self._partitioned:
                        self._partitioned.discard(peer)
                        out["rejoined"] = True
                return out
            out["error"] = err
            if attempt < attempts:
                out["retried"].append((attempt, err))
                with self._lock:
                    counts["retries"] += 1
                # seeded-jitter backoff on the injectable clock
                self.clock.sleep(self.backoff.delay_s(attempt, rng))
        with self._lock:
            counts["gave_up"] += 1
            if peer not in self._partitioned:
                self._partitioned.add(peer)
                out["entered"] = True
        return out

    # -- introspection / shutdown ---------------------------------------
    def partitioned(self, peer: str) -> bool:
        """True while ``peer`` is in an open partition window (the
        last send gave up and no delivery has succeeded since) — the
        signal callers use to go LOCAL-ONLY instead of wedging."""
        with self._lock:
            return peer in self._partitioned

    def stats(self) -> dict:
        with self._lock:
            return {"peers": {p: dict(c)
                              for p, c in self._counts.items()},
                    "partitioned": sorted(self._partitioned)}

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = list(self._conns.values())
            self._conns.clear()
        with contextlib.suppress(OSError):
            self._listener.close()
        for conn in conns:
            with contextlib.suppress(OSError):
                conn.close()
