"""scanpy-compatible function namespaces: ``sct.pp`` / ``sct.tl`` /
``sct.experimental``.

The registry's dotted operator names are the canonical API
(``sct.apply("cluster.leiden", ...)``); these wrappers exist so a
scanpy/reference user's muscle memory keeps working unchanged:

>>> import sctools_tpu as sct
>>> d = sct.pp.normalize_total(d, target_sum=1e4)
>>> d = sct.pp.log1p(d)
>>> d = sct.pp.highly_variable_genes(d, n_top_genes=2000, subset=True)
>>> d = sct.pp.pca(d); d = sct.pp.neighbors(d)
>>> d = sct.tl.leiden(d); d = sct.tl.umap(d)

Differences from scanpy, stated once: every wrapper is PURE (returns a
new CellData; nothing mutates in place) and takes ``backend=`` ("tpu"
default, "cpu" for the oracle).  Keyword names follow this package's
operators, with the common scanpy spellings accepted as aliases
(``n_top_genes``, ``n_comps``, ``n_neighbors``, ``n_genes``,
``gene_list``, ``maxiter`` — see ``_ALIASES``); the GUIDE's operator
map documents every rename.  Wrappers are thin — one ``apply`` call — except the three
scanpy entry points that bundle several steps (``calculate_qc_metrics``,
``neighbors``, ``recipe_*``), which compose the same registered ops a
user would chain by hand.
"""

from __future__ import annotations

from types import SimpleNamespace

from .registry import apply

# one-to-one renames: scanpy name -> registered operator
_PP = {
    "filter_cells": "qc.filter_cells",
    "filter_genes": "qc.filter_genes",
    "subsample": "qc.subsample",
    "sample": "qc.subsample",  # scanpy >=1.10 name
    "normalize_total": "normalize.library_size",
    "normalize_per_cell": "normalize.library_size",  # pre-1.0 scanpy name
    "log1p": "normalize.log1p",
    "scale": "normalize.scale",
    "regress_out": "normalize.regress_out",
    "downsample_counts": "normalize.downsample_counts",
    "highly_variable_genes": "hvg.select",
    "pca": "pca.randomized",
    "combat": "integrate.combat",
    "bbknn": "neighbors.bbknn",
    "magic": "impute.magic",
    "scrublet": "qc.doublet_score",
    "recipe_zheng17": "recipe.zheng17",
    "recipe_seurat": "recipe.seurat",
    "recipe_weinreb17": "recipe.weinreb17",
}

_TL = {
    "leiden": "cluster.leiden",
    "louvain": "cluster.louvain",
    "kmeans": "cluster.kmeans",
    "pca": "pca.randomized",  # scanpy exposes tl.pca AND pp.pca
    "dendrogram": "cluster.dendrogram",
    "umap": "embed.umap",
    "tsne": "embed.tsne",
    "diffmap": "embed.diffmap",
    "draw_graph": "embed.draw_graph",
    "embedding_density": "embed.density",
    "phate": "embed.phate",
    "dpt": "dpt.pseudotime",
    "paga": "graph.paga",
    "rank_genes_groups": "de.rank_genes_groups",
    "filter_rank_genes_groups": "de.filter_rank_genes_groups",
    "marker_gene_overlap": "de.marker_gene_overlap",
    "score_genes": "score.genes",
    "score_genes_cell_cycle": "score.cell_cycle",
    "ingest": "integrate.ingest",
    "palantir": "palantir.run",
    "wishbone": "wishbone.run",
    "phenograph": "cluster.phenograph",
    # scVelo tl.* muscle memory (scv.tl.*); tl.velocity and pp.moments
    # need signature-aware wrappers (mode=/n_neighbors=) and are
    # defined below, not here
    "velocity_graph": "velocity.graph",
    "velocity_embedding": "velocity.embedding",
    "recover_dynamics": "velocity.recover_dynamics",
    "latent_time": "velocity.latent_time",
    "terminal_states": "velocity.terminal_states",
    "fate_probabilities": "velocity.fate_probabilities",
    "lineage_drivers": "velocity.lineage_drivers",
}

_EXPERIMENTAL_PP = {
    "normalize_pearson_residuals": "normalize.pearson_residuals",
    "recipe_pearson_residuals": "recipe.pearson_residuals",
}


def _wrap(scanpy_name: str, op: str, aliases: dict | None = None):
    """``aliases`` maps scanpy keyword names onto this package's
    operator keywords, so muscle-memory call sites
    (``n_top_genes=``, ``n_comps=``, ...) work unchanged."""

    def f(data, backend: str = "tpu", **kw):
        if aliases:
            for scanpy_kw, our_kw in aliases.items():
                if scanpy_kw in kw:
                    if our_kw in kw:
                        raise TypeError(
                            f"{scanpy_name}: got both {scanpy_kw!r} "
                            f"and its alias {our_kw!r}")
                    kw[our_kw] = kw.pop(scanpy_kw)
        return apply(op, data, backend=backend, **kw)

    f.__name__ = scanpy_name
    f.__qualname__ = scanpy_name
    f.__doc__ = (f"scanpy-compat wrapper: ``{op}`` (see its registered "
                 f"docstring / docs/GUIDE.md for parameter names"
                 + (f"; accepts scanpy aliases {sorted(aliases)}"
                    if aliases else "") + ").")
    return f


# scanpy keyword spellings -> this package's operator keywords
_ALIASES = {
    "highly_variable_genes": {"n_top_genes": "n_top"},
    "normalize_per_cell": {"counts_per_cell_after": "target_sum"},
    "pca": {"n_comps": "n_components"},
    "rank_genes_groups": {"n_genes": "n_top"},
    "score_genes": {"gene_list": "genes"},
    "umap": {"maxiter": "n_epochs"},
}


def _calculate_qc_metrics(data, backend: str = "tpu", **kw):
    """scanpy ``pp.calculate_qc_metrics``: per-cell AND per-gene
    metrics (``qc.per_cell_metrics`` + ``qc.per_gene_metrics``)."""
    data = apply("qc.per_cell_metrics", data, backend=backend, **kw)
    return apply("qc.per_gene_metrics", data, backend=backend)


def _neighbors(data, backend: str = "tpu", k: int = 15,
               metric: str = "cosine", connectivities: bool = True,
               method: str = "umap", n_neighbors: int | None = None,
               **kw):
    """scanpy ``pp.neighbors``: kNN search plus the connectivity
    weights (``neighbors.knn`` + ``graph.connectivities``).
    ``method`` is scanpy's kernel choice ("umap" or "gauss"/"gaussian"),
    routed to ``graph.connectivities(mode=)``; everything else forwards
    to the kNN search."""
    if n_neighbors is not None:
        k = n_neighbors  # scanpy spelling
    data = apply("neighbors.knn", data, backend=backend, k=k,
                 metric=metric, **kw)
    if connectivities:
        mode = {"gauss": "gaussian"}.get(method, method)
        data = apply("graph.connectivities", data, backend=backend,
                     mode=mode)
    # scanpy-shaped provenance record (tooling reads
    # uns['neighbors']['params']['n_neighbors'])
    return data.with_uns(neighbors={
        "connectivities_key": "connectivities",
        "distances_key": "knn_distances",
        "params": {"n_neighbors": int(k), "metric": metric,
                   "method": method},
    })


def _moments(data, backend: str = "tpu", n_neighbors: int | None = None,
             n_pcs: int | None = None, metric: str = "cosine"):
    """scVelo ``pp.moments``: the canonical tutorial call passes
    ``n_pcs=``/``n_neighbors=`` and expects the neighbor graph to be
    (re)built first — compose pca/kNN accordingly, then smooth.
    Without those kwargs, the existing graph is used as-is."""
    if n_pcs is not None:
        data = apply("pca.randomized", data, backend=backend,
                     n_components=n_pcs)
    if (n_neighbors is not None or n_pcs is not None
            or "knn_indices" not in data.obsp):
        # n_pcs alone must ALSO rebuild the graph: smoothing over a
        # kNN built on the old embedding would be silently stale
        data = apply("neighbors.knn", data, backend=backend,
                     k=n_neighbors or 30, metric=metric)
    return apply("velocity.moments", data, backend=backend)


def _velocity(data, backend: str = "tpu", mode: str = "steady_state",
              **kw):
    """scVelo ``tl.velocity``: ``mode=`` routes between the
    steady-state γ fit ('steady_state'/'deterministic'), the
    second-moment stacked fit ('stochastic' — scVelo's default), and
    the dynamical ODE model ('dynamical')."""
    if mode == "dynamical":
        return apply("velocity.recover_dynamics", data,
                     backend=backend, **kw)
    if mode == "stochastic":
        return apply("velocity.estimate", data, backend=backend,
                     mode="stochastic", **kw)
    if mode in ("steady_state", "deterministic"):
        return apply("velocity.estimate", data, backend=backend, **kw)
    raise ValueError(
        f"tl.velocity: unknown mode {mode!r} (use 'steady_state', "
        f"'deterministic', 'stochastic' or 'dynamical')")


def _filter_genes_dispersion(data, backend: str = "tpu",
                             n_top_genes: int | None = None,
                             min_mean: float | None = None,
                             max_mean: float | None = None,
                             min_disp: float | None = None,
                             **kw):
    """Pre-1.0 scanpy ``pp.filter_genes_dispersion``, both call forms:
    ``n_top_genes=`` ranks by dispersion and subsets; the cutoff form
    (``min_mean``/``max_mean``/``min_disp``) masks on the per-gene
    mean of the input and the bin-normalised dispersion score that
    ``hvg.select`` stores in ``var['means']``/``var['hvg_score']``
    (the legacy analogue — this framework does not reproduce the
    pre-1.0 log-binning byte-for-byte)."""
    if n_top_genes is not None:
        return apply("hvg.select", data, backend=backend,
                     n_top=n_top_genes, flavor="dispersion",
                     subset=True, **kw)
    import numpy as np

    scored = apply("hvg.select", data, backend=backend,
                   flavor="dispersion", subset=False, **kw)
    mean = np.asarray(scored.var["means"])
    disp = np.asarray(scored.var["hvg_score"])
    keep = np.ones(scored.n_genes, bool)
    if min_mean is not None:
        keep &= mean >= min_mean
    if max_mean is not None:
        keep &= mean <= max_mean
    if min_disp is not None:
        keep &= disp >= min_disp
    if not keep.any():
        raise ValueError("filter_genes_dispersion: no gene passes the "
                         "cutoffs; loosen min_mean/max_mean/min_disp")
    idx = np.flatnonzero(keep)
    if backend == "tpu":
        from .ops.hvg import select_genes_device

        return select_genes_device(scored, idx, compact=True)
    return scored[:, idx]


def _scale_layers_like_x(before, after, layer_names, backend):
    """Apply the per-cell factors that took ``before.X`` to
    ``after.X`` onto the named layers (scVelo's filter_and_normalize
    normalises spliced/unspliced alongside X)."""
    import numpy as np

    def row_sums(d):
        X = d.X
        from .data.sparse import SparseCells, row_sum

        if isinstance(X, SparseCells):
            return np.asarray(row_sum(X))[: d.n_cells]
        if hasattr(X, "sum") and not isinstance(X, np.ndarray):
            return np.asarray(X.sum(axis=1)).ravel()
        return np.asarray(X).sum(axis=1)

    tb = row_sums(before)
    ta = row_sums(after)
    fac = np.where(tb > 0, ta / np.maximum(tb, 1e-12), 1.0)
    new = {}
    for name in layer_names:
        L = after.layers[name]
        try:
            import scipy.sparse as sp

            if sp.issparse(L):
                new[name] = (sp.diags(fac) @ L).astype(np.float32)
                continue
        except ImportError:  # pragma: no cover
            pass
        arr = np.asarray(L, np.float32) if backend == "cpu" else L
        n = min(len(fac), arr.shape[0])
        scaled = np.asarray(arr[:n], np.float32) * fac[:n, None]
        if arr.shape[0] > n:  # padded device rows stay as-is
            scaled = np.concatenate(
                [scaled, np.asarray(arr[n:], np.float32)])
        new[name] = scaled.astype(np.float32)
    return after.with_layers(**new)


def _filter_and_normalize(data, backend: str = "tpu",
                          min_shared_counts: int = 20,
                          n_top_genes: int | None = 2000,
                          log: bool = True):
    """scVelo ``pp.filter_and_normalize``: gene filter on total counts
    (the spliced X), library-size normalisation of X AND the
    spliced/unspliced layers (the same per-cell factors), optional HVG
    subset, log1p on X.  Stated deviations from the published helper
    (also listed under "Known API deviations" in docs/GUIDE.md):
    the gene filter uses X total counts, not spliced∩unspliced
    'shared counts' (the layers still ride through every subset
    aligned); ONLY min_cells-free count filtering is applied —
    scVelo adds no detected-cells floor here; and the spliced/
    unspliced layers are scaled by X's per-cell normalisation
    factors, where scVelo's ``pp.normalize_per_cell`` normalises
    each layer by its OWN initial per-layer counts — ported
    pipelines therefore get slightly different Ms/Mu than upstream
    when layer depth profiles differ from X's."""
    data = apply("qc.per_gene_metrics", data, backend=backend)
    data = apply("qc.filter_genes", data, backend=backend,
                 min_cells=None, min_counts=min_shared_counts)
    before = data
    data = apply("normalize.library_size", data, backend=backend)
    vel_layers = [n for n in ("spliced", "unspliced")
                  if n in data.layers]
    if vel_layers:
        data = _scale_layers_like_x(before, data, vel_layers, backend)
    if n_top_genes is not None:
        data = apply("hvg.select", data, backend=backend,
                     n_top=n_top_genes, flavor="dispersion",
                     subset=True)
    if log:
        data = apply("normalize.log1p", data, backend=backend)
    return data


def _experimental_hvg(data, backend: str = "tpu", **kw):
    """scanpy ``experimental.pp.highly_variable_genes`` (pearson
    residuals flavor by default)."""
    kw.setdefault("flavor", "pearson_residuals")
    return apply("hvg.select", data, backend=backend, **kw)


pp = SimpleNamespace(
    calculate_qc_metrics=_calculate_qc_metrics,
    neighbors=_neighbors,
    moments=_moments,
    filter_genes_dispersion=_filter_genes_dispersion,
    filter_and_normalize=_filter_and_normalize,
    **{name: _wrap(name, op, _ALIASES.get(name))
       for name, op in _PP.items()},
)

tl = SimpleNamespace(
    velocity=_velocity,
    **{name: _wrap(name, op, _ALIASES.get(name))
       for name, op in _TL.items()},
)

experimental = SimpleNamespace(pp=SimpleNamespace(
    highly_variable_genes=_experimental_hvg,
    **{name: _wrap(name, op) for name, op in _EXPERIMENTAL_PP.items()},
))

# scanpy.external (``import scanpy.external as sce``) entry points —
# the third-party tools scanpy wraps that this framework implements
# natively.  Same thin-_wrap contract as pp/tl.
_EXTERNAL_PP = {
    "harmony_integrate": "integrate.harmony",
    "mnn_correct": "integrate.mnn",
    "bbknn": "neighbors.bbknn",
    "magic": "impute.magic",
    "scrublet": "qc.doublet_score",
}
_EXTERNAL_TL = {
    "phenograph": "cluster.phenograph",
    "palantir": "palantir.run",
    "wishbone": "wishbone.run",
    "phate": "embed.phate",
}
external = SimpleNamespace(
    pp=SimpleNamespace(**{name: _wrap(name, op)
                          for name, op in _EXTERNAL_PP.items()}),
    tl=SimpleNamespace(**{name: _wrap(name, op)
                          for name, op in _EXTERNAL_TL.items()}),
)
