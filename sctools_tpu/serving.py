"""Survivable online annotation service — resident reference-model
state as a first-class fault domain.

Every prior fault-tolerance rung (retry → breaker → degrade →
quarantine → requeue → preempt) protects RUNS: work that arrives,
executes, and leaves.  The query-to-reference scenario (the raw-count
annotation survey, PAPERS.md) is a different traffic shape — a
pre-trained reference model kept DEVICE-RESIDENT for hours while
streams of small query batches map against it — and long-lived
resident state fails in ways no run-shaped ladder covers: a corrupted
model artifact, an evicted device buffer, a mid-traffic model
upgrade.  :class:`AnnotationService` owns that state and serves three
query kinds against it — label transfer (the ``integrate.ingest``
contract: project into the reference PCA space, distance-weighted
kNN vote), doublet flagging (Scrublet's simulated-neighbour
enrichment, with the expensive doublet simulation done ONCE at
artifact build — ``ops/doublet.py``'s machinery — so queries only pay
a kNN), and marker scoring (``ops/score.py``'s expression-matched
weight tables, frozen at build) — in three robustness layers:

**Verified state lifecycle.**  The reference model is an on-disk
artifact written through the checkpoint integrity layer
(:func:`build_reference_artifact` → ``checkpoint.save_npz_generations``:
content digest + the ``serving-model-v1`` identity fingerprint,
atomic rename, previous generation rotated to ``.prev``).  Every load
verifies before trusting; a corrupt generation is QUARANTINED — moved
beside the data with a ``.reason.json`` sidecar, never deleted —
journaled ``model_quarantined``, and the load falls back to ``.prev``
(one build of lost freshness, never a dead service).  A residency
HEALTH PROBE (are the device buffers still alive?) backs a degrade
ladder for the resident state itself::

    resident-on-device → re-place (host mirror → device)
                       → reload-from-artifact (verified; quarantine +
                         .prev on damage)
                       → cpu (serve from host arrays)

wired into the existing breaker machinery: device-placement failures
feed the per-backend shared :class:`~sctools_tpu.utils.failsafe.
CircuitBreaker`, and an OPEN breaker sends queries straight to the
host rung without a placement storm.  Rungs taken are counted in
``serve.state_reloads{reason=}``.

**Epoch-guarded hot-swap.**  :meth:`AnnotationService.swap` loads and
places the candidate artifact BESIDE the serving model, validates it
against the artifact's own canary (a stored slice of reference cells
with their expected labels — a model that cannot re-derive its own
canary labels is corrupt or mismatched, whatever its digest says),
and only then flips the serving epoch.  Queries are pinned to the
epoch they were ADMITTED under — the previous epoch's model stays
resident until the next swap, so an in-flight query never sees a
mid-query tensor swap — and a failed canary (or a corrupt candidate)
auto-rolls-back: the old epoch keeps serving, journaled
``swap_rolled_back``.  Successful swaps journal ``model_swapped``.

**Terminal-exactly-once queries.**  Admission rides the
:class:`~sctools_tpu.scheduler.RunScheduler` — per-tenant quotas,
queue-deadline feasibility, priority-correct shedding, per-query
deadlines (``deadline_s=`` at admission + the runner's
``step_deadline_s`` while executing), and the shared per-backend
breaker — so every query terminates in exactly one of
{completed, failed, rejected, shed} with a journaled reason (the
scheduler's funnel contract), counted in ``serve.queries{outcome=}``.
Chaos modes ``evict_state`` / ``corrupt_model`` fire on a dedicated
serving channel (``ChaosMonkey.on_serving``, consulted once per query
execution), so the whole ladder is tier-1 testable on one
VirtualClock with zero real sleeps.

**Shape bucketing (the low-latency half).**  Queries arrive in
arbitrary small shapes; compiling per shape would retrace forever.
Incoming batches are zero-padded to a small ladder of canonical
bucket row counts (:data:`DEFAULT_BUCKETS`; padding rows are inert —
every query kind is row-independent, and results are trimmed to the
real row count), and the pure query math executes as a fused plan
(``plan.FusedTransform`` over the ``serve.kernel`` op) whose inputs
INCLUDE the model arrays — so the process-wide plan cache serves
every query of a bucket after its first compile, across evictions,
re-places and even hot-swaps to a same-shaped model (the arrays are
inputs, not baked constants).  Zero retraces after warmup is CI-gated
via the existing ``plan.cache_hits``/``plan.cache_misses`` counters
(``bench.py --phase serve``).

>>> import sctools_tpu as sct
>>> ref = sct.run_recipe("annotation_reference", raw_ref)
>>> sct.serving.build_reference_artifact(ref, "model.npz",
...                                      labels_key="cell_type")
>>> with sct.AnnotationService("model.npz", backend="tpu") as svc:
...     t = svc.query(raw_query_counts, "label_transfer",
...                   tenant="lab-a", deadline_s=30)
...     print(t.result()["labels"])
"""

from __future__ import annotations

import os
import threading
import warnings
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from .data.dataset import CellData
from .plan import FusedTransform
from .registry import Pipeline, Transform, register
from .scheduler import RunRejected, RunScheduler
from .utils import telemetry
from .utils.checkpoint import (CheckpointCorruptError,
                               load_npz_verified, quarantine_checkpoint,
                               save_npz_generations)
from .utils.failsafe import RESOURCE, TRANSIENT, classify_error
from .utils.vclock import SYSTEM_CLOCK

#: identity fingerprint of the serving artifact — a foreign npz
#: renamed onto the model path fails verification instead of
#: half-parsing; bump on incompatible layout changes
SERVING_MODEL_FP = "serving-model-v1"

#: the query kinds :meth:`AnnotationService.query` serves
QUERY_KINDS = ("label_transfer", "doublet_flag", "marker_score")

#: canonical query-batch row counts (the shape-bucket ladder): an
#: n-row query pads to the smallest bucket >= n, so every batch size
#: in a bucket shares one compiled program; sizes past the ladder
#: keep doubling (serving is for SMALL frequent queries — atlas-sized
#: inputs belong on the batch pipeline).  The ladder is OWNED by
#: ``sctools_tpu.buckets`` — serving's query buckets are one instance
#: of the repo-wide shape-bucket policy the recipe path also pads to.
from .buckets import DEFAULT_BUCKETS  # noqa: E402  (re-export)
from .buckets import bucket_for as _bucket_for  # noqa: E402

#: artifact keys that become device-resident on place() (score-set
#: weight tables join them dynamically under their "score/<name>"
#: keys; canary/scvi payloads stay host-only)
_DEVICE_KEYS = ("PCs", "pca_mean", "ref_scores", "label_codes",
                "sim_scores")


def bucket_rows(n: int, buckets=DEFAULT_BUCKETS) -> int:
    """The canonical padded row count for an ``n``-row query batch:
    the smallest bucket >= ``n``, doubling past the ladder's end.
    Thin alias of :func:`sctools_tpu.buckets.bucket_for` kept for the
    serving API surface."""
    if n < 1:
        raise ValueError("bucket_rows: need at least one query row")
    return _bucket_for(n, buckets)


# ---------------------------------------------------------------------------
# Artifact build
# ---------------------------------------------------------------------------


def _dense_rows(M, rows: np.ndarray) -> np.ndarray:
    """Fetch selected rows of a counts matrix (scipy / numpy / packed
    SparseCells / device array) as dense float32 — build-time only."""
    import scipy.sparse as sp

    if hasattr(M, "to_scipy_csr"):  # device-packed SparseCells
        M = M.to_scipy_csr()
    if sp.issparse(M):
        return np.asarray(M[rows].todense(), np.float32)
    return np.asarray(M, np.float32)[rows]


def build_reference_artifact(ref: CellData, path: str, *,
                             labels_key: str = "cell_type",
                             score_sets: dict | None = None,
                             n_canary: int = 64,
                             sim_ratio: float = 1.0,
                             max_sim: int = 4096,
                             expected_rate: float = 0.06,
                             ctrl_size: int = 50, n_bins: int = 25,
                             target_sum: float = 1e4,
                             log1p: bool = True,
                             counts_layer: str = "counts",
                             seed: int = 0, version: str = "v1",
                             scvi_model=None) -> str:
    """Freeze a fitted reference into the serving artifact.

    ``ref`` must already carry the batch pipeline's PCA state
    (``varm['PCs']`` + ``obsm['X_pca']`` + ``uns['pca_mean']`` — the
    ``annotation_reference`` recipe produces exactly this shape) and
    the label column ``obs[labels_key]``.  The artifact stores
    everything a query needs, with the expensive parts done HERE, once:

    * the projection state (loadings, mean, reference scores, label
      codes + levels, gene names) for label transfer;
    * simulated-doublet embeddings (``ops/doublet.py``'s pair
      sampling + sum + normalise + project, on the raw counts in
      ``layers[counts_layer]``) so a doublet query is one kNN against
      resident state instead of a fresh simulation;
    * one expression-matched ``(n_genes, 2)`` weight table per entry
      of ``score_sets`` (``{name: gene list}``, ``ops/score.py``'s
      control binning frozen at build);
    * a CANARY — ``n_canary`` reference cells' raw counts with their
      expected label codes — the self-check every load and every
      hot-swap candidate must pass (:meth:`AnnotationService.swap`);
    * optionally the trained scvi parameters (``scvi_model``: a
      params pytree or a ``models.scvi.save_model`` path), embedded
      under ``scvi/...`` keys with the same flatten encoding.

    Written through ``checkpoint.save_npz_generations`` (digest +
    :data:`SERVING_MODEL_FP` fingerprint, atomic rename, previous
    generation rotated to ``.prev`` — the rollback target a corrupt
    newer generation falls back to).  ``target_sum``/``log1p`` record
    how queries must be normalised to match the reference's
    preprocessing.  Returns the content digest."""
    from .ops.doublet import _sample_pairs
    from .ops.score import (_control_indices, _gene_means_host,
                            _resolve_gene_indices, _score_weights)

    n = ref.n_cells
    if "PCs" not in ref.varm or "X_pca" not in ref.obsm:
        raise ValueError(
            "build_reference_artifact: reference needs varm['PCs'] + "
            "obsm['X_pca'] (+ uns['pca_mean']) — run the "
            "'annotation_reference' recipe (or pca.randomized) on it "
            "first")
    if labels_key not in ref.obs:
        raise KeyError(
            f"build_reference_artifact: obs has no {labels_key!r}")
    PCs = np.asarray(ref.varm["PCs"], np.float32)
    mu = np.asarray(ref.uns.get("pca_mean",
                                np.zeros(ref.n_genes)), np.float32)
    ref_scores = np.asarray(ref.obsm["X_pca"], np.float32)[:n]
    raw = np.asarray(ref.obs[labels_key]).astype(str)[:n]
    levels, codes = np.unique(raw, return_inverse=True)
    # the canary and the simulated doublets must be built from RAW
    # counts (the query kernel normalises them exactly once, like a
    # real query) — silently using an already-normalised X would
    # double-normalise both and bake a self-inconsistent artifact
    if counts_layer is None:
        counts = ref.X  # the caller asserts X itself holds raw counts
    elif counts_layer in ref.layers:
        counts = ref.layers[counts_layer]
    else:
        raise ValueError(
            f"build_reference_artifact: reference has no "
            f"layers[{counts_layer!r}] raw-counts snapshot — the "
            f"'annotation_reference' recipe snapshots one before "
            f"normalising; pass counts_layer=None only if X itself "
            f"still holds raw counts")

    arrays: dict = {
        "PCs": PCs, "pca_mean": mu, "ref_scores": ref_scores,
        "label_levels": levels.astype(str),
        "label_codes": codes.astype(np.int32),
        "target_sum": np.float64(target_sum),
        "log1p": np.int64(bool(log1p)),
        "expected_rate": np.float64(expected_rate),
        "version": np.array(str(version)),
    }
    if "gene_name" in ref.var:
        arrays["gene_names"] = np.asarray(
            ref.var["gene_name"]).astype(str)

    # simulated doublets, projected ONCE at build (ops/doublet.py's
    # simulation; queries only pay the kNN against these embeddings)
    n_sim = min(int(max_sim), max(1, int(round(sim_ratio * n))))
    pairs = _sample_pairs(n, n_sim, seed)
    D = (_dense_rows(counts, pairs[:, 0])
         + _dense_rows(counts, pairs[:, 1]))
    arrays["sim_scores"] = np.asarray(
        _project_rows_host(D, PCs, mu, target_sum, log1p), np.float32)
    arrays["sim_ratio"] = np.float64(n_sim / n)

    # expression-matched score-set weight tables (ops/score.py's
    # control binning, frozen against the REFERENCE's gene means)
    names = sorted(score_sets or {})
    arrays["score_set_names"] = np.asarray(names, dtype=str)
    if names:
        gm = _gene_means_host(ref)
        for i, name in enumerate(names):
            tgt = _resolve_gene_indices(ref, score_sets[name])
            ctrl = _control_indices(gm, tgt, ctrl_size, n_bins,
                                    seed + i)
            arrays[f"score/{name}"] = _score_weights(
                ref.n_genes, tgt, ctrl)

    # the canary: reference cells whose labels the model must be able
    # to re-derive (evenly spaced — covers the label space better
    # than a prefix)
    c = max(1, min(int(n_canary), n))
    canary_idx = np.unique(np.linspace(0, n - 1, c).astype(np.int64))
    arrays["canary_x"] = _dense_rows(counts, canary_idx)
    arrays["canary_codes"] = codes[canary_idx].astype(np.int32)

    if scvi_model is not None:
        from .models.scvi import flatten_params, load_model

        params = (load_model(scvi_model)[0]
                  if isinstance(scvi_model, str) else scvi_model)
        arrays.update(flatten_params(params, prefix="scvi"))

    return save_npz_generations(path, fingerprint=SERVING_MODEL_FP,
                                **arrays)


def _project_rows_host(X: np.ndarray, PCs, mu, target_sum,
                       log1p) -> np.ndarray:
    """Host-side normalise + project of dense count rows (build-time
    and the cpu-rung oracle; the traced twin lives in
    :func:`serve_kernel`)."""
    lib = X.sum(axis=1, keepdims=True)
    Xn = X * (float(target_sum) / np.maximum(lib, 1.0))
    if log1p:
        Xn = np.log1p(Xn)
    return (Xn - np.asarray(mu)[None, :]) @ np.asarray(PCs)


# ---------------------------------------------------------------------------
# The pure query kernel (fused-plan traced; model arrays are INPUTS)
# ---------------------------------------------------------------------------


def _normalize_traced(X, target_sum: float, log1p: bool):
    lib = jnp.sum(X, axis=1, keepdims=True)
    Xn = X * (target_sum / jnp.maximum(lib, 1.0))
    return jnp.log1p(Xn) if log1p else Xn


def _topk_neighbors(q, r, k: int, metric: str):
    """(idx, dist) of each query row's k nearest reference rows — a
    full (bucket, n_ref) distance matrix + ``lax.top_k``: one MXU
    matmul, fully traceable, right-sized for serving buckets (large
    references belong on the blocked batch kNN)."""
    if metric == "cosine":
        qn = q / jnp.maximum(
            jnp.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        rn = r / jnp.maximum(
            jnp.linalg.norm(r, axis=1, keepdims=True), 1e-12)
        d = 1.0 - qn @ rn.T
    else:
        d2 = (jnp.sum(q * q, axis=1)[:, None]
              + jnp.sum(r * r, axis=1)[None, :] - 2.0 * (q @ r.T))
        d = jnp.sqrt(jnp.maximum(d2, 0.0))
    neg, idx = jax.lax.top_k(-d, k)
    return idx, -neg


@register("serve.kernel", backend="tpu", fusable=True, mem_cost=3.0)
@register("serve.kernel", backend="cpu", fusable=True, mem_cost=3.0)
def serve_kernel(data: CellData, kind: str = "label_transfer",
                 k: int = 15, metric: str = "cosine",
                 n_levels: int = 0, target_sum: float = 1e4,
                 log1p: bool = True, sim_ratio: float = 1.0,
                 expected_rate: float = 0.06) -> CellData:
    """The PURE per-query math, one jit-traceable pass over a
    bucket-padded batch — the op the serving plan compiles
    (``plan.FusedTransform``).  The resident model rides in as INPUT
    leaves under ``uns`` (``serve_pcs``/``serve_mu``/``serve_ref``/
    ``serve_codes``/``serve_sim``/``serve_weights``), never as baked
    constants, so re-placed or hot-swapped same-shaped state hits the
    plan cache with zero retraces.  Padding rows are inert (every
    kind is row-independent); the service trims results to the real
    row count.  Adds ``obs['serve_label_code'/'serve_label_conf']``
    (label transfer), ``obs['serve_doublet']`` (doublet flag) or
    ``obs['serve_score']`` (marker score), plus
    ``obsm['serve_scores']`` for the projection kinds."""
    from .ops.doublet import _doublet_likelihood

    X = jnp.asarray(data.X, jnp.float32)
    Xn = _normalize_traced(X, float(target_sum), bool(log1p))
    obs = dict(data.obs)
    if kind == "marker_score":
        both = Xn @ jnp.asarray(data.uns["serve_weights"], jnp.float32)
        obs["serve_score"] = (both[:, 0] - both[:, 1]).astype(
            jnp.float32)
        return CellData(data.X, obs=obs)
    PCs = data.uns["serve_pcs"]
    scores = (Xn - data.uns["serve_mu"][None, :]) @ PCs
    if kind == "label_transfer":
        idx, dist = _topk_neighbors(scores, data.uns["serve_ref"],
                                    int(k), metric)
        w = 1.0 / jnp.maximum(dist, 1e-12)
        w = w / jnp.sum(w, axis=1, keepdims=True)
        nb = data.uns["serve_codes"][idx]
        votes = jnp.sum(
            jax.nn.one_hot(nb, int(n_levels), dtype=jnp.float32)
            * w[..., None], axis=1)
        obs["serve_label_code"] = jnp.argmax(votes, axis=1).astype(
            jnp.int32)
        # weights sum to 1 per row, so the winning vote mass IS the
        # confidence (matches integrate.ingest's <col>_confidence)
        obs["serve_label_conf"] = jnp.max(votes, axis=1).astype(
            jnp.float32)
        return CellData(data.X, obs=obs,
                        obsm={"serve_scores": scores})
    # doublet_flag: Scrublet's simulated-neighbour enrichment against
    # the embeddings frozen at artifact build
    ref = data.uns["serve_ref"]
    comb = jnp.concatenate([ref, data.uns["serve_sim"]], axis=0)
    k_adj = max(1, int(round(int(k) * (1.0 + float(sim_ratio)))))
    idx, _ = _topk_neighbors(scores, comb, k_adj, "euclidean")
    n_sim_nb = jnp.sum((idx >= ref.shape[0]).astype(jnp.float32),
                       axis=1)
    q = (n_sim_nb + 1.0) / (k_adj + 2.0)
    obs["serve_doublet"] = _doublet_likelihood(
        q, float(sim_ratio), float(expected_rate)).astype(jnp.float32)
    return CellData(data.X, obs=obs, obsm={"serve_scores": scores})


def annotate_host(host: dict, X: np.ndarray, kind: str, *, k: int = 15,
                  metric: str = "cosine") -> dict:
    """Numpy twin of :func:`serve_kernel` — the residency ladder's cpu
    rung AND the test oracle.  ``host`` is the artifact's array dict;
    ``X`` dense raw counts (no bucket padding needed — host numpy has
    no retrace to amortise).  Returns the kind's result arrays."""
    from .ops.doublet import _doublet_likelihood

    target_sum = float(host["target_sum"])
    log1p = bool(int(host["log1p"]))
    if kind == "marker_score":
        lib = X.sum(axis=1, keepdims=True)
        Xn = X * (target_sum / np.maximum(lib, 1.0))
        if log1p:
            Xn = np.log1p(Xn)
        both = Xn @ np.asarray(host["serve_weights"], np.float64)
        return {"score": (both[:, 0] - both[:, 1]).astype(np.float32)}
    scores = _project_rows_host(X, host["PCs"], host["pca_mean"],
                                target_sum, log1p)
    if kind == "label_transfer":
        idx, dist = _topk_host(scores, host["ref_scores"], k, metric)
        w = 1.0 / np.maximum(dist, 1e-12)
        w = w / w.sum(axis=1, keepdims=True)
        codes = np.asarray(host["label_codes"])
        L = int(np.asarray(host["label_levels"]).shape[0])
        votes = np.zeros((len(idx), L), np.float64)
        rows = np.repeat(np.arange(len(idx)), idx.shape[1])
        np.add.at(votes, (rows, codes[idx].ravel()), w.ravel())
        win = votes.argmax(axis=1)
        return {"codes": win.astype(np.int32),
                "confidence": votes[np.arange(len(idx)),
                                    win].astype(np.float32),
                "scores": scores.astype(np.float32)}
    sim = np.asarray(host["sim_scores"])
    ref = np.asarray(host["ref_scores"])
    r = float(host["sim_ratio"])
    k_adj = max(1, int(round(k * (1.0 + r))))
    comb = np.concatenate([ref, sim], axis=0)
    idx, _ = _topk_host(scores, comb, k_adj, "euclidean")
    q = ((idx >= ref.shape[0]).sum(axis=1) + 1.0) / (k_adj + 2.0)
    dbl = _doublet_likelihood(q, r, float(host["expected_rate"]))
    return {"doublet_score": np.asarray(dbl, np.float32),
            "scores": scores.astype(np.float32)}


def _topk_host(q, r, k, metric):
    q = np.asarray(q, np.float64)
    r = np.asarray(r, np.float64)
    if metric == "cosine":
        qn = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True),
                            1e-12)
        rn = r / np.maximum(np.linalg.norm(r, axis=1, keepdims=True),
                            1e-12)
        d = 1.0 - qn @ rn.T
    else:
        d = np.sqrt(np.maximum(
            (q * q).sum(1)[:, None] + (r * r).sum(1)[None, :]
            - 2.0 * q @ r.T, 0.0))
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return idx, np.take_along_axis(d, idx, axis=1)


# ---------------------------------------------------------------------------
# Resident model state
# ---------------------------------------------------------------------------


class _ResidentModel:
    """One artifact generation, resident: the host numpy mirror (the
    re-place rung's source), the device arrays (deleted = evicted —
    the residency probe checks ``is_deleted`` on every leaf), and the
    parsed metadata.  Epoch identity belongs to the owning service."""

    def __init__(self, arrays: dict, path: str, epoch: int,
                 generation: str):
        self.path = path
        self.epoch = epoch
        self.generation = generation
        self._dev: dict | None = None
        self._rehost(arrays)

    def _rehost(self, arrays: dict) -> None:
        missing = [k for k in ("PCs", "pca_mean", "ref_scores",
                               "label_levels", "label_codes",
                               "sim_scores", "canary_x",
                               "canary_codes")
                   if k not in arrays]
        if missing:
            raise ValueError(
                f"serving artifact {self.path!r} is missing keys "
                f"{missing} — not a build_reference_artifact() file")
        self.version = str(arrays.get("version", ""))
        self.levels = np.asarray(arrays["label_levels"]).astype(str)
        self.score_sets = tuple(
            np.asarray(arrays.get("score_set_names",
                                  np.zeros(0, "U1"))).astype(str))
        self.gene_names = (np.asarray(arrays["gene_names"]).astype(str)
                           if "gene_names" in arrays else None)
        self.n_genes = int(np.asarray(arrays["PCs"]).shape[0])
        self.meta = {
            "target_sum": float(arrays["target_sum"]),
            "log1p": bool(int(arrays["log1p"])),
            "sim_ratio": float(arrays["sim_ratio"]),
            "expected_rate": float(arrays["expected_rate"]),
            "n_levels": int(self.levels.shape[0]),
        }
        self._scvi_raw = {k: np.asarray(v) for k, v in arrays.items()
                          if k.startswith("scvi/")}
        keep = set(_DEVICE_KEYS) | {f"score/{s}"
                                    for s in self.score_sets} \
            | {"canary_x", "canary_codes", "target_sum", "log1p",
               "sim_ratio", "expected_rate", "label_levels"}
        self._host: dict | None = {
            k: np.asarray(v) for k, v in arrays.items() if k in keep}

    # -- residency ----------------------------------------------------
    def has_host(self) -> bool:
        return self._host is not None

    def resident(self) -> bool:
        """The residency health probe: device state present and no
        buffer deleted out from under us (eviction, device restart,
        chaos ``evict_state``).  Cheap — no device sync."""
        d = self._dev
        if d is None:
            return False
        return not any(getattr(a, "is_deleted", _never)()
                       for a in d.values())

    def place(self) -> None:
        """Put the query-path arrays on device (the canary and scvi
        payloads stay host-only — the canary enters through the
        normal bucketized query path when needed)."""
        host = self._host
        if host is None:
            raise RuntimeError(
                "resident model has no host mirror to place")
        dev_keys = set(_DEVICE_KEYS) | {f"score/{s}"
                                        for s in self.score_sets}
        self._dev = {k: jnp.asarray(host[k]) for k in dev_keys
                     if k in host}

    def evict(self) -> None:
        """Drop the device residency (chaos ``evict_state``; also the
        honest way to model a device restart): buffers are DELETED,
        so an in-flight query racing the eviction fails transiently
        and its retry re-enters the ladder."""
        dev, self._dev = self._dev, None
        for a in (dev or {}).values():
            a.delete()

    def drop_host(self) -> None:
        """Forget the host mirror too (chaos ``corrupt_model`` pairs
        this with on-disk damage, forcing the ladder all the way to
        the verified artifact reload)."""
        self._host = None

    def device_arrays(self) -> dict:
        if self._dev is None:
            raise RuntimeError("resident model is not placed")
        return self._dev

    def host_arrays(self) -> dict:
        if self._host is None:
            raise RuntimeError("resident model has no host mirror")
        return self._host

    def scvi_params(self):
        """The embedded scvi parameter pytree (``scvi_model=`` at
        build), or ``None``."""
        if not self._scvi_raw:
            return None
        from .models.scvi import unflatten_params

        return unflatten_params(self._scvi_raw, prefix="scvi")


def _never() -> bool:
    return False


# ---------------------------------------------------------------------------
# Registered query op (the scheduler-admitted step)
# ---------------------------------------------------------------------------

#: live services by name — how the registered ``serve.query`` op finds
#: its service from hashable step params (weak: a dropped service
#: must not be pinned by the registry)
_SERVICES: "weakref.WeakValueDictionary[str, AnnotationService]" = \
    weakref.WeakValueDictionary()
#: guards the check-then-register sequence (two concurrent
#: constructions of the same name must not both win — the loser's
#: in-flight queries would silently resolve to the winner's models)
_SERVICES_LOCK = threading.Lock()


def _resolve_service(name: str) -> "AnnotationService":
    svc = _SERVICES.get(name)
    if svc is None:
        raise ValueError(
            f"serve.query: no live AnnotationService named {name!r} "
            f"(known: {sorted(_SERVICES)})")
    return svc


@register("serve.query", backend="tpu")
@register("serve.query", backend="cpu")
def serve_query(data: CellData, service: str = "",
                kind: str = "label_transfer", epoch: int = 0,
                k: int = 15, metric: str = "cosine",
                score_set: str = "") -> CellData:
    """Execute one ADMITTED annotation query against the named
    service's resident reference model, pinned to the epoch it was
    admitted under (the hot-swap guard: a swap mid-queue never
    changes the model a query runs on).  The scheduler dispatches
    this as a normal retryable step, so transient resident-state
    failures (an eviction racing the query) retry through the
    residency ladder for free.  Adds the kind's ``serve_*`` outputs
    plus ``uns['serve_epoch'/'serve_mode']``."""
    svc = _resolve_service(service)
    return svc._execute_query(data, kind, int(epoch), int(k), metric,
                              score_set or None)


# ---------------------------------------------------------------------------
# Query tickets
# ---------------------------------------------------------------------------


class ServeTicket:
    """The caller's view of one admitted query: a thin shell over the
    scheduler's :class:`~sctools_tpu.scheduler.RunHandle` that trims
    bucket padding, maps label codes back to level strings, and
    accounts the terminal outcome into ``serve.queries{outcome=}`` /
    ``serve.latency_s`` exactly once."""

    def __init__(self, service: "AnnotationService", handle, *,
                 n: int, kind: str, epoch: int, t0: float, levels):
        self._service = service
        self.handle = handle
        self.n = n
        self.kind = kind
        self.epoch = epoch
        self._t0 = t0
        self._levels = levels
        self._accounted = False

    @property
    def status(self) -> str:
        return self.handle.status

    def done(self) -> bool:
        return self.handle.done()

    def wait(self, timeout: float | None = None) -> bool:
        return self.handle.wait(timeout)

    def result(self, timeout: float | None = None) -> dict:
        """Block for the query's terminal state.  Completed → the
        result dict (``labels``/``codes``/``confidence``/``scores``,
        ``doublet_score`` or ``score``, trimmed to the real row
        count, plus ``epoch``/``mode``).  Failed → re-raises the
        run's real error; shed → raises
        :class:`~sctools_tpu.scheduler.RunShed`."""
        if not self.handle.wait(timeout):
            raise TimeoutError(
                f"query (ticket {self.handle.ticket}) not terminal "
                f"after {timeout}s (status {self.status!r})")
        self._service._account(self, self.handle.status)
        out = self.handle.result()  # raises for failed/shed
        return self._postprocess(out)

    def _postprocess(self, out: CellData) -> dict:
        n = self.n
        res = {"kind": self.kind, "n": n,
               "epoch": int(out.uns.get("serve_epoch", self.epoch)),
               "mode": str(out.uns.get("serve_mode", "device"))}
        if self.kind == "label_transfer":
            codes = np.asarray(out.obs["serve_label_code"])[:n]
            res["codes"] = codes
            res["labels"] = np.asarray(self._levels)[codes]
            res["confidence"] = np.asarray(
                out.obs["serve_label_conf"])[:n]
            res["scores"] = np.asarray(out.obsm["serve_scores"])[:n]
        elif self.kind == "doublet_flag":
            res["doublet_score"] = np.asarray(
                out.obs["serve_doublet"])[:n]
        else:
            res["score"] = np.asarray(out.obs["serve_score"])[:n]
        return res

    def __repr__(self):
        return (f"ServeTicket(kind={self.kind!r}, n={self.n}, "
                f"epoch={self.epoch}, status={self.status!r})")


# ---------------------------------------------------------------------------
# The service
# ---------------------------------------------------------------------------


class AnnotationService:
    """The survivable online annotation service (module docstring has
    the full contract).

    Parameters
    ----------
    artifact : str
        Path of a :func:`build_reference_artifact` file.  Loads
        VERIFIED: a corrupt current generation is quarantined (never
        deleted, journaled ``model_quarantined``) and the ``.prev``
        generation serves instead; with no loadable generation the
        constructor raises.
    name : str
        The service's registry name — how admitted ``serve.query``
        steps (hashable params only) find their way back here, and
        the pattern chaos serving faults match.  Must be unique among
        live services.
    backend : str
        The backend query pipelines are submitted under (and the
        signature of the shared breaker the residency ladder feeds).
    scheduler : RunScheduler | None
        Admission layer to SHARE (its clock, metrics, journal, chaos
        and breaker registry are adopted); ``None`` builds a private
        one from the admission parameters below, shut down by
        :meth:`close`.
    max_concurrency, queue_high_water, tenant_max_in_flight,
    tenant_max_queued, quotas :
        Forwarded to the private scheduler (ignored when
        ``scheduler=`` is given).
    clock, metrics, journal_path, chaos, breakers, runner_defaults :
        Plumbing for the private scheduler; the model-lifecycle
        journal events land in the same file as the query funnel.
    mem_budget : memory.MemoryBudget | None
        Device-memory budget for the PRIVATE scheduler (with
        ``scheduler=`` the pool's own budget is adopted instead).
        When one is present — either way — the resident model holds a
        named STANDING reservation sized to its placed device bytes
        (updated on place / re-place / hot-swap, released at
        :meth:`close`), so admission contends for what is actually
        left of the device rather than the nameplate capacity.
    k, metric :
        Default kNN width / distance for the projection query kinds.
    buckets :
        The shape-bucket ladder (:func:`bucket_rows`).
    canary_threshold : float
        Minimum canary label agreement a hot-swap candidate must
        reach (:meth:`swap`); below it the swap auto-rolls-back.
    query_deadline_s : float | None
        Default per-query EXECUTION budget (the runner's
        ``step_deadline_s``); admission-time queue deadlines are per
        query via ``query(deadline_s=)``.
    """

    def __init__(self, artifact: str, *, name: str = "annot",
                 backend: str = "tpu",
                 scheduler: RunScheduler | None = None,
                 max_concurrency: int = 2, queue_high_water: int = 64,
                 tenant_max_in_flight: int = 2,
                 tenant_max_queued: int = 8, quotas: dict | None = None,
                 clock=None, metrics=None,
                 journal_path: str | None = None, chaos=None,
                 breakers=None, runner_defaults: dict | None = None,
                 mem_budget=None,
                 k: int = 15, metric: str = "cosine",
                 buckets=DEFAULT_BUCKETS,
                 canary_threshold: float = 0.9,
                 query_deadline_s: float | None = None,
                 slo_objectives=None):
        # reserve the name ATOMICALLY before any loading: a raced
        # duplicate construction must fail here, not silently steal
        # the name mid-flight
        with _SERVICES_LOCK:
            if name in _SERVICES:
                raise ValueError(
                    f"AnnotationService: a live service is already "
                    f"named {name!r} — pick another name")
            _SERVICES[name] = self
        self.name = name
        self.backend = backend
        self.k = int(k)
        self.metric = metric
        self.buckets = tuple(buckets)
        self.canary_threshold = float(canary_threshold)
        if scheduler is not None:
            # adopt the shared pool's plumbing wholesale: a service
            # timing queries on a different clock than the scheduler
            # admits them on would be incoherent
            self._sched = scheduler
            self._own_sched = False
            self.clock = scheduler.clock
            self.metrics = scheduler.metrics
            self.chaos = scheduler.chaos
            self._breakers = scheduler.breakers
            # the pool's memory budget (when configured): the
            # resident model holds a named STANDING reservation
            # against it, so query traffic and training jobs contend
            # for what is actually left of the device
            self._mem_budget = getattr(scheduler, "mem_budget", None)
        else:
            self.clock = clock if clock is not None else SYSTEM_CLOCK
            self.metrics = (metrics if metrics is not None
                            else telemetry.default_registry())
            self.chaos = chaos
            rd = dict(runner_defaults or {})
            if query_deadline_s is not None:
                rd.setdefault("step_deadline_s", query_deadline_s)
            self._sched = RunScheduler(
                max_concurrency=max_concurrency,
                queue_high_water=queue_high_water,
                tenant_max_in_flight=tenant_max_in_flight,
                tenant_max_queued=tenant_max_queued, quotas=quotas,
                clock=self.clock, metrics=self.metrics,
                journal_path=journal_path, breakers=breakers,
                chaos=chaos, runner_defaults=rd,
                mem_budget=mem_budget)
            self._own_sched = True
            self._breakers = self._sched.breakers
            self._mem_budget = mem_budget
        self.journal = self._sched.journal
        # serving-tier SLOs, on by default: p99-style query latency
        # and the error budget, ruled over the shared registry's
        # time-series trail and journaled into the query-funnel
        # journal.  slo_objectives=() disables; maybe_evaluate rides
        # the per-query accounting path (rate-limited, lock-free).
        from .slo import SLOMonitor, serving_objectives

        objectives = (serving_objectives()
                      if slo_objectives is None else slo_objectives)
        self.slo = (SLOMonitor(self.metrics, journal=self.journal,
                               clock=self.clock,
                               objectives=objectives)
                    if objectives else None)
        self._breaker = self._breakers.get(backend, clock=self.clock)
        self._state_lock = threading.Lock()
        # guards the standing reservation's closed-check-and-reserve
        # against close()'s release: without it an in-flight query's
        # re-place rung racing close() could re-reserve AFTER the
        # release and leak the hold on a shared pool's budget forever
        self._standing_lock = threading.Lock()
        self._acct_lock = threading.Lock()
        self._kernel_lock = threading.Lock()
        self._kernels: dict = {}
        self._outstanding: list[ServeTicket] = []
        self._swap_lock = threading.Lock()
        self._swap_claimed = False
        #: outcome record of the most recent :meth:`swap` — on
        #: success ``{"ok": True, "epoch", "version", "generation",
        #: "agreement"}``, on rollback ``{"ok": False, "reason",
        #: "epoch", ...}`` with the same fields the journal carries.
        #: The annotation factory reads this to journal its own
        #: cycle verdict without re-parsing the journal; swap is
        #: exclusive (try_acquire_swap), so no torn reads.
        self.last_swap: dict | None = None
        self._closed = False

        try:
            arrays, gen = self._load_verified_arrays(artifact)
            model = _ResidentModel(arrays, path=artifact, epoch=0,
                                   generation=gen)
            self._place_or_degrade(model)
        except BaseException:
            # a refused artifact must release the reserved name AND
            # not leak the private pool's process-global chaos hook
            # (RunScheduler.__init__ activated it; only shutdown
            # releases it)
            with _SERVICES_LOCK:
                if _SERVICES.get(name) is self:
                    del _SERVICES[name]
            if self._own_sched:
                self._sched.shutdown(wait=True)
            raise
        with self._state_lock:
            self._epoch = 0
            self._models = {0: model}
        self.journal.write("model_loaded", epoch=0, generation=gen,
                           version=model.version, reason="init")
        self._update_standing_reservation()

    # -- lifecycle -----------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self, wait: bool = True) -> None:
        """Stop admitting (private scheduler only), drain outstanding
        tickets' accounting, release the resident model's standing
        memory reservation, and unregister the service name."""
        self._closed = True
        try:
            if self._own_sched:
                self._sched.shutdown(wait=wait)
            self.drain(timeout=None if wait else 0.0)
        finally:
            if self._mem_budget is not None:
                # release under the standing lock: _closed is already
                # set, so a racing _update_standing_reservation either
                # ran before this (its hold is released here) or sees
                # _closed under the lock and does nothing
                with self._standing_lock:
                    held = self._mem_budget.holders().get(
                        self._standing_name())
                    self._mem_budget.release(self._standing_name())
                if held is not None:
                    self.journal.write(
                        "mem_released", standing=True,
                        service=self.name, bytes=held["bytes"],
                        reserved_total=
                        self._mem_budget.reserved_bytes())
            with _SERVICES_LOCK:
                if _SERVICES.get(self.name) is self:
                    del _SERVICES[self.name]

    def _standing_name(self) -> str:
        return f"serve:{self.name}:model"

    def _update_standing_reservation(self) -> None:
        """Size the resident model's STANDING reservation to the live
        models' placed device bytes (current + previous epoch — both
        stay resident across a swap).  Re-reserving the same name
        REPLACES the amount, so place / re-place / swap / eviction
        all converge on the truth; journaled only when the amount
        actually moved."""
        budget = self._mem_budget
        if budget is None:
            return
        changed = None
        # the model-set read AND the reserve commit share the standing
        # lock (state lock nested inside — nothing nests the other
        # way): a racing swap/re-place computing a STALE total must
        # not commit it last and leave the ledger under-counting the
        # resident bytes until the next placement event
        with self._standing_lock:
            with self._state_lock:
                models = list(getattr(self, "_models", {}).values())
            total = 0
            for mo in models:
                dev = mo._dev
                if dev:
                    total += sum(int(a.nbytes) for a in dev.values())
            if self._closed:
                # close() released (or is about to release) the hold
                # under this same lock — re-reserving here would leak
                # it on a shared pool's budget forever
                return
            prev = budget.holders().get(self._standing_name())
            if prev is not None and prev["bytes"] == total:
                return
            if total > 0:
                reserved = budget.reserve(self._standing_name(),
                                          total, standing=True)
                changed = ("reserve", total, reserved)
            elif prev is not None:
                reserved = budget.release(self._standing_name())
                changed = ("release", prev["bytes"], reserved)
        # journal OUTSIDE the lock (SCT011 discipline), with literal
        # event names (SCT009)
        if changed is not None:
            kind, nbytes, reserved = changed
            if kind == "reserve":
                self.journal.write("mem_reserved", standing=True,
                                   service=self.name, bytes=nbytes,
                                   reserved_total=reserved)
            else:
                self.journal.write("mem_released", standing=True,
                                   service=self.name, bytes=nbytes,
                                   reserved_total=reserved)

    def drain(self, timeout: float | None = None) -> None:
        """Account every outstanding ticket that is (or becomes,
        within ``timeout``) terminal — the sweep that keeps
        ``serve.queries{outcome=}`` complete even for callers that
        never touched their tickets.  Loops until the outstanding
        list is empty (a query racing :meth:`close` past the closed
        check is swept too) or a ticket stays non-terminal past
        ``timeout``."""
        while True:
            with self._acct_lock:
                pending = list(self._outstanding)
            if not pending:
                return
            leftover = 0
            for t in pending:
                t.wait(timeout)
                if t.done():
                    self._account(t, t.handle.status)
                else:
                    leftover += 1
            if leftover:
                return  # timed out on these; a later drain can finish

    # -- introspection -------------------------------------------------
    @property
    def scheduler(self):
        """The admission funnel this service runs queries through —
        shared when one was passed at construction, else the
        service-owned pool.  The annotation factory submits
        retraining through exactly this object so training contends
        with (and is preempted by) live query traffic."""
        return self._sched

    @property
    def epoch(self) -> int:
        with self._state_lock:
            return self._epoch

    @property
    def model_version(self) -> str:
        with self._state_lock:
            return self._models[self._epoch].version

    def scvi_params(self):
        """The serving model's embedded scvi parameters (or None)."""
        with self._state_lock:
            model = self._models[self._epoch]
        return model.scvi_params()

    def stats(self) -> dict:
        with self._state_lock:
            out = {"epoch": self._epoch,
                   "version": self._models[self._epoch].version,
                   "resident": self._models[self._epoch].resident(),
                   "epochs_live": sorted(self._models)}
        with self._acct_lock:
            out["outstanding"] = len(self._outstanding)
        # scheduler stats take its own locks (and breaker snapshots):
        # composed OUTSIDE ours
        out["scheduler"] = self._sched.stats()
        return out

    # -- admission -----------------------------------------------------
    def query(self, X, kind: str = "label_transfer", *,
              tenant: str = "default", priority: int = 0,
              deadline_s: float | None = None, k: int | None = None,
              score_set: str | None = None,
              trace_id: str | None = None) -> ServeTicket:
        """Admit one query batch (or refuse it — the scheduler's
        :class:`~sctools_tpu.scheduler.RunRejected`, counted
        ``outcome=rejected``).  ``X`` is raw counts — CellData, numpy,
        scipy or a device array — with the model's gene space; it is
        zero-padded to the shape bucket and submitted as one
        ``serve.query`` step pinned to the CURRENT epoch.  Returns a
        :class:`ServeTicket` immediately."""
        if self._closed:
            raise RuntimeError(
                f"AnnotationService {self.name!r} is closed — a "
                f"query would be admitted by the (shared) scheduler "
                f"only to fail at dispatch")
        # opportunistic sweep of already-terminal tickets: fire-and-
        # forget callers (never touching their tickets) must not grow
        # _outstanding — and pin every result payload — unboundedly
        # until close(); done() is one Event check, no blocking
        with self._acct_lock:
            done_now = [t for t in self._outstanding
                        if t.handle.done()]
        for t in done_now:
            self._account(t, t.handle.status)
        if kind not in QUERY_KINDS:
            raise ValueError(
                f"query kind {kind!r}: use one of {QUERY_KINDS}")
        with self._state_lock:
            epoch = self._epoch
            model = self._models[epoch]
        if kind == "marker_score":
            if not score_set:
                raise ValueError(
                    "marker_score queries need score_set= (one of "
                    f"{model.score_sets})")
            if score_set not in model.score_sets:
                raise ValueError(
                    f"unknown score_set {score_set!r}; the serving "
                    f"model carries {model.score_sets}")
        Xq, n = self._as_query_matrix(X, model)
        bucket = bucket_rows(n, self.buckets)
        Xp = np.zeros((bucket, Xq.shape[1]), np.float32)
        Xp[:n] = Xq
        data = CellData(Xp,
                        obs={"serve_valid": np.arange(bucket) < n})
        pipe = Pipeline([Transform(
            "serve.query", backend=self.backend, service=self.name,
            kind=kind, epoch=epoch,
            k=int(k if k is not None else self.k),
            metric=self.metric, score_set=score_set or "")])
        t0 = self.clock.monotonic()
        try:
            # the causal id is stamped at THIS admission (or passed
            # through from an upstream caller — the factory's cycle):
            # the scheduler journals it on the whole query funnel and
            # the runner carries it into span metadata
            handle = self._sched.submit(
                pipe, data, tenant=tenant, priority=priority,
                deadline_s=deadline_s, backend=self.backend,
                trace_id=trace_id)
        except RunRejected:
            self.metrics.counter("serve.queries",
                                 outcome="rejected").inc()
            raise
        ticket = ServeTicket(self, handle, n=n, kind=kind,
                             epoch=epoch, t0=t0, levels=model.levels)
        with self._acct_lock:
            self._outstanding.append(ticket)
        return ticket

    def _account(self, ticket: ServeTicket, outcome: str) -> None:
        with self._acct_lock:
            if ticket._accounted:
                return
            ticket._accounted = True
            if ticket in self._outstanding:
                self._outstanding.remove(ticket)
        self.metrics.counter("serve.queries", outcome=outcome).inc()
        if outcome == "completed":
            # the handle's own terminal stamp (scheduler clock — the
            # same clock, adopted), NOT the collection time: a caller
            # sitting on a finished ticket must not inflate the
            # latency histogram with its idle wall
            t1 = (ticket.handle.finished_at
                  if ticket.handle.finished_at is not None
                  else self.clock.monotonic())
            self.metrics.histogram("serve.latency_s").observe(
                t1 - ticket._t0)
        # SLO rulings ride the accounting cadence (rate-limited on
        # the injectable clock; a no-op between intervals)
        if self.slo is not None:
            self.slo.maybe_evaluate()

    def _as_query_matrix(self, X, model: _ResidentModel):
        import scipy.sparse as sp

        n_trim = None
        if isinstance(X, CellData):
            n_trim = X.n_cells
            if (model.gene_names is not None
                    and "gene_name" in X.var):
                qn = np.asarray(X.var["gene_name"]).astype(str)
                if qn.shape == model.gene_names.shape \
                        and not (qn == model.gene_names).all():
                    bad = int(np.argmin(qn == model.gene_names))
                    raise ValueError(
                        "query/reference gene names differ (first "
                        f"mismatch at {bad}) — align var spaces "
                        "first (integrate.ingest's contract)")
            X = X.X
        if hasattr(X, "to_scipy_csr"):
            X = X.to_scipy_csr()
        if sp.issparse(X):
            Xq = np.asarray(X.todense(), np.float32)
        else:
            Xq = np.asarray(X, np.float32)
        if Xq.ndim == 1:
            Xq = Xq[None, :]
        if n_trim is not None:
            Xq = Xq[:n_trim]
        if Xq.shape[1] != model.n_genes:
            raise ValueError(
                f"query has {Xq.shape[1]} genes but the serving "
                f"model was built over {model.n_genes} — queries "
                f"must share the reference's gene space")
        if Xq.shape[0] < 1:
            raise ValueError("empty query batch")
        return Xq, int(Xq.shape[0])

    # -- verified artifact loads ---------------------------------------
    def _load_verified_arrays(self, path: str):
        """Newest loadable artifact generation, VERIFIED: current,
        then ``.prev``.  A generation that fails the digest/
        fingerprint verify is quarantined (never deleted) with a
        journaled ``model_quarantined`` and the next one is tried.
        Deliberately a local twin of
        ``checkpoint.load_npz_generations`` rather than a call to it:
        serving additionally REQUIRES integrity keys, must journal
        the quarantine into the service's funnel, reports WHICH
        generation served (the swap/rollback evidence), and raises —
        not ``None`` — when nothing loads."""
        last_reason = "no artifact file"
        for cand, gen in ((path, "current"), (path + ".prev", "prev")):
            if not os.path.exists(cand):
                continue
            try:
                arrays = load_npz_verified(
                    cand, expect_fingerprint=SERVING_MODEL_FP,
                    require_digest=True)
                return arrays, gen
            except CheckpointCorruptError as e:
                last_reason = e.reason
                qpath = quarantine_checkpoint(cand, e.reason)
                warnings.warn(
                    f"AnnotationService: artifact generation "
                    f"{cand!r} failed verification ({e.reason}) — "
                    f"QUARANTINED to {qpath!r}, trying the previous "
                    f"generation", RuntimeWarning, stacklevel=3)
                self.journal.write("model_quarantined", path=qpath,
                                   reason=e.reason, generation=gen)
        raise CheckpointCorruptError(
            path, f"no loadable artifact generation ({last_reason})")

    def _rule_placement_failure(self, e: BaseException) -> str:
        """ONE ruling for a resident-state placement/kernel failure
        (three ladder sites share it, so breaker-feeding can never
        diverge between them): transient outages feed the shared
        breaker and rule the ``cpu`` host rung; RESOURCE means full,
        not broken — the ``oom`` host rung, breaker untouched;
        anything else re-raises (a program error must fail the
        query, not hide behind the ladder)."""
        cls = classify_error(e)
        if cls not in (TRANSIENT, RESOURCE):
            raise e
        if cls == TRANSIENT:
            self._breaker.record_failure()
        return "cpu" if cls == TRANSIENT else "oom"

    def _place_or_degrade(self, model: _ResidentModel) -> None:
        """Initial placement: a transiently-dead device (or one with
        no memory left — RESOURCE) must not kill the constructor —
        the model stays host-resident (the cpu rung) and the ladder
        re-places on a later query."""
        try:
            model.place()
        except Exception as e:  # noqa: BLE001 — classified below
            reason = self._rule_placement_failure(e)
            warnings.warn(
                f"AnnotationService: device placement failed "
                f"({reason} rung: {type(e).__name__}: {e}) — serving "
                f"from host arrays until the ladder re-places.",
                RuntimeWarning, stacklevel=3)

    # -- the hot-swap --------------------------------------------------
    def try_acquire_swap(self) -> bool:
        """Claim the EXCLUSIVE swap slot (one model swap in flight at
        a time; a second concurrent :meth:`swap` is refused rather
        than queued).  True for exactly one caller until
        :meth:`release_swap`; the pairing is machine-checked (sctlint
        SCT010 tracks this claim like the breaker probe slot)."""
        with self._swap_lock:
            if self._swap_claimed:
                return False
            self._swap_claimed = True
            return True

    def release_swap(self) -> None:
        with self._swap_lock:
            self._swap_claimed = False

    def swap(self, artifact: str) -> bool:
        """Epoch-guarded hot-swap to a new artifact under live
        traffic.

        The candidate loads VERIFIED (corrupt → quarantine + its own
        ``.prev``; nothing loadable → rolled back), is placed BESIDE
        the serving model, and must re-derive its own canary labels
        (agreement >= ``canary_threshold`` — the canary ran through
        the same bucketized plan path real queries use, which also
        pre-warms the plan cache for the new epoch).  Only then does
        the serving epoch flip; queries admitted before the flip
        complete on the model they were admitted under (the previous
        epoch stays resident until the NEXT swap).  Returns True
        (journal ``model_swapped``) or False on auto-rollback
        (journal ``swap_rolled_back``; the old epoch keeps serving).
        """
        if self._closed:
            raise RuntimeError(
                f"AnnotationService {self.name!r} is closed")
        if not self.try_acquire_swap():
            raise RuntimeError(
                "AnnotationService.swap: another swap is in flight")
        try:
            try:
                arrays, gen = self._load_verified_arrays(artifact)
                cand = _ResidentModel(arrays, path=artifact,
                                      epoch=-1, generation=gen)
            except (CheckpointCorruptError, ValueError) as e:
                self.last_swap = {"ok": False,
                                  "reason": "artifact_corrupt",
                                  "error": str(e),
                                  "epoch": self.epoch}
                self.journal.write(
                    "swap_rolled_back", reason="artifact_corrupt",
                    error=str(e), epoch=self.epoch)
                self.metrics.counter("serve.rollbacks").inc()
                warnings.warn(
                    f"AnnotationService.swap: candidate artifact "
                    f"refused ({e}) — ROLLED BACK, the serving epoch "
                    f"is unchanged.", RuntimeWarning, stacklevel=2)
                return False
            try:
                cand.place()
            except Exception as e:  # noqa: BLE001 — a device refusing
                # the candidate's placement (flaky/evicted — the very
                # regime operators swap in) is a ROLLBACK, not an
                # unjournaled raise; the old epoch keeps serving and
                # its own ladder handles the device
                if classify_error(e) == TRANSIENT:
                    self._breaker.record_failure()
                self.last_swap = {"ok": False,
                                  "reason": "placement_failed",
                                  "error": f"{type(e).__name__}: {e}",
                                  "epoch": self.epoch}
                self.journal.write(
                    "swap_rolled_back", reason="placement_failed",
                    error=f"{type(e).__name__}: {e}",
                    epoch=self.epoch)
                self.metrics.counter("serve.rollbacks").inc()
                warnings.warn(
                    f"AnnotationService.swap: candidate placement "
                    f"failed ({type(e).__name__}: {e}) — ROLLED "
                    f"BACK, the serving epoch is unchanged.",
                    RuntimeWarning, stacklevel=2)
                return False
            try:
                agreement = self._canary_agreement(cand)
            except Exception as e:  # noqa: BLE001 — a canary that
                # cannot even EXECUTE (candidate buffers evicted
                # between place and validate, a kernel raise) refuses
                # the candidate like a disagreement would: journaled
                # rollback, old epoch keeps serving
                if classify_error(e) == TRANSIENT:
                    self._breaker.record_failure()
                self.last_swap = {"ok": False,
                                  "reason": "canary_failed",
                                  "error": f"{type(e).__name__}: {e}",
                                  "epoch": self.epoch}
                self.journal.write(
                    "swap_rolled_back", reason="canary_failed",
                    error=f"{type(e).__name__}: {e}",
                    epoch=self.epoch)
                self.metrics.counter("serve.rollbacks").inc()
                warnings.warn(
                    f"AnnotationService.swap: canary validation "
                    f"raised ({type(e).__name__}: {e}) — ROLLED "
                    f"BACK, the serving epoch is unchanged.",
                    RuntimeWarning, stacklevel=2)
                return False
            if agreement < self.canary_threshold:
                self.last_swap = {"ok": False,
                                  "reason": "canary_disagreement",
                                  "agreement": round(agreement, 4),
                                  "candidate_version": cand.version,
                                  "epoch": self.epoch}
                self.journal.write(
                    "swap_rolled_back", reason="canary_disagreement",
                    agreement=round(agreement, 4),
                    candidate_version=cand.version, epoch=self.epoch)
                self.metrics.counter("serve.rollbacks").inc()
                warnings.warn(
                    f"AnnotationService.swap: candidate "
                    f"{cand.version!r} re-derived only "
                    f"{agreement:.1%} of its own canary labels "
                    f"(threshold {self.canary_threshold:.1%}) — "
                    f"ROLLED BACK.", RuntimeWarning, stacklevel=2)
                return False
            with self._state_lock:
                self._epoch += 1
                cand.epoch = self._epoch
                self._models[self._epoch] = cand
                # keep exactly current + previous: in-flight queries
                # are pinned to the epoch they were admitted under,
                # and anything older has no admitted queries left by
                # the time a SECOND swap lands (swaps are operator
                # actions, not traffic)
                for e in [e for e in self._models
                          if e < self._epoch - 1]:
                    del self._models[e]
            self.last_swap = {"ok": True, "epoch": cand.epoch,
                              "version": cand.version,
                              "generation": gen,
                              "agreement": round(agreement, 4)}
            self.journal.write("model_swapped", epoch=cand.epoch,
                               version=cand.version, generation=gen,
                               agreement=round(agreement, 4))
            self.metrics.counter("serve.swaps").inc()
            # both epochs are now resident (in-flight queries pin the
            # old one) — the standing reservation must say so
            self._update_standing_reservation()
            return True
        finally:
            self.release_swap()

    def _canary_agreement(self, model: _ResidentModel) -> float:
        """Label-transfer the model's own canary cells through the
        bucketized plan path and score agreement with the recorded
        codes.  Reference cells re-queried against their own model
        land on themselves (distance ~0 dominates the vote), so a
        healthy model scores ~1.0; garbage loadings or cross-wired
        state cannot."""
        host = model.host_arrays()
        cx = np.asarray(host["canary_x"], np.float32)
        bucket = bucket_rows(cx.shape[0], self.buckets)
        Xp = np.zeros((bucket, cx.shape[1]), np.float32)
        Xp[: cx.shape[0]] = cx
        data = CellData(Xp, obs={"serve_valid":
                                 np.arange(bucket) < cx.shape[0]})
        out = self._run_plan(data, model, "label_transfer", self.k,
                             self.metric, None)
        pred = np.asarray(out.obs["serve_label_code"])[: cx.shape[0]]
        return float(np.mean(pred == np.asarray(host["canary_codes"])))

    # -- query execution (scheduler worker side) ------------------------
    def _model_for(self, epoch: int) -> _ResidentModel:
        with self._state_lock:
            model = self._models.get(epoch)
            current = self._epoch
        if model is None:
            raise RuntimeError(
                f"serve.query: epoch {epoch} has been retired "
                f"(serving epoch {current}) — the query outlived two "
                f"hot-swaps; resubmit")
        return model

    def _execute_query(self, data: CellData, kind: str, epoch: int,
                       k: int, metric: str,
                       score_set: str | None) -> CellData:
        model = self._model_for(epoch)
        if self.chaos is not None:
            ruling = self.chaos.on_serving(self.name, path=model.path,
                                           backend=self.backend)
            if ruling is not None:
                self._apply_chaos_ruling(ruling, model)
        mode = self._ensure_state(model)
        if mode == "device":
            out = self._run_plan(data, model, kind, k, metric,
                                 score_set)
        else:
            out = self._run_host_query(data, model, kind, k, metric,
                                       score_set)
        return out.with_uns(serve_epoch=np.int64(epoch),
                            serve_mode=np.array(mode))

    def _apply_chaos_ruling(self, ruling: dict,
                            model: _ResidentModel) -> None:
        mode = ruling.get("mode")
        if mode == "evict_state":
            model.evict()
        elif mode == "corrupt_model":
            # the monkey already damaged the artifact bytes; dropping
            # BOTH residency tiers forces the ladder all the way to
            # the verified reload, where the damage is caught
            model.evict()
            model.drop_host()

    def _ensure_state(self, model: _ResidentModel) -> str:
        """The residency ladder (module docstring): returns
        ``"device"`` or ``"host"`` — the mode this query executes in.
        Raises when no rung can produce servable state (classified by
        the runner like any other step failure)."""
        if not self._breaker.allow():
            # breaker OPEN (this service's or any pool sharer's trip):
            # no placement storm — serve from host arrays outright
            if model.has_host():
                self.metrics.counter("serve.state_reloads",
                                     reason="breaker_open").inc()
                return "host"
        if model.resident():
            return "device"
        if model.has_host():
            # rung 2: re-place the evicted device state from the host
            # mirror
            try:
                model.place()
                self.metrics.counter("serve.state_reloads",
                                     reason="replace").inc()
                self._update_standing_reservation()
                return "device"
            except Exception as e:  # noqa: BLE001 — classified below
                reason = self._rule_placement_failure(e)
                self.metrics.counter("serve.state_reloads",
                                     reason=reason).inc()
                return "host"
        # rung 3: the host mirror is gone too — verified reload from
        # the artifact (corrupt generation → quarantine + .prev,
        # journaled by _load_verified_arrays)
        arrays, gen = self._load_verified_arrays(model.path)
        model._rehost(arrays)
        self.journal.write("model_loaded", epoch=model.epoch,
                           generation=gen, version=model.version,
                           reason="reload")
        self.metrics.counter("serve.state_reloads",
                             reason="artifact").inc()
        if not self._breaker.allow():
            # the reload rebuilt the host mirror, but the breaker is
            # (still) OPEN: no per-query placement storm against a
            # suspect device — serve host until a sharer's probe
            # closes it
            self.metrics.counter("serve.state_reloads",
                                 reason="breaker_open").inc()
            return "host"
        try:
            model.place()
            self._update_standing_reservation()
            return "device"
        except Exception as e:  # noqa: BLE001 — classified below
            # rung 4: the device itself is refusing placement — serve
            # from the fresh host mirror
            reason = self._rule_placement_failure(e)
            self.metrics.counter("serve.state_reloads",
                                 reason=reason).inc()
            return "host"

    def _kernel_for(self, model: _ResidentModel, kind: str, k: int,
                    metric: str) -> FusedTransform:
        m = model.meta
        key = (self.backend, kind, int(k), metric, m["n_levels"],
               m["target_sum"], m["log1p"], m["sim_ratio"],
               m["expected_rate"])
        with self._kernel_lock:
            ft = self._kernels.get(key)
            if ft is None:
                ft = FusedTransform(
                    [Transform("serve.kernel", backend=self.backend,
                               kind=kind, k=int(k), metric=metric,
                               n_levels=m["n_levels"],
                               target_sum=m["target_sum"],
                               log1p=m["log1p"],
                               sim_ratio=m["sim_ratio"],
                               expected_rate=m["expected_rate"])],
                    self.backend, metrics=self.metrics)
                self._kernels[key] = ft
        return ft

    def _run_plan(self, data: CellData, model: _ResidentModel,
                  kind: str, k: int, metric: str,
                  score_set: str | None) -> CellData:
        """Execute the pure kernel as a fused plan: model arrays ride
        as INPUT leaves (``uns``), so every same-shaped execution —
        across queries, evictions, re-places and same-shaped swaps —
        is a plan-cache hit (``plan.cache_hits``)."""
        dev = model.device_arrays()
        uns: dict = {}
        if kind == "marker_score":
            uns["serve_weights"] = dev[f"score/{score_set}"]
        else:
            uns["serve_pcs"] = dev["PCs"]
            uns["serve_mu"] = dev["pca_mean"]
            uns["serve_ref"] = dev["ref_scores"]
            if kind == "label_transfer":
                uns["serve_codes"] = dev["label_codes"]
            else:
                uns["serve_sim"] = dev["sim_scores"]
        payload = CellData(data.X, obs=dict(data.obs), uns=uns)
        return self._kernel_for(model, kind, k, metric)(payload)

    def _run_host_query(self, data: CellData, model: _ResidentModel,
                        kind: str, k: int, metric: str,
                        score_set: str | None) -> CellData:
        """The cpu rung: the numpy twin over the host mirror (results
        match the device path to f32 tolerance; tests pin it)."""
        host = dict(model.host_arrays())
        if kind == "marker_score":
            host["serve_weights"] = host[f"score/{score_set}"]
        res = annotate_host(host, np.asarray(data.X, np.float32),
                            kind, k=k, metric=metric)
        obs = dict(data.obs)
        obsm = {}
        if kind == "label_transfer":
            obs["serve_label_code"] = res["codes"]
            obs["serve_label_conf"] = res["confidence"]
            obsm["serve_scores"] = res["scores"]
        elif kind == "doublet_flag":
            obs["serve_doublet"] = res["doublet_score"]
            obsm["serve_scores"] = res["scores"]
        else:
            obs["serve_score"] = res["score"]
        return CellData(data.X, obs=obs, obsm=obsm)

    def __repr__(self):
        return (f"AnnotationService({self.name!r}, epoch={self.epoch},"
                f" backend={self.backend!r})")
