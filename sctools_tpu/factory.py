"""The annotation factory — a continuously-learning serving loop.

Every fault domain in the repo exists in isolation: durable shard
ingest (``data/shardstore.py``), pod-scale federation
(``federation.py``), preemptible streamed training
(``models/train_stream.py`` + ``scheduler.py``), the survivable
serving epoch (``serving.py``), the memory budget (``memory.py``).
:class:`AnnotationFactory` composes them into ONE closed loop —
annbatch's out-of-core training story fused with the raw-count
annotation survey's production-annotation story (PAPERS.md): a
service that keeps learning from fresh uploads without ever dropping
a query.

One **cycle** climbs four stages, each with its own durable cursor:

=========  =============================  ==========================
stage      durable commit point           resume evidence
=========  =============================  ==========================
ingest     shard-store manifest replace   append ledger label
           (``StoreWriter.append_to``)    (at-most-once per batch)
train      training-cursor checkpoint     cursor ``(epoch, pos)`` +
           every shard boundary; params   store digest; params
           artifact before cursor clear   content digest
build      ``save_npz_generations``       artifact content digest
           atomic rename
swap       serving-epoch flip inside      service epoch + artifact
           ``AnnotationService.swap``     version string
=========  =============================  ==========================

A factory killed ANYWHERE re-enters ``run_cycle`` on the same
directory and resumes at the first uncommitted stage: committed work
is never replayed (ingest batches dedup on the manifest's append
ledger, training resumes bitwise from its cursor, a committed build
is recognised by its recorded digest, a swap that landed before the
crash is recognised by the service's resident version).  Stage state
lives in ``cycles/c<N>/state.json``, written atomically
(tmp + ``os.replace``) and **epoch-fenced**: constructing a factory
on a directory bumps ``owner.json``'s epoch, and every state commit
from a stale incarnation raises :class:`FactoryFencedError` instead
of overwriting the new owner's progress — the same fencing ruling
the federation supervisor applies to requeued tickets.

Stage ROUTING is where the composition happens:

* **ingest** runs as federation tickets (``data.append_store`` below)
  when a supervisor is attached — a SIGKILLed ingest worker is
  respawned, the ticket requeued, and the redo finds the batch label
  already in the manifest ledger (or redoes a torn append
  byte-identically: orphan chunk files beyond the committed manifest
  are overwritten deterministically);
* **retrain** is submitted through the SHARED
  :class:`~.scheduler.RunScheduler` with ``preemptible=True`` — a
  serving spike preempts training at a shard boundary
  (checkpoint-then-yield), and the memory budget's admission ruling
  applies to the trainer like any other job;
* **build** freezes the trained parameters (loaded from the
  digest-verified ``params_out`` artifact — the pytree never crosses
  a worker boundary in memory) into a serving artifact via
  :func:`~.serving.build_reference_artifact`;
* **swap** canary-validates the candidate into the live service;
  rollback (corrupt artifact, placement failure, canary
  disagreement) terminals the cycle as ``swap_rolled_back`` with the
  journaled reason — the old epoch keeps serving.

Journal contract (``telemetry.JOURNAL_PROTOCOLS['factory']``): every
record carries ``cycle=`` and NEVER ``ticket=`` — the factory is a
stage ladder, not an admission funnel, and must not merge with the
scheduler's terminal-exactly-once proof.  Events are journaled
AFTER their stage's durable commit and deduped across resumes via
the state file's ``journaled`` list (at-least-once across a crash in
the tiny commit→journal window, exactly-once otherwise).

Chaos: the factory consults :meth:`~.utils.chaos.ChaosMonkey
.on_factory` once per stage ENTRY (``stage_crash`` faults match
``"<factory>/<stage>"`` composites) and raises
:class:`~.utils.chaos.ChaosCrash` — the deterministic in-process
stand-in for a worker SIGKILLed BETWEEN stages, driving exactly the
cross-domain resume seams the table above promises.
"""

from __future__ import annotations

import json
import os

import numpy as np

from .data.io import from_dense
from .data.shardstore import ShardStore, StoreWriter
from .recipes import run_recipe
from .registry import Pipeline, register
from .runner import _Journal
from .utils.chaos import ChaosCrash


class FactoryFencedError(RuntimeError):
    """A stale factory incarnation tried to commit stage state after
    a newer incarnation claimed the directory (``owner.json`` epoch
    advanced).  The stale owner must stop — its view of the cycle is
    no longer authoritative; the new owner resumes from the durable
    cursors."""


@register("data.append_store", backend="tpu")
@register("data.append_store", backend="cpu")
def append_store(data, store_dir: str = "", label: str = "",
                 expect_genes: int = 0):
    """Append ``data``'s counts to the durable shard store at
    ``store_dir`` as ONE verified batch (``StoreWriter.append_to`` —
    digest-verified reopen, atomic manifest commit), recording
    ``label`` in the manifest's append ledger.  **At-most-once by
    redo**: when the label is already in the ledger the append is
    skipped — how a federation ticket requeued after a worker
    SIGKILL (or a factory resuming a torn cycle) redoes the stage
    without double-ingesting.  Results land in uns:
    ``append_store_rows`` (0 when skipped), ``append_store_skipped``
    and ``append_store_digest`` (the store digest AFTER the commit —
    the training stage pins its cursor to it).  One registration
    serves both backends: the work is host-side file IO."""
    import scipy.sparse as sp

    store = ShardStore.open(store_dir)
    if label and label in store.append_labels():
        return data.with_uns(
            append_store_rows=np.int64(0),
            append_store_skipped=np.bool_(True),
            append_store_digest=str(store.manifest["store_digest"]))
    w = StoreWriter.append_to(store, label=label or None,
                              n_genes=(int(expect_genes) or None))
    X = data.X
    block = (X.tocsr() if sp.issparse(X)
             else sp.csr_matrix(np.asarray(X)))
    w.append(block)
    out = w.close()
    return data.with_uns(
        append_store_rows=np.int64(block.shape[0]),
        append_store_skipped=np.bool_(False),
        append_store_digest=str(out.manifest["store_digest"]))


class AnnotationFactory:
    """Federation-supervised ingest → retrain → freeze → canary swap
    (module docstring: stage/cursor table, routing, fencing).

    Parameters
    ----------
    factory_dir : str
        Durable home of the loop: ``owner.json`` (incarnation fence),
        ``cycles/c<N>/`` (per-cycle state, training cursor, params
        artifact, candidate serving artifact).
    store_dir : str
        The LIVE shard store ingest appends to and training streams
        from.
    service : AnnotationService
        The live service the loop feeds.  The factory adopts its
        journal, clock, metrics, chaos and shared scheduler (so
        retraining contends with — and is preempted by — real query
        traffic) unless overridden.
    ref_source : callable
        ``ref_source(store) -> CellData`` — the labelled reference
        snapshot to freeze after retraining (raw counts in ``X``,
        labels in ``obs[labels_key]``).  The factory runs the
        ``annotation_reference`` recipe on it before the freeze.
    supervisor : FederationSupervisor | None
        When attached, every ingest batch runs as a federated ticket
        (worker kill/wedge containment included); ``None`` appends
        in-process through the same op function.
    train_kw : dict | None
        Hyperparameters forwarded to ``model.scvi_stream``
        (``n_latent``, ``epochs``, ``batch_size``, ``seed``, ...).
    train_priority / ingest_tenant / train_tenant
        Funnel placement.  Training should sit BELOW query priority
        so serving spikes preempt it at shard boundaries.
    result_timeout_s : float
        Real-time ceiling on any one ticket/run wait (the underlying
        waits are event-driven; chaos tests advance a VirtualClock,
        so terminals arrive fast in real time).
    """

    STAGES = ("ingest", "train", "build", "swap")

    def __init__(self, factory_dir: str, *, store_dir: str, service,
                 ref_source, name: str = "factory",
                 supervisor=None, scheduler=None,
                 labels_key: str = "cell_type",
                 score_sets: dict | None = None,
                 n_components: int = 30, backend: str = "cpu",
                 train_kw: dict | None = None,
                 train_tenant: str = "factory-train",
                 train_priority: int = 0,
                 ingest_tenant: str = "factory-ingest",
                 ingest_priority: int = 0,
                 canary_seed: int = 0,
                 result_timeout_s: float = 300.0,
                 chaos=None, journal=None):
        self.factory_dir = str(factory_dir)
        self.store_dir = str(store_dir)
        self.service = service
        self.supervisor = supervisor
        self.scheduler = (scheduler if scheduler is not None
                          else service.scheduler)
        self.ref_source = ref_source
        self.name = name
        self.labels_key = labels_key
        self.score_sets = dict(score_sets or {})
        self.n_components = int(n_components)
        self.backend = backend
        self.train_kw = dict(train_kw or {})
        self.train_tenant = train_tenant
        self.train_priority = int(train_priority)
        self.ingest_tenant = ingest_tenant
        self.ingest_priority = int(ingest_priority)
        self.canary_seed = int(canary_seed)
        self.result_timeout_s = float(result_timeout_s)
        self.chaos = chaos if chaos is not None else service.chaos
        self.clock = service.clock
        self.metrics = service.metrics
        if journal is None:
            self.journal = service.journal
        elif isinstance(journal, str):
            self.journal = _Journal(journal)
        else:
            self.journal = journal
        os.makedirs(os.path.join(self.factory_dir, "cycles"),
                    exist_ok=True)
        # incarnation fence: claim the directory by bumping the owner
        # epoch; every later state commit re-checks it, so a stale
        # incarnation can never clobber the new owner's progress
        self._owner_epoch = self._read_owner() + 1
        self._write_json(os.path.join(self.factory_dir, "owner.json"),
                         {"epoch": self._owner_epoch})

    # -- durable state -------------------------------------------------
    @staticmethod
    def _write_json(path: str, obj: dict) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1, sort_keys=True)
        os.replace(tmp, path)

    def _read_owner(self) -> int:
        path = os.path.join(self.factory_dir, "owner.json")
        try:
            with open(path) as f:
                return int(json.load(f).get("epoch", 0))
        except (OSError, ValueError, json.JSONDecodeError):
            return 0

    def _check_fence(self) -> None:
        cur = self._read_owner()
        if cur != self._owner_epoch:
            raise FactoryFencedError(
                f"factory {self.name!r} incarnation {self._owner_epoch}"
                f" fenced by incarnation {cur} on {self.factory_dir} —"
                f" refusing a stale stage commit")

    def cycle_dir(self, cycle: int) -> str:
        return os.path.join(self.factory_dir, "cycles",
                            f"c{int(cycle):04d}")

    def _state_path(self, cycle: int) -> str:
        return os.path.join(self.cycle_dir(cycle), "state.json")

    def load_state(self, cycle: int) -> dict:
        """The cycle's committed stage state (``{}``-shaped fresh
        record when none/unreadable — redo is safe, every stage's
        WORK is idempotent by cursor/ledger/digest)."""
        try:
            with open(self._state_path(cycle)) as f:
                return json.load(f)
        except (OSError, ValueError, json.JSONDecodeError):
            return {"journaled": [], "batches": {}}

    def _commit_state(self, cycle: int, st: dict) -> None:
        self._check_fence()
        self._write_json(self._state_path(cycle), st)

    def next_cycle(self) -> int:
        """The cycle ``run_cycle`` should run next: the latest
        non-terminal cycle (resume target) or one past the latest
        terminal one."""
        base = os.path.join(self.factory_dir, "cycles")
        ids = sorted(int(d[1:]) for d in os.listdir(base)
                     if d.startswith("c") and d[1:].isdigit())
        if not ids:
            return 0
        last = ids[-1]
        return last if not self.load_state(last).get("terminal") \
            else last + 1

    # -- journaling ----------------------------------------------------
    # each factory event is journaled exactly once across resumes:
    # _needs_journal gates the literal journal.write call site (SCT009/
    # SCT012 check literals, so the write stays inline at each stage),
    # _mark_journaled commits the dedup record right after the write —
    # a crash inside that tiny window re-journals (at-least-once
    # there, never lost)
    def _needs_journal(self, st: dict, key: str) -> bool:
        return key not in st.setdefault("journaled", [])

    def _mark_journaled(self, cycle: int, st: dict, key: str) -> None:
        st["journaled"].append(key)
        self._commit_state(cycle, st)

    def _enter_stage(self, cycle: int, stage: str) -> None:
        """Chaos seam at every stage boundary: a firing
        ``stage_crash`` fault dies HERE — after the previous stage's
        durable commit, before this stage's first byte of work."""
        if self.chaos is not None:
            r = self.chaos.on_factory(self.name, stage,
                                      backend=self.backend)
            if r is not None and r.get("mode") == "stage_crash":
                raise ChaosCrash(
                    f"factory {self.name!r} killed entering stage "
                    f"{stage!r} of cycle {cycle}")

    # -- stages --------------------------------------------------------
    def _append_batch(self, label: str, batch,
                      trace_id: str = "") -> dict:
        params = dict(store_dir=self.store_dir, label=label,
                      expect_genes=int(
                          ShardStore.open(self.store_dir).n_genes))
        if self.supervisor is not None:
            pipe = Pipeline([("data.append_store", params)])
            h = self.supervisor.submit(pipe, batch,
                                       tenant=self.ingest_tenant,
                                       priority=self.ingest_priority,
                                       backend=self.backend,
                                       trace_id=trace_id or None)
            out = h.result(timeout=self.result_timeout_s)
        else:
            out = append_store(batch, **params)
        return {"label": label,
                "rows": int(out.uns["append_store_rows"]),
                "skipped": bool(out.uns["append_store_skipped"]),
                "store_digest": str(out.uns["append_store_digest"])}

    def _stage_ingest(self, cycle: int, st: dict, batches) -> None:
        if "ingest" not in st:
            self._enter_stage(cycle, "ingest")
            done = st.setdefault("batches", {})
            # batches run SEQUENTIALLY: the manifest replace is the
            # commit point and append_to has no cross-writer lock —
            # concurrency here would be a lost-update race, not a
            # speedup (the appends are small against training wall)
            for label, batch in batches:
                if label not in done:
                    done[label] = self._append_batch(
                        label, batch,
                        trace_id=st.get("trace_id", ""))
                    self._commit_state(cycle, st)
            store = ShardStore.open(self.store_dir)
            st["ingest"] = {
                "labels": [label for label, _ in batches],
                "store_digest": str(store.manifest["store_digest"]),
                "n_cells": store.n_cells,
            }
            self._commit_state(cycle, st)
        for label in st["ingest"]["labels"]:
            info = st["batches"][label]
            if self._needs_journal(st, f"ingest:{label}"):
                self.journal.write(
                    "ingest_committed", cycle=int(cycle),
                    factory=self.name, label=label,
                    rows=info["rows"], skipped=info["skipped"],
                    store_digest=info["store_digest"],
                    trace_id=st.get("trace_id", ""))
                self._mark_journaled(cycle, st, f"ingest:{label}")

    def _stage_train(self, cycle: int, st: dict) -> None:
        cdir = self.cycle_dir(cycle)
        cursor = os.path.join(cdir, "cursor.npz")
        params_out = os.path.join(cdir, "params.npz")
        if "train" not in st:
            self._enter_stage(cycle, "train")
            if self._needs_journal(st, "retrain"):
                self.journal.write(
                    "retrain_triggered", cycle=int(cycle),
                    factory=self.name, tenant=self.train_tenant,
                    store_digest=st["ingest"]["store_digest"],
                    trace_id=st.get("trace_id", ""))
                self._mark_journaled(cycle, st, "retrain")
            kw = dict(self.train_kw)
            kw.setdefault("checkpoint_every", 1)
            pipe = Pipeline([("model.scvi_stream", dict(
                store_dir=self.store_dir, checkpoint=cursor,
                params_out=params_out, journal=self.journal.path,
                **kw))])
            h = self.scheduler.submit(
                pipe, _carrier(), tenant=self.train_tenant,
                priority=self.train_priority, backend=self.backend,
                preemptible=True,
                trace_id=st.get("trace_id") or None)
            out = h.result(timeout=self.result_timeout_s)
            st["train"] = {
                "params": params_out,
                "params_digest": str(
                    out.uns["scvi_stream_params_digest"]),
                "epochs": int(out.uns["scvi_stream_epochs"]),
                "store_digest": st["ingest"]["store_digest"],
            }
            self._commit_state(cycle, st)

    def _stage_build(self, cycle: int, st: dict) -> None:
        cdir = self.cycle_dir(cycle)
        artifact = os.path.join(cdir, "artifact.npz")
        version = f"{self.name}-c{int(cycle):04d}"
        if "build" not in st:
            self._enter_stage(cycle, "build")
            from .models.scvi import load_model

            params, _meta = load_model(st["train"]["params"])
            ref = self.ref_source(ShardStore.open(self.store_dir))
            ref = run_recipe("annotation_reference", ref,
                             backend=self.backend,
                             n_components=self.n_components)
            digest = build_reference_artifact_checked(
                ref, artifact, labels_key=self.labels_key,
                score_sets=self.score_sets, seed=self.canary_seed,
                version=version, scvi_model=params)
            st["build"] = {"artifact": artifact, "digest": digest,
                           "version": version}
            self._commit_state(cycle, st)
        if self._needs_journal(st, "build"):
            self.journal.write(
                "artifact_built", cycle=int(cycle),
                factory=self.name, digest=st["build"]["digest"],
                version=st["build"]["version"],
                trace_id=st.get("trace_id", ""))
            self._mark_journaled(cycle, st, "build")

    def _stage_swap(self, cycle: int, st: dict) -> None:
        if "swap" not in st:
            self._enter_stage(cycle, "swap")
            version = st["build"]["version"]
            if self.service.model_version == version:
                # the flip landed before a crash took the factory
                # down — recognise it by the resident version rather
                # than re-swapping (a second flip would burn a
                # serving epoch for nothing)
                st["swap"] = {"ok": True,
                              "epoch": int(self.service.epoch),
                              "version": version,
                              "agreement": None, "resumed": True}
            else:
                ok = self.service.swap(st["build"]["artifact"])
                info = dict(self.service.last_swap or {})
                info["ok"] = bool(ok)
                info.setdefault("version", version)
                st["swap"] = info
            self._commit_state(cycle, st)
        sw = st["swap"]
        if sw.get("ok"):
            if self._needs_journal(st, "swap"):
                self.journal.write(
                    "swap_promoted", cycle=int(cycle),
                    factory=self.name, epoch=sw.get("epoch"),
                    version=sw.get("version"),
                    agreement=sw.get("agreement"),
                    trace_id=st.get("trace_id", ""))
                self._mark_journaled(cycle, st, "swap")
            st["terminal"] = "promoted"
        else:
            if self._needs_journal(st, "swap"):
                self.journal.write(
                    "swap_rolled_back", cycle=int(cycle),
                    factory=self.name,
                    reason=sw.get("reason", "unknown"),
                    epoch=sw.get("epoch"),
                    agreement=sw.get("agreement"),
                    trace_id=st.get("trace_id", ""))
                self._mark_journaled(cycle, st, "swap")
            st["terminal"] = "rolled_back"
        self._commit_state(cycle, st)

    # -- the loop ------------------------------------------------------
    def run_cycle(self, batches, *, cycle: int | None = None) -> dict:
        """Run (or RESUME) one full cycle over ``batches`` —
        ``[(label, CellData), ...]`` of fresh raw-count uploads — and
        return the terminal stage state.  Idempotent per cycle: a
        terminal cycle returns its record untouched; a torn cycle
        resumes at the first uncommitted stage (committed stages are
        skipped, proven by their cursors — no re-ingest, no replayed
        training shards, no rebuilt artifact, no double swap)."""
        if cycle is None:
            cycle = self.next_cycle()
        cycle = int(cycle)
        due = self.next_cycle()
        if cycle > due:
            # overlap refusal: a later cycle must not start while an
            # earlier one is live (non-terminal) — two cycles
            # interleaving their ingest cursors and swap verdicts on
            # one directory is exactly the double-promote shape the
            # incarnation fence exists to rule out
            raise ValueError(
                f"AnnotationFactory {self.name!r}: refusing to start "
                f"cycle {cycle} while cycle {due} is live "
                f"(non-terminal) — finish or roll back cycle {due} "
                f"first")
        os.makedirs(self.cycle_dir(cycle), exist_ok=True)
        st = self.load_state(cycle)
        if st.get("terminal"):
            return st
        if not st.get("trace_id"):
            # one trace context per CYCLE, minted at admission and
            # committed before any stage work: a resumed cycle reuses
            # the same id, so ingest tickets, the training run and the
            # swap all join into one fleet trace across crashes
            from .scheduler import new_trace_id

            st["trace_id"] = new_trace_id()
            self._commit_state(cycle, st)
        self._stage_ingest(cycle, st, list(batches))
        self._stage_train(cycle, st)
        self._stage_build(cycle, st)
        self._stage_swap(cycle, st)
        return st


def _carrier():
    """Minimal CellData vehicle for store-streaming pipeline steps
    (the counts live on disk; the funnel still wants a dataset)."""
    return from_dense(np.zeros((2, 2), np.float32))


def build_reference_artifact_checked(ref, path, **kw):
    """Thin indirection over :func:`~.serving.build_reference_artifact`
    (imported lazily so ``factory`` never pulls the serving module's
    jax surface at import time in journal-only tools)."""
    from .serving import build_reference_artifact

    return build_reference_artifact(ref, path, **kw)
