"""Shape bucketing: pad whole datasets to canonical bucket shapes.

Every differently-shaped upload that reaches :func:`plan.fused_pipeline`
traces and compiles its own XLA program — the cost rapids-singlecell
pays per GPU batch shape and annbatch pays at terabyte scale.  Serving
solved the QUERY half with a row-bucket ladder (PR 13: an n-row query
pads to the smallest bucket >= n, so every size in a bucket shares one
compiled program).  This module is the RECIPE half: pad whole
``CellData`` containers (cells AND genes) to bucket shapes, with an
explicit validity mask the mask-aware op family respects, so arbitrary
uploads hit one hot plan cache.

Policy
------
One ladder (:data:`DEFAULT_BUCKETS`, 16..4096 then doubling) serves
rows, genes and queries — serving's private ladder is now a re-export
of this one.  ``SparseCells`` capacity buckets to powers of two of the
lane multiple (128) for the same reason: capacity is a traced-shape
dimension that would otherwise retrace per upload nnz profile.

Mask convention
---------------
``pad_to_bucket`` zero-pads X/obs/var-aligned leaves and records the
validity mask in ``uns``:

* ``uns["bucket_row_mask"]``  — (bucket_rows,)  bool, True = real cell
* ``uns["bucket_col_mask"]``  — (bucket_genes,) bool, True = real gene
* ``uns["bucket_n_cells"]``   — 0-d int32, the true cell count
* ``uns["bucket_n_genes"]``   — 0-d int32, the true gene count

All four are NUMERIC leaves, so the plan cache keys them by
shape/dtype — they are TRACED inputs to the compiled program, never
baked constants.  Two uploads landing in the same bucket therefore
share one cache entry; the mask values flow in as runtime data.
Ops registered ``mask_aware=`` (see :mod:`sctools_tpu.registry`)
consult :func:`masks_of` and switch to masked reductions /
count-corrected moments so padded results match unpadded results on
the valid region.

Non-numeric annotation (gene-name strings, categorical labels) would
defeat the cache — opaque leaves are keyed by CONTENT digest — so
``pad_to_bucket`` stashes them host-side in the returned
:class:`BucketInfo` and ``trim_from_bucket`` restores them along with
cutting every leaf back to the true shape.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import numpy as np

from .config import config, round_up
from .data.dataset import CellData
from .data.sparse import SparseCells
from .utils import telemetry

#: the canonical shape-bucket ladder — serving's query ladder is this
#: same tuple (one constant to tune, one test surface); sizes past the
#: end keep doubling
DEFAULT_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)

#: uns keys carrying the validity mask (traced leaves — see module doc)
ROW_MASK_KEY = "bucket_row_mask"
COL_MASK_KEY = "bucket_col_mask"
N_CELLS_KEY = "bucket_n_cells"
N_GENES_KEY = "bucket_n_genes"
MASK_KEYS = (ROW_MASK_KEY, COL_MASK_KEY, N_CELLS_KEY, N_GENES_KEY)


def bucket_for(n: int, buckets=DEFAULT_BUCKETS) -> int:
    """Smallest bucket >= ``n``; doubles past the ladder's end."""
    if n < 1:
        raise ValueError("bucket_for: need at least one row/column")
    for b in buckets:
        if n <= b:
            return int(b)
    b = int(buckets[-1])
    while b < n:
        b *= 2
    return b


def capacity_bucket(capacity: int) -> int:
    """Bucketed ELL capacity: the next power-of-two multiple of the
    lane multiple (128).  Capacity is a traced-shape dim that varies
    with each upload's nnz profile — left unbucketed it would retrace
    per upload even when rows/genes bucket identically."""
    b = int(config.capacity_multiple)
    c = round_up(max(int(capacity), 1), b)
    while b < c:
        b *= 2
    return b


class BucketMasks(NamedTuple):
    """The validity mask quadruple a mask-aware op consumes.

    ``row``/``col`` are boolean arrays over the BUCKET shape;
    ``n_cells``/``n_genes`` are 0-d integer counts (traced — use them
    in arithmetic, never ``int()`` them inside jit).
    """

    row: Any  # (bucket_rows,) bool
    col: Any  # (bucket_genes,) bool
    n_cells: Any  # () int32
    n_genes: Any  # () int32


def masks_of(data) -> BucketMasks | None:
    """The dataset's bucket validity masks, or None when the data is
    not bucketized.  The single dispatch point of the mask-aware
    convention: ops branch on ``masks_of(data) is not None`` at trace
    time (key presence is part of the treedef, so the branch is
    stable per cache entry)."""
    uns = getattr(data, "uns", None)
    if not uns or ROW_MASK_KEY not in uns:
        return None
    try:
        return BucketMasks(uns[ROW_MASK_KEY], uns[COL_MASK_KEY],
                           uns[N_CELLS_KEY], uns[N_GENES_KEY])
    except KeyError as e:  # partial mask set = a corrupted container
        raise ValueError(
            f"bucketized data is missing mask key {e} — "
            f"pad_to_bucket writes all of {MASK_KEYS}") from None


@dataclasses.dataclass(frozen=True)
class BucketInfo:
    """Everything ``trim_from_bucket`` needs to undo a pad: the true
    shape, the bucket shape, and the stashed non-numeric annotation
    (kept host-side so opaque content never enters the plan key)."""

    n_cells: int
    n_genes: int
    bucket_cells: int
    bucket_genes: int
    stashed: dict  # (section, key) -> value

    @property
    def pad_rows(self) -> int:
        return self.bucket_cells - self.n_cells

    @property
    def pad_genes(self) -> int:
        return self.bucket_genes - self.n_genes


def _is_numeric_array(v) -> bool:
    dt = getattr(v, "dtype", None)
    return (dt is not None and getattr(dt, "kind", "?") in "biufc"
            and not (isinstance(v, np.ndarray) and dt.kind == "O"))


def _xp(a):
    """numpy for host arrays, jax.numpy for device arrays — padding at
    admission time must not force a host→device transfer."""
    if isinstance(a, np.ndarray):
        return np
    import jax.numpy as jnp

    return jnp


def _pad_axis(a, axis: int, target: int):
    """Zero-pad ``a`` along ``axis`` to length ``target``."""
    cur = a.shape[axis]
    if cur == target:
        return a
    if cur > target:
        raise ValueError(f"leaf axis {axis} is {cur}, exceeds the "
                         f"{target} bucket")
    xp = _xp(a)
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, target - cur)
    return xp.pad(a, widths)


def _pad_sparse(X: SparseCells, bucket_cells: int,
                bucket_genes: int) -> SparseCells:
    """Re-shape a padded-ELL matrix onto the bucket: rows pad with
    sentinel rows, capacity buckets to a pow2 lane multiple, and every
    existing sentinel (``== n_genes``) is REWRITTEN to the new
    one-past-the-end (``== bucket_genes``) — a stale sentinel would
    read as a real entry of gene ``n_genes`` and corrupt every
    segment reduction."""
    ind, dat = X.indices, X.data
    xp = _xp(ind)
    old_sent, new_sent = X.n_genes, bucket_genes
    if old_sent != new_sent:
        ind = xp.where(ind == old_sent, np.int32(new_sent),
                       ind).astype(np.int32)
    cap = capacity_bucket(X.capacity)
    if cap != X.capacity:
        ind = _pad_axis(ind, 1, cap)
        # freshly padded slots arrive as 0 (gene 0) — sentinel them
        pad = xp.arange(cap) >= X.capacity
        ind = xp.where(pad[None, :], np.int32(new_sent), ind)
        dat = _pad_axis(dat, 1, cap)
    if X.rows_padded != bucket_cells:
        if X.rows_padded > bucket_cells:
            raise ValueError(
                f"SparseCells rows_padded={X.rows_padded} exceeds the "
                f"{bucket_cells} bucket")
        extra = bucket_cells - X.rows_padded
        ind = xp.concatenate(
            [ind, xp.full((extra, cap), new_sent, np.int32)])
        dat = xp.concatenate([dat, xp.zeros((extra, cap), dat.dtype)])
    # n_cells/n_genes become the BUCKET dims (static aux data shared by
    # every upload in the bucket); the true counts live in the mask
    return SparseCells(ind, dat, bucket_cells, bucket_genes)


def _derive_mito(var: dict):
    """qc's mito fallback reads gene-name STRINGS at trace time; those
    are stashed (opaque), so bake the boolean column it derives — same
    predicate as ops/qc._mito_mask."""
    if "mito" in var or "gene_name" not in var:
        return None
    names = np.asarray(var["gene_name"])
    if names.dtype.kind not in ("U", "S", "O"):
        return None
    return np.char.startswith(np.char.upper(names.astype(str)), "MT-")


def pad_to_bucket(data: CellData, *, cell_buckets=DEFAULT_BUCKETS,
                  gene_buckets=DEFAULT_BUCKETS, metrics=None
                  ) -> tuple[CellData, BucketInfo]:
    """Pad ``data`` (cells AND genes) to its bucket shape.

    Returns ``(padded, info)``: ``padded`` carries the validity mask in
    ``uns`` (see module doc) and only numeric annotation; ``info``
    holds the stashed non-numeric leaves and the true shape for
    :func:`trim_from_bucket`.  Works on host (numpy/scipy) or device
    (jax) containers without changing residency.
    """
    import scipy.sparse as sp

    n, g = int(data.n_cells), int(data.n_genes)
    br = bucket_for(n, cell_buckets)
    bg = bucket_for(g, gene_buckets)
    stashed: dict = {}

    X = data.X
    if sp.issparse(X):
        X = SparseCells.from_scipy_csr(X)
    if isinstance(X, SparseCells):
        Xp = _pad_sparse(X, br, bg)
    else:
        Xp = _pad_axis(_pad_axis(X, 0, br), 1, bg)

    var_in = dict(data.var)
    mito = _derive_mito(var_in)
    if mito is not None:
        var_in["mito"] = mito

    def split(section: str, d: dict, pad_fn):
        out = {}
        for k, v in d.items():
            if _is_numeric_array(v):
                out[k] = pad_fn(v)
            else:
                stashed[(section, k)] = v
        return out

    obs = split("obs", data.obs, lambda v: _pad_axis(v, 0, br))
    var = split("var", var_in, lambda v: _pad_axis(v, 0, bg))
    obsm = split("obsm", data.obsm, lambda v: _pad_axis(v, 0, br))
    varm = split("varm", data.varm, lambda v: _pad_axis(v, 0, bg))
    obsp = split("obsp", data.obsp,
                 lambda v: _pad_axis(_pad_axis(v, 0, br), 1, br))
    layers = split(
        "layers", data.layers,
        lambda v: (_pad_sparse(v, br, bg) if isinstance(v, SparseCells)
                   else _pad_axis(_pad_axis(v, 0, br), 1, bg)))
    uns = split("uns", data.uns, lambda v: v)

    uns[ROW_MASK_KEY] = np.arange(br) < n
    uns[COL_MASK_KEY] = np.arange(bg) < g
    uns[N_CELLS_KEY] = np.asarray(n, np.int32)
    uns[N_GENES_KEY] = np.asarray(g, np.int32)

    m = metrics if metrics is not None else telemetry.default_registry()
    m.counter("bucket.pad_rows").inc(br - n)
    m.gauge("bucket.pad_frac", axis="cells").set((br - n) / br)
    m.gauge("bucket.pad_frac", axis="genes").set((bg - g) / bg)
    m.counter("bucket.hits", bucket=f"{br}x{bg}").inc()

    padded = CellData(Xp, obs=obs, var=var, obsm=obsm, varm=varm,
                      obsp=obsp, uns=uns, layers=layers)
    return padded, BucketInfo(n_cells=n, n_genes=g, bucket_cells=br,
                              bucket_genes=bg, stashed=stashed)


def _trim_axis(a, axis: int, target: int):
    if getattr(a, "ndim", 0) <= axis or a.shape[axis] <= target:
        return a
    sl = [slice(None)] * a.ndim
    sl[axis] = slice(0, target)
    return a[tuple(sl)]


def _trim_sparse(X: SparseCells, n: int, g: int) -> SparseCells:
    """Undo :func:`_pad_sparse`: cut padding rows back to the sublane
    multiple and rewrite the bucket sentinel to ``g``.  Capacity stays
    at its bucket (harmless: trailing slots are sentinel)."""
    rows = round_up(max(n, 1), config.sublane)
    ind = _trim_axis(X.indices, 0, rows)
    dat = _trim_axis(X.data, 0, rows)
    xp = _xp(ind)
    if X.n_genes != g:
        ind = xp.where(ind == X.n_genes, np.int32(g),
                       ind).astype(np.int32)
    return SparseCells(ind, dat, n, g)


def trim_from_bucket(data: CellData, info: BucketInfo) -> CellData:
    """Cut a bucketized result back to its true shape and restore the
    stashed non-numeric annotation.  uns arrays whose leading axis
    matches a bucket dim (op outputs like ``pca_mean``) are trimmed by
    the same rule as var/obs."""
    n, g = info.n_cells, info.n_genes
    br, bg = info.bucket_cells, info.bucket_genes

    X = data.X
    if isinstance(X, SparseCells):
        Xt = _trim_sparse(X, n, g)
    else:
        Xt = _trim_axis(_trim_axis(X, 0, n), 1, g)

    def cut(d: dict, fn):
        return {k: fn(v) for k, v in d.items()}

    def cut_uns(v):
        if _is_numeric_array(v) and getattr(v, "ndim", 0) >= 1:
            if v.shape[0] == br:
                return _trim_axis(v, 0, n)
            if v.shape[0] == bg:
                return _trim_axis(v, 0, g)
        return v

    obs = cut(data.obs, lambda v: _trim_axis(v, 0, n))
    var = cut(data.var, lambda v: _trim_axis(v, 0, g))
    obsm = cut(data.obsm, lambda v: _trim_axis(v, 0, n))
    varm = cut(data.varm, lambda v: _trim_axis(v, 0, g))
    obsp = cut(data.obsp,
               lambda v: _trim_axis(_trim_axis(v, 0, n), 1, n))
    layers = cut(
        data.layers,
        lambda v: (_trim_sparse(v, n, g) if isinstance(v, SparseCells)
                   else _trim_axis(_trim_axis(v, 0, n), 1, g)))
    uns = {k: cut_uns(v) for k, v in data.uns.items()
           if k not in MASK_KEYS}

    for (section, k), v in info.stashed.items():
        locals_map = {"obs": obs, "var": var, "obsm": obsm,
                      "varm": varm, "obsp": obsp, "uns": uns,
                      "layers": layers}
        locals_map[section].setdefault(k, v)

    return CellData(Xt, obs=obs, var=var, obsm=obsm, varm=varm,
                    obsp=obsp, uns=uns, layers=layers)


class TrimmingHandle:
    """Proxy around a scheduler :class:`RunHandle` whose ``result()``
    trims the bucket-padded output back to the caller's true shape.

    ``submit_recipe(..., bucketize=True)`` pads BEFORE admission (so
    the scheduler's memory estimate reads the bucket shape the device
    will actually hold) and hands this back so the caller never sees
    padding.  Everything else (``status``/``done``/``wait``/``cancel``/
    ``ticket``…) delegates to the wrapped handle.
    """

    def __init__(self, handle, info: BucketInfo):
        self._handle = handle
        self._info = info

    def result(self, timeout: float | None = None):
        return trim_from_bucket(self._handle.result(timeout), self._info)

    def __getattr__(self, name):
        return getattr(self._handle, name)


def validate_bucketizable(pipeline, backend: str) -> None:
    """Raise naming the first step that is not registered mask-aware —
    a non-mask-aware op would silently fold padding rows/genes into
    its reductions."""
    from . import registry

    for t in getattr(pipeline, "transforms", pipeline):
        name = getattr(t, "name", None) or t[0]
        params = getattr(t, "params", None)
        if params is None:
            params = t[1] if len(t) > 1 else {}
        if not registry.is_mask_aware(name, backend, params):
            raise ValueError(
                f"bucketize=True: step {name!r} (backend={backend}) is "
                f"not registered mask_aware — it would fold padding "
                f"into its reductions; run it unbucketized or register "
                f"a mask-aware adapter")
