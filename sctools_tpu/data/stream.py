"""Out-of-core streaming pipeline: atlas-scale matrices that do not
fit in HBM.

Reference parity: the reference framework streams AnnData CSR shards
through its preprocessing + kNN build (BASELINE.json north star: 10M
cells × 30k genes); its loader is native C++ (source unavailable —
SURVEY.md §0).

TPU design: the *sparse counts* are the only thing that doesn't fit —
at 10M cells the skinny dense iterates of randomized PCA ((n, ~60)
float32 ≈ 2.4 GB) and the final (n, 50) scores sit comfortably in HBM.
So the streaming decomposition is:

* **one stats pass** over h5ad CSR shards: each shard is packed to
  padded-ELL (native C++ packer), device_put, library-normalised +
  log1p'd, and reduced — per-cell QC metrics and per-gene
  (Σ, Σ², nnz) accumulate on device while the next shard loads (jax
  async dispatch overlaps the host IO with device compute);
* **HVG selection** from the accumulated per-gene moments
  (dispersion flavor — the normalised-variance ranking computable
  from one streaming pass);
* **streaming randomized PCA**: the power iteration's tall-skinny
  iterates Y/Q stay device-resident; each (re-)materialisation of
  ``Y = X_c @ Q`` / ``Z = X_cᵀ @ Q`` streams the HVG-subset shards
  through the fused subset→normalise→centered-matvec kernel.
  CholeskyQR2 orthonormalisation works on the device-resident Y —
  the same math as ops/pca.py, so single-chip and streaming paths
  agree to float tolerance;
* **kNN** on the device-resident scores via the standard blocked /
  Pallas search (ops/knn.py) — no extra streaming needed.

The full count matrix never exists in memory; peak host usage is one
shard, peak device usage is the skinny iterates.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..config import config, round_up
from .sparse import SparseCells, gene_stats, spmm, spmm_t


# ----------------------------------------------------------------------
# Shard sources
# ----------------------------------------------------------------------


@dataclasses.dataclass
class ShardSource:
    """A re-iterable source of (row_offset, device SparseCells) shards
    with uniform shapes (one compiled program serves every shard)."""

    factory: Callable[[], Iterator[SparseCells]]
    n_cells: int
    n_genes: int
    shard_rows: int

    def __iter__(self):
        offset = 0
        for shard in self.factory():
            yield offset, shard.device_put()
            offset += shard.n_cells

    @property
    def n_shards(self) -> int:
        return -(-self.n_cells // self.shard_rows)

    @classmethod
    def from_h5ad(cls, path: str, shard_rows: int = 65536,
                  capacity: int | None = None) -> "ShardSource":
        import h5py

        from .io import shard_iter

        # intermediate shards must have rows_padded == n_cells so row
        # offsets stay aligned across shards
        shard_rows = round_up(shard_rows, config.sublane)

        with h5py.File(path, "r") as h5:
            node = h5["X"]
            if hasattr(node, "attrs") and "shape" in node.attrs:
                n, g = tuple(node.attrs["shape"])
                if capacity is None and "indptr" in node:
                    # exact global max nnz/row from the indptr alone —
                    # no data read, and no risk of a later shard
                    # exceeding a first-shard estimate mid-stream
                    nnz_max = int(np.diff(node["indptr"][...]).max())
                    capacity = round_up(max(nnz_max, 1),
                                        config.capacity_multiple)
            else:
                n, g = node.shape
                if capacity is None:
                    # dense h5ad: any row may be fully dense
                    capacity = round_up(int(g), config.capacity_multiple)
        return cls(lambda: shard_iter(path, shard_rows, capacity=capacity),
                   int(n), int(g), shard_rows)

    @classmethod
    def from_scipy(cls, X, shard_rows: int = 65536,
                   capacity: int | None = None) -> "ShardSource":
        """In-memory CSR source (tests / moderate sizes)."""
        X = X.tocsr()
        n, g = X.shape
        shard_rows = round_up(shard_rows, config.sublane)
        if capacity is None:
            nnz_max = int(np.diff(X.indptr).max()) if X.nnz else 1
            capacity = round_up(max(nnz_max, 1), config.capacity_multiple)

        def factory():
            for s in range(0, n, shard_rows):
                yield SparseCells.from_scipy_csr(
                    X[s: s + shard_rows], capacity=capacity)

        return cls(factory, n, g, shard_rows)


# ----------------------------------------------------------------------
# Pass 1: QC + per-gene stats of the normalised log matrix
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("target_sum",))
def _shard_stats(x: SparseCells, mito_mask, target_sum: float):
    """Per-shard: (per-cell totals, n_genes, pct_mito;
    per-gene Σ/Σ²/nnz of log1p-normalised values)."""
    from ..ops.normalize import _library_size_sparse

    totals = jnp.sum(x.data, axis=1)
    n_genes_cell = x.nnz_per_row()
    mito_pad = jnp.concatenate([mito_mask.astype(x.data.dtype),
                                jnp.zeros((1,), x.data.dtype)])
    mito_counts = jnp.sum(
        x.data * jnp.take(mito_pad, x.indices), axis=1)
    pct_mito = jnp.where(totals > 0, 100.0 * mito_counts /
                         jnp.maximum(totals, 1e-12), 0.0)
    xs, _ = _library_size_sparse(x, target_sum)
    xn = xs.with_data(jnp.log1p(xs.data))
    s, ss, nnz = gene_stats(xn)
    return totals, n_genes_cell, pct_mito, jnp.stack([s, ss, nnz], axis=1)


def stream_stats(src: ShardSource, target_sum: float = 1e4,
                 mito_mask: np.ndarray | None = None) -> dict:
    """One pass: per-cell QC metrics (host) + per-gene moments of the
    normalised log matrix (device accumulator)."""
    if mito_mask is None:
        mito_mask = np.zeros(src.n_genes, bool)
    mito = jnp.asarray(mito_mask)
    totals, ngenes, pct, shard_stats = [], [], [], []
    shard_sizes = []
    for offset, shard in src:
        t, g, m, stats = _shard_stats(shard, mito, target_sum)
        n = shard.n_cells
        # keep DEVICE arrays here — np.asarray would sync and
        # serialise host IO with device compute; one fetch after the
        # loop preserves the async-dispatch overlap
        totals.append(t[:n])
        ngenes.append(g[:n])
        pct.append(m[:n])
        shard_stats.append(stats)
        shard_sizes.append(n)
    totals = [np.asarray(t) for t in totals]
    ngenes = [np.asarray(g) for g in ngenes]
    pct = [np.asarray(m) for m in pct]
    # Variance via per-shard centered moments combined in float64
    # (Chan's pairwise update).  Per-shard sums are float32 over <=64k
    # rows (benign); the naive global ss - n*mean^2 in float32 would
    # catastrophically cancel for low-dispersion genes at 10M cells.
    n_acc = 0
    mean = np.zeros(src.n_genes, np.float64)
    m2 = np.zeros(src.n_genes, np.float64)
    nnz = np.zeros(src.n_genes, np.float64)
    for stats, n_i in zip(shard_stats, shard_sizes):
        s_i, ss_i, nnz_i = np.asarray(stats).T.astype(np.float64)
        mean_i = s_i / n_i
        m2_i = np.maximum(ss_i - n_i * mean_i**2, 0.0)
        delta = mean_i - mean
        tot = n_acc + n_i
        m2 += m2_i + delta**2 * (n_acc * n_i / tot)
        mean += delta * (n_i / tot)
        nnz += nnz_i
        n_acc = tot
    n = src.n_cells
    var = np.maximum(m2 / max(n - 1, 1), 0.0)
    return {
        "total_counts": np.concatenate(totals),
        "n_genes": np.concatenate(ngenes),
        "pct_counts_mt": np.concatenate(pct),
        "gene_mean": mean,
        "gene_var": var,
        "gene_nnz": nnz,
        "n_cells": n,
    }


def stream_hvg(stats: dict, n_top: int = 2000) -> np.ndarray:
    """Dispersion-flavor HVG ranking from streamed moments (the
    seurat_v3 flavor needs a second clipped pass; dispersion is the
    one-pass ranking — documented divergence for the streaming path).
    Returns sorted gene indices."""
    from ..ops.hvg import _dispersion_scores

    scores = _dispersion_scores(stats["gene_mean"].astype(np.float64),
                                stats["gene_var"].astype(np.float64), np)
    order = np.argsort(-scores)[:n_top]
    return np.sort(order)


# ----------------------------------------------------------------------
# Streaming randomized PCA
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("target_sum", "g_sub"))
def _shard_matvec(x: SparseCells, mapping, mu, V, target_sum: float,
                  g_sub: int):
    """Fused subset→normalise→log1p→centered ``X_c @ V`` for one shard.
    mapping: (n_genes+1,) old→new gene id (dropped → g_sub sentinel).
    Returns (rows_padded, L) with padding rows zeroed."""
    from ..ops.normalize import _library_size_sparse

    xs, _ = _library_size_sparse(x, target_sum)  # totals over ALL genes
    xn = xs.with_data(jnp.log1p(xs.data))
    sub = SparseCells(jnp.take(mapping, xn.indices), xn.data,
                      xn.n_cells, g_sub)
    sub = sub.with_data(jnp.where(sub.indices == g_sub, 0.0, sub.data))
    out = spmm(sub, V) - (mu @ V)[None, :]
    return jnp.where(sub.row_mask()[:, None], out, 0.0)


@partial(jax.jit, static_argnames=("target_sum", "g_sub"))
def _shard_rmatvec(x: SparseCells, mapping, mu, Q, target_sum: float,
                   g_sub: int):
    """Fused centered ``X_cᵀ @ Q`` for one shard (padded rows of Q
    must be zero)."""
    from ..ops.normalize import _library_size_sparse

    xs, _ = _library_size_sparse(x, target_sum)
    xn = xs.with_data(jnp.log1p(xs.data))
    sub = SparseCells(jnp.take(mapping, xn.indices), xn.data,
                      xn.n_cells, g_sub)
    sub = sub.with_data(jnp.where(sub.indices == g_sub, 0.0, sub.data))
    Qm = jnp.where(sub.row_mask()[:, None], Q, 0.0)
    colsum = jnp.sum(Qm, axis=0)
    return spmm_t(sub, Qm) - jnp.outer(mu, colsum)


def _assemble_rows(blocks, n_rows):
    """Stack per-shard (rows_padded, L) device blocks into one
    device-resident (n_rows, L) array."""
    trimmed = []
    got = 0
    for b in blocks:
        take = min(b.shape[0], n_rows - got)
        trimmed.append(b[:take])
        got += take
    return jnp.concatenate(trimmed, axis=0)


def stream_pca(src: ShardSource, gene_idx: np.ndarray,
               gene_mean: np.ndarray, key, n_components: int = 50,
               oversample: int = 10, n_iter: int = 2,
               target_sum: float = 1e4):
    """Streaming randomized PCA on the HVG-subset normalised matrix.

    gene_mean: per-gene means of the FULL normalised matrix (from
    stream_stats) — the subset's centering vector is gene_mean[gene_idx].
    Returns (scores (n, k) device, components (g_sub, k), explained (k,)).
    """
    from ..ops.pca import cholesky_qr

    gene_idx = np.asarray(gene_idx)
    g_sub = len(gene_idx)
    mapping = np.full(src.n_genes + 1, g_sub, np.int32)
    mapping[gene_idx] = np.arange(g_sub, dtype=np.int32)
    mapping = jnp.asarray(mapping)
    mu = jnp.asarray(gene_mean[gene_idx].astype(np.float32))
    L = n_components + oversample

    def matvec_all(V):
        return _assemble_rows(
            [_shard_matvec(sh, mapping, mu, V, target_sum, g_sub)
             for _, sh in src], src.n_cells)

    def rmatvec_all(Q):
        acc = jnp.zeros((g_sub, Q.shape[1]), jnp.float32)
        for offset, sh in src:
            # rows of Q beyond this shard's n_cells (its row padding)
            # belong to the next shard, but _shard_rmatvec masks by
            # row_mask so they contribute nothing here
            q_blk = Q[offset: offset + sh.rows_padded]
            if q_blk.shape[0] < sh.rows_padded:  # dataset-end padding
                q_blk = jnp.concatenate(
                    [q_blk, jnp.zeros((sh.rows_padded - q_blk.shape[0],
                                       Q.shape[1]))])
            acc = acc + _shard_rmatvec(sh, mapping, mu, q_blk,
                                       target_sum, g_sub)
        return acc

    omega = jax.random.normal(key, (g_sub, L), jnp.float32)
    Q = cholesky_qr(matvec_all(omega))
    for _ in range(n_iter):
        Qz = cholesky_qr(rmatvec_all(Q))
        Q = cholesky_qr(matvec_all(Qz))
    B = rmatvec_all(Q).T  # (L, g_sub)
    U_b, S, Vt = jnp.linalg.svd(B, full_matrices=False)
    k = n_components
    scores = (Q @ U_b[:, :k]) * S[:k]
    components = Vt[:k].T
    explained = (S[:k] ** 2) / max(src.n_cells - 1, 1)
    return scores, components, explained


# ----------------------------------------------------------------------
# End-to-end streaming pipeline
# ----------------------------------------------------------------------


def stream_pipeline(src: ShardSource, *, n_top: int = 2000,
                    n_components: int = 50, k: int = 15,
                    metric: str = "cosine", target_sum: float = 1e4,
                    mito_mask: np.ndarray | None = None, seed: int = 0,
                    refine: int = 64) -> dict:
    """h5ad shards → QC → HVG → 50-PC randomized PCA → kNN, out of
    core (BASELINE.json configs[4] shape).  Returns a dict:
    obs metrics (host), hvg_genes, X_pca (device), knn indices and
    distances (device, padded rows -1)."""
    from ..ops.knn import knn_arrays

    stats = stream_stats(src, target_sum=target_sum, mito_mask=mito_mask)
    hvg_genes = stream_hvg(stats, n_top=n_top)
    scores, comps, expl = stream_pca(
        src, hvg_genes, stats["gene_mean"], jax.random.PRNGKey(seed),
        n_components=n_components, target_sum=target_sum)
    idx, dist = knn_arrays(scores, scores, k=k, metric=metric,
                           n_query=src.n_cells, n_cand=src.n_cells,
                           refine=refine)
    return {
        "obs": {"total_counts": stats["total_counts"],
                "n_genes": stats["n_genes"],
                "pct_counts_mt": stats["pct_counts_mt"]},
        "hvg_genes": hvg_genes,
        "X_pca": scores,
        "pca_components": comps,
        "pca_explained_variance": expl,
        "knn_indices": idx,
        "knn_distances": dist,
        "n_cells": src.n_cells,
    }
