"""Out-of-core streaming pipeline: atlas-scale matrices that do not
fit in HBM.

Reference parity: the reference framework streams AnnData CSR shards
through its preprocessing + kNN build (BASELINE.json north star: 10M
cells × 30k genes); its loader is native C++ (source unavailable —
SURVEY.md §0).

TPU design: the *sparse counts* are the only thing that doesn't fit —
at 10M cells the skinny dense iterates of randomized PCA ((n, ~60)
float32 ≈ 2.4 GB) and the final (n, 50) scores sit comfortably in HBM.
So the streaming decomposition is:

* **one stats pass** over h5ad CSR shards: each shard is packed to
  padded-ELL (native C++ packer), device_put, library-normalised +
  log1p'd, and reduced — per-cell QC metrics and per-gene
  (Σ, Σ², nnz) accumulate on device while the next shard loads (jax
  async dispatch overlaps the host IO with device compute);
* **HVG selection**: seurat_v3 (the BASELINE configs[2] flavor) fits
  the mean-variance trend on the pass-1 raw moments, then streams ONE
  more clipped-second-moment pass; the one-pass dispersion flavor
  needs no second pass;
* **streaming randomized PCA**: the power iteration's tall-skinny
  iterates Y/Q stay device-resident; each (re-)materialisation of
  ``Y = X_c @ Q`` / ``Z = X_cᵀ @ Q`` streams the HVG-subset shards
  through the fused subset→normalise→centered-matvec kernel.
  CholeskyQR2 orthonormalisation works on the device-resident Y —
  the same math as ops/pca.py, so single-chip and streaming paths
  agree to float tolerance;
* **kNN** on the device-resident scores via the standard blocked /
  Pallas search (ops/knn.py) — no extra streaming needed.

The full count matrix never exists in memory; peak host usage is a
small constant number of shards (the consumer's plus the prefetch
queue's — see ``_prefetch_iter``), peak device usage is the skinny
iterates.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..config import config, round_up
from ..utils import telemetry
from ..utils.checkpoint import (clear_npz_generations,
                                load_npz_generations,
                                save_npz_generations)
from ..utils.failsafe import TRANSIENT, classify_error
from ..utils.sync import hard_sync
from ..utils.vclock import SYSTEM_CLOCK
from .sparse import SparseCells, segment_reduce, spmm, spmm_t


# ----------------------------------------------------------------------
# Verified resume files (the streaming passes' checkpoints)
# ----------------------------------------------------------------------

#: identity fingerprints the pass checkpoints carry (a stream_pca file
#: renamed onto the stats path fails verification instead of
#: half-parsing); argument mismatches stay a ValueError — a checkpoint
#: for different arguments is WRONG, not corrupt, and must not be
#: quarantined
_STATS_FP = "stream_stats-v1"
_PCA_FP = "stream_pca-v1"


def _save_resume_npz(path: str, fingerprint: str, **arrays) -> None:
    """Write a streaming pass's resume state through the checkpoint
    integrity layer — generation-rotating verified npz
    (:func:`~..utils.checkpoint.save_npz_generations`): if the newest
    file is later ruled corrupt, resume falls back ONE save (one
    shard of lost work) instead of restarting the pass."""
    save_npz_generations(path, fingerprint=fingerprint, **arrays)


def _load_resume_npz(path: str, fingerprint: str) -> dict | None:
    """Verify-then-load a resume file with the deterministic
    newest → ``.prev`` → fresh fallback and quarantine-on-corruption
    (:func:`~..utils.checkpoint.load_npz_generations` — the out-of-
    core trainer shares the same convention)."""
    return load_npz_generations(path, fingerprint=fingerprint)


def _clear_resume_npz(path: str) -> None:
    clear_npz_generations(path)  # pass completed; state is stale


# ----------------------------------------------------------------------
# Shard sources
# ----------------------------------------------------------------------


def _tag_shard_index(e: BaseException, idx: int) -> BaseException:
    """Attach the failing shard's index to an exception surfacing out
    of the prefetch worker (``.shard_index``; also an ``add_note`` on
    pythons that have it) — the consumer sees WHERE the stream died
    without the worker's stack."""
    try:
        e.shard_index = idx
        if hasattr(e, "add_note"):
            e.add_note(f"[stream] raised while producing shard {idx}")
    except Exception:  # pragma: no cover - exotic exception types
        pass
    return e


def _prefetch_iter(make_gen, depth: int = 2, prepare=None, clock=None,
                   metrics=None, prepare_retries: int = 2,
                   stall_counter=None, overlap_counter=None):
    """Run a generator in a daemon worker thread, handing items over a
    bounded queue (``depth=2``: a DOUBLE-BUFFERED shard pipeline — the
    worker keeps shard N+1 fully prepared while the consumer computes
    on shard N, with one more slot so the worker never idles on the
    handoff).  ``prepare`` runs IN THE WORKER on every produced item —
    ``ShardSource`` passes its ``device_put``, so the native-packer
    CSR decode AND the host→device transfer of the next shard both
    overlap the current shard's device compute, even when
    ``config.stream_sync`` drains the device between shards (the axon
    tunnel mode, where jax's own async dispatch is off the table).

    Worker exceptions are CLASSIFIED (``failsafe.classify_error``)
    before they reach the consumer: a transient IO failure inside
    ``prepare`` (flaky-disk EIO, a dropped tunnel connection) gets up
    to ``prepare_retries`` bounded in-worker retries on the
    injectable clock (counted under ``ingest.retries``), so the
    stream survives a blip without restarting the whole pass;
    deterministic errors — and exhausted retries, and any
    generator-side raise (a generator cannot be re-``next``-ed) —
    surface immediately at the point of the failed item with the
    shard index attached (``exc.shard_index``).

    Overlap accounting goes to ``metrics`` (default: the process-wide
    telemetry registry) on the injectable ``clock`` — tier-1 drives it
    with a ``VirtualClock``-timed fake packer and zero real sleeps:

    * ``stream.stall_s``   — consumer seconds blocked on the queue
      (the stream is producer-bound: IO/pack/H2D is the bottleneck);
    * ``stream.overlap_s`` — producer work seconds hidden behind
      consumer compute (the overlap the double buffer exists to buy).

    ``stall_counter``/``overlap_counter`` override WHERE the two
    totals land (pass counter cells, not names — metric names must
    stay literals at their call sites for the SCT009 vocabulary
    check): the out-of-core trainer routes the same accounting into
    ``train.stall_s``/``train.overlap_s`` so a training run's device-
    feed efficiency is separable from any concurrent ingest.
    """
    import queue
    import threading

    clock = clock if clock is not None else SYSTEM_CLOCK
    m = metrics if metrics is not None else telemetry.default_registry()
    q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
    stop = threading.Event()
    _END = object()
    _ERR = object()

    def run_prepare(item, idx):
        """``prepare`` with bounded in-worker retries for CLASSIFIED
        transients only — a deterministic raise replays identically,
        so retrying it would just delay the consumer's diagnosis."""
        attempt = 0
        while True:
            try:
                return prepare(item)
            except Exception as e:
                if (classify_error(e) != TRANSIENT
                        or attempt >= prepare_retries):
                    raise _tag_shard_index(e, idx)
                attempt += 1
                m.counter("ingest.retries").inc()
                clock.sleep(min(0.05 * 2.0 ** (attempt - 1), 1.0))

    def put(item) -> bool:
        # stop-aware put: a consumer that abandons iteration (device
        # error mid-stream, GC) must not leave this thread blocked
        # forever holding the h5 handle + shard buffers
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        gen = make_gen()
        produced = 0
        try:
            while True:
                t0 = clock.monotonic()
                try:
                    item = next(gen)
                except StopIteration:
                    break
                except BaseException as e:
                    # generator-side raise: no retry possible (the
                    # generator is dead) — tag the shard and surface
                    raise _tag_shard_index(e, produced)
                if prepare is not None:
                    item = run_prepare(item, produced)
                # production wall: generator work + prepare (decode +
                # pack + device_put) — NOT time blocked on a full
                # queue, which is the consumer's compute, not ours
                work = clock.monotonic() - t0
                if not put((None, item, work)):
                    return  # consumer gone; generator finalised here
                produced += 1
        except BaseException as e:  # noqa: BLE001 - reraised below
            put((_ERR, e, 0.0))
        put(_END)

    th = threading.Thread(target=worker, daemon=True)
    th.start()
    stall_total = 0.0
    overlap_total = 0.0
    try:
        while True:
            t0 = clock.monotonic()
            item = q.get()
            stall = clock.monotonic() - t0
            if item is _END:
                return
            tag, payload, work = item
            if tag is _ERR:
                raise payload
            stall_total += stall
            # the slice of this item's production wall that did NOT
            # stall the consumer — i.e. was hidden behind compute
            overlap_total += max(0.0, work - stall)
            yield payload
    finally:
        stop.set()
        try:  # wake a producer blocked on a full queue
            q.get_nowait()
        except queue.Empty:
            pass
        # bounded join: an early-exiting consumer (preemption, a
        # cancelled training job, a device error mid-stream) must not
        # leave the worker mid-device_put while the process tears
        # down the runtime under it (observed as a C++ abort at
        # interpreter exit).  Normal completion joins instantly; a
        # wedged read is abandoned to its daemon fate after the bound.
        th.join(timeout=10.0)
        (stall_counter if stall_counter is not None
         else m.counter("stream.stall_s")).inc(stall_total)
        (overlap_counter if overlap_counter is not None
         else m.counter("stream.overlap_s")).inc(overlap_total)


@dataclasses.dataclass
class ShardSource:
    """A re-iterable source of (row_offset, device SparseCells) shards
    with uniform shapes (one compiled program serves every shard).

    ``sharding`` (optional, e.g. ``cell_sharding(mesh)``) places every
    shard cells-axis-sharded across a device mesh at ``device_put``
    time — the jitted per-shard programs then run SPMD with GSPMD
    collectives, composing out-of-core streaming with multi-chip
    execution (the 10M×30k north star needs both at once).  Use
    :meth:`with_mesh` to get a mesh-placed view of a source."""

    factory: Callable[[], Iterator[SparseCells]]
    n_cells: int
    n_genes: int
    shard_rows: int
    sharding: object | None = None
    # read/pack AND device_put the next shard in a worker thread while
    # the device chews the current one (on for IO-backed sources;
    # pointless for in-memory ones)
    prefetch: bool = False
    # optional range-aware factory(start_shard) that SEEKS to the
    # given shard index (h5 indptr slicing / CSR row slicing) — the
    # checkpoint/resume path of the streaming passes uses it to skip
    # already-accumulated shards without re-reading them
    factory_from: Callable[[int], Iterator[SparseCells]] | None = None
    # prefetch queue depth: 2 = double-buffered (shard N+1 decoded,
    # packed and device_put while shard N computes — see
    # ``_prefetch_iter``'s stream.overlap_s / stream.stall_s counters)
    prefetch_depth: int = 2

    def __iter__(self):
        yield from self.iter_from(0)

    def iter_from(self, start_shard: int):
        """Iterate ``(row_offset, device shard)`` starting at shard
        index ``start_shard``.  Range-aware sources seek; others read
        and discard the skipped shards (correct, just not free).
        With ``prefetch`` the ``device_put`` runs in the worker thread
        too, so the H2D transfer of shard N+1 overlaps compute on
        shard N."""
        if start_shard and self.factory_from is not None:
            base = lambda: self.factory_from(start_shard)  # noqa: E731
            skip = 0
        else:
            base = self.factory
            skip = start_shard

        def host_iter():
            for i, shard in enumerate(base()):
                if i < skip:
                    continue  # not range-aware: discarded before pack
                yield shard

        offset = start_shard * self.shard_rows
        if self.prefetch:
            it = _prefetch_iter(
                host_iter, depth=self.prefetch_depth,
                prepare=lambda s: s.device_put(self.sharding))
            for shard in it:
                yield offset, shard
                offset += shard.n_cells
        else:
            for shard in host_iter():
                yield offset, shard.device_put(self.sharding)
                offset += shard.n_cells

    def with_mesh(self, mesh) -> "ShardSource":
        """Copy of this source whose shards are placed cells-axis-
        sharded over ``mesh``.  Intermediate shards must divide evenly
        across the mesh (their ``rows_padded`` must equal
        ``shard_rows``, which therefore must be a mesh-size multiple —
        offsets would misalign otherwise, see from_h5ad)."""
        from ..parallel.mesh import cell_sharding

        n_dev = int(mesh.devices.size)
        mult = n_dev * config.sublane
        if self.shard_rows % mult:
            raise ValueError(
                f"shard_rows={self.shard_rows} must be a multiple of "
                f"mesh size × sublane = {mult} to shard evenly")
        base = self.factory
        base_from = self.factory_from

        def _pad(it):
            # the LAST shard may be short — pad its rows to a mesh
            # multiple so device_put can split it evenly (padding rows
            # are sentinel/zero, annihilated by every op)
            for shard in it:
                yield shard.pad_rows_to(round_up(shard.rows_padded, mult))

        return dataclasses.replace(
            self, factory=lambda: _pad(base()),
            factory_from=(None if base_from is None
                          else lambda k: _pad(base_from(k))),
            sharding=cell_sharding(mesh))

    @property
    def n_shards(self) -> int:
        return -(-self.n_cells // self.shard_rows)

    @classmethod
    def from_h5ad(cls, path: str, shard_rows: int = 65536,
                  capacity: int | None = None) -> "ShardSource":
        import h5py

        from .io import shard_iter

        # intermediate shards must have rows_padded == n_cells so row
        # offsets stay aligned across shards
        shard_rows = round_up(shard_rows, config.sublane)

        with h5py.File(path, "r") as h5:
            node = h5["X"]
            if hasattr(node, "attrs") and "shape" in node.attrs:
                n, g = tuple(node.attrs["shape"])
                if capacity is None and "indptr" in node:
                    # exact global max nnz/row from the indptr alone —
                    # no data read, and no risk of a later shard
                    # exceeding a first-shard estimate mid-stream
                    nnz_max = int(np.diff(node["indptr"][...]).max())
                    capacity = round_up(max(nnz_max, 1),
                                        config.capacity_multiple)
            else:
                n, g = node.shape
                if capacity is None:
                    # dense h5ad: any row may be fully dense
                    capacity = round_up(int(g), config.capacity_multiple)
        return cls(lambda: shard_iter(path, shard_rows, capacity=capacity),
                   int(n), int(g), shard_rows, prefetch=True,
                   factory_from=lambda k: shard_iter(
                       path, shard_rows, capacity=capacity,
                       start_row=k * shard_rows))

    @classmethod
    def from_scipy(cls, X, shard_rows: int = 65536,
                   capacity: int | None = None) -> "ShardSource":
        """In-memory CSR source (tests / moderate sizes)."""
        X = X.tocsr()
        n, g = X.shape
        shard_rows = round_up(shard_rows, config.sublane)
        if capacity is None:
            nnz_max = int(np.diff(X.indptr).max()) if X.nnz else 1
            capacity = round_up(max(nnz_max, 1), config.capacity_multiple)

        def factory_from(start_shard):
            for s in range(start_shard * shard_rows, n, shard_rows):
                yield SparseCells.from_scipy_csr(
                    X[s: s + shard_rows], capacity=capacity)

        return cls(lambda: factory_from(0), n, g, shard_rows,
                   factory_from=factory_from)


# ----------------------------------------------------------------------
# Pass 1: QC + per-gene stats of the normalised log matrix
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("target_sum",))
def _shard_stats(x: SparseCells, mito_mask, target_sum: float):
    """Per-shard: (per-cell totals, n_genes, pct_mito;
    per-gene moments of BOTH the raw counts and the log1p-normalised
    values, stacked as columns [s_raw, m2_raw, s_norm, m2_norm, nnz]).

    The second moments are SHARD-MEAN-CENTERED sums of squares, not
    raw Σx²: ``m2 = Σ_valid (x − μ_g)² + (n_valid − nnz_g)·μ_g²``
    with μ_g the shard's own per-gene mean.  Every term is
    non-negative, so the float32 segment sum carries ~√N·ε relative
    error of m2 ITSELF — computing Σx² in f32 and subtracting n·μ²
    later cancels catastrophically for low-dispersion genes where
    μ² ≫ var, regardless of shard size.  Shards combine in float64
    via Chan's pairwise update (stream_stats).

    Two fused segment passes over one index stream: pass A gets
    (Σ_raw, Σ_norm, nnz); pass B, seeded with pass A's on-device
    means, gets the two centered squares.  No host sync between them.
    """
    from ..ops.normalize import _library_size_sparse

    totals = jnp.sum(x.data, axis=1)
    n_genes_cell = x.nnz_per_row()
    mito_pad = jnp.concatenate([mito_mask.astype(x.data.dtype),
                                jnp.zeros((1,), x.data.dtype)])
    mito_counts = jnp.sum(
        x.data * jnp.take(mito_pad, x.indices), axis=1)
    pct_mito = jnp.where(totals > 0, 100.0 * mito_counts /
                         jnp.maximum(totals, 1e-12), 0.0)
    xs, _ = _library_size_sparse(x, target_sum)
    xn_data = jnp.log1p(xs.data)
    # segment_reduce blocks rows to _ROW_CHUNK multiples; pad the
    # parallel value plane likewise so dynamic_slice stays in range
    # (same trick as spmm_t)
    from .sparse import _ROW_CHUNK

    pad = (-x.rows_padded) % _ROW_CHUNK
    if pad:
        xn_data = jnp.concatenate(
            [xn_data, jnp.zeros((pad, x.capacity), xn_data.dtype)])
    n_cells = x.n_cells
    sentinel = x.sentinel

    def slot_sums(ind, dat, row_offset):
        rows = row_offset + jnp.arange(ind.shape[0])
        valid = (ind != sentinel) & (rows < n_cells)[:, None]
        blk = jax.lax.dynamic_slice_in_dim(
            xn_data, row_offset, ind.shape[0])
        return jnp.stack([dat, blk, valid.astype(dat.dtype)], axis=2)

    sums = segment_reduce(x, slot_sums, 3)  # (G, [s_raw, s_norm, nnz])
    s_raw, s_norm, nnz = sums[:, 0], sums[:, 1], sums[:, 2]
    inv_n = 1.0 / max(n_cells, 1)
    mu_raw = s_raw * inv_n
    mu_norm = s_norm * inv_n
    mu_raw_pad = jnp.concatenate([mu_raw, jnp.zeros((1,))])
    mu_norm_pad = jnp.concatenate([mu_norm, jnp.zeros((1,))])

    def slot_sq(ind, dat, row_offset):
        rows = row_offset + jnp.arange(ind.shape[0])
        valid = (ind != sentinel) & (rows < n_cells)[:, None]
        blk = jax.lax.dynamic_slice_in_dim(
            xn_data, row_offset, ind.shape[0])
        dr = jnp.where(valid, dat - jnp.take(mu_raw_pad, ind), 0.0)
        dn = jnp.where(valid, blk - jnp.take(mu_norm_pad, ind), 0.0)
        return jnp.stack([dr * dr, dn * dn], axis=2)

    sq = segment_reduce(x, slot_sq, 2)
    zeros = jnp.maximum(n_cells - nnz, 0.0)
    m2_raw = sq[:, 0] + zeros * mu_raw * mu_raw
    m2_norm = sq[:, 1] + zeros * mu_norm * mu_norm
    stats = jnp.stack([s_raw, m2_raw, s_norm, m2_norm, nnz], axis=1)
    return totals, n_genes_cell, pct_mito, stats


def stream_stats(src: ShardSource, target_sum: float = 1e4,
                 mito_mask: np.ndarray | None = None,
                 checkpoint: str | None = None) -> dict:
    """One pass: per-cell QC metrics (host) + per-gene moments of the
    normalised log matrix (device accumulator).

    ``checkpoint=`` makes the pass RESUMABLE: after every shard the
    fetched per-shard results are written through the checkpoint
    INTEGRITY layer (content digest + schema + pass fingerprint,
    atomic rename, previous generation rotated to ``.prev``) to the
    given ``.npz`` path; a rerun with the same arguments
    verify-loads it, seeks the source to the first unprocessed shard
    (range-aware sources skip the read entirely — see
    ``ShardSource.iter_from``), and finishes the pass.  A resume file
    that fails verification — bit rot, a write truncated by the very
    crash being recovered from — is QUARANTINED (moved beside the
    data with a ``.reason.json`` sidecar, never deleted) and resume
    falls back deterministically to the ``.prev`` generation (one
    shard earlier), then to a fresh pass.  This is the recovery story
    for the pass that historically killed tunneled TPU workers
    mid-atlas: a crashed process loses at most one shard of work.
    The files are deleted on successful completion.  Checkpointing
    forces a per-shard fetch (the same drain ``config.stream_sync``
    imposes on the tunnel), so leave it off when failure recovery
    isn't worth a sync per shard.
    """
    if mito_mask is None:
        mito_mask = np.zeros(src.n_genes, bool)
    mito = jnp.asarray(mito_mask)
    sync = config.stream_sync_enabled()
    totals, ngenes, pct, shard_stats = [], [], [], []
    shard_sizes = []
    start_shard = 0
    z = (_load_resume_npz(checkpoint, _STATS_FP)
         if checkpoint is not None else None)
    if z is not None:
        meta_ok = (int(z["n_cells"]) == src.n_cells
                   and int(z["n_genes"]) == src.n_genes
                   and int(z["shard_rows"]) == src.shard_rows
                   and float(z["target_sum"]) == float(target_sum))
        if not meta_ok:
            raise ValueError(
                f"stream_stats: checkpoint {checkpoint!r} was written "
                f"for a different source/arguments; delete it or pass "
                f"a fresh path")
        start_shard = int(z["next_shard"])
        sizes = z["shard_sizes"]
        bounds = np.concatenate([[0], np.cumsum(sizes)]).astype(int)
        for i, n_i in enumerate(sizes):
            totals.append(z["totals"][bounds[i]:bounds[i + 1]])
            ngenes.append(z["ngenes"][bounds[i]:bounds[i + 1]])
            pct.append(z["pct"][bounds[i]:bounds[i + 1]])
            shard_stats.append(z["stats"][i])
            shard_sizes.append(int(n_i))

    def _save_checkpoint(next_shard):
        _save_resume_npz(
            checkpoint, _STATS_FP,
            n_cells=src.n_cells, n_genes=src.n_genes,
            shard_rows=src.shard_rows, target_sum=target_sum,
            next_shard=next_shard,
            shard_sizes=np.asarray(shard_sizes, np.int64),
            totals=np.concatenate([np.asarray(t, np.float32)
                                   for t in totals]),
            ngenes=np.concatenate([np.asarray(g, np.float32)
                                   for g in ngenes]),
            pct=np.concatenate([np.asarray(m, np.float32)
                                for m in pct]),
            stats=np.stack([np.asarray(s, np.float32)
                            for s in shard_stats]))

    for k, (offset, shard) in enumerate(src.iter_from(start_shard),
                                        start=start_shard):
        t, g, m, stats = _shard_stats(shard, mito, target_sum)
        n = shard.n_cells
        # keep DEVICE arrays here — np.asarray would sync and
        # serialise host IO with device compute; one fetch after the
        # loop preserves the async-dispatch overlap.  Under
        # config.stream_sync (the axon tunnel) each shard is drained
        # before the next dispatch instead — see config.py.  The drain
        # is hard_sync, not block_until_ready: the tunnel returns from
        # block_until_ready before the program has run (utils/sync.py).
        if sync:
            hard_sync(stats)
        totals.append(t[:n])
        ngenes.append(g[:n])
        pct.append(m[:n])
        shard_stats.append(stats)
        shard_sizes.append(n)
        if checkpoint is not None:
            # fetch (the checkpoint needs host values anyway) + persist
            totals[-1] = np.asarray(totals[-1])
            ngenes[-1] = np.asarray(ngenes[-1])
            pct[-1] = np.asarray(pct[-1])
            shard_stats[-1] = np.asarray(shard_stats[-1])
            _save_checkpoint(k + 1)
    totals = [np.asarray(t) for t in totals]
    ngenes = [np.asarray(g) for g in ngenes]
    pct = [np.asarray(m) for m in pct]
    # Cross-shard combine in float64 via Chan's pairwise update.  The
    # per-shard m2 arrive already centered on the SHARD mean as sums
    # of non-negative f32 terms (see _shard_stats), so no f32
    # cancellation survives to this point; the combine itself is
    # float64 throughout.
    n_acc = 0
    mean_r = np.zeros(src.n_genes, np.float64)
    m2_r = np.zeros(src.n_genes, np.float64)
    mean_n = np.zeros(src.n_genes, np.float64)
    m2_n = np.zeros(src.n_genes, np.float64)
    nnz = np.zeros(src.n_genes, np.float64)
    for stats, n_i in zip(shard_stats, shard_sizes):
        s_r, m2r_i, s_n, m2n_i, nnz_i = \
            np.asarray(stats).T.astype(np.float64)
        for mean, m2, s_i, m2_i in ((mean_r, m2_r, s_r, m2r_i),
                                    (mean_n, m2_n, s_n, m2n_i)):
            mean_i = s_i / n_i
            delta = mean_i - mean
            tot = n_acc + n_i
            m2 += np.maximum(m2_i, 0.0) + delta**2 * (n_acc * n_i / tot)
            mean += delta * (n_i / tot)
        nnz += nnz_i
        n_acc += n_i
    n = src.n_cells
    if checkpoint is not None:
        _clear_resume_npz(checkpoint)
    return {
        "total_counts": np.concatenate(totals),
        "n_genes": np.concatenate(ngenes),
        "pct_counts_mt": np.concatenate(pct),
        "gene_mean": mean_n,
        "gene_var": np.maximum(m2_n / max(n - 1, 1), 0.0),
        "raw_gene_mean": mean_r,
        "raw_gene_var": np.maximum(m2_r / max(n - 1, 1), 0.0),
        "gene_nnz": nnz,
        "n_cells": n,
    }


@partial(jax.jit, static_argnames=())
def _shard_clipped_ssq(x: SparseCells, mu_over_std, inv_std, clip):
    """Per-shard Σ min(clip, (x − μ)/σ)² over stored slots (per gene).
    The zeros' contribution ((0 − μ)/σ clipped, squared, × count) is
    added by the caller from the pass-1 nnz counts."""
    n_cells = x.n_cells
    sentinel = x.sentinel
    mu_pad = jnp.concatenate([mu_over_std, jnp.zeros((1,))])
    inv_pad = jnp.concatenate([inv_std, jnp.zeros((1,))])

    def slot_vals(ind, dat, row_offset):
        z = jnp.take(inv_pad, ind) * dat - jnp.take(mu_pad, ind)
        z = jnp.clip(z, -clip, clip)
        rows = row_offset + jnp.arange(ind.shape[0])
        ok = (ind != sentinel) & (rows < n_cells)[:, None]
        return jnp.where(ok, z * z, 0.0)[:, :, None]

    return segment_reduce(x, slot_vals, 1)[:, 0]


@partial(jax.jit, static_argnames=())
def _pearson_zero_chunk(totals_block, p_chunk, theta, clip):
    """Zero-entry residual sums for a (cells-block × gene-chunk) tile:
    the x=0 residual depends only on the CELL total, so the baseline
    needs no matrix pass — just the pass-1 totals."""
    mu = totals_block[:, None] * p_chunk[None, :]
    denom = jnp.maximum(jnp.sqrt(mu + mu * mu / theta), 1e-12)
    r0 = jnp.clip(-mu / denom, -clip, clip)
    return jnp.sum(r0, axis=0), jnp.sum(r0 * r0, axis=0)


@partial(jax.jit, static_argnames=())
def _shard_pearson_corr(x: SparseCells, p_pad, theta, clip):
    """Stored-entry correction (r - r0, r² - r0²) per gene for one
    shard; row totals recomputed on device from the shard itself."""
    from .sparse import _ROW_CHUNK

    totals = jnp.sum(x.data, axis=1)
    pad = (-x.rows_padded) % _ROW_CHUNK
    if pad:
        totals = jnp.concatenate([totals, jnp.zeros((pad,),
                                                    totals.dtype)])
    n_cells = x.n_cells
    sentinel = x.sentinel

    def slot_vals(ind, dat, row_offset):
        rows = row_offset + jnp.arange(ind.shape[0])
        t = jax.lax.dynamic_slice_in_dim(totals, row_offset,
                                         ind.shape[0])
        mu = t[:, None] * jnp.take(p_pad, ind)
        denom = jnp.maximum(jnp.sqrt(mu + mu * mu / theta), 1e-12)
        r = jnp.clip((dat - mu) / denom, -clip, clip)
        r0 = jnp.clip(-mu / denom, -clip, clip)
        ok = (ind != sentinel) & (rows < n_cells)[:, None]
        return jnp.stack([jnp.where(ok, r - r0, 0.0),
                          jnp.where(ok, r * r - r0 * r0, 0.0)], axis=2)

    return segment_reduce(x, slot_vals, 2)


def stream_hvg(stats: dict, n_top: int = 2000,
               flavor: str = "seurat_v3",
               src: ShardSource | None = None,
               theta: float = 100.0) -> np.ndarray:
    """HVG ranking from streamed moments.  Returns sorted gene indices.

    ``"seurat_v3"`` (the BASELINE configs[2] flavor) ranks genes by
    clipped standardised variance of the RAW counts — same math as the
    in-memory ``hvg.select``: quadratic mean-variance trend fit on the
    pass-1 raw moments (host, float64), then ONE more streaming pass
    over ``src`` accumulating the clipped second moment per gene.
    Requires ``src`` (the clip threshold depends on the global trend,
    which only exists after pass 1 — the second pass is inherent to
    the flavor, not a streaming limitation).

    ``"dispersion"`` is the one-pass ranking from the normalised-matrix
    moments (no second pass, no ``src`` needed).
    """
    if flavor in ("dispersion", "seurat"):
        from ..ops.hvg import _dispersion_scores

        scores = _dispersion_scores(stats["gene_mean"].astype(np.float64),
                                    stats["gene_var"].astype(np.float64),
                                    np)
    elif flavor == "cell_ranger":
        # needs only the pass-1 moments — free at streaming scale
        from ..ops.hvg import _cell_ranger_scores

        scores = _cell_ranger_scores(stats["gene_mean"],
                                     stats["gene_var"])
    elif flavor == "seurat_v3":
        from ..ops.hvg import (_fit_mean_var_trend,
                               _seurat_v3_scores_from_stats)

        if src is None:
            raise ValueError(
                "stream_hvg(flavor='seurat_v3') needs src= for the "
                "clipped second pass")
        mean = stats["raw_gene_mean"]
        var = stats["raw_gene_var"]
        n = stats["n_cells"]
        std = np.maximum(np.sqrt(_fit_mean_var_trend(mean, var, np)),
                         1e-12)
        clip = float(np.sqrt(n))
        mu_over_std = jnp.asarray((mean / std).astype(np.float32))
        inv_std = jnp.asarray((1.0 / std).astype(np.float32))
        ssq = np.zeros(src.n_genes, np.float64)
        for _, shard in src:
            part = _shard_clipped_ssq(shard, mu_over_std, inv_std, clip)
            ssq += np.asarray(part, np.float64)  # fetch drains per shard
        zero_term = np.clip(-mean / std, -clip, clip) ** 2
        ssq += (n - stats["gene_nnz"]) * zero_term
        scores = _seurat_v3_scores_from_stats(mean, var, ssq, n, np)
    elif flavor == "pearson_residuals":
        # scanpy experimental flavor at streaming scale: the zero
        # baseline comes from the pass-1 cell totals alone (no matrix
        # pass), stored entries from ONE k-sparse pass over src
        if src is None:
            raise ValueError(
                "stream_hvg(flavor='pearson_residuals') needs src= "
                "for the stored-entry correction pass")
        n = stats["n_cells"]
        totals_all = np.asarray(stats["total_counts"], np.float64)
        gsum = np.asarray(stats["raw_gene_mean"], np.float64) * n
        p = gsum / max(totals_all.sum(), 1e-12)
        clip = jnp.float32(np.sqrt(n))
        th = jnp.float32(theta)
        G = src.n_genes
        S = np.zeros(G, np.float64)
        Q = np.zeros(G, np.float64)
        gchunk, cblock = 512, 65536
        p_dev = jnp.asarray(np.pad(p, (0, (-G) % gchunk)), jnp.float32)
        for c0 in range(0, n, cblock):
            tb = jnp.asarray(totals_all[c0:c0 + cblock], jnp.float32)
            for lo in range(0, G, gchunk):
                s0, q0 = _pearson_zero_chunk(
                    tb, jax.lax.dynamic_slice_in_dim(p_dev, lo, gchunk),
                    th, clip)
                hi = min(G, lo + gchunk)
                S[lo:hi] += np.asarray(s0, np.float64)[: hi - lo]
                Q[lo:hi] += np.asarray(q0, np.float64)[: hi - lo]
        p_pad = jnp.asarray(np.concatenate([p, [0.0]]), jnp.float32)
        for _, shard in src:
            corr = np.asarray(
                _shard_pearson_corr(shard, p_pad, th, clip), np.float64)
            S += corr[:, 0]
            Q += corr[:, 1]  # fetch drains per shard
        scores = (Q - S * S / n) / max(n - 1, 1)
    else:
        raise ValueError(f"unknown hvg flavor {flavor!r}")
    order = np.argsort(-scores)[:n_top]
    return np.sort(order)


# ----------------------------------------------------------------------
# Streaming randomized PCA
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("target_sum", "g_sub"))
def _shard_matvec(x: SparseCells, mapping, mu, V, target_sum: float,
                  g_sub: int):
    """Fused subset→normalise→log1p→centered ``X_c @ V`` for one shard.
    mapping: (n_genes+1,) old→new gene id (dropped → g_sub sentinel).
    Returns (rows_padded, L) with padding rows zeroed."""
    from ..ops.normalize import _library_size_sparse

    xs, _ = _library_size_sparse(x, target_sum)  # totals over ALL genes
    xn = xs.with_data(jnp.log1p(xs.data))
    sub = SparseCells(jnp.take(mapping, xn.indices), xn.data,
                      xn.n_cells, g_sub)
    sub = sub.with_data(jnp.where(sub.indices == g_sub, 0.0, sub.data))
    out = spmm(sub, V) - (mu @ V)[None, :]
    return jnp.where(sub.row_mask()[:, None], out, 0.0)


@partial(jax.jit, static_argnames=("target_sum", "g_sub"))
def _shard_rmatvec(x: SparseCells, mapping, mu, Q, target_sum: float,
                   g_sub: int):
    """Fused centered ``X_cᵀ @ Q`` for one shard (padded rows of Q
    must be zero)."""
    from ..ops.normalize import _library_size_sparse

    xs, _ = _library_size_sparse(x, target_sum)
    xn = xs.with_data(jnp.log1p(xs.data))
    sub = SparseCells(jnp.take(mapping, xn.indices), xn.data,
                      xn.n_cells, g_sub)
    sub = sub.with_data(jnp.where(sub.indices == g_sub, 0.0, sub.data))
    Qm = jnp.where(sub.row_mask()[:, None], Q, 0.0)
    colsum = jnp.sum(Qm, axis=0)
    return spmm_t(sub, Qm) - jnp.outer(mu, colsum)


def _iter_row_chunks(sh: SparseCells, step: int):
    """Yield ``(row_offset, sub_shard)`` row slices of one padded-ELL
    shard.  Execution-only (identical results): bounds the size of each
    jitted PCA program — the tunneled TPU worker wedged on full-shard
    (131072-row) matvec/rmatvec programs while 16384-row programs run
    (round-5 probe).  ``step <= 0`` yields the shard whole."""
    if step <= 0 or step >= sh.rows_padded:
        yield 0, sh
        return
    for a in range(0, sh.rows_padded, step):
        b = min(a + step, sh.rows_padded)
        yield a, SparseCells(sh.indices[a:b], sh.data[a:b],
                             max(0, min(sh.n_cells - a, b - a)),
                             sh.n_genes)


def _assemble_rows(blocks, n_rows):
    """Stack per-shard (rows_padded, L) device blocks into one
    device-resident (n_rows, L) array."""
    trimmed = []
    got = 0
    for b in blocks:
        take = min(b.shape[0], n_rows - got)
        trimmed.append(b[:take])
        got += take
    return jnp.concatenate(trimmed, axis=0)


def stream_pca(src: ShardSource, gene_idx: np.ndarray,
               gene_mean: np.ndarray, key, n_components: int = 50,
               oversample: int = 10, n_iter: int = 2,
               target_sum: float = 1e4, checkpoint: str | None = None):
    """Streaming randomized PCA on the HVG-subset normalised matrix.

    gene_mean: per-gene means of the FULL normalised matrix (from
    stream_stats) — the subset's centering vector is gene_mean[gene_idx].
    Returns (scores (n, k) device, components (g_sub, k), explained (k,)).

    ``checkpoint=`` makes the pass resumable at per-shard granularity
    for the (g_sub, L)-sized state: the power iteration is organised
    in rounds carrier → Q = qr(X @ carrier) → z = qr(Xᵀ Q), and only
    the SMALL carrier + the rmatvec accumulator are persisted (~L·g_sub
    floats, not the (n, L) Q — at 10M cells that array is GBs).  On
    resume, Q is recomputed from the carrier (one deterministic matvec
    sweep), then the rmatvec pass continues from the first unprocessed
    shard: a crash loses at most one matvec sweep.  The state is
    written through the checkpoint integrity layer exactly like
    ``stream_stats``' (verify-on-load, corrupt file quarantined with
    a reason sidecar, deterministic ``.prev`` fallback); the files
    are deleted on success.
    """
    from ..ops.pca import _sketch_omega, cholesky_qr

    gene_idx = np.asarray(gene_idx)
    g_sub = len(gene_idx)
    mapping = np.full(src.n_genes + 1, g_sub, np.int32)
    mapping[gene_idx] = np.arange(g_sub, dtype=np.int32)
    mapping = jnp.asarray(mapping)
    mu = jnp.asarray(gene_mean[gene_idx].astype(np.float32))
    L = n_components + oversample

    sync = config.stream_sync_enabled()

    row_chunk = config.stream_row_chunk_rows()

    def matvec_all(V):
        blocks = []
        for _, sh in src:
            for _, sub in _iter_row_chunks(sh, row_chunk):
                b = _shard_matvec(sub, mapping, mu, V, target_sum,
                                  g_sub)
                if sync:
                    hard_sync(b)
                blocks.append(b)
        return _assemble_rows(blocks, src.n_cells)

    start_round, start_shard, acc0 = 0, 0, None
    z = (_load_resume_npz(checkpoint, _PCA_FP)
         if checkpoint is not None else None)
    if z is not None:
        if not (int(z["n_cells"]) == src.n_cells
                and int(z["g_sub"]) == g_sub and int(z["L"]) == L
                and int(z["n_iter"]) == n_iter
                and float(z["target_sum"]) == float(target_sum)):
            raise ValueError(
                f"stream_pca: checkpoint {checkpoint!r} was written for "
                f"different arguments; delete it or pass a fresh path")
        start_round = int(z["round"])
        start_shard = int(z["next_shard"])
        carrier = jnp.asarray(z["carrier"])
        acc0 = jnp.asarray(z["acc"])
    else:
        # the per-gene fold_in sketch shared with randomized_pca_arrays:
        # row i depends only on (key, i), so the streaming and
        # in-memory runs start from the SAME carrier for the same key
        carrier = _sketch_omega(key, g_sub, L, jnp.float32)

    def rmatvec_all(Q, rnd, acc=None, first_shard=0):
        acc = (jnp.zeros((g_sub, Q.shape[1]), jnp.float32)
               if acc is None else acc)
        for offset, sh in src.iter_from(first_shard):
            # rows of Q beyond this shard's n_cells (its row padding)
            # belong to the next shard, but _shard_rmatvec masks by
            # row_mask so they contribute nothing here
            q_blk = Q[offset: offset + sh.rows_padded]
            if q_blk.shape[0] < sh.rows_padded:  # dataset-end padding
                q_blk = jnp.concatenate(
                    [q_blk, jnp.zeros((sh.rows_padded - q_blk.shape[0],
                                       Q.shape[1]))])
            for a, sub in _iter_row_chunks(sh, row_chunk):
                acc = acc + _shard_rmatvec(
                    sub, mapping, mu, q_blk[a: a + sub.rows_padded],
                    target_sum, g_sub)
                if sync:
                    hard_sync(acc)
            if checkpoint is not None:
                shard_i = offset // src.shard_rows
                _save_resume_npz(
                    checkpoint, _PCA_FP,
                    n_cells=src.n_cells, g_sub=g_sub, L=L,
                    n_iter=n_iter, target_sum=target_sum,
                    round=rnd, next_shard=shard_i + 1,
                    carrier=np.asarray(carrier),
                    acc=np.asarray(acc))
        return acc

    # rounds: carrier_r -> Q = qr(X c) -> z = rmatvec(Q);
    # r < n_iter: carrier_{r+1} = qr(z); r == n_iter: B = z.T -> SVD
    for rnd in range(start_round, n_iter + 1):
        Q = cholesky_qr(matvec_all(carrier))
        z = rmatvec_all(Q, rnd,
                        acc=acc0 if rnd == start_round else None,
                        first_shard=(start_shard
                                     if rnd == start_round else 0))
        acc0 = None
        if rnd < n_iter:
            carrier = cholesky_qr(z)
    B = z.T  # (L, g_sub)
    U_b, S, Vt = jnp.linalg.svd(B, full_matrices=False)
    k = n_components
    scores = (Q @ U_b[:, :k]) * S[:k]
    components = Vt[:k].T
    explained = (S[:k] ** 2) / max(src.n_cells - 1, 1)
    if checkpoint is not None:
        _clear_resume_npz(checkpoint)
    return scores, components, explained


# ----------------------------------------------------------------------
# End-to-end streaming pipeline
# ----------------------------------------------------------------------


def stream_pipeline(src: ShardSource, *, n_top: int = 2000,
                    n_components: int = 50, k: int = 15,
                    metric: str = "cosine", target_sum: float = 1e4,
                    mito_mask: np.ndarray | None = None, seed: int = 0,
                    refine: int = 64,
                    hvg_flavor: str = "seurat_v3",
                    mesh=None,
                    checkpoint_dir: str | None = None,
                    knn_chunk: int | None = None,
                    prefetch_depth: int | None = None) -> dict:
    """h5ad shards → QC → HVG → 50-PC randomized PCA → kNN, out of
    core (BASELINE.json configs[4] shape).  Returns a dict:
    obs metrics (host), hvg_genes, X_pca (device), knn indices and
    distances (device, padded rows -1).

    With ``mesh=`` every streamed shard is placed cells-axis-sharded
    across the mesh (GSPMD collectives in the per-shard programs) and
    the kNN runs as the ring-ppermute multi-chip search — the
    composition the 10M-cell north star requires (stream from disk,
    compute across chips).

    ``prefetch_depth`` overrides the source's prefetch queue depth
    (default 2: double-buffered — shard N+1's decode + pack +
    device_put overlap shard N's compute on EVERY streamed pass below;
    the ``stream.overlap_s`` / ``stream.stall_s`` telemetry counters
    say how much overlap the stream actually achieved)."""
    from ..ops.knn import knn_arrays

    if prefetch_depth is not None:
        src = dataclasses.replace(src, prefetch_depth=prefetch_depth)
    if mesh is not None and knn_chunk is not None:
        raise ValueError(
            "stream_pipeline: knn_chunk= applies to the single-device "
            "search only; the mesh path runs the ring kNN (drop one)")
    if mesh is not None:
        src = src.with_mesh(mesh)
    ck_stats = ck_pca = None
    if checkpoint_dir is not None:
        # crash recovery for the two heavy streamed passes (see
        # stream_stats/stream_pca checkpoint=); each file self-deletes
        # when its pass completes
        os.makedirs(checkpoint_dir, exist_ok=True)
        ck_stats = os.path.join(checkpoint_dir, "stream_stats.npz")
        ck_pca = os.path.join(checkpoint_dir, "stream_pca.npz")
    stats = stream_stats(src, target_sum=target_sum, mito_mask=mito_mask,
                         checkpoint=ck_stats)
    hvg_genes = stream_hvg(stats, n_top=n_top, flavor=hvg_flavor, src=src)
    scores, comps, expl = stream_pca(
        src, hvg_genes, stats["gene_mean"], jax.random.PRNGKey(seed),
        n_components=n_components, target_sum=target_sum,
        checkpoint=ck_pca)
    if mesh is not None:
        # the kNN tail runs INSIDE the plan layer: the registered
        # multichip op compiles as a ShardedCollective stage (the
        # pipeline's mesh threaded into the call, counted under
        # plan.sharded_stages, one retryable step when a runner owns
        # it) instead of a hand-called dispatch around the planner
        from ..data.dataset import CellData
        from ..plan import fused_pipeline
        from ..registry import Pipeline as _Pipeline

        tail = fused_pipeline(
            _Pipeline([("neighbors.knn_multichip",
                        {"k": k, "metric": metric,
                         "strategy": "ring"})], backend="tpu"),
            mesh=mesh)
        cd = tail.run(CellData(scores, obsm={"X_pca": scores}))
        idx = cd.obsp["knn_indices"]
        dist = cd.obsp["knn_distances"]
    elif knn_chunk is not None:
        # query-chunked search via the shared generator (ops/knn.py
        # iter_knn_chunks — also the bench atlas path's engine): ONE
        # compiled (chunk x n) program reused, each chunk drained
        from ..ops.knn import iter_knn_chunks

        parts_i, parts_d = [], []
        for _off, _nq, idx_c, dist_c, _s in iter_knn_chunks(
                scores, k=k, chunk=knn_chunk, metric=metric,
                refine=refine, n=src.n_cells):
            parts_i.append(idx_c)
            parts_d.append(dist_c)
        idx = jnp.concatenate(parts_i)
        dist = jnp.concatenate(parts_d)
    else:
        idx, dist = knn_arrays(scores, scores, k=k, metric=metric,
                               n_query=src.n_cells, n_cand=src.n_cells,
                               refine=refine)
    return {
        "obs": {"total_counts": stats["total_counts"],
                "n_genes": stats["n_genes"],
                "pct_counts_mt": stats["pct_counts_mt"]},
        "hvg_genes": hvg_genes,
        "X_pca": scores,
        "pca_components": comps,
        "pca_explained_variance": expl,
        "knn_indices": idx,
        "knn_distances": dist,
        "n_cells": src.n_cells,
    }
