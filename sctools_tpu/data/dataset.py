"""``CellData`` — the AnnData-shaped container transforms operate on.

Mirrors the reference's (and AnnData's) field layout so sctools users
find what they expect:

    X     — counts: SparseCells (device, padded-ELL) / scipy CSR (cpu
            backend) / dense array
    obs   — per-cell annotations (dict of (n_cells,) arrays)
    var   — per-gene annotations (dict of (n_genes,) arrays)
    obsm  — per-cell matrices (e.g. "X_pca": (n_cells, 50))
    varm  — per-gene matrices (e.g. "PCs": (n_genes, 50))
    obsp  — pairwise/graph data (e.g. "knn_indices", "knn_distances",
            "connectivities")
    layers — alternative X-shaped matrices (e.g. "counts" preserved
            before normalisation, "spliced"/"unspliced") — SparseCells
            / scipy CSR / dense, like X
    uns   — unstructured results (scalars/small arrays)

Unlike AnnData it is **functional**: transforms return a new CellData
(``replace``/``with_*`` helpers share unchanged fields).  It is a
registered pytree — dict keys and X's static metadata ride in the
treedef — so entire pipelines jit end-to-end on TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
import numpy as np

from .sparse import SparseCells


def _freeze(d: Mapping | None) -> dict:
    return dict(d) if d else {}


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CellData:
    X: Any
    obs: dict = dataclasses.field(default_factory=dict)
    var: dict = dataclasses.field(default_factory=dict)
    obsm: dict = dataclasses.field(default_factory=dict)
    varm: dict = dataclasses.field(default_factory=dict)
    obsp: dict = dataclasses.field(default_factory=dict)
    uns: dict = dataclasses.field(default_factory=dict)
    layers: dict = dataclasses.field(default_factory=dict)

    # ------------------------------------------------------------------
    def tree_flatten(self):
        dicts = (self.obs, self.var, self.obsm, self.varm, self.obsp,
                 self.uns, self.layers)
        keys = tuple(tuple(sorted(d)) for d in dicts)
        children = [self.X] + [
            d[k] for d, ks in zip(dicts, keys) for k in ks
        ]
        return children, keys

    @classmethod
    def tree_unflatten(cls, keys, children):
        X = children[0]
        rest = list(children[1:])
        dicts = []
        for ks in keys:
            dicts.append({k: rest.pop(0) for k in ks})
        return cls(X, *dicts)

    # ------------------------------------------------------------------
    @property
    def n_cells(self) -> int:
        X = self.X
        if isinstance(X, SparseCells):
            return X.n_cells
        return X.shape[0]

    @property
    def n_genes(self) -> int:
        X = self.X
        if isinstance(X, SparseCells):
            return X.n_genes
        return X.shape[1]

    @property
    def shape(self):
        return (self.n_cells, self.n_genes)

    def replace(self, **kw) -> "CellData":
        return dataclasses.replace(self, **kw)

    def with_X(self, X) -> "CellData":
        return self.replace(X=X)

    def with_obs(self, **entries) -> "CellData":
        return self.replace(obs={**self.obs, **entries})

    def with_var(self, **entries) -> "CellData":
        return self.replace(var={**self.var, **entries})

    def with_obsm(self, **entries) -> "CellData":
        return self.replace(obsm={**self.obsm, **entries})

    def with_varm(self, **entries) -> "CellData":
        return self.replace(varm={**self.varm, **entries})

    def with_obsp(self, **entries) -> "CellData":
        return self.replace(obsp={**self.obsp, **entries})

    def with_uns(self, **entries) -> "CellData":
        return self.replace(uns={**self.uns, **entries})

    def with_layers(self, **entries) -> "CellData":
        return self.replace(layers={**self.layers, **entries})

    # ------------------------------------------------------------------
    def device_put(self, sharding=None) -> "CellData":
        """Move to device: scipy CSR X is packed to SparseCells first."""
        import scipy.sparse as sp

        def put_matrix(v):  # X and layers share one packing path
            if sp.issparse(v):
                v = SparseCells.from_scipy_csr(v)
            if isinstance(v, SparseCells):
                return v.device_put(sharding)
            return jax.device_put(np.asarray(v), sharding)

        X = put_matrix(self.X)

        def put(d):
            out = {}
            for k, v in d.items():
                arr = np.asarray(v) if not isinstance(v, jax.Array) else v
                if getattr(arr, "dtype", None) is not None and arr.dtype.kind in "biufc":
                    out[k] = jax.device_put(arr)
                else:
                    out[k] = arr  # strings/objects stay host-side
            return out

        return CellData(
            X, put(self.obs), put(self.var), put(self.obsm),
            put(self.varm), put(self.obsp), dict(self.uns),
            {k: put_matrix(v) for k, v in self.layers.items()},
        )

    def to_host(self) -> "CellData":
        """Fetch to numpy.  Per-cell arrays produced by TPU ops carry
        the padded row count; they are trimmed back to ``n_cells``."""
        n = self.n_cells

        def fetch(v, trim=False):
            if isinstance(v, SparseCells):
                return v.to_scipy_csr()
            if isinstance(v, jax.Array):
                v = np.asarray(v)
            # Per-cell arrays from TPU ops may be padded to any block
            # multiple (rows_padded, kNN row_block, …) — anything
            # longer than n_cells is padding.
            if (trim and isinstance(v, np.ndarray) and v.ndim >= 1
                    and v.shape[0] > n):
                v = v[:n]
            return v

        return CellData(
            fetch(self.X),
            {k: fetch(v, trim=True) for k, v in self.obs.items()},
            {k: fetch(v) for k, v in self.var.items()},
            {k: fetch(v, trim=True) for k, v in self.obsm.items()},
            {k: fetch(v) for k, v in self.varm.items()},
            {k: fetch(v, trim=True) for k, v in self.obsp.items()},
            {k: fetch(v) for k, v in self.uns.items()},
            {k: fetch(v, trim=True) for k, v in self.layers.items()},
        )

    def __repr__(self):
        def ks(d):
            return ", ".join(sorted(d)) or "-"

        return (
            f"CellData(n_cells={self.n_cells}, n_genes={self.n_genes},\n"
            f"  X={type(self.X).__name__},\n"
            f"  obs: {ks(self.obs)}\n  var: {ks(self.var)}\n"
            f"  obsm: {ks(self.obsm)}\n  varm: {ks(self.varm)}\n"
            f"  obsp: {ks(self.obsp)}\n  layers: {ks(self.layers)}\n"
            f"  uns: {ks(self.uns)})"
        )


def _is_arraylike(v) -> bool:
    return isinstance(v, (np.ndarray, jax.Array)) or np.isscalar(v)
