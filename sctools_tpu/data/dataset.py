"""``CellData`` — the AnnData-shaped container transforms operate on.

Mirrors the reference's (and AnnData's) field layout so sctools users
find what they expect:

    X     — counts: SparseCells (device, padded-ELL) / scipy CSR (cpu
            backend) / dense array
    obs   — per-cell annotations (dict of (n_cells,) arrays)
    var   — per-gene annotations (dict of (n_genes,) arrays)
    obsm  — per-cell matrices (e.g. "X_pca": (n_cells, 50))
    varm  — per-gene matrices (e.g. "PCs": (n_genes, 50))
    obsp  — pairwise/graph data (e.g. "knn_indices", "knn_distances",
            "connectivities")
    layers — alternative X-shaped matrices (e.g. "counts" preserved
            before normalisation, "spliced"/"unspliced") — SparseCells
            / scipy CSR / dense, like X
    uns   — unstructured results (scalars/small arrays)

Unlike AnnData it is **functional**: transforms return a new CellData
(``replace``/``with_*`` helpers share unchanged fields).  It is a
registered pytree — dict keys and X's static metadata ride in the
treedef — so entire pipelines jit end-to-end on TPU.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
import numpy as np

from .sparse import SparseCells


def _freeze(d: Mapping | None) -> dict:
    return dict(d) if d else {}


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class CellData:
    X: Any
    obs: dict = dataclasses.field(default_factory=dict)
    var: dict = dataclasses.field(default_factory=dict)
    obsm: dict = dataclasses.field(default_factory=dict)
    varm: dict = dataclasses.field(default_factory=dict)
    obsp: dict = dataclasses.field(default_factory=dict)
    uns: dict = dataclasses.field(default_factory=dict)
    layers: dict = dataclasses.field(default_factory=dict)

    # ------------------------------------------------------------------
    def tree_flatten(self):
        dicts = (self.obs, self.var, self.obsm, self.varm, self.obsp,
                 self.uns, self.layers)
        keys = tuple(tuple(sorted(d)) for d in dicts)
        children = [self.X] + [
            d[k] for d, ks in zip(dicts, keys) for k in ks
        ]
        return children, keys

    @classmethod
    def tree_unflatten(cls, keys, children):
        X = children[0]
        rest = list(children[1:])
        dicts = []
        for ks in keys:
            dicts.append({k: rest.pop(0) for k in ks})
        return cls(X, *dicts)

    # ------------------------------------------------------------------
    @property
    def n_cells(self) -> int:
        X = self.X
        if isinstance(X, SparseCells):
            return X.n_cells
        return X.shape[0]

    @property
    def n_genes(self) -> int:
        X = self.X
        if isinstance(X, SparseCells):
            return X.n_genes
        return X.shape[1]

    @property
    def shape(self):
        return (self.n_cells, self.n_genes)

    # anndata-spelled aliases — the names every ported script reaches
    # for first (adata.n_obs, adata.var_names, ...).  Name arrays fall
    # back to positional string ids when no annotation exists, like a
    # fresh AnnData's default RangeIndex-as-strings.
    @property
    def n_obs(self) -> int:
        return self.n_cells

    @property
    def n_vars(self) -> int:
        return self.n_genes

    @property
    def obs_names(self) -> np.ndarray:
        for key in ("cell_name", "barcode"):
            if key in self.obs:
                return np.asarray(self.obs[key]).astype(str)
        return np.arange(self.n_cells).astype(str)

    @property
    def var_names(self) -> np.ndarray:
        if "gene_name" in self.var:
            return np.asarray(self.var["gene_name"]).astype(str)
        return np.arange(self.n_genes).astype(str)

    def replace(self, **kw) -> "CellData":
        return dataclasses.replace(self, **kw)

    def with_X(self, X) -> "CellData":
        return self.replace(X=X)

    def with_obs(self, **entries) -> "CellData":
        return self.replace(obs={**self.obs, **entries})

    def var_names_make_unique(self, join: str = "-") -> "CellData":
        """Deduplicate ``var['gene_name']`` by appending ``-1``,
        ``-2``, … to repeats, keeping the first occurrence unchanged
        (anndata ``.var_names_make_unique()`` — the call every 10x
        read is followed by, since CellRanger references repeat gene
        symbols).  No-op when names are absent or already unique.

        RETURNS A NEW ``CellData`` — you MUST reassign::

            data = data.var_names_make_unique()

        This deviates from anndata, whose method mutates in place;
        a ported script calling it without reassignment is a silent
        no-op (``CellData`` is immutable, so an in-place form cannot
        exist — see "Known API deviations" in docs/GUIDE.md)."""
        names = self.var.get("gene_name")
        if names is None:
            return self
        names = np.asarray(names).astype(str)
        if len(np.unique(names)) == len(names):
            return self
        # build as a python LIST — assigning 'A-1' into the input's
        # fixed-width '<U1' array truncates it straight back to 'A'
        existing = set(names.tolist())
        seen: dict = {}
        out: list = []
        for nm in names:
            k = seen.get(nm, 0)
            if k:  # repeat: suffix with its occurrence count
                new = f"{nm}{join}{k}"
                # the candidate may collide with a name ANYWHERE in
                # the array (earlier or later) or one already issued;
                # keep bumping (anndata warns here — we resolve)
                while new in existing or new in seen:
                    k += 1
                    new = f"{nm}{join}{k}"
                out.append(new)
                seen[new] = 1
            else:
                out.append(nm)
            seen[nm] = k + 1
        return self.with_var(gene_name=np.asarray(out))

    def with_var(self, **entries) -> "CellData":
        return self.replace(var={**self.var, **entries})

    def with_obsm(self, **entries) -> "CellData":
        return self.replace(obsm={**self.obsm, **entries})

    def with_varm(self, **entries) -> "CellData":
        return self.replace(varm={**self.varm, **entries})

    def with_obsp(self, **entries) -> "CellData":
        return self.replace(obsp={**self.obsp, **entries})

    def with_uns(self, **entries) -> "CellData":
        return self.replace(uns={**self.uns, **entries})

    def with_layers(self, **entries) -> "CellData":
        return self.replace(layers={**self.layers, **entries})

    # ------------------------------------------------------------------
    def device_put(self, sharding=None) -> "CellData":
        """Move to device: scipy CSR X is packed to SparseCells first."""
        import scipy.sparse as sp

        def put_matrix(v):  # X and layers share one packing path
            if sp.issparse(v):
                v = SparseCells.from_scipy_csr(v)
            if isinstance(v, SparseCells):
                return v.device_put(sharding)
            return jax.device_put(np.asarray(v), sharding)

        X = put_matrix(self.X)

        def put(d):
            out = {}
            for k, v in d.items():
                arr = np.asarray(v) if not isinstance(v, jax.Array) else v
                if getattr(arr, "dtype", None) is not None and arr.dtype.kind in "biufc":
                    out[k] = jax.device_put(arr)
                else:
                    out[k] = arr  # strings/objects stay host-side
            return out

        return CellData(
            X, put(self.obs), put(self.var), put(self.obsm),
            put(self.varm), put(self.obsp), dict(self.uns),
            {k: put_matrix(v) for k, v in self.layers.items()},
        )

    def to_host(self) -> "CellData":
        """Fetch to numpy.  Per-cell arrays produced by TPU ops carry
        the padded row count; they are trimmed back to ``n_cells``."""
        n = self.n_cells

        def fetch(v, trim=False):
            if isinstance(v, SparseCells):
                return v.to_scipy_csr()
            if isinstance(v, jax.Array):
                v = np.asarray(v)
            # Per-cell arrays from TPU ops may be padded to any block
            # multiple (rows_padded, kNN row_block, …) — anything
            # longer than n_cells is padding.
            if (trim and isinstance(v, np.ndarray) and v.ndim >= 1
                    and v.shape[0] > n):
                v = v[:n]
            return v

        return CellData(
            fetch(self.X),
            {k: fetch(v, trim=True) for k, v in self.obs.items()},
            {k: fetch(v) for k, v in self.var.items()},
            {k: fetch(v, trim=True) for k, v in self.obsm.items()},
            {k: fetch(v) for k, v in self.varm.items()},
            {k: fetch(v, trim=True) for k, v in self.obsp.items()},
            {k: fetch(v) for k, v in self.uns.items()},
            {k: fetch(v, trim=True) for k, v in self.layers.items()},
        )

    # ------------------------------------------------------------------
    def obs_vector(self, key: str) -> np.ndarray:
        """AnnData ``obs_vector``: an obs column, or a GENE's expression
        across cells (matched via var["gene_name"]) — the accessor
        plotting/inspection code reaches for."""
        if key in self.obs:
            return np.asarray(self.obs[key])[: self.n_cells]
        names = self.var.get("gene_name")
        if names is not None:
            pos = np.nonzero(np.asarray(names).astype(str) == key)[0]
            if len(pos):
                return _column_1d(self[:, int(pos[0])].X, self.n_cells)
        raise KeyError(
            f"obs_vector: {key!r} is neither an obs column nor a gene "
            "name")

    def var_vector(self, key: str) -> np.ndarray:
        """AnnData ``var_vector``: a var column, or a CELL's expression
        across genes (by integer position — CellData has no obs
        index)."""
        if key in self.var:
            return np.asarray(self.var[key])[: self.n_genes]
        if isinstance(key, (int, np.integer)):
            return _column_1d(self[int(key)].X, self.n_genes)
        raise KeyError(f"var_vector: {key!r} is not a var column")

    # ------------------------------------------------------------------
    def __getitem__(self, key) -> "CellData":
        """AnnData-style subsetting: ``d[cells]`` / ``d[:, genes]`` /
        ``d[cells, genes]``.  Selectors: slices, boolean masks, int
        index arrays, and (for the gene axis) gene-name arrays matched
        against ``var["gene_name"]``.  Returns a new CellData with X,
        obs/var, obsm/varm, and every layer sliced consistently; obsp
        is dropped on cell subsets (pairwise graphs refer to dropped
        rows — rebuild ``neighbors.knn``).  Works on host (scipy) and
        device (SparseCells gather) data alike."""
        if isinstance(key, tuple):
            if len(key) > 2:
                raise IndexError("CellData supports at most 2 axes")
            ckey = key[0]
            gkey = key[1] if len(key) > 1 else slice(None)
        else:
            ckey, gkey = key, slice(None)
        out = self
        gidx = _normalize_axis_key(gkey, self.n_genes,
                                   names=self.var.get("gene_name"),
                                   axis="gene")
        cidx = _normalize_axis_key(ckey, self.n_cells, names=None,
                                   axis="cell")
        on_host = not isinstance(self.X, SparseCells)
        if gidx is not None:
            if on_host:
                out = _host_subset_genes(out, gidx)
            else:
                from ..ops.hvg import select_genes_device

                out = select_genes_device(out, gidx)
        if cidx is not None:
            if on_host:
                out = _host_subset_cells(out, cidx)
            else:
                from ..ops.qc import select_cells_device

                out = select_cells_device(out, cidx)
        return out

    def __repr__(self):
        def ks(d):
            return ", ".join(sorted(d)) or "-"

        return (
            f"CellData(n_cells={self.n_cells}, n_genes={self.n_genes},\n"
            f"  X={type(self.X).__name__},\n"
            f"  obs: {ks(self.obs)}\n  var: {ks(self.var)}\n"
            f"  obsm: {ks(self.obsm)}\n  varm: {ks(self.varm)}\n"
            f"  obsp: {ks(self.obsp)}\n  layers: {ks(self.layers)}\n"
            f"  uns: {ks(self.uns)})"
        )


def _is_arraylike(v) -> bool:
    return isinstance(v, (np.ndarray, jax.Array)) or np.isscalar(v)


def _column_1d(M, n: int) -> np.ndarray:
    """A 1-row/1-column X slice as a flat numpy vector, whatever the
    residency (scipy / dense / SparseCells)."""
    if isinstance(M, SparseCells):
        return np.asarray(M.to_dense()).ravel()[:n]
    if hasattr(M, "toarray"):
        return M.toarray().ravel()[:n]
    return np.asarray(M).ravel()[:n]


def _normalize_axis_key(key, n: int, names, axis: str):
    """Selector → int index array, or None for the full-axis no-op."""
    if isinstance(key, slice):
        if key == slice(None):
            return None
        return np.arange(*key.indices(n))
    if isinstance(key, (int, np.integer)):
        if not -n <= key < n:
            raise IndexError(f"{axis} index {key} out of range for {n}")
        return np.array([key % n])
    arr = np.asarray(key)
    if arr.size == 0:
        # AnnData parity: an empty selection yields a 0-row/0-col view
        return np.empty(0, np.int64)
    if arr.ndim != 1:
        raise IndexError(
            f"{axis} selector must be 1-D, got shape {arr.shape}")
    if arr.dtype.kind == "b":
        if len(arr) != n and not (axis == "cell" and len(arr) > n):
            # only the CELL axis accepts longer masks (per-cell arrays
            # from TPU ops carry padded rows; the extra entries refer
            # to padding and are dropped) — a long mask on the gene
            # axis is a wrong-axis bug, not an idiom
            raise IndexError(
                f"boolean {axis} mask has length {len(arr)}, "
                f"expected {n}")
        return np.where(arr[:n])[0]
    if arr.dtype.kind in "iu":
        if arr.max() >= n or arr.min() < -n:
            raise IndexError(f"{axis} indices out of range for {n}")
        return arr % n
    if arr.dtype.kind in "US":
        if names is None:
            raise KeyError(
                "name-based selection is only supported on the gene "
                "axis (via var['gene_name']); select cells by mask or "
                "index instead" if axis == "cell" else
                "gene-name selection needs var['gene_name']")
        pos = {g: i for i, g in enumerate(np.asarray(names).astype(str))}
        missing = [g for g in arr.astype(str) if g not in pos]
        if missing:
            raise KeyError(f"unknown {axis} names: {missing[:5]}")
        return np.array([pos[g] for g in arr.astype(str)])
    raise TypeError(f"unsupported {axis} selector {type(key).__name__}")


def _slice_aligned(d: dict, idx: np.ndarray) -> dict:
    return {k: (np.asarray(v)[idx] if getattr(np.asarray(v), "ndim", 0)
                else v) for k, v in d.items()}


def _host_subset_cells(data: "CellData", idx: np.ndarray) -> "CellData":
    """Pure-host row subset (numpy/scipy stay numpy/scipy; no JAX)."""
    import scipy.sparse as sp

    def rows(M):
        return M.tocsr()[idx] if sp.issparse(M) else np.asarray(M)[idx]

    return CellData(rows(data.X),
                    obs=_slice_aligned(data.obs, idx),
                    var=dict(data.var),
                    obsm=_slice_aligned(data.obsm, idx),
                    varm=dict(data.varm),
                    obsp={},  # pairwise graphs refer to dropped rows
                    uns=dict(data.uns),
                    layers={k: rows(v) for k, v in data.layers.items()})


def _host_subset_genes(data: "CellData", idx: np.ndarray) -> "CellData":
    """Pure-host column subset."""
    import scipy.sparse as sp

    def cols(M):
        return (M.tocsc()[:, idx].tocsr() if sp.issparse(M)
                else np.asarray(M)[:, idx])

    return CellData(cols(data.X),
                    obs=dict(data.obs),
                    var=_slice_aligned(data.var, idx),
                    obsm=dict(data.obsm),
                    varm=_slice_aligned(data.varm, idx),
                    obsp=dict(data.obsp),
                    uns=dict(data.uns),
                    layers={k: cols(v) for k, v in data.layers.items()})
