"""Durable shard store + IO-failure domain for out-of-core ingest.

At atlas scale the counts never fit host RAM and an ingest reads from
real disks for hours (annbatch, PAPERS.md) — so the IO path needs the
same failure-containment ladder PRs 1/3/8 built for the compute path.
This module is that tier, in three layers:

**Durable store** (:class:`ShardStore` / :class:`StoreWriter` /
:func:`write_store`): a chunked on-disk format — one checksummed
``.npz`` per CSR chunk (``data/io.py`` ``write_csr_chunk``, the
checkpoint layer's ``_integrity/*`` conventions: content digest,
schema version, identity fingerprint) plus a ``manifest.json``
recording every chunk's digest, so THREE distinct failures are all
caught before a bad byte reaches the device: damaged bytes (file
digest mismatch), renamed/foreign files (fingerprint mismatch), and
cross-wired intact files (manifest-vs-file digest mismatch).  A shard
(the streaming unit, ``shard_rows`` cells) is several chunk files;
the read path reassembles them with the native multi-threaded CSR
decode (``csrc/scio.cpp`` ``scio_pack_ell_f32_chunks``, one thread
per chunk) into one padded-ELL :class:`~.sparse.SparseCells` sharing
the manifest's global ``capacity`` — one compiled program serves
every shard.

**Read scheduler** (:class:`ShardReadScheduler`): a reader pool above
the store feeding N concurrent consumer streams.  Requests are served
in ascending shard order across all consumers (approximate elevator
order — two consumers near each other read the same disk region) and
the chunks of one shard are one coalesced task read in file order.
Decoded bytes in flight are bounded by ``ram_budget_bytes``: a
consumer's lookahead submissions reserve their decoded size and stall
when the budget is spent (one in-flight read per consumer is always
allowed — progress beats the budget).  Every wait is driven off the
injectable clock (``utils/vclock.py``), so the whole failure domain
is tier-1 testable with zero real sleeps.

**IO-failure domain** (inside the scheduler's ``_await_shard``): the
read ladder mirrors the runner's containment ladder —

* per-read deadline: an attempt past ``read_deadline_s`` is abandoned
  and classified transient (a wedged NFS read must not wedge the
  ingest);
* classified retry: transient failures (injected ``io_error``, real
  ``OSError(EIO)`` — ``failsafe.classify_error``) retry with
  seeded-jitter backoff up to ``policy.max_attempts``;
* slow-read hedging: a straggler past ``hedge_after_s`` gets a
  duplicate read; the FIRST ready result wins (the straggler may
  still beat the hedge);
* quarantine: a digest/fingerprint/truncation failure is
  DETERMINISTIC — the chunk file is moved (never deleted) to
  ``quarantine/`` with a ``.reason.json`` sidecar
  (``checkpoint.quarantine_checkpoint``), a ``shard_quarantined``
  event is journaled, and the shard then fails or is skipped per
  ``on_corrupt=``.

Every terminated shard read lands in exactly one of {served,
retried-then-served, hedged, quarantined} — counted in the
``ingest.*`` metric family (SCT009 vocabulary).  Chaos modes
``slow_read`` / ``truncate_shard`` / ``io_error`` fire through
``ChaosMonkey.on_io`` (the scheduler consults it per chunk read), so
the whole ladder is exercised deterministically on a
:class:`~..utils.vclock.VirtualClock`.

Resume composes from the pieces that already exist: the store's
:meth:`ShardStore.source` is range-aware (``factory_from`` seeks), so
``stream_stats``/``stream_pca`` shard-granular checkpoints (now
verified through the same integrity layer) resume a killed ingest at
the next unprocessed shard with bitwise-identical results.
"""

from __future__ import annotations

import hashlib
import heapq
import itertools
import json
import os
import random
import threading

import numpy as np

from ..config import config, round_up
from ..utils import telemetry
from ..utils.checkpoint import (CheckpointCorruptError,
                                quarantine_checkpoint)
from ..utils.failsafe import (TRANSIENT, TransientDeviceError,
                              classify_error)
from ..utils.vclock import SYSTEM_CLOCK
from .sparse import SparseCells
from .stream import ShardSource

#: bump when the store layout changes incompatibly; manifests stamped
#: newer than the reader understands are refused (never half-parsed)
SHARDSTORE_SCHEMA = 1

_MANIFEST = "manifest.json"
_CHUNK_DIR = "chunks"


class ShardCorruptError(RuntimeError):
    """A store chunk failed integrity verification (damaged bytes,
    truncation, fingerprint or manifest-digest mismatch).
    Deterministic by classification — re-reading the same bytes fails
    the same way, so the ruling is quarantine + fail/skip, never a
    retry.  ``.chunk``/``.shard`` locate the failure, ``.path`` the
    file, ``.reason`` the machine-readable why."""

    def __init__(self, path: str, reason: str, chunk: int,
                 shard: int | None = None):
        super().__init__(f"chunk {chunk} ({path}): {reason}")
        self.path = path
        self.reason = reason
        self.chunk = chunk
        self.shard = shard


def _chunk_fingerprint(index: int, n_genes: int,
                       chunk_rows: int) -> str:
    """Identity fingerprint a chunk file carries in its
    ``_integrity/fingerprint`` slot: a pure function of the chunk's
    SLOT (index + store geometry), so a renamed file fails
    verification even before the manifest digest cross-check."""
    key = f"shardstore/chunk{index:05d}/g{n_genes}/cr{chunk_rows}"
    return hashlib.sha256(key.encode()).hexdigest()[:10]


class StoreWriter:
    """Append-only writer for a :class:`ShardStore` directory.

    ``append(csr_block)`` takes arbitrary-sized CSR row blocks (a
    generator can stream a store bigger than RAM into being) and
    flushes full ``chunk_rows``-row chunk files as rows accumulate;
    ``close()`` flushes the remainder and writes the manifest.  The
    global ELL ``capacity`` (max nnz/row over the whole store, rounded
    to the lane multiple) is discovered during the write and recorded
    in the manifest, so every later read shares one compiled program.
    """

    def __init__(self, directory: str, n_genes: int, *,
                 shard_rows: int = 65536, chunk_rows: int | None = None):
        self.directory = directory
        self.n_genes = int(n_genes)
        self.shard_rows = round_up(int(shard_rows), config.sublane)
        if chunk_rows is None:
            chunk_rows = max(self.shard_rows // 4, 1)
        self.chunk_rows = int(chunk_rows)
        if self.shard_rows % self.chunk_rows:
            raise ValueError(
                f"shard_rows={self.shard_rows} must be a multiple of "
                f"chunk_rows={self.chunk_rows} (a shard is a whole "
                f"number of chunk files)")
        os.makedirs(os.path.join(directory, _CHUNK_DIR), exist_ok=True)
        self._pending = []          # buffered csr blocks
        self._pending_rows = 0
        self._chunks: list[dict] = []
        self._n_cells = 0
        self._max_nnz = 0
        self._closed = False
        # append_to() seeds these from the manifest being extended
        self._base_capacity = 0
        self._appends: list[dict] = []
        self._append_label: str | None = None
        self._append_row_start = 0
        self._append_chunk_start = 0

    @classmethod
    def append_to(cls, store, *, label: str | None = None,
                  n_genes: int | None = None,
                  shard_rows: int | None = None,
                  chunk_rows: int | None = None,
                  verify_tail: bool = True) -> "StoreWriter":
        """Reopen an existing store for appending NEW chunks.

        The writer seeds its chunk ledger / row counters / nnz maximum
        from the store's manifest and continues chunk numbering where
        the store left off, so slot fingerprints stay a pure function
        of (index, geometry).  The commit point is the atomic manifest
        replace in :meth:`close` — a crash mid-append leaves orphan
        chunk files beyond the committed manifest that a deterministic
        redo overwrites byte-identically, which is what makes the
        factory's ingest an at-most-once commit.

        Refusals (all BEFORE any byte is written):

        * the recorded ``store_digest`` must recompute from the
          recorded chunk digests (a tampered/hand-edited manifest is
          not a base to extend);
        * any explicitly passed geometry (``n_genes`` / ``shard_rows``
          / ``chunk_rows``) must match the manifest — the caller's
          idea of the store and the store itself must agree;
        * the committed store must end on a chunk boundary
          (``n_cells % chunk_rows == 0``): a partial tail chunk would
          shift every appended row's shard arithmetic;
        * with ``verify_tail`` (default), the final committed chunk
          file must pass full integrity verification — the chunk most
          at risk of a torn previous append.

        ``label=`` records an entry in the manifest's append ledger on
        close (``{"label", "row_start", "rows", "chunk_start",
        "n_chunks"}``); :meth:`ShardStore.append_labels` answers
        "was this batch already committed?" for at-most-once ingest.
        """
        if isinstance(store, str):
            store = ShardStore.open(store)
        m = store.manifest
        mpath = os.path.join(store.directory, _MANIFEST)
        recomputed = hashlib.sha256("".join(
            c["digest"] for c in m["chunks"]).encode()).hexdigest()[:16]
        if recomputed != m.get("store_digest"):
            raise ShardCorruptError(
                mpath, "store_digest does not recompute from the "
                       "recorded chunk digests — refusing to extend a "
                       "tampered manifest", chunk=-1)
        for name, got in (("n_genes", n_genes),
                          ("shard_rows", shard_rows),
                          ("chunk_rows", chunk_rows)):
            if got is not None and int(got) != int(m[name]):
                raise ValueError(
                    f"append_to: {name}={got} does not match the "
                    f"store's {name}={m[name]} — geometry is frozen "
                    f"at creation")
        if store.n_cells % store.chunk_rows:
            raise ValueError(
                f"append_to: store ends mid-chunk ({store.n_cells} "
                f"cells, chunk_rows={store.chunk_rows}) — appending "
                f"would shift shard arithmetic for every new row")
        if verify_tail and m["chunks"]:
            tail = len(m["chunks"]) - 1
            from .io import read_csr_chunk
            read_csr_chunk(
                store.chunk_path(tail),
                expect_fingerprint=_chunk_fingerprint(
                    tail, store.n_genes, store.chunk_rows),
                expect_digest=m["chunks"][tail]["digest"])
        w = cls(store.directory, store.n_genes,
                shard_rows=store.shard_rows,
                chunk_rows=store.chunk_rows)
        w._chunks = [dict(c) for c in m["chunks"]]
        w._n_cells = store.n_cells
        w._max_nnz = int(m.get("max_nnz_row", 0))
        w._base_capacity = store.capacity
        w._appends = [dict(a) for a in m.get("appends", [])]
        w._append_label = label
        w._append_row_start = store.n_cells
        w._append_chunk_start = len(m["chunks"])
        return w

    def append(self, csr_block) -> None:
        import scipy.sparse as sp

        if self._closed:
            raise ValueError("StoreWriter is closed")
        block = sp.csr_matrix(csr_block)
        if block.shape[1] != self.n_genes:
            raise ValueError(
                f"append: block has {block.shape[1]} genes, store has "
                f"{self.n_genes}")
        self._pending.append(block)
        self._pending_rows += block.shape[0]
        if self._pending_rows >= self.chunk_rows:
            self._drain(final=False)

    def _drain(self, final: bool) -> None:
        """Emit every full chunk buffered so far (plus the remainder
        when ``final``) from ONE vstacked buffer — each chunk is a
        single row-slice copy, so a large ``append`` costs O(rows),
        not the O(rows²) a per-chunk re-slice of the shrinking
        remainder would."""
        import scipy.sparse as sp

        buf = (self._pending[0] if len(self._pending) == 1
               else sp.vstack(self._pending, format="csr"))
        a = 0
        while buf.shape[0] - a >= self.chunk_rows:
            self._write_chunk(buf[a: a + self.chunk_rows])
            a += self.chunk_rows
        if final and buf.shape[0] - a:
            self._write_chunk(buf[a:])
            a = buf.shape[0]
        rest = buf[a:]
        self._pending = [rest] if rest.shape[0] else []
        self._pending_rows = int(rest.shape[0])

    def _write_chunk(self, chunk) -> None:
        chunk.sort_indices()
        rows = chunk.shape[0]
        index = len(self._chunks)
        name = f"chunk-{index:05d}"
        path = os.path.join(self.directory, _CHUNK_DIR, f"{name}.npz")
        from .io import write_csr_chunk

        digest = write_csr_chunk(
            path, chunk.data.astype(np.float32, copy=False),
            chunk.indices, chunk.indptr, chunk.shape,
            fingerprint=_chunk_fingerprint(index, self.n_genes,
                                           self.chunk_rows))
        nnz_row = int(np.diff(chunk.indptr).max()) if rows else 0
        self._max_nnz = max(self._max_nnz, nnz_row)
        self._chunks.append({
            "file": f"{_CHUNK_DIR}/{name}.npz", "rows": int(rows),
            "row_start": int(self._n_cells), "nnz": int(chunk.nnz),
            "digest": digest,
        })
        self._n_cells += rows

    def close(self) -> "ShardStore":
        if self._closed:
            raise ValueError("StoreWriter already closed")
        if self._pending_rows:
            self._drain(final=True)
        self._closed = True
        # monotonically non-decreasing across appends: readers compiled
        # against the old capacity must stay valid for old shards
        capacity = max(round_up(max(self._max_nnz, 1),
                                config.capacity_multiple),
                       config.capacity_multiple,
                       self._base_capacity)
        if self._append_label is not None:
            self._appends.append({
                "label": self._append_label,
                "row_start": self._append_row_start,
                "rows": self._n_cells - self._append_row_start,
                "chunk_start": self._append_chunk_start,
                "n_chunks": len(self._chunks) - self._append_chunk_start,
            })
        manifest = {
            "schema": SHARDSTORE_SCHEMA,
            "n_cells": self._n_cells, "n_genes": self.n_genes,
            "shard_rows": self.shard_rows,
            "chunk_rows": self.chunk_rows,
            "capacity": capacity, "max_nnz_row": self._max_nnz,
            "dtype": "float32",
            "chunks": self._chunks,
            "appends": self._appends,
            "store_digest": hashlib.sha256("".join(
                c["digest"] for c in self._chunks).encode())
            .hexdigest()[:16],
        }
        tmp = os.path.join(self.directory, _MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        os.replace(tmp, os.path.join(self.directory, _MANIFEST))
        return ShardStore(self.directory, manifest)


def write_store(X, directory: str, *, shard_rows: int = 65536,
                chunk_rows: int | None = None) -> "ShardStore":
    """Write an in-memory CSR matrix as a durable shard store
    (convenience over :class:`StoreWriter`; for matrices bigger than
    RAM, stream blocks into ``StoreWriter.append`` instead)."""
    X = X.tocsr()
    w = StoreWriter(directory, X.shape[1], shard_rows=shard_rows,
                    chunk_rows=chunk_rows)
    step = w.chunk_rows
    for s in range(0, X.shape[0], step):
        w.append(X[s: s + step])
    return w.close()


class ShardStore:
    """An opened durable shard store (see module docstring for the
    on-disk format).  Cheap to open — the manifest is the only read;
    chunk files are read (and verified) lazily per shard."""

    def __init__(self, directory: str, manifest: dict):
        self.directory = directory
        self.manifest = manifest

    @classmethod
    def open(cls, directory: str) -> "ShardStore":
        path = os.path.join(directory, _MANIFEST)
        try:
            with open(path) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ShardCorruptError(
                path, f"manifest unreadable ({type(e).__name__}: {e})",
                chunk=-1) from e
        schema = int(manifest.get("schema", 0))
        if schema > SHARDSTORE_SCHEMA:
            raise ShardCorruptError(
                path, f"manifest schema {schema} newer than supported "
                      f"{SHARDSTORE_SCHEMA}", chunk=-1)
        for field in ("n_cells", "n_genes", "shard_rows", "chunk_rows",
                      "capacity", "chunks"):
            if field not in manifest:
                raise ShardCorruptError(
                    path, f"manifest missing field {field!r}", chunk=-1)
        return cls(directory, manifest)

    # -- geometry ------------------------------------------------------
    @property
    def n_cells(self) -> int:
        return int(self.manifest["n_cells"])

    @property
    def n_genes(self) -> int:
        return int(self.manifest["n_genes"])

    @property
    def shard_rows(self) -> int:
        return int(self.manifest["shard_rows"])

    @property
    def chunk_rows(self) -> int:
        return int(self.manifest["chunk_rows"])

    @property
    def capacity(self) -> int:
        return int(self.manifest["capacity"])

    @property
    def n_chunks(self) -> int:
        return len(self.manifest["chunks"])

    @property
    def n_shards(self) -> int:
        return -(-self.n_cells // self.shard_rows)

    def append_labels(self) -> list[str]:
        """Labels of every committed append batch (the manifest's
        append ledger, written by :meth:`StoreWriter.append_to` with
        ``label=``) — the at-most-once guard for factory ingest: a
        batch whose label is here is already durably committed."""
        return [a["label"] for a in self.manifest.get("appends", [])
                if a.get("label") is not None]

    def chunk_name(self, c: int) -> str:
        """Basename (sans extension) chaos fault patterns match."""
        return f"chunk-{c:05d}"

    def chunk_path(self, c: int) -> str:
        return os.path.join(self.directory,
                            self.manifest["chunks"][c]["file"])

    def chunk_range(self, shard: int) -> tuple[int, int]:
        """Chunk indices ``[c0, c1)`` making up ``shard``."""
        per = self.shard_rows // self.chunk_rows
        return shard * per, min(self.n_chunks, (shard + 1) * per)

    def shard_rows_of(self, shard: int) -> int:
        return (min(self.n_cells, (shard + 1) * self.shard_rows)
                - shard * self.shard_rows)

    def shard_nbytes_est(self) -> int:
        """Decoded padded-ELL bytes of one full shard (int32 ids +
        f32 values) — the RAM-budget accounting unit."""
        return self.shard_rows * self.capacity * 8

    # -- reads ---------------------------------------------------------
    def read_chunk_arrays(self, c: int, shard: int | None = None,
                          verify: bool = True) -> tuple:
        """Read + triple-verify one chunk file (self digest,
        slot fingerprint, manifest digest).  Integrity failures raise
        :class:`ShardCorruptError`."""
        from .io import read_csr_chunk

        rec = self.manifest["chunks"][c]
        path = self.chunk_path(c)
        try:
            return read_csr_chunk(
                path, verify=verify,
                expect_fingerprint=_chunk_fingerprint(
                    c, self.n_genes, self.chunk_rows),
                expect_digest=rec["digest"])
        except CheckpointCorruptError as e:
            raise ShardCorruptError(path, e.reason, chunk=c,
                                    shard=shard) from e

    def read_shard(self, shard: int, verify: bool = True,
                   on_chunk=None) -> SparseCells:
        """Read every chunk of ``shard`` (coalesced, file order) and
        decode into one padded-ELL :class:`SparseCells` via the native
        multi-threaded chunk decode.  ``on_chunk(index, name, path)``
        is called before each chunk read — the scheduler's chaos
        consult hook, kept HERE so the plain and scheduled read paths
        share one chunk loop (row arithmetic cannot diverge)."""
        c0, c1 = self.chunk_range(shard)
        chunks = []
        for c in range(c0, c1):
            if on_chunk is not None:
                on_chunk(c, self.chunk_name(c), self.chunk_path(c))
            data, indices, indptr, _shape = self.read_chunk_arrays(
                c, shard=shard, verify=verify)
            row0 = (self.manifest["chunks"][c]["row_start"]
                    - shard * self.shard_rows)
            chunks.append((indptr, indices, data, row0))
        return self.assemble_shard(shard, chunks)

    def assemble_shard(self, shard: int, chunks: list) -> SparseCells:
        from ..native import pack_ell_chunks

        rows = self.shard_rows_of(shard)
        rows_padded = round_up(max(rows, 1), config.sublane)
        indices, data = pack_ell_chunks(chunks, rows_padded,
                                        self.capacity,
                                        sentinel=self.n_genes)
        return SparseCells(indices, data, rows, self.n_genes)

    def quarantine_chunk(self, c: int, reason: str) -> str | None:
        """Move chunk ``c`` aside (never delete) with a
        ``.reason.json`` sidecar.  Returns the quarantined path, or
        ``None`` when the file is already gone (a prior ruling moved
        it — the quarantine is idempotent evidence-keeping, not a
        second verdict)."""
        path = self.chunk_path(c)
        if not os.path.exists(path):
            return None
        return quarantine_checkpoint(path, reason)

    # -- stream integration -------------------------------------------
    def iter_shards(self, start_shard: int = 0, verify: bool = True):
        """Plain (scheduler-less) shard iterator — serial verified
        reads, no retry/hedge ladder."""
        for i in range(start_shard, self.n_shards):
            yield self.read_shard(i, verify=verify)

    def source(self, scheduler: "ShardReadScheduler | None" = None,
               prefetch: bool = True) -> ShardSource:
        """A range-aware :class:`~.stream.ShardSource` over this store
        — the streaming passes (``stream_stats`` / ``stream_pca`` /
        ``stream_pipeline``) consume it unchanged, and their
        shard-granular checkpoints resume by SEEKING (``factory_from``
        starts mid-store without reading skipped shards).  With
        ``scheduler=`` every read goes through the IO-failure domain
        (retry/hedge/quarantine, RAM budget, locality order)."""
        if scheduler is not None:
            if scheduler.store is not self:
                raise ValueError("scheduler serves a different store")
            if scheduler.on_corrupt == "skip":
                raise ValueError(
                    "source(): on_corrupt='skip' would silently shift "
                    "row offsets mid-stream; streaming passes need "
                    "on_corrupt='fail' (use scheduler.iter_shards "
                    "directly for skip-tolerant consumers)")
            factory_from = scheduler.iter_shards
        else:
            factory_from = self.iter_shards
        return ShardSource(
            lambda: factory_from(0), self.n_cells, self.n_genes,
            self.shard_rows, prefetch=prefetch,
            factory_from=factory_from)


def open_store(directory: str) -> ShardStore:
    return ShardStore.open(directory)


# ----------------------------------------------------------------------
# Read scheduler (the IO-failure domain)
# ----------------------------------------------------------------------

_SKIPPED = object()


class _PendingRead:
    """One in-flight shard read.  The worker fills exactly one of
    ``result``/``error`` and sets ``done_evt``; ``ready_at`` is the
    (injectable-clock) instant the result becomes servable — a
    chaos-slow read completes in real time but stays 'in flight' in
    virtual time until then, which is what lets the hedge/SLO ladder
    run deterministically with zero real sleeps."""

    __slots__ = ("shard", "lock", "done_evt", "result", "error",
                 "ready_at", "nbytes", "abandoned", "released",
                 "holds_budget")

    def __init__(self, shard: int, holds_budget: bool = False):
        self.shard = shard
        self.lock = threading.Lock()
        self.done_evt = threading.Event()
        self.result = None
        self.error: BaseException | None = None
        self.ready_at = 0.0
        self.nbytes = 0
        self.abandoned = False
        self.released = False
        self.holds_budget = holds_budget

    def peek(self, clock):
        """``("pending" | "error" | "ready" | "deferred", value)``."""
        if not self.done_evt.is_set():
            return "pending", None
        with self.lock:
            if self.error is not None:
                return "error", self.error
            if clock.monotonic() >= self.ready_at:
                return "ready", self.result
            return "deferred", self.ready_at


class ShardReadScheduler:
    """Locality-aware, failure-contained reader pool above a
    :class:`ShardStore` (module docstring: layers 2 + 3).

    Parameters
    ----------
    store : ShardStore
    n_readers : int
        Reader threads shared by every consumer stream.
    ram_budget_bytes : int | None
        Bound on decoded shard bytes in flight across ALL consumers
        (``None`` = a small fixed lookahead).  Each consumer always
        gets one in-flight read regardless — progress beats budget.
    policy
        Retry policy (``runner.RetryPolicy``-shaped: ``max_attempts``
        + ``delay_s(attempt, rng)``); governs transient-failure
        retries per shard read.
    read_deadline_s / hedge_after_s : float | None
        Per-read deadline (overrun = abandoned + classified
        transient) and slow-read hedging SLO (straggler past it gets
        a duplicate read, first ready result wins).  Both measured on
        the injectable ``clock``.
    on_corrupt : "fail" | "skip"
        After the mandatory quarantine of a corrupt chunk: ``fail``
        raises :class:`ShardCorruptError` (streaming passes — offsets
        must not shift), ``skip`` drops the shard and records it in
        ``.skipped``.
    chaos : ChaosMonkey | None
        Consulted per chunk read (``on_io`` — the IO fault channel).
    journal
        ``runner._Journal``-shaped object or a path; receives
        ``shard_quarantined`` events.
    """

    def __init__(self, store: ShardStore, *, n_readers: int = 2,
                 ram_budget_bytes: int | None = None,
                 policy=None, read_deadline_s: float | None = None,
                 hedge_after_s: float | None = None,
                 on_corrupt: str = "fail",
                 clock=None, metrics=None, chaos=None, journal=None,
                 poll_s: float = 0.002):
        if on_corrupt not in ("fail", "skip"):
            raise ValueError("on_corrupt must be 'fail' or 'skip'")
        self.store = store
        self.n_readers = max(1, int(n_readers))
        self.ram_budget_bytes = ram_budget_bytes
        if policy is None:
            from ..runner import RetryPolicy

            policy = RetryPolicy(max_attempts=3, base_delay_s=0.05,
                                 max_delay_s=2.0)
        self.policy = policy
        self.read_deadline_s = read_deadline_s
        self.hedge_after_s = hedge_after_s
        self.on_corrupt = on_corrupt
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.metrics = (metrics if metrics is not None
                        else telemetry.default_registry())
        self.chaos = chaos
        self.journal = self._as_journal(journal)
        self.poll_s = float(poll_s)
        #: floor for real-time waits on an executing worker (virtual
        #: time must NOT advance while we wait on real work — only
        #: deferred/chaos waits burn the clock); the wait itself is
        #: event-driven, so this is a clamp, not a polling quantum
        self._min_wait_s = 0.001
        self._max_wait_s = 60.0
        self.skipped: list[int] = []
        self._cv = threading.Condition()
        self._heap: list = []
        self._seq = itertools.count()
        self._threads: list[threading.Thread] = []
        self._stop = False
        self._reserved = 0
        self._lock = threading.Lock()

    @staticmethod
    def _as_journal(j):
        if j is None or hasattr(j, "write"):
            return j
        from ..runner import _Journal

        return _Journal(str(j))

    # -- lifecycle -----------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    def _ensure_workers(self) -> None:
        with self._cv:
            if self._stop:
                raise ValueError("scheduler is closed")
            while len(self._threads) < self.n_readers:
                t = threading.Thread(target=self._worker, daemon=True)
                t.start()
                self._threads.append(t)

    # -- RAM budget ----------------------------------------------------
    def _try_reserve(self, nbytes: int) -> bool:
        if self.ram_budget_bytes is None:
            return True
        with self._lock:
            if self._reserved + nbytes > self.ram_budget_bytes:
                return False
            self._reserved += nbytes
            return True

    def _discard(self, req: _PendingRead) -> None:
        """Release a request's budget reservation exactly once (only
        lookahead submissions hold one — forced/retry/hedge reads are
        progress-over-budget) and mark it abandoned so a worker that
        hasn't started it yet skips the read."""
        with req.lock:
            req.abandoned = True
            if req.released or not req.holds_budget:
                req.released = True
                return
            req.released = True
        if self.ram_budget_bytes is not None:
            with self._lock:
                self._reserved = max(
                    0, self._reserved - self.store.shard_nbytes_est())

    # -- worker side ---------------------------------------------------
    def _submit(self, shard: int, priority: int = 1,
                holds_budget: bool = False) -> _PendingRead:
        req = _PendingRead(shard, holds_budget=holds_budget)
        with self._cv:
            # (priority, shard, seq): hedges (priority 0) jump the
            # queue; otherwise ascending shard order across every
            # consumer = the elevator/locality order
            heapq.heappush(self._heap,
                           (priority, shard, next(self._seq), req))
            self._cv.notify()
        return req

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._heap and not self._stop:
                    self._cv.wait()
                if not self._heap:
                    return  # stopped and drained
                _, _, _, req = heapq.heappop(self._heap)
            if req.abandoned:
                req.done_evt.set()
                continue
            self._execute(req)

    def _execute(self, req: _PendingRead) -> None:
        t0 = self.clock.monotonic()
        slow = [0.0]

        def consult(c, name, path):
            if self.chaos is None:
                return
            f = self.chaos.on_io(name, path)
            if f is None:
                return
            if f["mode"] == "io_error":
                raise TransientDeviceError(
                    f"chaos: injected io_error reading {name} "
                    f"(shard {req.shard})")
            if f["mode"] == "slow_read":
                slow[0] += float(f["slow_s"])
            # truncate_shard: the monkey damaged the file; the
            # verified read rules it corrupt

        try:
            shard = self.store.read_shard(req.shard, on_chunk=consult)
            with req.lock:
                req.result = shard
                req.nbytes = (shard.indices.nbytes + shard.data.nbytes)
                req.ready_at = t0 + slow[0]
        except BaseException as e:  # noqa: BLE001 — delivered to the
            # consumer's ladder, which classifies and rules on it
            with req.lock:
                req.error = e
                req.ready_at = t0
        req.done_evt.set()

    # -- consumer side -------------------------------------------------
    def iter_shards(self, start_shard: int = 0):
        """One consumer stream: yields decoded shards in order from
        ``start_shard``, each read passing through the full IO ladder.
        Multiple concurrent ``iter_shards`` generators share the
        reader pool, the locality order and the RAM budget."""
        yield from self.iter_order(range(start_shard,
                                         self.store.n_shards))

    def iter_order(self, order):
        """Yield decoded shards in an EXPLICIT index order (each read
        through the full IO ladder, sharing the pool/budget exactly
        like :meth:`iter_shards`).  This is the epoch-randomness seam
        for the out-of-core trainer: hand it a permuted-BLOCK order
        (blocks shuffled, ascending within a block) and the lookahead
        window's in-flight reads are still served in ascending shard
        order by the elevator heap — randomness at epoch granularity,
        coalesced reads at disk granularity."""
        order = [int(i) for i in order]
        n = self.store.n_shards
        for i in order:
            if not 0 <= i < n:
                raise IndexError(
                    f"iter_order: shard {i} out of range "
                    f"[0, {n})")
        self._ensure_workers()
        est = self.store.shard_nbytes_est()
        window = max(1, min(8, (self.ram_budget_bytes // est)
                            if self.ram_budget_bytes else 2))
        pending: dict[int, _PendingRead] = {}
        next_submit = 0
        try:
            for pos in range(len(order)):
                while (next_submit < len(order)
                       and next_submit - pos < window):
                    if next_submit == pos:
                        reserved = False  # forced: progress > budget
                    elif self._try_reserve(est):
                        reserved = True
                    else:
                        break
                    pending[next_submit] = self._submit(
                        order[next_submit], holds_budget=reserved)
                    next_submit += 1
                shard = self._await_shard(order[pos],
                                          pending.pop(pos))
                if shard is _SKIPPED:
                    continue
                yield shard
        finally:
            for r in pending.values():
                self._discard(r)

    def _await_shard(self, i: int, primary: _PendingRead):
        t0 = self.clock.monotonic()
        attempt_t0 = t0
        rng = random.Random((self.policy.seed, "ingest", i).__repr__())
        attempt = 1
        retried = False
        hedged = False
        hedge: _PendingRead | None = None
        errors: list[BaseException] = []

        def resubmit():
            nonlocal attempt, retried, attempt_t0, primary, hedge
            attempt += 1
            retried = True
            self.metrics.counter("ingest.retries").inc()
            self.clock.sleep(self.policy.delay_s(attempt - 1, rng))
            attempt_t0 = self.clock.monotonic()
            primary = self._submit(i)
            hedge = None

        while True:
            served = err_req = None
            for r in (primary, hedge):
                if r is None:
                    continue
                st, val = r.peek(self.clock)
                if st == "ready":
                    served = (r, val)
                    break
                if st == "error" and err_req is None:
                    err_req = (r, val)
            if served is not None:
                r, shard = served
                outcome = ("hedged" if hedged
                           else "retried" if retried else "served")
                self.metrics.counter("ingest.reads",
                                     outcome=outcome).inc()
                self.metrics.counter("ingest.bytes").inc(r.nbytes)
                self.metrics.histogram("ingest.read_wait_s").observe(
                    self.clock.monotonic() - t0)
                for other in (primary, hedge):
                    if other is not None:
                        self._discard(other)
                return shard
            if err_req is not None:
                r, e = err_req
                errors.append(e)
                self._discard(r)
                if r is hedge:
                    hedge = None
                else:
                    primary = None
                if primary is not None or hedge is not None:
                    continue  # the twin read may still serve
                # both attempts down: rule on the failure
                corrupt = next((x for x in errors
                                if isinstance(x, ShardCorruptError)),
                               None)
                if corrupt is not None:
                    self._quarantine_ruling(i, corrupt)
                    if self.on_corrupt == "fail":
                        raise corrupt
                    self.skipped.append(i)
                    return _SKIPPED
                if (classify_error(e) == TRANSIENT
                        and attempt < self.policy.max_attempts):
                    resubmit()
                    continue
                raise e
            # nothing servable yet — hedge/deadline rulings, then wait
            el = self.clock.monotonic() - attempt_t0
            if (self.hedge_after_s is not None and not hedged
                    and primary is not None and el >= self.hedge_after_s):
                hedged = True
                self.metrics.counter("ingest.hedges").inc()
                hedge = self._submit(i, priority=0)
                continue
            if (self.read_deadline_s is not None
                    and el >= self.read_deadline_s):
                for r in (primary, hedge):
                    if r is not None:
                        self._discard(r)
                primary = hedge = None
                if attempt < self.policy.max_attempts:
                    resubmit()
                    continue
                raise TransientDeviceError(
                    f"ingest: shard {i} read exceeded its "
                    f"{self.read_deadline_s:g}s deadline "
                    f"{attempt} time(s) — abandoning the straggler")
            self._wait_step(primary, hedge, attempt_t0)

    def _wait_step(self, primary, hedge, attempt_t0) -> None:
        """Block until something can change: an EVENT-DRIVEN real wait
        on a worker still executing (virtual time must not race ahead
        of real work; the timeout only exists so clock-based rulings
        — hedge SLO, per-read deadline — get re-evaluated), or an
        injectable-clock sleep when every in-flight result is merely
        deferred (a chaos-slow read's virtual release time — the only
        wait that burns clock time, which a VirtualClock burns
        instantly)."""
        in_flight = [r for r in (primary, hedge)
                     if r is not None and not r.done_evt.is_set()]
        if in_flight:
            # wake exactly on completion; re-check early only when a
            # ruling could fire before then
            el = self.clock.monotonic() - attempt_t0
            waits = [self._max_wait_s]
            if self.hedge_after_s is not None and hedge is None:
                waits.append(self.hedge_after_s - el)
            if self.read_deadline_s is not None:
                waits.append(self.read_deadline_s - el)
            in_flight[0].done_evt.wait(max(min(waits),
                                           self._min_wait_s))
            return
        # every in-flight result is merely DEFERRED (chaos-slow):
        # sleep the clock straight to the next event — the earliest
        # virtual release time or the next hedge/deadline boundary —
        # in ONE sleep, not poll_s quanta (a 30s slow_read must not
        # spin 15000 consumer iterations)
        now = self.clock.monotonic()
        candidates = [r.ready_at - now for r in (primary, hedge)
                      if r is not None]
        if self.hedge_after_s is not None and hedge is None \
                and primary is not None:
            candidates.append(attempt_t0 + self.hedge_after_s - now)
        if self.read_deadline_s is not None:
            candidates.append(attempt_t0 + self.read_deadline_s - now)
        ahead = [c for c in candidates if c > 0.0]
        self.clock.sleep(min(ahead) if ahead else self.poll_s)

    def _quarantine_ruling(self, shard: int, e: ShardCorruptError):
        dest = self.store.quarantine_chunk(e.chunk, e.reason)
        self.metrics.counter("ingest.quarantines").inc()
        if self.journal is not None:
            self.journal.write("shard_quarantined", shard=shard,
                               chunk=e.chunk, path=dest or e.path,
                               reason=e.reason,
                               policy=self.on_corrupt)
