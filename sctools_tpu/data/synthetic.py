"""Synthetic single-cell count data for tests and benchmarks.

Generates negative-binomial-ish sparse count matrices with realistic
structure: per-gene mean rates drawn from a lognormal (a few highly
expressed genes, a long tail), per-cell library-size variation, and a
configurable fraction of mitochondrial genes (names prefixed "MT-") so
QC metrics have something to measure.  Cluster structure (for kNN /
clustering tests) comes from mixing ``n_clusters`` distinct gene-program
rate vectors.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

from .dataset import CellData


def synthetic_counts(
    n_cells: int,
    n_genes: int,
    *,
    density: float = 0.05,
    n_clusters: int = 1,
    mito_frac: float = 0.01,
    seed: int = 0,
    dtype=np.float32,
) -> CellData:
    """Host-side CellData with scipy CSR counts + gene names.

    ``density`` is the expected nnz fraction per cell.
    """
    rng = np.random.default_rng(seed)
    n_mito = max(1, int(n_genes * mito_frac)) if mito_frac > 0 else 0

    # Per-cluster gene programs: lognormal base rates, cluster-specific
    # multipliers on a random subset of genes.
    base = rng.lognormal(mean=0.0, sigma=1.5, size=n_genes)
    programs = np.tile(base, (n_clusters, 1))
    for c in range(1, n_clusters):
        boost = rng.choice(n_genes, size=max(1, n_genes // 20), replace=False)
        programs[c, boost] *= rng.uniform(3.0, 10.0, size=len(boost))
    programs /= programs.sum(axis=1, keepdims=True)

    labels = rng.integers(0, n_clusters, size=n_cells)
    lib = rng.lognormal(mean=0.0, sigma=0.4, size=n_cells)
    cdfs = np.cumsum(programs, axis=1)

    target_nnz = int(density * n_genes)
    rows, cols, vals = [], [], []
    # Vectorised generation in chunks to bound memory.
    chunk = max(1, min(n_cells, 200_000_000 // max(target_nnz, 1) // 8))
    for start in range(0, n_cells, chunk):
        stop = min(n_cells, start + chunk)
        m = stop - start
        nnz = np.maximum(
            1, rng.poisson(target_nnz * lib[start:stop])
        ).astype(np.int64)
        nnz = np.minimum(nnz, n_genes)
        total = int(nnz.sum())
        row_idx = np.repeat(np.arange(start, stop), nnz)
        # Sample gene ids per draw from the cell's cluster program.
        # The distribution depends only on the cluster, so one
        # vectorised searchsorted per cluster suffices — no
        # Python-level per-cell loop (10M cells would take hours).
        draw_cluster = labels[row_idx]
        u = rng.random(total)
        gene_idx = np.empty(total, dtype=np.int32)
        for c in range(n_clusters):
            sel = draw_cluster == c
            gene_idx[sel] = np.searchsorted(cdfs[c], u[sel])
        gene_idx = np.clip(gene_idx, 0, n_genes - 1)
        count = rng.geometric(0.4, size=total).astype(dtype)
        rows.append(row_idx)
        cols.append(gene_idx)
        vals.append(count)

    coo = sp.coo_matrix(
        (np.concatenate(vals), (np.concatenate(rows), np.concatenate(cols))),
        shape=(n_cells, n_genes),
    )
    coo.sum_duplicates()
    X = coo.tocsr()

    gene_names = np.array(
        [f"MT-{i}" if i < n_mito else f"GENE{i}" for i in range(n_genes)]
    )
    return CellData(
        X,
        obs={"cluster_true": labels.astype(np.int32)},
        var={"gene_name": gene_names,
             "mito": (np.arange(n_genes) < n_mito)},
    )


def synthetic_ell(
    n_cells: int,
    n_genes: int,
    *,
    nnz_per_cell: int = 600,
    n_clusters: int = 8,
    seed: int = 0,
    rows_padded: int | None = None,
    capacity: int | None = None,
    dtype=np.float32,
):
    """Benchmark-scale generator: writes padded-ELL arrays directly,
    skipping COO/CSR assembly entirely (no global sort; a 10M-cell
    matrix generates in minutes on one core).

    Duplicate gene ids within a cell are possible and harmless for
    linear ops (they act as summed counts).  Returns
    (SparseCells-ready dict: indices, data, n_cells, n_genes, labels).
    """
    from ..config import config, round_up

    rng = np.random.default_rng(seed)
    capacity = capacity or round_up(int(nnz_per_cell * 2), config.capacity_multiple)
    rows_padded = rows_padded or round_up(n_cells, config.sublane)

    base = rng.lognormal(mean=0.0, sigma=1.5, size=n_genes)
    programs = np.tile(base, (n_clusters, 1))
    for c in range(1, n_clusters):
        boost = rng.choice(n_genes, size=max(1, n_genes // 20), replace=False)
        programs[c, boost] *= rng.uniform(3.0, 10.0, size=len(boost))
    programs /= programs.sum(axis=1, keepdims=True)
    cdfs = np.cumsum(programs, axis=1)
    labels = rng.integers(0, n_clusters, size=n_cells).astype(np.int32)

    lib = rng.lognormal(mean=0.0, sigma=0.4, size=n_cells)
    nnz = np.clip(rng.poisson(nnz_per_cell * lib), 1, capacity).astype(np.int64)

    indices = np.full((rows_padded, capacity), n_genes, dtype=np.int32)
    data = np.zeros((rows_padded, capacity), dtype=dtype)
    total = int(nnz.sum())
    row_of = np.repeat(np.arange(n_cells), nnz)
    slot_of = np.arange(total) - np.repeat(np.cumsum(nnz) - nnz, nnz)
    u = rng.random(total)
    gene_idx = np.empty(total, dtype=np.int32)
    draw_cluster = labels[row_of]
    for c in range(n_clusters):
        sel = draw_cluster == c
        gene_idx[sel] = np.searchsorted(cdfs[c], u[sel])
    np.clip(gene_idx, 0, n_genes - 1, out=gene_idx)
    indices[row_of, slot_of] = gene_idx
    data[row_of, slot_of] = rng.geometric(0.4, size=total).astype(dtype)
    return dict(indices=indices, data=data, n_cells=n_cells,
                n_genes=n_genes, labels=labels)


def gaussian_blobs(
    n_points: int,
    dim: int,
    n_clusters: int = 5,
    *,
    spread: float = 0.2,
    seed: int = 0,
    dtype=np.float32,
):
    """Dense clustered points for kNN/kmeans tests.

    Returns (points (n, dim), labels (n,)).
    """
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_clusters, dim)).astype(dtype)
    labels = rng.integers(0, n_clusters, size=n_points)
    pts = centers[labels] + spread * rng.normal(size=(n_points, dim)).astype(dtype)
    return pts.astype(dtype), labels.astype(np.int32)
