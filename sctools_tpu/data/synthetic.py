"""Synthetic single-cell count data for tests and benchmarks.

Generates negative-binomial-ish sparse count matrices with realistic
structure: per-gene mean rates drawn from a lognormal (a few highly
expressed genes, a long tail), per-cell library-size variation, and a
configurable fraction of mitochondrial genes (names prefixed "MT-") so
QC metrics have something to measure.  Cluster structure (for kNN /
clustering tests) comes from mixing ``n_clusters`` distinct gene-program
rate vectors.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

from .dataset import CellData


def synthetic_counts(
    n_cells: int,
    n_genes: int,
    *,
    density: float = 0.05,
    n_clusters: int = 1,
    mito_frac: float = 0.01,
    seed: int = 0,
    dtype=np.float32,
) -> CellData:
    """Host-side CellData with scipy CSR counts + gene names.

    ``density`` is the expected nnz fraction per cell.
    """
    rng = np.random.default_rng(seed)
    n_mito = max(1, int(n_genes * mito_frac)) if mito_frac > 0 else 0

    # Per-cluster gene programs: lognormal base rates, cluster-specific
    # multipliers on a random subset of genes.
    base = rng.lognormal(mean=0.0, sigma=1.5, size=n_genes)
    programs = np.tile(base, (n_clusters, 1))
    for c in range(1, n_clusters):
        boost = rng.choice(n_genes, size=max(1, n_genes // 20), replace=False)
        programs[c, boost] *= rng.uniform(3.0, 10.0, size=len(boost))
    programs /= programs.sum(axis=1, keepdims=True)

    labels = rng.integers(0, n_clusters, size=n_cells)
    lib = rng.lognormal(mean=0.0, sigma=0.4, size=n_cells)

    target_nnz = int(density * n_genes)
    rows, cols, vals = [], [], []
    # Vectorised generation in chunks to bound memory.
    chunk = max(1, min(n_cells, 200_000_000 // max(target_nnz, 1) // 8))
    for start in range(0, n_cells, chunk):
        stop = min(n_cells, start + chunk)
        m = stop - start
        nnz = np.maximum(
            1, rng.poisson(target_nnz * lib[start:stop])
        ).astype(np.int64)
        nnz = np.minimum(nnz, n_genes)
        total = int(nnz.sum())
        row_idx = np.repeat(np.arange(start, stop), nnz)
        # Sample gene ids per cell from its cluster's program, with ONE
        # flat searchsorted: each row's cdf lives in [0,1], so shifting
        # row r's cdf (and its uniforms) by 2r keeps rows sorted and
        # disjoint in a single global array — no Python-level per-cell
        # loop (10M cells would take hours otherwise).
        p = programs[labels[start:stop]]  # (m, n_genes)
        cdf = np.cumsum(p, axis=1)
        local_row = np.repeat(np.arange(m), nnz)
        flat_cdf = (cdf + 2.0 * np.arange(m)[:, None]).ravel()
        u = rng.random(total) + 2.0 * local_row
        gene_idx = (np.searchsorted(flat_cdf, u) - local_row * n_genes).astype(
            np.int32
        )
        gene_idx = np.clip(gene_idx, 0, n_genes - 1)
        count = rng.geometric(0.4, size=total).astype(dtype)
        rows.append(row_idx)
        cols.append(gene_idx)
        vals.append(count)

    coo = sp.coo_matrix(
        (np.concatenate(vals), (np.concatenate(rows), np.concatenate(cols))),
        shape=(n_cells, n_genes),
    )
    coo.sum_duplicates()
    X = coo.tocsr()

    gene_names = np.array(
        [f"MT-{i}" if i < n_mito else f"GENE{i}" for i in range(n_genes)]
    )
    return CellData(
        X,
        obs={"cluster_true": labels.astype(np.int32)},
        var={"gene_name": gene_names,
             "mito": (np.arange(n_genes) < n_mito)},
    )


def gaussian_blobs(
    n_points: int,
    dim: int,
    n_clusters: int = 5,
    *,
    spread: float = 0.2,
    seed: int = 0,
    dtype=np.float32,
):
    """Dense clustered points for kNN/kmeans tests.

    Returns (points (n, dim), labels (n,)).
    """
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_clusters, dim)).astype(dtype)
    labels = rng.integers(0, n_clusters, size=n_points)
    pts = centers[labels] + spread * rng.normal(size=(n_points, dim)).astype(dtype)
    return pts.astype(dtype), labels.astype(np.int32)
