"""Synthetic single-cell count data for tests and benchmarks.

Generates negative-binomial-ish sparse count matrices with realistic
structure: per-gene mean rates drawn from a lognormal (a few highly
expressed genes, a long tail), per-cell library-size variation, and a
configurable fraction of mitochondrial genes (names prefixed "MT-") so
QC metrics have something to measure.  Cluster structure (for kNN /
clustering tests) comes from mixing ``n_clusters`` distinct gene-program
rate vectors.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from .dataset import CellData


def synthetic_counts(
    n_cells: int,
    n_genes: int,
    *,
    density: float = 0.05,
    n_clusters: int = 1,
    mito_frac: float = 0.01,
    seed: int = 0,
    dtype=np.float32,
) -> CellData:
    """Host-side CellData with scipy CSR counts + gene names.

    ``density`` is the expected nnz fraction per cell.
    """
    rng = np.random.default_rng(seed)
    n_mito = max(1, int(n_genes * mito_frac)) if mito_frac > 0 else 0

    # Per-cluster gene programs: lognormal base rates, cluster-specific
    # multipliers on a random subset of genes.
    base = rng.lognormal(mean=0.0, sigma=1.5, size=n_genes)
    programs = np.tile(base, (n_clusters, 1))
    for c in range(1, n_clusters):
        boost = rng.choice(n_genes, size=max(1, n_genes // 20), replace=False)
        programs[c, boost] *= rng.uniform(3.0, 10.0, size=len(boost))
    programs /= programs.sum(axis=1, keepdims=True)

    labels = rng.integers(0, n_clusters, size=n_cells)
    lib = rng.lognormal(mean=0.0, sigma=0.4, size=n_cells)
    cdfs = np.cumsum(programs, axis=1)

    target_nnz = int(density * n_genes)
    rows, cols, vals = [], [], []
    # Vectorised generation in chunks to bound memory.
    chunk = max(1, min(n_cells, 200_000_000 // max(target_nnz, 1) // 8))
    for start in range(0, n_cells, chunk):
        stop = min(n_cells, start + chunk)
        m = stop - start
        nnz = np.maximum(
            1, rng.poisson(target_nnz * lib[start:stop])
        ).astype(np.int64)
        nnz = np.minimum(nnz, n_genes)
        total = int(nnz.sum())
        row_idx = np.repeat(np.arange(start, stop), nnz)
        # Sample gene ids per draw from the cell's cluster program.
        # The distribution depends only on the cluster, so one
        # vectorised searchsorted per cluster suffices — no
        # Python-level per-cell loop (10M cells would take hours).
        draw_cluster = labels[row_idx]
        u = rng.random(total)
        gene_idx = np.empty(total, dtype=np.int32)
        for c in range(n_clusters):
            sel = draw_cluster == c
            gene_idx[sel] = np.searchsorted(cdfs[c], u[sel])
        gene_idx = np.clip(gene_idx, 0, n_genes - 1)
        count = rng.geometric(0.4, size=total).astype(dtype)
        rows.append(row_idx)
        cols.append(gene_idx)
        vals.append(count)

    coo = sp.coo_matrix(
        (np.concatenate(vals), (np.concatenate(rows), np.concatenate(cols))),
        shape=(n_cells, n_genes),
    )
    coo.sum_duplicates()
    X = coo.tocsr()

    gene_names = np.array(
        [f"MT-{i}" if i < n_mito else f"GENE{i}" for i in range(n_genes)]
    )
    return CellData(
        X,
        obs={"cluster_true": labels.astype(np.int32)},
        var={"gene_name": gene_names,
             "mito": (np.arange(n_genes) < n_mito)},
    )


def synthetic_ell(
    n_cells: int,
    n_genes: int,
    *,
    nnz_per_cell: int = 600,
    n_clusters: int = 8,
    seed: int = 0,
    rows_padded: int | None = None,
    capacity: int | None = None,
    dtype=np.float32,
):
    """Benchmark-scale generator: writes padded-ELL arrays directly,
    skipping COO/CSR assembly entirely (no global sort; a 10M-cell
    matrix generates in minutes on one core).

    Duplicate gene ids within a cell are possible and harmless for
    linear ops (they act as summed counts).  Returns
    (SparseCells-ready dict: indices, data, n_cells, n_genes, labels).
    """
    from ..config import config, round_up

    rng = np.random.default_rng(seed)
    capacity = capacity or round_up(int(nnz_per_cell * 2), config.capacity_multiple)
    rows_padded = rows_padded or round_up(n_cells, config.sublane)

    base = rng.lognormal(mean=0.0, sigma=1.5, size=n_genes)
    programs = np.tile(base, (n_clusters, 1))
    for c in range(1, n_clusters):
        boost = rng.choice(n_genes, size=max(1, n_genes // 20), replace=False)
        programs[c, boost] *= rng.uniform(3.0, 10.0, size=len(boost))
    programs /= programs.sum(axis=1, keepdims=True)
    cdfs = np.cumsum(programs, axis=1)
    labels = rng.integers(0, n_clusters, size=n_cells).astype(np.int32)

    lib = rng.lognormal(mean=0.0, sigma=0.4, size=n_cells)
    nnz = np.clip(rng.poisson(nnz_per_cell * lib), 1, capacity).astype(np.int64)

    indices = np.full((rows_padded, capacity), n_genes, dtype=np.int32)
    data = np.zeros((rows_padded, capacity), dtype=dtype)
    total = int(nnz.sum())
    row_of = np.repeat(np.arange(n_cells), nnz)
    slot_of = np.arange(total) - np.repeat(np.cumsum(nnz) - nnz, nnz)
    u = rng.random(total)
    gene_idx = np.empty(total, dtype=np.int32)
    draw_cluster = labels[row_of]
    for c in range(n_clusters):
        sel = draw_cluster == c
        gene_idx[sel] = np.searchsorted(cdfs[c], u[sel])
    np.clip(gene_idx, 0, n_genes - 1, out=gene_idx)
    indices[row_of, slot_of] = gene_idx
    data[row_of, slot_of] = rng.geometric(0.4, size=total).astype(dtype)
    return dict(indices=indices, data=data, n_cells=n_cells,
                n_genes=n_genes, labels=labels)


def _cluster_cdfs(n_genes: int, n_clusters: int, seed: int) -> np.ndarray:
    """Per-cluster gene-program CDFs (host, tiny): lognormal base rates
    with cluster-specific boosts — same structure as synthetic_ell."""
    rng = np.random.default_rng(seed)
    base = rng.lognormal(mean=0.0, sigma=1.5, size=n_genes)
    programs = np.tile(base, (n_clusters, 1))
    for c in range(1, n_clusters):
        boost = rng.choice(n_genes, size=max(1, n_genes // 20),
                           replace=False)
        programs[c, boost] *= rng.uniform(3.0, 10.0, size=len(boost))
    programs /= programs.sum(axis=1, keepdims=True)
    return np.cumsum(programs, axis=1).astype(np.float32)


def ell_shard_device(key, cdfs, n_valid, *, rows: int, capacity: int,
                     n_genes: int):
    """Generate one padded-ELL shard ON DEVICE (no host RAM, no
    host→device transfer — essential on bench hosts with one CPU core
    and a tunneled TPU).

    Gene ids are drawn with replacement, then duplicate slots within a
    row are MERGED on device (sort + run-total + sentinel the rest):
    duplicates are harmless for linear ops (X@V sums slot
    contributions either way) but the streaming pipeline applies
    log1p PER SLOT, and log1p(a)+log1p(b) != log1p(a+b) — unmerged
    duplicates made the device-generated "matrix" disagree with its
    own CSR export wherever a nonlinear op ran (r4 session-2 finding:
    streamed HVG moments off by 2x on hot genes).
    Rows >= ``n_valid`` are zeroed/sentineled padding.
    Counts are geometric(p=0.4); gene ids are inverse-CDF draws from
    the row's cluster program.

    Returns (indices (rows, capacity) int32, data (rows, capacity) f32,
    labels (rows,) int32).

    Generation runs as fixed-quantum row chunks (``config.gen_chunk_rows``
    per jitted program, key folded per chunk): the single full-shard
    program at 131072x28672x512 deterministically crashed the tunneled
    TPU worker ("kernel fault", round-5 live window) while smaller
    programs ran.  Output is deterministic in (key, quantum); the
    quantum is a config constant precisely so re-iteration regenerates
    identical shards.
    """
    from ..config import config

    chunk = max(8, min(int(config.gen_chunk_rows), rows))
    n_valid = int(n_valid)
    if chunk >= rows:
        return _ell_shard_device_jit(key, cdfs, jnp.asarray(n_valid),
                                     rows=rows, capacity=capacity,
                                     n_genes=n_genes)
    outs = []
    for ci, start in enumerate(range(0, rows, chunk)):
        crows = min(chunk, rows - start)
        cvalid = max(0, min(n_valid - start, crows))
        outs.append(_ell_shard_device_jit(
            jax.random.fold_in(key, ci), cdfs, jnp.asarray(cvalid),
            rows=crows, capacity=capacity, n_genes=n_genes))
    idx = jnp.concatenate([o[0] for o in outs], axis=0)
    vals = jnp.concatenate([o[1] for o in outs], axis=0)
    labels = jnp.concatenate([o[2] for o in outs], axis=0)
    return idx, vals, labels


@partial(jax.jit, static_argnames=("rows", "capacity", "n_genes"))
def _ell_shard_device_jit(key, cdfs, n_valid, *, rows, capacity, n_genes):
    n_clusters = cdfs.shape[0]
    ku, kv, kl = jax.random.split(key, 3)
    labels = jax.random.randint(kl, (rows,), 0, n_clusters)
    u = jax.random.uniform(ku, (rows, capacity), jnp.float32)
    # ONE searchsorted over the offset-concatenated cdfs instead of a
    # per-cluster unroll: shifting cluster c's cdf (values in [0,1])
    # into [c, c+1) keeps the concatenation sorted, and querying
    # u + label lands each draw in its own cluster's segment.  The
    # unrolled form cost 8x the search work and was measured as 97%
    # of the generator chunk's wall on a v5e (8.59 s of 8.88 s at
    # 16384x512; the flat form runs 1.12 s).  The f32 quantization of
    # (u + c) can flip ~0.4% of draws to an adjacent gene at a cdf
    # bin boundary — a <=5e-7 probability-mass shift, irrelevant for
    # synthetic fixtures; determinism in (key, quantum) is unchanged.
    flat = (cdfs
            + jnp.arange(n_clusters, dtype=cdfs.dtype)[:, None]
            ).reshape(-1)
    q = u + labels[:, None].astype(jnp.float32)
    idx = (jnp.searchsorted(flat, q).astype(jnp.int32)
           - labels[:, None] * n_genes)
    idx = jnp.clip(idx, 0, n_genes - 1)
    uv = jax.random.uniform(kv, (rows, capacity), jnp.float32,
                            minval=1e-7, maxval=1.0)
    vals = jnp.ceil(jnp.log1p(-uv * (1 - 1e-7)) /
                    float(np.log(1.0 - 0.4))).astype(jnp.float32)
    vals = jnp.maximum(vals, 1.0)
    row_ok = jnp.arange(rows) < n_valid
    idx = jnp.where(row_ok[:, None], idx, n_genes)
    vals = jnp.where(row_ok[:, None], vals, 0.0)
    # merge duplicate gene ids within each row (see docstring): sort
    # slots by gene, sum each run into its first slot, sentinel the
    # rest.  Scatter-free: run totals come from the row cumsum gathered
    # at each run's last slot (a scatter-based vmapped segment_sum was
    # a prime suspect in the tunnel worker "kernel fault" crashes).
    # Counts are small integers and the row cumsum stays < 2^24, so
    # the f32 differences are exact.
    order = jnp.argsort(idx, axis=1)
    si = jnp.take_along_axis(idx, order, axis=1)
    sv = jnp.take_along_axis(vals, order, axis=1)
    first = jnp.concatenate(
        [jnp.ones((rows, 1), bool), si[:, 1:] != si[:, :-1]], axis=1)
    csum = jnp.cumsum(sv, axis=1)
    pos = jnp.broadcast_to(jnp.arange(capacity, dtype=jnp.int32),
                           (rows, capacity))
    # index of the next run's first slot (capacity when none), then the
    # last slot of THIS run = next_first - 1
    nf = jax.lax.cummin(jnp.where(first, pos, capacity), axis=1,
                        reverse=True)
    nf_after = jnp.concatenate(
        [nf[:, 1:], jnp.full((rows, 1), capacity, jnp.int32)], axis=1)
    last = nf_after - 1
    totals = jnp.take_along_axis(csum, last, axis=1) - csum + sv
    idx = jnp.where(first, si, n_genes)
    vals = jnp.where(first & (idx < n_genes), totals, 0.0)
    return idx, vals, labels


class DeviceSyntheticSource:
    """ShardSource-compatible source of device-generated synthetic
    shards (see data/stream.py for the consumer protocol: iterating
    yields ``(row_offset, SparseCells)`` with uniform shard shapes).

    ``materialize=True`` generates every shard once and keeps it in
    HBM (fastest for multi-pass algorithms like streaming PCA when the
    matrix fits); ``False`` regenerates each shard deterministically
    from the per-shard key on every pass — zero steady-state HBM
    beyond the shard being processed, mimicking an IO-backed stream.
    """

    def __init__(self, n_cells: int, n_genes: int, *, capacity: int = 512,
                 shard_rows: int = 131072, n_clusters: int = 8,
                 seed: int = 0, materialize: bool = True):
        from ..config import config, round_up

        self.n_cells = int(n_cells)
        self.n_genes = int(n_genes)
        self.capacity = round_up(capacity, config.capacity_multiple)
        self.shard_rows = min(round_up(shard_rows, config.sublane),
                              round_up(self.n_cells, config.sublane))
        self.seed = seed
        self._cdfs = None  # device cdfs, built lazily
        self._n_clusters = n_clusters
        self._shards = None
        if materialize:
            self.materialize()

    def materialize(self, progress=None) -> None:
        """Generate and retain every shard in HBM, BLOCKING on each
        before generating the next (one in-flight generation at a
        time — the benchmarked axon tunnel wedges under deep async
        pipelines of large programs, and a blind ``list(gen)`` gave
        round 3 no way to tell which shard killed the worker).
        ``progress(i, seconds)`` is called per shard."""
        import time as _time

        from ..utils.sync import hard_sync

        shards = []
        for i, shard in enumerate(self._generate()):
            t0 = _time.time()
            # hard_sync, not block_until_ready: the tunnel returns from
            # block_until_ready before the program has run (utils/sync.py)
            hard_sync(shard.data)
            if progress is not None:
                progress(i, _time.time() - t0)
            shards.append(shard)
        self._shards = shards

    def _gen_cdfs(self):
        if self._cdfs is None:
            import jax as _jax

            self._cdfs = _jax.device_put(
                _cluster_cdfs(self.n_genes, self._n_clusters, self.seed))
        return self._cdfs

    def _generate(self, start_shard: int = 0):
        import jax as _jax

        from .sparse import SparseCells

        cdfs = self._gen_cdfs()
        base = _jax.random.PRNGKey(self.seed)
        starts = range(start_shard * self.shard_rows, self.n_cells,
                       self.shard_rows)
        for si, start in enumerate(starts, start=start_shard):
            n_valid = min(self.shard_rows, self.n_cells - start)
            idx, dat, _ = ell_shard_device(
                _jax.random.fold_in(base, si), cdfs, n_valid,
                rows=self.shard_rows, capacity=self.capacity,
                n_genes=self.n_genes)
            yield SparseCells(idx, dat, n_valid, self.n_genes)

    def __iter__(self):
        yield from self.iter_from(0)

    def iter_from(self, start_shard: int):
        """Source-protocol resume hook (see ShardSource.iter_from):
        materialized shards are sliced; regenerating sources skip the
        per-shard keys below ``start_shard`` without generating."""
        offset = start_shard * self.shard_rows
        if self._shards is not None:
            shards = self._shards[start_shard:]
        else:
            shards = self._generate(start_shard=start_shard)
        for shard in shards:
            yield offset, shard
            offset += shard.n_cells

    @property
    def n_shards(self) -> int:
        return -(-self.n_cells // self.shard_rows)


def gaussian_blobs(
    n_points: int,
    dim: int,
    n_clusters: int = 5,
    *,
    spread: float = 0.2,
    seed: int = 0,
    dtype=np.float32,
):
    """Dense clustered points for kNN/kmeans tests.

    Returns (points (n, dim), labels (n,)).
    """
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_clusters, dim)).astype(dtype)
    labels = rng.integers(0, n_clusters, size=n_points)
    pts = centers[labels] + spread * rng.normal(size=(n_points, dim)).astype(dtype)
    return pts.astype(dtype), labels.astype(np.int32)
