"""Device-resident sparse count matrices, TPU-first.

The reference (dpeerlab/sctools; source unavailable — SURVEY.md §0)
stores counts as AnnData CSR shards and the north star asks for
"device-resident BCOO blocks".  A literal BCOO (coordinate list) is a
poor fit for the TPU: every op would become a gather/scatter over an
unpredictable index stream, which XLA cannot tile onto the VPU/MXU.

Instead we use a **padded-ELL** layout, the TPU-native equivalent:

    indices : (rows_padded, capacity) int32  — gene ids, row-major
    data    : (rows_padded, capacity) float32 — counts

Each cell's nonzeros occupy the leading slots of its row; the rest of
the row is padding (``index == n_genes`` sentinel, ``value == 0``).
``capacity`` is the max nnz/row rounded up to a lane multiple (128) and
``rows_padded`` rounds up to a sublane/sharding multiple.  Benefits:

* **static shapes** — one XLA compilation for any batch of shards;
* per-cell reductions (library size, QC, normalisation) are dense
  vectorised ops over the rows — pure VPU work, no scatter;
* ``X @ V`` (PCA matvec) is a gather of V rows + an einsum — and V is
  small enough to live in VMEM;
* ``Xᵀ @ W`` / per-gene stats are a single ``segment_sum`` over the
  flattened slot array;
* rows shard cleanly across a device mesh for multi-chip pipelines.

Interop: ``from_scipy_csr``/``to_scipy_csr`` round-trip exactly, and
``to_bcoo`` produces a ``jax.experimental.sparse.BCOO`` for users who
want the stock JAX sparse type.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..config import config, round_up


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class SparseCells:
    """Padded-ELL sparse matrix of shape ``(n_cells, n_genes)``.

    ``indices``/``data`` may be numpy (host) or jax (device) arrays;
    ``device_put`` moves them.  Padding slots have ``indices ==
    n_genes`` (one-past-the-end sentinel) and ``data == 0`` — so a
    gather from a ``(n_genes+1, d)`` table whose final row is zero
    silently annihilates padding, and ``segment_sum`` with
    ``num_segments == n_genes + 1`` accumulates padding into a bin that
    is then dropped.
    """

    indices: jax.Array  # (rows_padded, capacity) int32
    data: jax.Array  # (rows_padded, capacity) float
    n_cells: int  # static
    n_genes: int  # static

    # -- pytree protocol (n_cells/n_genes are static aux data) --------
    def tree_flatten(self):
        return (self.indices, self.data), (self.n_cells, self.n_genes)

    @classmethod
    def tree_unflatten(cls, aux, children):
        indices, data = children
        return cls(indices, data, *aux)

    # -- basic properties ---------------------------------------------
    @property
    def shape(self):
        return (self.n_cells, self.n_genes)

    @property
    def rows_padded(self) -> int:
        return self.indices.shape[0]

    @property
    def capacity(self) -> int:
        return self.indices.shape[1]

    @property
    def sentinel(self) -> int:
        return self.n_genes

    def valid_mask(self) -> jax.Array:
        """(rows_padded, capacity) bool — True at real nonzero slots."""
        return self.indices != self.n_genes

    def row_mask(self) -> jax.Array:
        """(rows_padded,) bool — True for real (non-padding) cells."""
        return jnp.arange(self.rows_padded) < self.n_cells

    def with_data(self, data: jax.Array) -> "SparseCells":
        """Same sparsity pattern, new values (functional update)."""
        return SparseCells(self.indices, data, self.n_cells, self.n_genes)

    def nnz_per_row(self) -> jax.Array:
        return jnp.sum(self.valid_mask(), axis=1, dtype=jnp.int32)

    def device_put(self, sharding=None) -> "SparseCells":
        ind = jax.device_put(jnp.asarray(self.indices), sharding)
        dat = jax.device_put(jnp.asarray(self.data), sharding)
        return SparseCells(ind, dat, self.n_cells, self.n_genes)

    # -- conversions ---------------------------------------------------
    @classmethod
    def from_scipy_csr(
        cls,
        csr,
        capacity: int | None = None,
        rows_multiple: int | None = None,
        dtype=None,
    ) -> "SparseCells":
        """Pack a ``scipy.sparse.csr_matrix`` into padded-ELL.

        Uses the native C++ packer when available (csrc/scio.cpp),
        falling back to a vectorised numpy pack.
        """
        import scipy.sparse as sp

        if not sp.issparse(csr):
            raise TypeError(f"expected scipy sparse matrix, got {type(csr)}")
        csr = csr.tocsr()
        csr.sort_indices()
        n_cells, n_genes = csr.shape
        dtype = dtype or config.dtype
        nnz = np.diff(csr.indptr)
        max_nnz = int(nnz.max()) if len(nnz) else 0
        if capacity is None:
            capacity = max(round_up(max(max_nnz, 1), config.capacity_multiple),
                           config.capacity_multiple)
        elif max_nnz > capacity:
            raise ValueError(
                f"capacity={capacity} < max nnz/row={max_nnz}; "
                "refusing to drop counts"
            )
        rows_multiple = rows_multiple or config.sublane
        rows_padded = round_up(max(n_cells, 1), rows_multiple)

        from ..native import pack_ell  # numpy fallback inside

        indices, data = pack_ell(
            csr.indptr.astype(np.int64),
            csr.indices.astype(np.int32),
            csr.data.astype(dtype),
            rows_padded,
            capacity,
            sentinel=n_genes,
        )
        return cls(indices, data, n_cells, n_genes)

    def to_scipy_csr(self):
        import scipy.sparse as sp

        ind = np.asarray(self.indices)
        dat = np.asarray(self.data)
        mask = ind != self.n_genes
        nnz = mask.sum(axis=1)[: self.n_cells]
        indptr = np.zeros(self.n_cells + 1, dtype=np.int64)
        np.cumsum(nnz, out=indptr[1:])
        rows = np.repeat(np.arange(self.rows_padded), mask.sum(axis=1))
        keep = rows < self.n_cells
        return sp.csr_matrix(
            (dat[mask][keep], ind[mask][keep], indptr),
            shape=(self.n_cells, self.n_genes),
        )

    def to_bcoo(self):
        """Stock ``jax.experimental.sparse.BCOO`` view (padding kept as
        explicit zeros at column ``n_genes - 1`` is avoided by clamping
        then relying on zero data)."""
        from jax.experimental import sparse as jsparse

        rows = jnp.broadcast_to(
            jnp.arange(self.rows_padded)[:, None], self.indices.shape
        )
        cols = jnp.minimum(self.indices, self.n_genes - 1)
        idx = jnp.stack([rows.ravel(), cols.ravel()], axis=1)
        return jsparse.BCOO(
            (self.data.ravel(), idx), shape=(self.rows_padded, self.n_genes)
        )[: self.n_cells]

    def to_dense(self) -> jax.Array:
        """Densify (small matrices / tests only)."""
        table = jnp.zeros((self.rows_padded, self.n_genes + 1), self.data.dtype)
        table = jax.vmap(lambda t, i, d: t.at[i].add(d))(
            table, self.indices, self.data
        )
        return table[: self.n_cells, : self.n_genes]

    def pad_rows_to(self, rows_padded: int) -> "SparseCells":
        if rows_padded < self.rows_padded:
            raise ValueError("cannot shrink row padding below current")
        if rows_padded == self.rows_padded:
            return self
        extra = rows_padded - self.rows_padded
        ind = jnp.concatenate(
            [jnp.asarray(self.indices),
             jnp.full((extra, self.capacity), self.sentinel, jnp.int32)]
        )
        dat = jnp.concatenate(
            [jnp.asarray(self.data),
             jnp.zeros((extra, self.capacity), self.data.dtype)]
        )
        return SparseCells(ind, dat, self.n_cells, self.n_genes)

    def __repr__(self):
        return (
            f"SparseCells(shape=({self.n_cells}, {self.n_genes}), "
            f"padded={self.rows_padded}x{self.capacity}, "
            f"dtype={self.data.dtype})"
        )


# ----------------------------------------------------------------------
# Core sparse linear algebra primitives (jittable).
#
# Everything that expands a (rows, capacity) slot array by a feature
# dimension d is CHUNKED over row blocks with a lax.scan/lax.map:
# materialising (rows, capacity, d) at atlas scale is tens of GB, while
# one (block, capacity, d) tile stays ~100 MB and the scan carry for
# gene-axis reductions is only (n_genes+1, d).  These ops are
# bandwidth-bound, so sequential blocks cost nothing.
# ----------------------------------------------------------------------

_ROW_CHUNK = 2048


def _blocked_pair(x: "SparseCells", block: int):
    """Block indices/data with proper padding (sentinel idx, zero val)."""
    R, C = x.indices.shape
    nb = (R + block - 1) // block
    pad = nb * block - R
    ind, dat = x.indices, x.data
    if pad:
        ind = jnp.concatenate(
            [ind, jnp.full((pad, C), x.sentinel, ind.dtype)])
        dat = jnp.concatenate([dat, jnp.zeros((pad, C), dat.dtype)])
    return ind.reshape(nb, block, C), dat.reshape(nb, block, C), nb, pad


def segment_reduce(x: "SparseCells", slot_values_fn, d: int,
                   dtype=None, block: int = _ROW_CHUNK) -> jax.Array:
    """Generic gene-axis reduction: accumulates
    ``segment_sum(slot_values_fn(ind_blk, dat_blk, row_offset))`` over
    row blocks into a (n_genes, d) result.

    ``slot_values_fn(ind, dat, row_offset) -> (block, capacity, d)``.
    """
    dtype = dtype or x.data.dtype
    ind_b, dat_b, nb, _ = _blocked_pair(x, block)
    G1 = x.n_genes + 1

    def body(acc, inp):
        i, (ind, dat) = inp
        vals = slot_values_fn(ind, dat, i * block)  # (block, C, d)
        acc = acc + jax.ops.segment_sum(
            vals.reshape(-1, d), ind.ravel(), num_segments=G1
        )
        return acc, None

    acc0 = jnp.zeros((G1, d), dtype)
    acc, _ = jax.lax.scan(
        body, acc0, (jnp.arange(nb), (ind_b, dat_b)))
    return acc[: x.n_genes]


@partial(jax.jit, static_argnames=("precision", "block"))
def spmm(x: SparseCells, v: jax.Array, precision=None,
         block: int = _ROW_CHUNK) -> jax.Array:
    """``X @ V`` for padded-ELL ``X`` and dense ``V`` of shape (G, d).

    TPU mapping: per row-block, gather V rows (V padded with a zero
    row so sentinel indices vanish) and contract slots — VPU-bound
    with V resident in VMEM for typical d ≤ 512.

    Dtype policy: with ``precision=None`` the contraction follows
    ``config.matmul_dtype`` — bfloat16 inputs with float32
    accumulation when the policy says bf16, true float32
    (Precision.HIGHEST — on TPU, f32 inputs at DEFAULT silently run
    bf16 MXU passes) otherwise.  The policy is captured at TRACE time:
    flip ``config.matmul_dtype`` before the first call of a given
    shape, not between calls (same caveat as every jitted
    config-resolved knob; the bench sets it right after acquire).
    Output is always float32.
    """
    if precision is None:
        use_bf16 = jnp.dtype(config.matmul_dtype) == jnp.bfloat16
        precision = (jax.lax.Precision.DEFAULT if use_bf16
                     else jax.lax.Precision.HIGHEST)
        in_dtype = jnp.bfloat16 if use_bf16 else jnp.float32
    else:
        in_dtype = v.dtype
    vp = jnp.concatenate([v, jnp.zeros((1, v.shape[1]), v.dtype)], axis=0)
    vp = vp.astype(in_dtype)
    ind_b, dat_b, nb, pad = _blocked_pair(x, block)

    def per_block(args):
        ind, dat = args
        gathered = jnp.take(vp, ind, axis=0)  # (block, C, d)
        return jnp.einsum("rc,rcd->rd", dat.astype(in_dtype), gathered,
                          precision=precision,
                          preferred_element_type=jnp.float32)

    out = jax.lax.map(per_block, (ind_b, dat_b))  # (nb, block, d)
    out = out.reshape(nb * block, v.shape[1])
    return out[: x.rows_padded].astype(jnp.float32)


@partial(jax.jit, static_argnames=("block",))
def spmm_t(x: SparseCells, w: jax.Array, block: int = _ROW_CHUNK) -> jax.Array:
    """``Xᵀ @ W`` for dense ``W`` of shape (rows_padded, d) → (G, d).

    Padding rows of W must be zero, or use ``x.row_mask()`` upstream.
    Chunked segment-sum; the sentinel bin (index G) is dropped.
    """
    d = w.shape[-1]
    # dynamic_slice needs in-range offsets: pad w to the blocked size.
    pad = (-x.rows_padded) % block
    wp = jnp.concatenate([w, jnp.zeros((pad, d), w.dtype)]) if pad else w

    def slot_vals(ind, dat, row_offset):
        wblk = jax.lax.dynamic_slice_in_dim(wp, row_offset, ind.shape[0])
        return dat[:, :, None] * wblk[:, None, :]

    return segment_reduce(x, slot_vals, d, dtype=w.dtype, block=block)


@jax.jit
def row_sum(x: SparseCells) -> jax.Array:
    """Per-cell total counts, (rows_padded,)."""
    return jnp.sum(x.data, axis=1)


@jax.jit
def gene_sum(x: SparseCells) -> jax.Array:
    """Per-gene total counts, (n_genes,)."""
    return gene_stats(x)[0]


@jax.jit
def gene_stats(x: SparseCells) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-gene (sum, sum of squares, nnz count) across *valid* cells.

    One fused chunked pass: three segment-sums over the same index
    stream.  Padding rows contribute zeros (their data is zero) except
    for the nnz count, which masks explicitly.

    NOTE: deriving a variance as ``ss − n·mean²`` from these f32 sums
    cancels catastrophically when ``mean² ≫ var`` — use
    :func:`gene_moments` for variances.
    """
    n_cells = x.n_cells

    def slot_vals(ind, dat, row_offset):
        rows = row_offset + jnp.arange(ind.shape[0])
        valid = (ind != x.sentinel) & (rows < n_cells)[:, None]
        return jnp.stack([dat, dat * dat, valid.astype(dat.dtype)], axis=2)

    out = segment_reduce(x, slot_vals, 3)
    return out[:, 0], out[:, 1], out[:, 2]


@jax.jit
def gene_moments(x: SparseCells, n_valid=None
                 ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-gene (mean, CENTERED second moment Σ(x−μ)², nnz) across
    valid cells, cancellation-free.

    Two fused passes over one index stream: pass 1 gets sums/nnz;
    pass 2, seeded with the on-device means, accumulates the
    non-negative ``Σ_valid (x−μ)²`` and adds the zeros' closed-form
    contribution ``(n−nnz)·μ²``.  Every f32 sum is of non-negative
    terms, so the relative error is ~√N·ε of the moment ITSELF —
    unlike ``ss − n·μ²``, which loses all precision for genes with
    ``μ² ≫ var`` (housekeeping genes on raw counts).  Same scheme as
    the streaming stats pass (data/stream.py _shard_stats).

    ``n_valid`` (TRACED scalar) overrides the static ``x.n_cells`` as
    the population count — the bucket-mask path (buckets.py), where
    ``x.n_cells`` is the bucket row count and padding rows are fully
    sentinel (they already drop out of the slot sums; only the
    divisions and the zeros term see the count).
    """
    n_cells = x.n_cells

    def slot_sums(ind, dat, row_offset):
        rows = row_offset + jnp.arange(ind.shape[0])
        valid = (ind != x.sentinel) & (rows < n_cells)[:, None]
        return jnp.stack([dat, valid.astype(dat.dtype)], axis=2)

    out1 = segment_reduce(x, slot_sums, 2)  # (no dead Σx² slot here)
    s, nnz = out1[:, 0], out1[:, 1]
    if n_valid is None:
        mu = s / max(n_cells, 1)
        n = n_cells
    else:
        n = jnp.asarray(n_valid, s.dtype)
        mu = s / jnp.maximum(n, 1.0)
    mu_pad = jnp.concatenate([mu, jnp.zeros((1,), mu.dtype)])

    def slot_sq(ind, dat, row_offset):
        rows = row_offset + jnp.arange(ind.shape[0])
        valid = (ind != x.sentinel) & (rows < n_cells)[:, None]
        d = jnp.where(valid, dat - jnp.take(mu_pad, ind), 0.0)
        return (d * d)[:, :, None]

    m2 = segment_reduce(x, slot_sq, 1)[:, 0]
    m2 = m2 + jnp.maximum(n - nnz, 0.0) * mu * mu
    return mu, m2, nnz
