"""Device-resident sparse count matrices, TPU-first.

The reference (dpeerlab/sctools; source unavailable — SURVEY.md §0)
stores counts as AnnData CSR shards and the north star asks for
"device-resident BCOO blocks".  A literal BCOO (coordinate list) is a
poor fit for the TPU: every op would become a gather/scatter over an
unpredictable index stream, which XLA cannot tile onto the VPU/MXU.

Instead we use a **padded-ELL** layout, the TPU-native equivalent:

    indices : (rows_padded, capacity) int32  — gene ids, row-major
    data    : (rows_padded, capacity) float32 — counts

Each cell's nonzeros occupy the leading slots of its row; the rest of
the row is padding (``index == n_genes`` sentinel, ``value == 0``).
``capacity`` is the max nnz/row rounded up to a lane multiple (128) and
``rows_padded`` rounds up to a sublane/sharding multiple.  Benefits:

* **static shapes** — one XLA compilation for any batch of shards;
* per-cell reductions (library size, QC, normalisation) are dense
  vectorised ops over the rows — pure VPU work, no scatter;
* ``X @ V`` (PCA matvec) is a gather of V rows + an einsum — and V is
  small enough to live in VMEM;
* ``Xᵀ @ W`` / per-gene stats are a single ``segment_sum`` over the
  flattened slot array;
* rows shard cleanly across a device mesh for multi-chip pipelines.

Interop: ``from_scipy_csr``/``to_scipy_csr`` round-trip exactly, and
``to_bcoo`` produces a ``jax.experimental.sparse.BCOO`` for users who
want the stock JAX sparse type.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..config import config, round_up


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class SparseCells:
    """Padded-ELL sparse matrix of shape ``(n_cells, n_genes)``.

    ``indices``/``data`` may be numpy (host) or jax (device) arrays;
    ``device_put`` moves them.  Padding slots have ``indices ==
    n_genes`` (one-past-the-end sentinel) and ``data == 0`` — so a
    gather from a ``(n_genes+1, d)`` table whose final row is zero
    silently annihilates padding, and ``segment_sum`` with
    ``num_segments == n_genes + 1`` accumulates padding into a bin that
    is then dropped.
    """

    indices: jax.Array  # (rows_padded, capacity) int32
    data: jax.Array  # (rows_padded, capacity) float
    n_cells: int  # static
    n_genes: int  # static

    # -- pytree protocol (n_cells/n_genes are static aux data) --------
    def tree_flatten(self):
        return (self.indices, self.data), (self.n_cells, self.n_genes)

    @classmethod
    def tree_unflatten(cls, aux, children):
        indices, data = children
        return cls(indices, data, *aux)

    # -- basic properties ---------------------------------------------
    @property
    def shape(self):
        return (self.n_cells, self.n_genes)

    @property
    def rows_padded(self) -> int:
        return self.indices.shape[0]

    @property
    def capacity(self) -> int:
        return self.indices.shape[1]

    @property
    def sentinel(self) -> int:
        return self.n_genes

    def valid_mask(self) -> jax.Array:
        """(rows_padded, capacity) bool — True at real nonzero slots."""
        return self.indices != self.n_genes

    def row_mask(self) -> jax.Array:
        """(rows_padded,) bool — True for real (non-padding) cells."""
        return jnp.arange(self.rows_padded) < self.n_cells

    def with_data(self, data: jax.Array) -> "SparseCells":
        """Same sparsity pattern, new values (functional update)."""
        return SparseCells(self.indices, data, self.n_cells, self.n_genes)

    def nnz_per_row(self) -> jax.Array:
        return jnp.sum(self.valid_mask(), axis=1, dtype=jnp.int32)

    def device_put(self, sharding=None) -> "SparseCells":
        ind = jax.device_put(jnp.asarray(self.indices), sharding)
        dat = jax.device_put(jnp.asarray(self.data), sharding)
        return SparseCells(ind, dat, self.n_cells, self.n_genes)

    # -- conversions ---------------------------------------------------
    @classmethod
    def from_scipy_csr(
        cls,
        csr,
        capacity: int | None = None,
        rows_multiple: int | None = None,
        dtype=None,
    ) -> "SparseCells":
        """Pack a ``scipy.sparse.csr_matrix`` into padded-ELL.

        Uses the native C++ packer when available (csrc/scio.cpp),
        falling back to a vectorised numpy pack.
        """
        import scipy.sparse as sp

        if not sp.issparse(csr):
            raise TypeError(f"expected scipy sparse matrix, got {type(csr)}")
        csr = csr.tocsr()
        csr.sort_indices()
        n_cells, n_genes = csr.shape
        dtype = dtype or config.dtype
        nnz = np.diff(csr.indptr)
        max_nnz = int(nnz.max()) if len(nnz) else 0
        if capacity is None:
            capacity = max(round_up(max(max_nnz, 1), config.capacity_multiple),
                           config.capacity_multiple)
        elif max_nnz > capacity:
            raise ValueError(
                f"capacity={capacity} < max nnz/row={max_nnz}; "
                "refusing to drop counts"
            )
        rows_multiple = rows_multiple or config.sublane
        rows_padded = round_up(max(n_cells, 1), rows_multiple)

        from ..native import pack_ell  # numpy fallback inside

        indices, data = pack_ell(
            csr.indptr.astype(np.int64),
            csr.indices.astype(np.int32),
            csr.data.astype(dtype),
            rows_padded,
            capacity,
            sentinel=n_genes,
        )
        return cls(indices, data, n_cells, n_genes)

    def to_scipy_csr(self):
        import scipy.sparse as sp

        ind = np.asarray(self.indices)
        dat = np.asarray(self.data)
        mask = ind != self.n_genes
        nnz = mask.sum(axis=1)[: self.n_cells]
        indptr = np.zeros(self.n_cells + 1, dtype=np.int64)
        np.cumsum(nnz, out=indptr[1:])
        rows = np.repeat(np.arange(self.rows_padded), mask.sum(axis=1))
        keep = rows < self.n_cells
        return sp.csr_matrix(
            (dat[mask][keep], ind[mask][keep], indptr),
            shape=(self.n_cells, self.n_genes),
        )

    def to_bcoo(self):
        """Stock ``jax.experimental.sparse.BCOO`` view (padding kept as
        explicit zeros at column ``n_genes - 1`` is avoided by clamping
        then relying on zero data)."""
        from jax.experimental import sparse as jsparse

        rows = jnp.broadcast_to(
            jnp.arange(self.rows_padded)[:, None], self.indices.shape
        )
        cols = jnp.minimum(self.indices, self.n_genes - 1)
        idx = jnp.stack([rows.ravel(), cols.ravel()], axis=1)
        return jsparse.BCOO(
            (self.data.ravel(), idx), shape=(self.rows_padded, self.n_genes)
        )[: self.n_cells]

    def to_dense(self) -> jax.Array:
        """Densify (small matrices / tests only)."""
        table = jnp.zeros((self.rows_padded, self.n_genes + 1), self.data.dtype)
        table = jax.vmap(lambda t, i, d: t.at[i].add(d))(
            table, self.indices, self.data
        )
        return table[: self.n_cells, : self.n_genes]

    def pad_rows_to(self, rows_padded: int) -> "SparseCells":
        if rows_padded < self.rows_padded:
            raise ValueError("cannot shrink row padding below current")
        if rows_padded == self.rows_padded:
            return self
        extra = rows_padded - self.rows_padded
        ind = jnp.concatenate(
            [jnp.asarray(self.indices),
             jnp.full((extra, self.capacity), self.sentinel, jnp.int32)]
        )
        dat = jnp.concatenate(
            [jnp.asarray(self.data),
             jnp.zeros((extra, self.capacity), self.data.dtype)]
        )
        return SparseCells(ind, dat, self.n_cells, self.n_genes)

    def __repr__(self):
        return (
            f"SparseCells(shape=({self.n_cells}, {self.n_genes}), "
            f"padded={self.rows_padded}x{self.capacity}, "
            f"dtype={self.data.dtype})"
        )


# ----------------------------------------------------------------------
# Core sparse linear algebra primitives (jittable).
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("precision",))
def spmm(x: SparseCells, v: jax.Array, precision=None) -> jax.Array:
    """``X @ V`` for padded-ELL ``X`` and dense ``V`` of shape (G, d).

    TPU mapping: gather V rows (V padded with a zero row so sentinel
    indices vanish), then a slot-reduction einsum — VPU-bound with V
    resident in VMEM for typical d ≤ 512.
    """
    vp = jnp.concatenate([v, jnp.zeros((1, v.shape[1]), v.dtype)], axis=0)
    gathered = jnp.take(vp, x.indices, axis=0)  # (R, C, d)
    return jnp.einsum(
        "rc,rcd->rd", x.data.astype(v.dtype), gathered, precision=precision
    )


@jax.jit
def spmm_t(x: SparseCells, w: jax.Array) -> jax.Array:
    """``Xᵀ @ W`` for dense ``W`` of shape (rows_padded, d) → (G, d).

    Padding rows of W must be zero, or use ``x.row_mask()`` upstream.
    Implemented as one segment-sum over the flattened slot array; the
    sentinel bin (index G) is dropped.
    """
    contrib = x.data[:, :, None] * w[:, None, :]  # (R, C, d)
    flat_idx = x.indices.ravel()
    flat = contrib.reshape(-1, w.shape[-1])
    out = jax.ops.segment_sum(flat, flat_idx, num_segments=x.n_genes + 1)
    return out[: x.n_genes]


@jax.jit
def row_sum(x: SparseCells) -> jax.Array:
    """Per-cell total counts, (rows_padded,)."""
    return jnp.sum(x.data, axis=1)


@jax.jit
def gene_sum(x: SparseCells) -> jax.Array:
    """Per-gene total counts, (n_genes,)."""
    flat = x.data.ravel()
    out = jax.ops.segment_sum(flat, x.indices.ravel(), num_segments=x.n_genes + 1)
    return out[: x.n_genes]


@jax.jit
def gene_stats(x: SparseCells) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-gene (sum, sum of squares, nnz count) across *valid* cells.

    One fused pass: three segment-sums over the same index stream.
    Padding rows contribute zeros (their data is zero) except for the
    nnz count, which masks explicitly.
    """
    idx = x.indices.ravel()
    d = x.data.ravel()
    valid = (x.valid_mask() & x.row_mask()[:, None]).ravel()
    stacked = jnp.stack(
        [d, d * d, valid.astype(d.dtype)], axis=1
    )  # (R*C, 3)
    out = jax.ops.segment_sum(stacked, idx, num_segments=x.n_genes + 1)
    out = out[: x.n_genes]
    return out[:, 0], out[:, 1], out[:, 2]
