"""``concat`` — AnnData-style concatenation of CellData objects.

Capability parity: ``anndata.concat`` (cell axis), the operation every
multi-sample workflow starts with — merge runs, tag each cell with its
source, then integrate (``integrate.harmony`` / ``integrate.combat`` /
``neighbors.bbknn`` all consume the ``label`` column this writes).
The reference source was unavailable (/root/reference empty —
SURVEY.md §0); the behavioral contract implemented here is the public
anndata one:

* ``join="inner"``: keep genes present in every input (by
  ``var['gene_name']`` when all inputs carry it, else by position,
  requiring equal widths);
* ``join="outer"``: union of genes, absent entries zero (anndata's
  sparse fill);
* obs columns: union of keys; missing entries filled with NaN
  (numeric) or ``""`` (strings);
* obsm/layers: keys common to ALL inputs are concatenated, others
  dropped (anndata drops them too); obsp/uns are dropped (pairwise
  graphs do not survive concatenation).

Host-side by design: concatenation is data management that happens
before ``device_put`` — the device format (padded ELL) is built once,
from the merged matrix, not stitched from per-input paddings.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from .dataset import CellData

__all__ = ["concat"]


def _gene_names(d: CellData):
    n = d.var.get("gene_name")
    return None if n is None else np.asarray(n).astype(str)


def _to_csr(X):
    import scipy.sparse as sp

    from .sparse import SparseCells

    if isinstance(X, SparseCells):
        X = X.to_scipy_csr()
    if sp.issparse(X):
        return X.tocsr()
    return sp.csr_matrix(np.asarray(X))


def _reindex_csr(X, old_names, new_names):
    """Map columns of X (labelled old_names) onto the new_names axis;
    genes absent from old_names become empty (zero) columns."""
    import scipy.sparse as sp

    pos = {n: i for i, n in enumerate(new_names)}
    col_map = np.full(len(old_names), -1, np.int64)
    for i, n in enumerate(old_names):
        j = pos.get(n)
        if j is not None:
            col_map[i] = j
    X = X.tocoo()
    keep = col_map[X.col] >= 0
    return sp.csr_matrix(
        (X.data[keep], (X.row[keep], col_map[X.col[keep]])),
        shape=(X.shape[0], len(new_names)))


def concat(datas: Sequence[CellData], *, join: str = "inner",
           label: str | None = None,
           keys: Sequence[str] | None = None) -> CellData:
    """Concatenate along the cell axis.  ``label``/``keys`` add a
    per-cell source column (defaults to "0", "1", … when keys is
    None), the input ``integrate.*``/``neighbors.bbknn`` expect as
    ``batch_key``.  (anndata's ``index_unique`` has no analogue here —
    CellData carries no obs index to uniquify.)"""
    if join not in ("inner", "outer"):
        raise ValueError(f"concat: unknown join {join!r}")
    datas = list(datas)
    if not datas:
        raise ValueError("concat: need at least one CellData")
    if keys is not None and label is None:
        raise ValueError(
            "concat: keys= without label= would be silently dropped — "
            "pass label='batch' (the obs column the keys become)")
    if keys is not None and len(keys) != len(datas):
        raise ValueError("concat: len(keys) != len(datas)")

    names = [_gene_names(d) for d in datas]
    if all(n is not None for n in names):
        for i, nm in enumerate(names):
            if len(set(nm)) != len(nm):
                dup = next(g for g, c in zip(
                    *np.unique(nm, return_counts=True)) if c > 1)
                raise ValueError(
                    f"concat: input {i} has duplicate gene names "
                    f"(e.g. {dup!r}) — name-joined concatenation would "
                    "silently merge their counts; deduplicate "
                    "var['gene_name'] first (anndata: var_names_make_"
                    "unique)")
        if join == "inner":
            common = set(names[0])
            for n in names[1:]:
                common &= set(n)
            # preserve the FIRST input's gene order (anndata semantics)
            new_names = np.array([g for g in names[0] if g in common])
        else:
            seen = dict.fromkeys(names[0])
            for n in names[1:]:
                seen.update(dict.fromkeys(n))
            new_names = np.array(list(seen))
        mats = [_reindex_csr(_to_csr(d.X), nm, new_names)
                for d, nm in zip(datas, names)]
        # var: keep the FIRST input's columns, reindexed onto the new
        # gene axis (outer-join genes absent from it get NaN/"") — the
        # positional path below keeps datas[0].var whole, so the named
        # path must not silently drop metadata either
        new_var = {"gene_name": new_names}
        src_pos = {g: i for i, g in enumerate(names[0])}
        take = np.array([src_pos.get(g, -1) for g in new_names])
        for col, v in datas[0].var.items():
            if col == "gene_name":
                continue
            v = np.asarray(v)
            if v.shape[:1] != (len(names[0]),):
                continue
            if v.dtype.kind in "ifub":
                filled = np.full(len(new_names), np.nan)
                filled[take >= 0] = v[take[take >= 0]].astype(np.float64)
            else:
                filled = np.full(len(new_names), "", dtype=object)
                filled[take >= 0] = v[take[take >= 0]]
            new_var[col] = filled
    else:
        widths = {d.n_genes for d in datas}
        if len(widths) != 1:
            raise ValueError(
                f"concat: inputs have differing gene counts {widths} and "
                "not all carry var['gene_name'] to align by")
        new_names = None
        mats = [_to_csr(d.X) for d in datas]
        new_var = dict(datas[0].var)

    import scipy.sparse as sp

    n_per = [m.shape[0] for m in mats]
    X = sp.vstack(mats, format="csr")

    # obs: union of keys, filled where absent
    new_obs: dict = {}
    all_keys: dict = {}
    for d in datas:
        all_keys.update(dict.fromkeys(d.obs))
    for kcol in all_keys:
        parts = []
        numeric = all(
            np.asarray(d.obs[kcol]).dtype.kind in "ifub"
            for d in datas if kcol in d.obs)
        for d, n in zip(datas, n_per):
            if kcol in d.obs:
                parts.append(np.asarray(d.obs[kcol])[:n])
            elif numeric:
                parts.append(np.full(n, np.nan))
            else:
                parts.append(np.full(n, "", dtype=object))
        new_obs[kcol] = np.concatenate(parts)
    if label is not None:
        tags = ([str(k) for k in keys] if keys is not None
                else [str(i) for i in range(len(datas))])
        new_obs[label] = np.concatenate(
            [np.full(n, t, dtype=object) for n, t in zip(n_per, tags)])

    # obsm/layers: intersection only
    common_obsm = set(datas[0].obsm)
    common_layers = set(datas[0].layers)
    for d in datas[1:]:
        common_obsm &= set(d.obsm)
        common_layers &= set(d.layers)
    new_obsm = {kk: np.concatenate(
        [np.asarray(d.obsm[kk])[:n] for d, n in zip(datas, n_per)], axis=0)
        for kk in common_obsm}
    new_layers = {}
    for kk in common_layers:
        if new_names is not None:
            parts = [_reindex_csr(_to_csr(d.layers[kk]), nm, new_names)
                     for d, nm in zip(datas, names)]
        else:
            parts = [_to_csr(d.layers[kk]) for d in datas]
        new_layers[kk] = sp.vstack(parts, format="csr")

    return CellData(X, obs=new_obs, var=new_var, obsm=new_obsm,
                    layers=new_layers)
