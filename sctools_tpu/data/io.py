"""IO: readers for the formats sctools users bring.

* ``read_h5ad`` — AnnData HDF5 files (CSR/CSC/dense X, obs/var columns)
  read directly with h5py; no anndata dependency.
* ``read_10x_mtx`` — 10x Genomics MatrixMarket triples
  (matrix.mtx + features/genes.tsv + barcodes.tsv), using the native
  C++ parser when built.
* ``from_scipy`` / ``from_dense`` — in-memory entry points.
* ``shard_iter`` — stream a large on-disk matrix as row shards for
  out-of-core pipelines (AnnData CSR shards → padded-ELL blocks).
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np

from ..config import config, round_up
from .dataset import CellData
from .sparse import SparseCells


def from_scipy(X, obs=None, var=None, **kw) -> CellData:
    return CellData(X.tocsr(), obs=obs or {}, var=var or {}, **kw)


def from_dense(X, obs=None, var=None, **kw) -> CellData:
    return CellData(np.asarray(X), obs=obs or {}, var=var or {}, **kw)


# ----------------------------------------------------------------------
# h5ad
# ----------------------------------------------------------------------


def _read_h5_matrix(h5, path="X"):
    import scipy.sparse as sp

    node = h5[path]
    if isinstance(node, __import__("h5py").Dataset):
        return node[...]
    enc = node.attrs.get("encoding-type", b"")
    enc = enc.decode() if isinstance(enc, bytes) else enc
    shape = tuple(node.attrs["shape"]) if "shape" in node.attrs else None
    data = node["data"][...]
    indices = node["indices"][...]
    indptr = node["indptr"][...]
    if enc.startswith("csc"):
        return sp.csc_matrix((data, indices, indptr), shape=shape).tocsr()
    return sp.csr_matrix((data, indices, indptr), shape=shape)


def _read_h5_frame(h5, path):
    """Read an AnnData obs/var group into a dict of numpy arrays."""
    out = {}
    if path not in h5:
        return out
    node = h5[path]
    import h5py

    if isinstance(node, h5py.Dataset):  # old-style structured array
        arr = node[...]
        if arr.dtype.names:
            for name in arr.dtype.names:
                out[name] = _decode(arr[name])
        return out
    for key in node:
        if key.startswith("_") or key == "__categories":
            continue
        child = node[key]
        if isinstance(child, h5py.Dataset):
            out[key] = _decode(child[...])
        elif "categories" in child and "codes" in child:
            cats = _decode(child["categories"][...])
            codes = child["codes"][...]
            out[key] = np.where(codes >= 0, cats[np.maximum(codes, 0)], "")
    return out


def _decode(arr):
    arr = np.asarray(arr)
    if arr.dtype.kind in ("S", "O"):
        return np.array(
            [x.decode() if isinstance(x, bytes) else x for x in arr.ravel()]
        ).reshape(arr.shape)
    return arr


def _read_h5_tree(node):
    """Recursive reader for uns/obsp-style groups: CSR subgroups come
    back as scipy matrices, plain groups as dicts, datasets decoded."""
    import h5py

    if isinstance(node, h5py.Dataset):
        return _decode(node[...])
    enc = node.attrs.get("encoding-type", b"")
    enc = enc.decode() if isinstance(enc, bytes) else enc
    if str(enc).startswith(("csr", "csc")):  # _read_h5_matrix converts
        return _read_h5_matrix(node.parent, node.name.rsplit("/", 1)[-1])
    return {k: _read_h5_tree(node[k]) for k in node}


def read_h5ad(path: str, load_obsm: bool = True,
              load_layers: bool = True,
              load_obsp: bool = True) -> CellData:
    import h5py

    with h5py.File(path, "r") as h5:
        X = _read_h5_matrix(h5, "X")
        obs = _read_h5_frame(h5, "obs")
        var = _read_h5_frame(h5, "var")
        obsm = {}
        varm = {}
        if load_obsm:
            if "obsm" in h5:
                for key in h5["obsm"]:
                    obsm[key] = h5["obsm"][key][...]
            if "varm" in h5:
                for key in h5["varm"]:
                    varm[key] = h5["varm"][key][...]
        layers = {}
        # opt-out: velocity-style files carry X-sized spliced/unspliced
        # layers — pipelines that never touch them shouldn't pay 3x IO
        if load_layers and "layers" in h5:
            for key in h5["layers"]:
                layers[key] = _read_h5_matrix(h5["layers"], key)
        obsp = {}
        # opt-out for the same reason: external files can carry
        # n_obs x n_obs distance/connectivity matrices here
        if load_obsp and "obsp" in h5:
            for key in h5["obsp"]:
                obsp[key] = _read_h5_tree(h5["obsp"][key])
        uns = {}
        if "uns" in h5:
            for key in h5["uns"]:
                uns[key] = _read_h5_tree(h5["uns"][key])
    if "gene_name" not in var:
        for cand in ("_index", "index", "gene_symbols", "gene_ids"):
            if cand in var:
                var["gene_name"] = var.pop(cand)
                break
    return CellData(X, obs=obs, var=var, obsm=obsm, varm=varm,
                    layers=layers, obsp=obsp, uns=uns)


def write_h5ad(data: CellData, path: str) -> None:
    """Minimal AnnData-compatible writer (CSR X, flat obs/var)."""
    import h5py
    import scipy.sparse as sp

    host = data.to_host() if _on_device(data) else data

    def write_matrix(parent, name, M):
        if sp.issparse(M):
            M = M.tocsr()
            g = parent.create_group(name)
            g.attrs["encoding-type"] = "csr_matrix"
            g.attrs["encoding-version"] = "0.1.0"
            g.attrs["shape"] = np.array(M.shape, dtype=np.int64)
            g.create_dataset("data", data=M.data)
            g.create_dataset("indices", data=M.indices)
            g.create_dataset("indptr", data=M.indptr)
        else:
            parent.create_dataset(name, data=np.asarray(M))

    def write_value(g, k, v):
        if isinstance(v, dict):
            # nested uns (dendrogram, paga, …): a subgroup, AnnData-style
            sub = g.create_group(str(k))
            for kk, vv in v.items():
                write_value(sub, kk, vv)
            return
        if sp.issparse(v):
            write_matrix(g, str(k), v)
            return
        if v is None:
            # scanpy idiom uns['log1p'] = {'base': None}; h5 has no
            # null — store the AnnData-ish empty-string sentinel
            v = np.asarray("", dtype=object)
        v = np.asarray(v)
        if v.dtype.kind in ("U", "O"):
            v = v.astype(h5py_str())
        g.create_dataset(str(k), data=v)

    with h5py.File(path, "w") as h5:
        write_matrix(h5, "X", host.X)
        if host.layers:
            lg = h5.create_group("layers")
            for k, v in host.layers.items():
                write_matrix(lg, k, v)
        for name, d in (("obs", host.obs), ("var", host.var),
                        ("obsm", host.obsm), ("varm", host.varm),
                        ("obsp", host.obsp), ("uns", host.uns)):
            g = h5.create_group(name)
            for k, v in d.items():
                write_value(g, k, v)


def h5py_str():
    import h5py

    return h5py.string_dtype()


def _on_device(data: CellData) -> bool:
    import jax

    return isinstance(data.X, (SparseCells, jax.Array)) or any(
        isinstance(v, (SparseCells, jax.Array))
        for v in data.layers.values())


# ----------------------------------------------------------------------
# 10x mtx
# ----------------------------------------------------------------------


def read_10x_mtx(path: str) -> CellData:
    """Read a 10x-style directory: matrix.mtx(.gz), features/genes.tsv,
    barcodes.tsv.  Matrix is genes×cells on disk (10x convention) and
    transposed to cells×genes here."""
    import gzip
    import scipy.sparse as sp

    from ..native import parse_mtx

    def find(*names):
        for n in names:
            for suff in ("", ".gz"):
                p = os.path.join(path, n + suff)
                if os.path.exists(p):
                    return p
        return None

    mtx = find("matrix.mtx")
    if mtx is None:
        raise FileNotFoundError(f"no matrix.mtx[.gz] under {path}")
    if mtx.endswith(".gz"):
        import scipy.io

        with gzip.open(mtx, "rb") as fh:
            m = scipy.io.mmread(fh).tocoo()
        nr, nc, rows, cols, vals = m.shape[0], m.shape[1], m.row, m.col, m.data
    else:
        nr, nc, rows, cols, vals = parse_mtx(mtx)
    X = sp.coo_matrix((vals, (cols, rows)), shape=(nc, nr)).tocsr()  # cells×genes

    var: dict = {}
    feats = find("features.tsv", "genes.tsv")
    if feats:
        opener = gzip.open if feats.endswith(".gz") else open
        with opener(feats, "rt") as fh:
            lines = [l.rstrip("\n").split("\t") for l in fh]
        var["gene_ids"] = np.array([l[0] for l in lines])
        var["gene_name"] = np.array([l[1] if len(l) > 1 else l[0] for l in lines])
    obs: dict = {}
    bars = find("barcodes.tsv")
    if bars:
        opener = gzip.open if bars.endswith(".gz") else open
        with opener(bars, "rt") as fh:
            obs["barcode"] = np.array([l.strip() for l in fh])
    return CellData(X, obs=obs, var=var)


# ----------------------------------------------------------------------
# Generic text / matrix-market readers + extension dispatch
# (scanpy sc.read_csv / sc.read_text / sc.read_mtx / sc.read parity;
# reference source unavailable — SURVEY.md §0 — the public scanpy
# signatures are the contract)
# ----------------------------------------------------------------------


def read_csv(path: str, delimiter: str | None = ",",
             first_column_names: bool | None = None,
             dtype=np.float32) -> CellData:
    """Read a dense delimited cells×genes table.

    Row 1 is taken as gene names when non-numeric; the first column
    is taken as cell names when ``first_column_names=True`` or (None)
    when its first data entry is non-numeric — scanpy's read_csv
    detection rules."""
    import csv as _csv

    def _is_num(s: str) -> bool:
        try:
            float(s)
            return True
        except ValueError:
            return False

    opener = __import__("gzip").open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        if delimiter is None:
            rows = [l.split() for l in fh if l.strip()]
        else:
            rows = [r for r in _csv.reader(fh, delimiter=delimiter) if r]
    if not rows:
        raise ValueError(f"read_csv: {path} is empty")
    header = rows[0]
    has_header = not all(_is_num(c) for c in header[1:] or header)
    body = rows[1:] if has_header else rows
    if not body:
        raise ValueError(f"read_csv: {path} has a header but no data")
    if first_column_names is None:
        first_column_names = not _is_num(body[0][0])
    obs: dict = {}
    if first_column_names:
        obs["cell_name"] = np.array([r[0] for r in body])
        body = [r[1:] for r in body]
        if has_header and len(header) == len(body[0]) + 1:
            header = header[1:]
    X = np.array(body, dtype=dtype)  # C-level str->float, not a
    # per-cell Python conversion (ragged rows still raise ValueError)
    var: dict = {}
    if has_header:
        if len(header) != X.shape[1]:
            raise ValueError(
                f"read_csv: header has {len(header)} names for "
                f"{X.shape[1]} data columns")
        var["gene_name"] = np.array(header)
    return CellData(X, obs=obs, var=var)


def read_text(path: str, delimiter: str | None = None,
              first_column_names: bool | None = None,
              dtype=np.float32) -> CellData:
    """``read_csv`` with whitespace splitting by default (scanpy
    sc.read_text)."""
    return read_csv(path, delimiter=delimiter,
                    first_column_names=first_column_names, dtype=dtype)


def read_mtx(path: str, transpose: bool = False) -> CellData:
    """Read a single matrix-market file AS STORED (scanpy sc.read_mtx:
    no 10x directory convention, no implicit transpose — pass
    ``transpose=True`` for genes×cells files)."""
    import scipy.io
    import scipy.sparse as sp

    if path.endswith(".gz"):
        import gzip

        with gzip.open(path, "rb") as fh:
            m = scipy.io.mmread(fh)
    else:
        m = scipy.io.mmread(path)
    m = m.T if transpose else m
    X = sp.csr_matrix(m)
    return CellData(X)


def read(path: str, **kw) -> CellData:
    """Extension-dispatching reader (scanpy ``sc.read``): .h5ad,
    .loom, .mtx[.gz], .csv[.gz], .txt/.tsv/.tab[.gz], .h5 (10x)."""
    base = path[:-3] if path.endswith(".gz") else path
    ext = os.path.splitext(base)[1].lower()
    if ext == ".h5ad":
        return read_h5ad(path, **kw)
    if ext == ".loom":
        return read_loom(path, **kw)
    if ext == ".mtx":
        return read_mtx(path, **kw)
    if ext == ".csv":
        return read_csv(path, **kw)
    if ext in (".txt", ".tsv", ".tab", ".data"):
        kw.setdefault("delimiter",
                      "\t" if ext in (".tsv", ".tab") else None)
        return read_text(path, **kw)
    if ext == ".h5":
        return read_10x_h5(path, **kw)
    raise ValueError(
        f"read: unknown extension {ext!r} for {path!r} (use read_h5ad/"
        f"read_loom/read_mtx/read_csv/read_text/read_10x_h5 directly)")


# ----------------------------------------------------------------------
# Durable shard-store chunks (out-of-core ingest tier)
# ----------------------------------------------------------------------


def write_csr_chunk(path: str, data, indices, indptr, shape,
                    fingerprint: str | None = None) -> str:
    """Write ONE shard-store chunk: a CSR row-slice as a checksummed
    ``.npz`` carrying the checkpoint layer's ``_integrity/*`` keys
    (content digest + schema + identity ``fingerprint``), atomic via
    rename.  Returns the chunk's content digest (the manifest records
    it, so a cross-wired chunk file — intact bytes, wrong slot — is
    caught by manifest-vs-file digest comparison even though the file
    self-verifies)."""
    from ..utils.checkpoint import _content_digest, save_npz_verified

    arrays = {
        "data": np.ascontiguousarray(data),
        "indices": np.ascontiguousarray(indices, np.int32),
        "indptr": np.ascontiguousarray(indptr, np.int64),
        "shape": np.asarray(shape, np.int64),
    }
    return save_npz_verified(path, fingerprint=fingerprint, **arrays)


def read_csr_chunk(path: str, expect_fingerprint: str | None = None,
                   expect_digest: str | None = None,
                   verify: bool = True) -> tuple:
    """Read-and-verify the twin of :func:`write_csr_chunk`.  Returns
    ``(data, indices, indptr, shape)``.  ``verify=True`` (the default
    — chunk reads feed hours-long ingests, trusting a damaged file is
    never worth one skipped hash pass) re-hashes the payload and
    checks the identity fingerprint, raising
    ``CheckpointCorruptError`` with a machine-readable ``.reason`` on
    unreadable bytes, digest/schema/fingerprint mismatch, or missing
    integrity keys (every chunk is WRITTEN with them, so a digestless
    chunk is truncated or foreign, not legacy).  ``expect_digest=``
    (the manifest's recorded digest) additionally catches a
    cross-wired file: intact bytes that self-verify but belong in a
    different slot — all from the SAME single read.  The verify
    ladder itself lives in ``checkpoint.load_npz_verified`` — ONE
    integrity ruling for resume files and store chunks alike."""
    from ..utils.checkpoint import _read_arrays, load_npz_verified

    if verify:
        arrays = load_npz_verified(
            path, expect_fingerprint=expect_fingerprint,
            require_digest=True, expect_digest=expect_digest)
    else:
        arrays = _read_arrays(path)
    return (arrays["data"], arrays["indices"], arrays["indptr"],
            tuple(int(x) for x in arrays["shape"]))


# ----------------------------------------------------------------------
# Shard streaming (out-of-core)
# ----------------------------------------------------------------------


def shard_iter(path: str, shard_rows: int, capacity: int | None = None,
               start_row: int = 0) -> Iterator[SparseCells]:
    """Stream an h5ad CSR matrix as padded-ELL shards of ``shard_rows``
    cells without loading the whole matrix.

    Every shard shares one global ``capacity`` so a single compiled
    program processes all shards; pass ``capacity=`` to override the
    first-shard estimate (an undersized estimate raises).
    ``start_row`` (a ``shard_rows`` multiple) seeks straight to that
    shard without reading the skipped ones — checkpoint/resume of
    streaming passes depends on this being a true seek, not a
    read-and-discard.
    """
    import h5py
    import scipy.sparse as sp

    if start_row % shard_rows:
        raise ValueError(
            f"start_row={start_row} must be a multiple of "
            f"shard_rows={shard_rows}")
    with h5py.File(path, "r") as h5:
        node = h5["X"]
        if isinstance(node, h5py.Dataset):
            n = node.shape[0]
            for s in range(start_row, n, shard_rows):
                e = min(n, s + shard_rows)
                sub = sp.csr_matrix(node[s:e])
                if capacity is None:
                    nnz_max = int(np.diff(sub.indptr).max()) if e > s else 1
                    capacity = round_up(max(nnz_max * 2, 1),
                                        config.capacity_multiple)
                yield SparseCells.from_scipy_csr(sub, capacity=capacity)
            return
        enc = node.attrs.get("encoding-type", b"csr_matrix")
        enc = enc.decode() if isinstance(enc, bytes) else enc
        if not str(enc).startswith("csr"):
            raise NotImplementedError(
                f"shard_iter requires CSR-encoded X, got {enc!r}; "
                "convert with read_h5ad(...) + write_h5ad(...) first"
            )
        indptr = node["indptr"][...]
        shape = tuple(node.attrs["shape"])
        n = shape[0]
        for s in range(start_row, n, shard_rows):
            e = min(n, s + shard_rows)
            lo, hi = indptr[s], indptr[e]
            sub = sp.csr_matrix(
                (node["data"][lo:hi], node["indices"][lo:hi],
                 indptr[s : e + 1] - lo),
                shape=(e - s, shape[1]),
            )
            if capacity is None:
                nnz_max = int(np.diff(sub.indptr).max()) if e > s else 1
                capacity = round_up(max(nnz_max * 2, 1), config.capacity_multiple)
            yield SparseCells.from_scipy_csr(sub, capacity=capacity)


def read_10x_h5(path: str, genome: str | None = None) -> CellData:
    """Read a 10x Genomics CellRanger ``.h5`` file (scanpy
    ``read_10x_h5``).  Handles both layouts the format has shipped:

    * CellRanger >=3: one ``/matrix`` group with ``features/...``
      (``id``, ``name``, ``feature_type``);
    * CellRanger 2: one group per genome with ``genes``/``gene_names``
      (``genome=`` selects it; defaults to the only/first group).

    The stored matrix is features x barcodes in CSC-of-the-transpose
    form — i.e. exactly CSR of cells x genes once reinterpreted, so no
    transpose pass is needed: indptr walks barcodes, indices are
    feature ids.
    """
    import h5py
    import scipy.sparse as sp

    with h5py.File(path, "r") as f:
        if "matrix" in f:
            g = f["matrix"]
            feat = g["features"]
            var = {
                "gene_ids": np.asarray(feat["id"]).astype(str),
                "gene_name": np.asarray(feat["name"]).astype(str),
            }
            # the CellRanger v3 spec names it 'feature_type'
            # (singular); some writers emit the plural
            for ft in ("feature_type", "feature_types"):
                if ft in feat:
                    var["feature_type"] = np.asarray(feat[ft]).astype(str)
                    break
        else:
            groups = [k for k in f.keys()
                      if isinstance(f[k], h5py.Group)]
            if not groups:
                raise ValueError(
                    f"read_10x_h5: no matrix group in {path!r}")
            if genome is None and len(groups) > 1:
                # a mixed-species file read half-empty without warning
                # is worse than an error
                raise ValueError(
                    f"read_10x_h5: multiple genome groups {groups} in "
                    f"{path!r}; pass genome= to pick one")
            name = genome or groups[0]
            if name not in f:
                raise ValueError(
                    f"read_10x_h5: genome {name!r} not in {groups}")
            g = f[name]
            var = {
                "gene_ids": np.asarray(g["genes"]).astype(str),
                "gene_name": np.asarray(g["gene_names"]).astype(str),
            }
        n_genes, n_cells = (int(x) for x in g["shape"][:])
        X = sp.csr_matrix(
            (np.asarray(g["data"], np.float32),
             np.asarray(g["indices"]),
             np.asarray(g["indptr"])),
            shape=(n_cells, n_genes))
        obs = {"barcode": np.asarray(g["barcodes"]).astype(str)}
    return CellData(X, obs=obs, var=var)


def read_loom(path: str, sparse: bool = True,
              obs_names: str = "CellID",
              var_names: str = "Gene") -> CellData:
    """Read a ``.loom`` file (scanpy ``read_loom``) — the velocyto
    output format whose ``/layers`` (``spliced``/``unspliced``/
    ``ambiguous``) feed ``velocity.*`` directly.

    Loom stores genes x cells; everything is transposed to
    cells x genes here.  ``sparse=True`` converts the (chunked-dense)
    matrix and layers to CSR on the fly, row-block by row-block, so
    the full dense matrix never materialises in memory.
    """
    import h5py
    import scipy.sparse as sp

    def to_cells_by_genes(dset):
        # loom matrices are (genes, cells); read in gene-row blocks
        # and build the transposed CSR incrementally
        g, c = dset.shape
        if not sparse:
            return np.asarray(dset[:], np.float32).T
        blocks = []
        step = max(1, min(g, 4096))
        for lo in range(0, g, step):
            blk = np.asarray(dset[lo: lo + step], np.float32)
            blocks.append(sp.csr_matrix(blk.T))  # (cells, block_genes)
        return sp.hstack(blocks, format="csr")

    with h5py.File(path, "r") as f:
        X = to_cells_by_genes(f["matrix"])
        layers = {}
        if "layers" in f:
            for name in f["layers"]:
                layers[name] = to_cells_by_genes(f["layers"][name])
        obs, var = {}, {}
        for attrs, out, names_key, rename in (
                (f.get("col_attrs"), obs, obs_names, "cell_id"),
                (f.get("row_attrs"), var, var_names, "gene_name")):
            if attrs is None:
                continue
            for k in attrs:
                v = np.asarray(attrs[k])
                if v.dtype.kind in "SO":
                    v = v.astype(str)
                out[rename if k == names_key else k] = v
    return CellData(X, obs=obs, var=var, layers=layers)


def write_loom(data: CellData, path: str) -> None:
    """Write a ``.loom`` file (genes x cells, layers included) —
    round-trips with :func:`read_loom`.  Dense on disk (the loom
    format); row/col attrs carry var/obs columns."""
    import h5py
    import scipy.sparse as sp

    def dense_T(M):
        if isinstance(M, SparseCells):
            M = M.to_scipy_csr()
        if sp.issparse(M):
            M = M.toarray()
        return np.asarray(M, np.float32).T  # genes x cells

    n = data.n_cells
    with h5py.File(path, "w") as f:
        f.create_dataset("matrix", data=dense_T(data.X))
        if data.layers:
            lay = f.create_group("layers")
            for k, v in data.layers.items():
                lay.create_dataset(k, data=dense_T(v))
        ca = f.create_group("col_attrs")
        for k, v in data.obs.items():
            v = np.asarray(v)[:n]
            ca.create_dataset("CellID" if k == "cell_id" else k,
                              data=(v.astype("S") if v.dtype.kind
                                    in "US" else v))
        ra = f.create_group("row_attrs")
        for k, v in data.var.items():
            v = np.asarray(v)
            ra.create_dataset("Gene" if k == "gene_name" else k,
                              data=(v.astype("S") if v.dtype.kind
                                    in "US" else v))
