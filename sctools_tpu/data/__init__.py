from .dataset import CellData
from .sparse import SparseCells, gene_stats, gene_sum, row_sum, spmm, spmm_t
from . import io, synthetic
from .shardstore import (ShardReadScheduler, ShardStore, StoreWriter,
                         open_store, write_store)

__all__ = [
    "CellData", "SparseCells", "spmm", "spmm_t", "row_sum", "gene_sum",
    "gene_stats", "io", "synthetic",
    "ShardStore", "ShardReadScheduler", "StoreWriter", "open_store",
    "write_store",
]
