"""Tiled graph-kernel family for the post-kNN tail: banded Pallas
kernels + blocked-XLA twins behind one dispatcher.

Why this module exists: once preprocessing is fused on-chip (plan.py)
and sharded across the mesh, the wall-clock concentrates in the graph
consumers — MAGIC's diffusion scan, ``velocity.moments``, Palantir's
power iterations, ``graph.jaccard``, the t-SNE repulsion sweep.  All
of them are gather/segment-sum loops over the padded (n, k) kNN edge
list, and the legacy implementations materialise whole-graph
intermediates (an (n, k, d) gather per matvec, an (n, k, k, k)
equality mask for Jaccard) and stream the full x table past every row
block.  This module supplies the tiled forms:

* **Pallas banded kernels** (the TPU instantiation).  Rows are
  processed in (block, ·) VMEM tiles; the x table is swept in a
  BANDED window of column blocks around the diagonal.  Edges are
  applied MXU-style: a k-step one-hot accumulation builds the dense
  (rb, cb) local weight matrix, and the tile contribution is ONE
  matmul ``W_local @ x_window`` — no HBM round-trip for the gathered
  rows, no scatter.  The band is what ``graph.reorder`` (ops/graph.py)
  buys: after the RCM/locality pass every neighbour of row block i
  falls within ``band_rows`` of the diagonal, so the window sweep
  covers ``O(band/ n)`` of the table instead of all of it.  With no
  reorder (``band_rows=None``) the sweep covers every block —
  correct for any graph, just not banded-fast.
* **Blocked-XLA twins** (the off-TPU instantiation, and what
  ``"auto"`` resolves to on this CI box).  The same row tiling
  expressed as ``lax.map`` over row blocks with a per-block gather —
  bitwise identical to the legacy whole-graph path (same per-row
  reduction order) while never materialising the (n, k, d)
  intermediate; measured 5.5x over the legacy gather on the 2-core
  CI box at 32k cells (tools/bench_graph.py).
* **The legacy gather path** stays registered as the correctness
  fallback: ``SCTOOLS_PALLAS_GRAPH=0`` (or
  ``configure(graph_impl="gather")``) restores it byte-for-byte.

Dispatch: :func:`resolved_impl` maps ``config.graph_impl`` —
``"auto"`` → ``"pallas"`` on a real TPU backend, ``"xla"`` elsewhere
(interpreter-mode Pallas off-TPU is pure overhead; the parity suite
exercises it explicitly).  Every dispatch ticks the
``graph.kernel_calls`` counter (labelled kernel=, impl=) — for eager
callers that is one tick per execution, for callers inside an
enclosing ``jax.jit`` one tick per trace (the dispatcher runs at
trace time; the compiled program re-runs without re-dispatching).

Numerics contract: the blocked-XLA twins are BITWISE identical to the
legacy gather path (identical per-row reduction order).  The Pallas
kernels accumulate each row over the banded window sweep instead of
the k edge slots, so results agree to float32 reduction-order ulps
(~1e-6 relative; the parity tests and the ``run_checks.sh``
graph-parity stage pin the tolerance).  Jaccard counts are small
exact integers on every path, so Jaccard parity is exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import config, round_up

_NEG = float("-inf")

#: the JACCARD kernel gathers neighbour lists by one-hot id-MATMUL —
#: ids ride float32 exactly only below 2^24, so larger graphs fall
#: back to the blocked-XLA twin (a silent precision loss on ids would
#: corrupt edges, not just round them).  The matvec/rmatvec kernels
#: compare ids in int32 and are not subject to this limit.
_MAX_EXACT_F32_ID = 1 << 24


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def resolved_impl() -> str:
    """The graph-kernel implementation this process runs:
    ``config.graph_impl`` with ``"auto"`` resolved to ``"pallas"`` on
    a real TPU backend and the blocked ``"xla"`` twins elsewhere
    (same policy as ``config.resolved_knn_impl`` — interpreter-mode
    Pallas off-TPU is pure overhead)."""
    impl = config.graph_impl
    if impl == "auto":
        return "xla" if config.interpret_mode() else "pallas"
    return impl


def _count(kernel: str, impl: str) -> None:
    from ..utils import telemetry

    telemetry.default_registry().counter(
        "graph.kernel_calls", kernel=kernel, impl=impl).inc()


def _band_blocks(band_rows: int | None, block: int,
                 n_blocks: int) -> int:
    """Banded-sweep halo in blocks: a neighbour within ``band_rows``
    of its row is at most ``ceil(band/block) + 1`` row blocks away
    (the +1 covers band windows straddling a block boundary)."""
    if band_rows is None:
        return n_blocks - 1
    return min(-(-int(band_rows) // block) + 1, n_blocks - 1)


# ---------------------------------------------------------------------------
# shared tile algebra (module-level so the k-step loops are written
# once and stay outside the kernel bodies proper)
# ---------------------------------------------------------------------------


def _local_edge_weights(idx_blk, w_blk, col0, cb: int, k: int):
    """Dense (rb, cb) local weight matrix of the edges from this row
    block into the column window starting at ``col0``:
    ``W[r, c] = Σ_t w[r, t] · [idx[r, t] == col0 + c]`` — the k-step
    one-hot accumulation that turns the gather into an MXU matmul.
    Negative (padding) ids never match; duplicate slots add."""
    rb = idx_blk.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, (rb, cb), 1) + col0
    W = jnp.zeros((rb, cb), jnp.float32)
    for t in range(k):
        hit = cols == idx_blk[:, t][:, None]
        W = W + jnp.where(hit, w_blk[:, t][:, None], 0.0)
    return W


def _window_match_counts(idx_blk, own_vals, tab_win, col0, cb: int,
                         k: int):
    """Per-slot neighbour-list statistics against a column window of
    the id table: for every row r and slot t whose neighbour id falls
    in ``[col0, col0 + cb)``, gather that neighbour's list from
    ``tab_win`` (one-hot matmul — ids ride float32 exactly below
    2^24) and return (match counts vs ``own_vals``, neighbour-list
    valid counts), full accumulator width with zeros in the padded
    slots.  Slots outside the window contribute zeros — each slot is
    counted exactly once across a full band sweep.

    ``idx_blk``/``own_vals`` are the FULL (rb, k_pad) tiles (padding
    -1 / -3); ``tab_win`` the (cb, k_pad) id-table window (padding
    -2).  Only the first ``k`` slots are swept; the (rb, k_pad, k)
    equality expansion value-slices own to its real width."""
    rb, k_pad = idx_blk.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (rb, cb), 1) + col0
    tab_f = tab_win.astype(jnp.float32)
    own_k = own_vals[:, :k].astype(jnp.float32)  # (rb, k)
    inter = jnp.zeros((rb, k_pad), jnp.float32)
    vj = jnp.zeros((rb, k_pad), jnp.float32)
    for t in range(k):
        hit = (cols == idx_blk[:, t][:, None]).astype(jnp.float32)
        nbr = jnp.dot(hit, tab_f,
                      preferred_element_type=jnp.float32)  # (rb, k_pad)
        h = jnp.sum(hit, axis=1)  # (rb,) 1 when slot t in window
        eq = nbr[:, :, None] == own_k[:, None, :]  # (rb, k_pad, k)
        cnt = jnp.sum(eq.astype(jnp.float32), axis=(1, 2))
        inter = inter.at[:, t].set(jnp.where(h > 0, cnt, 0.0))
        vj = vj.at[:, t].set(
            jnp.where(h > 0, jnp.sum((nbr >= 0).astype(jnp.float32),
                                     axis=1), 0.0))
    return inter, vj


# ---------------------------------------------------------------------------
# knn_matvec — banded Pallas kernel + blocked-XLA twin
# ---------------------------------------------------------------------------


def _matvec_kernel(idx_ref, w_ref, x_ref, out_ref, acc, *, k: int,
                   rb: int, cb: int, halo: int, n_blocks: int):
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    raw = i + j - halo  # unclamped window block; the index_map clamps,
    # so out-of-range sweep steps must contribute NOTHING (the clamped
    # edge blocks would otherwise be double-counted)
    in_range = (raw >= 0) & (raw < n_blocks)

    @pl.when(in_range)
    def _():
        cj = jnp.clip(raw, 0, n_blocks - 1)
        idx_blk = idx_ref[:]
        w_blk = jnp.where(idx_blk < 0, 0.0, w_ref[:])
        W = _local_edge_weights(idx_blk, w_blk, cj * cb, cb, k)
        acc[:] += jnp.dot(W, x_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        out_ref[:] = acc[:]


def _rmatvec_kernel(idx_ref, w_ref, x_ref, out_ref, acc, *, k: int,
                    rb: int, cb: int, halo: int, n_blocks: int):
    """Transposed accumulation: output block j collects
    ``W_localᵀ @ x_rows`` from every row block within the band —
    the segment-sum expressed as the adjoint of the one-hot matmul."""
    j = pl.program_id(0)  # output (column) block
    s = pl.program_id(1)  # sweep over contributing row blocks

    @pl.when(s == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    raw = j + s - halo
    in_range = (raw >= 0) & (raw < n_blocks)

    @pl.when(in_range)
    def _():
        idx_blk = idx_ref[:]
        w_blk = jnp.where(idx_blk < 0, 0.0, w_ref[:])
        W = _local_edge_weights(idx_blk, w_blk, j * cb, cb, k)
        acc[:] += jnp.dot(W.T, x_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(s == pl.num_programs(1) - 1)
    def _():
        out_ref[:] = acc[:]


@functools.partial(
    jax.jit,
    static_argnames=("k", "n", "d", "block", "halo", "transpose",
                     "interpret"))
def _pallas_matvec_jit(idx, w, x, *, k: int, n: int, d: int,
                       block: int, halo: int, transpose: bool,
                       interpret: bool):
    n_blocks = -(-n // block)
    n_pad = n_blocks * block
    k_pad = round_up(k, config.lane)
    d_pad = round_up(d, config.lane)
    idx_p = jnp.full((n_pad, k_pad), -1, jnp.int32).at[:n, :k].set(
        idx.astype(jnp.int32))
    w_p = jnp.zeros((n_pad, k_pad), jnp.float32).at[:n, :k].set(
        w.astype(jnp.float32))
    x_p = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :d].set(
        x.astype(jnp.float32))
    band = min(2 * halo + 1, 2 * (n_blocks - 1) + 1)
    kernel = functools.partial(
        _rmatvec_kernel if transpose else _matvec_kernel,
        k=k, rb=block, cb=block, halo=halo, n_blocks=n_blocks)

    def swept(a, b):
        # the banded window block this sweep step covers (clamped;
        # the kernel masks the out-of-range steps the clamp aliases)
        return (jnp.clip(a + b - halo, 0, n_blocks - 1), 0)

    def anchored(a, b):
        return (a, 0)

    # forward: idx/w/out ride the row block (grid dim 0), x rides the
    # swept window.  transpose: out rides the COLUMN block (grid dim
    # 0) while idx/w/x all ride the swept contributing row block.
    edge_map = anchored if not transpose else swept
    x_map = swept
    out = pl.pallas_call(
        kernel,
        grid=(n_blocks, band),
        in_specs=[
            pl.BlockSpec((block, k_pad), edge_map,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, k_pad), edge_map,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, d_pad), x_map,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block, d_pad), anchored,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, d_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block, d_pad), jnp.float32)],
        interpret=interpret,
    )(idx_p, w_p, x_p)
    return out[:n, :d]


@functools.partial(jax.jit, static_argnames=("block",))
def _matvec_blocked_xla(knn_idx, weights, x, block: int = 2048):
    """The blocked-XLA twin: ``lax.map`` over row blocks, per-block
    gather + einsum.  Bitwise identical to the legacy whole-graph
    gather (same per-row reduction order over the k slots) while the
    working set stays one (block, k, d) tile."""
    n, k = knn_idx.shape
    safe = jnp.where(knn_idx < 0, 0, knn_idx)
    w = jnp.where(knn_idx < 0, 0.0, weights.astype(jnp.float32))
    nb = -(-n // block)
    pad = nb * block - n
    if pad:
        safe = jnp.concatenate(
            [safe, jnp.zeros((pad, k), safe.dtype)])
        w = jnp.concatenate([w, jnp.zeros((pad, k), w.dtype)])

    def per_block(args):
        s, wb = args
        g = jnp.take(x, s, axis=0)  # (block, k, d)
        return jnp.einsum("nk,nkd->nd", wb, g,
                          precision=jax.lax.Precision.HIGHEST)

    out = jax.lax.map(per_block, (safe.reshape(nb, block, k),
                                  w.reshape(nb, block, k)))
    return out.reshape(-1, x.shape[-1])[:n]


def matvec(knn_idx, weights, x, *, band_rows: int | None = None,
           block: int | None = None, impl: str | None = None):
    """``P @ x`` on the (n, k) edge list through the tiled family.

    ``band_rows``: the reordered graph's bandwidth (``graph.reorder``
    records it in ``uns['graph_bandwidth']``) — bounds the Pallas
    banded sweep; ``None`` sweeps the whole table (correct for any
    layout).  The blocked-XLA twin and the legacy gather ignore it
    (their gathers are already position-independent).

    ``impl`` pins the implementation explicitly.  ``None`` resolves
    the config at TRACE time — callers that wrap this in their own
    ``jax.jit`` must thread ``resolved_impl()`` through a STATIC arg
    instead (as ``diffusion_eigs``/``stationary_arrays``/
    ``fate_probs_arrays``/``tsne_layout_arrays`` do), or a later
    ``configure(graph_impl=...)``/escape-hatch flip is silently
    ignored by their already-cached traces."""
    impl = impl or resolved_impl()
    n, k = knn_idx.shape
    d = x.shape[-1]
    _count("matvec", impl)
    if impl == "gather":
        from .graph import _knn_matvec_gather

        return _knn_matvec_gather(knn_idx, weights, x)
    if impl == "xla":
        return _matvec_blocked_xla(knn_idx, weights, x,
                                   block=_xla_block(block))
    blk = _pallas_block(block)
    n_blocks = -(-n // blk)
    return _pallas_matvec_jit(
        knn_idx, weights, x, k=k, n=n, d=d, block=blk,
        halo=_band_blocks(band_rows, blk, n_blocks), transpose=False,
        interpret=config.interpret_mode())


def rmatvec(knn_idx, weights, x, n: int | None = None, *,
            band_rows: int | None = None, block: int | None = None,
            impl: str | None = None):
    """``Pᵀ @ x`` (the segment-sum adjoint) through the tiled family.
    The xla/gather impls share the legacy segment-sum path (its
    (n, k, d) intermediate is small for the d=1..T callers); the
    Pallas path runs the transposed banded kernel.  ``impl`` as in
    :func:`matvec`."""
    impl = impl or resolved_impl()
    nn = n if n is not None else x.shape[0]
    if impl == "pallas" and nn != knn_idx.shape[0]:
        impl = "xla"  # rectangular rmatvec stays on the legacy path
    _count("rmatvec", impl)
    if impl in ("gather", "xla"):
        from .graph import _knn_rmatvec_segsum

        return _knn_rmatvec_segsum(knn_idx, weights, x, n=nn)
    blk = _pallas_block(block)
    n_blocks = -(-nn // blk)
    return _pallas_matvec_jit(
        knn_idx, weights, x, k=knn_idx.shape[1], n=nn, d=x.shape[-1],
        block=blk, halo=_band_blocks(band_rows, blk, n_blocks),
        transpose=True, interpret=config.interpret_mode())


def _pallas_block(block: int | None) -> int:
    b = block or min(config.row_block, 256)
    return round_up(max(b, config.sublane), config.sublane)


def _xla_block(block: int | None) -> int:
    return block or min(config.row_block * 2, 2048)


# ---------------------------------------------------------------------------
# jaccard — banded Pallas kernel + slot-loop XLA twin
# ---------------------------------------------------------------------------


def _slot_match_counts(tab, safe, own, k: int):
    """Per-slot neighbour-list match/valid counts: for each slot t,
    gather neighbour t's list and count matches against the row's own
    list — k passes over (block, k, k) tiles instead of one
    (block, k, k, k) mask (the legacy ``jaccard_arrays`` shape).  The
    smaller intermediate is the entire win: measured 1.86x on the
    CPU CI box at 32k rows, exact-equal results."""
    inter = jnp.zeros(safe.shape, jnp.int32)
    vj = jnp.zeros(safe.shape, jnp.int32)
    for t in range(k):
        nbr_t = jnp.take(tab, safe[:, t], axis=0)    # (block, k)
        eq = nbr_t[:, :, None] == own[:, None, :]    # (block, k, k)
        inter = inter.at[:, t].set(jnp.sum(eq, axis=(1, 2)))
        vj = vj.at[:, t].set(jnp.sum(nbr_t >= 0, axis=1))
    return inter, vj


@functools.partial(jax.jit, static_argnames=("block",))
def _jaccard_slotloop_xla(knn_idx, block: int = 1024):
    """The blocked-XLA jaccard twin: same row tiling and sentinel
    scheme as the legacy ``graph.jaccard_arrays``, with the k³
    equality mask restructured into k cache-resident (block, k, k)
    passes.  Counts are exact integers — results are identical."""
    n, k = knn_idx.shape
    tab = jnp.concatenate(
        [jnp.where(knn_idx < 0, -2, knn_idx),
         jnp.full((1, k), -2, knn_idx.dtype)])
    nb = -(-n // block)
    pad = nb * block - n
    idx_p = (jnp.concatenate(
        [knn_idx, jnp.full((pad, k), -1, knn_idx.dtype)])
        if pad else knn_idx)

    def per_block(iblk):  # (block, k)
        own = jnp.where(iblk < 0, -3, iblk)
        safe = jnp.where(iblk < 0, n, iblk)
        inter, vj = _slot_match_counts(tab, safe, own, k)
        vi = jnp.sum(iblk >= 0, axis=1).astype(jnp.float32)
        interf = inter.astype(jnp.float32)
        union = vi[:, None] + vj.astype(jnp.float32) - interf
        return jnp.where(iblk < 0, 0.0,
                         interf / jnp.maximum(union, 1.0))

    out = jax.lax.map(per_block, idx_p.reshape(nb, block, k))
    return out.reshape(-1, k)[:n]


def _jaccard_kernel(idx_ref, own_ref, tab_ref, out_ref, acc_i, acc_j,
                    *, k: int, rb: int, cb: int, halo: int,
                    n_blocks: int):
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc_i[:] = jnp.zeros_like(acc_i)
        acc_j[:] = jnp.zeros_like(acc_j)

    raw = i + j - halo
    in_range = (raw >= 0) & (raw < n_blocks)

    @pl.when(in_range)
    def _():
        cj = jnp.clip(raw, 0, n_blocks - 1)
        inter, vj = _window_match_counts(
            idx_ref[:], own_ref[:], tab_ref[:], cj * cb, cb, k)
        acc_i[:] += inter
        acc_j[:] += vj

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        idx_blk = idx_ref[:]
        vi = jnp.sum((idx_blk >= 0).astype(jnp.float32), axis=1,
                     keepdims=True)
        union = vi + acc_j[:] - acc_i[:]
        out_ref[:] = jnp.where(idx_blk < 0, 0.0,
                               acc_i[:] / jnp.maximum(union, 1.0))


@functools.partial(jax.jit,
                   static_argnames=("k", "n", "block", "halo",
                                    "interpret"))
def _pallas_jaccard_jit(knn_idx, *, k: int, n: int, block: int,
                        halo: int, interpret: bool):
    n_blocks = -(-n // block)
    n_pad = n_blocks * block
    k_pad = round_up(k, config.lane)
    idx_p = jnp.full((n_pad, k_pad), -1, jnp.int32).at[:n, :k].set(
        knn_idx.astype(jnp.int32))
    # own-list padding -3, table padding -2: the two sentinel families
    # can never match each other or a real id (same scheme as the
    # legacy jaccard_arrays)
    own = jnp.where(idx_p < 0, -3, idx_p)
    tab = jnp.where(idx_p < 0, -2, idx_p)
    band = min(2 * halo + 1, 2 * (n_blocks - 1) + 1)
    kernel = functools.partial(_jaccard_kernel, k=k, rb=block,
                               cb=block, halo=halo, n_blocks=n_blocks)
    out = pl.pallas_call(
        kernel,
        grid=(n_blocks, band),
        in_specs=[
            pl.BlockSpec((block, k_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, k_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, k_pad),
                         lambda i, j: (jnp.clip(i + j - halo, 0,
                                                n_blocks - 1), 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block, k_pad), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, k_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block, k_pad), jnp.float32),
                        pltpu.VMEM((block, k_pad), jnp.float32)],
        interpret=interpret,
    )(idx_p, own, tab)
    return out[:n, :k]


def jaccard(knn_idx, *, block: int | None = None,
            band_rows: int | None = None, impl: str | None = None):
    """Per-edge neighbour-set Jaccard through the tiled family:
    ``"gather"`` = the legacy one-shot (block, k, k, k) equality mask
    (``graph.jaccard_arrays``), ``"xla"`` = the slot-loop twin (k
    cache-resident (block, k, k) passes — measured 1.86x on the CPU
    CI box), ``"pallas"`` = the banded one-hot kernel.  Counts are
    small exact integers on every path, so results are identical."""
    impl = impl or resolved_impl()
    n = knn_idx.shape[0]
    if impl == "pallas" and n >= _MAX_EXACT_F32_ID:
        impl = "xla"
    _count("jaccard", impl)
    if impl == "gather":
        from .graph import jaccard_arrays

        return jaccard_arrays(knn_idx, block=block or 1024)
    if impl == "xla":
        return _jaccard_slotloop_xla(knn_idx, block=block or 1024)
    blk = _pallas_block(block)
    n_blocks = -(-n // blk)
    return _pallas_jaccard_jit(
        knn_idx, k=knn_idx.shape[1], n=n, block=blk,
        halo=_band_blocks(band_rows, blk, n_blocks),
        interpret=config.interpret_mode())


# ---------------------------------------------------------------------------
# t-SNE repulsion — all-pairs tile sweep as one kernel
# ---------------------------------------------------------------------------


def _tsne_rep_kernel(yq_ref, yc_ref, out_ref, acc, *, dim: int,
                     rb: int, cb: int, n: int):
    """One (rb, cb) tile of the exact t-SNE repulsion: the Student-t
    kernel W against this column block (one MXU matmul for the cross
    term), the force factorisation ``y_i·ΣW² − W²·Y`` (second
    matmul), and the Z row-sum — fused so the (rb, cb) score tile
    never leaves VMEM.  Output layout: columns [0, dim) carry the
    force, column dim carries the Z row-sum (self-pair excluded)."""
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    yq = yq_ref[:]  # (rb, d_pad) — zero beyond dim
    yc = yc_ref[:]  # (cb, d_pad)
    grow = i * rb + jax.lax.broadcasted_iota(jnp.int32, (rb, cb), 0)
    gcol = j * cb + jax.lax.broadcasted_iota(jnp.int32, (rb, cb), 1)
    s = jnp.dot(yq, yc.T, preferred_element_type=jnp.float32)
    qn = jnp.sum(yq * yq, axis=1)[:, None]
    cn = jnp.sum(yc * yc, axis=1)[None, :]
    d2 = jnp.maximum(qn - 2.0 * s + cn, 0.0)
    w = 1.0 / (1.0 + d2)
    # padding rows/cols and the self pair carry no repulsion mass
    valid = (grow < n) & (gcol < n) & (grow != gcol)
    w = jnp.where(valid, w, 0.0)
    w2 = w * w
    f = (yq * jnp.sum(w2, axis=1)[:, None]
         - jnp.dot(w2, yc, preferred_element_type=jnp.float32))
    zrow = jnp.sum(w, axis=1)
    upd = f.at[:, dim].set(f[:, dim] + zrow)
    acc[:] += upd

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        out_ref[:] = acc[:]


@functools.partial(jax.jit,
                   static_argnames=("n", "dim", "block", "interpret"))
def _pallas_tsne_repulsion_jit(y, *, n: int, dim: int, block: int,
                               interpret: bool):
    n_blocks = -(-n // block)
    n_pad = n_blocks * block
    d_pad = round_up(dim + 1, config.lane)  # +1: the Z column
    y_p = jnp.zeros((n_pad, d_pad), jnp.float32).at[:n, :dim].set(
        y.astype(jnp.float32))
    kernel = functools.partial(_tsne_rep_kernel, dim=dim, rb=block,
                               cb=block, n=n)
    out = pl.pallas_call(
        kernel,
        grid=(n_blocks, n_blocks),
        in_specs=[
            pl.BlockSpec((block, d_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, d_pad), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block, d_pad), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, d_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block, d_pad), jnp.float32)],
        interpret=interpret,
    )(y_p, y_p)
    f = out[:n, :dim]
    z = jnp.maximum(jnp.sum(out[:n, dim]), 1e-12)
    return f, z


def tsne_repulsion(y, n: int, *, block: int | None = None,
                   impl: str | None = None):
    """Exact all-pairs t-SNE repulsion ``(forces (n, d), Z)`` through
    the tiled family, or ``None`` when the resolved impl is not
    ``"pallas"`` — the caller (ops/tsne.py) then keeps its blocked
    ``lax.map`` two-matmul sweep, which IS the xla twin of this
    kernel."""
    impl = impl or resolved_impl()
    if impl != "pallas":
        return None
    _count("tsne_repulsion", impl)
    # VMEM budget caps the tile edge: the kernel holds several
    # (rb, cb) f32 intermediates (s, d2, w, w2) live at once, so a
    # 2048-edge tile (~16.8 MB EACH) cannot fit — 512 keeps the live
    # set at a few MB.  Callers' larger `block` values are XLA-twin
    # row-tile sizes, not VMEM shapes; clamp rather than trust them.
    blk = _pallas_block(min(block or 512, 512))
    return _pallas_tsne_repulsion_jit(
        y, n=n, dim=y.shape[1], block=blk,
        interpret=config.interpret_mode())


# ---------------------------------------------------------------------------
# gather_rows — the blocked row-gather member of the family
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("block",))
def _gather_rows_blocked(x, idx, block: int = 2048):
    n = idx.shape[0]
    k = idx.shape[1]
    nb = -(-n // block)
    pad = nb * block - n
    idx_p = (jnp.concatenate([idx, jnp.zeros((pad, k), idx.dtype)])
             if pad else idx)
    out = jax.lax.map(lambda s: jnp.take(x, s, axis=0),
                      idx_p.reshape(nb, block, k))
    return out.reshape((-1, k) + x.shape[1:])[:n]


def gather_rows(x, idx, *, block: int | None = None):
    """``x[idx]`` for an (n, k) int index matrix, row-block tiled so
    the (n, k, d) result streams through (block, k, d) working sets
    (the epoch-loop gathers in embed.umap / embed.tsne / the Palantir
    directed chain).  ``idx`` must be pre-clamped non-negative.  The
    legacy ``"gather"`` impl is the plain whole-array take."""
    if resolved_impl() == "gather":
        return jnp.take(x, idx, axis=0)
    return _gather_rows_blocked(x, idx, block=_xla_block(block))
