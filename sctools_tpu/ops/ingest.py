"""``integrate.ingest`` — map query cells onto a reference atlas.

Capability parity: scanpy's ``tl.ingest`` (the reference source at
/root/reference was empty — SURVEY.md §0; the behavioral contract here
is the public scanpy operation): fit nothing on the query, instead
project it into the reference's fitted PCA space, find each query
cell's k nearest reference cells there, then

* transfer categorical ``obs`` columns by distance-weighted majority
  vote,
* transfer numeric ``obs`` columns and reference ``obsm`` embeddings
  (e.g. ``X_umap``) by distance-weighted averaging.

TPU design: the two heavy stages — the centered projection
``(Xq − μ) @ PCs`` (one spmm on the MXU) and the blocked kNN search —
run on device via the existing ``spmm``/``knn_arrays`` machinery; the
O(n_query × k) vote/average bookkeeping is host numpy (it is three
orders of magnitude smaller than the search and data-dependent on
category alphabets, which jit cannot trace).

The query must be preprocessed identically to the reference (same
normalize/log1p chain, same gene space) — same contract as scanpy's
ingest, which refuses mismatched ``var_names``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells, spmm
from ..registry import register


def _weights(dist: np.ndarray, eps: float = 1e-12) -> np.ndarray:
    """Inverse-distance weights, rows normalised to 1.  An exact hit
    (dist 0) gets all the mass of its row via the eps floor."""
    w = 1.0 / np.maximum(dist.astype(np.float64), eps)
    return w / w.sum(axis=1, keepdims=True)


def _transfer(ref_obs, ref_obsm, obs, embeddings, idx, dist, n_query):
    """Host-side vote/average given fetched neighbor (idx, dist)."""
    idx = np.asarray(idx)[:n_query]
    dist = np.asarray(dist)[:n_query]
    w = _weights(dist)
    new_obs: dict = {}
    for col in obs:
        if col not in ref_obs:
            raise KeyError(f"ingest: obs column {col!r} not in reference")
        vals = np.asarray(ref_obs[col])
        if vals.dtype.kind in "ifu":
            new_obs[col] = (w * vals[idx].astype(np.float64)).sum(axis=1)
        else:
            levels, codes = np.unique(vals, return_inverse=True)
            votes = np.zeros((len(idx), len(levels)), np.float64)
            rows = np.repeat(np.arange(len(idx)), idx.shape[1])
            np.add.at(votes, (rows, codes[idx].ravel()), w.ravel())
            win = votes.argmax(axis=1)
            new_obs[col] = levels[win]
            new_obs[f"{col}_confidence"] = votes[
                np.arange(len(idx)), win]
    new_obsm: dict = {}
    for emb in embeddings:
        if emb not in ref_obsm or emb == "X_pca":
            # scanpy parity: only transfer what the ref has; X_pca is
            # always produced by the projection itself, never by
            # neighbor interpolation
            continue
        E = np.asarray(ref_obsm[emb])[:, :]
        new_obsm[emb] = np.einsum("qk,qkd->qd", w, E[idx])
    return new_obs, new_obsm


def _check(query: CellData, ref: CellData):
    if query.n_genes != ref.n_genes:
        raise ValueError(
            f"ingest: query has {query.n_genes} genes but reference has "
            f"{ref.n_genes} — align var spaces first (same contract as "
            "scanpy tl.ingest)")
    qn, rn = query.var.get("gene_name"), ref.var.get("gene_name")
    if qn is not None and rn is not None:
        qn, rn = np.asarray(qn), np.asarray(rn)
        if qn.shape == rn.shape and not (qn == rn).all():
            bad = int(np.argmin(qn == rn))
            raise ValueError(
                "ingest: query/reference gene names differ (first "
                f"mismatch at {bad}: {qn[bad]!r} vs {rn[bad]!r}) — a "
                "same-width projection onto mismatched loadings would "
                "transfer confidently-wrong labels")
    if "PCs" not in ref.varm or "X_pca" not in ref.obsm:
        raise ValueError(
            "ingest: reference needs varm['PCs'] + obsm['X_pca'] — run "
            "pca.randomized on it first")


@register("integrate.ingest", backend="tpu")
def ingest_tpu(query: CellData, *, ref: CellData,
               obs: tuple | list = (), embeddings=("X_umap",),
               k: int = 15, metric: str = "cosine",
               refine: int = 64) -> CellData:
    """Returns ``query`` with transferred obs columns (categoricals add
    a ``<col>_confidence`` sibling), obsm["X_pca"] in the reference's
    space, and any requested reference embeddings interpolated."""
    from .knn import knn_arrays

    _check(query, ref)
    PCs = jnp.asarray(ref.varm["PCs"], jnp.float32)
    mu = jnp.asarray(ref.uns.get("pca_mean", np.zeros(ref.n_genes)),
                     jnp.float32)
    Xq = query.X
    if isinstance(Xq, SparseCells):
        scores = spmm(Xq, PCs) - (mu @ PCs)[None, :]
        scores = jnp.where(Xq.row_mask()[:, None], scores, 0.0)
    else:
        scores = (jnp.asarray(Xq, jnp.float32) - mu[None, :]) @ PCs
    ref_scores = jnp.asarray(ref.obsm["X_pca"], jnp.float32)
    n_q = query.n_cells
    idx, dist = knn_arrays(scores, ref_scores, k=k, metric=metric,
                           n_query=n_q, n_cand=ref.n_cells, refine=refine)
    new_obs, new_obsm = _transfer(ref.obs, ref.obsm, obs, embeddings,
                                  idx, dist, n_q)
    out = query.with_obsm(X_pca=scores[:n_q], **new_obsm)
    return out.with_obs(**new_obs)


@register("integrate.ingest", backend="cpu")
def ingest_cpu(query: CellData, *, ref: CellData,
               obs: tuple | list = (), embeddings=("X_umap",),
               k: int = 15, metric: str = "cosine",
               refine: int = 64) -> CellData:
    import scipy.sparse as sp

    from .knn import knn_numpy

    _check(query, ref)
    PCs = np.asarray(ref.varm["PCs"], np.float64)
    mu = np.asarray(ref.uns.get("pca_mean", np.zeros(ref.n_genes)),
                    np.float64)
    Xq = query.X
    if sp.issparse(Xq):
        scores = Xq @ PCs - (mu @ PCs)[None, :]
    else:
        scores = (np.asarray(Xq, np.float64) - mu) @ PCs
    idx, dist = knn_numpy(scores, np.asarray(ref.obsm["X_pca"],
                                             np.float64),
                          k=k, metric=metric)
    new_obs, new_obsm = _transfer(ref.obs, ref.obsm, obs, embeddings,
                                  idx, dist, query.n_cells)
    out = query.with_obsm(X_pca=np.asarray(scores), **new_obsm)
    return out.with_obs(**new_obs)
