"""``metrics.morans_i`` / ``metrics.gearys_c`` — spatial/graph
autocorrelation per gene.

Capability parity: scanpy ``sc.metrics.morans_i`` and
``sc.metrics.gearys_c`` (reference source unavailable — SURVEY.md §0;
the public formulas are the contract), computed over the kNN
connectivities graph this framework already builds:

* Moran's I_g  = (n / S0) · Σ_i z_i (Wz)_i / Σ_i z_i²
* Geary's C_g = ((n−1) / 2S0) · Σ_ij w_ij (x_i − x_j)² / Σ_i z_i²

with z the per-gene centered values and S0 = Σ w_ij.  The pair term
expands to matvecs — Σ_ij w_ij (x_i−x_j)² = Σ_i r_i x_i² + Σ_j c_j x_j²
− 2 Σ_i x_i (Wx)_i with r/c the row/col weight sums — so both metrics
are three k-sparse gather-matvecs over a (n, G_chunk) value block,
chunked across genes.  No (n, n) object, no scatter.

Accepts dense X, a layer, or an obsm basis via ``use_rep``; sparse X
is densified per gene-chunk only.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells
from ..registry import register

_GCHUNK = 256  # (n, k, chunk) gather tile stays modest at atlas n


def _edge_arrays(data: CellData, xp):
    if "knn_indices" not in data.obsp:
        raise KeyError("metrics: run neighbors.knn (+ "
                       "graph.connectivities) first")
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    if "connectivities" in data.obsp:
        w = np.asarray(data.obsp["connectivities"], np.float64)[:n]
    else:
        w = np.ones_like(idx, np.float64)
    w = np.where(idx >= 0, w, 0.0)
    return idx, w


def _resolve_values(data: CellData, use_rep):
    """Pick the value matrix once per call; host scipy matrices are
    converted to CSC a single time here so the per-chunk column slices
    below don't redo an O(nnz) conversion per 256-gene chunk."""
    if use_rep == "X":
        M = data.X
    else:
        M = data.layers.get(use_rep, data.obsm.get(use_rep))
        if M is None:
            raise KeyError(f"metrics: no layer/obsm named {use_rep!r}")
    if not isinstance(M, SparseCells) and hasattr(M, "tocsc"):
        M = M.tocsc()
    return M


def _values_chunk(M, n, lo, hi, xp):
    if isinstance(M, SparseCells):
        from .hvg import subset_genes_sparse

        return subset_genes_sparse(M, np.arange(lo, hi)).to_dense()[:n]
    if hasattr(M, "tocsc"):  # scipy sparse, already CSC
        return np.asarray(M[:, lo:hi].todense(), np.float64)
    return xp.asarray(M)[:n, lo:hi]


@partial(jax.jit, static_argnames=("graph_impl",))
def _auto_terms(idx, w, Xc, colsum_w, graph_impl: str | None = None):
    """Per gene: (num_moran, num_geary, denom) for one value block.
    The edge sums ride graph.knn_matvec (gather-weight-sum; weights
    already zeroed on -1 slots by the caller).  ``graph_impl``
    (static) pins the tiled-family impl so config flips re-key this
    jit's cache."""
    from .graph import knn_matvec

    z = Xc - jnp.mean(Xc, axis=0, keepdims=True)
    Wz = knn_matvec(idx, w, z, impl=graph_impl)
    num_i = jnp.sum(z * Wz, axis=0)
    r = jnp.sum(w, axis=1)
    Wx = knn_matvec(idx, w, Xc, impl=graph_impl)
    num_c = (jnp.sum(r[:, None] * Xc * Xc, axis=0)
             + jnp.sum(colsum_w[:, None] * Xc * Xc, axis=0)
             - 2.0 * jnp.sum(Xc * Wx, axis=0))
    denom = jnp.sum(z * z, axis=0)
    return num_i, num_c, denom


def _metrics(data: CellData, use_rep, device):
    idx, w = _edge_arrays(data, np)
    n = len(idx)
    S0 = float(w.sum())
    colsum = np.zeros(n)
    np.add.at(colsum, np.where(idx >= 0, idx, 0).ravel(),
              w.ravel())
    G = (data.n_genes if use_rep == "X" or use_rep in data.layers
         else np.asarray(data.obsm[use_rep]).shape[1])
    mor = np.zeros(G)
    gea = np.zeros(G)
    if device:
        idx_d = jnp.asarray(idx)
        w_d = jnp.asarray(w, jnp.float32)
        cs_d = jnp.asarray(colsum, jnp.float32)
    M = _resolve_values(data, use_rep)
    for lo in range(0, G, _GCHUNK):
        hi = min(G, lo + _GCHUNK)
        Xc = _values_chunk(M, data.n_cells, lo, hi,
                           jnp if device else np)
        if device:
            from .pallas_graph import resolved_impl

            ni, nc, dn = _auto_terms(idx_d, w_d,
                                     jnp.asarray(Xc, jnp.float32),
                                     cs_d,
                                     graph_impl=resolved_impl())
            ni, nc, dn = (np.asarray(a, np.float64) for a in (ni, nc, dn))
        else:
            Xc = np.asarray(Xc, np.float64)
            z = Xc - Xc.mean(axis=0, keepdims=True)
            safe = np.where(idx >= 0, idx, 0)
            Wz = np.einsum("nk,nkg->ng", w, z[safe])
            ni = (z * Wz).sum(axis=0)
            r = w.sum(axis=1)
            Wx = np.einsum("nk,nkg->ng", w, Xc[safe])
            nc = ((r[:, None] * Xc * Xc).sum(axis=0)
                  + (colsum[:, None] * Xc * Xc).sum(axis=0)
                  - 2.0 * (Xc * Wx).sum(axis=0))
            dn = (z * z).sum(axis=0)
        dn = np.maximum(dn, 1e-12)
        mor[lo:hi] = (n / S0) * ni / dn
        gea[lo:hi] = ((n - 1) / (2.0 * S0)) * nc / dn
    return mor, gea


@register("metrics.morans_i", backend="tpu")
def morans_i_tpu(data: CellData, use_rep: str = "X") -> CellData:
    """Adds var["morans_i"] (or uns["morans_i_<rep>"] for obsm reps):
    +1 = neighbours share the gene's value, 0 = noise, <0 =
    anti-correlated over the graph."""
    mor, _ = _metrics(data, use_rep, device=True)
    if use_rep == "X" or use_rep in data.layers:
        return data.with_var(morans_i=mor.astype(np.float32))
    return data.with_uns(**{f"morans_i_{use_rep}": mor})


@register("metrics.morans_i", backend="cpu")
def morans_i_cpu(data: CellData, use_rep: str = "X") -> CellData:
    mor, _ = _metrics(data, use_rep, device=False)
    if use_rep == "X" or use_rep in data.layers:
        return data.with_var(morans_i=mor.astype(np.float32))
    return data.with_uns(**{f"morans_i_{use_rep}": mor})


@register("metrics.gearys_c", backend="tpu")
def gearys_c_tpu(data: CellData, use_rep: str = "X") -> CellData:
    """Adds var["gearys_c"]: 0 = perfect positive autocorrelation over
    the graph, 1 = none, >1 = anti-correlated (complements Moran's I)."""
    _, gea = _metrics(data, use_rep, device=True)
    if use_rep == "X" or use_rep in data.layers:
        return data.with_var(gearys_c=gea.astype(np.float32))
    return data.with_uns(**{f"gearys_c_{use_rep}": gea})


@register("metrics.gearys_c", backend="cpu")
def gearys_c_cpu(data: CellData, use_rep: str = "X") -> CellData:
    _, gea = _metrics(data, use_rep, device=False)
    if use_rep == "X" or use_rep in data.layers:
        return data.with_var(gearys_c=gea.astype(np.float32))
    return data.with_uns(**{f"gearys_c_{use_rep}": gea})
