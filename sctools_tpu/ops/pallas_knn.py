"""Fused distance + top-k Pallas kernel for ``neighbors.knn``.

Reference parity: the reference framework's kNN hot loop is a custom
CUDA kernel (source unavailable — SURVEY.md §0); this is its TPU
counterpart, written against the Mosaic/Pallas TPU programming model
(/opt/skills/guides/pallas_guide.md).

Design: one grid cell per (query-block i, candidate-block j), with j
the fastest-varying grid dimension.  Each cell

1. computes the (QB, CB) similarity tile ``Q_i @ C_jᵀ`` on the MXU
   (bfloat16 inputs, float32 accumulation);
2. merges the tile into a per-query running top-k held in **VMEM
   scratch** that persists across the j sweep — a k-step selection
   loop (max + first-argmax + mask), all VPU work on 2-D tiles;
3. on the last j writes the merged (QB, K_PAD) values/indices out.

Versus the XLA path (ops/knn.py) the score tile never round-trips to
HBM and no (QB, k+CB) sort runs per tile — the merge touches each
score exactly k times in registers/VMEM.  Off-TPU the kernel runs in
interpreter mode (config.pallas_interpret), which is how the CPU test
suite exercises it; numerics are identical to the XLA path up to
matmul precision.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..config import config, round_up

_NEG = float("-inf")  # plain float: jax-array constants cannot be captured by kernels


def _score_tile(q_ref, c_ref, *, qb, cb, n_cand, metric, exclude_self,
                precision):
    """The (qb, cb) similarity tile of grid cell (i, j): MXU matmul,
    metric rewrite, candidate-range and self masks.  Shared by both
    merge kernels so mask/tie-break fixes cannot diverge.
    Returns (s, gcol)."""
    j = pl.program_id(1)
    q = q_ref[:]  # (qb, d)
    c = c_ref[:]  # (cb, d)
    s = jnp.dot(q, c.T, preferred_element_type=jnp.float32,
                precision=precision)  # MXU
    if metric == "euclidean":
        qn2 = jnp.sum(q.astype(jnp.float32) ** 2, axis=1, keepdims=True)
        cn2 = jnp.sum(c.astype(jnp.float32) ** 2, axis=1, keepdims=True)
        s = -(qn2 - 2.0 * s + cn2.T)
    col = jax.lax.broadcasted_iota(jnp.int32, (qb, cb), 1)
    gcol = j * cb + col  # (qb, cb) global candidate ids
    s = jnp.where(gcol >= n_cand, _NEG, s)
    if exclude_self:
        i = pl.program_id(0)
        grow = i * qb + jax.lax.broadcasted_iota(jnp.int32, (qb, cb), 0)
        s = jnp.where(gcol == grow, _NEG, s)
    return s, gcol


def _select_topk(A, I, k, write_v, write_i):
    """k-step selection (max + first-index + suppress) over value
    matrix ``A`` with aligned ids ``I``; emits each extracted
    (value, id) pair through the write callbacks (ties break to the
    lowest column — keep in lockstep across both kernels)."""
    qb, width = A.shape
    allcol = jax.lax.broadcasted_iota(jnp.int32, (qb, width), 1)
    big = jnp.int32(width)
    for t in range(k):
        vmax = jnp.max(A, axis=1)  # (qb,)
        sel = jnp.min(jnp.where(A >= vmax[:, None], allcol, big), axis=1)
        hit = allcol == sel[:, None]
        ival = jnp.sum(jnp.where(hit, I, 0), axis=1)
        write_v(t, vmax)
        write_i(t, jnp.where(jnp.isfinite(vmax), ival, -1))
        A = jnp.where(hit, _NEG, A)


def _knn_kernel(q_ref, c_ref, out_v_ref, out_i_ref, acc_v, acc_i, *,
                k: int, qb: int, cb: int, k_pad: int, n_cand: int,
                metric: str, exclude_self: bool, precision):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc_v[:] = jnp.full((qb, k_pad), _NEG, jnp.float32)
        acc_i[:] = jnp.full((qb, k_pad), -1, jnp.int32)

    s, gcol = _score_tile(q_ref, c_ref, qb=qb, cb=cb, n_cand=n_cand,
                          metric=metric, exclude_self=exclude_self,
                          precision=precision)

    # merge: k-step selection over the union of the running top-k and
    # the fresh tile.  Values/ids are captured before the in-place
    # scratch writes below, so the loop reads a consistent snapshot.
    A = jnp.concatenate([acc_v[:], s], axis=1)  # (qb, k_pad + cb)
    I = jnp.concatenate([acc_i[:], gcol], axis=1)
    _select_topk(A, I, k,
                 lambda t, v: acc_v.__setitem__((slice(None), t), v),
                 lambda t, i_: acc_i.__setitem__((slice(None), t), i_))

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        out_v_ref[:] = acc_v[:]
        out_i_ref[:] = acc_i[:]


def _knn_kernel_binned(q_ref, c_ref, out_v_ref, out_i_ref, acc_v, acc_i,
                       *, k: int, qb: int, cb: int, k_pad: int,
                       n_bins: int, n_cand: int, metric: str,
                       exclude_self: bool, precision):
    """Binned-approximate merge (the TPU-KNN shape): the accumulator
    holds ONE candidate per bin (bin = column position mod n_bins), so
    the per-tile merge is a reshape-max plus an elementwise running
    max — no k-step selection until the very last tile.  Two global
    top-k candidates land in one bin with probability ~k²/(2·n_bins),
    losing the weaker one: that is the approximation, the same
    trade `lax.approx_max_k` makes, tunable via n_bins and recovered
    downstream by the refine re-rank's wider search."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc_v[:] = jnp.full((qb, n_bins), _NEG, jnp.float32)
        acc_i[:] = jnp.full((qb, n_bins), -1, jnp.int32)

    s, _gcol = _score_tile(q_ref, c_ref, qb=qb, cb=cb, n_cand=n_cand,
                           metric=metric, exclude_self=exclude_self,
                           precision=precision)

    # per-bin max of this tile: (qb, cb) -> (qb, cb//n_bins, n_bins)
    folds = cb // n_bins
    s3 = s.reshape(qb, folds, n_bins)
    tile_max = jnp.max(s3, axis=1)  # (qb, n_bins)
    # index of that max: first fold achieving it, bin-local -> global
    fold_iota = jax.lax.broadcasted_iota(jnp.int32, (qb, folds, n_bins), 1)
    hit = s3 >= tile_max[:, None, :]
    fold_sel = jnp.min(jnp.where(hit, fold_iota, jnp.int32(folds)), axis=1)
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (qb, n_bins), 1)
    tile_idx = j * cb + fold_sel * n_bins + bin_iota

    better = tile_max > acc_v[:]
    acc_v[:] = jnp.where(better, tile_max, acc_v[:])
    acc_i[:] = jnp.where(better & jnp.isfinite(tile_max), tile_idx,
                         acc_i[:])

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        # exact top-k extraction over the n_bins survivors, once
        _select_topk(
            acc_v[:], acc_i[:], k,
            lambda t, v: out_v_ref.__setitem__((slice(None), t), v),
            lambda t, i_: out_i_ref.__setitem__((slice(None), t), i_))
        if k_pad > k:  # lane padding past the real k, in one store
            out_v_ref[:, k:] = jnp.full((qb, k_pad - k), _NEG,
                                        jnp.float32)
            out_i_ref[:, k:] = jnp.full((qb, k_pad - k), -1,
                                        jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "n_query", "n_cand", "qb", "cb",
                     "mm_dtype", "exclude_self", "interpret", "lane",
                     "merge", "n_bins"),
)
def _pallas_knn_jit(query, cand, *, k, metric, n_query, n_cand, qb, cb,
                    mm_dtype, exclude_self, interpret, lane,
                    merge="select", n_bins=512):
    from .knn import _prep

    mm_dtype = jnp.dtype(mm_dtype)
    d_pad = round_up(query.shape[1], lane)
    nq_pad = round_up(n_query, qb)
    nc_pad = round_up(n_cand, cb)
    k_pad = round_up(k, lane)

    q = jnp.zeros((nq_pad, d_pad), jnp.float32)
    q = q.at[: query.shape[0], : query.shape[1]].set(
        query.astype(jnp.float32))
    c = jnp.zeros((nc_pad, d_pad), jnp.float32)
    c = c.at[: cand.shape[0], : cand.shape[1]].set(cand.astype(jnp.float32))
    q = _prep(q, metric, mm_dtype)
    c = _prep(c, metric, mm_dtype)

    grid = (nq_pad // qb, nc_pad // cb)
    # float32 inputs need HIGHEST or the MXU drops to bf16 passes
    # (same convention as ops/knn.py)
    precision = (jax.lax.Precision.HIGHEST if mm_dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    if merge == "binned":
        kernel = functools.partial(
            _knn_kernel_binned, k=k, qb=qb, cb=cb, k_pad=k_pad,
            n_bins=n_bins, n_cand=n_cand, metric=metric,
            exclude_self=exclude_self, precision=precision)
        scratch = [pltpu.VMEM((qb, n_bins), jnp.float32),
                   pltpu.VMEM((qb, n_bins), jnp.int32)]
    else:
        kernel = functools.partial(
            _knn_kernel, k=k, qb=qb, cb=cb, k_pad=k_pad, n_cand=n_cand,
            metric=metric, exclude_self=exclude_self, precision=precision)
        scratch = [pltpu.VMEM((qb, k_pad), jnp.float32),
                   pltpu.VMEM((qb, k_pad), jnp.int32)]
    vals, idxs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((qb, d_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((cb, d_pad), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((qb, k_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((qb, k_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nq_pad, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((nq_pad, k_pad), jnp.int32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, c)
    vals = vals[:, :k]
    idxs = idxs[:, :k]
    dists = (1.0 - vals) if metric == "cosine" else jnp.sqrt(
        jnp.maximum(-vals, 0.0))
    qvalid = jnp.arange(nq_pad) < n_query
    idxs = jnp.where(qvalid[:, None], idxs, -1)
    return idxs, dists


def pallas_knn_arrays(query, cand, *, k: int = 15, metric: str = "cosine",
                      n_query: int | None = None, n_cand: int | None = None,
                      query_block: int | None = None,
                      cand_block: int | None = None,
                      exclude_self: bool = False,
                      merge: str = "select", n_bins: int = 512):
    """Drop-in counterpart of ``knn.knn_arrays`` (coarse search only —
    compose with ``knn._refine_jit`` for the exact re-rank).

    ``merge``: "select" (exact k-step selection per tile, the
    default) or "binned" (one-candidate-per-bin running max — ~k× less
    VPU work per tile, approximate: two true top-k in one of the
    ``n_bins`` bins lose the weaker, P ≈ k²/2·n_bins per query; exact
    whenever ``n_cand <= n_bins`` since every candidate then owns its
    bin)."""
    if metric not in ("cosine", "euclidean"):
        raise ValueError(f"unknown metric {metric!r}")
    if merge not in ("select", "binned"):
        raise ValueError(f"unknown merge {merge!r}")
    n_query = n_query or query.shape[0]
    n_cand = n_cand or cand.shape[0]
    # Mosaic requires VMEM tiles aligned to the (sublane, lane) grid:
    # round user-supplied block sizes up to the f32 tile multiples
    # instead of handing an unaligned BlockSpec to the compiler.
    qb = query_block or min(config.row_block, 256)
    cb = cand_block or min(config.col_block, 1024)
    qb = round_up(max(qb, config.sublane), config.sublane)
    cb = round_up(max(cb, config.lane), config.lane)
    if merge == "binned":
        if k > n_bins:
            raise ValueError(f"k={k} > n_bins={n_bins}")
        n_bins = round_up(n_bins, config.lane)
        cb = round_up(cb, n_bins)  # the fold reshape needs cb % n_bins == 0
    return _pallas_knn_jit(
        query, cand, k=k, metric=metric, n_query=n_query, n_cand=n_cand,
        qb=qb, cb=cb,
        mm_dtype=str(jnp.dtype(config.matmul_dtype)),
        exclude_self=exclude_self,
        interpret=config.interpret_mode(),
        lane=config.lane,
        merge=merge, n_bins=n_bins,
    )
