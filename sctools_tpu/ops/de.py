"""Differential expression: ``de.rank_genes_groups``.

Scanpy-parity surface (``tl.rank_genes_groups``) for the two standard
methods, built TPU-first:

* ``t-test`` (Welch): per-group gene means/variances come from ONE
  ``Xᵀ @ onehot`` pass — on the padded-ELL layout that is
  ``spmm_t(X, G)`` + ``spmm_t(X², G)`` (chunked segment-sums), on
  dense X two MXU matmuls.  No per-group loop over the data.
* ``wilcoxon`` (Mann-Whitney U, normal approximation with tie
  correction): per-gene average ranks are computed by a vmapped
  sort + double ``searchsorted`` (O(n log n) per gene, static
  shapes) over gene blocks of static width (memory-bounded — the
  full dense matrix never materialises), then per-group rank sums
  are exact ``segment_sum`` reductions (NOT one-hot MXU matmuls,
  whose bf16 passes corrupt rank-magnitude sums).

P-values (t / normal survival functions) and BH adjustment are tiny
(n_groups × n_genes) and computed host-side with scipy — keeping
special functions off the accelerator where they don't pay.

Reference note: dpeerlab/sctools' own DE surface could not be read
(reference missing, SURVEY.md §0); this follows the scanpy semantics
its domain implies, with the CPU backend as the scipy oracle.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells, spmm, spmm_t
from ..registry import register


# ----------------------------------------------------------------------
# shared label handling
# ----------------------------------------------------------------------


def _group_codes(data: CellData, groupby: str):
    """(codes int32 (n_cells,), level names list[str])."""
    if groupby not in data.obs:
        raise KeyError(f"rank_genes_groups: obs has no key {groupby!r}; "
                       f"available: {sorted(data.obs)}")
    # per-cell obs arrays from TPU ops may carry padded rows — trim
    # before computing levels, or padding values become a bogus group
    v = np.asarray(data.obs[groupby])[: data.n_cells]
    n = v.shape[0]
    levels, codes = np.unique(v, return_inverse=True)
    return codes.astype(np.int32), [str(l) for l in levels], n


def _bh_adjust(p: np.ndarray) -> np.ndarray:
    """Benjamini-Hochberg along the last axis."""
    n = p.shape[-1]
    order = np.argsort(p, axis=-1)
    ranked = np.take_along_axis(p, order, axis=-1)
    q = ranked * n / np.arange(1, n + 1)
    q = np.minimum.accumulate(q[..., ::-1], axis=-1)[..., ::-1]
    out = np.empty_like(q)
    np.put_along_axis(out, order, np.clip(q, 0, 1), axis=-1)
    return out


def _logfoldchange(mean_g, mean_rest, base: float = 2.0):
    """scanpy's logFC convention: data is log1p-normalised, so undo the
    log, ratio the (pseudo-counted) expm1 means, re-log in base 2."""
    return (np.log(np.expm1(mean_g) + 1e-9)
            - np.log(np.expm1(mean_rest) + 1e-9)) / np.log(base)


# ----------------------------------------------------------------------
# group moments (sum / sumsq / count per group per gene)
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_groups",))
def _group_moments_dense(X, codes, n_groups):
    # segment_sum, NOT a one-hot MXU matmul: on TPU the matmul would
    # run in bf16 and ranks/counts magnitudes (~n_cells) lose integer
    # precision catastrophically.
    X = X.astype(jnp.float32)
    s = jax.ops.segment_sum(X, codes, num_segments=n_groups)
    ss = jax.ops.segment_sum(X * X, codes, num_segments=n_groups)
    cnt = jax.ops.segment_sum(jnp.ones_like(codes, jnp.float32), codes,
                              num_segments=n_groups)
    return s, ss, cnt


@partial(jax.jit, static_argnames=("n_groups", "need_ss"))
def _group_moments_sparse(x: SparseCells, codes, n_groups, need_ss=True):
    # codes padded with -1 for padding rows -> one_hot gives zero row.
    onehot = jax.nn.one_hot(codes, n_groups, dtype=x.data.dtype)
    s = spmm_t(x, onehot).T               # (g, genes)
    # the squared-data pass is skipped when only means are needed
    ss = (spmm_t(x.with_data(x.data * x.data), onehot).T
          if need_ss else jnp.zeros_like(s))
    cnt = jnp.sum(onehot, axis=0)
    return s, ss, cnt


def _group_means(s, cnt):
    """Per-group and rest means from group sums/counts alone."""
    s, cnt = np.asarray(s, np.float64), np.asarray(cnt, np.float64)
    tot_s, tot_n = s.sum(0), cnt.sum()
    n1 = np.maximum(cnt, 1.0)[:, None]
    n2 = np.maximum(tot_n - cnt, 1.0)[:, None]
    return s / n1, (tot_s[None, :] - s) / n2


def _welch_stats(s, ss, cnt, overestim_var=False, ref=None):
    """Per-group vs rest (or vs a REFERENCE group, scanpy
    ``reference=``) Welch t statistics + dfs, numpy in float64.

    ``overestim_var`` reproduces scanpy's ``t-test_overestim_var``:
    the rest-group variance is divided by the *group's* size instead
    of the rest's, deliberately overestimating the standard error.
    """
    s, ss, cnt = (np.asarray(a, np.float64) for a in (s, ss, cnt))
    tot_s, tot_ss, tot_n = s.sum(0), ss.sum(0), cnt.sum()
    t_stats, dfs, m_g, m_r = [], [], [], []
    for g in range(s.shape[0]):
        n1 = max(cnt[g], 1.0)
        if ref is None:
            n2 = max(tot_n - cnt[g], 1.0)
            s2, ss2 = tot_s - s[g], tot_ss - ss[g]
        else:
            n2 = max(cnt[ref], 1.0)
            s2, ss2 = s[ref], ss[ref]
        m1 = s[g] / n1
        m2 = s2 / n2
        v1 = np.maximum((ss[g] - n1 * m1**2) / max(n1 - 1, 1.0), 0.0)
        v2 = np.maximum((ss2 - n2 * m2**2)
                        / max(n2 - 1, 1.0), 0.0)
        n2_eff = n1 if overestim_var else n2
        se2_1, se2_2 = v1 / n1, v2 / n2_eff
        denom = np.sqrt(se2_1 + se2_2)
        t = (m1 - m2) / np.maximum(denom, 1e-30)
        df = (se2_1 + se2_2) ** 2 / np.maximum(
            se2_1**2 / max(n1 - 1, 1.0)
            + se2_2**2 / max(n2_eff - 1, 1.0), 1e-300)
        t_stats.append(t)
        dfs.append(df)
        m_g.append(m1)
        m_r.append(m2)
    return (np.stack(t_stats), np.stack(dfs), np.stack(m_g), np.stack(m_r))


# ----------------------------------------------------------------------
# wilcoxon ranks (TPU): vmapped sort + double searchsorted
# ----------------------------------------------------------------------


@jax.jit
def _average_ranks(X):
    """Column-wise average ranks (1-based, ties averaged) and the
    per-column tie term ``sum(t^3 - t)``; X is (n_cells, n_genes)."""

    def per_gene(col):
        xs = jnp.sort(col)
        left = jnp.searchsorted(xs, col, side="left")
        right = jnp.searchsorted(xs, col, side="right")
        ranks = 0.5 * (left + right + 1)
        # tie term: count each run of equal values once, at its first
        # sorted occurrence
        lo = jnp.searchsorted(xs, xs, side="left")
        hi = jnp.searchsorted(xs, xs, side="right")
        t = (hi - lo).astype(jnp.float32)
        first = lo == jnp.arange(col.shape[0])
        tie = jnp.sum(jnp.where(first, t**3 - t, 0.0))
        return ranks, tie

    ranks, ties = jax.vmap(per_gene, in_axes=1, out_axes=(1, 0))(X)
    return ranks, ties


@partial(jax.jit, static_argnames=("n_groups",))
def _group_rank_sums(ranks, codes, n_groups):
    # Sum CENTERED ranks (rank - (n+1)/2) with segment_sum: the group
    # deviation from its null mean is computed directly instead of as
    # a difference of two huge numbers, so f32 stays well-conditioned
    # even at atlas scale (raw rank sums ~ n1*n/2 would swamp f32).
    n = ranks.shape[0]
    centered = ranks - 0.5 * (n + 1)
    rs = jax.ops.segment_sum(centered, codes, num_segments=n_groups)
    cnt = jax.ops.segment_sum(jnp.ones_like(codes, jnp.float32), codes,
                              num_segments=n_groups)
    return rs, cnt  # (g, genes) centered rank sums, (g,)


@partial(jax.jit, static_argnames=("width",))
def _dense_gene_block(x: SparseCells, lo, width):
    """Densify gene columns [lo, lo+width) of a SparseCells —
    (n_cells, width).  Same scatter as ``to_dense`` but over a
    narrow table, so the full matrix never materialises (the whole
    point for atlas-scale wilcoxon)."""
    shifted = x.indices - lo
    inb = (shifted >= 0) & (shifted < width) & (x.indices != x.sentinel)
    tgt = jnp.where(inb, shifted, width)  # width = drop bin
    table = jnp.zeros((x.indices.shape[0], width + 1), x.data.dtype)
    table = jax.vmap(lambda t, i, d: t.at[i].add(d))(table, tgt, x.data)
    return table[: x.n_cells, :width]


_GENE_BLOCK = 2048


def _blocked_rank_sums(get_block, n_genes, codes, n_groups):
    """Accumulate per-gene tie terms and per-group rank sums over gene
    blocks of static width; trailing all-zero pad columns are trimmed
    host-side."""
    rs_chunks, tie_chunks, cnt = [], [], None
    for lo in range(0, n_genes, _GENE_BLOCK):
        blk = get_block(lo)  # (n_cells, _GENE_BLOCK) — maybe padded
        ranks, ties = _average_ranks(blk)
        rs, cnt = _group_rank_sums(ranks, codes, n_groups)
        rs_chunks.append(np.asarray(rs))
        tie_chunks.append(np.asarray(ties))
    rank_sums = np.concatenate(rs_chunks, axis=1)[:, :n_genes]
    ties = np.concatenate(tie_chunks)[:n_genes]
    return ties, cnt, rank_sums


def _wilcoxon_z(centered_rank_sums, cnt, ties, n, tie_correct):
    """z from CENTERED per-group rank sums (null mean already zero)."""
    rs = np.asarray(centered_rank_sums, np.float64)
    cnt = np.asarray(cnt, np.float64)
    ties = np.asarray(ties, np.float64)
    zs = []
    for g in range(rs.shape[0]):
        n1 = cnt[g]
        n2 = n - n1
        var = n1 * n2 * (n + 1) / 12.0
        if tie_correct:
            var = var * (1.0 - ties / max(n**3 - n, 1.0))
        zs.append(rs[g] / np.sqrt(np.maximum(var, 1e-30)))
    return np.stack(zs)


# ----------------------------------------------------------------------
# the registered op
# ----------------------------------------------------------------------


def _finalise(data, scores, pvals, lfc, levels, method, n_top,
              pts_pair=None, reference="rest"):
    """Sort per group, BH-adjust, stash scanpy-shaped uns entry.
    ``pts_pair`` (scanpy ``pts=True``): per-group expressing-cell
    fractions, stored UNSORTED as (n_groups, n_genes) ``pts`` /
    ``pts_rest`` — indexed by gene id, not by the ranked order."""
    padj = _bh_adjust(pvals)
    order = np.argsort(-scores, axis=1)
    if n_top is not None:
        order = order[:, :n_top]
    gene_names = None
    if "gene_name" in data.var:
        gene_names = np.asarray(data.var["gene_name"]).astype(str)
    take = lambda a: np.take_along_axis(a, order, axis=1)
    result = {
        "method": method,
        "reference": reference,
        "groups": levels,
        "indices": order,
        "names": (gene_names[order] if gene_names is not None else order),
        "scores": take(scores),
        "pvals": take(pvals),
        "pvals_adj": take(padj),
        "logfoldchanges": take(lfc),
    }
    if pts_pair is not None:
        result["pts"], result["pts_rest"] = (
            np.asarray(p) for p in pts_pair)
    return data.with_uns(rank_genes_groups=result)


def _logreg_scores(data: CellData, codes, n_groups, l2: float = 1e-4,
                   n_steps: int = 300, lr: float = 0.1, seed: int = 0):
    """Multinomial logistic-regression coefficients (scanpy's
    method="logreg" scores): softmax CE + L2, optax Adam, full-batch,
    logits via ``spmm`` so sparse X never densifies.  The SAME jax
    program serves both backends (logreg has no scipy oracle in this
    environment; the tests gate it on marker recovery instead)."""
    import optax

    n = data.n_cells
    X = data.X
    y = jnp.asarray(codes[:n])
    dense = not isinstance(X, SparseCells)
    if dense:
        X = jnp.asarray(
            X.toarray() if hasattr(X, "toarray") else X
        )[:n].astype(jnp.float32)

    key = jax.random.PRNGKey(seed)
    params = {"W": 1e-3 * jax.random.normal(
        key, (data.n_genes, n_groups), jnp.float32),
        "b": jnp.zeros((n_groups,), jnp.float32)}
    tx = optax.adam(lr)
    opt = tx.init(params)

    # X and y enter as jit ARGUMENTS (X is a pytree either way) —
    # closing over them would bake the matrix into the jaxpr as a
    # constant, the large-constant pathology models/scvi.py documents
    @jax.jit
    def step(params, opt, Xop, yv):
        def loss_fn(p):
            logits = ((Xop @ p["W"] if dense
                       else spmm(Xop, p["W"])[:n]) + p["b"])
            lg = jax.nn.log_softmax(logits, axis=1)
            ce = -jnp.mean(jnp.take_along_axis(lg, yv[:, None], axis=1))
            return ce + l2 * jnp.sum(p["W"] ** 2)

        loss, g = jax.value_and_grad(loss_fn)(params)
        up, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, up), opt, loss

    for _ in range(n_steps):
        params, opt, _ = step(params, opt, X, y)
    return np.asarray(params["W"]).T  # (n_groups, n_genes)


def _rank_genes_groups(data: CellData, groupby: str, method: str,
                       n_top, tie_correct: bool, dense_ranks_via,
                       group_moments, pts: bool = False,
                       device: bool = True, groups=None,
                       reference: str = "rest"):
    from scipy import stats as sps

    codes_host, levels, n_obs = _group_codes(data, groupby)
    n_groups = len(levels)
    ref_idx = None
    if reference != "rest":
        if str(reference) not in levels:
            raise ValueError(
                f"rank_genes_groups: reference {reference!r} is not a "
                f"level of obs[{groupby!r}] ({levels})")
        if method == "logreg":
            raise ValueError(
                "rank_genes_groups: reference= other than 'rest' is "
                "not defined for method='logreg' (multinomial over "
                "all groups); use a t-test or wilcoxon")
        ref_idx = levels.index(str(reference))

    if ref_idx is not None and method == "wilcoxon":
        # scanpy's wilcoxon-vs-reference ranks only the PAIR subset —
        # run each selected group as a 2-level sub-comparison, where
        # group-vs-rest IS group-vs-reference, and stitch the rows.
        # Each pairwise run reuses the full blocked-rank machinery on
        # the subset (CellData.__getitem__ works on both residencies).
        from ..registry import apply as _apply

        v = np.asarray(data.obs[groupby])[:n_obs].astype(str)
        want = (None if groups is None else {str(g) for g in groups})
        if want is not None:
            unknown = want - set(levels)
            if unknown:
                raise ValueError(
                    f"rank_genes_groups: groups {sorted(unknown)} are "
                    f"not levels of obs[{groupby!r}] ({levels})")
        sel = [l for l in levels
               if (want is None or l in want) and l != str(reference)]
        if not sel:
            raise ValueError(
                f"rank_genes_groups: groups={groups!r} selects no "
                f"level of {levels}")
        backend = "tpu" if device else "cpu"
        parts = []
        for g_level in sel:
            sub = data[(v == g_level) | (v == str(reference))]
            r = _apply("de.rank_genes_groups", sub, backend=backend,
                       groupby=groupby, method="wilcoxon",
                       n_top=n_top, tie_correct=tie_correct,
                       groups=[g_level], pts=pts)
            parts.append(r.uns["rank_genes_groups"])
        result = {"method": "wilcoxon", "reference": reference,
                  "groups": sel}
        for key in ("indices", "names", "scores", "pvals",
                    "pvals_adj", "logfoldchanges"):
            result[key] = np.concatenate([p[key] for p in parts])
        if pts:
            for key in ("pts", "pts_rest"):
                result[key] = np.concatenate([p[key] for p in parts])
        return data.with_uns(rank_genes_groups=result)

    if method == "logreg":
        scores = _logreg_scores(data, codes_host, n_groups)
        pvals = np.full_like(scores, np.nan)  # scanpy parity: no pvals
        s, _, cnt2 = group_moments(codes_host, n_groups, need_ss=False)
        m_g, m_r = _group_means(s, cnt2)
    elif method in ("t-test", "t-test_overestim_var"):
        s, ss, cnt = group_moments(codes_host, n_groups, need_ss=True)
        t, df, m_g, m_r = _welch_stats(
            s, ss, cnt, overestim_var=(method == "t-test_overestim_var"),
            ref=ref_idx)
        pvals = 2.0 * sps.t.sf(np.abs(t), np.maximum(df, 1.0))
        scores = t
    elif method == "wilcoxon":
        ties, cnt, rank_sums = dense_ranks_via(codes_host, n_groups)
        z = _wilcoxon_z(rank_sums, cnt, ties, n_obs, tie_correct)
        pvals = 2.0 * sps.norm.sf(np.abs(z))
        scores = z
        s, _, cnt2 = group_moments(codes_host, n_groups, need_ss=False)
        m_g, m_r = _group_means(s, cnt2)
    else:
        raise ValueError(f"unknown method {method!r}; use 't-test', "
                         f"'t-test_overestim_var', 'wilcoxon' or "
                         f"'logreg'")
    lfc = _logfoldchange(m_g, m_r)
    pts_pair = (_expression_fractions(data, codes_host, n_groups,
                                      device) if pts else None)
    if groups is not None or ref_idx is not None:
        want = (None if groups is None else {str(g) for g in groups})
        if want is not None:
            unknown = want - set(levels)
            if unknown:
                raise ValueError(
                    f"rank_genes_groups: groups {sorted(unknown)} are "
                    f"not levels of obs[{groupby!r}] ({levels})")
        keep = [i for i, l in enumerate(levels)
                if (want is None or l in want) and i != ref_idx]
        if not keep:
            raise ValueError(
                f"rank_genes_groups: groups={groups!r} selects no "
                f"level of {levels}")
        if pts_pair is not None:
            frac_in, frac_out = (np.asarray(p) for p in pts_pair)
            if ref_idx is not None:
                # vs a named reference: the "rest" column is the
                # REFERENCE group's own expressing fraction (scanpy's
                # pct_nz_reference), not the vs-rest complement
                frac_out = np.broadcast_to(
                    frac_in[ref_idx], frac_in.shape).copy()
            pts_pair = (frac_in[keep], frac_out[keep])
        scores, pvals, lfc = scores[keep], pvals[keep], lfc[keep]
        levels = [levels[i] for i in keep]
    return _finalise(data, scores, pvals, lfc, levels, method, n_top,
                     pts_pair=pts_pair, reference=reference)


@register("de.rank_genes_groups", backend="tpu")
def rank_genes_groups_tpu(data: CellData, groupby: str = "label",
                          method: str = "t-test", n_top: int | None = None,
                          tie_correct: bool = True,
                          pts: bool = False, groups=None,
                          reference: str = "rest") -> CellData:
    """Rank genes characterising each group vs the rest (scanpy
    ``tl.rank_genes_groups``), group-vs-rest for every level of
    ``obs[groupby]``.

    Results land in ``uns["rank_genes_groups"]`` (host numpy): names /
    indices, scores (t or z), pvals, BH-adjusted pvals, and
    log2-fold-changes, each (n_groups × n_top_or_all_genes), sorted by
    descending score per group.
    """
    X = data.X
    n = data.n_cells
    n_genes = data.n_genes

    if isinstance(X, SparseCells):
        def group_moments(codes_host, n_groups, need_ss=True):
            # codes padded with -1 -> one_hot zero rows for padding
            c = np.full(X.rows_padded, -1, np.int32)
            c[:n] = codes_host[:n]
            return _group_moments_sparse(X, jnp.asarray(c), n_groups,
                                         need_ss=need_ss)

        def dense_ranks_via(codes_host, n_groups):
            width = min(_GENE_BLOCK, n_genes)
            return _blocked_rank_sums(
                lambda lo: _dense_gene_block(X, lo, width),
                n_genes, jnp.asarray(codes_host), n_groups)
    else:
        Xd = jnp.asarray(X)

        def group_moments(codes_host, n_groups, need_ss=True):
            del need_ss  # dense moments cost one fused pass either way
            return _group_moments_dense(
                Xd[:n], jnp.asarray(codes_host), n_groups)

        def dense_ranks_via(codes_host, n_groups):
            return _blocked_rank_sums(
                lambda lo: Xd[:n, lo:lo + _GENE_BLOCK],
                n_genes, jnp.asarray(codes_host), n_groups)

    return _rank_genes_groups(data, groupby, method, n_top, tie_correct,
                              dense_ranks_via, group_moments, pts=pts,
                              device=True, groups=groups,
                              reference=reference)


@register("de.rank_genes_groups", backend="cpu")
def rank_genes_groups_cpu(data: CellData, groupby: str = "label",
                          method: str = "t-test", n_top: int | None = None,
                          tie_correct: bool = True,
                          pts: bool = False, groups=None,
                          reference: str = "rest") -> CellData:
    """scipy oracle: same statistics via dense numpy/scipy."""
    import scipy.sparse as sp
    from scipy import stats as sps

    X = data.X
    X = np.asarray(X.todense()) if sp.issparse(X) else np.asarray(X)
    X = X.astype(np.float64)
    codes_host, levels, n_obs = _group_codes(data, groupby)
    n_groups = len(levels)

    def group_moments(codes, ng, need_ss=True):
        del need_ss
        onehot = np.eye(ng)[codes]
        return onehot.T @ X, onehot.T @ (X * X), onehot.sum(0)

    def dense_ranks_via(codes, ng):
        ranks = sps.rankdata(X, axis=0)
        # per-gene tie term
        ties = np.zeros(X.shape[1])
        for j in range(X.shape[1]):
            _, t = np.unique(X[:, j], return_counts=True)
            ties[j] = np.sum(t.astype(np.float64) ** 3 - t)
        onehot = np.eye(ng)[codes]
        n = X.shape[0]
        return ties, onehot.sum(0), onehot.T @ (ranks - 0.5 * (n + 1))

    return _rank_genes_groups(data, groupby, method, n_top, tie_correct,
                              dense_ranks_via, group_moments, pts=pts,
                              device=False, groups=groups,
                              reference=reference)


# ----------------------------------------------------------------------
# de.filter_rank_genes_groups — expression-fraction / fold-change
# filter over an existing ranking (scanpy pp namesake)
# ----------------------------------------------------------------------


def _expression_fractions(data: CellData, codes, n_groups, device: bool):
    """(n_groups, n_genes) fraction of cells expressing each gene,
    in-group and out-group."""
    n = data.n_cells
    n_per = np.bincount(codes, minlength=n_groups).astype(np.float64)
    if device and isinstance(data.X, SparseCells):
        # binarise the data plane and reuse the grouped-sum machinery
        # (same padded-codes convention as rank_genes_groups_tpu)
        x = data.X
        c = np.full(x.rows_padded, -1, np.int32)
        c[:n] = codes[:n]
        s, _, _ = _group_moments_sparse(
            x.with_data((x.data > 0).astype(x.data.dtype)),
            jnp.asarray(c), n_groups, need_ss=False)
        nnz_gj = np.asarray(s)
    elif device:
        Xd = jnp.asarray(data.X)[:n]
        oh = jax.nn.one_hot(jnp.asarray(codes[:n]), n_groups,
                            dtype=jnp.float32)
        nnz_gj = np.asarray(oh.T @ (Xd > 0).astype(jnp.float32))
    else:
        import scipy.sparse as sp

        onehot = np.zeros((n, n_groups), np.float32)
        onehot[np.arange(n), codes] = 1.0
        X = data.X
        B = (X > 0) if sp.issparse(X) else sp.csr_matrix(
            np.asarray(X) > 0)
        nnz_gj = (B.astype(np.float32).T @ onehot).T
    total = nnz_gj.sum(axis=0, keepdims=True)
    frac_in = nnz_gj / np.maximum(n_per[:, None], 1.0)
    frac_out = (total - nnz_gj) / np.maximum(
        (n - n_per)[:, None], 1.0)
    return frac_in, frac_out


def _filter_rank_genes_groups(data: CellData, groupby, key,
                              min_in_group_fraction,
                              max_out_group_fraction,
                              min_fold_change, device: bool):
    if key not in data.uns:
        raise KeyError(
            f"filter_rank_genes_groups: uns has no {key!r} — run "
            "de.rank_genes_groups first")
    res = data.uns[key]
    codes, levels, _ = _group_codes(data, groupby)
    if list(res["groups"]) != list(levels):
        raise ValueError(
            f"filter_rank_genes_groups: obs[{groupby!r}] levels "
            f"{levels} do not match the ranking's groups "
            f"{list(res['groups'])}")
    frac_in, frac_out = _expression_fractions(
        data, codes, len(levels), device)
    idx = np.asarray(res["indices"])  # (groups, m) gene ids, ranked
    rows = np.arange(len(levels))[:, None]
    ok = ((frac_in[rows, idx] >= min_in_group_fraction)
          & (frac_out[rows, idx] <= max_out_group_fraction)
          & (np.asarray(res["logfoldchanges"])
             >= np.log2(min_fold_change)))
    names = np.asarray(res["names"]).astype(object)
    names[~ok] = None  # scanpy parity: filtered entries become NaN/None
    out = dict(res)
    out["names_filtered"] = names
    out["kept"] = ok
    out["frac_in_group"] = frac_in[rows, idx]
    out["frac_out_group"] = frac_out[rows, idx]
    return data.with_uns(**{f"{key}_filtered": out})


@register("de.filter_rank_genes_groups", backend="tpu")
def filter_rank_genes_groups_tpu(
        data: CellData, groupby: str = "label",
        key: str = "rank_genes_groups",
        min_in_group_fraction: float = 0.25,
        max_out_group_fraction: float = 0.5,
        min_fold_change: float = 1.0) -> CellData:
    """Filter an existing ``de.rank_genes_groups`` result by in-group
    expression fraction, out-group expression fraction, and minimum
    fold change (scanpy ``pp.filter_rank_genes_groups``).  Adds
    ``uns[key + '_filtered']`` with ``names_filtered`` (non-passing
    entries None), the boolean ``kept`` mask, and both fraction
    matrices.  The per-group expression fractions are one binarised
    ``spmm_t`` on device."""
    return _filter_rank_genes_groups(
        data, groupby, key, min_in_group_fraction,
        max_out_group_fraction, min_fold_change, device=True)


@register("de.filter_rank_genes_groups", backend="cpu")
def filter_rank_genes_groups_cpu(
        data: CellData, groupby: str = "label",
        key: str = "rank_genes_groups",
        min_in_group_fraction: float = 0.25,
        max_out_group_fraction: float = 0.5,
        min_fold_change: float = 1.0) -> CellData:
    return _filter_rank_genes_groups(
        data, groupby, key, min_in_group_fraction,
        max_out_group_fraction, min_fold_change, device=False)
