"""Clustering: ``cluster.kmeans`` (minibatch-free Lloyd on MXU),
``cluster.leiden`` (parallel modularity optimisation), and
``cluster.leiden_like`` (cheaper label propagation, kept for
compatibility).

TPU design: k-means assignment is the same blocked score-matmul as
kNN (centroids replicated in VMEM, argmax over MXU scores); the
update step is one ``segment_sum`` per iteration.  Everything runs
under one ``lax.scan`` over iterations — no host round-trips.

``cluster.leiden`` is the reference-parity community detector
(louvain/leiden family): γ-resolution Newman modularity optimised by
device-parallel local-move rounds (alternating node-parity halves —
the deterministic analogue of parallel Louvain's random half-sweeps)
interleaved with host-side aggregation merges on the coarse community
graph.  True Leiden's *refinement* queue is inherently sequential and
does not map to XLA; the parallel-moves + aggregation scheme reaches
modularity within a few percent of a serial greedy Louvain (asserted
in tests/test_leiden.py against the CPU oracle and an independent
modularity metric).

``cluster.leiden_like`` is the earlier label-propagation scheme —
faster, no resolution parameter, kept as a registered transform.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


from ..data.dataset import CellData
from ..registry import register


@partial(jax.jit, static_argnames=("n_clusters", "n_iter", "block"))
def kmeans_arrays(points, key, n_clusters: int = 8, n_iter: int = 25,
                  block: int = 4096):
    """Lloyd's algorithm.  points: (n, d) dense.  Returns (labels (n,),
    centroids (k, d), inertia ())."""
    n, d = points.shape
    pts = jnp.asarray(points, jnp.float32)

    # k-means++-lite init: sample k points with probability ∝ squared
    # distance to the running centroid set, approximated by one
    # D²-weighted draw round (full k-means++ is sequential in k; one
    # weighted round captures most of the benefit and stays parallel).
    i0 = jax.random.choice(key, n, (1,))
    c0 = pts[i0]  # (1, d)
    d2 = jnp.sum((pts - c0) ** 2, axis=1)
    probs = d2 / jnp.maximum(d2.sum(), 1e-12)
    rest = jax.random.choice(key, n, (n_clusters - 1,), replace=False, p=probs)
    centroids = jnp.concatenate([c0, pts[rest]], axis=0)  # (k, d)

    nb = -(-n // block)
    pad = nb * block - n
    pts_pad = jnp.concatenate([pts, jnp.zeros((pad, d), pts.dtype)]) if pad else pts
    valid = jnp.arange(nb * block) < n

    def assign(centroids):
        cn2 = jnp.sum(centroids**2, axis=1)  # (k,)

        def per_block(args):
            p = args  # (block, d)
            s = jnp.dot(p, centroids.T, preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST)
            d2 = cn2[None, :] - 2.0 * s  # + ||p||² (constant per row)
            lab = jnp.argmin(d2, axis=1).astype(jnp.int32)
            best = jnp.min(d2, axis=1) + jnp.sum(p * p, axis=1)
            return lab, best

        labs, best = jax.lax.map(per_block, pts_pad.reshape(nb, block, d))
        return labs.reshape(-1), best.reshape(-1)

    def step(centroids, _):
        labels, best = assign(centroids)
        labels_v = jnp.where(valid, labels, n_clusters)  # padding → dropped bin
        sums = jax.ops.segment_sum(
            jnp.where(valid[:, None], pts_pad, 0.0), labels_v,
            num_segments=n_clusters + 1)[:n_clusters]
        counts = jax.ops.segment_sum(
            valid.astype(jnp.float32), labels_v,
            num_segments=n_clusters + 1)[:n_clusters]
        new_c = jnp.where(counts[:, None] > 0,
                          sums / jnp.maximum(counts[:, None], 1.0),
                          centroids)
        inertia = jnp.sum(jnp.where(valid, best, 0.0))
        return new_c, inertia

    centroids, inertias = jax.lax.scan(step, centroids, None, length=n_iter)
    labels, best = assign(centroids)
    inertia = jnp.sum(jnp.where(valid, best, 0.0))
    return labels[:n], centroids, inertia


@register("cluster.kmeans", backend="tpu")
def kmeans_tpu(data: CellData, n_clusters: int = 8, n_iter: int = 25,
               use_rep: str = "X_pca", seed: int = 0) -> CellData:
    """Adds obs["kmeans"], uns["kmeans_centroids"], uns["kmeans_inertia"]."""
    from .knn import _get_rep

    rep = _get_rep(data, use_rep)
    labels, centroids, inertia = kmeans_arrays(
        jnp.asarray(rep)[: data.n_cells], jax.random.PRNGKey(seed),
        n_clusters=n_clusters, n_iter=n_iter)
    return data.with_obs(kmeans=labels).with_uns(
        kmeans_centroids=centroids, kmeans_inertia=inertia)


@register("cluster.kmeans", backend="cpu")
def kmeans_cpu(data: CellData, n_clusters: int = 8, n_iter: int = 25,
               use_rep: str = "X_pca", seed: int = 0) -> CellData:
    """numpy Lloyd oracle (same init scheme family, own RNG)."""
    from .knn import _get_rep_cpu

    rep = np.asarray(_get_rep_cpu(data, use_rep), np.float64)[: data.n_cells]
    rng = np.random.default_rng(seed)
    n = len(rep)
    # full sequential k-means++ (the numpy oracle can afford it)
    centroids = rep[rng.choice(n, 1)]
    for _ in range(n_clusters - 1):
        d2 = np.min(((rep[:, None, :] - centroids[None]) ** 2).sum(-1), axis=1)
        p = d2 / max(d2.sum(), 1e-12)
        centroids = np.concatenate([centroids, rep[rng.choice(n, 1, p=p)]])
    labels = np.zeros(n, np.int32)
    for _ in range(n_iter):
        d2 = ((rep[:, None, :] - centroids[None, :, :]) ** 2).sum(-1) \
            if n * n_clusters * rep.shape[1] < 5e7 else None
        if d2 is None:
            s = rep @ centroids.T
            d2 = (centroids**2).sum(1)[None, :] - 2 * s
        labels = np.argmin(d2, axis=1).astype(np.int32)
        for j in range(n_clusters):
            m = labels == j
            if m.any():
                centroids[j] = rep[m].mean(axis=0)
    inertia = float(((rep - centroids[labels]) ** 2).sum())
    return data.with_obs(kmeans=labels).with_uns(
        kmeans_centroids=centroids.astype(np.float32),
        kmeans_inertia=np.float32(inertia))


# ----------------------------------------------------------------------
# Label propagation over the kNN graph ("leiden-like" communities).
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_iter",))
def label_propagation_arrays(knn_idx, weights, n_iter: int = 30):
    """Weighted label propagation on a kNN graph.

    knn_idx: (n, k) int32 neighbour ids (-1 = missing); weights:
    (n, k) edge weights.  Starts from singleton labels; each round a
    node adopts the best-supported neighbour label, but only when its
    support STRICTLY beats the node's current label (monotone — plain
    synchronous propagation oscillates), with support ties resolved
    toward the lower label id (also monotone).  Self-edges never vote.
    Fully deterministic.
    """
    n, k = knn_idx.shape
    labels0 = jnp.arange(n, dtype=jnp.int32)
    safe_idx = jnp.where(knn_idx < 0, 0, knn_idx)
    # self-edges must not vote: a self-weight of 1.0 (distance 0 in
    # the UMAP kernel) would pin every node to its own singleton label
    row_ids = jnp.arange(n, dtype=knn_idx.dtype)[:, None]
    dead = (knn_idx < 0) | (knn_idx == row_ids)
    w = jnp.where(dead, 0.0, weights.astype(jnp.float32))

    block = 8192
    nb = -(-n // block)
    pad = nb * block - n

    def step(labels, _):
        neigh_labels = jnp.take(labels, safe_idx, axis=0)  # (n, k)
        nl = jnp.where(dead, -1, neigh_labels)
        wv = w
        cur = labels
        if pad:
            nl = jnp.concatenate([nl, jnp.full((pad, k), -1, nl.dtype)])
            wv = jnp.concatenate([wv, jnp.zeros((pad, k), wv.dtype)])
            cur = jnp.concatenate([cur, jnp.full((pad,), -1, cur.dtype)])

        def per_block(args):
            sl, sw, cl = args  # (block, k), (block, k), (block,)
            # vote weight of each position's label: O(k²) pairwise
            # equality mask — k is small, so this is trivial VPU work
            # and avoids any scatter into (n, n_labels).
            same = sl[:, None, :] == sl[:, :, None]  # (block, k, k)
            acc = jnp.sum(jnp.where(same, sw[:, None, :], 0.0), axis=2)
            acc = jnp.where(sl < 0, -1.0, acc)
            # tie-break: highest weight, then lowest label id — as two
            # exact passes (a combined scalar key would let label ids
            # override genuine weight differences)
            bw = jnp.max(acc, axis=1)
            cand = jnp.where(acc == bw[:, None], sl,
                             jnp.iinfo(jnp.int32).max)
            lab = jnp.min(cand, axis=1)
            # support for the CURRENT label among neighbours
            cur_support = jnp.sum(
                jnp.where(sl == cl[:, None], sw, 0.0), axis=1)
            return lab, bw, cur_support

        lab, bw, cur_sup = jax.lax.map(
            per_block, (nl.reshape(nb, block, k), wv.reshape(nb, block, k),
                        cur.reshape(nb, block)))
        lab = lab.reshape(-1)[:n]
        bw = bw.reshape(-1)[:n]
        cur_sup = cur_sup.reshape(-1)[:n]
        # monotone update: adopt a STRICTLY better-supported label
        # (synchronous best-of-all updates oscillate and fragment);
        # on support ties adopt the LOWER id — label ids then only
        # decrease, which merges equal-support plateau fragments
        # without reintroducing oscillation.
        valid_lab = (lab >= 0) & (lab < jnp.iinfo(jnp.int32).max)
        better = bw > cur_sup + 1e-12
        tie_lower = (jnp.abs(bw - cur_sup) <= 1e-12) & (lab < labels)
        adopt = (better | tie_lower) & valid_lab
        return jnp.where(adopt, lab, labels), None

    labels, _ = jax.lax.scan(step, labels0, None, length=n_iter)
    return labels


def _compact_labels(labels: np.ndarray) -> np.ndarray:
    uniq, inv = np.unique(labels, return_inverse=True)
    return inv.astype(np.int32)


def _coarse_ell(labels: np.ndarray, idx: np.ndarray, w: np.ndarray,
                max_capacity: int = 1024):
    """Aggregate a (possibly directed) ELL graph by community labels
    into a symmetric coarse ELL graph over ``m`` supernodes (host,
    scipy).  Intra-community weight becomes a SELF-LOOP on the
    supernode (stored once per row; ``louvain_moves_arrays`` counts it
    in the degree but never lets it vote).  Hub rows beyond
    ``max_capacity`` keep their heaviest off-diagonal edges, with
    symmetry restored by dropping the reverse copies too; the diagonal
    is never dropped (it carries the internal weight the next level's
    modularity needs).

    Returns (idx2 (m, cap) int32 with -1 padding, w2 (m, cap) f32).
    """
    import scipy.sparse as sp

    n, k = idx.shape
    m = int(labels.max()) + 1
    rows = np.repeat(labels.astype(np.int64), k)
    cols = idx.reshape(-1)
    keep = cols >= 0
    cj = labels[np.clip(cols, 0, n - 1)].astype(np.int64)
    vals = np.asarray(w, np.float64).reshape(-1)
    A = sp.coo_matrix((vals[keep], (rows[keep], cj[keep])),
                      shape=(m, m)).tocsr()
    A.sum_duplicates()
    S = (0.5 * (A + A.T)).tocsr()  # no-op for symmetric input
    S.eliminate_zeros()
    nnz = np.diff(S.indptr)
    if len(nnz) and int(nnz.max()) > max_capacity:
        for r in np.flatnonzero(nnz > max_capacity):
            lo, hi = S.indptr[r], S.indptr[r + 1]
            d = S.data[lo:hi]
            offd = np.flatnonzero(S.indices[lo:hi] != r)
            n_drop = (hi - lo) - max_capacity
            drop = offd[np.argpartition(d[offd], n_drop - 1)[:n_drop]]
            d[drop] = 0.0
        S.eliminate_zeros()
        # edge kept iff kept in BOTH rows → symmetric again; diagonal
        # of minimum(S, Sᵀ) is S's own diagonal, so self-loops survive
        S = S.minimum(S.T).tocsr()
        S.eliminate_zeros()
        nnz = np.diff(S.indptr)
    cap = max(int(nnz.max()) if len(nnz) and S.nnz else 1, 1)
    idx2 = np.full((m, cap), -1, np.int32)
    w2 = np.zeros((m, cap), np.float32)
    slot = np.arange(S.nnz) - np.repeat(S.indptr[:-1], nnz)
    rr = np.repeat(np.arange(m), nnz)
    idx2[rr, slot] = S.indices
    w2[rr, slot] = S.data
    return idx2, w2


def _modularity_merge(labels: np.ndarray, knn_idx: np.ndarray,
                      weights: np.ndarray, resolution: float = 1.0,
                      max_communities: int = 4096) -> np.ndarray:
    """Leiden-style aggregation phase: merge communities of the coarse
    label graph while γ-aware modularity increases.

    Pure parallel local moves / LPA leave stable same-cluster
    fragments (a fragment's internal support beats boundary votes);
    merging on the aggregated graph is exactly how Louvain/Leiden
    escape that.  Gain of merging communities i, j with the coarse
    matrix ``A`` (each undirected edge counted once per direction,
    ``total = ΣA = 2m``):

        ΔQ = 2·(A_ij/total − γ·deg_i·deg_j/total²)

    — the same normalisation as :func:`modularity`, verified by the
    stored-vs-recomputed assertion in tests/test_leiden.py.

    The dense (m, m) coarse matrix + one-merge-per-argmax loop is
    O(m²) memory / O(m³) time — fine for a few thousand communities,
    not for an atlas-scale first level.  Above ``max_communities`` the
    graph is first AGGREGATED (``_coarse_ell``) and coarsened by
    device-parallel local-move rounds on the supernode graph
    (``louvain_moves_arrays`` — standard Louvain aggregation: ΔQ on
    the coarse graph equals ΔQ on the original), recursing until the
    community count fits the dense merge.  If a level makes no
    progress the current labels are returned honestly rather than
    looping.
    """
    labels = _compact_labels(labels)
    m = int(labels.max()) + 1 if len(labels) else 0
    if m <= 1:
        return labels
    if m > max_communities:
        cidx, cw = _coarse_ell(labels, knn_idx, weights)
        # the move kernel's per-block (block, cap, cap) community mask
        # is O(block·cap²): scale the block down for wide coarse rows
        # (cap can reach _coarse_ell's 1024 on hub-heavy graphs) so
        # the tile stays ~64 MB instead of OOMing at the default 8192
        cap = max(cidx.shape[1], 1)
        block = int(min(8192, max(8, (1 << 24) // (cap * cap))))
        sub = np.asarray(louvain_moves_arrays(
            jnp.asarray(cidx), jnp.asarray(cw),
            jnp.arange(m, dtype=jnp.int32), resolution=resolution,
            n_rounds=20, block=block))
        sub = _compact_labels(sub)
        if int(sub.max()) + 1 >= m:  # no coarsening — avoid recursing
            return labels
        sub = _modularity_merge(sub, cidx, cw, resolution=resolution,
                                max_communities=max_communities)
        return _compact_labels(sub[labels])
    n, k = knn_idx.shape
    li = np.repeat(labels, k)
    cols = knn_idx.reshape(-1)
    keep = cols >= 0
    lj = labels[np.clip(cols, 0, n - 1)]
    w = np.asarray(weights, np.float64).reshape(-1)
    A = np.zeros((m, m))
    np.add.at(A, (li[keep], lj[keep]), w[keep])
    A = 0.5 * (A + A.T)
    total = A.sum()
    if total <= 0:
        return labels
    # Round-based greedy MATCHING merges: each round picks a maximal
    # set of DISJOINT positive-gain pairs (best partner per community,
    # taken greedily by gain) and applies them all at once via a
    # one-hot aggregation (two BLAS gemms).  ΔQ of disjoint merges is
    # exactly additive, so every round strictly increases modularity —
    # same stopping rule as a serial argmax loop, but O(rounds·m²)
    # instead of the O(m³) one-merge-per-argmax that round 4 measured
    # taking minutes at m≈2-4k (it hung the 20k-node parity test).
    group = np.arange(m)
    while m > 1:
        deg = A.sum(axis=1)
        gain = 2.0 * (A / total
                      - resolution * np.outer(deg, deg) / (total * total))
        np.fill_diagonal(gain, -np.inf)
        j_best = np.argmax(gain, axis=1)
        g_best = gain[np.arange(m), j_best]
        order = np.argsort(-g_best)
        taken = np.zeros(m, bool)
        target = np.arange(m)
        n_pairs = 0
        for i in order:
            if g_best[i] <= 1e-12:
                break
            j = j_best[i]
            if taken[i] or taken[j]:
                continue
            taken[i] = taken[j] = True
            target[j] = i
            n_pairs += 1
        if n_pairs == 0:
            break
        keep = np.flatnonzero(target == np.arange(m))
        new_id = np.full(m, -1)
        new_id[keep] = np.arange(len(keep))
        mapping = new_id[target]  # every j maps to its partner's new id
        M = np.zeros((m, len(keep)))
        M[np.arange(m), mapping] = 1.0
        A = M.T @ A @ M
        group = mapping[group]
        m = len(keep)
    return _compact_labels(group[labels])


@register("cluster.leiden_like", backend="tpu")
def leiden_like_tpu(data: CellData, n_iter: int = 30,
                    weight_key: str = "connectivities") -> CellData:
    """Community labels from label propagation over the kNN graph
    (deterministic) plus a modularity merge of the coarse label graph.
    Requires neighbors.knn (+ optionally graph.connectivities for
    weighted votes).  Adds obs["leiden_like"]."""
    if "knn_indices" not in data.obsp:
        raise ValueError("run neighbors.knn first")
    idx = jnp.asarray(data.obsp["knn_indices"])[: data.n_cells]
    if weight_key in data.obsp:
        w = jnp.asarray(data.obsp[weight_key])[: data.n_cells]
    else:
        w = jnp.ones_like(idx, dtype=jnp.float32)
    labels = label_propagation_arrays(idx, w, n_iter=n_iter)
    # the merge phase must see the same self-edge-free weights the
    # propagation used (CPU oracle masks identically)
    idx_h = np.asarray(idx)
    dead = (idx_h < 0) | (idx_h == np.arange(data.n_cells)[:, None])
    w_h = np.where(dead, 0.0, np.asarray(w))
    labels = _modularity_merge(np.asarray(labels), idx_h, w_h)
    return data.with_obs(leiden_like=jnp.asarray(labels))


@register("cluster.leiden_like", backend="cpu")
def leiden_like_cpu(data: CellData, n_iter: int = 30,
                    weight_key: str = "connectivities") -> CellData:
    """numpy oracle of the same propagation scheme."""
    if "knn_indices" not in data.obsp:
        raise ValueError("run neighbors.knn first")
    idx = np.asarray(data.obsp["knn_indices"])[: data.n_cells]
    n, k = idx.shape
    if weight_key in data.obsp:
        w = np.asarray(data.obsp[weight_key], np.float64)[: data.n_cells]
    else:
        w = np.ones_like(idx, np.float64)
    dead = (idx < 0) | (idx == np.arange(n)[:, None])  # no self-votes
    w = np.where(dead, 0.0, w)
    safe = np.where(idx < 0, 0, idx)
    labels = np.arange(n, dtype=np.int64)
    for _ in range(n_iter):
        nl = np.where(dead, -1, labels[safe])
        new = labels.copy()
        for i in range(n):
            votes: dict = {}
            for j in range(k):
                if w[i, j] > 0:
                    votes[nl[i, j]] = votes.get(nl[i, j], 0.0) + w[i, j]
            if votes:
                # highest weight, then lowest label id (mirror TPU)
                best = min(votes, key=lambda L: (-votes[L], L))
                cur_sup = votes.get(labels[i], 0.0)
                if votes[best] > cur_sup + 1e-12 or (
                        abs(votes[best] - cur_sup) <= 1e-12
                        and best < labels[i]):
                    new[i] = best
        if (new == labels).all():
            break
        labels = new
    labels = _modularity_merge(labels, idx, w)
    return data.with_obs(leiden_like=labels)


# ----------------------------------------------------------------------
# cluster.leiden — true modularity optimisation (resolution-aware)
# ----------------------------------------------------------------------


def _symmetrize_knn(idx: np.ndarray, w: np.ndarray,
                    max_capacity: int | None = None):
    """Directed kNN ELL → symmetric union ELL (host, one-time).

    Louvain/Leiden modularity is defined on an undirected graph; the
    kNN graph is directed.  Combine ``A`` and ``Aᵀ`` by elementwise
    max (the UMAP fuzzy-union convention) and repack to padded ELL.

    A row's symmetrised degree is out-degree + in-degree, and kNN
    graphs in high dimensions have hubs whose IN-degree is unbounded —
    an unchecked capacity would make the device kernel's per-row
    (cap, cap) community mask O(hub²) and OOM-prone.  Rows beyond
    ``max_capacity`` (default 4k) keep only their ``max_capacity``
    heaviest edges, and symmetry is restored by dropping the reverse
    copies too (edge kept iff kept in BOTH rows), so degrees and
    modularity stay consistent on the truncated graph.

    Returns (idx2 (n, c) int32 with -1 padding, w2 (n, c) float32).
    """
    import scipy.sparse as sp

    n, k = idx.shape
    if max_capacity is None:
        max_capacity = max(4 * k, 64)
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    cols = idx.reshape(-1).astype(np.int64)
    vals = np.asarray(w, np.float64).reshape(-1)
    keep = (cols >= 0) & (vals > 0) & (cols != rows)
    A = sp.coo_matrix((vals[keep], (rows[keep], cols[keep])),
                      shape=(n, n)).tocsr()
    A.sum_duplicates()
    S = A.maximum(A.T).tocsr()
    nnz = np.diff(S.indptr)
    if len(nnz) and int(nnz.max()) > max_capacity:
        hubs = np.flatnonzero(nnz > max_capacity)
        for r in hubs:  # few hub rows; host loop is fine
            lo, hi = S.indptr[r], S.indptr[r + 1]
            d = S.data[lo:hi]
            # positional argpartition (not a value threshold): a value
            # cut keeps every tie, which on constant-weight graphs
            # keeps everything
            drop = np.argpartition(d, len(d) - max_capacity)[
                : len(d) - max_capacity]
            d[drop] = 0.0
        S.eliminate_zeros()
        # edge kept iff kept in BOTH rows → symmetric again
        S = S.minimum(S.T).tocsr()
        S.eliminate_zeros()
        nnz = np.diff(S.indptr)
    cap = int(nnz.max()) if len(nnz) and S.nnz else 1
    idx2 = np.full((n, cap), -1, np.int32)
    w2 = np.zeros((n, cap), np.float32)
    slot = np.arange(S.nnz) - np.repeat(S.indptr[:-1], nnz)
    rr = np.repeat(np.arange(n), nnz)
    idx2[rr, slot] = S.indices
    w2[rr, slot] = S.data
    return idx2, w2


@partial(jax.jit, static_argnames=("n_rounds", "block"))
def louvain_moves_arrays(idx, w, labels0, resolution: float = 1.0,
                         n_rounds: int = 20, block: int = 8192):
    """Parallel modularity local-move rounds on a SYMMETRIC ELL graph.

    Each round every node computes the modularity gain of moving to
    each neighbouring community —

        ΔQ ∝ (w_{i→c} − w_{i→cur}) − γ·d_i·(Σ_c − Σ_cur + d_i)/2m

    — via one ``segment_sum`` of degrees per community plus an O(k²)
    per-row same-community mask (no scatter into an (n, n_comms)
    table).  Moves apply to alternating node-id parity halves:
    synchronous all-node moves oscillate (two adjacent nodes swap
    communities forever).  The parity split is a deterministic
    ANALOGUE of parallel Louvain's random half-sweeps, not an
    equivalent: fixed halves can still leave move patterns random
    sweeps would break, so it reaches somewhat lower modularity than
    serial greedy Louvain on adversarial graphs — the gap is bounded
    empirically in tests/test_leiden.py (within 5% of the serial
    oracle), not guaranteed.  Ties break toward the lower community
    id.  Returns int32 labels.
    """
    n, k = idx.shape
    dead = idx < 0
    safe = jnp.where(dead, 0, idx)
    # Self-loops appear when the "graph" is an aggregated coarse graph
    # (internal community weight).  They count toward the node's
    # degree but must never vote: a supernode's internal weight moves
    # with it, so it cancels out of every ΔQ.
    row_ids = jnp.arange(n, dtype=idx.dtype)[:, None]
    novote = dead | (idx == row_ids)
    w_deg = jnp.where(dead, 0.0, w.astype(jnp.float32))
    wv = jnp.where(novote, 0.0, w_deg)
    deg = jnp.sum(w_deg, axis=1)  # (n,) — includes self-loops
    m2 = jnp.maximum(jnp.sum(deg), 1e-12)  # 2m

    nb = -(-n // block)
    pad = nb * block - n
    parity = jnp.arange(n, dtype=jnp.int32) % 2

    def pad_to(x, fill):
        if pad == 0:
            return x
        shape = (pad,) + x.shape[1:]
        return jnp.concatenate([x, jnp.full(shape, fill, x.dtype)])

    def round_step(labels, r):
        sig = jax.ops.segment_sum(deg, labels, num_segments=n)  # Σ_tot
        nl = jnp.where(novote, -1, jnp.take(labels, safe))
        sig_nl = jnp.take(sig, jnp.where(nl < 0, 0, nl))
        sig_cur = jnp.take(sig, labels)

        args = (pad_to(nl, -1), pad_to(wv, 0.0), pad_to(labels, 0),
                pad_to(sig_nl, 0.0), pad_to(sig_cur, 0.0),
                pad_to(deg, 0.0))

        def per_block(a):
            bnl, bw, bcur, bsig, bsigc, bdeg = a
            same = bnl[:, None, :] == bnl[:, :, None]  # (blk, k, k)
            wc = jnp.sum(jnp.where(same, bw[:, None, :], 0.0), axis=2)
            w_cur = jnp.sum(
                jnp.where(bnl == bcur[:, None], bw, 0.0), axis=1)
            gain = (wc - w_cur[:, None]) - resolution * bdeg[:, None] * (
                bsig - (bsigc[:, None] - bdeg[:, None])) / m2
            gain = jnp.where((bnl < 0) | (bnl == bcur[:, None]),
                             -jnp.inf, gain)
            bg = jnp.max(gain, axis=1)
            cand = jnp.where(gain == bg[:, None], bnl,
                             jnp.iinfo(jnp.int32).max)
            bc = jnp.min(cand, axis=1)
            return bg, bc

        bg, bc = jax.lax.map(
            per_block, tuple(x.reshape((nb, block) + x.shape[1:])
                             for x in args))
        bg = bg.reshape(-1)[:n]
        bc = bc.reshape(-1)[:n]
        active = parity == (r % 2)
        move = active & (bg > 1e-12) & (bc < jnp.iinfo(jnp.int32).max)
        return jnp.where(move, bc, labels), None

    labels, _ = jax.lax.scan(round_step, jnp.asarray(labels0, jnp.int32),
                             jnp.arange(n_rounds, dtype=jnp.int32))
    return labels


def modularity(idx: np.ndarray, w: np.ndarray, labels: np.ndarray,
               resolution: float = 1.0) -> float:
    """Newman modularity of a partition on a SYMMETRIC ELL graph
    (each undirected edge stored in both rows).  Host-side metric for
    tests/benches — independent of both optimisers."""
    labels = np.asarray(labels)
    idx = np.asarray(idx)
    w = np.asarray(w, np.float64)
    dead = idx < 0
    wv = np.where(dead, 0.0, w)
    safe = np.where(dead, 0, idx)
    deg = wv.sum(axis=1)
    m2 = deg.sum()
    if m2 <= 0:
        return 0.0
    same = labels[safe] == labels[:, None]
    w_in = np.where(same & ~dead, wv, 0.0).sum()
    sig = np.bincount(labels, weights=deg,
                      minlength=int(labels.max()) + 1)
    return float(w_in / m2 - resolution * np.sum((sig / m2) ** 2))


def _leiden_graph(data: CellData, weight_key: str):
    if "knn_indices" not in data.obsp:
        raise ValueError("run neighbors.knn first")
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    if weight_key in data.obsp:
        w = np.asarray(data.obsp[weight_key], np.float64)[:n]
    else:
        w = np.ones_like(idx, np.float64)
    return _symmetrize_knn(idx, w)


@register("cluster.leiden", backend="tpu")
def leiden_tpu(data: CellData, resolution: float = 1.0,
               n_rounds: int = 20, n_levels: int = 3,
               weight_key: str = "connectivities",
               key_added: str = "leiden") -> CellData:
    """Modularity clustering of the kNN graph: device-parallel local
    moves (``louvain_moves_arrays``) interleaved with host coarse-graph
    merges, Louvain-style, until modularity stops improving.  The
    ``resolution`` parameter γ scales the null-model term (higher →
    more, smaller communities).  Adds obs["leiden"],
    uns["leiden_modularity"].  Requires neighbors.knn (+ optionally
    graph.connectivities for weighted edges)."""
    idx2, w2 = _leiden_graph(data, weight_key)
    idx_j, w_j = jnp.asarray(idx2), jnp.asarray(w2)
    labels = np.arange(data.n_cells, dtype=np.int32)
    best_q, best_labels = -np.inf, labels
    for _ in range(max(1, n_levels)):
        labels = np.asarray(louvain_moves_arrays(
            idx_j, w_j, jnp.asarray(labels), resolution=resolution,
            n_rounds=n_rounds))
        labels = _modularity_merge(labels, idx2, w2, resolution=resolution)
        q = modularity(idx2, w2, labels, resolution=resolution)
        if q <= best_q + 1e-9:
            break
        best_q, best_labels = q, labels
    return data.with_obs(
        **{key_added: best_labels.astype(np.int32)}).with_uns(
        **{f"{key_added}_modularity": np.float32(best_q),
           f"{key_added}_resolution": np.float32(resolution)})


@register("cluster.leiden", backend="cpu")
def leiden_cpu(data: CellData, resolution: float = 1.0,
               n_rounds: int = 20, n_levels: int = 3,
               weight_key: str = "connectivities",
               key_added: str = "leiden") -> CellData:
    """Sequential greedy Louvain oracle (same gain formula, node-by-
    node sweeps in id order — the classic serial algorithm the
    device's parallel half-sweeps approximate).

    The sweep loop runs natively when ``csrc/libscio.so`` is built
    (``scio_louvain_sweeps`` — identical visit order, gain formula and
    tie-breaks), which lifts the oracle from toy sizes to 100k+ nodes;
    the pure-Python loop below is the always-available fallback and
    the specification the native sweep is tested against
    (tests/test_leiden.py::test_native_sweeps_match_python)."""
    idx2, w2 = _leiden_graph(data, weight_key)
    n, k = idx2.shape
    labels = np.arange(n, dtype=np.int64)
    best_q, best_labels = -np.inf, labels
    for _level in range(max(1, n_levels)):
        labels = _serial_sweeps(idx2, w2, labels, resolution, n_rounds)
        labels = _modularity_merge(labels, idx2, w2, resolution=resolution)
        q = modularity(idx2, w2, labels, resolution=resolution)
        if q <= best_q + 1e-9:
            break
        best_q, best_labels = q, labels
        labels = labels.astype(np.int64)
    return data.with_obs(
        **{key_added: best_labels.astype(np.int32)}).with_uns(
        **{f"{key_added}_modularity": np.float32(best_q),
           f"{key_added}_resolution": np.float32(resolution)})


def _serial_sweeps(idx2, w2, labels, resolution, n_rounds,
                   force_python: bool = False):
    """Greedy serial local-move sweeps; native when available."""
    from ..native import louvain_sweeps

    if not force_python:
        out = louvain_sweeps(idx2, w2, labels.astype(np.int32),
                             resolution=resolution, n_sweeps=n_rounds)
        if out is not None:
            return out.astype(np.int64)
    n, k = idx2.shape
    dead = idx2 < 0
    wv = np.where(dead, 0.0, w2.astype(np.float64))
    safe = np.where(dead, 0, idx2)
    deg = wv.sum(axis=1)
    m2 = max(deg.sum(), 1e-12)
    labels = labels.astype(np.int64).copy()
    sig = np.bincount(labels, weights=deg, minlength=n).astype(float)
    for _sweep in range(n_rounds):
        moved = 0
        for i in range(n):
            votes: dict = {}
            for j in range(k):
                if not dead[i, j] and safe[i, j] != i:  # self never votes
                    votes[labels[safe[i, j]]] = (
                        votes.get(labels[safe[i, j]], 0.0) + wv[i, j])
            cur = labels[i]
            w_cur = votes.get(cur, 0.0)
            best_c, best_g = cur, 0.0
            for c, wc in sorted(votes.items()):
                if c == cur:
                    continue
                g = (wc - w_cur) - resolution * deg[i] * (
                    sig[c] - (sig[cur] - deg[i])) / m2
                if g > best_g + 1e-12:
                    best_c, best_g = c, g
            if best_c != cur:
                sig[cur] -= deg[i]
                sig[best_c] += deg[i]
                labels[i] = best_c
                moved += 1
        if moved == 0:
            break
    return labels


# ----------------------------------------------------------------------
# cluster.phenograph — Jaccard graph + community detection
# ----------------------------------------------------------------------


@register("cluster.phenograph", backend="tpu")
def phenograph_tpu(data: CellData, n_iter: int = 30,
                   jaccard_block: int = 1024) -> CellData:
    """PhenoGraph: reweight the kNN graph by neighbour-set Jaccard
    similarity, then detect communities (label propagation +
    modularity merge — see cluster.leiden_like for the divergence
    note vs true Louvain).  Requires neighbors.knn.  Adds
    obs["phenograph"], obsp["jaccard"].  ``jaccard_block`` forwards
    to ``graph.jaccard``'s row-tile size (results are identical for
    every value; it used to be unreachable from here)."""
    from .graph import jaccard_tpu

    if "jaccard" not in data.obsp:
        data = jaccard_tpu(data, block=jaccard_block)
    out = leiden_like_tpu(data, n_iter=n_iter, weight_key="jaccard")
    return _as_phenograph(data, out)


@register("cluster.phenograph", backend="cpu")
def phenograph_cpu(data: CellData, n_iter: int = 30,
                   jaccard_block: int = 1024) -> CellData:
    from .graph import jaccard_cpu

    if "jaccard" not in data.obsp:
        data = jaccard_cpu(data, block=jaccard_block)
    out = leiden_like_cpu(data, n_iter=n_iter, weight_key="jaccard")
    return _as_phenograph(data, out)


def _as_phenograph(before: CellData, after: CellData) -> CellData:
    """Move the delegated leiden_like labels to obs["phenograph"],
    restoring (or dropping) the caller's own obs["leiden_like"]."""
    obs = dict(after.obs)
    labels = obs.pop("leiden_like")
    if "leiden_like" in before.obs:
        obs["leiden_like"] = before.obs["leiden_like"]
    obs["phenograph"] = labels
    return after.replace(obs=obs)


def adjusted_rand_index(a, b) -> float:
    """ARI between two labelings (test/bench metric)."""
    a = np.asarray(a)
    b = np.asarray(b)
    n = len(a)
    ua, ia = np.unique(a, return_inverse=True)
    ub, ib = np.unique(b, return_inverse=True)
    m = np.zeros((len(ua), len(ub)), np.int64)
    np.add.at(m, (ia, ib), 1)
    ai = m.sum(1)
    bj = m.sum(0)
    comb = lambda x: x * (x - 1) / 2.0
    s_ij = comb(m).sum()
    s_a = comb(ai).sum()
    s_b = comb(bj).sum()
    s_n = comb(np.float64(n))
    expected = s_a * s_b / s_n
    max_idx = 0.5 * (s_a + s_b)
    if max_idx == expected:
        return 1.0
    return float((s_ij - expected) / (max_idx - expected))


# ----------------------------------------------------------------------
# cluster.dendrogram — hierarchy of group centroids (scanpy
# tl.dendrogram): complete linkage on 1 - Pearson correlation of the
# per-group mean embeddings
# ----------------------------------------------------------------------


def _dendrogram(data: CellData, groupby: str, use_rep: str,
                method: str, rep):
    from scipy.cluster import hierarchy
    from scipy.spatial.distance import squareform

    labels = np.asarray(data.obs[groupby])[: data.n_cells]
    levels, codes = np.unique(labels, return_inverse=True)
    rep = np.asarray(rep, np.float64)[: data.n_cells]
    means = np.stack([rep[codes == g].mean(axis=0)
                      for g in range(len(levels))])
    if len(levels) < 2:
        raise ValueError(
            f"cluster.dendrogram: obs[{groupby!r}] has "
            f"{len(levels)} level(s); need at least 2")
    corr = np.corrcoef(means)
    # degenerate-but-legal centroids (zero variance across features,
    # or a 1-column rep) give NaN correlation rows; treat them as
    # uncorrelated (distance 1) rather than crashing linkage
    corr = np.nan_to_num(corr, nan=0.0)
    np.fill_diagonal(corr, 1.0)
    # scanpy links on the condensed 1 - Pearson distance of the
    # centroid matrix, not euclidean pdist; keep the stored linkage
    # consistent with the stored correlation_matrix.
    dist = np.maximum(1.0 - corr, 0.0)
    np.fill_diagonal(dist, 0.0)
    Z = hierarchy.linkage(squareform(dist, checks=False), method=method)
    order = hierarchy.leaves_list(Z)
    return data.with_uns(**{f"dendrogram_{groupby}": {
        "linkage": Z,
        "groupby": groupby,
        "use_rep": use_rep,
        "categories_ordered": [str(levels[i]) for i in order],
        "categories_idx_ordered": order.astype(np.int64),
        "correlation_matrix": corr,
    }})


@register("cluster.dendrogram", backend="tpu")
def dendrogram_tpu(data: CellData, groupby: str = "leiden",
                   use_rep: str = "X_pca",
                   method: str = "complete") -> CellData:
    """Hierarchical clustering of GROUP CENTROIDS (scanpy
    ``tl.dendrogram``): per-group means of ``obsm[use_rep]``, scipy
    linkage (default ``complete``) on the condensed 1 - Pearson
    correlation distance, leaf order.  Adds
    ``uns['dendrogram_<groupby>']``.  The heavy per-cell embedding
    already lives on device; the (n_groups x d) linkage is microscopic
    host work on both backends.
    """
    from .knn import _get_rep

    return _dendrogram(data, groupby, use_rep, method,
                       np.asarray(_get_rep(data, use_rep)))


@register("cluster.dendrogram", backend="cpu")
def dendrogram_cpu(data: CellData, groupby: str = "leiden",
                   use_rep: str = "X_pca",
                   method: str = "complete") -> CellData:
    from .knn import _get_rep_cpu

    return _dendrogram(data, groupby, use_rep, method,
                       _get_rep_cpu(data, use_rep))


# ----------------------------------------------------------------------
# cluster.louvain — scanpy's name for the same modularity optimiser
# ----------------------------------------------------------------------


@register("cluster.louvain", backend="tpu")
def louvain_tpu(data: CellData, resolution: float = 1.0,
                n_rounds: int = 20, n_levels: int = 3,
                weight_key: str = "connectivities") -> CellData:
    """scanpy ``tl.louvain`` naming: identical computation to
    ``cluster.leiden`` (this package's optimiser IS the Louvain
    local-moves + aggregation scheme — see the module docstring), with
    the result stored under obs["louvain"]."""
    return leiden_tpu(data, resolution=resolution, n_rounds=n_rounds,
                      n_levels=n_levels, weight_key=weight_key,
                      key_added="louvain")


@register("cluster.louvain", backend="cpu")
def louvain_cpu(data: CellData, resolution: float = 1.0,
                n_rounds: int = 20, n_levels: int = 3,
                weight_key: str = "connectivities") -> CellData:
    return leiden_cpu(data, resolution=resolution, n_rounds=n_rounds,
                      n_levels=n_levels, weight_key=weight_key,
                      key_added="louvain")
