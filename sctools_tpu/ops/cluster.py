"""Clustering: ``cluster.kmeans`` (minibatch-free Lloyd on MXU) and
``cluster.leiden_like`` (graph label propagation over the kNN graph).

TPU design: k-means assignment is the same blocked score-matmul as
kNN (centroids replicated in VMEM, argmax over MXU scores); the
update step is one ``segment_sum`` per iteration.  Everything runs
under one ``lax.scan`` over iterations — no host round-trips.

The Leiden-like transform is a deterministic label-propagation scheme
over the kNN graph (argmax over neighbour-label votes, iterated).
True Leiden's refinement phase is data-dependent sequential work that
does not map to XLA; label propagation reaches comparable modularity
on kNN graphs and is embarrassingly parallel.  Documented divergence
from the reference's louvain/leiden.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


from ..data.dataset import CellData
from ..registry import register


@partial(jax.jit, static_argnames=("n_clusters", "n_iter", "block"))
def kmeans_arrays(points, key, n_clusters: int = 8, n_iter: int = 25,
                  block: int = 4096):
    """Lloyd's algorithm.  points: (n, d) dense.  Returns (labels (n,),
    centroids (k, d), inertia ())."""
    n, d = points.shape
    pts = jnp.asarray(points, jnp.float32)

    # k-means++-lite init: sample k points with probability ∝ squared
    # distance to the running centroid set, approximated by one
    # D²-weighted draw round (full k-means++ is sequential in k; one
    # weighted round captures most of the benefit and stays parallel).
    i0 = jax.random.choice(key, n, (1,))
    c0 = pts[i0]  # (1, d)
    d2 = jnp.sum((pts - c0) ** 2, axis=1)
    probs = d2 / jnp.maximum(d2.sum(), 1e-12)
    rest = jax.random.choice(key, n, (n_clusters - 1,), replace=False, p=probs)
    centroids = jnp.concatenate([c0, pts[rest]], axis=0)  # (k, d)

    nb = -(-n // block)
    pad = nb * block - n
    pts_pad = jnp.concatenate([pts, jnp.zeros((pad, d), pts.dtype)]) if pad else pts
    valid = jnp.arange(nb * block) < n

    def assign(centroids):
        cn2 = jnp.sum(centroids**2, axis=1)  # (k,)

        def per_block(args):
            p = args  # (block, d)
            s = jnp.dot(p, centroids.T, preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST)
            d2 = cn2[None, :] - 2.0 * s  # + ||p||² (constant per row)
            lab = jnp.argmin(d2, axis=1).astype(jnp.int32)
            best = jnp.min(d2, axis=1) + jnp.sum(p * p, axis=1)
            return lab, best

        labs, best = jax.lax.map(per_block, pts_pad.reshape(nb, block, d))
        return labs.reshape(-1), best.reshape(-1)

    def step(centroids, _):
        labels, best = assign(centroids)
        labels_v = jnp.where(valid, labels, n_clusters)  # padding → dropped bin
        sums = jax.ops.segment_sum(
            jnp.where(valid[:, None], pts_pad, 0.0), labels_v,
            num_segments=n_clusters + 1)[:n_clusters]
        counts = jax.ops.segment_sum(
            valid.astype(jnp.float32), labels_v,
            num_segments=n_clusters + 1)[:n_clusters]
        new_c = jnp.where(counts[:, None] > 0,
                          sums / jnp.maximum(counts[:, None], 1.0),
                          centroids)
        inertia = jnp.sum(jnp.where(valid, best, 0.0))
        return new_c, inertia

    centroids, inertias = jax.lax.scan(step, centroids, None, length=n_iter)
    labels, best = assign(centroids)
    inertia = jnp.sum(jnp.where(valid, best, 0.0))
    return labels[:n], centroids, inertia


@register("cluster.kmeans", backend="tpu")
def kmeans_tpu(data: CellData, n_clusters: int = 8, n_iter: int = 25,
               use_rep: str = "X_pca", seed: int = 0) -> CellData:
    """Adds obs["kmeans"], uns["kmeans_centroids"], uns["kmeans_inertia"]."""
    from .knn import _get_rep

    rep = _get_rep(data, use_rep)
    labels, centroids, inertia = kmeans_arrays(
        jnp.asarray(rep)[: data.n_cells], jax.random.PRNGKey(seed),
        n_clusters=n_clusters, n_iter=n_iter)
    return data.with_obs(kmeans=labels).with_uns(
        kmeans_centroids=centroids, kmeans_inertia=inertia)


@register("cluster.kmeans", backend="cpu")
def kmeans_cpu(data: CellData, n_clusters: int = 8, n_iter: int = 25,
               use_rep: str = "X_pca", seed: int = 0) -> CellData:
    """numpy Lloyd oracle (same init scheme family, own RNG)."""
    from .knn import _get_rep_cpu

    rep = np.asarray(_get_rep_cpu(data, use_rep), np.float64)[: data.n_cells]
    rng = np.random.default_rng(seed)
    n = len(rep)
    # full sequential k-means++ (the numpy oracle can afford it)
    centroids = rep[rng.choice(n, 1)]
    for _ in range(n_clusters - 1):
        d2 = np.min(((rep[:, None, :] - centroids[None]) ** 2).sum(-1), axis=1)
        p = d2 / max(d2.sum(), 1e-12)
        centroids = np.concatenate([centroids, rep[rng.choice(n, 1, p=p)]])
    labels = np.zeros(n, np.int32)
    for _ in range(n_iter):
        d2 = ((rep[:, None, :] - centroids[None, :, :]) ** 2).sum(-1) \
            if n * n_clusters * rep.shape[1] < 5e7 else None
        if d2 is None:
            s = rep @ centroids.T
            d2 = (centroids**2).sum(1)[None, :] - 2 * s
        labels = np.argmin(d2, axis=1).astype(np.int32)
        for j in range(n_clusters):
            m = labels == j
            if m.any():
                centroids[j] = rep[m].mean(axis=0)
    inertia = float(((rep - centroids[labels]) ** 2).sum())
    return data.with_obs(kmeans=labels).with_uns(
        kmeans_centroids=centroids.astype(np.float32),
        kmeans_inertia=np.float32(inertia))


# ----------------------------------------------------------------------
# Label propagation over the kNN graph ("leiden-like" communities).
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_iter",))
def label_propagation_arrays(knn_idx, weights, n_iter: int = 30):
    """Weighted label propagation on a kNN graph.

    knn_idx: (n, k) int32 neighbour ids (-1 = missing); weights:
    (n, k) edge weights.  Starts from singleton labels; each round a
    node adopts the best-supported neighbour label, but only when its
    support STRICTLY beats the node's current label (monotone — plain
    synchronous propagation oscillates), with support ties resolved
    toward the lower label id (also monotone).  Self-edges never vote.
    Fully deterministic.
    """
    n, k = knn_idx.shape
    labels0 = jnp.arange(n, dtype=jnp.int32)
    safe_idx = jnp.where(knn_idx < 0, 0, knn_idx)
    # self-edges must not vote: a self-weight of 1.0 (distance 0 in
    # the UMAP kernel) would pin every node to its own singleton label
    row_ids = jnp.arange(n, dtype=knn_idx.dtype)[:, None]
    dead = (knn_idx < 0) | (knn_idx == row_ids)
    w = jnp.where(dead, 0.0, weights.astype(jnp.float32))

    block = 8192
    nb = -(-n // block)
    pad = nb * block - n

    def step(labels, _):
        neigh_labels = jnp.take(labels, safe_idx, axis=0)  # (n, k)
        nl = jnp.where(dead, -1, neigh_labels)
        wv = w
        cur = labels
        if pad:
            nl = jnp.concatenate([nl, jnp.full((pad, k), -1, nl.dtype)])
            wv = jnp.concatenate([wv, jnp.zeros((pad, k), wv.dtype)])
            cur = jnp.concatenate([cur, jnp.full((pad,), -1, cur.dtype)])

        def per_block(args):
            sl, sw, cl = args  # (block, k), (block, k), (block,)
            # vote weight of each position's label: O(k²) pairwise
            # equality mask — k is small, so this is trivial VPU work
            # and avoids any scatter into (n, n_labels).
            same = sl[:, None, :] == sl[:, :, None]  # (block, k, k)
            acc = jnp.sum(jnp.where(same, sw[:, None, :], 0.0), axis=2)
            acc = jnp.where(sl < 0, -1.0, acc)
            # tie-break: highest weight, then lowest label id — as two
            # exact passes (a combined scalar key would let label ids
            # override genuine weight differences)
            bw = jnp.max(acc, axis=1)
            cand = jnp.where(acc == bw[:, None], sl,
                             jnp.iinfo(jnp.int32).max)
            lab = jnp.min(cand, axis=1)
            # support for the CURRENT label among neighbours
            cur_support = jnp.sum(
                jnp.where(sl == cl[:, None], sw, 0.0), axis=1)
            return lab, bw, cur_support

        lab, bw, cur_sup = jax.lax.map(
            per_block, (nl.reshape(nb, block, k), wv.reshape(nb, block, k),
                        cur.reshape(nb, block)))
        lab = lab.reshape(-1)[:n]
        bw = bw.reshape(-1)[:n]
        cur_sup = cur_sup.reshape(-1)[:n]
        # monotone update: adopt a STRICTLY better-supported label
        # (synchronous best-of-all updates oscillate and fragment);
        # on support ties adopt the LOWER id — label ids then only
        # decrease, which merges equal-support plateau fragments
        # without reintroducing oscillation.
        valid_lab = (lab >= 0) & (lab < jnp.iinfo(jnp.int32).max)
        better = bw > cur_sup + 1e-12
        tie_lower = (jnp.abs(bw - cur_sup) <= 1e-12) & (lab < labels)
        adopt = (better | tie_lower) & valid_lab
        return jnp.where(adopt, lab, labels), None

    labels, _ = jax.lax.scan(step, labels0, None, length=n_iter)
    return labels


def _compact_labels(labels: np.ndarray) -> np.ndarray:
    uniq, inv = np.unique(labels, return_inverse=True)
    return inv.astype(np.int32)


def _modularity_merge(labels: np.ndarray, knn_idx: np.ndarray,
                      weights: np.ndarray) -> np.ndarray:
    """Leiden-style aggregation phase: greedily merge communities of
    the coarse label graph while modularity increases.

    Pure LPA leaves stable same-cluster fragments (a fragment's
    internal support beats boundary votes); merging on the aggregated
    graph is exactly how Louvain/Leiden escape that.  The coarse graph
    has only #labels nodes, so this is negligible host-side work.
    """
    labels = _compact_labels(labels)
    m = labels.max() + 1 if len(labels) else 0
    if m <= 1:
        return labels
    n, k = knn_idx.shape
    li = np.repeat(labels, k)
    cols = knn_idx.reshape(-1)
    keep = cols >= 0
    lj = labels[np.clip(cols, 0, n - 1)]
    w = np.asarray(weights, np.float64).reshape(-1)
    A = np.zeros((m, m))
    np.add.at(A, (li[keep], lj[keep]), w[keep])
    A = 0.5 * (A + A.T)
    total = A.sum()
    if total <= 0:
        return labels
    group = np.arange(m)
    while True:
        deg = A.sum(axis=1)
        # modularity gain of merging i,j: 2*(A_ij/total - deg_i*deg_j/total²)
        gain = 2.0 * (A / total - np.outer(deg, deg) / (total * total))
        np.fill_diagonal(gain, -np.inf)
        i, j = np.unravel_index(np.argmax(gain), gain.shape)
        if gain[i, j] <= 1e-12:
            break
        # merge j into i
        A[i] += A[j]
        A[:, i] += A[:, j]
        A[i, i] += 0.0
        A = np.delete(np.delete(A, j, axis=0), j, axis=1)
        group[group == j] = i
        group[group > j] -= 1
        m -= 1
        if m <= 1:
            break
    return _compact_labels(group[labels])


@register("cluster.leiden_like", backend="tpu")
def leiden_like_tpu(data: CellData, n_iter: int = 30,
                    weight_key: str = "connectivities") -> CellData:
    """Community labels from label propagation over the kNN graph
    (deterministic) plus a modularity merge of the coarse label graph.
    Requires neighbors.knn (+ optionally graph.connectivities for
    weighted votes).  Adds obs["leiden_like"]."""
    if "knn_indices" not in data.obsp:
        raise ValueError("run neighbors.knn first")
    idx = jnp.asarray(data.obsp["knn_indices"])[: data.n_cells]
    if weight_key in data.obsp:
        w = jnp.asarray(data.obsp[weight_key])[: data.n_cells]
    else:
        w = jnp.ones_like(idx, dtype=jnp.float32)
    labels = label_propagation_arrays(idx, w, n_iter=n_iter)
    # the merge phase must see the same self-edge-free weights the
    # propagation used (CPU oracle masks identically)
    idx_h = np.asarray(idx)
    dead = (idx_h < 0) | (idx_h == np.arange(data.n_cells)[:, None])
    w_h = np.where(dead, 0.0, np.asarray(w))
    labels = _modularity_merge(np.asarray(labels), idx_h, w_h)
    return data.with_obs(leiden_like=jnp.asarray(labels))


@register("cluster.leiden_like", backend="cpu")
def leiden_like_cpu(data: CellData, n_iter: int = 30,
                    weight_key: str = "connectivities") -> CellData:
    """numpy oracle of the same propagation scheme."""
    if "knn_indices" not in data.obsp:
        raise ValueError("run neighbors.knn first")
    idx = np.asarray(data.obsp["knn_indices"])[: data.n_cells]
    n, k = idx.shape
    if weight_key in data.obsp:
        w = np.asarray(data.obsp[weight_key], np.float64)[: data.n_cells]
    else:
        w = np.ones_like(idx, np.float64)
    dead = (idx < 0) | (idx == np.arange(n)[:, None])  # no self-votes
    w = np.where(dead, 0.0, w)
    safe = np.where(idx < 0, 0, idx)
    labels = np.arange(n, dtype=np.int64)
    for _ in range(n_iter):
        nl = np.where(dead, -1, labels[safe])
        new = labels.copy()
        for i in range(n):
            votes: dict = {}
            for j in range(k):
                if w[i, j] > 0:
                    votes[nl[i, j]] = votes.get(nl[i, j], 0.0) + w[i, j]
            if votes:
                # highest weight, then lowest label id (mirror TPU)
                best = min(votes, key=lambda L: (-votes[L], L))
                cur_sup = votes.get(labels[i], 0.0)
                if votes[best] > cur_sup + 1e-12 or (
                        abs(votes[best] - cur_sup) <= 1e-12
                        and best < labels[i]):
                    new[i] = best
        if (new == labels).all():
            break
        labels = new
    labels = _modularity_merge(labels, idx, w)
    return data.with_obs(leiden_like=labels)


# ----------------------------------------------------------------------
# cluster.phenograph — Jaccard graph + community detection
# ----------------------------------------------------------------------


@register("cluster.phenograph", backend="tpu")
def phenograph_tpu(data: CellData, n_iter: int = 30) -> CellData:
    """PhenoGraph: reweight the kNN graph by neighbour-set Jaccard
    similarity, then detect communities (label propagation +
    modularity merge — see cluster.leiden_like for the divergence
    note vs true Louvain).  Requires neighbors.knn.  Adds
    obs["phenograph"], obsp["jaccard"]."""
    from .graph import jaccard_tpu

    if "jaccard" not in data.obsp:
        data = jaccard_tpu(data)
    out = leiden_like_tpu(data, n_iter=n_iter, weight_key="jaccard")
    return _as_phenograph(data, out)


@register("cluster.phenograph", backend="cpu")
def phenograph_cpu(data: CellData, n_iter: int = 30) -> CellData:
    from .graph import jaccard_cpu

    if "jaccard" not in data.obsp:
        data = jaccard_cpu(data)
    out = leiden_like_cpu(data, n_iter=n_iter, weight_key="jaccard")
    return _as_phenograph(data, out)


def _as_phenograph(before: CellData, after: CellData) -> CellData:
    """Move the delegated leiden_like labels to obs["phenograph"],
    restoring (or dropping) the caller's own obs["leiden_like"]."""
    obs = dict(after.obs)
    labels = obs.pop("leiden_like")
    if "leiden_like" in before.obs:
        obs["leiden_like"] = before.obs["leiden_like"]
    obs["phenograph"] = labels
    return after.replace(obs=obs)


def adjusted_rand_index(a, b) -> float:
    """ARI between two labelings (test/bench metric)."""
    a = np.asarray(a)
    b = np.asarray(b)
    n = len(a)
    ua, ia = np.unique(a, return_inverse=True)
    ub, ib = np.unique(b, return_inverse=True)
    m = np.zeros((len(ua), len(ub)), np.int64)
    np.add.at(m, (ia, ib), 1)
    ai = m.sum(1)
    bj = m.sum(0)
    comb = lambda x: x * (x - 1) / 2.0
    s_ij = comb(m).sum()
    s_a = comb(ai).sum()
    s_b = comb(bj).sum()
    s_n = comb(np.float64(n))
    expected = s_a * s_b / s_n
    max_idx = 0.5 * (s_a + s_b)
    if max_idx == expected:
        return 1.0
    return float((s_ij - expected) / (max_idx - expected))
