"""``embed.umap`` — UMAP layout optimisation, TPU-first.

Reference parity: dpeerlab/sctools exposes a UMAP embedding step
(source unavailable — SURVEY.md §0; the algorithm is the published
UMAP method: optimise a 2/3-D layout of the fuzzy-simplicial-set graph
by attraction along edges and negative-sampling repulsion).

TPU design: the reference-style implementation (umap-learn) does
asynchronous per-edge SGD with data-dependent sampling — a scalar
loop that cannot map to XLA.  Here each epoch is **full-batch and
vectorised**: every kNN edge exerts its weight-scaled attractive
force simultaneously (a gather along the k axis + a segment-sum for
the symmetric reaction), and every vertex draws ``n_neg`` fresh
uniform negative samples per epoch (``jax.random`` inside the scan —
no host round-trips).  The whole optimisation is one
``lax.scan`` over epochs with a linearly decaying step size, so it
jit-compiles to a single XLA program; forces use the same
clip-to-±4 stabilisation as the reference algorithm.  This is the
standard dense-hardware reformulation (cf. the batched layouts in
GPU UMAP implementations) and converges to layouts of the same
quality, though not bit-identical to umap-learn's sequential SGD.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..registry import register
from .pallas_graph import gather_rows


def fit_ab(min_dist: float = 0.1, spread: float = 1.0):
    """Fit the (a, b) of Φ(d) = 1/(1 + a·d^{2b}) to the target curve
    exp(-(d - min_dist)/spread) for d > min_dist, 1 otherwise — the
    same calibration umap-learn performs (least squares on a grid)."""
    if abs(min_dist - 0.1) < 1e-9 and abs(spread - 1.0) < 1e-9:
        return 1.5769434, 0.8950608  # the canonical defaults
    from scipy.optimize import curve_fit

    xv = np.linspace(0, spread * 3, 300)
    yv = np.where(xv < min_dist, 1.0, np.exp(-(xv - min_dist) / spread))
    (a, b), _ = curve_fit(lambda x, a, b: 1.0 / (1.0 + a * x ** (2 * b)),
                          xv, yv, p0=(1.5, 0.9), maxfev=10000)
    return float(a), float(b)


@partial(jax.jit, static_argnames=("n_epochs", "n_neg", "a", "b",
                                   "repulsion_strength"))
def umap_layout_arrays(knn_idx, weights, init, key, n_epochs: int = 200,
                       n_neg: int = 5, a: float = 1.5769434,
                       b: float = 0.8950608, lr: float = 1.0,
                       repulsion_strength: float = 1.0):
    """Optimise the layout.  knn_idx/weights: (n, k) symmetrised fuzzy
    graph (self-edges and -1 slots get weight 0); init: (n, d) layout.
    Returns the final (n, d) embedding (float32)."""
    n, k = knn_idx.shape
    row_ids = jnp.arange(n, dtype=knn_idx.dtype)[:, None]
    dead = (knn_idx < 0) | (knn_idx == row_ids)
    w = jnp.where(dead, 0.0, weights.astype(jnp.float32))
    safe = jnp.where(knn_idx < 0, 0, knn_idx)
    y0 = jnp.asarray(init, jnp.float32)
    eps = 1e-3

    def epoch(y, inp):
        step, ekey = inp
        alpha = lr * (1.0 - step / n_epochs)
        yj = gather_rows(y, safe)                    # (n, k, d)
        diff = y[:, None, :] - yj                    # (n, k, d)
        d2 = jnp.sum(diff * diff, axis=2)            # (n, k)
        # attractive force along edges:  dCE/dd² of -log Φ, scaled by w
        # (d2 clamped away from 0 — b < 1 makes the exponent negative)
        grad_coef = (-2.0 * a * b * jnp.maximum(d2, eps) ** (b - 1.0)
                     / (1.0 + a * d2 ** b))          # ≤ 0
        att = jnp.clip(grad_coef[:, :, None] * diff, -4.0, 4.0) * w[:, :, None]
        g = jnp.sum(att, axis=1)
        # symmetric reaction on the neighbour end (Newton's third law —
        # the edge list is directed, the energy is not)
        flat = (-att).reshape(-1, y.shape[1])
        g = g + jax.ops.segment_sum(
            flat, safe.reshape(-1), num_segments=n)
        # negative sampling: n_neg uniform vertices per node per epoch
        # (the repulsion inner loop — its gather rides the tiled
        # family like the edge gather above)
        negs = jax.random.randint(ekey, (n, n_neg), 0, n)
        yn = gather_rows(y, negs)                    # (n, m, d)
        diff_n = y[:, None, :] - yn
        d2n = jnp.sum(diff_n * diff_n, axis=2)
        rep_coef = (2.0 * repulsion_strength * b
                    / ((eps + d2n) * (1.0 + a * d2n ** b)))  # ≥ 0
        rep = jnp.clip(rep_coef[:, :, None] * diff_n, -4.0, 4.0)
        g = g + jnp.sum(rep, axis=1)
        # g accumulates update *directions* (attraction coef ≤ 0 points
        # i toward j; repulsion coef ≥ 0 points away), umap-learn's
        # convention — so the step is simply y + α·g
        return y + alpha * g, None

    steps = jnp.arange(n_epochs, dtype=jnp.float32)
    keys = jax.random.split(key, n_epochs)
    y, _ = jax.lax.scan(epoch, y0, (steps, keys))
    return y


def _spectral_init(data: CellData, n_dims: int, seed: int, backend: str,
                   scale: float = 10.0):
    """UMAP's spectral initialisation: leading diffusion-map
    coordinates rescaled to ~[-scale, scale] with a pinch of noise."""
    from .graph import spectral_cpu, spectral_tpu

    sp = spectral_tpu if backend == "tpu" else spectral_cpu
    d = sp(data, n_comps=n_dims, seed=seed)
    emb = np.asarray(d.obsm["X_diffmap"])[: data.n_cells, :n_dims]
    emb = emb / max(np.abs(emb).max(), 1e-12) * scale
    rng = np.random.default_rng(seed)
    return (emb + rng.normal(scale=1e-3, size=emb.shape)).astype(np.float32)


def umap_layout_numpy(idx, w, init, seed, n_epochs: int = 200,
                      n_neg: int = 5, a: float = 1.5769434,
                      b: float = 0.8950608, lr: float = 1.0,
                      repulsion_strength: float = 1.0):
    """Independent numpy oracle of the same full-batch scheme (its own
    RNG for negative samples — layouts agree in quality metrics, not
    bitwise)."""
    rng = np.random.default_rng(seed)
    n, k = idx.shape
    dead = (idx < 0) | (idx == np.arange(n)[:, None])
    w = np.where(dead, 0.0, np.asarray(w, np.float64))
    safe = np.where(idx < 0, 0, idx)
    y = np.asarray(init, np.float64).copy()
    eps = 1e-3
    for step in range(n_epochs):
        alpha = lr * (1.0 - step / n_epochs)
        diff = y[:, None, :] - y[safe]
        d2 = (diff * diff).sum(2)
        coef = (-2.0 * a * b * np.maximum(d2, eps) ** (b - 1.0)
                / (1.0 + a * d2 ** b))
        att = np.clip(coef[:, :, None] * diff, -4.0, 4.0) * w[:, :, None]
        g = att.sum(1)
        np.add.at(g, safe.reshape(-1), -att.reshape(-1, y.shape[1]))
        negs = rng.integers(0, n, (n, n_neg))
        diff_n = y[:, None, :] - y[negs]
        d2n = (diff_n * diff_n).sum(2)
        rep_c = (2.0 * repulsion_strength * b
                 / ((eps + d2n) * (1.0 + a * d2n ** b)))
        g = g + np.clip(rep_c[:, :, None] * diff_n, -4.0, 4.0).sum(1)
        y = y + alpha * g
    return y.astype(np.float32)


def _sym_union_numpy(idx, w):
    """Independent scipy implementation of the fuzzy-set union +
    edge-multiplicity normalisation (the cpu oracle's counterpart of
    ``_symmetrized_weights(mode="union")``)."""
    import scipy.sparse as sp

    n, k = idx.shape
    rows = np.repeat(np.arange(n), k)
    cols = idx.reshape(-1)
    vals = np.asarray(w, np.float64).reshape(-1)
    keep = cols >= 0
    W = sp.csr_matrix((vals[keep], (rows[keep], cols[keep])), shape=(n, n))
    U = (W + W.T - W.multiply(W.T)).tocsr()
    WT = W.T.tocsr()
    out = np.zeros(n * k)
    mult = np.ones(n * k)
    out[keep] = np.asarray(U[rows[keep], cols[keep]]).ravel()
    mult[keep] += np.asarray(WT[rows[keep], cols[keep]]).ravel() > 0
    return (out / mult).reshape(n, k).astype(np.float32)


def _umap_prepare(data: CellData, backend: str, n_dims, min_dist, spread,
                  seed, init):
    """Shared graph/init/calibration prologue → (data, idx, w, init,
    a, b).  The fuzzy-union weight ``w_sym`` of every undirected edge
    is divided by the number of directed entries carrying it: with the
    Newton's-third-law reaction in the layout step, each endpoint then
    receives exactly ``w_sym`` of attraction per epoch whether the
    edge appears in one kNN list or both (matching the reference's
    symmetric-CSR semantics).  TPU backend keeps everything on device;
    cpu uses the independent scipy implementation."""
    from .graph import (_require_knn, _symmetrized_weights,
                        connectivities_cpu, connectivities_tpu)

    if "connectivities" not in data.obsp:
        data = (connectivities_tpu if backend == "tpu"
                else connectivities_cpu)(data)
    n = data.n_cells
    idx, _ = _require_knn(data)
    w = jnp.asarray(np.asarray(data.obsp["connectivities"],
                               np.float32)[:n])
    if backend == "tpu":
        w = _symmetrized_weights(idx, w, mode="union_norm")
    else:
        idx = np.asarray(idx)
        w = _sym_union_numpy(idx, np.asarray(w))
    if init is None:
        init = _spectral_init(data, n_dims, seed, backend)
    else:
        init = np.asarray(init, np.float32)
        if init.shape != (n, n_dims):
            raise ValueError(
                f"init must have shape ({n}, {n_dims}), got {init.shape}")
    a, b = fit_ab(min_dist, spread)
    return data, idx, w, init, a, b


@register("embed.umap", backend="tpu")
def umap_tpu(data: CellData, n_dims: int = 2, min_dist: float = 0.1,
             spread: float = 1.0, n_epochs: int = 200, n_neg: int = 5,
             lr: float = 1.0, seed: int = 0, init=None) -> CellData:
    """Adds obsm["X_umap"].  Requires neighbors.knn (connectivities
    are computed if missing); ``init`` overrides the spectral
    initialisation with an (n, n_dims) layout."""
    data, idx, w, init, a, b = _umap_prepare(
        data, "tpu", n_dims, min_dist, spread, seed, init)
    y = umap_layout_arrays(
        jnp.asarray(idx), jnp.asarray(w), jnp.asarray(init),
        jax.random.PRNGKey(seed), n_epochs=n_epochs, n_neg=n_neg,
        a=a, b=b, lr=lr)
    return data.with_obsm(X_umap=y).with_uns(umap_min_dist=min_dist)


@partial(jax.jit, static_argnames=("n_epochs", "n_neg"))
def fa2_layout_arrays(knn_idx, weights, init, key, n_epochs: int = 300,
                      n_neg: int = 10, repulsion: float = 1.0,
                      gravity: float = 1.0, lr: float = 0.1):
    """ForceAtlas2-style layout on the kNN graph, full-batch.

    Linear attraction ``-w·diff`` along edges, degree-scaled
    ``(deg_i+1)(deg_j+1)/d²`` repulsion estimated by negative sampling
    (``repulsion`` times the *sample mean* over ``n_neg`` draws — the
    mean-repulsion parameterisation, so the repulsion magnitude is
    independent of graph size; the CPU oracle uses the same scheme),
    and a gravity term pulling to the origin.  Same vectorised scheme as the
    UMAP optimiser: one ``lax.scan`` over epochs, no host round-trips.
    """
    n, k = knn_idx.shape
    row_ids = jnp.arange(n, dtype=knn_idx.dtype)[:, None]
    dead = (knn_idx < 0) | (knn_idx == row_ids)
    w = jnp.where(dead, 0.0, weights.astype(jnp.float32))
    safe = jnp.where(knn_idx < 0, 0, knn_idx)
    deg = jnp.sum(w, axis=1) + 1.0  # (n,)
    y0 = jnp.asarray(init, jnp.float32)
    eps = 1e-3
    rep_scale = repulsion / max(n_neg, 1)  # mean over the n_neg draws

    def epoch(y, inp):
        step, ekey = inp
        alpha = lr * (1.0 - step / n_epochs)
        yj = gather_rows(y, safe)
        diff = y[:, None, :] - yj
        att = -(w[:, :, None] * diff)
        g = jnp.sum(att, axis=1)
        g = g + jax.ops.segment_sum(
            (-att).reshape(-1, y.shape[1]), safe.reshape(-1),
            num_segments=n)
        negs = jax.random.randint(ekey, (n, n_neg), 0, n)
        diff_n = y[:, None, :] - gather_rows(y, negs)
        d2n = jnp.sum(diff_n * diff_n, axis=2)
        rep_c = (deg[:, None] * jnp.take(deg, negs)) / (eps + d2n)
        rep = jnp.clip(rep_c[:, :, None] * diff_n, -10.0, 10.0)
        g = g + rep_scale * jnp.sum(rep, axis=1)
        g = g - gravity * deg[:, None] * y / jnp.maximum(
            jnp.linalg.norm(y, axis=1, keepdims=True), eps)
        return y + alpha * jnp.clip(g, -10.0, 10.0), None

    steps = jnp.arange(n_epochs, dtype=jnp.float32)
    keys = jax.random.split(key, n_epochs)
    y, _ = jax.lax.scan(epoch, y0, (steps, keys))
    return y


@register("embed.force_directed", backend="tpu")
def force_directed_tpu(data: CellData, n_dims: int = 2,
                       n_epochs: int = 300, n_neg: int = 10,
                       repulsion: float = 1.0, gravity: float = 1.0,
                       lr: float = 0.1, seed: int = 0,
                       init=None) -> CellData:
    """ForceAtlas2-style graph layout (scanpy's draw_graph parity).
    Adds obsm["X_draw_graph"].  Requires neighbors.knn."""
    from .graph import _require_knn, connectivities_tpu

    if "connectivities" not in data.obsp:
        data = connectivities_tpu(data)
    n = data.n_cells
    idx, _ = _require_knn(data)
    w = jnp.asarray(np.asarray(data.obsp["connectivities"],
                               np.float32)[:n])
    if init is None:
        init = _spectral_init(data, n_dims, seed, "tpu", scale=1.0)
    else:
        init = np.asarray(init, np.float32)
        if init.shape != (n, n_dims):
            raise ValueError(
                f"init must have shape ({n}, {n_dims}), got {init.shape}")
    y = fa2_layout_arrays(idx, w, jnp.asarray(init),
                          jax.random.PRNGKey(seed), n_epochs=n_epochs,
                          n_neg=n_neg, repulsion=repulsion,
                          gravity=gravity, lr=lr)
    return data.with_obsm(X_draw_graph=y)


@register("embed.force_directed", backend="cpu")
def force_directed_cpu(data: CellData, n_dims: int = 2,
                       n_epochs: int = 300, n_neg: int = 10,
                       repulsion: float = 1.0, gravity: float = 1.0,
                       lr: float = 0.1, seed: int = 0,
                       init=None) -> CellData:
    """Numpy oracle of the same scheme."""
    from .graph import _require_knn, connectivities_cpu

    if "connectivities" not in data.obsp:
        data = connectivities_cpu(data)
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    w = np.asarray(data.obsp["connectivities"], np.float64)[:n]
    dead = (idx < 0) | (idx == np.arange(n)[:, None])
    w = np.where(dead, 0.0, w)
    safe = np.where(idx < 0, 0, idx)
    deg = w.sum(1) + 1.0
    if init is None:
        init = _spectral_init(data, n_dims, seed, "cpu", scale=1.0)
    else:
        init = np.asarray(init, np.float32)
        if init.shape != (n, n_dims):
            raise ValueError(
                f"init must have shape ({n}, {n_dims}), got {init.shape}")
    rng = np.random.default_rng(seed)
    y = np.asarray(init, np.float64).copy()
    eps = 1e-3
    rep_scale = repulsion / max(n_neg, 1)  # mirrors the TPU kernel
    for step in range(n_epochs):
        alpha = lr * (1.0 - step / n_epochs)
        diff = y[:, None, :] - y[safe]
        att = -(w[:, :, None] * diff)
        g = att.sum(1)
        np.add.at(g, safe.reshape(-1), -att.reshape(-1, y.shape[1]))
        negs = rng.integers(0, n, (n, n_neg))
        diff_n = y[:, None, :] - y[negs]
        d2n = (diff_n * diff_n).sum(2)
        rep_c = (deg[:, None] * deg[negs]) / (eps + d2n)
        g = g + rep_scale * np.clip(
            rep_c[:, :, None] * diff_n, -10.0, 10.0).sum(1)
        g = g - gravity * deg[:, None] * y / np.maximum(
            np.linalg.norm(y, axis=1, keepdims=True), eps)
        y = y + alpha * np.clip(g, -10.0, 10.0)
    return data.with_obsm(X_draw_graph=y.astype(np.float32))


@register("embed.umap", backend="cpu")
def umap_cpu(data: CellData, n_dims: int = 2, min_dist: float = 0.1,
             spread: float = 1.0, n_epochs: int = 200, n_neg: int = 5,
             lr: float = 1.0, seed: int = 0, init=None) -> CellData:
    """Numpy oracle backend (independent implementation of the same
    full-batch scheme)."""
    data, idx, w, init, a, b = _umap_prepare(
        data, "cpu", n_dims, min_dist, spread, seed, init)
    y = umap_layout_numpy(idx, w, init, seed, n_epochs=n_epochs,
                          n_neg=n_neg, a=a, b=b, lr=lr)
    return data.with_obsm(X_umap=y).with_uns(umap_min_dist=min_dist)


# ----------------------------------------------------------------------
# embed.draw_graph — scanpy's name for the force-directed layout
# ----------------------------------------------------------------------


@register("embed.draw_graph", backend="tpu")
def draw_graph_tpu(data: CellData, n_dims: int = 2, n_epochs: int = 300,
                   n_neg: int = 10, repulsion: float = 1.0,
                   gravity: float = 1.0, lr: float = 0.1,
                   seed: int = 0, init=None) -> CellData:
    """scanpy ``tl.draw_graph`` naming for ``embed.force_directed`` —
    identical computation, identical ``obsm["X_draw_graph"]`` output."""
    return force_directed_tpu(data, n_dims=n_dims, n_epochs=n_epochs,
                              n_neg=n_neg, repulsion=repulsion,
                              gravity=gravity, lr=lr, seed=seed,
                              init=init)


@register("embed.draw_graph", backend="cpu")
def draw_graph_cpu(data: CellData, n_dims: int = 2, n_epochs: int = 300,
                   n_neg: int = 10, repulsion: float = 1.0,
                   gravity: float = 1.0, lr: float = 0.1,
                   seed: int = 0, init=None) -> CellData:
    return force_directed_cpu(data, n_dims=n_dims, n_epochs=n_epochs,
                              n_neg=n_neg, repulsion=repulsion,
                              gravity=gravity, lr=lr, seed=seed,
                              init=init)
